"""Deterministic fault-injection tier: a chaos TCP proxy for the edge.

The reference proves its delivery continuity operationally (parmon
respawn loops, resend-inventory-on-reconnect — ``gypartha.cc:965``,
``gy_socket_stat.h:1235``); this tier proves ours in CI: a seeded
asyncio proxy sits between agents and the server and injects the
failure vocabulary of real networks —

- **corrupt**    flip one byte in flight (poison header / payload),
- **truncate**   drop the tail of the stream and close mid-frame,
- **disconnect** abrupt close at an arbitrary byte offset,
- **stall**      stop forwarding for a while (slow-loris; the conn
  stays open and silent — the idle/handshake reap's prey),
- chunk **re-splitting** (exercises partial-frame reassembly),
- added **latency/jitter** per forwarded chunk,
- coordinated **server-kill windows** (refuse + drop every conn —
  the proxy-side view of a dead server; test harnesses pair it with
  an actual server restart),
- **wedge windows** (ISSUE 15): stop forwarding in BOTH directions
  while keeping every conn open — the stalled-NOT-dead upstream, the
  hard fault-domain case: requests are accepted, responses never
  come, and no conn error ever fires (circuit breakers see nothing
  until a timeout; hedged reads are what bound the latency). Also a
  manual ``proxy.wedged`` toggle for harness-driven schedules,
- **asymmetric latency/jitter** (ISSUE 19): per-direction delays
  (``latency_c2s_s``/``latency_s2c_s``) — the WAN shape where the ask
  path and the answer path cost differently,
- **partition windows** (ISSUE 19): bytes in BOTH directions are
  silently DROPPED (counted) while every conn stays open — unlike a
  wedge the bytes never arrive, so a healed stream is torn mid-frame
  and the endpoints' resync machinery (frame-error close, counted
  relay gaps, subscription resyncs) must recover; also a manual
  ``proxy.partitioned`` toggle,
- **region-kill scheduling** (ISSUE 19): :class:`RegionKill` drives
  kill/restart callbacks on deterministic windows — the harness-side
  clock for region-wide SIGKILL campaigns.

The PR-15 fault-domain campaign points these at the INTER-TIER hops
(gateway→replica, subscription client→gateway) as well as the
original agent↔server edge — ``fault_both=True`` faults the
server→client direction too (responses, pushes), which the PR-4
plans never exercised.

Determinism: every fault decision derives from a seeded
:class:`FaultPlan` keyed by (seed, conn index) and **byte offsets**,
not wall clock or chunk timing — the same plan against the same byte
stream injects the same faults at the same positions.

Operator CLI: ``python -m gyeeta_tpu chaos --upstream-port 10038
--listen-port 10039 --faults corrupt,stall`` — point agents at the
proxy port and watch the hardening counters on /metrics.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import random
from typing import Iterable, Optional

log = logging.getLogger("gyeeta_tpu.chaos")

_CHUNK = 1 << 16

FAULT_KINDS = ("corrupt", "truncate", "disconnect", "stall")


class FaultPlan:
    """Seeded, reproducible fault schedule.

    ``conn_faults(conn_idx)`` yields ``(byte_offset, kind)`` events for
    the agent→server direction of the ``conn_idx``-th accepted conn;
    offsets are spaced ~exponentially with mean ``mean_fault_bytes``.
    ``latency_s``/``jitter_s`` delay every forwarded chunk; ``resplit``
    re-splits forwarded chunks into smaller writes (max size drawn per
    chunk). ``kill_windows`` are (start_s, end_s) intervals relative to
    proxy start during which ALL conns are dropped and new ones
    refused.
    """

    def __init__(self, seed: int = 0,
                 fault_kinds: Iterable[str] = (),
                 mean_fault_bytes: int = 1 << 18,
                 first_fault_bytes: Optional[int] = None,
                 stall_s: float = 1.0,
                 latency_s: float = 0.0,
                 jitter_s: float = 0.0,
                 resplit: int = 0,
                 kill_windows: Iterable[tuple] = (),
                 wedge_windows: Iterable[tuple] = (),
                 fault_both: bool = False,
                 latency_c2s_s: Optional[float] = None,
                 latency_s2c_s: Optional[float] = None,
                 jitter_c2s_s: Optional[float] = None,
                 jitter_s2c_s: Optional[float] = None,
                 partition_windows: Iterable[tuple] = ()):
        self.seed = seed
        self.fault_kinds = tuple(fault_kinds)
        for k in self.fault_kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {k!r} "
                                 f"(known: {FAULT_KINDS})")
        self.mean_fault_bytes = int(mean_fault_bytes)
        self.first_fault_bytes = first_fault_bytes
        self.stall_s = stall_s
        self.latency_s = latency_s
        self.jitter_s = jitter_s
        self.resplit = int(resplit)
        self.kill_windows = tuple((float(a), float(b))
                                  for a, b in kill_windows)
        # (start_s, end_s) intervals during which the proxy forwards
        # NOTHING in either direction but keeps every conn open — a
        # stalled (wedged) upstream, not a dead one
        self.wedge_windows = tuple((float(a), float(b))
                                   for a, b in wedge_windows)
        # fault the server→client direction too (responses/pushes):
        # the inter-tier hops fail on the answer path as often as the
        # ask path
        self.fault_both = bool(fault_both)
        # asymmetric WAN shape: per-direction latency/jitter override
        # the symmetric knobs when set (None = inherit)
        self.latency_c2s_s = latency_c2s_s
        self.latency_s2c_s = latency_s2c_s
        self.jitter_c2s_s = jitter_c2s_s
        self.jitter_s2c_s = jitter_s2c_s
        # (start_s, end_s) intervals during which BOTH directions are
        # silently dropped (counted) while every conn stays open — a
        # network partition, not a stall: the bytes never arrive
        self.partition_windows = tuple((float(a), float(b))
                                       for a, b in partition_windows)

    def _rng(self, conn_idx: int, salt: int = 0) -> random.Random:
        # int-mixed seed (tuple seeding is deprecated and hash-based)
        return random.Random(((self.seed * 1_000_003 + conn_idx)
                              * 8191 + salt) & 0x7FFFFFFFFFFF)

    def conn_faults(self, conn_idx: int, max_events: int = 4096):
        """Deterministic (byte_offset, kind) schedule for one conn."""
        if not self.fault_kinds:
            return
        rng = self._rng(conn_idx, salt=1)
        off = self.first_fault_bytes if self.first_fault_bytes \
            is not None else int(rng.expovariate(
                1.0 / self.mean_fault_bytes)) + 64
        for _ in range(max_events):
            yield int(off), rng.choice(self.fault_kinds)
            off += int(rng.expovariate(1.0 / self.mean_fault_bytes)) + 64

    def in_kill_window(self, t_rel: float) -> bool:
        return any(a <= t_rel < b for a, b in self.kill_windows)

    def in_wedge_window(self, t_rel: float) -> bool:
        return any(a <= t_rel < b for a, b in self.wedge_windows)

    def in_partition_window(self, t_rel: float) -> bool:
        return any(a <= t_rel < b for a, b in self.partition_windows)

    def latency_for(self, direction: str) -> float:
        v = self.latency_c2s_s if direction == "c2s" \
            else self.latency_s2c_s
        return self.latency_s if v is None else float(v)

    def jitter_for(self, direction: str) -> float:
        v = self.jitter_c2s_s if direction == "c2s" \
            else self.jitter_s2c_s
        return self.jitter_s if v is None else float(v)


class ChaosProxy:
    """Seeded fault-injecting TCP proxy (agent side → ``listen``,
    server side → ``upstream``). ``upstream`` is mutable — a restarted
    server on a new port just reassigns it. ``stats`` counts injected
    faults by kind (the harness's ground truth for accounting)."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 plan: Optional[FaultPlan] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.upstream = (upstream_host, upstream_port)
        self.plan = plan or FaultPlan()
        self.host, self.port = host, port
        self.refusing = False         # manual server-kill coordination
        self.wedged = False           # manual stalled-upstream toggle
        self.partitioned = False      # manual partition toggle
        self.stats: collections.Counter = collections.Counter()
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()      # live (cwriter, uwriter) pairs
        self._n_accepted = 0
        self._t0 = 0.0
        self._kill_task: Optional[asyncio.Task] = None
        self._was_partitioned = False

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple[str, int]:
        loop = asyncio.get_running_loop()
        self._t0 = loop.time()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.host, self.port = sock[0], sock[1]
        if self.plan.kill_windows or self.plan.wedge_windows \
                or self.plan.partition_windows:
            self._kill_task = asyncio.create_task(self._kill_monitor())
        log.info("chaos proxy on %s:%d -> %s:%d (faults=%s seed=%d)",
                 self.host, self.port, *self.upstream,
                 ",".join(self.plan.fault_kinds) or "none",
                 self.plan.seed)
        return self.host, self.port

    async def stop(self) -> None:
        if self._kill_task:
            self._kill_task.cancel()
            self._kill_task = None
        if self._server:
            self._server.close()
            self.drop_all()
            await self._server.wait_closed()
            self._server = None

    def drop_all(self) -> None:
        """Abort every live conn (both halves) — the server-kill edge."""
        for cw, uw in list(self._conns):
            for w in (cw, uw):
                try:
                    w.close()
                except Exception:     # pragma: no cover
                    pass
        self.stats["dropped_conns"] += len(self._conns)

    async def _kill_monitor(self) -> None:
        loop = asyncio.get_running_loop()
        was = False
        was_wedged = False
        while True:
            await asyncio.sleep(0.05)
            now = loop.time() - self._t0
            inwin = self.plan.in_kill_window(now)
            if inwin and not was:
                log.info("chaos: kill window opens at t=%.2fs", now)
                self.refusing = True
                self.drop_all()
            elif was and not inwin:
                log.info("chaos: kill window closes at t=%.2fs", now)
                self.refusing = False
            was = inwin
            inwedge = self.plan.in_wedge_window(now)
            if inwedge and not was_wedged:
                log.info("chaos: wedge window opens at t=%.2fs", now)
                self.wedged = True
                self.stats["wedge_spans"] += 1
            elif was_wedged and not inwedge:
                log.info("chaos: wedge window closes at t=%.2fs", now)
                self.wedged = False
            was_wedged = inwedge
            inpart = self.plan.in_partition_window(now)
            if inpart and not self._was_partitioned:
                log.info("chaos: partition opens at t=%.2fs", now)
                self.partitioned = True
                self.stats["partition_spans"] += 1
            elif self._was_partitioned and not inpart:
                log.info("chaos: partition heals at t=%.2fs", now)
                self.partitioned = False
            self._was_partitioned = inpart

    # ------------------------------------------------------------- conn path
    async def _handle(self, creader, cwriter) -> None:
        idx = self._n_accepted
        self._n_accepted += 1
        if self.refusing:
            self.stats["refused_conns"] += 1
            cwriter.close()
            return
        try:
            ureader, uwriter = await asyncio.open_connection(
                *self.upstream)
        except OSError:
            self.stats["refused_conns"] += 1
            cwriter.close()
            return
        pair = (cwriter, uwriter)
        self._conns.add(pair)
        try:
            c2s = asyncio.create_task(self._pump(
                creader, uwriter, idx, faulted=True,
                direction="c2s"))
            s2c = asyncio.create_task(self._pump(
                ureader, cwriter, idx,
                faulted=self.plan.fault_both, direction="s2c"))
            done, pending = await asyncio.wait(
                {c2s, s2c}, return_when=asyncio.FIRST_COMPLETED)
            for t in pending:
                t.cancel()
            for t in pending:
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        finally:
            self._conns.discard(pair)
            for w in (cwriter, uwriter):
                try:
                    w.close()
                except Exception:     # pragma: no cover
                    pass

    async def _pump(self, reader, writer, conn_idx: int,
                    faulted: bool, direction: str = "c2s") -> None:
        """Forward bytes one direction, applying the plan's faults
        (agent→server only) plus latency/jitter/re-splitting."""
        plan = self.plan
        rng = plan._rng(conn_idx, salt=2 if faulted else 3)
        faults = plan.conn_faults(conn_idx) if faulted else iter(())
        next_off, kind = next(faults, (None, None))
        offset = 0
        try:
            while True:
                data = await reader.read(_CHUNK)
                if not data:
                    return
                while data:
                    if next_off is not None and \
                            offset + len(data) > next_off:
                        cut = max(0, next_off - offset)
                        pre, at = data[:cut], data[cut:]
                        if pre:
                            await self._fwd(writer, pre, rng, direction)
                            offset += len(pre)
                        self.stats[kind] += 1
                        if kind == "corrupt":
                            # flip every bit of ONE byte in flight
                            bad = bytes([at[0] ^ 0xFF]) + at[1:]
                            await self._fwd(writer, bad, rng, direction)
                            offset += len(bad)
                            data = b""
                        elif kind == "stall":
                            # hold the stream: conn open, bytes parked
                            await asyncio.sleep(plan.stall_s)
                            data = at
                        elif kind == "truncate":
                            # tail vanishes, then the conn does
                            return
                        else:                     # disconnect
                            return
                        next_off, kind = next(faults, (None, None))
                    else:
                        await self._fwd(writer, data, rng, direction)
                        offset += len(data)
                        data = b""
        except (ConnectionError, OSError):
            return

    async def _fwd(self, writer, data: bytes, rng: random.Random,
                   direction: str = "c2s") -> None:
        plan = self.plan
        # partitioned: the bytes are GONE (counted), the conn is not —
        # a healed stream resumes torn mid-frame and the endpoints'
        # resync machinery must recover, counted, never silently
        if self.partitioned:
            self.stats["partition_dropped_chunks"] += 1
            self.stats["partition_dropped_bytes"] += len(data)
            return
        # wedged: park (conn open, bytes held) until the toggle/window
        # clears — the stalled-not-dead upstream both directions see
        if self.wedged:
            self.stats["wedged_chunks"] += 1
            t0 = asyncio.get_running_loop().time()
            while self.wedged:
                await asyncio.sleep(0.02)
            self.stats["wedged_s"] += round(
                asyncio.get_running_loop().time() - t0, 3)
        step = len(data)
        if plan.resplit:
            step = rng.randint(max(1, plan.resplit // 4), plan.resplit)
        lat = plan.latency_for(direction)
        jit = plan.jitter_for(direction)
        for i in range(0, len(data), step):
            if lat or jit:
                self.stats[f"delayed_chunks_{direction}"] += 1
                await asyncio.sleep(lat + jit * rng.random())
            writer.write(data[i: i + step])
            await writer.drain()


class RegionKill:
    """Deterministic region-wide kill scheduler (ISSUE 19): at each
    window's OPEN edge call ``kill_cb`` (the harness SIGKILLs the
    region's processes), at its CLOSE edge call ``restart_cb`` (the
    harness respawns them). Pure ``in_window(t_rel)`` carries the
    schedule so unit tests cover edge semantics without a clock;
    :meth:`run` polls a real clock and fires the callbacks exactly
    once per edge (``stats['region_kills']``/``['region_restarts']``
    are the ground truth for the campaign's accounting). Callbacks
    may be sync or async; the task finishes once every window has
    closed and fired."""

    def __init__(self, windows: Iterable[tuple], kill_cb=None,
                 restart_cb=None, poll_s: float = 0.05):
        self.windows = tuple(sorted((float(a), float(b))
                                    for a, b in windows))
        for a, b in self.windows:
            if b <= a:
                raise ValueError(f"empty region-kill window {a}..{b}")
        self.kill_cb = kill_cb
        self.restart_cb = restart_cb
        self.poll_s = float(poll_s)
        self.stats: collections.Counter = collections.Counter()

    def in_window(self, t_rel: float) -> bool:
        return any(a <= t_rel < b for a, b in self.windows)

    @property
    def end(self) -> float:
        return max((b for _a, b in self.windows), default=0.0)

    async def _fire(self, cb) -> None:
        if cb is None:
            return
        out = cb()
        if asyncio.iscoroutine(out):
            await out

    async def run(self, t0: Optional[float] = None) -> None:
        loop = asyncio.get_running_loop()
        t0 = loop.time() if t0 is None else t0
        was = False
        while True:
            now = loop.time() - t0
            inwin = self.in_window(now)
            if inwin and not was:
                log.info("chaos: region kill at t=%.2fs", now)
                self.stats["region_kills"] += 1
                await self._fire(self.kill_cb)
            elif was and not inwin:
                log.info("chaos: region restart at t=%.2fs", now)
                self.stats["region_restarts"] += 1
                await self._fire(self.restart_cb)
            was = inwin
            if not inwin and now >= self.end:
                return
            await asyncio.sleep(self.poll_s)


async def run_proxy(args) -> None:
    """CLI driver: run the proxy until interrupted, reporting injected
    fault counts on a cadence."""
    plan = FaultPlan(
        seed=args.seed,
        fault_kinds=[f for f in args.faults.split(",") if f]
        if args.faults else (),
        mean_fault_bytes=args.mean_fault_kb << 10,
        stall_s=args.stall_s,
        latency_s=args.latency_ms / 1e3,
        jitter_s=args.jitter_ms / 1e3,
        resplit=args.resplit,
        kill_windows=[(args.kill_at, args.kill_at + args.kill_for)]
        if args.kill_for > 0 else (),
        wedge_windows=[(args.wedge_at,
                        args.wedge_at + args.wedge_for)]
        if getattr(args, "wedge_for", 0) > 0 else (),
        fault_both=getattr(args, "fault_both", False),
        latency_c2s_s=(args.latency_c2s_ms / 1e3
                       if getattr(args, "latency_c2s_ms", None)
                       is not None else None),
        latency_s2c_s=(args.latency_s2c_ms / 1e3
                       if getattr(args, "latency_s2c_ms", None)
                       is not None else None),
        partition_windows=[(args.partition_at,
                            args.partition_at + args.partition_for)]
        if getattr(args, "partition_for", 0) > 0 else ())
    proxy = ChaosProxy(args.upstream_host, args.upstream_port, plan,
                       host=args.listen_host, port=args.listen_port)
    host, port = await proxy.start()
    print(f"chaos proxy on {host}:{port} -> "
          f"{args.upstream_host}:{args.upstream_port}", flush=True)
    try:
        while True:
            await asyncio.sleep(args.report_interval)
            if proxy.stats:
                log.info("chaos stats %s", dict(proxy.stats))
    finally:
        await proxy.stop()
