"""Stock node-webserver simulator: a byte-level NM query client.

The mirror of ``sim/partha.py`` for the QUERY half of the reference
protocol: where ParthaSim synthesizes the partha→madhava NOTIFY
streams, NodeWebSim speaks the node-webserver→madhava conn contract
(``ingest/refquery.py`` — NM_CONNECT_CMD_S handshake, QUERY_CMD_S with
QUERY_WEB_JSON / CRUD_GENERIC_JSON / CRUD_ALERT_JSON bodies, chunked
QUERY_RESPONSE_S reads) with ZERO GYT-specific frames on the wire.
Drives the NM edge in tests and in ``ci.sh``'s smoke boot; the
``gyeeta_tpu nm probe`` CLI wraps it for operators.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import Optional

import numpy as np

from gyeeta_tpu.ingest import refproto as RP
from gyeeta_tpu.ingest import refquery as RQ
from gyeeta_tpu.ingest import wire


class NMError(RuntimeError):
    """Server answered with a REF_RESP_ERROR envelope."""

    def __init__(self, obj: dict):
        super().__init__(str(obj.get("error", obj)))
        self.errcode = obj.get("errcode")
        self.obj = obj


class NodeWebSim:
    """One stock node-webserver conn (handshake + query loop).

    Usage::

        nw = NodeWebSim()
        await nw.connect(host, port)
        out = await nw.query_web("svcstate", filter=..., maxrecs=10)
        await nw.crud_alert({"op": "add", "objtype": "alertdef", ...})
        await nw.close()
    """

    def __init__(self, hostname: str = "nodeweb-sim",
                 node_port: int = 10039,
                 node_version: int = 0x000501,
                 comm_version: int = RP.REF_COMM_VERSION,
                 min_madhava_version: int = 0x000500):
        self.hostname = hostname
        self.node_port = node_port
        self.node_version = node_version
        self.comm_version = comm_version
        self.min_madhava_version = min_madhava_version
        self._seq = itertools.count(1)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self.handshake: dict = {}

    # --------------------------------------------------------- lifecycle
    async def connect(self, host: str, port: int) -> dict:
        """Dial the server and run the NM_CONNECT handshake. Returns the
        parsed NM_CONNECT_RESP_S fields; raises NMError on a gate
        rejection (the conn is closed server-side after an error
        response, like the reference)."""
        self._reader, self._writer = await asyncio.open_connection(
            host, port)
        self._writer.write(RQ.encode_nm_connect_cmd(
            hostname=self.hostname, node_port=self.node_port,
            node_version=self.node_version,
            comm_version=self.comm_version,
            min_madhava_version=self.min_madhava_version))
        await self._writer.drain()
        buf = await self._reader.readexactly(
            RP.REF_HEADER_DT.itemsize + RQ.REF_NM_CONNECT_RESP_DT.itemsize)
        resp = RQ.parse_nm_connect_resp(buf)
        self.handshake = resp
        if resp["error_code"]:
            await self.close()
            raise NMError({"error": resp["error_string"],
                           "errcode": resp["error_code"]})
        return resp

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._reader = self._writer = None

    # ------------------------------------------------------------- frames
    async def _read_frame(self) -> tuple[int, bytes]:
        hsz = RP.REF_HEADER_DT.itemsize
        hdr_b = await self._reader.readexactly(hsz)
        hdr = np.frombuffer(hdr_b, RP.REF_HEADER_DT, count=1)[0]
        if int(hdr["magic"]) not in RP.REF_MAGICS:
            raise wire.FrameError(
                f"bad magic 0x{int(hdr['magic']):08x}")
        total = int(hdr["total_sz"])
        if total < hsz or total >= wire.MAX_COMM_DATA_SZ:
            raise wire.FrameError(f"bad total_sz {total}")
        body = await self._reader.readexactly(total - hsz)
        pad = int(hdr["padding_sz"])
        return int(hdr["data_type"]), body[: len(body) - pad]

    async def request(self, qtype: int, body_obj: dict,
                      timeout_sec: float = 100.0) -> dict:
        """One framed request → the accumulated JSON response (chunked
        is_completed=0 partials are joined before parsing). Raises
        NMError on an error-envelope response."""
        seqid = next(self._seq)
        self._writer.write(RQ.encode_query_cmd(seqid, qtype, body_obj,
                                               timeout_sec))
        await self._writer.drain()
        chunks: list[bytes] = []
        resptype = RQ.REF_RESP_NULL
        while True:
            dtype, body = await self._read_frame()
            if dtype != RQ.REF_COMM_QUERY_RESP:
                raise wire.FrameError(f"unexpected data_type {dtype}")
            sid, resptype, done, chunk = RQ.parse_response_chunk(body)
            if sid != seqid:
                raise wire.FrameError(
                    f"seqid mismatch: sent {seqid}, got {sid}")
            chunks.append(chunk)
            if done:
                break
        obj = json.loads(b"".join(chunks) or b"null")
        if resptype == RQ.REF_RESP_ERROR:
            raise NMError(obj if isinstance(obj, dict)
                          else {"error": obj})
        return obj

    # ------------------------------------------------------------- verbs
    async def query_web(self, subsys, options: Optional[dict] = None,
                        **opt_kw) -> dict:
        """QUERY_WEB_JSON: ``subsys`` is a qtype code (int) or a
        subsystem name; keyword options merge over ``options`` (filter,
        maxrecs, columns, sortcol, sortdir, aggr, groupby...)."""
        opts = dict(options or {})
        opts.update(opt_kw)
        body = {"qtype": subsys}
        if opts:
            body["options"] = opts
        return await self.request(RQ.REF_QUERY_WEB_JSON, body)

    async def crud_generic(self, req: dict) -> dict:
        """CRUD_GENERIC_JSON: tracedef/tag add/delete."""
        return await self.request(RQ.REF_CRUD_GENERIC_JSON, req)

    async def crud_alert(self, req: dict) -> dict:
        """CRUD_ALERT_JSON: alertdef/silence/inhibit/action add/delete."""
        return await self.request(RQ.REF_CRUD_ALERT_JSON, req)
