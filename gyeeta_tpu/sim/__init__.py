"""Synthetic agent firehose (simulator tier).

Replaces live partha agents with a deterministic, vectorized generator of
wire-format event batches — the analogue of the reference's multi-agent
scale harness (``partha/test_multi_partha.sh`` — N synthetic agent ids on one
box) and pcap replay (``partha/gy_pseudo_pcap_cap.cc``), but generating the
event-struct stream directly (``partha/gy_ebpf_kernel_struct.h:209-325``
record vocabulary) so benchmarks and tests are reproducible without kernels.
"""

from gyeeta_tpu.sim.partha import ParthaSim  # noqa: F401
