"""Postgres history backend behind the same SQL-generation seam.

The reference's durable tier is day-partitioned Postgres with partition
maintenance in PL/pgSQL (``common/gy_postgres.h:1493``,
``server/gy_mdb_schema.cc:85-940``). :class:`PgHistoryStore` keeps the
sqlite :class:`~gyeeta_tpu.history.store.HistoryStore`'s EXACT query
semantics — criteria→SQL dual execution, cross-partition aggregation
merge, retention — and swaps only what the engine requires:

- connection + ``?``→``%s`` paramstyle (psycopg 3 or psycopg2,
  lazy-imported: the package stays importable without a driver);
- typed ``CREATE TABLE`` (sqlite's dynamic columns → double precision /
  text / boolean by field kind);
- catalog introspection (``information_schema`` for sqlite_master).

Per-day TABLES are the partition unit (created on first write, dropped
by retention) — the same maintenance granularity as the reference's
``add_partition``/``drop_partition`` jobs; native ``PARTITION BY
RANGE`` would change ops, not behavior, and the seam keeps either
choice private to this class.

Select at config time by URL: ``--history-db postgresql://…`` routes
here, any other path stays sqlite (``history.open_store``). The
environment this tree builds in has no Postgres server or driver, so
the backend is exercised by ``tests/test_pgstore.py`` only when
``GYT_PG_DSN`` is set (compose ships a postgres service wired for it —
see deploy/docker-compose.yml).
"""

from __future__ import annotations

from gyeeta_tpu.history.store import _TABLES, HistoryStore, _day_of, \
    _table
from gyeeta_tpu.query import fieldmaps


def _pg_type(fd) -> str:
    if fd.kind == "num":
        return "double precision"
    if fd.kind == "bool":
        return "boolean"
    return "text"                 # str + enum (presentation strings)


class _PgDb:
    """sqlite-shaped facade over a psycopg connection: qmark→format
    paramstyle; AUTOCOMMIT with explicit BEGIN/COMMIT only inside
    ``with`` blocks. Bare reads must not open transactions (a server
    answering historical queries would sit idle-in-transaction for
    hours, blocking vacuum, and one failed statement would poison the
    connection with 'current transaction is aborted' forever) —
    psycopg's own ``with conn`` also CLOSES the connection, which is
    not what the store's transaction blocks mean."""

    def __init__(self, conn):
        conn.autocommit = True
        self._conn = conn

    def execute(self, q: str, params=()):
        cur = self._conn.cursor()
        if params:
            cur.execute(q.replace("?", "%s"), list(params))
        else:
            # no args ⇒ no client-side %-interpolation: literal '%'
            # (LIKE patterns) must pass through untouched
            cur.execute(q)
        return cur

    def executemany(self, q: str, seq) -> None:
        cur = self._conn.cursor()
        cur.executemany(q.replace("?", "%s"), [list(p) for p in seq])

    def commit(self) -> None:
        pass                      # autocommit: nothing pending

    def close(self) -> None:
        self._conn.close()

    def __enter__(self):
        self.execute("BEGIN")
        return self

    def __exit__(self, et, ev, tb):
        self.execute("COMMIT" if et is None else "ROLLBACK")


def _connect(dsn: str):
    try:
        import psycopg
        return psycopg.connect(dsn)
    except ImportError:
        pass
    try:
        import psycopg2
        return psycopg2.connect(dsn)
    except ImportError:
        raise RuntimeError(
            "postgresql:// history backend needs psycopg (v3) or "
            "psycopg2 installed") from None


class PgHistoryStore(HistoryStore):
    """Day-partitioned Postgres snapshot store (same interface)."""

    # CAST rounds in Postgres; FLOOR matches the numpy path's
    # ``time // step * step`` (and sqlite's truncating CAST)
    TIME_BUCKET_SQL = "FLOOR(time/{step})*{step}"
    # case-sensitive containment, same semantics as sqlite instr and
    # the live numpy path's `in`
    SUBSTR_SQL = "strpos({col}, ?) > 0"

    def __init__(self, dsn: str):
        import threading
        # deliberately NOT calling super().__init__ (it opens sqlite)
        self.db = _PgDb(_connect(dsn))
        self._known: set = set()
        # serializes the history writer thread against fold-thread
        # readers (one psycopg connection is not thread-safe)
        self._dblock = threading.RLock()

    # ---------------------------------------------------- overrides
    def _ensure(self, subsys: str, day: str) -> str:
        t = _table(subsys, day)
        if t not in self._known:
            fmap = fieldmaps.field_map(subsys)
            cols = ", ".join(
                f"{c} {_pg_type(fmap[c])}" if c in fmap else f"{c} text"
                for c in _TABLES[subsys])
            with self.db:
                self.db.execute(
                    f"CREATE TABLE IF NOT EXISTS {t} "
                    f"(time double precision, {cols})")
                self.db.execute(
                    f"CREATE INDEX IF NOT EXISTS idx_{t}_time "
                    f"ON {t}(time)")
            self._known.add(t)
        return t

    def _partition(self, subsys: str, day: str):
        t = _table(subsys, day)
        if t not in self._known:
            cur = self.db.execute(
                "SELECT table_name FROM information_schema.tables "
                "WHERE table_schema = current_schema() "
                "AND table_type = 'BASE TABLE' "
                "AND table_name = ?", (t,))
            if cur.fetchone() is None:
                return None
            self._known.add(t)
        return t

    def _own_partitions(self) -> list:
        """OUR day tables only: scoped to the current schema, base
        tables, and the exact names this store creates — a shared
        database must never lose a foreign table to retention."""
        cur = self.db.execute(
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_schema = current_schema() "
            "AND table_type = 'BASE TABLE'")
        prefixes = tuple(f"{s}tbl_" for s in _TABLES)
        out = []
        for (name,) in cur.fetchall():
            for p in prefixes:
                day = name[len(p):]
                if name.startswith(p) and day.isdigit():
                    out.append((name, day))
                    break
        return out

    def cleanup(self, keep_days: int, now: float) -> int:
        cutoff = _day_of(now - keep_days * 86400.0)
        dropped = 0
        with self._dblock:
            for name, day in self._own_partitions():
                if day < cutoff:
                    self.db.execute(f"DROP TABLE {name}")
                    self._known.discard(name)
                    dropped += 1
            self.db.commit()
        return dropped

    def days(self) -> list:
        with self._dblock:
            return sorted({day for _, day in self._own_partitions()})


def open_store(path_or_dsn: str) -> HistoryStore:
    """Backend selection by URL: postgresql:// → Postgres, else sqlite."""
    if path_or_dsn.startswith(("postgresql://", "postgres://")):
        return PgHistoryStore(path_or_dsn)
    return HistoryStore(path_or_dsn)
