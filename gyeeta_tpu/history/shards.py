"""Columnar snapshot shards: the on-disk format of the time-travel tier.

The relational ``history/store.py`` answers row-level SQL history; it
cannot answer "what was the ENGINE state at 09:14" for sketch-derived
subsystems (``topk`` is only meaningful as merged device state, the dep
graph is a slab, ``flowstate`` is a sketch readback). A shard closes
that gap: one npz per compaction window holding

- the full serialized engine state (every AggState leaf — the HLL
  registers, CMS counters, t-digest centroids and InvSketch candidate
  buckets travel as-is, so ANY state-backed subsystem materializes
  from a shard exactly as it does from live HBM), plus the dep-graph
  leaves;
- per-subsystem columnar snapshots (the same column panels the query
  tier serves, string columns stored as fixed-width unicode so loads
  never need pickle) for the relational subsystems — windowed
  aggregation across shards reads these without re-materializing
  state;
- a meta record: tick range, wall-time range, level, config
  fingerprint, and the WAL position the compactor had consumed when it
  emitted the shard (the restart-resume point).

Shards are atomic AND durable (tmp + fsync + rename + dir fsync, the
``checkpoint.save`` discipline); the manifest (``gyt_manifest.json``)
is rewritten the same way AFTER the shard lands, so a SIGKILL at any
byte leaves either the old manifest (shard invisible, recompacted) or
the new one (shard durable) — never a manifest pointing at a torn
file. Stranded ``*.tmp.npz`` are swept like ``checkpoint.
sweep_stale_tmp``.

Downsample levels (``raw`` → ``mid`` → ``hour``): the engine's sketches
are MONOTONE (HLL registers / CMS counters / exact top-K counts only
grow), so the sketch-merge of a run of consecutive shards is exactly
the newest shard's state — a downsampled shard keeps that state and
replaces the per-shard column panels with the windowed per-entity
aggregate (mean for numeric fields, last observation for
string/enum/bool), which is what a window query over the merged span
would have computed from the raws.
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Optional

import numpy as np

MANIFEST = "gyt_manifest.json"
_SHARD_FMT = "gyt_shard_{level}_{tick0:08d}_{tick1:08d}.npz"
LEVELS = ("raw", "mid", "hour")

# subsystems whose column panels are persisted per shard (mirrors the
# relational history tables + svcsumm); everything else materializes
# from the serialized engine state on demand
SNAP_SUBSYS = ("svcstate", "hoststate", "clusterstate", "taskstate",
               "cpumem", "tracereq")


class _NullStats:
    def bump(self, name, n=1):
        pass

    def gauge(self, name, v):
        pass


def _fsync_dir(d: pathlib.Path) -> None:
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:               # pragma: no cover — exotic fs
        pass


def _atomic_npz(path: pathlib.Path, payload: dict) -> int:
    """tmp + fsync + rename + dir fsync. Returns bytes written."""
    tmp = path.with_suffix(".tmp.npz")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    nbytes = tmp.stat().st_size
    tmp.rename(path)
    _fsync_dir(path.parent)
    return nbytes


def _col_key(subsys: str, name: str) -> str:
    return f"c|{subsys}|{name}"


def _atomic_json(path: pathlib.Path, obj: dict) -> None:
    """tmp + fsync + rename + dir fsync for manifests (shard store AND
    the parted store's root)."""
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(obj))
    with open(tmp, "rb+") as f:
        os.fsync(f.fileno())
    tmp.rename(path)
    _fsync_dir(path.parent)


class _ResolveMixin:
    """Time/tick resolution over a ``shards()`` listing — shared by the
    flat :class:`ShardStore` and the :class:`PartedShardStore` (whose
    entries additionally carry per-part sub-entries)."""

    def shards(self, level: Optional[str] = None) -> list:
        raise NotImplementedError

    def newest(self, level: str = "raw") -> Optional[dict]:
        s = self.shards(level)
        return s[-1] if s else None

    def resolve_at(self, at) -> Optional[dict]:
        """The shard answering "state at ``at``": newest shard whose
        window END is <= ``at`` (state at a timestamp = state at the
        last closed window), preferring finer levels on ties; a
        timestamp before every shard resolves to the earliest one.
        ``at`` is epoch seconds, or ``("tick", N)`` for tick-pinned
        resolution."""
        shards = self.shards()
        if not shards:
            return None
        rank = {lv: i for i, lv in enumerate(LEVELS)}
        if isinstance(at, tuple) and at[0] == "tick":
            n = int(at[1])
            cands = [e for e in shards if e["tick1"] <= n]
            key = "tick1"
        else:
            ts = float(at)
            cands = [e for e in shards if e["t1"] <= ts]
            key = "t1"
        if not cands:
            cands = shards
            return min(cands, key=lambda e: (e[key],
                                             rank[e["level"]]))
        return max(cands, key=lambda e: (e[key], -rank[e["level"]]))

    def resolve_window(self, t0: float, t1: float) -> list:
        """Shards SAMPLING the window ``[t0, t1]`` (their window end
        falls inside it), finest level first per span — coarse shards
        cover only ranges no finer shard samples. Oldest→newest."""
        sel: list = []
        covered: list = []
        for level in LEVELS:
            for e in self.shards(level):
                if not (t0 <= e["t1"] <= t1):
                    continue
                if any(c0 <= e["tick1"] <= c1 for c0, c1 in covered):
                    continue
                sel.append(e)
                covered.append((e["tick0"], e["tick1"]))
        sel.sort(key=lambda e: (e["tick1"], e["tick0"]))
        return sel

    def lag_seconds(self, now: Optional[float] = None) -> float:
        """Wall-clock distance from now to the newest shard's window
        end — the ``gyt_compact_lag_seconds`` gauge."""
        s = self.shards()
        if not s:
            return 0.0
        now = time.time() if now is None else now
        return max(0.0, now - max(e["t1"] for e in s))


class ShardStore(_ResolveMixin):
    """Manifest-driven shard directory: writers (the compactor) add
    shards and advance the position; readers (``timeview``) resolve
    ``at=``/``window=`` requests against the manifest only — a shard
    file not named by the manifest does not exist as far as queries
    are concerned."""

    def __init__(self, path, stats=None):
        self.dir = pathlib.Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.stats = stats if stats is not None else _NullStats()
        self._manifest_cache = None       # (mtime, size, dict)

    # --------------------------------------------------------- manifest
    def _mpath(self) -> pathlib.Path:
        return self.dir / MANIFEST

    def manifest(self) -> dict:
        """Current manifest (mtime-cached — queries re-read only after
        the compactor rewrote it)."""
        p = self._mpath()
        try:
            st = p.stat()
        except FileNotFoundError:
            return {"version": 1, "pos": None, "tick": 0, "shards": []}
        key = (st.st_mtime_ns, st.st_size)
        if self._manifest_cache and self._manifest_cache[0] == key:
            return self._manifest_cache[1]
        m = json.loads(p.read_text())
        self._manifest_cache = (key, m)
        return m

    def _write_manifest(self, m: dict) -> None:
        _atomic_json(self._mpath(), m)
        self._manifest_cache = None

    def position(self) -> Optional[tuple]:
        """The compactor's durable WAL position (``(seg, off)``) — the
        resume point, advanced only when a shard lands."""
        pos = self.manifest().get("pos")
        return tuple(pos) if pos else None

    def tick(self) -> int:
        """Window tick of the newest durable shard."""
        return int(self.manifest().get("tick", 0))

    # ----------------------------------------------------------- hygiene
    def sweep_stale_tmp(self) -> int:
        """Remove staging orphans a SIGKILL mid-write left behind (the
        ``checkpoint.sweep_stale_tmp`` discipline) plus shard files the
        manifest does not name (a crash between shard rename and
        manifest rewrite — they will be re-emitted by recompaction)."""
        n = 0
        named = {e["file"] for e in self.manifest().get("shards", [])}
        for p in list(self.dir.glob("*.tmp.npz")) \
                + list(self.dir.glob("*.json.tmp")):
            try:
                p.unlink()
                n += 1
            except OSError:       # pragma: no cover — already gone
                pass
        for p in self.dir.glob("gyt_shard_*.npz"):
            if p.name not in named:
                try:
                    p.unlink()
                    n += 1
                except OSError:   # pragma: no cover
                    pass
        if n:
            self.stats.bump("compact_tmp_swept", n)
        return n

    # ------------------------------------------------------------- write
    def add_shard(self, *, level: str, tick0: int, tick1: int,
                  t0: float, t1: float, state_leaves, dep_leaves,
                  columns: dict, cfg_fp: str = "",
                  wal_pos: Optional[tuple] = None,
                  replaces: Optional[list] = None,
                  deltas: Optional[dict] = None) -> dict:
        """Write one shard + advance the manifest atomically.

        ``columns`` maps subsys → ``(cols_dict, mask)``;
        ``replaces`` names manifest entries this shard supersedes (the
        downsample path: sources drop from the manifest in the SAME
        rewrite that adds the merged shard, then their files unlink);
        ``deltas`` maps panel name → {"key": (n,) keys, "hist": (n, B)
        window-delta histograms, optional "td": {means/weights/vmin/
        vmax}} — the per-window mergeable summaries true windowed
        quantiles merge (``history/winquant.py``)."""
        assert level in LEVELS, level
        name = _SHARD_FMT.format(level=level, tick0=int(tick0),
                                 tick1=int(tick1))
        payload: dict = {}
        for i, leaf in enumerate(state_leaves):
            payload[f"s{i}"] = np.asarray(leaf)
        for i, leaf in enumerate(dep_leaves):
            payload[f"d{i}"] = np.asarray(leaf)
        delta_meta: dict = {}
        for dname, d in (deltas or {}).items():
            keys = np.asarray(d["key"])
            payload[f"wd|{dname}|key"] = keys.astype("U") if len(keys) \
                else np.zeros(0, "U1")
            payload[f"wd|{dname}|hist"] = np.asarray(d["hist"],
                                                     np.float32)
            ent_meta = {"n": int(len(keys)),
                        "b": int(np.asarray(d["hist"]).shape[-1])}
            td = d.get("td")
            if td is not None:
                for k in ("means", "weights", "vmin", "vmax"):
                    payload[f"wt|{dname}|{k}"] = np.asarray(
                        td[k], np.float32)
                ent_meta["td"] = True
            delta_meta[dname] = ent_meta
        subsys_cols: dict = {}
        for subsys, (cols, mask) in columns.items():
            names = []
            for cname, arr in cols.items():
                arr = np.asarray(arr)
                if arr.dtype == object:
                    # fixed-width unicode: loads never need pickle
                    arr = arr.astype("U") if len(arr) else \
                        np.zeros(0, "U1")
                payload[_col_key(subsys, cname)] = arr
                names.append(cname)
            payload[f"m|{subsys}"] = np.asarray(mask, bool)
            subsys_cols[subsys] = names
        meta = {"level": level, "tick0": int(tick0), "tick1": int(tick1),
                "t0": float(t0), "t1": float(t1), "cfg": cfg_fp,
                "nstate": len(state_leaves), "ndep": len(dep_leaves),
                "cols": subsys_cols, "deltas": delta_meta,
                "wal": list(wal_pos) if wal_pos else None}
        payload["__meta__"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)
        nbytes = _atomic_npz(self.dir / name, payload)
        ent = {"file": name, "level": level, "tick0": int(tick0),
               "tick1": int(tick1), "t0": float(t0), "t1": float(t1),
               "bytes": int(nbytes)}
        m = self.manifest()
        drop = {e["file"] for e in (replaces or [])}
        shards = [e for e in m.get("shards", [])
                  if e["file"] not in drop and e["file"] != name]
        shards.append(ent)
        shards.sort(key=lambda e: (e["tick0"], e["tick1"]))
        m2 = dict(m)
        m2["version"] = 1
        m2["shards"] = shards
        if wal_pos is not None:
            m2["pos"] = list(wal_pos)
            m2["tick"] = max(int(m.get("tick", 0)), int(tick1))
        self._write_manifest(m2)
        for e in (replaces or []):       # sources are now unreferenced
            try:
                (self.dir / e["file"]).unlink()
            except OSError:              # pragma: no cover
                pass
        self.stats.bump("compact_shards")
        return ent

    def drop(self, ents: list) -> int:
        """Retention drop: remove entries from the manifest first, then
        unlink the files."""
        if not ents:
            return 0
        gone = {e["file"] for e in ents}
        m = dict(self.manifest())
        m["shards"] = [e for e in m.get("shards", [])
                       if e["file"] not in gone]
        self._write_manifest(m)
        for f in gone:
            try:
                (self.dir / f).unlink()
            except OSError:              # pragma: no cover
                pass
        self.stats.bump("compact_shards_dropped", len(gone))
        return len(gone)

    # -------------------------------------------------------------- read
    def shards(self, level: Optional[str] = None) -> list:
        out = self.manifest().get("shards", [])
        if level is not None:
            out = [e for e in out if e["level"] == level]
        return sorted(out, key=lambda e: (e["tick0"], e["tick1"]))

    def load(self, ent: dict) -> dict:
        """Load one shard → {"meta", "state" (leaf list), "dep" (leaf
        list), "columns" {subsys: (cols, mask)}, "deltas" {name:
        {"key", "hist", "td"?}}}. String columns come back as object
        arrays (the live column convention)."""
        with np.load(self.dir / ent["file"]) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            state = [z[f"s{i}"] for i in range(meta["nstate"])]
            dep = [z[f"d{i}"] for i in range(meta["ndep"])]
            columns = {}
            for subsys, names in meta.get("cols", {}).items():
                cols = {}
                for cname in names:
                    arr = z[_col_key(subsys, cname)]
                    if arr.dtype.kind == "U":
                        arr = arr.astype(object)
                    cols[cname] = arr
                columns[subsys] = (cols, z[f"m|{subsys}"])
            deltas = {}
            for dname, dm in meta.get("deltas", {}).items():
                d = {"key": z[f"wd|{dname}|key"],
                     "hist": z[f"wd|{dname}|hist"]}
                if dm.get("td"):
                    d["td"] = {k: z[f"wt|{dname}|{k}"]
                               for k in ("means", "weights",
                                         "vmin", "vmax")}
                deltas[dname] = d
        return {"meta": meta, "state": state, "dep": dep,
                "columns": columns, "deltas": deltas}


# ----------------------------------------------------------- parted store
PART_FMT = "part_{shard:02d}"


def part_dirs(root) -> list:
    """``part_NN`` sub-store directories of a parted shard root, shard
    order; empty for a flat (single-store) dir."""
    d = pathlib.Path(root)
    if not d.is_dir():
        return []
    out = []
    for p in sorted(d.glob("part_*")):
        if p.is_dir():
            try:
                out.append((int(p.name.split("_")[-1]), p))
            except ValueError:
                continue
    return [p for _i, p in sorted(out)]


class PartedShardStore(_ResolveMixin):
    """The parallel compactor's layout: ``part_NN/`` sub-stores (one
    per WAL shard, each a normal manifest-atomic :class:`ShardStore`
    written by its own replay worker) under a ROOT manifest that
    publishes only the windows EVERY part has durably emitted.

    The root manifest is the consistency boundary: the supervisor
    rewrites it (tmp+fsync+rename) only after a whole pass lands, so a
    SIGKILL at ANY worker boundary leaves either the old root (the new
    partial windows invisible — recompaction converges) or the new one
    — never a window naming a part that is missing it. Entries carry
    ``parts``: the per-part sub-entries, which ``timeview`` materializes
    WITHOUT funneling through one process-wide state."""

    def __init__(self, path, stats=None, nparts: Optional[int] = None):
        self.dir = pathlib.Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.stats = stats if stats is not None else _NullStats()
        self._manifest_cache = None
        if nparts is None:
            dirs = part_dirs(self.dir)
        else:
            dirs = [self.dir / PART_FMT.format(shard=s)
                    for s in range(int(nparts))]
        self.parts = [ShardStore(p, stats=self.stats) for p in dirs]

    # --------------------------------------------------------- manifest
    def _mpath(self) -> pathlib.Path:
        return self.dir / MANIFEST

    def manifest(self) -> dict:
        p = self._mpath()
        try:
            st = p.stat()
        except FileNotFoundError:
            return {"version": 2, "layout": "parted",
                    "nparts": len(self.parts), "pos": None, "tick": 0,
                    "shards": []}
        key = (st.st_mtime_ns, st.st_size)
        if self._manifest_cache and self._manifest_cache[0] == key:
            return self._manifest_cache[1]
        m = json.loads(p.read_text())
        self._manifest_cache = (key, m)
        return m

    def rebuild_root(self) -> dict:
        """Publish the intersection of the part manifests: a window is
        visible only at a (level, tick range) EVERY part carries (a
        killed pass leaves parts briefly divergent; the intersection
        shrinks, never lies — the next pass converges them). Also
        records the per-shard WAL resume positions (``[shard, seg,
        off]`` triples — ``journal.floors_of`` shape)."""
        per_part = [{(e["level"], e["tick0"], e["tick1"]): e
                     for e in p.shards()} for p in self.parts]
        ents = []
        if per_part:
            common = set(per_part[0])
            for d in per_part[1:]:
                common &= set(d)
            for key in sorted(common, key=lambda k: (k[1], k[2])):
                subs = [d[key] for d in per_part]
                ents.append({
                    "level": key[0], "tick0": key[1], "tick1": key[2],
                    "t0": min(e["t0"] for e in subs),
                    "t1": max(e["t1"] for e in subs),
                    "bytes": int(sum(e["bytes"] for e in subs)),
                    "parts": subs,
                })
        pos = []
        for s, p in enumerate(self.parts):
            pp = p.position()
            if pp is not None:
                pos.append([s, int(pp[0]), int(pp[1])])
        m = {"version": 2, "layout": "parted",
             "nparts": len(self.parts),
             "pos": pos or None,
             "tick": min((p.tick() for p in self.parts), default=0),
             "shards": ents}
        _atomic_json(self._mpath(), m)
        self._manifest_cache = None
        return m

    # ------------------------------------------------------------- read
    def shards(self, level: Optional[str] = None) -> list:
        out = self.manifest().get("shards", [])
        if level is not None:
            out = [e for e in out if e["level"] == level]
        return sorted(out, key=lambda e: (e["tick0"], e["tick1"]))

    def position(self) -> Optional[list]:
        pos = self.manifest().get("pos")
        return list(pos) if pos else None

    def tick(self) -> int:
        return int(self.manifest().get("tick", 0))

    def load_part(self, part: int, ent: dict) -> dict:
        return self.parts[part].load(ent)

    def sweep_stale_tmp(self) -> int:
        n = 0
        for p in self.parts:
            n += p.sweep_stale_tmp()
        for p in list(self.dir.glob("*.json.tmp")):
            try:
                p.unlink()
                n += 1
            except OSError:          # pragma: no cover
                pass
        return n


def is_parted(path) -> bool:
    """Detect the parted layout without loading anything: the root
    manifest says so, or ``part_NN`` sub-stores exist (first pass not
    yet published)."""
    d = pathlib.Path(path)
    p = d / MANIFEST
    if p.exists():
        try:
            return json.loads(p.read_text()).get("layout") == "parted"
        except (OSError, ValueError):    # pragma: no cover — torn root
            return bool(part_dirs(d))
    return bool(part_dirs(d))


def open_shard_store(path, stats=None):
    """THE store-opening entry: a parted root opens as a
    :class:`PartedShardStore`, anything else as the flat
    :class:`ShardStore` — runtime, CLI and smoke all route here so a
    shard dir written by either compactor serves identically."""
    if is_parted(path):
        return PartedShardStore(path, stats=stats)
    return ShardStore(path, stats=stats)
