"""Historical tier: day-partitioned relational store for state snapshots.

The reference keeps current state in memory and history in Postgres with
per-day partitioned tables (``server/gy_mdb_schema.cc:85-940``:
listenstatetbl, hoststatetbl, ... + partition create/cleanup functions).
Same design here: the live path is the device sketch readback; the
historical path is SQL over day-partitioned tables written on a cadence.

Backend: sqlite3 (stdlib) with day partitioning via table suffixes —
identical schema/semantics to the reference's approach; swapping the
connection for libpq gives the Postgres deployment (same SQL dialect for
everything used here).
"""

from gyeeta_tpu.history.store import HistoryStore, to_sql

__all__ = ["HistoryStore", "to_sql"]
