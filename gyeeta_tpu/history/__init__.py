"""Historical tier: day-partitioned relational store for state snapshots.

The reference keeps current state in memory and history in Postgres with
per-day partitioned tables (``server/gy_mdb_schema.cc:85-940``:
listenstatetbl, hoststatetbl, ... + partition create/cleanup functions).
Same design here: the live path is the device sketch readback; the
historical path is SQL over day-partitioned tables written on a cadence.

Backends behind one seam (``open_store``): sqlite3 (stdlib, default —
tests and single-box runs) and Postgres
(``--history-db postgresql://…`` → ``pgstore.PgHistoryStore``, the
reference's durable tier; day-table partition maintenance mirrors its
add/drop partition jobs).
"""

from gyeeta_tpu.history.store import HistoryStore, to_sql
from gyeeta_tpu.history.pgstore import PgHistoryStore, open_store

__all__ = ["HistoryStore", "PgHistoryStore", "open_store", "to_sql"]

# The time-travel tier (WAL compaction → columnar snapshot shards →
# windowed queries) lives beside the relational store:
#   history/shards.py    — shard files + manifest (ShardStore)
#   history/compactor.py — sealed-WAL → shard compaction daemon
#   history/timeview.py  — at=/window= query materialization
#   history/histwriter.py — batched single-writer thread for this store
# (imported lazily by the runtimes to keep cold-start imports light)
