"""Batched single-writer thread for the relational history store.

The per-tick ``self.history.write(...)`` block used to run SYNCHRONOUS
SQL inside ``run_tick`` — a slow sqlite fsync or a stalled Postgres
round trip stalled the fold thread for its full duration (the exact
inversion the WAL writer thread already fixed for the journal). Now
the tick loop only renders the snapshot rows (device readbacks must
stay on the fold thread) and ENQUEUES the sweep; one writer thread
owns every store write.

Discipline (mirrors ``utils/journal.py``):
- bounded queue (``history_queue_max`` sweeps): when the DB outruns the
  tick cadence the OLDEST queued sweeps drop, COUNTED
  (``history_write_dropped`` / ``_rows``), never silently; queue depth
  rides the ``gyt_history_write_queue_depth`` gauge;
- read-your-writes where it matters: ``barrier()`` drains the queue
  before db-mode alertdef evaluation and historical SQL queries, so
  only the paths that actually read the store pay for ordering;
- ``close()`` drains and joins (graceful shutdown loses nothing).

Store access is serialized by the store's own lock (``HistoryStore``
methods are thread-safe), so reader threads and this writer share one
connection safely.
"""

from __future__ import annotations

import collections
import threading
from typing import Iterable, Optional


class _NullStats:
    def bump(self, name, n=1):
        pass

    def gauge(self, name, v):
        pass


class HistoryWriter:
    def __init__(self, store, stats=None, max_queue: int = 64):
        self.store = store
        self.stats = stats if stats is not None else _NullStats()
        self.max_queue = max(1, int(max_queue))
        self._cv = threading.Condition()
        self._q: collections.deque = collections.deque()
        self._busy = False                # a sweep is mid-write
        self._closing = False
        self._worker = threading.Thread(target=self._loop,
                                        name="gyt-hist-writer",
                                        daemon=True)
        self._worker.start()

    def write_sweep(self, items: Iterable[tuple]) -> None:
        """Enqueue one tick's sweep: ``[(subsys, t, rows), ...]``. The
        fold thread returns in microseconds; a full queue drops the
        OLDEST sweep, counted."""
        items = list(items)
        if not items:
            return
        with self._cv:
            if self._closing:
                return
            while len(self._q) >= self.max_queue:
                old = self._q.popleft()
                self.stats.bump("history_write_dropped")
                self.stats.bump("history_write_dropped_rows",
                                sum(len(r) for _s, _t, r in old))
            self._q.append(items)
            self.stats.gauge("history_write_queue_depth",
                             float(len(self._q)))
            self._cv.notify_all()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closing:
                    self._cv.wait(timeout=0.5)
                if not self._q and self._closing:
                    self._cv.notify_all()
                    return
                items = self._q.popleft()
                self._busy = True
                self.stats.gauge("history_write_queue_depth",
                                 float(len(self._q)))
            try:
                for subsys, t, rows in items:
                    self.store.write(subsys, t, rows)
                    self.stats.bump("history_write_rows", len(rows))
                self.stats.bump("history_write_sweeps")
            except Exception:     # noqa: BLE001 — a failing DB must
                #                   not kill the writer; counted loss
                self.stats.bump("history_write_errors")
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def barrier(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued sweep is durably in the store
        (the read-your-writes gate for db-mode alertdefs and
        historical SQL queries). Returns False on timeout."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while (self._q or self._busy) \
                    and self._worker.is_alive():
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    def close(self) -> None:
        """Drain + join (idempotent)."""
        with self._cv:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
        self._worker.join(timeout=30.0)
