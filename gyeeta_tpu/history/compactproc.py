"""Parallel history compaction: per-shard WAL replay worker processes.

The compactor was the last single-process bottleneck of the history
tier: one replay Runtime consumed the WHOLE sharded WAL through a
k-way tick merge (``history/compactor.py``). But the sharded WAL is
host-partitioned — records in different ``shard_NN/`` subdirs are
host-DISJOINT (a host hashes to exactly one shard, PR 10), so each
shard's sealed stream can replay through its OWN per-shard runtime
with no cross-shard interaction at all. That per-shard decomposition
is this module's canonical unit of work:

- ``--compact-procs N`` runs N spawned WORKER processes (fresh
  interpreters, CPU jax — the workers never touch the serving
  process's device state). WAL shard ``s`` goes to worker ``s % N``
  (the PR-12 sticky-group idiom); each worker runs a stock
  :class:`~gyeeta_tpu.history.compactor.Compactor` per shard over that
  shard's subdir (a flat journal dir) into its own ``part_NN/``
  sub-store, with per-shard resume positions in the part manifests.
  Replay of one shard is deterministic (append order × tick stamps),
  so the parts are BIT-IDENTICAL for any worker count — ``procs=1``
  and ``procs=8`` produce the same bytes, only the wall clock moves.

- The SUPERVISOR owns everything that needs the live journal: it
  seals, snapshots each shard's sealed bound (workers read at most
  that far — they must never chase a segment the live writer still
  owns), and after a pass rebuilds the parted store's ROOT manifest
  (``shards.PartedShardStore.rebuild_root``: the intersection of part
  windows, written tmp+fsync+rename). A SIGKILL at any worker
  boundary therefore leaves either the old root (new windows
  invisible; parts converge on the next pass) or the new one — never
  a window some part has not durably emitted. Truncate floors hand
  back per shard (``journal.floors_of`` triples), exactly like the
  single-process compactor.

- Queries serve the parted layout through
  ``timeview.PartedSnapshot`` — per-part materialization merged at
  column level, never funneled through one process-wide replay state.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import threading
import time
from typing import Optional

from gyeeta_tpu.history import shards as SH
from gyeeta_tpu.utils import journal as J

log = logging.getLogger("gyeeta_tpu.history.compactproc")


class _NullStats:
    def bump(self, name, n=1):
        pass

    def gauge(self, name, v):
        pass

    def timeit(self, name):
        import contextlib
        return contextlib.nullcontext()


def _part_group_worker(cfg, opts, jobs, upto_tick, q) -> None:
    """One worker process: replay each assigned WAL shard through a
    per-shard Compactor (sequentially — parallelism is ACROSS
    workers). Runs in a fresh interpreter; force the CPU backend
    before jax loads so a TPU-serving host never has its devices
    claimed by replay workers."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import resource
    import traceback

    try:
        from gyeeta_tpu.history.compactor import Compactor
        from gyeeta_tpu.utils.selfstats import Stats
        # bench methodology knob (bench.py compact_par): replay a WAL
        # prefix first so the measured pass's rusage is steady-state
        # (fold compiles + XLA cache loads land in the warm pass —
        # the in-process jit memo carries them into the measured one)
        warm = os.environ.get("GYT_COMPACT_WARM_SEQ")
        for shard, jdir, pdir, upto in jobs:
            if warm:
                wt = os.environ.get("GYT_COMPACT_WARM_TICK")
                cw = Compactor(cfg, opts, journal_dir=jdir,
                               shard_dir=pdir, stats=Stats(),
                               upto_seq=int(warm))
                try:
                    cw.compact_once(
                        upto_tick=int(wt) if wt else None)
                finally:
                    cw.close()
            st = Stats()
            r0 = resource.getrusage(resource.RUSAGE_SELF)
            t0 = time.perf_counter()
            c = Compactor(cfg, opts, journal_dir=jdir, shard_dir=pdir,
                          stats=st, upto_seq=upto)
            try:
                rep = c.compact_once(upto_tick=upto_tick)
            finally:
                c.close()
            r1 = resource.getrusage(resource.RUSAGE_SELF)
            rep["cpu_s"] = round((r1.ru_utime - r0.ru_utime)
                                 + (r1.ru_stime - r0.ru_stime), 4)
            rep["wall_s"] = round(time.perf_counter() - t0, 4)
            rep["counters"] = dict(st.counters)
            # crash injection for the SIGKILL-at-every-worker-boundary
            # consistency test: die HERE — this shard's part manifest
            # is durable, the supervisor's root manifest is not — with
            # no cleanup, exactly like a SIGKILL
            if os.environ.get("GYT_COMPACT_DIE_SHARD") == str(shard):
                os._exit(9)
            q.put(("ok", shard, rep))
        q.put(("done", os.getpid(), None))
    except BaseException:           # noqa: BLE001 — surfaces upstream
        q.put(("err", os.getpid(), traceback.format_exc()))


class ParallelCompactor:
    """Drop-in sibling of :class:`Compactor` (same ``compact_once`` /
    ``start`` / ``stop`` / ``close`` surface) that writes the PARTED
    store layout via N replay worker processes."""

    def __init__(self, cfg, opts, procs: int, *, journal=None,
                 journal_dir: Optional[str] = None,
                 shard_dir: Optional[str] = None, stats=None,
                 clock=None):
        self.cfg = cfg
        self.opts = opts
        self.journal = journal
        self.journal_dir = journal_dir or opts.journal_dir
        if not self.journal_dir:
            raise ValueError("compaction needs a journal dir (the WAL "
                             "is the history source)")
        self.subdirs = J.sharded_subdirs(self.journal_dir)
        if not self.subdirs:
            raise ValueError(
                "--compact-procs needs a SHARDED WAL (shard_NN/ "
                "subdirs, serve --shards); a flat journal has no "
                "shard boundaries to parallelize across")
        self.procs = max(1, int(procs))
        if self.procs > len(self.subdirs):
            raise ValueError(
                f"--compact-procs {self.procs} > {len(self.subdirs)} "
                "WAL shards: workers beyond the shard count would "
                "idle (parallelism is at shard boundaries)")
        self.stats = stats if stats is not None else _NullStats()
        self.store = SH.PartedShardStore(
            shard_dir or opts.hist_shard_dir, stats=self.stats,
            nparts=len(self.subdirs))
        self.store.sweep_stale_tmp()
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._workers: list = []      # live worker Process objects
        #                               (tests SIGKILL them mid-pass)

    # --------------------------------------------------------- one pass
    def compact_once(self, seal: bool = False,
                     upto_tick: Optional[int] = None) -> dict:
        with self._lock:
            return self._compact_once(seal, upto_tick)

    def _compact_once(self, seal, upto_tick) -> dict:
        t0 = time.perf_counter()
        if seal and self.journal is not None:
            self.journal.seal_active()
        uptos = self.journal.sealed_upto() \
            if self.journal is not None else [None] * len(self.subdirs)
        if not isinstance(uptos, (list, tuple)):
            uptos = [uptos] * len(self.subdirs)
        jobs_of = {w: [] for w in range(self.procs)}
        for s, sub in enumerate(self.subdirs):
            pdir = self.store.dir / SH.PART_FMT.format(shard=s)
            jobs_of[s % self.procs].append(
                (s, str(sub), str(pdir),
                 uptos[s] if s < len(uptos) else None))
        reports = self._run_workers(jobs_of, upto_tick)
        # every part landed durably → publish the new root view; the
        # rebuild is the pass's ONLY root-manifest write (atomic)
        self.store.rebuild_root()
        if self.journal is not None:
            pos = self.store.position()
            if pos:
                self.journal.set_truncate_floor(J.floors_of(pos))
        secs = max(time.perf_counter() - t0, 1e-9)
        nrec = sum(r["records"] for r in reports.values())
        windows = sum(r["windows"] for r in reports.values())
        dropped = sum(r["retention_dropped"] for r in reports.values())
        if nrec:
            self.stats.gauge("compact_replay_ev_per_sec",
                             round(nrec / secs, 1))
        self.stats.gauge("compact_par_workers", float(self.procs))
        self.stats.gauge("compact_lag_seconds",
                         round(self.store.lag_seconds(self._clock()),
                               3))
        self.stats.bump("compact_passes")
        for r in reports.values():
            for k, v in r.get("counters", {}).items():
                if k.startswith(("compact_", "wd_", "wal_", "replay")):
                    self.stats.bump(k, v)
        return {"chunks": sum(r["chunks"] for r in reports.values()),
                "records": nrec, "windows": windows,
                "ev_per_sec": round(nrec / secs, 1),
                "secs": round(secs, 4), "retention_dropped": dropped,
                "tick": self.store.tick(), "workers": self.procs,
                "per_shard": {s: {"records": r["records"],
                                  "windows": r["windows"],
                                  "cpu_s": r["cpu_s"],
                                  "wall_s": r["wall_s"]}
                              for s, r in sorted(reports.items())}}

    def _run_workers(self, jobs_of: dict, upto_tick) -> dict:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = []
        for w, jobs in jobs_of.items():
            if not jobs:
                continue
            p = ctx.Process(target=_part_group_worker,
                            args=(self.cfg, self.opts, jobs,
                                  upto_tick, q),
                            daemon=True,
                            name=f"gyt-compact-w{w}")
            p.start()
            procs.append(p)
        self._workers = procs
        reports: dict = {}
        failures: list = []
        pending = len(procs)
        import queue as _queue
        try:
            while pending:
                try:
                    kind, key, payload = q.get(timeout=0.5)
                except _queue.Empty:
                    # a SIGKILLed worker never sends "done" — notice
                    # its corpse instead of blocking the pass forever
                    if all(not p.is_alive() for p in procs):
                        break
                    continue
                if kind == "ok":
                    reports[key] = payload
                elif kind == "err":
                    failures.append(payload)
                    pending -= 1
                else:                      # "done"
                    pending -= 1
        except (EOFError, OSError):        # pragma: no cover
            pass
        while True:                        # late in-flight messages
            try:
                kind, key, payload = q.get_nowait()
            except (_queue.Empty, EOFError, OSError):
                break
            if kind == "ok":
                reports[key] = payload
            elif kind == "err":
                failures.append(payload)
        for p in procs:
            p.join(timeout=60.0)
            if p.exitcode not in (0, None) and not failures:
                failures.append(
                    f"worker {p.name} exited {p.exitcode} (killed "
                    "mid-pass?) — root manifest NOT advanced")
        self._workers = []
        missing = [s for s in range(len(self.subdirs))
                   if s not in reports]
        if failures or missing:
            self.stats.bump("compact_par_worker_failures")
            raise RuntimeError(
                "parallel compaction pass failed "
                f"(missing shards {missing}): "
                + ("; ".join(failures) or "worker died"))
        return reports

    # ------------------------------------------------------------- daemon
    def start(self, interval: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        interval = float(interval
                         if interval is not None
                         else self.opts.hist_compact_interval_s)
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    rep = self.compact_once(seal=True)
                    if rep["windows"]:
                        log.info("compacted %d window(s) across %d "
                                 "worker(s), %d chunk(s), %.0f ev/s",
                                 rep["windows"], rep["workers"],
                                 rep["chunks"], rep["ev_per_sec"])
                except Exception:     # noqa: BLE001 — daemon survives
                    self.stats.bump("compact_errors")
                    log.exception("parallel compaction pass failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="gyt-compactor-par")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=60.0)
        self._thread = None

    def close(self) -> None:
        self.stop()
        for p in self._workers:       # pragma: no cover — abnormal
            if p.is_alive():
                p.terminate()
        self._workers = []
