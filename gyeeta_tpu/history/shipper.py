"""Source-region segment shipper: sealed WAL segments → remote staging.

The sending half of the segment-ship protocol (``net/segship.py`` has
the wire format, the receiver, and the crash-consistency contract).
A :class:`SegmentShipper` runs beside the source journal — in the
serve process (``serve --ship-to HOST:PORT``) or standalone over a
WAL directory (``gyeeta_tpu ship``) — and repeatedly:

1. scans the journal for SEALED segments (``sealed_upto`` bounds the
   scan when a live journal is attached; in offline dir mode every
   present segment is sealed — the dir must have no live writer),
2. ships each not-yet-terminal segment in ascending seq order per
   shard: one content-hashing read pass (blake2b + chunk count), a
   ``T_SMETA`` announce, then raw ``T_SDATA`` frames from the offset
   the receiver already holds (per-segment RESUME after any
   disconnect or SIGKILL on either side),
3. advances the journal's NAMED ship truncate floor
   (``set_truncate_floor(floor, name="ship")``) to the oldest
   non-terminal seq — checkpoint truncation can never delete a
   sealed-but-unshipped segment, so the ship tier's disk pin is
   exactly (sealed − landed) segments,
4. heartbeats its cumulative counters + the monotone
   ``sealed_segments`` high-water so the receiver's global ledger
   (``sealed == shipped + counted drops``) includes segments that
   never made it off the box.

Supervised like the relay worker: jittered reconnect backoff, one
instance token per process run (the receiver's epoch boundary). A
terminal receiver verdict (``done``/``shed``/``conflict``) marks the
key locally so steady state re-announces nothing; a shipper restart
re-announces everything still on disk and the receiver's ledger
answers ``done`` instantly.

Optional bounded pinned backlog (``GYT_SHIP_PIN_MB`` > 0): when the
floor-pinned unshipped bytes exceed the bound (a long receiver
outage), the OLDEST unshipped segment is announced as a permanent
``T_SDROP`` — a counted ledger drop, never silence — and the floor
advances. Default 0 = unbounded: disk pays for exactness
(OPERATIONS.md "Remote compaction region" sizes it).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pathlib
import random
import socket
import struct
import time
import uuid
from typing import Optional

from gyeeta_tpu.net import segship as SP
from gyeeta_tpu.utils import journal as J

log = logging.getLogger("gyeeta_tpu.history.shipper")


def pin_max_bytes(env=None) -> int:
    env = os.environ if env is None else env
    mb = int(env.get("GYT_SHIP_PIN_MB", "0") or 0)
    return mb << 20


def seg_info(path) -> tuple[int, str, int]:
    """One read pass over a sealed segment: (size, blake2b hex,
    record count). Records are WAL chunks; a torn header ends the
    count cleanly (sealed segments should have none — the writer
    truncates torn tails on open — but a foreign copy might)."""
    data = pathlib.Path(path).read_bytes()
    h = hashlib.blake2b(data, digest_size=32).hexdigest()
    nrec = 0
    off = len(J.MAGIC)
    whdr = J._WHDR
    while off + whdr.size <= len(data):
        try:
            _t, nbytes, _hid, _tick, _cid = whdr.unpack_from(data, off)
        except struct.error:               # pragma: no cover
            break
        if off + whdr.size + nbytes > len(data):
            break
        nrec += 1
        off += whdr.size + nbytes
    return len(data), h, nrec


class SegmentShipper:
    """Threaded blocking-socket uplink shipping sealed segments to a
    :class:`~gyeeta_tpu.net.segship.SegmentReceiver`. ``cfg`` keys:

    - ``target``: (host, port) of the receiver,
    - ``shipper_id``: stable source identity (the provenance key),
    - ``journal``: live Journal / ShardedJournal (ship floor + sealed
      bound), or None with
    - ``dir``: offline WAL root (every segment treated as sealed),
    - ``stats``: source-side Stats registry (``ship_*`` rows),
    - ``scan_s`` / ``hb_s`` / ``chunk_bytes`` / ``pin_bytes`` knobs,
    - ``once``: one full pass then stop (the CLI's batch mode).
    """

    def __init__(self, cfg: dict):
        from gyeeta_tpu.utils.journal import _NullStats
        self.cfg = dict(cfg)
        self.target = tuple(cfg["target"])
        self.shipper_id = str(cfg.get("shipper_id")
                              or f"ship-{socket.gethostname()}")
        self.journal = cfg.get("journal")
        d = cfg.get("dir")
        if self.journal is not None:
            self.dir = pathlib.Path(self.journal.dir)
        elif d is not None:
            self.dir = pathlib.Path(d)
        else:
            raise ValueError("SegmentShipper needs a journal or a dir")
        self.stats = cfg.get("stats") or _NullStats()
        env = cfg.get("env") or os.environ
        self.scan_s = float(cfg.get("scan_s",
                                    env.get("GYT_SHIP_SCAN_S", 0.5)))
        self.hb_s = float(cfg.get("hb_s", SP.hb_interval_s(env)))
        self.chunk = int(cfg.get("chunk_bytes", SP.chunk_bytes(env)))
        self.pin_max = int(cfg.get("pin_bytes", pin_max_bytes(env)))
        self.once = bool(cfg.get("once"))
        self.token = uuid.uuid4().hex[:16]
        self.running = True
        self._sock: Optional[socket.socket] = None
        self._rbuf = b""
        self._backoff = 0.1
        self._last_hb = 0.0
        self._done: set[tuple[int, int]] = set()   # terminal keys
        self._counted: set[tuple[int, int]] = set()  # sealed-counted
        self._floors: dict[int, int] = {}
        # crash injection for the chaos smoke: _exit(9) right after
        # the k-th segment reaches a terminal verdict — the SIGKILL-at
        # -every-ship-boundary probe
        self._die_after = int(env.get("GYT_SHIP_DIE_AFTER_ACKS", "0")
                              or 0)
        self._acks = 0
        # layout: sharded journals own shard_NN/ subdirs; a flat dir
        # ships as shard 0 into the staging root. Duck-typed across
        # Journal, ShardedJournal and the mproc ProcWalView (n +
        # subdir_fmt, no .shards list).
        sharded = False
        if self.journal is not None:
            shards = getattr(self.journal, "shards", None)
            if shards is not None:         # ShardedJournal
                self.subdirs = [pathlib.Path(j.dir) for j in shards]
                sharded = True
            elif int(getattr(self.journal, "n", 1)) > 1:
                fmt = getattr(self.journal, "subdir_fmt",
                              "shard_{:02d}")   # mproc ProcWalView
                self.subdirs = [self.dir / fmt.format(s)
                                for s in range(self.journal.n)]
                sharded = True
            else:
                self.subdirs = [self.dir]
        else:
            subs = J.sharded_subdirs(self.dir)
            sharded = bool(subs)
            self.subdirs = list(subs) or [self.dir]
        self.layout = "sharded" if sharded else "flat"

    # ------------------------------------------------------------ socket
    def _connect(self) -> bool:
        try:
            s = socket.create_connection(self.target, timeout=10.0)
            s.settimeout(30.0)
            self._sock, self._rbuf = s, b""
            self._send(SP.jframe(SP.T_SHELLO, {
                "shipper_id": self.shipper_id, "token": self.token,
                "pid": os.getpid(), "layout": self.layout,
                "nshards": len(self.subdirs),
                "host": socket.gethostname()}))
            ftype, msg = self._recv_json()
            if ftype != SP.T_SHELLO_OK or not msg.get("ok"):
                log.warning("ship hello refused: %s", msg)
                self.stats.bump("ship_hello_refused")
                self._drop_sock()
                return False
            self._backoff = 0.1
            self.stats.gauge("ship_uplink_up", 1.0)
            return True
        except (OSError, ValueError):
            self._drop_sock()
            return False

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:                # pragma: no cover
                pass
            self._sock = None
            self.stats.bump("ship_reconnects")
            self.stats.gauge("ship_uplink_up", 0.0)
        self._rbuf = b""

    def _send(self, buf: bytes) -> None:
        self._sock.sendall(buf)

    def _recv_frame(self) -> tuple[int, bytes]:
        need = SP._FH.size
        while len(self._rbuf) < need:
            b = self._sock.recv(65536)
            if not b:
                raise ConnectionError("ship uplink closed")
            self._rbuf += b
        magic, ftype, _fl, blen = SP._FH.unpack_from(self._rbuf, 0)
        if magic != SP.SHIP_MAGIC or blen >= SP.MAX_BODY:
            raise ValueError("bad ship frame")
        need = SP._FH.size + blen
        while len(self._rbuf) < need:
            b = self._sock.recv(65536)
            if not b:
                raise ConnectionError("ship uplink closed")
            self._rbuf += b
        body = self._rbuf[SP._FH.size:need]
        self._rbuf = self._rbuf[need:]
        return ftype, body

    def _recv_json(self) -> tuple[int, dict]:
        import json
        ftype, body = self._recv_frame()
        return ftype, (json.loads(body) if body else {})

    # -------------------------------------------------------------- scan
    def _sealed_bounds(self) -> list[Optional[int]]:
        """Per-shard EXCLUSIVE sealed bound; None = every present
        segment is sealed (offline dir mode)."""
        if self.journal is None:
            return [None] * len(self.subdirs)
        u = self.journal.sealed_upto()
        if isinstance(u, (list, tuple)):
            return [int(x) for x in u]
        return [int(u)]

    def _pending(self) -> list[tuple[int, int, pathlib.Path]]:
        """(shard, seq, path) of sealed, non-terminal segments,
        shard-major ascending-seq (the floor advances in order)."""
        out = []
        bounds = self._sealed_bounds()
        for s, sub in enumerate(self.subdirs):
            bound = bounds[s] if s < len(bounds) else None
            for seq in J.dir_segments(sub):
                if bound is not None and seq >= bound:
                    continue
                if (s, seq) in self._done:
                    continue
                out.append((s, seq, sub / J._SEG_FMT.format(seq)))
        return out

    def _advance_floor(self) -> None:
        """Ship floor per shard: the oldest non-terminal sealed seq
        (or the sealed bound when nothing is pending). Registered
        under the "ship" name so truncation bounds at
        min(checkpoint, compactor, ship)."""
        if self.journal is None:
            return
        bounds = self._sealed_bounds()
        floors = []
        for s, sub in enumerate(self.subdirs):
            bound = bounds[s] if s < len(bounds) else None
            segs = [q for q in J.dir_segments(sub)
                    if bound is None or q < bound]
            pend = [q for q in segs if (s, q) not in self._done]
            if pend:
                fl = min(pend)
            elif bound is not None:
                fl = bound
            else:
                fl = (max(segs) + 1) if segs else 0
            floors.append(int(fl))
            self._floors[s] = int(fl)
        if len(self.subdirs) > 1:
            self.journal.set_truncate_floor(floors, name="ship")
        else:
            self.journal.set_truncate_floor(floors[0], name="ship")
        self.stats.gauge("ship_floor_segments",
                         float(sum(floors)))

    def _count_sealed(self) -> None:
        """Source-side sealed ledger: segment count is the monotone
        per-shard sealed_upto sum (survives restarts + truncation);
        records/bytes bump once per newly observed key (cumulative,
        delta-folded by the receiver per epoch)."""
        bounds = self._sealed_bounds()
        if all(b is not None for b in bounds):
            total = sum(bounds)
        else:
            total = sum(len(J.dir_segments(sub))
                        for sub in self.subdirs)
        self.stats.gauge("ship_sealed_segments", float(total))
        self._sealed_total = total

    # -------------------------------------------------------------- ship
    def _ship_one(self, shard: int, seq: int,
                  path: pathlib.Path) -> bool:
        """Announce + stream one segment to a terminal verdict.
        Returns True when the key reached a terminal state."""
        import json
        try:
            size, digest, nrec = seg_info(path)
        except OSError:
            return False                   # raced truncation; rescan
        if (shard, seq) not in self._counted:
            self._counted.add((shard, seq))
            self.stats.bump("ship_sealed_records", nrec)
            self.stats.bump("ship_sealed_bytes", size)
        meta = {"shard": shard, "seq": seq, "size": size,
                "hash": digest, "nrec": nrec,
                "src": {"host": socket.gethostname(),
                        "pid": os.getpid()}}
        self._send(SP.jframe(SP.T_SMETA, meta))
        ftype, resp = self._recv_json()
        if ftype != SP.T_SRESP:
            raise ValueError("expected SRESP")
        status = resp.get("status")
        if status in ("done", "shed", "conflict"):
            if status != "done":
                self.stats.bump("ship_dropped_segments")
                self.stats.bump("ship_dropped_records", nrec)
                self.stats.bump("ship_dropped_bytes", size)
                if status == "conflict":
                    self.stats.bump("ship_hash_conflicts")
            else:
                self._bump_shipped(nrec, size)
            self._terminal(shard, seq)
            return True
        if status != "send":
            raise ValueError(f"bad SRESP status {status!r}")
        off = int(resp.get("off", 0))
        if off:
            self.stats.bump("ship_resumed_bytes", off)
        with open(path, "rb") as f:
            f.seek(off)
            while True:
                b = f.read(self.chunk)
                if not b:
                    break
                self._send(SP.frame(SP.T_SDATA, b))
        self._send(SP.jframe(SP.T_SEND, {}))
        ftype, ack = self._recv_json()
        if ftype != SP.T_SACK:
            raise ValueError("expected SACK")
        if not ack.get("ok"):
            # wire corruption — the receiver discarded the partial;
            # re-announce re-ships the immutable bytes from scratch
            self.stats.bump("ship_hash_retries")
            return False
        self._bump_shipped(nrec, size)
        self._terminal(shard, seq)
        return True

    def _bump_shipped(self, nrec: int, size: int) -> None:
        self.stats.bump("ship_shipped_segments")
        self.stats.bump("ship_shipped_records", nrec)
        self.stats.bump("ship_shipped_bytes", size)

    def _terminal(self, shard: int, seq: int) -> None:
        self._done.add((shard, seq))
        self._acks += 1
        if self._die_after and self._acks >= self._die_after:
            os._exit(9)                    # chaos: die AT the boundary

    def _shed_backlog(self) -> None:
        """Bounded pinned backlog: with GYT_SHIP_PIN_MB set, a
        receiver outage longer than the bound sheds the OLDEST
        unshipped segments as announced permanent drops (counted at
        both ends) instead of pinning disk forever."""
        if not self.pin_max:
            return
        pend = self._pending()
        total = 0
        sizes = {}
        for s, q, p in pend:
            try:
                sizes[(s, q)] = p.stat().st_size
                total += sizes[(s, q)]
            except OSError:
                sizes[(s, q)] = 0
        for s, q, p in pend:               # oldest-first per shard
            if total <= self.pin_max:
                break
            try:
                size, digest, nrec = seg_info(p)
            except OSError:
                continue
            try:
                self._send(SP.jframe(SP.T_SDROP, {
                    "shard": s, "seq": q, "size": size, "nrec": nrec,
                    "hash": digest, "reason": "source_shed"}))
                ftype, ack = self._recv_json()
                if ftype != SP.T_SACK or not ack.get("ok"):
                    continue
            except (OSError, ValueError, ConnectionError):
                raise
            self.stats.bump("ship_dropped_segments")
            self.stats.bump("ship_dropped_records", nrec)
            self.stats.bump("ship_dropped_bytes", size)
            self._terminal(s, q)
            total -= sizes.get((s, q), 0)

    def _heartbeat(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_hb < self.hb_s:
            return
        self._last_hb = now
        snap = getattr(self.stats, "snapshot", None)
        ctrs = {k: v for k, v in (snap() if snap else {}).items()
                if isinstance(v, (int, float))
                and str(k).startswith("ship_")}
        self._send(SP.jframe(SP.T_SHB, {
            "counters": ctrs,
            "sealed_segments": getattr(self, "_sealed_total", 0)}))

    # --------------------------------------------------------------- run
    def run(self) -> None:
        """Supervised loop: connect → ship pending → floor → idle
        scan. ``stop()`` (or ``once``) ends it."""
        while self.running:
            if self._sock is None:
                if not self._connect():
                    time.sleep(self._backoff
                               * (1.0 + random.random() * 0.25))
                    self._backoff = min(self._backoff * 2, 5.0)
                    continue
            try:
                pending = self._pending()
                self._count_sealed()
                progressed = False
                for s, q, p in pending:
                    if not self.running:
                        break
                    if self._ship_one(s, q, p):
                        progressed = True
                    self._advance_floor()
                    self._heartbeat()
                self._shed_backlog()
                self._advance_floor()
                self._heartbeat(force=progressed)
                if self.once and not self._pending():
                    self._heartbeat(force=True)
                    break
                t_end = time.monotonic() + self.scan_s
                while self.running and time.monotonic() < t_end:
                    self._heartbeat()
                    time.sleep(min(0.05, self.scan_s))
            except (ConnectionError, OSError, ValueError) as e:
                log.info("ship uplink lost (%s); reconnecting", e)
                self._drop_sock()
        self._drop_sock()

    def stop(self) -> None:
        self.running = False

    def ship_once(self) -> dict:
        """Blocking single pass (the CLI batch mode): ship every
        sealed segment to a terminal verdict, return the local
        counters."""
        self.once = True
        self.run()
        snap = getattr(self.stats, "snapshot", None)
        return {k: v for k, v in (snap() if snap else {}).items()
                if str(k).startswith("ship_")}


# ======================================================================
# CLI entry (the source-region process)
# ======================================================================

def ship_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu ship",
        description="ship sealed WAL segments to a remote compaction "
                    "region's staging receiver (net/segship.py)")
    ap.add_argument("--dir", required=True,
                    help="WAL root (flat or shard_NN/) — must have no "
                         "live writer in dir mode")
    ap.add_argument("--to", required=True,
                    help="HOST:PORT of the segment receiver")
    ap.add_argument("--id", default=None, help="stable shipper id")
    ap.add_argument("--once", action="store_true",
                    help="one full pass, then exit (default: follow)")
    ap.add_argument("--scan-s", type=float, default=None)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s shipper %(message)s")
    from gyeeta_tpu.utils.selfstats import Stats
    host, _, port = args.to.rpartition(":")
    cfg = {"target": (host or "127.0.0.1", int(port)),
           "shipper_id": args.id, "dir": args.dir, "stats": Stats(),
           "once": args.once}
    if args.scan_s is not None:
        cfg["scan_s"] = args.scan_s
    sh = SegmentShipper(cfg)
    print(f"SHIP_RUN id={sh.shipper_id} layout={sh.layout} "
          f"shards={len(sh.subdirs)}", flush=True)
    if args.once:
        rep = sh.ship_once()
        print("SHIP_DONE "
              f"shipped={rep.get('ship_shipped_segments', 0)} "
              f"dropped={rep.get('ship_dropped_segments', 0)}",
              flush=True)
    else:
        import signal

        def _stop(_sig, _frm):
            sh.stop()
        try:
            signal.signal(signal.SIGTERM, _stop)
            signal.signal(signal.SIGINT, _stop)
        except ValueError:                 # non-main thread (tests)
            pass
        sh.run()
    return 0


if __name__ == "__main__":                 # pragma: no cover
    raise SystemExit(ship_main())
