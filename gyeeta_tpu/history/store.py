"""Day-partitioned history store + criteria→SQL dual execution.

Mirrors the reference's split (``common/gy_query_criteria.h``: every
criterion can both evaluate in-memory and emit a SQL WHERE clause): the
query layer evaluates criteria columnar on live snapshots, while this
module translates the same expression tree to SQL for the historical
path. Comparators without a clean SQL form (``like``/``notlike`` regex)
fall back to a post-filter in Python — flagged by ``to_sql``.
"""

from __future__ import annotations

import datetime
import json
import sqlite3
from typing import Iterable, Optional

from gyeeta_tpu.query import criteria as C
from gyeeta_tpu.query import fieldmaps

# subsys → persisted columns (json field names; enum codecs applied on
# write so history stores presentation values like the reference DB does
# for state strings via statetojson)
_TABLES = {
    "svcstate": [f.json for f in fieldmaps.SVCSTATE_FIELDS],
    "hoststate": [f.json for f in fieldmaps.HOSTSTATE_FIELDS],
    "clusterstate": [f.json for f in fieldmaps.CLUSTERSTATE_FIELDS],
    "taskstate": [f.json for f in fieldmaps.TASKSTATE_FIELDS],
}


def _day_of(t: float) -> str:
    return datetime.datetime.fromtimestamp(
        t, datetime.timezone.utc).strftime("%Y%m%d")


def _table(subsys: str, day: str) -> str:
    return f"{subsys}tbl_{day}"


def to_sql(tree, subsys: str):
    """Expression tree → (where_sql, params, exact) — exact=False when a
    post-filter pass is still required (regex comparators)."""
    if tree is None:
        return "1=1", [], True
    if isinstance(tree, C.Criterion):
        if tree.subsys != subsys:
            return "1=1", [], True     # CRIT_SKIP analogue
        fd = fieldmaps.field_map(subsys)[tree.field]
        col = fd.json
        vals = list(tree.values)
        if fd.kind == "enum":
            # history rows store presentation strings (row_to_json);
            # normalize query literals (numeric or string) through the
            # codec so both execution paths compare in the same domain.
            # Ordering comparators would compare lexicographically in SQL
            # but by ordinal live — post-filter those instead of pruning.
            if tree.op in ("<", "<=", ">", ">="):
                return "1=1", [], False
            vals = [fd.to_json(fd.from_json(v)) for v in vals]
        if tree.op == "=":
            return f"{col} = ?", [vals[0]], True
        if tree.op == "!=":
            return f"{col} != ?", [vals[0]], True
        if tree.op in ("<", "<=", ">", ">="):
            return f"{col} {tree.op} ?", [vals[0]], True
        if tree.op == "in":
            q = ",".join("?" * len(vals))
            return f"{col} IN ({q})", vals, True
        if tree.op == "notin":
            q = ",".join("?" * len(vals))
            return f"{col} NOT IN ({q})", vals, True
        if tree.op in ("substr", "notsubstr"):
            esc = (str(vals[0]).replace("\\", "\\\\")
                   .replace("%", "\\%").replace("_", "\\_"))
            neg = "NOT " if tree.op == "notsubstr" else ""
            return (f"{col} {neg}LIKE ? ESCAPE '\\'", [f"%{esc}%"], True)
        if tree.op in ("like", "notlike", "bit2", "bit3"):
            # no portable SQL form → select broadly, post-filter in python
            return "1=1", [], False
        raise ValueError(f"comparator {tree.op} not translatable")
    if tree.op == "not":
        inner, params, exact = to_sql(tree.children[0], subsys)
        if not exact:
            # NOT over an approximated clause must not prune in SQL
            return "1=1", [], False
        return f"NOT ({inner})", params, True
    parts, params, exact = [], [], True
    for ch in tree.children:
        s, p, e = to_sql(ch, subsys)
        parts.append(f"({s})")
        params.extend(p)
        exact = exact and e
    joiner = " AND " if tree.op == "and" else " OR "
    # an OR with an inexact branch must not prune rows in SQL
    if tree.op == "or" and not exact:
        return "1=1", [], False
    return joiner.join(parts), params, exact


class HistoryStore:
    """sqlite-backed day-partitioned snapshot store."""

    def __init__(self, path: str = ":memory:"):
        self.db = sqlite3.connect(path)
        self.db.execute("PRAGMA journal_mode=WAL")
        self._known: set = set()

    def _ensure(self, subsys: str, day: str) -> str:
        t = _table(subsys, day)
        if t not in self._known:
            cols = ", ".join(f"{c}" for c in _TABLES[subsys])
            self.db.execute(
                f"CREATE TABLE IF NOT EXISTS {t} (time REAL, {cols})")
            self.db.execute(
                f"CREATE INDEX IF NOT EXISTS idx_{t}_time ON {t}(time)")
            self._known.add(t)
        return t

    def write(self, subsys: str, t: float, rows: Iterable[dict]) -> int:
        """Persist one snapshot sweep (rows from query.api.execute)."""
        if subsys not in _TABLES:
            raise ValueError(f"no history table for {subsys!r}")
        tab = self._ensure(subsys, _day_of(t))
        cols = _TABLES[subsys]
        q = (f"INSERT INTO {tab} (time, {', '.join(cols)}) VALUES "
             f"({', '.join('?' * (len(cols) + 1))})")
        n = 0
        with self.db:
            for r in rows:
                self.db.execute(q, [t] + [r.get(c) for c in cols])
                n += 1
        return n

    def _days_between(self, tstart: float, tend: float):
        d = datetime.datetime.fromtimestamp(tstart, datetime.timezone.utc)
        end = datetime.datetime.fromtimestamp(tend, datetime.timezone.utc)
        out = []
        while d.date() <= end.date():
            out.append(d.strftime("%Y%m%d"))
            d += datetime.timedelta(days=1)
        return out

    def query(self, subsys: str, tstart: float, tend: float,
              filter: Optional[str] = None, maxrecs: int = 10000):
        """Historical query: criteria → SQL across day partitions, with
        python post-filter for regex comparators (dual execution)."""
        tree = C.parse(filter) if filter else None
        where, params, exact = to_sql(tree, subsys)
        cols = ["time"] + _TABLES[subsys]
        out = []
        for day in self._days_between(tstart, tend):
            t = _table(subsys, day)
            if t not in self._known:
                row = self.db.execute(
                    "SELECT name FROM sqlite_master WHERE name=?",
                    (t,)).fetchone()
                if row is None:
                    continue
                self._known.add(t)
            # with an inexact WHERE, LIMIT must count post-filtered rows:
            # stream unlimited and post-filter as we go
            q = (f"SELECT {', '.join(cols)} FROM {t} "
                 f"WHERE time >= ? AND time <= ? AND ({where}) "
                 f"ORDER BY time")
            for rec in self.db.execute(q, [tstart, tend] + params):
                row = dict(zip(cols, rec))
                if not exact and tree is not None \
                        and not self._match(tree, subsys, row):
                    continue
                out.append(row)
                if len(out) >= maxrecs:
                    return out
        return out

    @staticmethod
    def _match(tree, subsys: str, row: dict) -> bool:
        """Single-row in-memory eval (the post-filter half of dual
        execution): rebuild 1-element columns keyed like live snapshots."""
        import numpy as np
        fixed = {}
        fmap = fieldmaps.field_map(subsys)
        for k, v in row.items():
            if k == "time" or k not in fmap:
                continue
            fd = fmap[k]
            if v is None:
                # NULL column: enum -1 / NaN / "" never match a criterion
                arr = (np.array([""], object) if fd.kind == "str"
                       else np.array([-1.0 if fd.kind == "enum"
                                      else np.nan]))
            elif fd.kind == "enum":
                arr = np.array([float(fd.from_json(v))])
            elif isinstance(v, str):
                arr = np.array([v], object)
            else:
                arr = np.array([float(v)])
            fixed[fd.col] = arr
        return bool(C.evaluate(tree, fixed, subsys)[0])

    def cleanup(self, keep_days: int, now: float) -> int:
        """Drop partitions older than keep_days (partition maintenance,
        ref gy_mdb_schema.cc partition cleanup functions)."""
        cutoff = _day_of(now - keep_days * 86400.0)
        dropped = 0
        rows = self.db.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name LIKE '%tbl_%'").fetchall()
        for (name,) in rows:
            day = name.rsplit("_", 1)[-1]
            if day.isdigit() and day < cutoff:
                self.db.execute(f"DROP TABLE {name}")
                self._known.discard(name)
                dropped += 1
        self.db.commit()
        return dropped

    def days(self) -> list:
        rows = self.db.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name LIKE '%tbl_%'").fetchall()
        return sorted({r[0].rsplit("_", 1)[-1] for r in rows})
