"""Day-partitioned history store + criteria→SQL dual execution.

Mirrors the reference's split (``common/gy_query_criteria.h``: every
criterion can both evaluate in-memory and emit a SQL WHERE clause): the
query layer evaluates criteria columnar on live snapshots, while this
module translates the same expression tree to SQL for the historical
path. Comparators without a clean SQL form (``like``/``notlike`` regex)
fall back to a post-filter in Python — flagged by ``to_sql``.
"""

from __future__ import annotations

import datetime
import json
import sqlite3
from typing import Iterable, Optional

from gyeeta_tpu.query import criteria as C
from gyeeta_tpu.query import fieldmaps

# subsys → persisted columns (json field names; enum codecs applied on
# write so history stores presentation values like the reference DB does
# for state strings via statetojson)
_TABLES = {
    "svcstate": [f.json for f in fieldmaps.SVCSTATE_FIELDS],
    "hoststate": [f.json for f in fieldmaps.HOSTSTATE_FIELDS],
    "clusterstate": [f.json for f in fieldmaps.CLUSTERSTATE_FIELDS],
    "taskstate": [f.json for f in fieldmaps.TASKSTATE_FIELDS],
    "cpumem": [f.json for f in fieldmaps.CPUMEM_FIELDS],
    "tracereq": [f.json for f in fieldmaps.TRACEREQ_FIELDS],
}


def _day_of(t: float) -> str:
    return datetime.datetime.fromtimestamp(
        t, datetime.timezone.utc).strftime("%Y%m%d")


def _table(subsys: str, day: str) -> str:
    return f"{subsys}tbl_{day}"


# case-SENSITIVE containment, matching the live numpy path's `in`
# (criteria.py): sqlite instr / Postgres strpos — sqlite LIKE is
# ASCII case-insensitive and would diverge between backends
_SUBSTR_SQLITE = "instr({col}, ?) > 0"


def _bool_literal(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def to_sql(tree, subsys: str, substr_fmt: str = _SUBSTR_SQLITE):
    """Expression tree → (where_sql, params, exact) — exact=False when a
    post-filter pass is still required (regex comparators).
    ``substr_fmt`` is the backend's case-sensitive containment SQL."""
    if tree is None:
        return "1=1", [], True
    if isinstance(tree, C.Criterion):
        if tree.subsys != subsys:
            return "1=1", [], True     # CRIT_SKIP analogue
        fd = fieldmaps.field_map(subsys)[tree.field]
        col = fd.json
        vals = list(tree.values)
        if fd.kind == "bool":
            # sqlite stores bools as 0/1 and compares loosely; Postgres
            # boolean columns reject integer literals — normalize to
            # real bools so both backends see the same typed parameter
            vals = [_bool_literal(v) for v in vals]
        if fd.kind == "enum":
            # history rows store presentation strings (row_to_json);
            # normalize query literals (numeric or string) through the
            # codec so both execution paths compare in the same domain.
            # Ordering comparators would compare lexicographically in SQL
            # but by ordinal live — post-filter those instead of pruning.
            if tree.op in ("<", "<=", ">", ">="):
                return "1=1", [], False
            vals = [fd.to_json(fd.from_json(v)) for v in vals]
        if tree.op == "=":
            return f"{col} = ?", [vals[0]], True
        if tree.op == "!=":
            return f"{col} != ?", [vals[0]], True
        if tree.op in ("<", "<=", ">", ">="):
            return f"{col} {tree.op} ?", [vals[0]], True
        if tree.op == "in":
            q = ",".join("?" * len(vals))
            return f"{col} IN ({q})", vals, True
        if tree.op == "notin":
            q = ",".join("?" * len(vals))
            return f"{col} NOT IN ({q})", vals, True
        if tree.op in ("substr", "notsubstr"):
            expr = substr_fmt.format(col=col)
            if tree.op == "notsubstr":
                expr = f"NOT ({expr})"
            return expr, [str(vals[0])], True
        if tree.op in ("like", "notlike", "bit2", "bit3"):
            # no portable SQL form → select broadly, post-filter in python
            return "1=1", [], False
        raise ValueError(f"comparator {tree.op} not translatable")
    if tree.op == "not":
        inner, params, exact = to_sql(tree.children[0], subsys,
                                      substr_fmt)
        if not exact:
            # NOT over an approximated clause must not prune in SQL
            return "1=1", [], False
        return f"NOT ({inner})", params, True
    parts, params, exact = [], [], True
    for ch in tree.children:
        s, p, e = to_sql(ch, subsys, substr_fmt)
        parts.append(f"({s})")
        params.extend(p)
        exact = exact and e
    joiner = " AND " if tree.op == "and" else " OR "
    # an OR with an inexact branch must not prune rows in SQL
    if tree.op == "or" and not exact:
        return "1=1", [], False
    return joiner.join(parts), params, exact


class HistoryStore:
    """sqlite-backed day-partitioned snapshot store."""

    # floor-division time bucket (positive time): CAST truncates here;
    # backends where CAST rounds (Postgres) override with FLOOR
    TIME_BUCKET_SQL = "CAST(time/{step} AS INTEGER)*{step}"
    # case-sensitive containment (live-path semantics); PG overrides
    SUBSTR_SQL = _SUBSTR_SQLITE

    def __init__(self, path: str = ":memory:"):
        import threading
        # one connection shared between the fold thread's readers
        # (historical queries, db-mode alertdefs) and the history
        # writer thread (history/histwriter.py) — every access is
        # serialized by self._dblock, so check_same_thread can be off
        self.db = sqlite3.connect(path, check_same_thread=False)
        self.db.execute("PRAGMA journal_mode=WAL")
        self._known: set = set()
        self._dblock = threading.RLock()

    def _ensure(self, subsys: str, day: str) -> str:
        t = _table(subsys, day)
        if t not in self._known:
            cols = ", ".join(f"{c}" for c in _TABLES[subsys])
            self.db.execute(
                f"CREATE TABLE IF NOT EXISTS {t} (time REAL, {cols})")
            self.db.execute(
                f"CREATE INDEX IF NOT EXISTS idx_{t}_time ON {t}(time)")
            self._known.add(t)
        return t

    def write(self, subsys: str, t: float, rows: Iterable[dict]) -> int:
        """Persist one snapshot sweep (rows from query.api.execute)."""
        if subsys not in _TABLES:
            raise ValueError(f"no history table for {subsys!r}")
        with self._dblock:
            tab = self._ensure(subsys, _day_of(t))
            cols = _TABLES[subsys]
            q = (f"INSERT INTO {tab} (time, {', '.join(cols)}) VALUES "
                 f"({', '.join('?' * (len(cols) + 1))})")
            params = [[t] + [r.get(c) for c in cols] for r in rows]
            with self.db:
                # one executemany per sweep: at snapshot scale (50k
                # hosts × 1/min) row-at-a-time commits are the write-
                # amplification bug VERDICT r2 flagged (the reference
                # batches via DB_WRITE_ARR, server/gy_mconnhdlr.h:350)
                self.db.executemany(q, params)
        return len(params)

    def _partition(self, subsys: str, day: str):
        """Partition table name if it exists (cached probe), else None."""
        t = _table(subsys, day)
        if t not in self._known:
            row = self.db.execute(
                "SELECT name FROM sqlite_master WHERE name=?",
                (t,)).fetchone()
            if row is None:
                return None
            self._known.add(t)
        return t

    def _days_between(self, tstart: float, tend: float):
        d = datetime.datetime.fromtimestamp(tstart, datetime.timezone.utc)
        end = datetime.datetime.fromtimestamp(tend, datetime.timezone.utc)
        out = []
        while d.date() <= end.date():
            out.append(d.strftime("%Y%m%d"))
            d += datetime.timedelta(days=1)
        return out

    def query(self, subsys: str, tstart: float, tend: float,
              filter: Optional[str] = None, maxrecs: int = 10000):
        """Historical query: criteria → SQL across day partitions, with
        python post-filter for regex comparators (dual execution)."""
        tree = C.parse(filter) if filter else None
        where, params, exact = to_sql(tree, subsys,
                                      substr_fmt=self.SUBSTR_SQL)
        cols = ["time"] + _TABLES[subsys]
        out = []
        with self._dblock:
            for day in self._days_between(tstart, tend):
                t = self._partition(subsys, day)
                if t is None:
                    continue
                # with an inexact WHERE, LIMIT must count post-filtered
                # rows: stream unlimited and post-filter as we go
                q = (f"SELECT {', '.join(cols)} FROM {t} "
                     f"WHERE time >= ? AND time <= ? AND ({where}) "
                     f"ORDER BY time")
                for rec in self.db.execute(q, [tstart, tend] + params):
                    row = dict(zip(cols, rec))
                    if not exact and tree is not None \
                            and not self._match(tree, subsys, row):
                        continue
                    out.append(row)
                    if len(out) >= maxrecs:
                        return out
        return out

    @staticmethod
    def _match(tree, subsys: str, row: dict) -> bool:
        """Single-row in-memory eval (the post-filter half of dual
        execution): rebuild 1-element columns keyed like live snapshots."""
        import numpy as np
        fixed = {}
        fmap = fieldmaps.field_map(subsys)
        for k, v in row.items():
            if k == "time" or k not in fmap:
                continue
            fd = fmap[k]
            if v is None:
                # NULL column: enum -1 / NaN / "" never match a criterion
                arr = (np.array([""], object) if fd.kind == "str"
                       else np.array([-1.0 if fd.kind == "enum"
                                      else np.nan]))
            elif fd.kind == "enum":
                arr = np.array([float(fd.from_json(v))])
            elif isinstance(v, str):
                arr = np.array([v], object)
            else:
                arr = np.array([float(v)])
            fixed[fd.col] = arr
        return bool(C.evaluate(tree, fixed, subsys)[0])

    def aggr_query(self, subsys: str, tstart: float, tend: float,
                   aggr, groupby=None, filter: Optional[str] = None,
                   step: Optional[float] = None, maxrecs: int = 10000):
        """Historical aggregation (the ``web_db_aggr_*`` analogue).

        Exact-translatable filters with SQL-native ops push GROUP BY into
        each day partition and merge partials host-side; percentile ops or
        inexact filters fetch the filtered rows and run the shared numpy
        aggregator — identical semantics either way (dual execution,
        ``common/gy_query_common.cc:736``).
        """
        from gyeeta_tpu.query import aggr as A

        specs = [A.parse_aggr(s, subsys) for s in (
            [aggr] if isinstance(aggr, str) else list(aggr))]
        if isinstance(groupby, str):
            groupby = [groupby]
        gb = A.parse_groupby(groupby, subsys)
        if "time" in gb and not step:
            raise ValueError("groupby 'time' needs 'step' seconds")
        tree = C.parse(filter) if filter else None
        where, params, exact = to_sql(tree, subsys,
                                      substr_fmt=self.SUBSTR_SQL)
        push = A.sql_pushdown(specs, gb, step,
                              bucket_expr=self.TIME_BUCKET_SQL) \
            if exact else None
        if push is not None:
            # avg is rewritten sum+count inside, so every SQL-native op
            # merges across partitions; only percentiles force numpy
            return self._aggr_sql(subsys, tstart, tend, push, specs, gb,
                                  where, params, step, maxrecs)
        cap = 1 << 22
        rows = self.query(subsys, tstart, tend, filter, maxrecs=cap)
        if len(rows) >= cap:
            # silently aggregating a truncated prefix would return
            # confidently wrong numbers — refuse instead
            raise ValueError(
                "aggregation fallback hit the row-fetch cap "
                f"({cap}); narrow the time range or drop "
                "percentile/regex terms so SQL pushdown applies")
        if "time" in gb:
            for r in rows:
                r["time"] = float(r["time"] // float(step) * float(step))
        out = A.aggregate_rows(rows, specs, gb)
        return out[:maxrecs]

    def _aggr_sql(self, subsys, tstart, tend, push, specs, gb, where,
                  params, step, maxrecs):
        """SQL GROUP BY per partition + cross-partition merge.

        AVG across partitions is not mergeable from partial AVGs — callers
        route non-mergeable multi-partition cases through the numpy path;
        here avg is rewritten as sum+count and divided after the merge.
        """
        sel, grp = push
        # rewrite avg → sum/count pairs for cross-partition mergeability
        sel2, post = [], []
        for i, s in enumerate(specs):
            if s.op == "avg":
                sel2.append(f"SUM({s.field}) AS \"__s{i}\"")
                sel2.append(f"COUNT({s.field}) AS \"__c{i}\"")
                post.append(("avg", s.alias, f"__s{i}", f"__c{i}"))
            else:
                sel2.append(sel[len(grp) + i])
                post.append((s.op, s.alias, s.alias, None))
        acc: dict = {}
        self._dblock.acquire()
        try:
            self._aggr_scan(subsys, tstart, tend, sel, sel2, grp, gb,
                            where, params, post, acc)
        finally:
            self._dblock.release()
        out = []
        for key, row in acc.items():
            rec = dict(zip(gb, key))
            for op, alias, scol, ccol in post:
                if op == "avg":
                    c = row.get(ccol) or 0
                    rec[alias] = (row.get(scol) or 0) / c if c else 0.0
                else:
                    # NULL (zero matching rows) → 0.0, matching the numpy
                    # path's _apply-on-empty so both execution paths agree
                    v = row.get(scol)
                    rec[alias] = 0.0 if v is None else v
            out.append(rec)
            if len(out) >= maxrecs:
                break
        return out

    def _aggr_scan(self, subsys, tstart, tend, sel, sel2, grp, gb,
                   where, params, post, acc) -> None:
        for day in self._days_between(tstart, tend):
            t = self._partition(subsys, day)
            if t is None:
                continue
            q = (f"SELECT {', '.join(list(sel[:len(grp)]) + sel2)} "
                 f"FROM {t} WHERE time >= ? AND time <= ? AND ({where})")
            if grp:
                q += f" GROUP BY {', '.join(grp)}"
            names = [g for g in gb] + [c.rsplit(' AS ', 1)[-1].strip('"')
                                       for c in sel2]
            for rec in self.db.execute(q, [tstart, tend] + params):
                row = dict(zip(names, rec))
                key = tuple(row[g] for g in gb)
                cur = acc.get(key)
                if cur is None:
                    acc[key] = row
                    continue
                for op, alias, scol, ccol in post:
                    if op in ("sum", "count"):
                        cur[scol] = (cur[scol] or 0) + (row[scol] or 0)
                    elif op in ("min", "max"):
                        vals = [x for x in (cur[scol], row[scol])
                                if x is not None]
                        cur[scol] = ((min if op == "min" else max)(vals)
                                     if vals else None)
                    elif op == "avg":
                        cur[scol] = (cur[scol] or 0) + (row[scol] or 0)
                        cur[ccol] = (cur[ccol] or 0) + (row[ccol] or 0)

    def cleanup(self, keep_days: int, now: float) -> int:
        """Drop partitions older than keep_days (partition maintenance,
        ref gy_mdb_schema.cc partition cleanup functions)."""
        cutoff = _day_of(now - keep_days * 86400.0)
        dropped = 0
        with self._dblock:
            rows = self.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name LIKE '%tbl_%'").fetchall()
            for (name,) in rows:
                day = name.rsplit("_", 1)[-1]
                if day.isdigit() and day < cutoff:
                    self.db.execute(f"DROP TABLE {name}")
                    self._known.discard(name)
                    dropped += 1
            self.db.commit()
        return dropped

    def days(self) -> list:
        with self._dblock:
            rows = self.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name LIKE '%tbl_%'").fetchall()
        return sorted({r[0].rsplit("_", 1)[-1] for r in rows})
