"""WAL compaction daemon: sealed journal segments → snapshot shards.

The write half of the time-travel tier. Every accepted wire chunk
already lands in the PR-5 write-ahead journal in feed order, stamped
with the window tick it was folded under; checkpoints are positioned
against it. The compactor is a SECOND, full-rate consumer of that
journal: it re-folds sealed segments through the normal decode path
and the fused ``fold_all`` megakernel (a dedicated replay Runtime —
same geometry as the serving one, so every compiled fold is shared via
the process-wide jit memo), runs the 5s window tick exactly where the
live engine ran it (the chunk tick stamps are the evidence), and at
every ``hist_window_ticks`` boundary emits one columnar snapshot shard
(``history/shards.py``).

Correctness contract: the WAL records the exact accepted-chunk
sequence and fold boundaries of the live engine, so the replayed state
at tick T is BIT-IDENTICAL to the live engine state at T (asserted in
``tests/test_timeview.py`` on both runtimes). A window [W0, W1] is
emitted only once a chunk stamped tick >= W1 has been read — appends
are ordered, so every chunk belonging to the window is provably behind
it; the live engine's open window is never guessed at.

Handoff: the compactor registers a truncate floor on the live journal
(``Journal.set_truncate_floor``) so checkpoint-driven truncation can
never delete segments it has not consumed; its own durable position
(the newest raw shard's recorded WAL position) advances the floor.
Restart resume re-seeds the replay runtime from the newest raw shard —
the shard doubles as the compactor's checkpoint.

Retention ages raw → downsampled → dropped: raw shards beyond
``hist_retain_raw`` merge into ``mid`` shards (``hist_mid_every`` raws
each — sketch state is monotone, so the newest member's state IS the
window merge; columns aggregate per entity), mids beyond
``hist_retain_mid`` merge into ``hour`` shards, hours beyond
``hist_retain_hour`` drop.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from gyeeta_tpu.history import shards as SH, winquant as WQ
from gyeeta_tpu.history.timeview import aggregate_window_columns
from gyeeta_tpu.utils import journal as J

log = logging.getLogger("gyeeta_tpu.history.compactor")


class _NullStats:
    def bump(self, name, n=1):
        pass

    def gauge(self, name, v):
        pass

    def timeit(self, name):
        import contextlib
        return contextlib.nullcontext()


def strip_opts(opts):
    """RuntimeOpts for the REPLAY runtime: identical fold/tick behavior
    (aging, td drain bounds, dep geometry — state evolution must match
    the live engine bit-for-bit), with every side-channel that would
    double-write disabled (journal, checkpoints, relational history,
    shard emission is the compactor's own job)."""
    return opts._replace(journal_dir=None, checkpoint_dir=None,
                         history_db=None, hist_shard_dir=None)


class Compactor:
    """One compaction pipeline: journal dir → replay runtime → shard
    store. Drive it synchronously (``compact_once``, tests/CLI/bench)
    or as a daemon thread (``start``/``stop``)."""

    def __init__(self, cfg, opts, *, journal=None,
                 journal_dir: Optional[str] = None,
                 shard_dir: Optional[str] = None,
                 runtime_factory=None, stats=None, clock=None,
                 upto_seq=None):
        self.cfg = cfg
        self.opts = opts
        self.window_ticks = max(1, int(opts.hist_window_ticks))
        self.journal = journal            # live Journal (seal + floor);
        #                                   None = offline dir read
        # journal-less bound: a parallel-compaction worker reads files
        # another process's live journal owns — it must stop at the
        # sealed bound the supervisor snapshotted, exactly as a live
        # journal object's sealed_upto() would bound it
        self._upto_seq = upto_seq
        self.journal_dir = journal_dir or opts.journal_dir
        if not self.journal_dir:
            raise ValueError("compaction needs a journal dir (the WAL "
                             "is the history source)")
        self.stats = stats if stats is not None else _NullStats()
        self.store = SH.ShardStore(shard_dir or opts.hist_shard_dir,
                                   stats=self.stats)
        self.store.sweep_stale_tmp()
        self._factory = runtime_factory
        self._clock = clock or time.time
        self._rt = None
        self._pos: Optional[tuple] = None   # in-memory WAL resume point
        # monotone-leaf snapshots at the last emit: the per-window
        # delta base (winquant). None = engine state is all-zero.
        self._delta_base: Optional[dict] = None
        self._win_t0: Optional[float] = None
        self._win_t1: Optional[float] = None
        self._last_t: Optional[float] = None
        self._lock = threading.Lock()       # one compaction at a time
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------ replay engine
    def _make_rt(self):
        sopts = strip_opts(self.opts)
        if self._factory is not None:
            return self._factory(self.cfg, sopts)
        from gyeeta_tpu.runtime import Runtime
        return Runtime(self.cfg, sopts)

    def _load_into(self, rt, ent: dict) -> None:
        """Re-seed the replay runtime from a shard (restart resume —
        the shard is the compactor's checkpoint)."""
        import jax

        data = self.store.load(ent)

        def unflatten(leaves, like):
            refs, treedef = jax.tree_util.tree_flatten(like)
            if len(leaves) != len(refs):
                raise ValueError(
                    f"shard {ent['file']}: {len(leaves)} leaves != "
                    f"engine {len(refs)}")
            fixed = []
            for arr, ref in zip(leaves, refs):
                refn = np.asarray(ref)
                if arr.shape != refn.shape:
                    raise ValueError(
                        f"shard {ent['file']}: leaf {arr.shape} != "
                        f"engine {refn.shape}")
                fixed.append(arr.astype(refn.dtype, copy=False))
            if hasattr(rt, "mesh"):
                # sharded runtime: re-shard each leaf like its live
                # counterpart (the restore() discipline)
                fixed = [jax.device_put(a, r.sharding)
                         if hasattr(r, "sharding") else a
                         for a, r in zip(fixed, refs)]
            else:
                # commit to the device BEFORE the donating folds see
                # the state: a numpy-leaf pytree through a cache-
                # reloaded donating executable aborts on the 0.4.x
                # jaxlib line (layout resolution — same bug family
                # conftest documents for shard_map reloads)
                fixed = [jax.device_put(a) for a in fixed]
            return jax.tree_util.tree_unflatten(treedef, fixed)

        rt.state = unflatten(data["state"], rt.state)
        rt.dep = unflatten(data["dep"], rt.dep)
        # the resumed shard's monotone leaves ARE the delta base: the
        # next window's delta is state-at-next-emit − this state
        self._delta_base = {name: WQ.leaf_of(rt.state, name)
                            .astype(np.float64)
                            for name in WQ.DELTA_SPECS}
        rt._tick_no = int(ent["tick1"])
        rt._td_dirty = True
        if hasattr(rt, "_pressures"):
            rt._pressures.clear()
        if hasattr(rt, "_pressure"):
            rt._pressure = None
        rt._cols.bump()
        self._last_t = float(ent["t1"])
        wal = data["meta"].get("wal")
        if wal and isinstance(wal[0], (list, tuple)):
            # sharded WAL: [shard, seg, off] triples → per-shard map
            self._pos = {int(e[0]): (int(e[1]), int(e[2]))
                         for e in wal}
        else:
            self._pos = tuple(wal) if wal else None

    def _ensure_rt(self):
        if self._rt is not None:
            return self._rt
        rt = self._make_rt()
        newest = self.store.newest("raw")
        if newest is not None:
            self._load_into(rt, newest)
        else:
            self._pos = None
        self._rt = rt
        return rt

    # --------------------------------------------------------- compaction
    def compact_once(self, seal: bool = False,
                     upto_tick: Optional[int] = None) -> dict:
        """One pass: read sealed WAL from the resume position, re-fold,
        emit shards at window boundaries, run retention.

        ``seal=True`` rotates the live journal first so the current
        window's bytes become consumable. ``upto_tick`` additionally
        ticks the replay engine past the last chunk's stamp — ONLY
        sound when the journal is sealed and the producer is quiesced
        (tests / shutdown / bench), because in-flight windows have no
        completeness evidence otherwise."""
        with self._lock:
            return self._compact_once(seal, upto_tick)

    def _pos_serial(self):
        """JSON-stable resume position: the flat ``(seg, off)`` pair,
        or ``[shard, seg, off]`` triples for the sharded WAL."""
        if isinstance(self._pos, dict):
            return [[int(s), int(p[0]), int(p[1])]
                    for s, p in sorted(self._pos.items())]
        return self._pos

    def _chunk_stream(self, upto):
        """Sealed-WAL chunks from the resume position: the flat-dir
        walk, or the tick-merged walk over ``shard_NN/`` subdirs when
        the journal is sharded (the mesh tier's per-shard WAL — the
        merge keeps windows in order; within a tick the cross-shard
        interleave is irrelevant, records are host-disjoint). Yields
        ``(pos_update_fn, t, hid, tick, cid, chunk)``."""
        subdirs = J.sharded_subdirs(self.journal_dir)
        if subdirs:
            pos_map = dict(self._pos) if isinstance(self._pos, dict) \
                else {}
            for s, seq, off, t, hid, tick, cid, chunk in \
                    J.read_sealed_sharded(subdirs, pos_map, upto,
                                          stats=self.stats):
                def upd(s=s, seq=seq, off=off):
                    cur = dict(self._pos) if isinstance(self._pos,
                                                        dict) else {}
                    cur[s] = (seq, off)
                    self._pos = cur
                yield upd, t, hid, tick, cid, chunk
            return
        for seq, off, t, hid, tick, cid, chunk in J.read_sealed(
                self.journal_dir, self._pos, upto, stats=self.stats):
            def upd(seq=seq, off=off):
                self._pos = (seq, off)
            yield upd, t, hid, tick, cid, chunk

    def _compact_once(self, seal, upto_tick) -> dict:
        t_wall = time.perf_counter()
        rt = self._ensure_rt()
        if seal and self.journal is not None:
            self.journal.seal_active()
        upto = self.journal.sealed_upto() \
            if self.journal is not None else self._upto_seq
        if upto is not None and not isinstance(upto, (list, tuple)) \
                and J.sharded_subdirs(self.journal_dir):
            upto = None                    # layout mismatch: read all
        nrec = nch = windows = 0
        with self.stats.timeit("compact_replay"):
            for upd, t, hid, tick, cid, chunk in self._chunk_stream(
                    upto):
                if tick > rt._tick_no:
                    windows += self._tick_to(rt, tick)
                nrec += rt.feed(chunk, hid=hid, conn_id=cid)
                nch += 1
                upd()
                self._win_t0 = t if self._win_t0 is None \
                    else min(self._win_t0, t)
                self._win_t1 = t if self._win_t1 is None \
                    else max(self._win_t1, t)
            rt.flush()
            if upto_tick is not None and upto_tick > rt._tick_no:
                windows += self._tick_to(rt, int(upto_tick))
        secs = max(time.perf_counter() - t_wall, 1e-9)
        ev_s = nrec / secs
        if nrec:
            self.stats.gauge("compact_replay_ev_per_sec",
                             round(ev_s, 1))
        self.stats.gauge("compact_lag_seconds",
                         round(self.store.lag_seconds(self._clock()),
                               3))
        self.stats.bump("compact_passes")
        if self.journal is not None:
            pos = self.store.position()
            if pos is not None:
                # durable handoff: checkpoint truncation may now drop
                # segments the shard tier has absorbed
                self.journal.set_truncate_floor(J.floors_of(pos))
        dropped = self.retention()
        return {"chunks": nch, "records": nrec, "windows": windows,
                "ev_per_sec": round(ev_s, 1), "secs": round(secs, 4),
                "retention_dropped": dropped,
                "tick": rt._tick_no}

    def _tick_to(self, rt, target: int) -> int:
        """Advance the replay engine's window tick to ``target``
        (chunks stamped ``target`` are about to fold), emitting a raw
        shard at every window boundary crossed — the exact cadence the
        live engine ran."""
        emitted = 0
        while rt._tick_no < target:
            rt.run_tick()
            if rt._tick_no % self.window_ticks == 0:
                self._emit(rt)
                emitted += 1
        return emitted

    def _emit(self, rt) -> None:
        import jax

        from gyeeta_tpu.query.lazycols import LazyCols
        from gyeeta_tpu.utils.checkpoint import _cfg_fingerprint

        tick1 = rt._tick_no
        tick0 = tick1 - self.window_ticks
        colsfn = getattr(rt, "_cached_columns", None) \
            or rt._merged_columns
        columns = {}
        for subsys in SH.SNAP_SUBSYS:
            cols, mask = colsfn(subsys)
            if isinstance(cols, LazyCols):
                cols = cols.full()
            columns[subsys] = (cols, np.asarray(mask, bool))
        t1 = self._win_t1 if self._win_t1 is not None \
            else (self._last_t if self._last_t is not None
                  else self._clock())
        t0 = self._win_t0 if self._win_t0 is not None else t1
        # per-window sketch deltas: end-state minus the last emit's
        # base for every monotone loghist leaf — the mergeable partial
        # aggregates true windowed quantiles sum (winquant module doc)
        deltas, self._delta_base, diag = WQ.extract_deltas(
            self.cfg, rt.state, columns, self._delta_base)
        for k, v in diag.items():
            if v:
                self.stats.bump(k, v)
        with self.stats.timeit("compact_emit"):
            ent = self.store.add_shard(
                level="raw", tick0=tick0, tick1=tick1, t0=t0, t1=t1,
                state_leaves=jax.tree_util.tree_leaves(rt.state),
                dep_leaves=jax.tree_util.tree_leaves(rt.dep),
                columns=columns,
                cfg_fp=_cfg_fingerprint(self.cfg),
                wal_pos=self._pos_serial(),
                deltas=deltas)
        self.stats.gauge("compact_shard_bytes", float(ent["bytes"]))
        self._last_t = t1
        self._win_t0 = self._win_t1 = None

    # ---------------------------------------------------------- retention
    def retention(self) -> int:
        """Age raw → mid → hour → dropped. Returns shards removed
        (merged sources + expired hours)."""
        removed = 0
        removed += self._downsample(
            "raw", "mid", self.window_ticks * self.opts.hist_mid_every,
            self.opts.hist_retain_raw)
        removed += self._downsample(
            "mid", "hour",
            self.window_ticks * self.opts.hist_mid_every
            * self.opts.hist_hour_every,
            self.opts.hist_retain_mid)
        hours = self.store.shards("hour")
        extra = len(hours) - int(self.opts.hist_retain_hour)
        if extra > 0:
            removed += self.store.drop(hours[:extra])
        return removed

    def _downsample(self, src: str, dst: str, dst_ticks: int,
                    retain: int) -> int:
        srcs = self.store.shards(src)
        old = srcs[: max(0, len(srcs) - int(retain))]
        if not old:
            return 0
        kept_groups = {e["tick0"] // dst_ticks
                       for e in srcs[len(old):]}
        groups: dict = {}
        for e in old:
            groups.setdefault(e["tick0"] // dst_ticks, []).append(e)
        removed = 0
        for g in sorted(groups):
            members = sorted(groups[g], key=lambda e: e["tick1"])
            if g in kept_groups:
                continue      # younger members still inside retention
            self._merge_group(members, dst)
            removed += len(members)
        return removed

    def _merge_group(self, members: list, dst: str) -> None:
        """Merge consecutive shards into one downsampled shard: newest
        member's sketch state (monotone sketches — the merge IS the
        newest state), per-entity aggregated columns, and SUMMED
        per-window delta panels (deltas are additive partial
        aggregates, so a downsampled shard answers windowed quantiles
        at full fidelity — only the window boundaries coarsen)."""
        data = [self.store.load(e) for e in members]
        columns = {}
        for subsys in SH.SNAP_SUBSYS:
            parts = [d["columns"][subsys] for d in data
                     if subsys in d["columns"]]
            if parts:
                columns[subsys] = aggregate_window_columns(subsys,
                                                           parts)
        deltas = {}
        names = {n for d in data for n in d.get("deltas", {})}
        for name in names:
            parts = [(d["deltas"][name]["key"],
                      d["deltas"][name]["hist"])
                     for d in data if name in d.get("deltas", {})]
            if len(parts) != len(data):
                continue     # a member predates delta panels: a merged
                #              panel would silently undercount — omit it
                #              (windowed quantiles reject, never lie)
            keys, hist = WQ.merge_delta_rows(parts)
            ent = {"key": keys, "hist": hist.astype(np.float32)}
            if WQ.DELTA_SPECS[name].td and len(keys):
                m, w, vmin, vmax = WQ.td_from_hist(
                    hist, WQ.spec_of(self.cfg, name),
                    int(getattr(self.cfg, "td_capacity", 64)))
                ent["td"] = {"means": m, "weights": w,
                             "vmin": vmin, "vmax": vmax}
            deltas[name] = ent
        newest = data[-1]
        self.store.add_shard(
            level=dst,
            tick0=members[0]["tick0"], tick1=members[-1]["tick1"],
            t0=min(e["t0"] for e in members),
            t1=max(e["t1"] for e in members),
            state_leaves=newest["state"], dep_leaves=newest["dep"],
            columns=columns, cfg_fp=newest["meta"].get("cfg", ""),
            wal_pos=None, replaces=members, deltas=deltas)
        self.stats.bump("compact_downsampled")

    # ------------------------------------------------------------- daemon
    def start(self, interval: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        interval = float(interval
                         if interval is not None
                         else self.opts.hist_compact_interval_s)
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    rep = self.compact_once(seal=True)
                    if rep["windows"]:
                        log.info("compacted %d window(s), %d chunk(s), "
                                 "%.0f ev/s", rep["windows"],
                                 rep["chunks"], rep["ev_per_sec"])
                except Exception:     # noqa: BLE001 — daemon survives
                    self.stats.bump("compact_errors")
                    log.exception("compaction pass failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="gyt-compactor")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=30.0)
        self._thread = None

    def close(self) -> None:
        self.stop()
        if self._rt is not None:
            self._rt.close()
            self._rt = None
