"""Time-travel queries: ``at=``/``window=`` served from snapshot shards.

The read half of the history tier (``history/compactor.py`` writes the
shards): an ``at=<ts>`` request materializes the shard covering that
instant into a TRANSIENT engine snapshot — the serialized state leaves
re-enter the same pytree shape the live engine uses, so every
state-backed subsystem (including ``topk`` heavy-hitter recovery with
its honest error bounds, ``flowstate``, and the dep-graph views) is
served by the UNCHANGED ``query/api.py`` pipeline; relational
subsystems read the shard's stored column panels directly. A
``window=<dur>`` request aggregates per-entity across every shard
sampling the range (mean for numeric fields, last observation
otherwise), and ``topk`` becomes a windowed DIFF: value = est(end) −
est(baseline), errbound = eb(end) + eb(baseline) — both ends are CMS
upper bounds, so the window count lies within ±errbound of the
reported value (bounds stay honest through subtraction).

Snapshots are ColumnCache-compatible: each materialized shard carries
its own version-keyed column memo, so repeated queries against the
same instant pay the state readbacks once. All three query edges (GYT
binary, REST ``?at=``/``?window=``, stock NM ``tstart``/``tend``
options) route here through ``Runtime.query`` — byte-equal responses
by construction.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import numpy as np

from gyeeta_tpu.query import api, fieldmaps

# suffix durations accepted by at=/window= ("90" = seconds)
_DUR_UNIT = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_dur(v) -> float:
    """Duration → seconds: 900, "900", "15m", "2h", "1d"."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    if s and s[-1] in _DUR_UNIT:
        return float(s[:-1]) * _DUR_UNIT[s[-1]]
    return float(s)


def parse_when(v, now: float):
    """``at=`` value → epoch seconds, or ``("tick", N)``.

    Accepts epoch numbers, ``-15m`` (relative to now) and ``tick:N``
    (window-tick pinned — what tests and the smoke use for exact shard
    addressing)."""
    if isinstance(v, str):
        s = v.strip()
        if s.startswith("tick:"):
            return ("tick", int(s[5:]))
        if s.startswith("-"):
            return now - parse_dur(s[1:])
        return float(s)
    return float(v)


def hist_recover(rt, state) -> dict:
    """Heavy-hitter key recovery from an ARBITRARY state pytree (the
    shard-materialized snapshot) — the same decode + merge math as
    ``Runtime.heavy_recover``/``ShardedRuntime.heavy_recover`` without
    the live-runtime side effects (no stats, no promotion edge)."""
    from gyeeta_tpu.sketch import invertible

    cfg = rt.cfg
    if hasattr(rt, "_rollup"):           # ShardedRuntime: collectives
        ru = rt._rollup(state)
        out = {
            "topk_hi": np.asarray(ru.flow_topk.key_hi),
            "topk_lo": np.asarray(ru.flow_topk.key_lo),
            "topk_counts": np.asarray(ru.flow_topk.counts),
            "topk_est": np.asarray(ru.hh_topk_est),
            "hh_hi": np.asarray(ru.hh_hi),
            "hh_lo": np.asarray(ru.hh_lo),
            "hh_ok": np.asarray(ru.hh_ok),
            "hh_est": np.asarray(ru.hh_est),
        }
        evicted = float(np.asarray(ru.flow_topk.evicted))
        total = float(np.asarray(ru.hh_total_mass))
    else:
        out = {k: np.asarray(v)
               for k, v in rt._hh_recover(state).items()}
        evicted = float(out["evicted"])
        total = float(out["total_mass"])
    err_term = invertible.cms_error_term(total, cfg.cms_width)
    hot_thresh = (cfg.hh_hot_frac * total
                  if cfg.hh_hot_frac > 0 else 0.0)
    flows, recovered, _hot = invertible.merge_recovered_np(
        out, err_term, hot_thresh)
    return {"flows": flows, "err_term": err_term, "total_mass": total,
            "evicted": evicted, "recovered_keys": len(recovered)}


def _window_layout(subsys: str, parts: list):
    """Shared front half of the window aggregators: column names and
    numeric/string/other classification from the LAST part's columns."""
    fmap = fieldmaps.field_map(subsys)
    kind_of = {fd.col: fd.kind for fd in fmap.values()}
    cols_last = parts[-1][0]
    names = [c for c in cols_last]
    keycols = [c for c in names if kind_of.get(c) == "str"]
    numcols = [c for c in names
               if c not in keycols and kind_of.get(c) == "num"]
    othcols = [c for c in names
               if c not in keycols and kind_of.get(c) != "num"]
    return kind_of, cols_last, names, keycols, numcols, othcols


def _positional_window(parts, names, kind_of, cols_last):
    """Key-less subsystems (clusterstate): aggregate positionally."""
    L = min(len(np.asarray(p[1])) for p in parts)
    out = {}
    for c in names:
        if kind_of.get(c) == "num":
            out[c] = np.mean(
                [np.asarray(p[0][c][:L], np.float64)
                 for p in parts], axis=0)
        else:
            out[c] = np.asarray(cols_last[c][:L])
    mask = np.zeros(L, bool)
    for p in parts:
        mask |= np.asarray(p[1][:L], bool)
    return out, mask


def aggregate_window_columns(subsys: str, parts: list):
    """Per-entity aggregate of column snapshots (oldest→newest):
    numeric fields average across the samples an entity appears in;
    string/enum/bool fields keep the LAST observation; the mask is the
    union of liveness. Entities are keyed by the subsystem's string
    identity columns; subsystems without one (clusterstate) aggregate
    positionally.

    Vectorized (ROADMAP history item (a)): the keyed python loop cost
    O(rows × columns) dict operations — a 131k-row shard over a 24h
    window took seconds per subsystem. Here grouping is ONE np.unique
    over a composite key plus bincount segment sums; group order is
    first appearance (matching the loop), and per-group numeric sums
    add in the same flat oldest→newest sequence, so results are
    bit-identical to :func:`aggregate_window_columns_ref`."""
    kind_of, cols_last, names, keycols, numcols, othcols = \
        _window_layout(subsys, parts)
    if not keycols:
        return _positional_window(parts, names, kind_of, cols_last)

    key_flat = {c: [] for c in keycols}
    num_flat = {c: [] for c in numcols}
    oth_flat = {c: [] for c in othcols}
    for cols, mask in parts:
        idx = np.nonzero(np.asarray(mask, bool))[0]
        for c in keycols:
            key_flat[c].append(np.asarray(cols[c])[idx])
        for c in numcols:
            num_flat[c].append(np.asarray(cols[c], np.float64)[idx])
        for c in othcols:
            oth_flat[c].append(np.asarray(cols[c])[idx])
    key_flat = {c: np.concatenate(v) for c, v in key_flat.items()}
    num_flat = {c: np.concatenate(v) for c, v in num_flat.items()}
    oth_flat = {c: np.concatenate(v) for c, v in oth_flat.items()}
    N = len(key_flat[keycols[0]])

    # composite group key: the str identity columns joined with an
    # unlikely separator (identity values are hex ids / names — \x1f
    # cannot appear in them)
    if N == 0:
        keys = np.empty(0, "U1")
    elif len(keycols) == 1:
        keys = key_flat[keycols[0]].astype("U")
    else:
        keys = key_flat[keycols[0]].astype("U")
        for c in keycols[1:]:
            keys = np.char.add(np.char.add(keys, "\x1f"),
                               key_flat[c].astype("U"))
    uniq, first, inv = np.unique(keys, return_index=True,
                                 return_inverse=True)
    # np.unique sorts; remap group ids to FIRST-APPEARANCE order so
    # output row order matches the reference loop
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq))
    g = rank[inv]
    n = len(uniq)
    counts = np.bincount(g, minlength=n).astype(np.float64)
    first_rows = first[order]
    # last observation per group = max flat index (flat order IS
    # oldest→newest)
    last_rows = np.zeros(n, np.int64)
    if N:
        np.maximum.at(last_rows, g, np.arange(N))

    out = {}
    for c in keycols:
        col = np.empty(n, object)
        col[:] = key_flat[c][first_rows]
        out[c] = col
    for c in numcols:
        out[c] = (np.bincount(g, weights=num_flat[c], minlength=n)
                  / np.maximum(counts, 1.0))
    for c in othcols:
        ref = np.asarray(cols_last[c])
        vals = oth_flat[c][last_rows] if N else np.empty(0, ref.dtype)
        if ref.dtype == object or ref.dtype.kind in "US":
            col = np.empty(n, object)
            col[:] = vals
            out[c] = col
        else:
            out[c] = np.asarray(vals, ref.dtype)
    out = {c: out[c] for c in names if c in out}
    return out, np.ones(n, bool)


def aggregate_window_columns_ref(subsys: str, parts: list):
    """Reference implementation (the pre-vectorization keyed python
    loop) — kept for the parity test and the old-vs-new bench row;
    NOT on the serving path."""
    kind_of, cols_last, names, keycols, numcols, othcols = \
        _window_layout(subsys, parts)
    if not keycols:
        return _positional_window(parts, names, kind_of, cols_last)
    order: list = []
    acc: dict = {}
    for cols, mask in parts:
        mask = np.asarray(mask, bool)
        idx = np.nonzero(mask)[0]
        keys = list(zip(*(np.asarray(cols[c])[idx] for c in keycols))) \
            if len(idx) else []
        nums = {c: np.asarray(cols[c], np.float64)[idx]
                for c in numcols}
        oth = {c: np.asarray(cols[c])[idx] for c in othcols}
        for j, k in enumerate(keys):
            a = acc.get(k)
            if a is None:
                a = acc[k] = {"n": 0,
                              "sum": dict.fromkeys(numcols, 0.0),
                              "last": {}}
                order.append(k)
            a["n"] += 1
            for c in numcols:
                a["sum"][c] += float(nums[c][j])
            for c in othcols:
                a["last"][c] = oth[c][j]
    n = len(order)
    out = {}
    for ki, c in enumerate(keycols):
        col = np.empty(n, object)
        col[:] = [k[ki] for k in order]
        out[c] = col
    for c in numcols:
        out[c] = np.array([acc[k]["sum"][c] / acc[k]["n"]
                           for k in order], np.float64)
    for c in othcols:
        ref = np.asarray(cols_last[c])
        vals = [acc[k]["last"][c] for k in order]
        if ref.dtype == object or ref.dtype.kind in "US":
            col = np.empty(n, object)
            col[:] = vals
            out[c] = col
        else:
            out[c] = np.array(vals, ref.dtype)
    # restore original column order
    out = {c: out[c] for c in names if c in out}
    return out, np.ones(n, bool)


class HistSnapshot:
    """One shard materialized as a transient, ColumnCache-compatible
    engine snapshot: stored column panels serve the relational
    subsystems directly; everything state-backed (``topk``,
    ``flowstate``, the dep views, …) re-enters the live pytree shape
    and is produced by the unchanged column providers."""

    def __init__(self, rt, store, ent: dict):
        self.rt = rt
        self.store = store
        self.ent = ent
        self._data = None
        self._state = None
        self._dep = None
        from gyeeta_tpu.utils.colcache import ColumnCache
        self._cols = ColumnCache()        # per-snapshot memo (immutable
        #                                   shard → version never bumps)

    def _load(self) -> dict:
        if self._data is None:
            self._data = self.store.load(self.ent)
        return self._data

    def _unflatten(self, leaves, like):
        ref_leaves, treedef = jax.tree_util.tree_flatten(like)
        if len(leaves) != len(ref_leaves):
            raise ValueError(
                f"shard {self.ent['file']} has {len(leaves)} leaves, "
                f"engine expects {len(ref_leaves)} — incompatible "
                "geometry/version")
        fixed = []
        for arr, ref in zip(leaves, ref_leaves):
            # shape/dtype METADATA only — never np.asarray(ref): the
            # template is the LIVE state, and a device readback here
            # would race the fold's donation when a historical query
            # materializes on a worker thread (aval metadata stays
            # valid even after the buffer is donated away)
            if arr.shape != tuple(ref.shape):
                raise ValueError(
                    f"shard {self.ent['file']}: leaf shape {arr.shape} "
                    f"!= engine {tuple(ref.shape)}")
            fixed.append(arr.astype(np.dtype(ref.dtype), copy=False))
        return jax.tree_util.tree_unflatten(treedef, fixed)

    @property
    def state(self):
        if self._state is None:
            self._state = self._unflatten(self._load()["state"],
                                          self.rt.state)
        return self._state

    @property
    def dep(self):
        if self._dep is None:
            self._dep = self._unflatten(self._load()["dep"],
                                        self.rt.dep)
        return self._dep

    def columns(self, subsys: str):
        """The ``columns_fn`` contract of ``api.execute``."""
        return self._cols.get(subsys, lambda: self._columns(subsys))

    def _columns(self, subsys: str):
        stored = self._load()["columns"]
        if subsys in stored:
            return stored[subsys]
        if subsys == "svcsumm":
            cols, live = self.columns("svcstate")
            return api.svcsumm_from_svc(cols, live, self.rt.names)
        if subsys == "topk":
            rec = hist_recover(self.rt, self.state)
            return api.heavy_topk_columns(
                rec["flows"], svc=self.columns("svcstate"),
                trace=self.columns("tracereq"))
        rt = self.rt
        if hasattr(rt, "_merged_columns_state"):   # ShardedRuntime
            return rt._merged_columns_state(subsys, self.state,
                                            self.dep, self._cols)
        if subsys in api._COLUMNS_OF or subsys in api._DEP_COLUMNS_OF:
            return api.columns_for(rt.cfg, self.state, subsys,
                                   names=rt.names, dep=self.dep)
        raise ValueError(
            f"subsystem {subsys!r} is not available historically "
            "(registry/CRUD-backed views are not shard-persisted)")


class _WindowColumns:
    """``columns_fn`` over a shard RANGE: per-entity aggregation for
    relational subsystems, baseline-diffed recovery for ``topk``."""

    def __init__(self, tv: "TimeView", ents: list, start: float,
                 end: float):
        self.tv = tv
        self.ents = ents
        self.start, self.end = start, end
        self._memo: dict = {}

    def columns(self, subsys: str):
        got = self._memo.get(subsys)
        if got is None:
            got = self._memo[subsys] = self._columns(subsys)
        return got

    def _columns(self, subsys: str):
        if subsys == "topk":
            return self._topk_window()
        parts = [self.tv.snap(e).columns(subsys) for e in self.ents]
        return aggregate_window_columns(subsys, parts)

    def _topk_window(self):
        rt = self.tv.rt
        end_snap = self.tv.snap(self.ents[-1])
        rec_end = hist_recover(rt, end_snap.state)
        base_ent = self.tv.store.resolve_at(self.start)
        rows = [(rid, v, eb, "window")
                for rid, v, eb, _src in rec_end["flows"]]
        if base_ent is not None \
                and base_ent["t1"] <= self.start \
                and base_ent["tick1"] < self.ents[-1]["tick1"]:
            rec_base = hist_recover(rt, self.tv.snap(base_ent).state)
            base = {rid: (v, eb)
                    for rid, v, eb, _s in rec_base["flows"]}
            rows = []
            for rid, v, eb, _src in rec_end["flows"]:
                v0, eb0 = base.get(rid, (0.0, rec_base["err_term"]))
                dv = v - v0
                if dv <= 0:
                    continue
                rows.append((rid, dv, eb + eb0, "window"))
            rows.sort(key=lambda r: (-r[1], r[0]))
        # dense rankings (conns / errrate / p99resp) report the
        # window-END snapshot — they are point-in-time gauges, not
        # accumulating counts
        return api.heavy_topk_columns(
            rows, svc=end_snap.columns("svcstate"),
            trace=end_snap.columns("tracereq"))


class TimeView:
    """``at=``/``window=`` request router bound to one runtime + shard
    store. Materialized snapshots ride a small LRU so dashboard bursts
    against the same instant pay the load once."""

    MAX_SNAPS = 4

    def __init__(self, rt, store, clock=None):
        import threading
        import time as _time
        self.rt = rt
        self.store = store
        self._clock = clock or _time.time
        self._snaps: collections.OrderedDict = collections.OrderedDict()
        # the snapshot LRU is shared by the serving loop and (via the
        # off-loop query executor / windowed alertdefs) worker threads
        self._lock = threading.Lock()

    def snap(self, ent: dict) -> HistSnapshot:
        key = ent["file"]
        with self._lock:
            s = self._snaps.get(key)
            if s is None:
                s = HistSnapshot(self.rt, self.store, ent)
                self._snaps[key] = s
                while len(self._snaps) > self.MAX_SNAPS:
                    self._snaps.popitem(last=False)
            else:
                self._snaps.move_to_end(key)
            return s

    # ------------------------------------------------------------ query
    def query(self, req: dict) -> dict:
        req = dict(req)
        at = req.pop("at", None)
        window = req.pop("window", None)
        tstart = req.pop("tstart", None)
        tend = req.pop("tend", None)
        opts = api.QueryOptions.from_json(req)
        rt = self.rt
        if at is not None:
            ent = self.store.resolve_at(parse_when(at, self._clock()))
            if ent is None:
                raise ValueError("no history shards yet (compaction "
                                 "has not emitted a window)")
            snap = self.snap(ent)
            out = api.execute(rt.cfg, None, opts, names=rt.names,
                              columns_fn=snap.columns)
            out["at"] = ent["t1"]
            out["tick"] = ent["tick1"]
            return out
        newest = self.store.newest("raw") or (
            self.store.shards()[-1] if self.store.shards() else None)
        if newest is None:
            raise ValueError("no history shards yet (compaction has "
                             "not emitted a window)")
        end = float(tend) if tend is not None else float(newest["t1"])
        if window is not None:
            start = end - parse_dur(window)
        elif tstart is not None:
            start = float(tstart)
        else:
            raise ValueError("historical query needs at=, window= or "
                             "tstart/tend")
        ents = self.store.resolve_window(start, end)
        if not ents:
            raise ValueError(
                f"no history shards sample [{start}, {end}]")
        win = _WindowColumns(self, ents, start, end)
        out = api.execute(rt.cfg, None, opts, names=rt.names,
                          columns_fn=win.columns)
        out["window"] = [start, end]
        out["shards"] = len(ents)
        return out

    def window_columns_for(self, subsys: str, window) -> tuple:
        """Windowed (cols, mask) for alertdef evaluation — the
        ``subsys@window`` column source realtime defs with a
        ``window`` field reference (windowed aggregates as alert
        criteria)."""
        newest = self.store.newest("raw") or (
            self.store.shards()[-1] if self.store.shards() else None)
        if newest is None:
            raise ValueError("no history shards yet")
        end = float(newest["t1"])
        start = end - parse_dur(window)
        ents = self.store.resolve_window(start, end)
        if not ents:
            raise ValueError(
                f"no history shards sample [{start}, {end}]")
        return _WindowColumns(self, ents, start, end).columns(subsys)


def route_historical(rt, req: dict) -> Optional[dict]:
    """Shared three-edge routing (GYT binary, REST, stock NM): a
    request carrying ``at``/``window`` goes to the shard tier; a
    ``tstart``/``tend`` range goes to the relational history store
    when one is configured (back-compat SQL semantics), else to the
    shard tier. Returns None for live queries."""
    historical = ("at" in req or "window" in req
                  or "tstart" in req or "tend" in req)
    if not historical:
        return None
    tv = getattr(rt, "timeview", None)
    sql = getattr(rt, "history", None)
    if "at" not in req and "window" not in req and sql is not None:
        return None                   # caller's relational path serves it
    if tv is None:
        raise ValueError(
            "time-travel query needs history shards (run with "
            "--shard-dir / hist_shard_dir)")
    with rt.stats.timeit("timeview_query"):
        return tv.query(req)
