"""Time-travel queries: ``at=``/``window=`` served from snapshot shards.

The read half of the history tier (``history/compactor.py`` writes the
shards): an ``at=<ts>`` request materializes the shard covering that
instant into a TRANSIENT engine snapshot — the serialized state leaves
re-enter the same pytree shape the live engine uses, so every
state-backed subsystem (including ``topk`` heavy-hitter recovery with
its honest error bounds, ``flowstate``, and the dep-graph views) is
served by the UNCHANGED ``query/api.py`` pipeline; relational
subsystems read the shard's stored column panels directly. A
``window=<dur>`` request aggregates per-entity across every shard
sampling the range (mean for numeric fields, last observation
otherwise), and ``topk`` becomes a windowed DIFF: value = est(end) −
est(baseline), errbound = eb(end) + eb(baseline) — both ends are CMS
upper bounds, so the window count lies within ±errbound of the
reported value (bounds stay honest through subtraction).

Snapshots are ColumnCache-compatible: each materialized shard carries
its own version-keyed column memo, so repeated queries against the
same instant pay the state readbacks once. All three query edges (GYT
binary, REST ``?at=``/``?window=``, stock NM ``tstart``/``tend``
options) route here through ``Runtime.query`` — byte-equal responses
by construction.
"""

from __future__ import annotations

import collections
import functools
from typing import Optional

import jax
import numpy as np

from gyeeta_tpu.history import winquant as WQ
from gyeeta_tpu.query import api, fieldmaps

# suffix durations accepted by at=/window= ("90" = seconds)
_DUR_UNIT = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_dur(v) -> float:
    """Duration → seconds: 900, "900", "15m", "2h", "1d"."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    if s and s[-1] in _DUR_UNIT:
        return float(s[:-1]) * _DUR_UNIT[s[-1]]
    return float(s)


def parse_when(v, now: float):
    """``at=`` value → epoch seconds, or ``("tick", N)``.

    Accepts epoch numbers, ``-15m`` (relative to now) and ``tick:N``
    (window-tick pinned — what tests and the smoke use for exact shard
    addressing)."""
    if isinstance(v, str):
        s = v.strip()
        if s.startswith("tick:"):
            return ("tick", int(s[5:]))
        if s.startswith("-"):
            return now - parse_dur(s[1:])
        return float(s)
    return float(v)


@functools.lru_cache(maxsize=8)
def _plain_recover_fn(cfg):
    """Memoized read-only recovery program for a PLAIN (single-slab)
    state pytree — what a parted store's per-part snapshots decode
    with, independent of the serving runtime's kind (the serving tier
    may be a mesh; the parts were replayed by per-shard runtimes)."""
    from gyeeta_tpu.engine import step
    return jax.jit(lambda s: step.heavy_recover(cfg, s))


def plain_recover(cfg, state) -> dict:
    """Heavy-hitter recovery + bound-honest merge over one plain state
    pytree (the single-runtime half of :func:`hist_recover`)."""
    from gyeeta_tpu.sketch import invertible

    out = {k: np.asarray(v)
           for k, v in _plain_recover_fn(cfg)(state).items()}
    evicted = float(out["evicted"])
    total = float(out["total_mass"])
    err_term = invertible.cms_error_term(total, cfg.cms_width)
    hot_thresh = (cfg.hh_hot_frac * total
                  if cfg.hh_hot_frac > 0 else 0.0)
    flows, recovered, _hot = invertible.merge_recovered_np(
        out, err_term, hot_thresh)
    return {"flows": flows, "err_term": err_term, "total_mass": total,
            "evicted": evicted, "recovered_keys": len(recovered)}


def hist_recover(rt, state) -> dict:
    """Heavy-hitter key recovery from an ARBITRARY state pytree (the
    shard-materialized snapshot) — the same decode + merge math as
    ``Runtime.heavy_recover``/``ShardedRuntime.heavy_recover`` without
    the live-runtime side effects (no stats, no promotion edge)."""
    from gyeeta_tpu.sketch import invertible

    cfg = rt.cfg
    if hasattr(rt, "_rollup"):           # ShardedRuntime: collectives
        ru = rt._rollup(state)
        out = {
            "topk_hi": np.asarray(ru.flow_topk.key_hi),
            "topk_lo": np.asarray(ru.flow_topk.key_lo),
            "topk_counts": np.asarray(ru.flow_topk.counts),
            "topk_est": np.asarray(ru.hh_topk_est),
            "hh_hi": np.asarray(ru.hh_hi),
            "hh_lo": np.asarray(ru.hh_lo),
            "hh_ok": np.asarray(ru.hh_ok),
            "hh_est": np.asarray(ru.hh_est),
        }
        evicted = float(np.asarray(ru.flow_topk.evicted))
        total = float(np.asarray(ru.hh_total_mass))
    else:
        out = {k: np.asarray(v)
               for k, v in rt._hh_recover(state).items()}
        evicted = float(out["evicted"])
        total = float(out["total_mass"])
    err_term = invertible.cms_error_term(total, cfg.cms_width)
    hot_thresh = (cfg.hh_hot_frac * total
                  if cfg.hh_hot_frac > 0 else 0.0)
    flows, recovered, _hot = invertible.merge_recovered_np(
        out, err_term, hot_thresh)
    return {"flows": flows, "err_term": err_term, "total_mass": total,
            "evicted": evicted, "recovered_keys": len(recovered)}


def _window_layout(subsys: str, parts: list):
    """Shared front half of the window aggregators: column names and
    numeric/string/other classification from the LAST part's columns."""
    fmap = fieldmaps.field_map(subsys)
    kind_of = {fd.col: fd.kind for fd in fmap.values()}
    cols_last = parts[-1][0]
    names = [c for c in cols_last]
    keycols = [c for c in names if kind_of.get(c) == "str"]
    numcols = [c for c in names
               if c not in keycols and kind_of.get(c) == "num"]
    othcols = [c for c in names
               if c not in keycols and kind_of.get(c) != "num"]
    return kind_of, cols_last, names, keycols, numcols, othcols


def _positional_window(parts, names, kind_of, cols_last):
    """Key-less subsystems (clusterstate): aggregate positionally."""
    L = min(len(np.asarray(p[1])) for p in parts)
    out = {}
    for c in names:
        if kind_of.get(c) == "num":
            out[c] = np.mean(
                [np.asarray(p[0][c][:L], np.float64)
                 for p in parts], axis=0)
        else:
            out[c] = np.asarray(cols_last[c][:L])
    mask = np.zeros(L, bool)
    for p in parts:
        mask |= np.asarray(p[1][:L], bool)
    return out, mask


def aggregate_window_columns(subsys: str, parts: list):
    """Per-entity aggregate of column snapshots (oldest→newest):
    numeric fields average across the samples an entity appears in;
    string/enum/bool fields keep the LAST observation; the mask is the
    union of liveness. Entities are keyed by the subsystem's string
    identity columns; subsystems without one (clusterstate) aggregate
    positionally.

    Vectorized (ROADMAP history item (a)): the keyed python loop cost
    O(rows × columns) dict operations — a 131k-row shard over a 24h
    window took seconds per subsystem. Here grouping is ONE np.unique
    over a composite key plus bincount segment sums; group order is
    first appearance (matching the loop), and per-group numeric sums
    add in the same flat oldest→newest sequence, so results are
    bit-identical to :func:`aggregate_window_columns_ref`."""
    kind_of, cols_last, names, keycols, numcols, othcols = \
        _window_layout(subsys, parts)
    if not keycols:
        return _positional_window(parts, names, kind_of, cols_last)

    key_flat = {c: [] for c in keycols}
    num_flat = {c: [] for c in numcols}
    oth_flat = {c: [] for c in othcols}
    for cols, mask in parts:
        idx = np.nonzero(np.asarray(mask, bool))[0]
        for c in keycols:
            key_flat[c].append(np.asarray(cols[c])[idx])
        for c in numcols:
            num_flat[c].append(np.asarray(cols[c], np.float64)[idx])
        for c in othcols:
            oth_flat[c].append(np.asarray(cols[c])[idx])
    key_flat = {c: np.concatenate(v) for c, v in key_flat.items()}
    num_flat = {c: np.concatenate(v) for c, v in num_flat.items()}
    oth_flat = {c: np.concatenate(v) for c, v in oth_flat.items()}
    N = len(key_flat[keycols[0]])

    # composite group key: the str identity columns joined with an
    # unlikely separator (identity values are hex ids / names — \x1f
    # cannot appear in them)
    if N == 0:
        keys = np.empty(0, "U1")
    elif len(keycols) == 1:
        keys = key_flat[keycols[0]].astype("U")
    else:
        keys = key_flat[keycols[0]].astype("U")
        for c in keycols[1:]:
            keys = np.char.add(np.char.add(keys, "\x1f"),
                               key_flat[c].astype("U"))
    uniq, first, inv = np.unique(keys, return_index=True,
                                 return_inverse=True)
    # np.unique sorts; remap group ids to FIRST-APPEARANCE order so
    # output row order matches the reference loop
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq))
    g = rank[inv]
    n = len(uniq)
    counts = np.bincount(g, minlength=n).astype(np.float64)
    first_rows = first[order]
    # last observation per group = max flat index (flat order IS
    # oldest→newest)
    last_rows = np.zeros(n, np.int64)
    if N:
        np.maximum.at(last_rows, g, np.arange(N))

    out = {}
    for c in keycols:
        col = np.empty(n, object)
        col[:] = key_flat[c][first_rows]
        out[c] = col
    for c in numcols:
        out[c] = (np.bincount(g, weights=num_flat[c], minlength=n)
                  / np.maximum(counts, 1.0))
    for c in othcols:
        ref = np.asarray(cols_last[c])
        vals = oth_flat[c][last_rows] if N else np.empty(0, ref.dtype)
        if ref.dtype == object or ref.dtype.kind in "US":
            col = np.empty(n, object)
            col[:] = vals
            out[c] = col
        else:
            out[c] = np.asarray(vals, ref.dtype)
    out = {c: out[c] for c in names if c in out}
    return out, np.ones(n, bool)


def aggregate_window_columns_ref(subsys: str, parts: list):
    """Reference implementation (the pre-vectorization keyed python
    loop) — kept for the parity test and the old-vs-new bench row;
    NOT on the serving path."""
    kind_of, cols_last, names, keycols, numcols, othcols = \
        _window_layout(subsys, parts)
    if not keycols:
        return _positional_window(parts, names, kind_of, cols_last)
    order: list = []
    acc: dict = {}
    for cols, mask in parts:
        mask = np.asarray(mask, bool)
        idx = np.nonzero(mask)[0]
        keys = list(zip(*(np.asarray(cols[c])[idx] for c in keycols))) \
            if len(idx) else []
        nums = {c: np.asarray(cols[c], np.float64)[idx]
                for c in numcols}
        oth = {c: np.asarray(cols[c])[idx] for c in othcols}
        for j, k in enumerate(keys):
            a = acc.get(k)
            if a is None:
                a = acc[k] = {"n": 0,
                              "sum": dict.fromkeys(numcols, 0.0),
                              "last": {}}
                order.append(k)
            a["n"] += 1
            for c in numcols:
                a["sum"][c] += float(nums[c][j])
            for c in othcols:
                a["last"][c] = oth[c][j]
    n = len(order)
    out = {}
    for ki, c in enumerate(keycols):
        col = np.empty(n, object)
        col[:] = [k[ki] for k in order]
        out[c] = col
    for c in numcols:
        out[c] = np.array([acc[k]["sum"][c] / acc[k]["n"]
                           for k in order], np.float64)
    for c in othcols:
        ref = np.asarray(cols_last[c])
        vals = [acc[k]["last"][c] for k in order]
        if ref.dtype == object or ref.dtype.kind in "US":
            col = np.empty(n, object)
            col[:] = vals
            out[c] = col
        else:
            out[c] = np.array(vals, ref.dtype)
    # restore original column order
    out = {c: out[c] for c in names if c in out}
    return out, np.ones(n, bool)


class HistSnapshot:
    """One shard materialized as a transient, ColumnCache-compatible
    engine snapshot: stored column panels serve the relational
    subsystems directly; everything state-backed (``topk``,
    ``flowstate``, the dep views, …) re-enters the live pytree shape
    and is produced by the unchanged column providers."""

    def __init__(self, rt, store, ent: dict, *, state_tpl=None,
                 dep_tpl=None, plain: bool = False):
        self.rt = rt
        self.store = store
        self.ent = ent
        self._data = None
        self._state = None
        self._dep = None
        # parted stores materialize PER-PART snapshots: the part was
        # replayed by a plain per-shard Runtime, so its leaves unflatten
        # against a plain-geometry template (shape metadata only, via
        # jax.eval_shape — never the serving runtime's possibly-stacked
        # mesh state) and state-backed subsystems decode via the plain
        # column providers even when the serving runtime is a mesh
        self._state_tpl = state_tpl
        self._dep_tpl = dep_tpl
        self._plain = plain
        from gyeeta_tpu.utils.colcache import ColumnCache
        self._cols = ColumnCache()        # per-snapshot memo (immutable
        #                                   shard → version never bumps)

    def _load(self) -> dict:
        if self._data is None:
            self._data = self.store.load(self.ent)
        return self._data

    def _unflatten(self, leaves, like):
        ref_leaves, treedef = jax.tree_util.tree_flatten(like)
        if len(leaves) != len(ref_leaves):
            raise ValueError(
                f"shard {self.ent['file']} has {len(leaves)} leaves, "
                f"engine expects {len(ref_leaves)} — incompatible "
                "geometry/version")
        fixed = []
        for arr, ref in zip(leaves, ref_leaves):
            # shape/dtype METADATA only — never np.asarray(ref): the
            # template is the LIVE state, and a device readback here
            # would race the fold's donation when a historical query
            # materializes on a worker thread (aval metadata stays
            # valid even after the buffer is donated away)
            if arr.shape != tuple(ref.shape):
                raise ValueError(
                    f"shard {self.ent['file']}: leaf shape {arr.shape} "
                    f"!= engine {tuple(ref.shape)}")
            fixed.append(arr.astype(np.dtype(ref.dtype), copy=False))
        return jax.tree_util.tree_unflatten(treedef, fixed)

    @property
    def state(self):
        if self._state is None:
            tpl = self._state_tpl if self._state_tpl is not None \
                else self.rt.state
            self._state = self._unflatten(self._load()["state"], tpl)
        return self._state

    @property
    def dep(self):
        if self._dep is None:
            tpl = self._dep_tpl if self._dep_tpl is not None \
                else self.rt.dep
            self._dep = self._unflatten(self._load()["dep"], tpl)
        return self._dep

    def delta_names(self) -> set:
        """Delta panel names this shard carries."""
        return set(self._load().get("deltas", {}))

    def deltas(self, names) -> Optional[dict]:
        """Per-window delta panels (winquant) for ``names``, or None
        when ANY is absent (a shard predating delta panels — windowed
        quantiles must reject, never approximate)."""
        stored = self._load().get("deltas", {})
        if any(n not in stored for n in names):
            return None
        return {n: (stored[n]["key"], stored[n]["hist"])
                for n in names}

    def recover(self) -> dict:
        """Heavy-hitter recovery over this snapshot's state."""
        if self._plain:
            return plain_recover(self.rt.cfg, self.state)
        return hist_recover(self.rt, self.state)

    def columns(self, subsys: str):
        """The ``columns_fn`` contract of ``api.execute``."""
        return self._cols.get(subsys, lambda: self._columns(subsys))

    def _columns(self, subsys: str):
        stored = self._load()["columns"]
        if subsys in stored:
            return stored[subsys]
        if subsys == "svcsumm":
            cols, live = self.columns("svcstate")
            return api.svcsumm_from_svc(cols, live, self.rt.names)
        if subsys == "topk":
            rec = self.recover()
            return api.heavy_topk_columns(
                rec["flows"], svc=self.columns("svcstate"),
                trace=self.columns("tracereq"))
        rt = self.rt
        if not self._plain and hasattr(rt, "_merged_columns_state"):
            return rt._merged_columns_state(subsys, self.state,
                                            self.dep, self._cols)
        if subsys in api._COLUMNS_OF or subsys in api._DEP_COLUMNS_OF:
            return api.columns_for(rt.cfg, self.state, subsys,
                                   names=rt.names, dep=self.dep)
        raise ValueError(
            f"subsystem {subsys!r} is not available historically "
            "(registry/CRUD-backed views are not shard-persisted)")


def _merge_group_rows(cols: dict, mask, keycols: list,
                      sumcols: list) -> tuple:
    """Group concatenated per-part rows by identity: ``sumcols`` sum,
    everything else keeps the first observation; first-appearance
    order. The cross-part merge for views whose entity can appear in
    more than one part (a dep edge reported by hosts on two shards)."""
    mask = np.asarray(mask, bool)
    idx = np.nonzero(mask)[0]
    if len(idx) == 0:
        return {c: np.asarray(cols[c])[:0] for c in cols}, \
            np.zeros(0, bool)
    keys = np.asarray(cols[keycols[0]])[idx].astype("U")
    for c in keycols[1:]:
        keys = np.char.add(np.char.add(keys, WQ.KEY_SEP),
                           np.asarray(cols[c])[idx].astype("U"))
    uniq, first, inv = np.unique(keys, return_index=True,
                                 return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq))
    g = rank[inv]
    n = len(uniq)
    first_rows = idx[first[order]]
    out = {}
    for c in cols:
        src = np.asarray(cols[c])
        if c in sumcols:
            acc = np.zeros(n, np.float64)
            np.add.at(acc, g, src[idx].astype(np.float64))
            out[c] = acc
        else:
            out[c] = src[first_rows]
    return out, np.ones(n, bool)


class PartedSnapshot:
    """One parted-store window materialized WITHOUT funneling through
    a single process-wide state: each ``part_NN`` sub-shard (the
    output of one parallel replay worker over one WAL shard) opens as
    its own plain :class:`HistSnapshot`, and queries merge at column
    level — concatenation for entity-disjoint panels (hosts hash to
    exactly one WAL shard, so their services/tasks/APIs are
    part-disjoint), bound-honest summation for the global sketch views
    (per-part flow values are upper bounds; the merged value sums them
    and sums their error bounds)."""

    def __init__(self, tv: "TimeView", store, ent: dict):
        self.tv = tv
        self.rt = tv.rt
        self.ent = ent
        state_tpl, dep_tpl = tv._part_templates()
        self.snaps = [
            HistSnapshot(tv.rt, store.parts[i], pe,
                         state_tpl=state_tpl, dep_tpl=dep_tpl,
                         plain=True)
            for i, pe in enumerate(ent["parts"])]
        self._memo: dict = {}
        self._rec = None

    # ----------------------------------------------------------- topk
    def recover(self) -> dict:
        if self._rec is not None:
            return self._rec
        agg: dict = {}
        err = ev = tot = 0.0
        nrec = 0
        for s in self.snaps:
            rec = s.recover()
            err += rec["err_term"]
            ev += rec["evicted"]
            tot += rec["total_mass"]
            nrec += rec["recovered_keys"]
            for rid, v, eb, src in rec["flows"]:
                cur = agg.get(rid)
                if cur is None:
                    agg[rid] = [v, eb, src]
                else:
                    cur[0] += v
                    cur[1] += eb
        rows = sorted(((rid, v, eb, src)
                       for rid, (v, eb, src) in agg.items()),
                      key=lambda r: (-r[1], r[0]))
        self._rec = {"flows": rows, "err_term": err,
                     "total_mass": tot, "evicted": ev,
                     "recovered_keys": nrec}
        return self._rec

    def delta_names(self) -> set:
        names = None
        for s in self.snaps:
            got = s.delta_names()
            names = got if names is None else names & got
        return names or set()

    def deltas(self, names) -> Optional[dict]:
        per = [s.deltas(names) for s in self.snaps]
        if any(p is None for p in per):
            return None
        out = {}
        for n in names:
            out[n] = WQ.merge_delta_rows([p[n] for p in per])
        return out

    # -------------------------------------------------------- columns
    def columns(self, subsys: str):
        got = self._memo.get(subsys)
        if got is None:
            got = self._memo[subsys] = self._columns(subsys)
        return got

    def _concat(self, parts: list) -> tuple:
        cols = {k: np.concatenate(
            [np.asarray(p[0][k]) for p in parts])
            for k in parts[0][0]}
        mask = np.concatenate([np.asarray(p[1], bool) for p in parts])
        return cols, mask

    def _columns(self, subsys: str):
        from gyeeta_tpu.query.lazycols import LazyCols

        if subsys == fieldmaps.SUBSYS_CLUSTERSTATE:
            parts = [s.columns(subsys) for s in self.snaps]
            out = {}
            for c in parts[0][0]:
                vals = [float(np.asarray(p[0][c])[0]) if len(p[0][c])
                        else 0.0 for p in parts]
                out[c] = np.array([float(np.sum(vals))])
            nh = float(out.get("nhosts", np.zeros(1))[0])
            bad = float(out.get("nbad", np.zeros(1))[0]) \
                + float(out.get("nsevere", np.zeros(1))[0])
            out["issue_frac"] = np.array([bad / max(nh, 1.0)])
            return out, np.ones(1, bool)
        if subsys == "svcsumm":
            cols, live = self.columns("svcstate")
            return api.svcsumm_from_svc(cols, live, self.rt.names)
        if subsys == "topk":
            rec = self.recover()
            return api.heavy_topk_columns(
                rec["flows"], svc=self.columns("svcstate"),
                trace=self.columns("tracereq"))
        if subsys == fieldmaps.SUBSYS_FLOWSTATE:
            rec = self.recover()
            n = len(rec["flows"])
            ids = np.empty(n, object)
            ids[:] = [r[0] for r in rec["flows"]]
            cols = {"flowid": ids,
                    "bytes": np.array([r[1] for r in rec["flows"]],
                                      np.float64),
                    "evictedbytes": np.full(n, float(rec["evicted"]))}
            return cols, np.ones(n, bool)
        if subsys == fieldmaps.SUBSYS_SVCMESH:
            raise ValueError(
                "svcmesh is not available over parted history stores "
                "(mesh clusters cannot be labelled per part)")
        parts = [s.columns(subsys) for s in self.snaps]
        parts = [((p[0].full() if isinstance(p[0], LazyCols)
                   else p[0]), p[1]) for p in parts]
        cols, mask = self._concat(parts)
        # views whose entity may be reported from several parts merge
        # by identity with summed flow stats (everything panel-backed
        # is part-disjoint and stays concatenated)
        if subsys == fieldmaps.SUBSYS_SVCDEP:
            return _merge_group_rows(cols, mask, ["cliid", "serid"],
                                     ["nconn", "bytes"])
        if subsys == fieldmaps.SUBSYS_ACTIVECONN:
            return _merge_group_rows(
                cols, mask, ["svcid"],
                ["nclients", "nconn", "bytes", "nsvccli"])
        if subsys == fieldmaps.SUBSYS_CLIENTCONN:
            return _merge_group_rows(cols, mask, ["cliid"],
                                     ["nservers", "nconn", "bytes"])
        return cols, mask


class _WindowColumns:
    """``columns_fn`` over a shard RANGE: per-entity aggregation for
    relational subsystems, baseline-diffed recovery for ``topk``."""

    def __init__(self, tv: "TimeView", ents: list, start: float,
                 end: float):
        self.tv = tv
        self.ents = ents
        self.start, self.end = start, end
        self._memo: dict = {}
        self._deltas: dict = {}       # panel name → merged (keys, hist)

    def columns(self, subsys: str):
        got = self._memo.get(subsys)
        if got is None:
            got = self._memo[subsys] = self._columns(subsys)
        return got

    # -------------------------------------------------- quantile merge
    def delta_support(self) -> set:
        """Delta panels EVERY covering shard carries — the windowed
        quantile sources this window can honor."""
        avail = set(WQ.DELTA_SPECS)
        for e in self.ents:
            avail &= self.tv.snap(e).delta_names()
        return avail

    def _merged_deltas(self, panel: str):
        got = self._deltas.get(panel)
        if got is None:
            parts = []
            for e in self.ents:
                d = self.tv.snap(e).deltas([panel])
                if d is None:
                    return None
                parts.append(d[panel])
            got = self._deltas[panel] = WQ.merge_delta_rows(parts)
        return got

    def _apply_window_quantiles(self, subsys: str, cols, mask):
        """Override quantile fields with TRUE windowed quantiles: the
        covering windows' delta histograms sum per entity (the exact
        mergeable-summary merge) and each field reads its quantile off
        the merged histogram. Fields whose delta panel is missing
        (pre-delta shards) are REMOVED from the output — and counted —
        never served as the old silent mean-of-snapshots."""
        qf = WQ.quantile_fields(subsys)
        if not qf or not isinstance(cols, dict) or not len(mask):
            if qf and isinstance(cols, dict):
                # empty window: fields stay, values are vacuous
                pass
            return cols
        panels = {f.panel for f in qf.values()}
        merged = {p: self._merged_deltas(p) for p in panels}
        row_keys = None
        for field, f in qf.items():
            fd = fieldmaps.field_map(subsys).get(field)
            if fd is None or fd.col not in cols:
                continue
            if merged[f.panel] is None:
                cols.pop(fd.col, None)
                self.tv.rt.stats.bump("windowed_quant_fields_omitted")
                continue
            if row_keys is None:
                row_keys = WQ.composite_keys(
                    WQ.DELTA_SPECS[f.panel].subsys, cols,
                    np.arange(len(mask)))
            spec = WQ.spec_of(self.tv.rt.cfg, f.panel)
            hists = WQ.lookup_hists(row_keys, merged[f.panel],
                                    spec.nbuckets)
            if f.q is None:
                vals = WQ.np_hist_mean(hists, spec)
            else:
                vals = WQ.np_hist_quantiles(
                    hists, spec, [f.q])[:, 0]
            cols[fd.col] = np.asarray(
                vals, np.float64) / WQ.DELTA_SPECS[f.panel].scale
        return cols

    def _columns(self, subsys: str):
        if subsys == "topk":
            return self._topk_window()
        parts = [self.tv.snap(e).columns(subsys) for e in self.ents]
        cols, mask = aggregate_window_columns(subsys, parts)
        cols = self._apply_window_quantiles(subsys, cols, mask)
        return cols, mask

    def _topk_window(self):
        end_snap = self.tv.snap(self.ents[-1])
        rec_end = end_snap.recover()
        base_ent = self.tv.store.resolve_at(self.start)
        rows = [(rid, v, eb, "window")
                for rid, v, eb, _src in rec_end["flows"]]
        if base_ent is not None \
                and base_ent["t1"] <= self.start \
                and base_ent["tick1"] < self.ents[-1]["tick1"]:
            rec_base = self.tv.snap(base_ent).recover()
            base = {rid: (v, eb)
                    for rid, v, eb, _s in rec_base["flows"]}
            rows = []
            for rid, v, eb, _src in rec_end["flows"]:
                v0, eb0 = base.get(rid, (0.0, rec_base["err_term"]))
                dv = v - v0
                if dv <= 0:
                    continue
                rows.append((rid, dv, eb + eb0, "window"))
            rows.sort(key=lambda r: (-r[1], r[0]))
        # dense rankings (conns / errrate / p99resp) report the
        # window-END snapshot — they are point-in-time gauges, not
        # accumulating counts
        return api.heavy_topk_columns(
            rows, svc=end_snap.columns("svcstate"),
            trace=end_snap.columns("tracereq"))


class TimeView:
    """``at=``/``window=`` request router bound to one runtime + shard
    store. Materialized snapshots ride a small LRU so dashboard bursts
    against the same instant pay the load once."""

    MAX_SNAPS = 4

    def __init__(self, rt, store, clock=None):
        import threading
        import time as _time
        self.rt = rt
        self.store = store
        self._clock = clock or _time.time
        self._snaps: collections.OrderedDict = collections.OrderedDict()
        # the snapshot LRU is shared by the serving loop and (via the
        # off-loop query executor / windowed alertdefs) worker threads
        self._lock = threading.Lock()
        self._tpl = None              # parted per-part unflatten
        #                               templates (metadata-only)

    def _part_templates(self) -> tuple:
        """Plain-geometry (state, dep) templates for per-part snapshot
        materialization — jax.eval_shape only (no allocation, and
        NEVER the serving runtime's live buffers)."""
        if self._tpl is None:
            from gyeeta_tpu.engine import aggstate
            from gyeeta_tpu.parallel import depgraph as dg
            cfg, opts = self.rt.cfg, self.rt.opts
            self._tpl = (
                jax.eval_shape(lambda: aggstate.init(cfg)),
                jax.eval_shape(lambda: dg.init(
                    opts.dep_pair_capacity, opts.dep_edge_capacity)))
        return self._tpl

    def snap(self, ent: dict):
        if "parts" in ent:
            key = ("parted", ent["level"], ent["tick0"], ent["tick1"],
                   tuple(pe["file"] for pe in ent["parts"]))
        else:
            key = ent["file"]
        with self._lock:
            s = self._snaps.get(key)
            if s is None:
                if "parts" in ent:
                    s = PartedSnapshot(self, self.store, ent)
                else:
                    s = HistSnapshot(self.rt, self.store, ent)
                self._snaps[key] = s
                while len(self._snaps) > self.MAX_SNAPS:
                    self._snaps.popitem(last=False)
            else:
                self._snaps.move_to_end(key)
            return s

    # ------------------------------------------------------------ query
    def query(self, req: dict) -> dict:
        req = dict(req)
        at = req.pop("at", None)
        window = req.pop("window", None)
        tstart = req.pop("tstart", None)
        tend = req.pop("tend", None)
        opts = api.QueryOptions.from_json(req)
        rt = self.rt
        if at is not None:
            ent = self.store.resolve_at(parse_when(at, self._clock()))
            if ent is None:
                raise ValueError("no history shards yet (compaction "
                                 "has not emitted a window)")
            snap = self.snap(ent)
            out = api.execute(rt.cfg, None, opts, names=rt.names,
                              columns_fn=snap.columns)
            out["at"] = ent["t1"]
            out["tick"] = ent["tick1"]
            self._cover(out)
            return out
        newest = self.store.newest("raw") or (
            self.store.shards()[-1] if self.store.shards() else None)
        if newest is None:
            raise ValueError("no history shards yet (compaction has "
                             "not emitted a window)")
        end = float(tend) if tend is not None else float(newest["t1"])
        if window is not None:
            start = end - parse_dur(window)
        elif tstart is not None:
            start = float(tstart)
        else:
            raise ValueError("historical query needs at=, window= or "
                             "tstart/tend")
        ents = self.store.resolve_window(start, end)
        if not ents:
            raise ValueError(
                f"no history shards sample [{start}, {end}]")
        win = _WindowColumns(self, ents, start, end)
        self._check_windowed_quantiles(opts, win)
        out = api.execute(rt.cfg, None, opts, names=rt.names,
                          columns_fn=win.columns)
        out["window"] = [start, end]
        out["shards"] = len(ents)
        self._cover(out)
        return out

    def _cover(self, out: dict) -> None:
        """Stamp the store's durable coverage onto a historical
        response: the gateway's no-TTL historical cache admits an
        entry only when the requested instant/range lies INSIDE
        coverage at render time — interior resolutions are immutable
        (compaction only appends windows; downsampling preserves the
        delta merges), while a request past the frontier would
        re-resolve once the next window lands."""
        newest = self.store.shards()
        if newest:
            out["hist_cover_tick"] = max(e["tick1"] for e in newest)
            out["hist_cover_t"] = max(e["t1"] for e in newest)

    def _check_windowed_quantiles(self, opts, win: "_WindowColumns"
                                  ) -> None:
        """Validation-time gate for windowed quantile fields: a
        request that REFERENCES one (filter/sort/projection/aggr) is
        REJECTED — counted — when any covering shard lacks its delta
        panel. Silently serving the old mean-of-snapshots would be a
        wrong number wearing a quantile's name; an implicit full
        projection instead omits the field (also counted)."""
        qf = WQ.quantile_fields(opts.subsys)
        if not qf:
            return
        refs = WQ.referenced_fields(opts) & set(qf)
        if not refs:
            return
        avail = win.delta_support()
        bad = sorted(f for f in refs if qf[f].panel not in avail)
        if bad:
            self.rt.stats.bump("windowed_quant_rejected")
            raise ValueError(
                f"windowed quantile field(s) {bad} need per-window "
                "sketch deltas, but the covering shards predate delta "
                "panels (recompact, or drop the field) — windowed "
                "quantiles are never approximated from snapshot means")

    def window_columns_for(self, subsys: str, window) -> tuple:
        """Windowed (cols, mask) for alertdef evaluation — the
        ``subsys@window`` column source realtime defs with a
        ``window`` field reference (windowed aggregates as alert
        criteria)."""
        newest = self.store.newest("raw") or (
            self.store.shards()[-1] if self.store.shards() else None)
        if newest is None:
            raise ValueError("no history shards yet")
        end = float(newest["t1"])
        start = end - parse_dur(window)
        ents = self.store.resolve_window(start, end)
        if not ents:
            raise ValueError(
                f"no history shards sample [{start}, {end}]")
        return _WindowColumns(self, ents, start, end).columns(subsys)


def route_historical(rt, req: dict) -> Optional[dict]:
    """Shared three-edge routing (GYT binary, REST, stock NM): a
    request carrying ``at``/``window`` goes to the shard tier; a
    ``tstart``/``tend`` range goes to the relational history store
    when one is configured (back-compat SQL semantics), else to the
    shard tier. Returns None for live queries."""
    historical = ("at" in req or "window" in req
                  or "tstart" in req or "tend" in req)
    if not historical:
        return None
    tv = getattr(rt, "timeview", None)
    sql = getattr(rt, "history", None)
    if "at" not in req and "window" not in req and sql is not None:
        return None                   # caller's relational path serves it
    if tv is None:
        raise ValueError(
            "time-travel query needs history shards (run with "
            "--shard-dir / hist_shard_dir)")
    with rt.stats.timeit("timeview_query"):
        return tv.query(req)
