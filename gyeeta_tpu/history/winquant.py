"""Per-window sketch deltas → true windowed quantiles.

``window=24h p99`` used to be served as the MEAN of per-shard snapshot
quantiles — not a quantile at all. The engine's per-entity response
loghists are MONOTONE accumulators (``resp_win.alltime``,
``api_resp_hist``, ``task_cpu_hist`` only ever grow), so the histogram
of exactly the samples folded inside a compaction window is
``state_at_window_end − state_at_window_start`` — an exact per-window
partial aggregate. Those deltas are mergeable summaries in the
Agarwal-et-al sense: the merge across windows is plain ``+``, so a
``window=<dur>`` quantile is the quantile of the SUMMED covering
deltas — the same monotone-merge proof the downsampler already uses
(newest-state = window merge), applied to the subtraction direction.

This module owns everything both sides share:

- which monotone leaves become delta panels (``DELTA_SPECS``), and
  which query fields are quantiles over them (``QUANTILE_FIELDS``);
- the compactor-side extraction (``extract_deltas``): end−start per
  slab row, keyed by the subsystem's string identity columns (the SAME
  composite key the window aggregator groups by), negative rows
  clamped and counted (a slab row recycled to a new entity mid-window
  subtracts a stranger's baseline);
- a derived per-entity t-digest delta for the service response panel
  (``td_from_hist``): the window histogram re-expressed as ≤C
  centroids at bucket-midpoint resolution — the compact mergeable form
  for consumers that cannot afford the full (S, B) panel. Quantile
  SERVING always uses the loghist deltas (exact merge); the digest is
  a documented derivation, never a second source of truth;
- the read-side merge + numpy quantile math (``merge_delta_rows``,
  ``np_hist_quantiles``) — numerically the mirror of
  ``sketch/loghist.quantiles`` so shard-served quantiles equal the
  offline exact merge bit-for-bit (modulo the documented XLA-vs-numpy
  bucket-edge flips, PR 11's loghist tolerance).

Error model (OPERATIONS.md "Distributed compaction & windowed
quantiles"): within a window the delta is exact; quantile error is the
loghist's γ-bound (<2% for the resp spec). Entities that aged OUT
mid-window drop their last partial window (undercount, counted via
``wd_dead_rows``); slab-row reuse inside one window clamps to zero
(counted via ``wd_clamped_rows``). Both are bounded by one window's
traffic for one entity.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from gyeeta_tpu.query import fieldmaps

# separator for composite entity keys (identity values are hex ids /
# interned names — \x1f cannot appear in them; the same convention
# timeview.aggregate_window_columns uses)
KEY_SEP = "\x1f"


class DeltaSpec(NamedTuple):
    subsys: str                  # panel whose identity columns key rows
    spec_attr: str               # EngineCfg attr holding the LogHistSpec
    leaf: str                    # dotted path into AggState
    scale: float                 # raw bucket unit → JSON field unit
    td: bool = False             # also derive the t-digest delta panel


# name → how to pull the monotone loghist out of the engine state.
# Leaves may carry a leading mesh-shard axis (stacked ShardedRuntime
# state); extraction reshapes to (-1, B), which matches the shard-major
# row order of the merged column panels.
DELTA_SPECS = {
    # per-service response-time loghist (usec buckets → msec fields)
    "svc_resp": DeltaSpec("svcstate", "resp_spec", "resp_win.alltime",
                          1e3, td=True),
    # per-(service, API) trace latency loghist (usec → msec)
    "api_resp": DeltaSpec("tracereq", "apiresp_spec", "api_resp_hist",
                          1e3),
    # per-process-group CPU%% baseline loghist
    "task_cpu": DeltaSpec("taskstate", "taskcpu_spec", "task_cpu_hist",
                          1.0),
}


class QuantField(NamedTuple):
    panel: str                   # DELTA_SPECS key
    q: Optional[float]           # quantile in (0,1); None = window mean


# JSON fields that are QUANTILES (or the histogram mean) of a delta
# panel. In ``window=`` mode the level suffix in the field name
# (5s/5m/5d) is vacuous — every resp field is the stated quantile of
# the ONE merged window histogram (documented in OPERATIONS.md).
# Snapshot (`at=`) serving is untouched: panels store the live values.
_SVC_QF = {
    "resp5s": QuantField("svc_resp", None),
    "p95resp5s": QuantField("svc_resp", 0.95),
    "p99resp5s": QuantField("svc_resp", 0.99),
    "p95resp5m": QuantField("svc_resp", 0.95),
    "p50resp5d": QuantField("svc_resp", 0.50),
    "p95resp5d": QuantField("svc_resp", 0.95),
}
_TASK_QF = {"cpup95": QuantField("task_cpu", 0.95)}
QUANTILE_FIELDS = {
    "svcstate": _SVC_QF,
    "extsvcstate": _SVC_QF,
    "tracereq": {
        "p50resp": QuantField("api_resp", 0.50),
        "p95resp": QuantField("api_resp", 0.95),
        "p99resp": QuantField("api_resp", 0.99),
    },
    "taskstate": _TASK_QF,
    # taskstate presets share the field map → same quantile sources
    "topcpu": _TASK_QF, "toppgcpu": _TASK_QF, "toprss": _TASK_QF,
    "topdelay": _TASK_QF, "topfork": _TASK_QF,
}


# ------------------------------------------------------------ registry
# New subsystems register their monotone leaves and quantile fields at
# import time instead of editing the literals above (ROADMAP history
# item: qps baselines / new panels get true windowed quantiles without
# touching this module). The read side goes through
# ``quantile_fields()`` (both timeview call sites), so a registered
# field is picked up by window SERVING and by the windowed-alertdef
# column check identically — a field can't silently skip the windowed
# path (tests/test_cq.py pins coverage).

def register_delta_spec(name: str, spec: DeltaSpec,
                        replace: bool = False) -> DeltaSpec:
    """Register a monotone loghist leaf as a delta panel. The
    compactor extracts end−start per window for every registered
    panel; ``spec_attr`` must name a LogHistSpec on EngineCfg."""
    if not replace and name in DELTA_SPECS \
            and DELTA_SPECS[name] != spec:
        raise ValueError(f"delta panel {name!r} already registered "
                         f"with a different spec")
    DELTA_SPECS[name] = spec
    return spec


def register_quantile_field(subsys: str, field: str, qf: QuantField,
                            replace: bool = False) -> QuantField:
    """Register one JSON field of ``subsys`` as a quantile (or window
    mean, ``q=None``) over a registered delta panel. Validates at
    registration: the panel must exist in ``DELTA_SPECS`` and the
    field in the subsystem's field map — a typo fails HERE, not as a
    silently-unwindowed field at query time."""
    if qf.panel not in DELTA_SPECS:
        raise ValueError(
            f"quantile field {subsys}.{field} references unknown "
            f"delta panel {qf.panel!r} (register_delta_spec first)")
    if field not in fieldmaps.field_map(subsys):
        raise ValueError(
            f"{field!r} is not a field of {subsys!r}")
    cur = QUANTILE_FIELDS.setdefault(subsys, {})
    if not replace and cur.get(field) not in (None, qf):
        raise ValueError(f"{subsys}.{field} already registered "
                         f"with a different source")
    # subsystems sharing a dict literal (taskstate presets) see the
    # registration together — that sharing is the point
    cur[field] = qf
    return qf


def quantile_fields(subsys: str) -> dict:
    """The subsystem's windowed-quantile fields ({} when none) — THE
    read-side accessor (timeview's window serving and the windowed
    criteria check both resolve through it)."""
    return QUANTILE_FIELDS.get(subsys) or {}


def spec_of(cfg, name: str):
    return getattr(cfg, DELTA_SPECS[name].spec_attr)


def leaf_of(state, name: str) -> np.ndarray:
    """The monotone loghist leaf as a (rows, B) numpy array (a leading
    mesh-shard axis flattens shard-major, matching merged panels)."""
    obj = state
    for part in DELTA_SPECS[name].leaf.split("."):
        obj = getattr(obj, part)
    arr = np.asarray(obj)
    return arr.reshape(-1, arr.shape[-1])


# ------------------------------------------------------------ identity
def keycols_of(subsys: str, cols) -> list:
    """The subsystem's string identity columns, in column order — the
    SAME derivation ``timeview._window_layout`` groups by, so delta
    rows and aggregated window rows key identically."""
    fmap = fieldmaps.field_map(subsys)
    kind_of = {fd.col: fd.kind for fd in fmap.values()}
    return [c for c in cols if kind_of.get(c) == "str"]


def composite_keys(subsys: str, cols, idx: np.ndarray) -> np.ndarray:
    """Rows ``idx`` of the panel → composite identity keys (U array)."""
    keycols = keycols_of(subsys, cols)
    if not keycols:
        raise ValueError(f"{subsys!r} has no string identity columns")
    keys = np.asarray(cols[keycols[0]])[idx].astype("U")
    for c in keycols[1:]:
        keys = np.char.add(np.char.add(keys, KEY_SEP),
                           np.asarray(cols[c])[idx].astype("U"))
    return keys


# ----------------------------------------------------------- extraction
def extract_deltas(cfg, state, columns: dict, base: Optional[dict]
                   ) -> tuple:
    """One window's delta panels.

    ``columns``: the shard's column panels (subsys → (cols, mask)) —
    the identity source; rows align positionally with the loghist
    slabs (both are slab-row order, shard-major when stacked).
    ``base``: {name: (rows, B) ndarray} captured at the PREVIOUS emit
    (None = engine started from zero).

    Returns ``(deltas, new_base, diag)`` where ``deltas`` maps name →
    {"key": (n,) U array, "hist": (n, B) f32} and ``diag`` counts the
    clamped / dead-entity rows for the compactor's stats."""
    deltas: dict = {}
    new_base: dict = {}
    diag = {"wd_clamped_rows": 0, "wd_dead_rows": 0}
    for name, ds in DELTA_SPECS.items():
        cur = leaf_of(state, name).astype(np.float64)
        new_base[name] = cur
        if ds.subsys not in columns:
            continue
        cols, mask = columns[ds.subsys]
        mask = np.asarray(mask, bool)
        if len(mask) != cur.shape[0]:
            # geometry drift between panel and slab — never emit a
            # misaligned panel (queries would join wrong entities)
            continue
        prev = base.get(name) if base else None
        d = cur - prev if prev is not None else cur.copy()
        neg = d < 0
        if neg.any():
            diag["wd_clamped_rows"] += int((neg.any(axis=1)).sum())
            d = np.maximum(d, 0.0)
        nonzero = d.sum(axis=1) > 0
        diag["wd_dead_rows"] += int((nonzero & ~mask).sum())
        idx = np.nonzero(nonzero & mask)[0]
        keys = composite_keys(ds.subsys, cols, idx)
        deltas[name] = {"key": keys,
                        "hist": d[idx].astype(np.float32)}
        if ds.td:
            spec = spec_of(cfg, name)
            m, w, vmin, vmax = td_from_hist(
                d[idx], spec, int(getattr(cfg, "td_capacity", 64)))
            deltas[name]["td"] = {"means": m, "weights": w,
                                  "vmin": vmin, "vmax": vmax}
    return deltas, new_base, diag


# ------------------------------------------------------------ np mirror
def np_bucket_mid(spec, bucket: np.ndarray) -> np.ndarray:
    g = spec.gamma
    return spec.vmin * np.exp(
        (bucket.astype(np.float32) + 0.5) * np.float32(np.log(g)))


def np_hist_quantiles(hists: np.ndarray, spec, qs) -> np.ndarray:
    """(n, B) histograms → (n, Q) quantiles. The numpy mirror of
    ``sketch/loghist.quantiles`` (same −1e-6 target slack, same
    midpoint estimator) so merged-delta quantiles equal the offline
    exact merge's bit-for-bit."""
    hists = np.asarray(hists, np.float32)
    qs = np.asarray(qs, np.float32)
    cdf = np.cumsum(hists, axis=-1)                      # (n, B)
    tot = cdf[..., -1:]                                  # (n, 1)
    target = qs[None, :] * tot                           # (n, Q)
    ge = cdf[:, None, :] >= target[:, :, None] - 1e-6    # (n, Q, B)
    idx = np.argmax(ge, axis=-1).astype(np.int32)
    val = np_bucket_mid(spec, idx)
    return np.where(tot > 0, val, 0.0)


def np_hist_mean(hists: np.ndarray, spec) -> np.ndarray:
    hists = np.asarray(hists, np.float32)
    mids = np_bucket_mid(spec, np.arange(spec.nbuckets, dtype=np.int32))
    tot = hists.sum(axis=-1)
    s = (hists * mids).sum(axis=-1)
    return np.where(tot > 0, s / np.maximum(tot, 1.0), 0.0)


# ----------------------------------------------------------- td derive
def td_from_hist(hists: np.ndarray, spec, capacity: int) -> tuple:
    """Per-row window histograms → per-row t-digest deltas.

    The k-bin clustering of ``sketch/tdigest._compress`` in numpy:
    buckets are already ascending in mean, so cluster id is the
    arcsine-scaled midpoint quantile; weights segment-sum into ≤C
    centroids. Resolution is bounded by the loghist γ (the digest is a
    DERIVED summary — see module doc)."""
    hists = np.asarray(hists, np.float64)
    n, B = hists.shape
    mids = np_bucket_mid(spec, np.arange(B)).astype(np.float64)
    means = np.zeros((n, capacity), np.float32)
    weights = np.zeros((n, capacity), np.float32)
    lo_edge = spec.vmin * (spec.gamma ** np.arange(B))
    hi_edge = spec.vmin * (spec.gamma ** (np.arange(B) + 1))
    vmin = np.zeros(n, np.float32)
    vmax = np.zeros(n, np.float32)
    if n == 0:
        return means, weights, vmin, vmax
    delta = 2.0 * (capacity - 1)
    tot = hists.sum(axis=1, keepdims=True)
    cum = np.cumsum(hists, axis=1)
    q_mid = (cum - 0.5 * hists) / np.maximum(tot, 1e-30)

    def k1(q):
        return (delta / (2.0 * np.pi)) * np.arcsin(
            np.clip(2.0 * q - 1.0, -1.0, 1.0))

    k = k1(q_mid) - k1(0.0)
    cid = np.clip(np.floor(k).astype(np.int64), 0, capacity - 1)
    cid = np.where(hists > 0, cid, capacity - 1)
    rows = np.repeat(np.arange(n), B)
    flat = rows * capacity + cid.ravel()
    w_acc = np.zeros(n * capacity, np.float64)
    s_acc = np.zeros(n * capacity, np.float64)
    np.add.at(w_acc, flat, hists.ravel())
    np.add.at(s_acc, flat, (hists * mids[None, :]).ravel())
    w_acc = w_acc.reshape(n, capacity)
    s_acc = s_acc.reshape(n, capacity)
    weights = w_acc.astype(np.float32)
    means = np.where(w_acc > 0, s_acc / np.maximum(w_acc, 1e-30),
                     0.0).astype(np.float32)
    occ = hists > 0
    first = np.argmax(occ, axis=1)
    last = B - 1 - np.argmax(occ[:, ::-1], axis=1)
    has = occ.any(axis=1)
    vmin = np.where(has, lo_edge[first], 0.0).astype(np.float32)
    vmax = np.where(has, hi_edge[last], 0.0).astype(np.float32)
    return means, weights, vmin, vmax


# --------------------------------------------------------------- merge
def merge_delta_rows(parts: list) -> tuple:
    """Merge delta panels (``(keys, hist)`` pairs, any order) by
    entity: histograms SUM per composite key (the exact mergeable-
    summary merge). Returns ``(keys, hist)`` in first-appearance
    order."""
    ks = [np.asarray(k) for k, _h in parts if len(np.asarray(k))]
    hs = [np.asarray(h, np.float64) for k, h in parts
          if len(np.asarray(k))]
    if not ks:
        return np.empty(0, "U1"), np.zeros((0, 0), np.float64)
    keys = np.concatenate([k.astype("U") for k in ks])
    hist = np.concatenate(hs, axis=0)
    uniq, first, inv = np.unique(keys, return_index=True,
                                 return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq))
    g = rank[inv]
    out = np.zeros((len(uniq), hist.shape[1]), np.float64)
    np.add.at(out, g, hist)
    return uniq[order], out


def lookup_hists(keys: np.ndarray, merged: tuple, nbuckets: int
                 ) -> np.ndarray:
    """Row keys → (n, B) histograms from a merged delta panel (rows
    with no delta — no samples in the window — are zero)."""
    mkeys, mhist = merged
    out = np.zeros((len(keys), nbuckets), np.float64)
    if len(mkeys) == 0 or len(keys) == 0:
        return out
    pos = {k: i for i, k in enumerate(mkeys.tolist())}
    B = min(nbuckets, mhist.shape[1])
    for j, k in enumerate(np.asarray(keys).tolist()):
        i = pos.get(k)
        if i is not None:
            out[j, :B] = mhist[i, :B]
    return out


# ----------------------------------------------------- field references
def referenced_fields(opts) -> set:
    """Every field a QueryOptions references by name — filter criteria,
    sort column, explicit projection, aggregation specs — so windowed
    validation can reject quantile references the shards cannot honor
    instead of silently approximating them."""
    from gyeeta_tpu.query import criteria

    refs: set = set()
    if opts.filter:
        try:
            tree = criteria.parse(opts.filter)
        except Exception:            # noqa: BLE001 — fails downstream
            tree = None

        def walk(node):
            if node is None:
                return
            if isinstance(node, criteria.Criterion):
                if node.subsys == opts.subsys:
                    refs.add(node.field)
                return
            for ch in node.children:
                walk(ch)
        walk(tree)
    if opts.sortcol:
        refs.add(opts.sortcol)
    if opts.columns:
        refs.update(opts.columns)
    if opts.aggr:
        from gyeeta_tpu.query import aggr as A
        for s in opts.aggr:
            try:
                sp = A.parse_aggr(s, opts.subsys)
                if sp.field != "*":
                    refs.add(sp.field)
            except Exception:        # noqa: BLE001 — fails downstream
                pass
    if opts.groupby:
        refs.update(opts.groupby)
    return refs
