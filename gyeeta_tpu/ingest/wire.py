"""GYT wire format: COMM_HEADER-compatible framing + typed event records.

Field-for-field equivalent of the reference protocol's framing and hot event
structs (``common/gy_comm_proto.h``): ``COMM_HEADER`` (:336 — magic/total_sz/
data_type/padding, 8-byte aligned, 16MB cap), ``EVENT_NOTIFY`` (:486 —
subtype + nevents), ``TCP_CONN_NOTIFY`` (:1665, ≤2048/batch),
``LISTENER_STATE_NOTIFY`` (:2183, ≤512/batch), ``HOST_STATE_NOTIFY`` (:2289).

Differences from the reference (deliberate, TPU-first):
- records are **fixed width** (no trailing cmdline/issue strings — strings are
  interned host-side to 64-bit ids before serialization), so a whole batch
  decodes with one ``np.frombuffer`` and converts to device columns with zero
  per-record Python;
- ``RESP_SAMPLE`` is new: the reference aggregates response times into
  CPU histograms *inside the agent* (``common/gy_socket_stat.h`` resp_hist_);
  our agents forward raw duty-cycle-sampled (glob_id, resp_usec) pairs and the
  device does all sketching — that is the point of the TPU tier;
- IP addresses travel as 16 raw bytes (IPv4-mapped for v4) + port, the
  field content of ``IP_PORT`` (``common/gy_inet_inc.h``).

Layouts are explicit little-endian numpy structured dtypes; every struct is
8-byte aligned by construction (itemsize % 8 == 0), mirroring the reference's
``alignas(8)`` + explicit padding discipline.
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------- constants
MAGIC_PM = 0x47590001   # partha-equivalent agent -> aggregation tier
MAGIC_MS = 0x47590002   # aggregation tier -> global tier
MAGIC_NQ = 0x47590003   # query client (node webserver analogue)

MAX_COMM_DATA_SZ = 16 * 1024 * 1024   # 16MB frame cap (gy_comm_proto.h:31)

# COMM_TYPE (header data_type_)
COMM_EVENT_NOTIFY = 1
COMM_QUERY_CMD = 2
COMM_QUERY_RESP = 3
COMM_REGISTER_REQ = 4     # agent handshake (ref PS_REGISTER_REQ_S :584)
COMM_REGISTER_RESP = 5
COMM_TRACE_SET = 6        # server→agent capture control (ref
#                           REQ_TRACE_SET, gy_comm_proto.h:3295; rides
#                           the event conn in reverse — the analogue of
#                           the reference's CLI_TYPE_RESP_REQ direction)
COMM_SUBSCRIBE_CMD = 8    # client→server streaming subscription: the
#                           payload is a standard QUERY_HDR + JSON
#                           envelope; the server answers an open-ended
#                           stream of QUERY_RESP frames (status
#                           QS_PARTIAL, seqid echoed) where EACH frame
#                           body is one complete subscription event
#                           (query/delta.py: full | delta | ack) —
#                           pushed when snaptick advances, not polled.
#                           The conn closing (either end) ends the
#                           subscription; a QS_ERROR frame reports a
#                           rejected registration. Pre-v6 servers
#                           answer unknown data_type like any other
#                           junk query frame (counted, conn survives).
COMM_THROTTLE = 7         # server→agent admission control: hold feeds
#                           in the agent spool for N ms (backpressure —
#                           server pressure becomes agent-side spooling
#                           instead of engine-side garbage; versioned
#                           like NOTIFY_AGENT_STATS — old agents skip
#                           unknown control dtypes)

# NOTIFY_TYPE (EVENT_NOTIFY subtype_)
NOTIFY_TCP_CONN = 10          # flow close/open records
NOTIFY_LISTENER_STATE = 11    # 5s per-service state
NOTIFY_HOST_STATE = 12        # 5s per-host rollup
NOTIFY_RESP_SAMPLE = 13       # raw response-time samples (TPU-first)
NOTIFY_AGGR_TASK_STATE = 14   # 5s per-process-group state
NOTIFY_CPU_MEM_STATE = 15     # 2s host cpu/mem state
NOTIFY_NAME_INTERN = 16       # string-intern announcements (TPU-first)
NOTIFY_REQ_TRACE = 17         # request-trace transactions (per-API)
NOTIFY_LISTENER_INFO = 18     # listener static metadata (ip/port/cmdline)
NOTIFY_HOST_INFO = 19         # static host inventory (hw/os/cloud)
NOTIFY_CGROUP_STATE = 20      # 5s per-cgroup stats
NOTIFY_MOUNT_STATE = 21       # mount/filesystem inventory + freespace
NOTIFY_NETIF_STATE = 22       # net interface inventory + traffic rates
NOTIFY_TASK_PING = 23         # process-group keepalive (no stats; the
#                               ref PING_TASK_AGGR, gy_comm_proto.h:1384
#                               — refreshes ageing, never inserts)
NOTIFY_AGENT_STATS = 24       # agent self-report: spool drops/resends +
#                               connect timeouts since the last report —
#                               delivery-continuity accounting the server
#                               folds into its own selfstats registry so
#                               /metrics shows fleet-wide loss counters
NOTIFY_SKETCH_DELTA = 26      # edge pre-aggregation (wire v5): the
#                               agent folds its own conn/resp streams
#                               locally (sketch/edgefold.py) and ships
#                               ONE stream of mergeable delta records
#                               per sweep instead of N raw tuples —
#                               per-svc counter/loghist partials,
#                               incremental HLL register maxes, top
#                               flow aggregates with a truncation
#                               errbound, and dep-graph edge sums. The
#                               server folds them with the SAME
#                               monotone-merge semantics the history
#                               downsampler proves (sketch merge =
#                               state union; counters scatter-add).
#                               v4 servers skip the unknown subtype
#                               COUNTED (drain2 forward compat).
NOTIFY_SWEEP_SEQ = 25         # agent sweep sequence mark: one record
#                               prepended to every built sweep carrying
#                               the agent's monotone sweep counter. The
#                               WAL dedup contract rides on it: the
#                               server tracks the per-host high-water
#                               mark (journaled with checkpoints,
#                               rebuilt by WAL replay) and echoes it in
#                               REGISTER_RESP, so a reconnecting agent
#                               drops already-durable sweeps from its
#                               resend spool instead of double-folding
#                               them (checkpoint + replay + resend
#                               never double-counts)

MAX_CONNS_PER_BATCH = 2048    # gy_comm_proto.h:1711
MAX_LISTENERS_PER_BATCH = 512  # gy_comm_proto.h:2222
MAX_RESP_PER_BATCH = 4096
MAX_HOSTS_PER_BATCH = 4096

HEADER_DT = np.dtype([
    ("magic", "<u4"),
    ("total_sz", "<u4"),      # header + notify + payload, 8-aligned
    ("data_type", "<u4"),
    ("padding_sz", "<u4"),
])

EVENT_NOTIFY_DT = np.dtype([
    ("subtype", "<u4"),
    ("nevents", "<u4"),
])

IP_PORT_DT = np.dtype([
    ("ip", "u1", (16,)),      # IPv6 bytes; IPv4 mapped ::ffff:a.b.c.d
    ("port", "<u2"),
    ("pad", "u1", (6,)),
])

# TCP_CONN record — field-for-field vs gy_comm_proto.h:1665, strings interned.
TCP_CONN_DT = np.dtype([
    ("cli", IP_PORT_DT),
    ("ser", IP_PORT_DT),
    ("nat_cli", IP_PORT_DT),
    ("nat_ser", IP_PORT_DT),
    ("tusec_start", "<u8"),
    ("tusec_close", "<u8"),
    ("cli_task_aggr_id", "<u8"),
    ("cli_related_listen_id", "<u8"),
    ("cli_madhava_id", "<u8"),
    ("peer_machine_id_hi", "<u8"),
    ("peer_machine_id_lo", "<u8"),
    ("ser_related_listen_id", "<u8"),
    ("ser_glob_id", "<u8"),
    ("ser_madhava_id", "<u8"),
    ("bytes_sent", "<u8"),     # client perspective
    ("bytes_rcvd", "<u8"),
    ("cli_pid", "<i4"),
    ("ser_pid", "<i4"),
    ("ser_conn_hash", "<u4"),
    ("ser_sock_inode", "<u4"),
    ("cli_comm_id", "<u8"),    # interned comm string (ref: cli_comm_[16])
    ("ser_comm_id", "<u8"),
    ("cli_cmdline_id", "<u8"),  # interned cmdline (ref: trailing string)
    ("host_id", "<u4"),        # source agent index (shard routing key)
    ("flags", "<u4"),          # bit0 connect, bit1 accept, bit2 loopback,
                               # bit3 pre-existing, bit4 notified-before
])

# LISTENER_STATE record — field-for-field vs gy_comm_proto.h:2183.
LISTENER_STATE_DT = np.dtype([
    ("glob_id", "<u8"),
    ("nqrys_5s", "<u4"),
    ("total_resp_5sec", "<u4"),
    ("nconns", "<u4"),
    ("nconns_active", "<u4"),
    ("ntasks", "<u4"),
    ("p95_5s_resp_ms", "<u4"),
    ("p95_5min_resp_ms", "<u4"),
    ("curr_kbytes_inbound", "<u4"),
    ("curr_kbytes_outbound", "<u4"),
    ("ser_errors", "<u4"),
    ("cli_errors", "<u4"),
    ("tasks_delay_usec", "<u4"),
    ("tasks_cpudelay_usec", "<u4"),
    ("tasks_blkiodelay_usec", "<u4"),
    ("tasks_user_cpu", "<u4"),
    ("tasks_sys_cpu", "<u4"),
    ("tasks_rss_mb", "<u4"),
    ("ntasks_issue", "<u2"),
    ("is_http_svc", "u1"),
    ("curr_state", "u1"),
    ("curr_issue", "u1"),
    ("issue_bit_hist", "u1"),
    ("high_resp_bit_hist", "u1"),
    ("last_issue_subsrc", "u1"),
    ("query_flags", "<u4"),
    ("host_id", "<u4"),
    ("pad", "u1", (4,)),
    ("issue_string_id", "<u8"),  # interned (ref: trailing issue_string_)
])

# HOST_STATE record — field-for-field vs gy_comm_proto.h:2289.
HOST_STATE_DT = np.dtype([
    ("curr_time_usec", "<u8"),
    ("ntasks_issue", "<u4"),
    ("ntasks_severe", "<u4"),
    ("ntasks", "<u4"),
    ("nlisten_issue", "<u4"),
    ("nlisten_severe", "<u4"),
    ("nlisten", "<u4"),
    ("curr_state", "u1"),
    ("issue_bit_hist", "u1"),
    ("cpu_issue", "u1"),
    ("mem_issue", "u1"),
    ("severe_cpu_issue", "u1"),
    ("severe_mem_issue", "u1"),
    ("pad", "u1", (2,)),
    ("host_id", "<u4"),
    ("pad2", "u1", (4,)),
])

# RESP_SAMPLE — TPU-first raw response-time sample (see module docstring).
RESP_SAMPLE_DT = np.dtype([
    ("glob_id", "<u8"),
    ("resp_usec", "<u4"),
    ("host_id", "<u4"),
])

# AGGR_TASK_STATE record — field-for-field vs gy_comm_proto.h:2114
# (process-group 5s state; comm string interned, issue string dropped).
AGGR_TASK_DT = np.dtype([
    ("aggr_task_id", "<u8"),
    ("comm_id", "<u8"),            # interned onecomm_[16]
    ("related_listen_id", "<u8"),
    ("tcp_kbytes", "<u4"),
    ("tcp_conns", "<u4"),
    ("total_cpu_pct", "<f4"),
    ("rss_mb", "<u4"),
    ("cpu_delay_msec", "<u4"),
    ("vm_delay_msec", "<u4"),
    ("blkio_delay_msec", "<u4"),
    ("forks_sec", "<f4"),          # group fork rate (TOPFORK source;
    #                                ref TASK_TOP_PROCS fork view)
    ("ntasks_total", "<u2"),
    ("ntasks_issue", "<u2"),
    ("curr_state", "u1"),
    ("curr_issue", "u1"),
    ("pad", "u1", (2,)),
    ("host_id", "<u4"),
    ("pad2", "u1", (4,)),
])

MAX_TASKS_PER_BATCH = 1200     # gy_comm_proto.h:2139 MAX_NUM_TASKS

# TASK_PING record — process-group keepalive (the ref PING_TASK_AGGR,
# gy_comm_proto.h:1384: long-lived quiet groups refresh their ageing
# clock without a stats sweep; the fold looks the key up and touches
# task_last_tick, never inserting)
TASK_PING_DT = np.dtype([
    ("aggr_task_id", "<u8"),
    ("host_id", "<u4"),
    ("pad", "u1", (4,)),
])

MAX_PINGS_PER_BATCH = 2048     # ref PING_TASK_AGGR::MAX_NUM_PINGS

# AGENT_STATS record — agent-side delivery-continuity counters reported
# as DELTAS after each reconnect (the agent is the only process that can
# see its own spool drops; the server folds the deltas into monotone
# counters so ``gyt_spool_dropped_total`` renders in /metrics with the
# rest of the robustness surface).
AGENT_STATS_DT = np.dtype([
    ("host_id", "<u4"),
    ("spool_dropped", "<u4"),          # sweeps evicted from a full spool
    ("spool_dropped_records", "<u4"),  # records inside those sweeps
    ("spool_resent", "<u4"),           # spooled sweeps resent on reconnect
    ("connect_timeouts", "<u4"),       # dial deadlines that fired
    ("pad", "<u4"),
])

MAX_AGENT_STATS_PER_BATCH = 64

# SWEEP_SEQ record — the per-sweep sequence mark (see NOTIFY_SWEEP_SEQ).
SWEEP_SEQ_DT = np.dtype([
    ("host_id", "<u4"),
    ("pad", "<u4"),
    ("seq", "<u8"),                    # monotone per agent process
])

MAX_SWEEP_SEQ_PER_BATCH = 64

# CPU_MEM_STATE record — the 2s host cpu/mem path (field content of
# CPU_MEM_STATE_NOTIFY, gy_comm_proto.h:2024: cpu pcts, context switches,
# forks, runnable procs, RSS/commit pcts, swap, paging, reclaim stalls,
# OOM kills). Agent sends raw gauges every 2s; the server classifies
# (semantic/cpumem.py), unlike the 5s HOST_STATE which carries the
# agent's own verdicts.
CPU_MEM_DT = np.dtype([
    ("cpu_pct", "<f4"),
    ("usercpu_pct", "<f4"),
    ("syscpu_pct", "<f4"),
    ("iowait_pct", "<f4"),
    ("max_core_cpu_pct", "<f4"),   # hottest single core
    ("cs_sec", "<f4"),             # context switches/sec
    ("forks_sec", "<f4"),
    ("procs_running", "<f4"),
    ("rss_pct", "<f4"),
    ("commit_pct", "<f4"),
    ("swap_free_pct", "<f4"),
    ("pg_inout_sec", "<f4"),       # pages in+out/sec
    ("swap_inout_sec", "<f4"),
    ("allocstall_sec", "<f4"),     # direct-reclaim stalls/sec
    ("oom_kills", "<f4"),
    ("ncpus", "<f4"),
    ("host_id", "<u4"),
    ("pad", "u1", (4,)),
])

MAX_CPUMEM_PER_BATCH = 4096

# REQ_TRACE record — one parsed request/response transaction (field
# content of REQ_TRACE_TRAN, gy_comm_proto.h:3288: api signature +
# latency + status + sizes; the signature string is interned host-side
# like every other string, NAME_KIND_API announcements).
REQ_TRACE_DT = np.dtype([
    ("svc_glob_id", "<u8"),
    ("api_id", "<u8"),            # interned normalized signature
    ("conn_id", "<u8"),           # traced connection identity (wire v3;
    #                               TRACECONN grouping, ref
    #                               json_db_traceconn_arr)
    ("cli_task_aggr_id", "<u8"),  # requesting process group (cprocid)
    ("cli_comm_id", "<u8"),       # interned client comm (cname)
    ("tusec", "<u8"),             # request first-byte time
    ("resp_usec", "<u4"),
    ("bytes_in", "<u4"),
    ("bytes_out", "<u4"),
    ("status", "<u2"),            # HTTP status / PG 0-ok 1-err
    ("proto", "u1"),              # trace.PROTO_*
    ("is_error", "u1"),
    ("host_id", "<u4"),
    ("pad", "u1", (4,)),
])

MAX_TRACE_PER_BATCH = 4096

# LISTENER_INFO record — static listener metadata announced once per
# listener (+ on reconnect): the field content of the reference's
# NEW_LISTENER / LISTENER_INFO_REQ path (``gy_comm_proto.h:2499``,
# listener tables ``common/gy_socket_stat.h``). Low-rate metadata: kept
# host-side by the server (not a device slab) and joined into svcinfo
# query rows.
LISTENER_INFO_DT = np.dtype([
    ("glob_id", "<u8"),
    ("addr", IP_PORT_DT),
    ("tusec_start", "<u8"),
    ("cmdline_id", "<u8"),        # interned command line
    ("comm_id", "<u8"),           # interned process comm
    ("related_listen_id", "<u8"),
    ("pid", "<i4"),
    ("is_any_ip", "u1"),
    ("is_http", "u1"),
    ("pad", "u1", (2,)),
    ("host_id", "<u4"),
    ("pad2", "u1", (4,)),
])

MAX_LISTENER_INFO_PER_BATCH = 1024

# HOST_INFO record — static host inventory announced at registration
# (+ on change): the field content of HOST_INFO_NOTIFY
# (``gy_comm_proto.h:2843``) — distribution/kernel/processor strings,
# core/memory topology (``common/gy_sys_hardware.h`` SYS_HARDWARE),
# cloud instance metadata (``common/gy_cloud_metadata.h`` IMDS fields).
# All strings interned (NAME_KIND_MISC); announce-rate → host-side
# registry, never a device slab.
HOST_INFO_DT = np.dtype([
    ("host_id", "<u4"),
    ("ncpus", "<u2"),              # online cores
    ("nnuma", "<u2"),
    ("ram_mb", "<u4"),
    ("swap_mb", "<u4"),
    ("boot_tusec", "<u8"),
    ("kern_ver_id", "<u8"),        # interned "6.1.0-18-amd64"
    ("distro_id", "<u8"),          # interned distribution name
    ("cputype_id", "<u8"),         # interned processor model
    ("instance_id", "<u8"),        # interned cloud instance id
    ("region_id", "<u8"),          # interned region name
    ("zone_id", "<u8"),            # interned zone name
    ("virt_type", "u1"),           # 0 none, 1 vm, 2 container
    ("cloud_type", "u1"),          # 0 none, 1 aws, 2 gcp, 3 azure
    ("is_k8s", "u1"),
    ("pad", "u1", (5,)),
])

MAX_HOST_INFO_PER_BATCH = 1024

# CGROUP_STATE record — 5s per-cgroup sweep: the queryable essence of the
# reference's cgroup tier (``common/gy_cgroup_stat.h`` CGROUP_HANDLE: v1
# cpuacct/cpu/memory/blkio + v2 unified stats, throttling, limits).
# Agents send the top-N cgroups by usage; cg_id is the path hash, the
# path string is interned.
CGROUP_DT = np.dtype([
    ("cg_id", "<u8"),              # hash of cgroup path
    ("dir_id", "<u8"),             # interned path string
    ("cpu_pct", "<f4"),
    ("cpu_limit_pct", "<f4"),      # <0 = no limit
    ("cpu_throttled_pct", "<f4"),  # fraction of periods throttled
    ("rss_mb", "<f4"),
    ("memory_limit_mb", "<f4"),    # <0 = no limit
    ("pgmajfault_sec", "<f4"),
    ("nprocs", "<u4"),
    ("is_v2", "u1"),
    ("state", "u1"),               # OBJ_STATE_E verdict from the agent
    ("pad", "u1", (2,)),
    ("host_id", "<u4"),
    ("pad2", "u1", (4,)),
])

MAX_CGROUPS_PER_BATCH = 2048

# MOUNT_STATE record — mount/filesystem inventory with freespace
# tracking (the capability of the reference's MOUNT_HDLR,
# ``common/gy_mount_disk.h:233``: per-mount fstype + freespace updated
# on a cadence; pseudo-filesystems excluded agent-side).
MOUNT_DT = np.dtype([
    ("mnt_id", "<u8"),             # hash of (device, mountpoint)
    ("dir_id", "<u8"),             # interned mountpoint path
    ("fstype_id", "<u8"),          # interned filesystem type
    ("size_mb", "<f4"),
    ("free_mb", "<f4"),
    ("used_pct", "<f4"),
    ("inodes_used_pct", "<f4"),
    ("is_network_fs", "u1"),       # nfs/cifs/… (gy_mount_disk.h:512)
    ("pad", "u1", (3,)),
    ("host_id", "<u4"),
])

MAX_MOUNTS_PER_BATCH = 1024

# NETIF_STATE record — interface inventory + rate deltas (the
# capability of the reference's NET_IF_HDLR, ``common/gy_netif.h:708``:
# speed, observed traffic, error rates per interface).
NETIF_DT = np.dtype([
    ("if_id", "<u8"),              # hash of interface name
    ("name_id", "<u8"),            # interned interface name
    ("speed_mbps", "<f4"),         # link speed (-1 unknown)
    ("rx_mb_sec", "<f4"),
    ("tx_mb_sec", "<f4"),
    ("rx_errs_sec", "<f4"),
    ("tx_errs_sec", "<f4"),
    ("is_up", "u1"),
    ("pad", "u1", (3,)),
    ("host_id", "<u4"),
    ("pad2", "u1", (4,)),     # 8-byte itemsize alignment
])

MAX_NETIF_PER_BATCH = 1024

# NAME_INTERN — the host-side half of the fixed-width record contract: the
# reference carries comm[16]/cmdline/issue strings inline in every record
# (e.g. gy_comm_proto.h:1708 trailing cmdline); we instead intern strings
# to 64-bit ids at the agent and announce (id, kind, utf-8 bytes) once.
# Queries resolve ids back to names via the InternTable (utils/intern.py).
NAME_KIND_COMM = 1      # process comm / command name
NAME_KIND_SVC = 2       # service (listener) name, id == glob_id
NAME_KIND_HOST = 3      # hostname, id == host_id
NAME_KIND_API = 4       # normalized API signature, id == hash(signature)
NAME_KIND_MISC = 5      # host-info / cgroup-path / other metadata strings
MAX_NAME_BYTES = 48

NAME_INTERN_DT = np.dtype([
    ("name_id", "<u8"),
    ("kind", "<u4"),
    ("nlen", "<u4"),
    ("name", "u1", (MAX_NAME_BYTES,)),
])

MAX_NAMES_PER_BATCH = 1024

# SKETCH_DELTA record — ONE fixed columnar layout for every mergeable
# partial an edge-folding agent ships (wire v5; see sketch/edgefold.py
# for the producer and engine/step.py:ingest_delta for the fold). The
# record is a typed envelope: ``kind`` selects how the 96-byte payload
# block decodes (sparse (index, weight) pairs / packed flow triplets /
# raw f32 vectors), ``nitem`` counts the occupied payload items, and
# ``errb`` is the self-describing error bound the row contributes
# (DK_RESID rows: flow mass truncated at the agent — folded into the
# top-K ``evicted`` undercount bound, the same annotation topk rows
# already carry). Splitting one logical sweep across any number of
# records/frames is ALWAYS safe: every fold the records feed is a
# monotone merge (scatter-add for counters/histograms/CMS/edges,
# scatter-max for HLL registers), so chunking never changes semantics.
DELTA_PAYLOAD_BYTES = 96

DELTA_DT = np.dtype([
    ("key_hi", "<u4"),       # svc glob-id halves (svc-keyed kinds),
    ("key_lo", "<u4"),       #   server svc for DK_DEP, 0 otherwise
    ("aux_hi", "<u4"),       # DK_DEP: client entity id halves
    ("aux_lo", "<u4"),
    ("payload", "u1", (DELTA_PAYLOAD_BYTES,)),
    ("errb", "<f4"),         # self-describing bound (DK_RESID: bytes)
    ("kind", "u1"),          # DK_* selector
    ("flags", "u1"),         # DK_DEP bit0: client entity is a listener
    ("nitem", "<u2"),        # occupied payload items
    ("host_id", "<u4"),      # source agent (shard routing key)
    ("pad", "u1", (4,)),
])

# payload interpretations (all little-endian, packed)
DELTA_PAIR_DT = np.dtype([("idx", "<u2"), ("wt", "<f4")])   # 6 B/item
DELTA_FLOW_DT = np.dtype([("hi", "<u4"), ("lo", "<u4"),
                          ("val", "<f4")])                  # 12 B/item
DELTA_PAIRS = DELTA_PAYLOAD_BYTES // DELTA_PAIR_DT.itemsize    # 16
DELTA_FLOWS = DELTA_PAYLOAD_BYTES // DELTA_FLOW_DT.itemsize    # 8
DELTA_SAMPLES = DELTA_PAYLOAD_BYTES // 4                       # 24 f32

# DK_* record kinds (unknown kinds are skipped + counted at decode —
# the same forward-compat discipline as unknown subtypes)
DK_SVC_CTR = 1    # payload f32[6]: bytes_sent, bytes_rcvd, n_close,
#                   dur_sum_us, n_conn_records, n_resp_records — the
#                   exact per-service counter columns the raw fold
#                   would have produced (scatter-add, ctr_win order)
DK_SVC_HIST = 2   # pairs (resp loghist bucket, count) — exact
DK_SVC_HLL = 3    # pairs (register, rank) for the per-svc distinct-
#                   client HLL — incremental register maxes
DK_GLOB_HLL = 4   # pairs (register, rank) for the global flow HLL
DK_SVC_TD = 5     # f32 samples for the per-svc t-digest stage
#                   (duty-cycled at the negotiated stride)
DK_FLOW = 6       # packed (flow_hi, flow_lo, bytes) aggregates — the
#                   CMS / top-K / invertible-bucket inputs
DK_DEP = 7        # one dependency edge: key=server svc, aux=client
#                   entity, payload f32[2] = [nconn, bytes]
DK_RESID = 8      # sweep residual: errb = flow bytes truncated by the
#                   agent's flow_max cap (→ top-K evicted bound)

MAX_DELTA_PER_BATCH = 1024

DTYPE_OF_SUBTYPE = {
    NOTIFY_SKETCH_DELTA: DELTA_DT,
    NOTIFY_TCP_CONN: TCP_CONN_DT,
    NOTIFY_LISTENER_STATE: LISTENER_STATE_DT,
    NOTIFY_HOST_STATE: HOST_STATE_DT,
    NOTIFY_RESP_SAMPLE: RESP_SAMPLE_DT,
    NOTIFY_AGGR_TASK_STATE: AGGR_TASK_DT,
    NOTIFY_CPU_MEM_STATE: CPU_MEM_DT,
    NOTIFY_NAME_INTERN: NAME_INTERN_DT,
    NOTIFY_REQ_TRACE: REQ_TRACE_DT,
    NOTIFY_LISTENER_INFO: LISTENER_INFO_DT,
    NOTIFY_HOST_INFO: HOST_INFO_DT,
    NOTIFY_CGROUP_STATE: CGROUP_DT,
    NOTIFY_MOUNT_STATE: MOUNT_DT,
    NOTIFY_NETIF_STATE: NETIF_DT,
    NOTIFY_TASK_PING: TASK_PING_DT,
    NOTIFY_AGENT_STATS: AGENT_STATS_DT,
    NOTIFY_SWEEP_SEQ: SWEEP_SEQ_DT,
}

# per-type batch caps enforced at decode (ref: per-struct MAX_NUM_* +
# validate() checks, gy_comm_proto.h:1711,2222)
MAX_OF_SUBTYPE = {
    NOTIFY_TCP_CONN: MAX_CONNS_PER_BATCH,
    NOTIFY_LISTENER_STATE: MAX_LISTENERS_PER_BATCH,
    NOTIFY_HOST_STATE: MAX_HOSTS_PER_BATCH,
    NOTIFY_RESP_SAMPLE: MAX_RESP_PER_BATCH,
    NOTIFY_AGGR_TASK_STATE: MAX_TASKS_PER_BATCH,
    NOTIFY_CPU_MEM_STATE: MAX_CPUMEM_PER_BATCH,
    NOTIFY_NAME_INTERN: MAX_NAMES_PER_BATCH,
    NOTIFY_REQ_TRACE: MAX_TRACE_PER_BATCH,
    NOTIFY_LISTENER_INFO: MAX_LISTENER_INFO_PER_BATCH,
    NOTIFY_HOST_INFO: MAX_HOST_INFO_PER_BATCH,
    NOTIFY_CGROUP_STATE: MAX_CGROUPS_PER_BATCH,
    NOTIFY_MOUNT_STATE: MAX_MOUNTS_PER_BATCH,
    NOTIFY_NETIF_STATE: MAX_NETIF_PER_BATCH,
    NOTIFY_TASK_PING: MAX_PINGS_PER_BATCH,
    NOTIFY_AGENT_STATS: MAX_AGENT_STATS_PER_BATCH,
    NOTIFY_SWEEP_SEQ: MAX_SWEEP_SEQ_PER_BATCH,
    NOTIFY_SKETCH_DELTA: MAX_DELTA_PER_BATCH,
}

for _name, _dt in [("HEADER_DT", HEADER_DT), ("EVENT_NOTIFY_DT", EVENT_NOTIFY_DT),
                   ("TCP_CONN_DT", TCP_CONN_DT),
                   ("LISTENER_STATE_DT", LISTENER_STATE_DT),
                   ("HOST_STATE_DT", HOST_STATE_DT),
                   ("RESP_SAMPLE_DT", RESP_SAMPLE_DT),
                   ("AGGR_TASK_DT", AGGR_TASK_DT),
                   ("CPU_MEM_DT", CPU_MEM_DT),
                   ("NAME_INTERN_DT", NAME_INTERN_DT),
                   ("REQ_TRACE_DT", REQ_TRACE_DT),
                   ("LISTENER_INFO_DT", LISTENER_INFO_DT),
                   ("HOST_INFO_DT", HOST_INFO_DT),
                   ("CGROUP_DT", CGROUP_DT),
                   ("TASK_PING_DT", TASK_PING_DT),
                   ("AGENT_STATS_DT", AGENT_STATS_DT),
                   ("SWEEP_SEQ_DT", SWEEP_SEQ_DT),
                   ("DELTA_DT", DELTA_DT)]:
    assert _dt.itemsize % 8 == 0, (_name, _dt.itemsize)


# ----------------------------------------------------- control-plane msgs
# Registration (ref PS_REGISTER_REQ_S/PM_CONNECT_CMD_S,
# gy_comm_proto.h:584-952, version gates :55-56): one message class —
# single-controller design collapses the partha→shyama→madhava two-step
# into one handshake; machine-id → host_id stickiness replaces
# assign_partha_madhava placement (gy_shconnhdlr.cc:5876).
REGISTER_REQ_DT = np.dtype([
    ("machine_id_hi", "<u8"),    # SYS_HARDWARE machine-id analogue
    ("machine_id_lo", "<u8"),
    ("wire_version", "<u4"),
    ("conn_type", "<u4"),        # CONN_EVENT | CONN_QUERY
    ("hostname_id", "<u8"),      # interned hostname (announce separately)
])

REGISTER_RESP_DT = np.dtype([
    ("status", "<u4"),
    ("host_id", "<u4"),          # assigned dense engine index
    ("curr_version", "<u4"),
    ("pad", "u1", (4,)),
])

CONN_EVENT = 1
CONN_QUERY = 2

REG_OK = 0
REG_ERR_VERSION = 1              # older than MIN_WIRE_VERSION
REG_ERR_CAPACITY = 2             # host slots exhausted (n_hosts)

# Trace capture control (server→agent): which services to capture.
# One record per service; enable=0 stops capture (ref REQ_TRACE_SET /
# SM_REQ_TRACE_DEF_NEW→partha distribution, gy_comm_proto.h:3295,3377).
TRACE_SET_DT = np.dtype([
    ("svc_glob_id", "<u8"),
    ("enable", "u1"),
    ("pad", "u1", (7,)),
])

MAX_TRACE_SET_PER_BATCH = 4096


def encode_trace_set(svc_ids, enable) -> bytes:
    """(svc_glob_ids, enable flags) → COMM_TRACE_SET frame(s); large
    sets chunk at the batch cap like every other record stream."""
    recs = np.zeros(len(svc_ids), TRACE_SET_DT)
    recs["svc_glob_id"] = np.asarray(svc_ids, np.uint64)
    recs["enable"] = np.asarray(enable, np.uint8)
    return b"".join(
        _frame(COMM_TRACE_SET,
               recs[i: i + MAX_TRACE_SET_PER_BATCH].tobytes(), MAGIC_MS)
        for i in range(0, max(len(recs), 1), MAX_TRACE_SET_PER_BATCH))


def decode_trace_set(payload: bytes) -> np.ndarray:
    n = len(payload) // TRACE_SET_DT.itemsize
    return np.frombuffer(payload, TRACE_SET_DT, count=n)


# Admission control (server→agent backpressure): which feed classes to
# hold in the agent's spool, for how long. Priority-aware shedding
# (PSketch, PAPERS.md): trace/pcap feeds throttle BEFORE svc/task
# state, so health classification degrades last. hold_ms=0 releases a
# class early. Unknown feed ids are ignored by receivers (forward
# compatible, the NOTIFY_AGENT_STATS versioning discipline).
FEED_TRACE = 1            # request-trace / pcap transaction streams
FEED_ALL = 2              # every sweep (state feeds included)

THROTTLE_DT = np.dtype([
    ("feed", "<u4"),
    ("hold_ms", "<u4"),
])

assert THROTTLE_DT.itemsize % 8 == 0


def encode_throttle(feeds, hold_ms: int, magic: int = MAGIC_MS) -> bytes:
    """(feed classes, hold duration ms) → one COMM_THROTTLE frame."""
    return encode_throttle_multi([(f, hold_ms) for f in feeds], magic)


def encode_throttle_multi(pairs, magic: int = MAGIC_MS) -> bytes:
    """[(feed, hold_ms), …] → one COMM_THROTTLE frame (hold 0 releases
    that class early)."""
    pairs = list(pairs)
    recs = np.zeros(len(pairs), THROTTLE_DT)
    recs["feed"] = np.asarray([p[0] for p in pairs], np.uint32)
    recs["hold_ms"] = np.asarray([p[1] for p in pairs], np.uint32)
    return _frame(COMM_THROTTLE, recs.tobytes(), magic)


def decode_throttle(payload: bytes) -> np.ndarray:
    n = len(payload) // THROTTLE_DT.itemsize
    return np.frombuffer(payload, THROTTLE_DT, count=n)


# Query multiplexing (ref QUERY_CMD/QUERY_RESPONSE, gy_comm_proto.h:502,
# 536; ≤4K outstanding :53): seqid echoes back with the JSON response.
QUERY_HDR_DT = np.dtype([
    ("seqid", "<u8"),
    ("status", "<u4"),           # req: 0; resp: QS_*
    ("nbytes", "<u4"),           # JSON payload bytes (before pad)
])

QS_OK = 0
QS_ERROR = 1                     # payload = {"error": msg}
QS_BUSY = 2                      # too many outstanding queries
QS_PARTIAL = 3                   # streamed chunk; more frames follow

MAX_OUTSTANDING_QUERIES = 64     # per conn (global 4K analogue)

# streamed-response chunk size: large results ride as a sequence of
# QS_PARTIAL frames closed by the final status frame — the reference
# streams web responses in 16MB heap-buffer chunks up to 4GB total
# (gy_msg_comm.h buffer discipline); 1MB chunks keep frames well under
# the 16MB frame cap with room for framing
QUERY_CHUNK_BYTES = 1 << 20


# ------------------------------------------------------------ integrity
# EVENT frames carry an XOR-fold payload checksum riding the unused
# upper bits of ``padding_sz`` (legit pad is 0..7): bit 31 flags
# presence, bits 8..15 hold the fold of every byte after the 16B
# header. TCP guarantees are per-hop, not end-to-end through proxies /
# buggy middleware — and the chaos tier proves a single flipped payload
# byte would otherwise fold GARBAGE into the engine silently (phantom
# hosts from a corrupted host_id). An XOR fold detects every single-byte
# corruption; flagless frames (old captures, control frames) skip
# verification, so the format stays backward compatible.
CHK_FLAG = 0x80000000
_CHK_SHIFT = 8


def _xor8(b) -> int:
    a = np.frombuffer(b, np.uint8)
    return int(np.bitwise_xor.reduce(a)) if a.size else 0


def _frame(data_type: int, payload: bytes, magic: int) -> bytes:
    pad = (-len(payload)) % 8
    total = HEADER_DT.itemsize + len(payload) + pad
    if total >= MAX_COMM_DATA_SZ:
        raise FrameError(f"frame {total} bytes exceeds 16MB cap")
    hdr = np.zeros((), HEADER_DT)
    hdr["magic"] = magic
    hdr["total_sz"] = total
    hdr["data_type"] = data_type
    hdr["padding_sz"] = pad
    return hdr.tobytes() + payload + b"\x00" * pad


def encode_register_req(machine_id: int, conn_type: int,
                        wire_version: int, hostname_id: int = 0) -> bytes:
    r = np.zeros((), REGISTER_REQ_DT)
    r["machine_id_hi"] = np.uint64((machine_id >> 64)
                                   & 0xFFFFFFFFFFFFFFFF)
    r["machine_id_lo"] = np.uint64(machine_id & 0xFFFFFFFFFFFFFFFF)
    r["wire_version"] = wire_version
    r["conn_type"] = conn_type
    r["hostname_id"] = np.uint64(hostname_id)
    return _frame(COMM_REGISTER_REQ, r.tobytes(), MAGIC_PM)


# Edge pre-aggregation negotiation (wire v5): when the server opts in
# (GYT_PREAGG=1), REGISTER_RESP grows a second trailing extension after
# the v4 last_seq word — the sketch geometry the agent MUST fold with
# (the server's resp loghist spec and HLL precisions are engine-cfg
# compile-time constants; a mismatched agent partial would scatter into
# the wrong buckets). Agents that predate v5 parse the fixed prefix +
# last_seq and ignore the tail; agents that understand it enable delta
# sweeps (net/agent.py). No advert → the agent stays in raw mode.
PREAGG_MAGIC = 0x50524147        # "GARP" little-endian sanity word

PREAGG_DT = np.dtype([
    ("magic", "<u4"),
    ("hll_p_svc", "<u4"),        # per-svc distinct-client HLL precision
    ("hll_p_global", "<u4"),     # global flow HLL precision
    ("td_stride", "<u4"),        # digest duty-cycle (1-in-N samples)
    ("resp_nbuckets", "<u4"),    # resp loghist spec (vmin/vmax below)
    ("flow_max", "<u4"),         # per-sweep flow-aggregate cap; mass
    #                              past it ships as a DK_RESID bound
    ("resp_vmin", "<f8"),
    ("resp_vmax", "<f8"),
])

assert PREAGG_DT.itemsize % 8 == 0

_PREAGG_FIELDS = ("hll_p_svc", "hll_p_global", "td_stride",
                  "resp_nbuckets", "flow_max", "resp_vmin", "resp_vmax")


def encode_preagg(params: dict) -> bytes:
    """Pre-aggregation advert dict → the REGISTER_RESP v5 tail."""
    r = np.zeros((), PREAGG_DT)
    r["magic"] = PREAGG_MAGIC
    for f in _PREAGG_FIELDS:
        r[f] = params[f]
    return r.tobytes()


def decode_preagg(buf: bytes):
    """v5 tail bytes → params dict, or None when absent/foreign."""
    if len(buf) < PREAGG_DT.itemsize:
        return None
    r = np.frombuffer(buf, PREAGG_DT, count=1)[0]
    if int(r["magic"]) != PREAGG_MAGIC:
        return None
    out = {f: (float(r[f]) if f.startswith("resp_v") else int(r[f]))
           for f in _PREAGG_FIELDS}
    return out


def encode_register_resp(status: int, host_id: int,
                         curr_version: int, last_seq: int = 0,
                         preagg: dict | None = None) -> bytes:
    """REGISTER_RESP + the v4 trailing extension: the server's durable
    per-host sweep-seq high-water mark (``last_seq``), + the optional
    v5 pre-aggregation advert (``preagg``, see PREAGG_DT). Agents built
    before v4 parse the fixed prefix and ignore the tail; agents that
    understand it prune already-durable sweeps from their resend spool
    (the WAL dedup contract, see NOTIFY_SWEEP_SEQ)."""
    r = np.zeros((), REGISTER_RESP_DT)
    r["status"] = status
    r["host_id"] = host_id
    r["curr_version"] = curr_version
    ext = np.uint64(last_seq).tobytes()
    if preagg is not None:
        ext += encode_preagg(preagg)
    return _frame(COMM_REGISTER_RESP, r.tobytes() + ext, MAGIC_MS)


def decode_register_resp(payload: bytes) -> tuple:
    """REGISTER_RESP payload → (status, host_id, curr_version,
    last_seq, preagg). ``last_seq`` is 0 when the server predates the
    v4 extension (16-byte fixed payload only); ``preagg`` is None
    unless the server advertised the v5 edge pre-aggregation tail."""
    r = np.frombuffer(payload, REGISTER_RESP_DT, count=1)[0]
    last_seq = 0
    preagg = None
    base = REGISTER_RESP_DT.itemsize
    if len(payload) >= base + 8:
        last_seq = int(np.frombuffer(payload, "<u8", 1, base)[0])
        preagg = decode_preagg(payload[base + 8:])
    return (int(r["status"]), int(r["host_id"]),
            int(r["curr_version"]), last_seq, preagg)


def encode_query(seqid: int, obj, status: int = QS_OK,
                 resp: bool = False) -> bytes:
    import json as _json
    payload = _json.dumps(obj).encode()
    h = np.zeros((), QUERY_HDR_DT)
    h["seqid"] = np.uint64(seqid)
    h["status"] = status
    h["nbytes"] = len(payload)
    return _frame(COMM_QUERY_RESP if resp else COMM_QUERY_CMD,
                  h.tobytes() + payload, MAGIC_NQ)


def iter_query_frames(seqid: int, obj, status: int = QS_OK,
                      chunk_bytes: int = QUERY_CHUNK_BYTES):
    """Yield a streamed frame sequence for a (possibly large) JSON
    response: N-1 QS_PARTIAL chunks + one final frame carrying
    ``status``. A small response is exactly one ordinary frame.
    Writers send each frame as it yields (bounded transport memory; the
    JSON text itself is materialized once — ``json.dumps`` — so peak is
    ~1× payload, vs ~3× when the whole frame blob is pre-joined)."""
    import json as _json
    payload = _json.dumps(obj).encode()
    for off in range(0, max(len(payload), 1), chunk_bytes):
        body = payload[off: off + chunk_bytes]
        last = off + chunk_bytes >= len(payload)
        h = np.zeros((), QUERY_HDR_DT)
        h["seqid"] = np.uint64(seqid)
        h["status"] = status if last else QS_PARTIAL
        h["nbytes"] = len(body)
        yield _frame(COMM_QUERY_RESP, h.tobytes() + body, MAGIC_NQ)


def encode_query_frames(seqid: int, obj, status: int = QS_OK,
                        chunk_bytes: int = QUERY_CHUNK_BYTES) -> bytes:
    """Joined form of :func:`iter_query_frames` (tests / small results)."""
    return b"".join(iter_query_frames(seqid, obj, status, chunk_bytes))


def decode_query_chunk(payload: bytes):
    """QUERY_RESP frame payload → (seqid, status, raw_body_bytes).

    Callers accumulate QS_PARTIAL bodies and JSON-parse once the final
    status arrives (the streamed-response read side)."""
    h = np.frombuffer(payload, QUERY_HDR_DT, count=1)[0]
    n = int(h["nbytes"])
    body = payload[QUERY_HDR_DT.itemsize: QUERY_HDR_DT.itemsize + n]
    return int(h["seqid"]), int(h["status"]), body


def decode_query_payload(payload: bytes):
    """QUERY_CMD/RESP frame payload → (seqid, status, json_obj)."""
    import json as _json
    h = np.frombuffer(payload, QUERY_HDR_DT, count=1)[0]
    n = int(h["nbytes"])
    body = payload[QUERY_HDR_DT.itemsize: QUERY_HDR_DT.itemsize + n]
    return int(h["seqid"]), int(h["status"]), _json.loads(body or b"null")


def encode_frame(subtype: int, records: np.ndarray,
                 magic: int = MAGIC_PM) -> bytes:
    """Frame a structured record array as COMM_HEADER+EVENT_NOTIFY+payload.

    Raises FrameError at the producer for frames the decoder would reject
    (per-subtype batch caps, 16MB frame cap) — a malformed frame in a byte
    stream poisons every frame behind it.
    """
    cap = MAX_OF_SUBTYPE.get(subtype)
    if cap is not None and len(records) > cap:
        raise FrameError(
            f"{len(records)} records > cap {cap} for subtype {subtype}")
    payload = records.tobytes()
    total = HEADER_DT.itemsize + EVENT_NOTIFY_DT.itemsize + len(payload)
    if total >= MAX_COMM_DATA_SZ:
        raise FrameError(f"frame {total} bytes exceeds 16MB cap")
    hdr = np.zeros((), HEADER_DT)
    hdr["magic"] = magic
    hdr["total_sz"] = total          # records are 8-aligned → no padding
    hdr["data_type"] = COMM_EVENT_NOTIFY
    ev = np.zeros((), EVENT_NOTIFY_DT)
    ev["subtype"] = subtype
    ev["nevents"] = len(records)
    ev_b = ev.tobytes()
    # pad 0 + checksum of everything after the header (see CHK_FLAG)
    hdr["padding_sz"] = CHK_FLAG | (
        (_xor8(ev_b) ^ _xor8(payload)) << _CHK_SHIFT)
    return hdr.tobytes() + ev_b + payload


def encode_frames_chunked(subtype: int, records: np.ndarray,
                          magic: int = MAGIC_PM) -> bytes:
    """Frame a record array of ANY length: split at the subtype's batch
    cap (``MAX_OF_SUBTYPE``) into as many frames as needed. The one
    cap-split loop for every producer (sim, real collectors, replay)."""
    cap = MAX_OF_SUBTYPE.get(subtype, len(records) or 1)
    return b"".join(encode_frame(subtype, records[i:i + cap], magic)
                    for i in range(0, len(records), cap))


class FrameError(ValueError):
    """Corrupt / hostile framing. ``reason`` is a short machine label
    (``bad_magic`` / ``bad_size`` / ``truncated`` / ``bad_frame``) the
    server attributes rejects to (``frames_rejected|reason=...``)."""

    def __init__(self, msg: str, reason: str = "bad_frame"):
        super().__init__(msg)
        self.reason = reason


import struct as _struct  # noqa: E402
# (magic, total_sz) — the per-read hot-path header peek
_HDR_PREFIX_UNPACK = _struct.Struct("<II").unpack_from


def complete_prefix(buf: bytes) -> int:
    """Length of the longest prefix of COMPLETE frames.

    Per-connection reassembly helper: a server multiplexing many conns
    into one decoder must hold back each conn's trailing partial frame
    (another conn's bytes would otherwise splice into the middle of it).
    Walks headers only — O(frames), no payload touched. Raises
    FrameError on a corrupt header so the caller can drop the conn."""
    off = 0
    n = len(buf)
    hsz = HEADER_DT.itemsize
    esz = EVENT_NOTIFY_DT.itemsize
    unpack = _HDR_PREFIX_UNPACK
    magics = (MAGIC_PM, MAGIC_MS, MAGIC_NQ)
    while off + hsz <= n:
        magic, total = unpack(buf, off)
        if magic not in magics:
            raise FrameError(f"bad magic {magic:#x} at {off}",
                             reason="bad_magic")
        # same bound as decode_frames — a frame this walk accepts must
        # never be one the decoders reject at the header
        if total < hsz + esz or total >= MAX_COMM_DATA_SZ:
            raise FrameError(f"bad total_sz {total} at {off}",
                             reason="bad_size")
        if off + total > n:
            break
        off += total
    return off


def count_events(buf: bytes) -> int:
    """Total EVENT_NOTIFY records across the complete frames of ``buf``
    (header walk only — payloads untouched). The spool/loss-accounting
    helper: agents count what a sweep carries before spooling it, so a
    dropped sweep's records can be attributed, not silently lost."""
    n = 0
    off = 0
    ln = len(buf)
    hsz = HEADER_DT.itemsize
    esz = EVENT_NOTIFY_DT.itemsize
    while off + hsz <= ln:
        _magic, total = _HDR_PREFIX_UNPACK(buf, off)
        if total < hsz or off + total > ln:
            break
        dtype = int.from_bytes(buf[off + 8: off + 12], "little")
        if dtype == COMM_EVENT_NOTIFY and total >= hsz + esz:
            n += int.from_bytes(buf[off + hsz + 4: off + hsz + 8],
                                "little")
        off += total
    return n


async def read_frame(reader, first: bytes = b"",
                     timeout=None) -> tuple[int, bytes]:
    """THE validated async frame reader → ``(data_type, payload)``.

    Shared by the agent and the server (one validation discipline on
    both ends of the wire): magic gate, ``total_sz`` bounds (a corrupt
    header can neither hang ``readexactly`` on a multi-MB read nor
    crash it on a short one) and ``padding_sz`` bounds, all checked
    BEFORE the body read. ``first`` carries bytes already peeked off
    the stream. Raises :class:`FrameError` (with a ``reason``) on a
    poison or truncated header, ``asyncio.IncompleteReadError`` on a
    clean EOF at a frame boundary, and ``asyncio.TimeoutError`` when
    ``timeout`` (whole-frame deadline, seconds) expires."""
    if timeout is not None:
        import asyncio
        return await asyncio.wait_for(_read_frame(reader, first), timeout)
    return await _read_frame(reader, first)


async def _read_frame(reader, first: bytes = b"") -> tuple[int, bytes]:
    import asyncio
    hsz = HEADER_DT.itemsize
    try:
        hdr_b = first + await reader.readexactly(hsz - len(first))
    except asyncio.IncompleteReadError as e:
        if first or e.partial:
            raise FrameError(
                f"truncated header ({len(first) + len(e.partial)}"
                f"/{hsz} bytes at EOF)", reason="truncated") from e
        raise                    # clean EOF at a frame boundary
    hdr = np.frombuffer(hdr_b, HEADER_DT, count=1)[0]
    magic = int(hdr["magic"])
    if magic not in (MAGIC_PM, MAGIC_MS, MAGIC_NQ):
        raise FrameError(f"bad magic {magic:#x}", reason="bad_magic")
    total = int(hdr["total_sz"])
    if total < hsz or total >= MAX_COMM_DATA_SZ:
        raise FrameError(f"bad total_sz {total}", reason="bad_size")
    padf = int(hdr["padding_sz"])
    pad = padf & 0xFF                # upper bits carry the checksum
    if pad > total - hsz:
        raise FrameError(f"bad padding_sz {pad} (total_sz {total})",
                         reason="bad_size")
    try:
        body = await reader.readexactly(total - hsz)
    except asyncio.IncompleteReadError as e:
        raise FrameError(
            f"truncated frame body ({len(e.partial)}/{total - hsz} "
            f"bytes at EOF)", reason="truncated") from e
    if padf & CHK_FLAG and \
            _xor8(body) != (padf >> _CHK_SHIFT) & 0xFF:
        raise FrameError("payload checksum mismatch", reason="checksum")
    return int(hdr["data_type"]), body[: len(body) - pad]


def decode_frames(buf: bytes, counts: Optional[dict] = None,
                  event_only: bool = False):
    """Parse a byte stream of frames → list of (subtype, structured array).

    Returns (frames, bytes_consumed): a trailing partial frame is left for
    the caller to resume with more bytes — the batched analogue of the
    partial-read resume in the reference's epoll conntrack
    (``common/gy_epoll_conntrack.h``).

    Hardening (the feed-path contract; mirrored bit-for-bit by the
    native deframer):
    - known subtypes enforce EXACT sizing (``nevents·itemsize`` must
      fill the frame) — slack means a corrupted ``nevents``, rejected
      loudly rather than silently decoding fewer records than sent;
    - frames flagged with :data:`CHK_FLAG` verify the XOR payload
      checksum (a flipped byte in flight is a counted reject, not
      garbage folded into the engine);
    - with ``counts`` given, records claimed by skipped
      unknown-subtype frames accumulate under
      ``counts["unknown_records"]`` (countable, not silent);
    - with ``event_only=True`` (the event-conn feed path) a non-EVENT
      ``data_type`` raises instead of skipping — nothing else belongs
      on that stream, so it is a corrupted byte.
    """
    frames = []
    off = 0
    n = len(buf)
    hsz = HEADER_DT.itemsize
    esz = EVENT_NOTIFY_DT.itemsize
    while off + hsz <= n:
        hdr = np.frombuffer(buf, HEADER_DT, count=1, offset=off)[0]
        if hdr["magic"] not in (MAGIC_PM, MAGIC_MS, MAGIC_NQ):
            raise FrameError(f"bad magic {hdr['magic']:#x} at {off}",
                             reason="bad_magic")
        total = int(hdr["total_sz"])
        if total < hsz + esz or total >= MAX_COMM_DATA_SZ:
            raise FrameError(f"bad total_sz {total} at {off}",
                             reason="bad_size")
        if off + total > n:
            break  # partial frame
        padf = int(hdr["padding_sz"])
        if padf & CHK_FLAG and \
                _xor8(buf[off + hsz: off + total]) \
                != (padf >> _CHK_SHIFT) & 0xFF:
            raise FrameError(f"payload checksum mismatch at {off}",
                             reason="checksum")
        if hdr["data_type"] == COMM_EVENT_NOTIFY:
            ev = np.frombuffer(buf, EVENT_NOTIFY_DT, 1, off + hsz)[0]
            subtype = int(ev["subtype"])
            nev = int(ev["nevents"])
            dt = DTYPE_OF_SUBTYPE.get(subtype)
            if dt is not None:
                if nev > MAX_OF_SUBTYPE[subtype]:
                    raise FrameError(
                        f"nevents {nev} > cap {MAX_OF_SUBTYPE[subtype]} "
                        f"for subtype {subtype} at {off}",
                        reason="bad_size")
                need = hsz + esz + nev * dt.itemsize
                if need != total:
                    raise FrameError(
                        f"nevents {nev} does not fill frame at {off} "
                        f"(need {need}, total {total})",
                        reason="bad_size")
                recs = np.frombuffer(buf, dt, nev, off + hsz + esz)
                frames.append((subtype, recs))
            else:
                # unknown subtypes skipped (forward compat, ref version
                # gates) — but COUNTED when the caller asks: a skipped
                # frame's records must never be silent loss
                if counts is not None:
                    counts["unknown_records"] = \
                        counts.get("unknown_records", 0) + nev
        elif event_only:
            # the event stream carries EVENT_NOTIFY frames only — any
            # other data_type there is a corrupted byte, and skipping
            # it would silently lose the frame's records
            raise FrameError(
                f"unexpected data_type {int(hdr['data_type'])} on the "
                f"event stream at {off}", reason="bad_dtype")
        off += total
    return frames, off
