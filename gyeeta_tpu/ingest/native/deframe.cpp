// Native wire deframer: the hot L1 byte path in C++.
//
// The reference's L1 epoll threads validate COMM_HEADER framing and batch
// payload records into DB_WRITE_ARR before handing to workers
// (server/gy_mconnhdlr.cc:2430-2520). This is that stage for the TPU
// ingest tier: scan a byte stream, validate every frame, and compact all
// records of one subtype into a single contiguous output buffer — so
// Python does exactly one np.frombuffer per subtype per drain, no
// per-frame interpreter work.
//
// The subtype table (subtype, itemsize, cap) is NOT compiled in: the
// Python loader pushes it via gyt_set_table() from wire.DTYPE_OF_SUBTYPE
// at load time, so the native path can never drift from wire.py — the
// single-source-of-truth discipline the reference gets from sharing one
// gy_comm_proto.h between all components.
//
// Validation rules are identical to wire.decode_frames: magic check,
// total_sz bounds, per-subtype batch caps, nevents-fits-frame.
//
// Build: ingest/native/build.py (g++ -O3 -shared). Loaded via ctypes
// (ingest/native/__init__.py) with transparent fallback to the Python
// decoder when the shared object is absent.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t MAGIC_PM = 0x47590001u;
constexpr uint32_t MAGIC_MS = 0x47590002u;
constexpr uint32_t MAGIC_NQ = 0x47590003u;
constexpr uint32_t MAX_COMM_DATA_SZ = 16u * 1024u * 1024u;
constexpr uint32_t COMM_EVENT_NOTIFY = 1u;

constexpr int64_t HDR_SZ = 16;   // HEADER_DT
constexpr int64_t EV_SZ = 8;     // EVENT_NOTIFY_DT
constexpr int32_t MAX_TYPES = 64;

struct Header {
  uint32_t magic;
  uint32_t total_sz;
  uint32_t data_type;
  uint32_t padding_sz;
};

struct EventNotify {
  uint32_t subtype;
  uint32_t nevents;
};

struct SubtypeInfo {
  uint32_t subtype;
  int64_t itemsize;
  uint32_t cap;
};

SubtypeInfo g_table[MAX_TYPES];
int32_t g_ntypes = 0;

int32_t index_of(uint32_t subtype) {
  for (int32_t i = 0; i < g_ntypes; i++)
    if (g_table[i].subtype == subtype) return i;
  return -1;
}

const SubtypeInfo* info_of(uint32_t subtype) {
  const int32_t i = index_of(subtype);
  return i >= 0 ? &g_table[i] : nullptr;
}

enum GytErr : int32_t {
  GYT_OK = 0,
  GYT_BAD_MAGIC = 1,
  GYT_BAD_TOTAL = 2,
  GYT_CAP_EXCEEDED = 3,
  GYT_NEV_OVERFLOW = 4,
  GYT_OUT_FULL = 5,
  GYT_BAD_TABLE = 6,
  GYT_BAD_DTYPE = 7,   // non-EVENT frame on the event stream (the feed
                       // path carries EVENT_NOTIFY only — anything else
                       // is a corrupted data_type byte, and skipping it
                       // would be silent record loss)
  GYT_BAD_CHECKSUM = 8,  // flagged frame's XOR payload fold mismatched
};

// padding_sz bit 31 flags a payload checksum in bits 8..15 (wire.py
// CHK_FLAG): XOR fold of every byte after the 16B header. Verified in
// the sizing scan (one extra read pass; the extract pass trusts it).
constexpr uint32_t CHK_FLAG = 0x80000000u;

inline uint8_t xor_fold(const uint8_t* p, int64_t n) {
  uint8_t x = 0;
  for (int64_t i = 0; i < n; i++) x ^= p[i];  // -O3 vectorizes this
  return x;
}

}  // namespace

extern "C" {

// Install the subtype table: n triples of (subtype, itemsize, cap).
// Called once by the Python loader before any scan/extract; itemsizes
// must be 8-aligned (wire.py asserts the same on its side).
int32_t gyt_set_table(const int64_t* triples, int32_t n) {
  if (n < 1 || n > MAX_TYPES) return GYT_BAD_TABLE;
  for (int32_t i = 0; i < n; i++) {
    const int64_t itemsize = triples[i * 3 + 1];
    if (itemsize <= 0 || itemsize % 8 != 0) return GYT_BAD_TABLE;
    g_table[i].subtype = static_cast<uint32_t>(triples[i * 3 + 0]);
    g_table[i].itemsize = itemsize;
    g_table[i].cap = static_cast<uint32_t>(triples[i * 3 + 2]);
  }
  g_ntypes = n;
  return GYT_OK;
}

// Echo the installed table back (layout handshake round-trip).
int32_t gyt_layout(int64_t* out, int64_t max_triples) {
  int32_t n = 0;
  for (int32_t i = 0; i < g_ntypes; i++) {
    if (n >= max_triples) break;
    out[n * 3 + 0] = g_table[i].subtype;
    out[n * 3 + 1] = g_table[i].itemsize;
    out[n * 3 + 2] = g_table[i].cap;
    n++;
  }
  return n;
}

// Scan [buf, buf+len): validate frames; copy records of `subtype` into
// out (capacity out_cap bytes). A trailing partial frame is left for
// resume. Returns GYT_OK or first error; *consumed = bytes fully parsed,
// *out_nrec = records written, *total_nrec = records of this subtype seen
// (== written unless GYT_OUT_FULL).
int32_t gyt_extract(const uint8_t* buf, int64_t len, uint32_t subtype,
                    uint8_t* out, int64_t out_cap, int64_t* consumed,
                    int64_t* out_nrec, int64_t* total_nrec) {
  const SubtypeInfo* want = info_of(subtype);
  int64_t off = 0, written = 0, seen = 0;
  *consumed = 0;
  *out_nrec = 0;
  *total_nrec = 0;
  if (want == nullptr) return GYT_BAD_TABLE;

  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;  // partial frame: resume later

    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const SubtypeInfo* si = info_of(ev.subtype);
      if (si != nullptr) {
        if (ev.nevents > si->cap) return GYT_CAP_EXCEEDED;
        // EXACT sizing: producers frame records tightly, so any slack
        // or overflow means a corrupted nevents — reject it (counted)
        // instead of silently decoding fewer records than were sent
        const int64_t need =
            HDR_SZ + EV_SZ + static_cast<int64_t>(ev.nevents) * si->itemsize;
        if (need != total) return GYT_NEV_OVERFLOW;
        if (ev.subtype == subtype && ev.nevents > 0) {
          const int64_t nbytes =
              static_cast<int64_t>(ev.nevents) * si->itemsize;
          seen += ev.nevents;
          if (written + nbytes <= out_cap) {
            std::memcpy(out + written, buf + off + HDR_SZ + EV_SZ,
                        static_cast<size_t>(nbytes));
            written += nbytes;
          } else {
            *consumed = off;
            *out_nrec = written / want->itemsize;
            *total_nrec = seen;
            return GYT_OUT_FULL;
          }
        }
      }
      // unknown subtypes skipped (forward compat)
    } else {
      return GYT_BAD_DTYPE;  // event stream carries EVENT_NOTIFY only
    }
    off += total;
  }
  *consumed = off;
  *out_nrec = written / want->itemsize;
  *total_nrec = seen;
  return GYT_OK;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Columnar TCP_CONN decode: raw records → the ConnBatch column arrays.
// The hashing (murmur3 finalizer chains, xor-folded IPv6 words, the
// 5-tuple flow key) is bit-identical to utils/hashing.py's numpy path —
// a parity test diffs the two on random records. Field offsets are NOT
// compiled in: the Python loader pushes them from wire.TCP_CONN_DT
// (gyt_set_conn_layout), same discipline as the subtype table.

namespace {

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

inline uint32_t mix64(uint32_t hi, uint32_t lo, uint32_t salt) {
  const uint32_t s = (salt + 1u) * 0x9E3779B9u;
  uint32_t h = fmix32(lo ^ s);
  return fmix32(hi ^ h ^ salt);
}

// offsets into one TCP_CONN record, pushed from Python
struct ConnLayout {
  int64_t itemsize;
  int64_t cli, ser, nat_cli, nat_ser;  // IP_PORT offsets (16B ip first)
  int64_t tusec_start, tusec_close;
  int64_t cli_task, cli_rel, ser_glob;
  int64_t bytes_sent, bytes_rcvd;
  int64_t host_id, flags;
  int64_t port_off;  // offset of port WITHIN an IP_PORT sub-record
};

ConnLayout g_conn{};
bool g_conn_set = false;

inline void fold_ip(const uint8_t* p, uint32_t* hi, uint32_t* lo) {
  uint32_t w[4];
  std::memcpy(w, p, 16);
  *hi = w[0] ^ w[2];
  *lo = w[1] ^ w[3];
}

inline bool ip_nonzero(const uint8_t* p) {
  uint64_t a, b;
  std::memcpy(&a, p, 8);
  std::memcpy(&b, p + 8, 8);
  return (a | b) != 0;
}

}  // namespace

extern "C" {

// fields: itemsize then the 14 offsets in ConnLayout order.
int32_t gyt_set_conn_layout(const int64_t* fields, int32_t n) {
  if (n != 15) return GYT_BAD_TABLE;
  int64_t* dst = &g_conn.itemsize;
  for (int32_t i = 0; i < 15; i++) dst[i] = fields[i];
  if (g_conn.itemsize <= 0 || g_conn.itemsize % 8 != 0)
    return GYT_BAD_TABLE;
  g_conn_set = true;
  return GYT_OK;
}

// Decode n records at `recs` into pre-allocated column arrays (each of
// length >= n). Semantics identical to decode.conn_batch's per-record
// math; Python pads/validates lanes.
int32_t gyt_decode_conn(
    const uint8_t* recs, int64_t n, uint32_t* svc_hi, uint32_t* svc_lo,
    uint32_t* flow_hi, uint32_t* flow_lo, uint32_t* cli_hi,
    uint32_t* cli_lo, uint32_t* task_hi, uint32_t* task_lo,
    uint32_t* rel_hi, uint32_t* rel_lo, float* bytes_sent,
    float* bytes_rcvd, float* duration_us, int32_t* host_id,
    uint8_t* is_close, uint8_t* is_accept) {
  if (!g_conn_set) return GYT_BAD_TABLE;
  const ConnLayout& L = g_conn;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* r = recs + i * L.itemsize;
    uint64_t u64;

    std::memcpy(&u64, r + L.ser_glob, 8);
    svc_hi[i] = static_cast<uint32_t>(u64 >> 32);
    svc_lo[i] = static_cast<uint32_t>(u64);
    std::memcpy(&u64, r + L.cli_task, 8);
    task_hi[i] = static_cast<uint32_t>(u64 >> 32);
    task_lo[i] = static_cast<uint32_t>(u64);
    std::memcpy(&u64, r + L.cli_rel, 8);
    rel_hi[i] = static_cast<uint32_t>(u64 >> 32);
    rel_lo[i] = static_cast<uint32_t>(u64);

    // NAT-aware effective tuple (post-NAT view when conntrack resolved)
    const uint8_t* cli = r + L.cli;
    const uint8_t* ser = r + L.ser;
    const uint8_t* ncli = r + L.nat_cli;
    const uint8_t* nser = r + L.nat_ser;
    const bool nat_c = ip_nonzero(ncli);
    const bool nat_s = ip_nonzero(nser);
    const uint8_t* eff_cli = nat_c ? ncli : cli;
    const uint8_t* eff_ser = nat_s ? nser : ser;
    uint16_t cport, sport;
    std::memcpy(&cport, (nat_c ? ncli : cli) + L.port_off, 2);
    std::memcpy(&sport, (nat_s ? nser : ser) + L.port_off, 2);

    uint32_t cip_hi, cip_lo, sip_hi, sip_lo;
    fold_ip(eff_cli, &cip_hi, &cip_lo);
    fold_ip(eff_ser, &sip_hi, &sip_lo);

    // flow_key (utils/hashing.py): ports word, two mix64 streams, chain
    const uint32_t ports =
        (static_cast<uint32_t>(cport) << 16) | sport;
    const uint32_t a = mix64(cip_hi, cip_lo, 1);
    const uint32_t b = mix64(sip_hi, sip_lo, 2);
    const uint32_t f_lo = fmix32(a ^ (ports * 0x85EBCA6Bu));
    const uint32_t f_hi = fmix32(b ^ (6u * 0xC2B2AE35u) ^ f_lo);
    flow_hi[i] = f_hi;
    flow_lo[i] = f_lo;

    // client endpoint identity: address-only hash
    const uint32_t c_hi = fmix32(cip_hi ^ 0xC11E57u);
    cli_hi[i] = c_hi;
    cli_lo[i] = fmix32(cip_lo ^ c_hi);

    uint64_t bs, br, t0, t1;
    std::memcpy(&bs, r + L.bytes_sent, 8);
    std::memcpy(&br, r + L.bytes_rcvd, 8);
    std::memcpy(&t0, r + L.tusec_start, 8);
    std::memcpy(&t1, r + L.tusec_close, 8);
    bytes_sent[i] = static_cast<float>(bs);
    bytes_rcvd[i] = static_cast<float>(br);
    const bool closed = t1 > 0;
    duration_us[i] = closed ? static_cast<float>(t1 - t0) : 0.0f;
    is_close[i] = closed ? 1 : 0;

    uint32_t hid, flags;
    std::memcpy(&hid, r + L.host_id, 4);
    std::memcpy(&flags, r + L.flags, 4);
    host_id[i] = static_cast<int32_t>(hid);
    is_accept[i] = (flags & 2u) ? 1 : 0;
  }
  return GYT_OK;
}

}  // extern "C"

extern "C" {

// One-pass multi-subtype extract: walk the frame stream ONCE and append
// every known subtype's records into its own caller-provided buffer
// (outs/out_caps indexed in gyt_set_table order; outs[i] may be null
// when the scan counted zero records). This replaces the per-subtype
// gyt_extract walk in drain(): scan + one extract pass total, instead
// of scan + one walk per present subtype.
int32_t gyt_extract_multi(const uint8_t* buf, int64_t len,
                          uint8_t* const* outs, const int64_t* out_caps,
                          int64_t* out_nrec, int64_t* consumed) {
  int64_t off = 0;
  int64_t written[MAX_TYPES];  // bytes appended per table slot
  for (int32_t i = 0; i < g_ntypes; i++) {
    written[i] = 0;
    out_nrec[i] = 0;
  }
  *consumed = 0;
  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;  // partial frame: resume later
    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const int32_t idx = index_of(ev.subtype);
      if (idx >= 0) {
        const SubtypeInfo& si = g_table[idx];
        if (ev.nevents > si.cap) return GYT_CAP_EXCEEDED;
        const int64_t nbytes =
            static_cast<int64_t>(ev.nevents) * si.itemsize;
        if (HDR_SZ + EV_SZ + nbytes != total) return GYT_NEV_OVERFLOW;
        if (ev.nevents > 0) {
          if (outs[idx] == nullptr ||
              written[idx] + nbytes > out_caps[idx]) {
            *consumed = off;
            return GYT_OUT_FULL;
          }
          std::memcpy(outs[idx] + written[idx], buf + off + HDR_SZ + EV_SZ,
                      static_cast<size_t>(nbytes));
          written[idx] += nbytes;
          out_nrec[idx] += ev.nevents;
        }
      }
      // unknown subtypes skipped (forward compat)
    } else {
      return GYT_BAD_DTYPE;  // event stream carries EVENT_NOTIFY only
    }
    off += total;
  }
  *consumed = off;
  return GYT_OK;
}

// Count frames + records per subtype without copying (sizing pass).
// counts: array of g_ntypes int64, in gyt_set_table order.
// *unknown_records counts records claimed by EVENT frames of UNKNOWN
// subtype (forward compat / corrupted subtype byte): they are skipped,
// but the skip must be COUNTABLE — silent loss breaks the chaos tier's
// delivery accounting.
int32_t gyt_scan2(const uint8_t* buf, int64_t len, int64_t* counts,
                  int64_t* consumed, int64_t* unknown_records) {
  int64_t off = 0;
  for (int32_t i = 0; i < g_ntypes; i++) counts[i] = 0;
  *consumed = 0;
  *unknown_records = 0;
  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;
    if ((h.padding_sz & CHK_FLAG) &&
        xor_fold(buf + off + HDR_SZ, total - HDR_SZ) !=
            static_cast<uint8_t>(h.padding_sz >> 8))
      return GYT_BAD_CHECKSUM;
    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const int32_t idx = index_of(ev.subtype);
      if (idx >= 0) {
        if (ev.nevents > g_table[idx].cap) return GYT_CAP_EXCEEDED;
        const int64_t need = HDR_SZ + EV_SZ +
            static_cast<int64_t>(ev.nevents) * g_table[idx].itemsize;
        if (need != total) return GYT_NEV_OVERFLOW;
        counts[idx] += ev.nevents;
      } else {
        *unknown_records += ev.nevents;
      }
    } else {
      return GYT_BAD_DTYPE;  // event stream carries EVENT_NOTIFY only
    }
    off += total;
  }
  *consumed = off;
  return GYT_OK;
}

int32_t gyt_scan(const uint8_t* buf, int64_t len, int64_t* counts,
                 int64_t* consumed) {
  int64_t unknown = 0;
  return gyt_scan2(buf, len, counts, consumed, &unknown);
}

}  // extern "C"

// ---------------------------------------------------------------------
// Generic record→column pack kernels: the executor half of the
// wire→columnar compiler. Python compiles a column plan from the
// numpy structured dtype (field offset + scalar kind per output
// column — ingest/native/__init__.py builds it from wire.py, the same
// single-source-of-truth discipline as the subtype table) and these
// kernels execute it in one pass over the raw records, writing
// straight into caller-provided preallocated column buffers. Casts
// are the exact C equivalents of numpy's .astype() on the same
// scalars, so the output is bit-identical to ingest/decode.py's
// reference builders (the parity fuzz test diffs both).

namespace {

enum PackKind : int64_t {
  PK_U1 = 1, PK_U2 = 2, PK_U4 = 3, PK_U8 = 4, PK_I4 = 5, PK_F4 = 6,
};

inline bool kind_ok(int64_t k) { return k >= PK_U1 && k <= PK_F4; }

inline int64_t kind_size(int64_t k) {
  switch (k) {
    case PK_U1: return 1;
    case PK_U2: return 2;
    case PK_U4: case PK_I4: case PK_F4: return 4;
    default: return 8;
  }
}

inline float load_f32(const uint8_t* p, int64_t kind) {
  switch (kind) {
    case PK_U1: return static_cast<float>(*p);
    case PK_U2: { uint16_t v; std::memcpy(&v, p, 2);
                  return static_cast<float>(v); }
    case PK_U4: { uint32_t v; std::memcpy(&v, p, 4);
                  return static_cast<float>(v); }
    case PK_U8: { uint64_t v; std::memcpy(&v, p, 8);
                  return static_cast<float>(v); }
    case PK_I4: { int32_t v; std::memcpy(&v, p, 4);
                  return static_cast<float>(v); }
    default:    { float v; std::memcpy(&v, p, 4); return v; }
  }
}

inline int32_t load_i32(const uint8_t* p, int64_t kind) {
  switch (kind) {
    case PK_U1: return static_cast<int32_t>(*p);
    case PK_U2: { uint16_t v; std::memcpy(&v, p, 2);
                  return static_cast<int32_t>(v); }
    case PK_U4: { uint32_t v; std::memcpy(&v, p, 4);
                  return static_cast<int32_t>(v); }
    case PK_U8: { uint64_t v; std::memcpy(&v, p, 8);
                  return static_cast<int32_t>(v); }
    case PK_I4: { int32_t v; std::memcpy(&v, p, 4); return v; }
    default:    { float v; std::memcpy(&v, p, 4);
                  return static_cast<int32_t>(v); }
  }
}

}  // namespace

extern "C" {

// n records → (n, ncols) float32 row-major. ops = ncols pairs of
// (src_offset, kind). The stat/panel/vals matrix builder for
// LISTENER/HOST/TASK/CPU_MEM sweeps (replaces decode.py's per-field
// python loops).
int32_t gyt_pack_f32(const uint8_t* recs, int64_t n, int64_t itemsize,
                     const int64_t* ops, int32_t ncols, float* out) {
  if (itemsize <= 0 || ncols <= 0) return GYT_BAD_TABLE;
  for (int32_t c = 0; c < ncols; c++) {
    const int64_t off = ops[2 * c], kind = ops[2 * c + 1];
    if (!kind_ok(kind) || off < 0 || off + kind_size(kind) > itemsize)
      return GYT_BAD_TABLE;
  }
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* r = recs + i * itemsize;
    float* o = out + i * ncols;
    for (int32_t c = 0; c < ncols; c++)
      o[c] = load_f32(r + ops[2 * c], ops[2 * c + 1]);
  }
  return GYT_OK;
}

// One u64 field per record → (hi, lo) uint32 column pair (the TPU
// 64-bit id split of decode.split_u64).
int32_t gyt_split_u64(const uint8_t* recs, int64_t n, int64_t itemsize,
                      int64_t off, uint32_t* hi, uint32_t* lo) {
  if (itemsize <= 0 || off < 0 || off + 8 > itemsize)
    return GYT_BAD_TABLE;
  for (int64_t i = 0; i < n; i++) {
    uint64_t v;
    std::memcpy(&v, recs + i * itemsize + off, 8);
    hi[i] = static_cast<uint32_t>(v >> 32);
    lo[i] = static_cast<uint32_t>(v);
  }
  return GYT_OK;
}

// One scalar field per record → int32 column (host_id / state / issue).
int32_t gyt_pack_i32(const uint8_t* recs, int64_t n, int64_t itemsize,
                     int64_t off, int64_t kind, int32_t* out) {
  if (itemsize <= 0 || !kind_ok(kind) || off < 0
      || off + kind_size(kind) > itemsize)
    return GYT_BAD_TABLE;
  for (int64_t i = 0; i < n; i++)
    out[i] = load_i32(recs + i * itemsize + off, kind);
  return GYT_OK;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Specialized RESP_SAMPLE decode: the highest-rate subtype (4096/batch
// vs 2048 conns) gets a fused single-pass kernel instead of three
// generic ones. Layout pushed from wire.RESP_SAMPLE_DT like the conn
// layout.

namespace {

struct RespLayout {
  int64_t itemsize, glob_id, resp_usec, host_id;
};

RespLayout g_resp{};
bool g_resp_set = false;

}  // namespace

extern "C" {

int32_t gyt_set_resp_layout(const int64_t* fields, int32_t n) {
  if (n != 4) return GYT_BAD_TABLE;
  g_resp.itemsize = fields[0];
  g_resp.glob_id = fields[1];
  g_resp.resp_usec = fields[2];
  g_resp.host_id = fields[3];
  if (g_resp.itemsize <= 0 || g_resp.itemsize % 8 != 0)
    return GYT_BAD_TABLE;
  g_resp_set = true;
  return GYT_OK;
}

// Decode n RESP_SAMPLE records into pre-allocated columns: glob_id
// split, resp_usec → float32, host_id → int32 — bit-identical to
// decode.resp_batch's numpy math.
int32_t gyt_decode_resp(const uint8_t* recs, int64_t n, uint32_t* svc_hi,
                        uint32_t* svc_lo, float* resp_us,
                        int32_t* host_id) {
  if (!g_resp_set) return GYT_BAD_TABLE;
  const RespLayout& L = g_resp;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* r = recs + i * L.itemsize;
    uint64_t gid;
    uint32_t ru, hid;
    std::memcpy(&gid, r + L.glob_id, 8);
    std::memcpy(&ru, r + L.resp_usec, 4);
    std::memcpy(&hid, r + L.host_id, 4);
    svc_hi[i] = static_cast<uint32_t>(gid >> 32);
    svc_lo[i] = static_cast<uint32_t>(gid);
    resp_us[i] = static_cast<float>(ru);
    host_id[i] = static_cast<int32_t>(hid);
  }
  return GYT_OK;
}

}  // extern "C"
