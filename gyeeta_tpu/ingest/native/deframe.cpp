// Native wire deframer: the hot L1 byte path in C++.
//
// The reference's L1 epoll threads validate COMM_HEADER framing and batch
// payload records into DB_WRITE_ARR before handing to workers
// (server/gy_mconnhdlr.cc:2430-2520). This is that stage for the TPU
// ingest tier: scan a byte stream, validate every frame, and compact all
// records of one subtype into a single contiguous output buffer — so
// Python does exactly one np.frombuffer per subtype per drain, no
// per-frame interpreter work.
//
// Layouts mirror gyeeta_tpu/ingest/wire.py exactly (little-endian,
// 8-aligned structured dtypes). Validation rules are identical to
// wire.decode_frames: magic check, total_sz bounds, per-subtype batch
// caps, nevents-fits-frame.
//
// Build: ingest/native/build.py (g++ -O3 -shared). Loaded via ctypes
// (ingest/native/__init__.py) with transparent fallback to the Python
// decoder when the shared object is absent.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t MAGIC_PM = 0x47590001u;
constexpr uint32_t MAGIC_MS = 0x47590002u;
constexpr uint32_t MAGIC_NQ = 0x47590003u;
constexpr uint32_t MAX_COMM_DATA_SZ = 16u * 1024u * 1024u;
constexpr uint32_t COMM_EVENT_NOTIFY = 1u;

constexpr int64_t HDR_SZ = 16;   // HEADER_DT
constexpr int64_t EV_SZ = 8;     // EVENT_NOTIFY_DT

struct Header {
  uint32_t magic;
  uint32_t total_sz;
  uint32_t data_type;
  uint32_t padding_sz;
};

struct EventNotify {
  uint32_t subtype;
  uint32_t nevents;
};

// per-subtype record sizes + caps, must match wire.py DTYPE_OF_SUBTYPE
struct SubtypeInfo {
  uint32_t subtype;
  int64_t itemsize;
  uint32_t cap;
};

constexpr SubtypeInfo kSubtypes[] = {
    {10, 240, 2048},   // TCP_CONN      (TCP_CONN_DT.itemsize)
    {11, 104, 512},    // LISTENER_STATE
    {12, 48, 4096},    // HOST_STATE
    {13, 16, 4096},    // RESP_SAMPLE
};

const SubtypeInfo* info_of(uint32_t subtype) {
  for (const auto& s : kSubtypes)
    if (s.subtype == subtype) return &s;
  return nullptr;
}

enum GytErr : int32_t {
  GYT_OK = 0,
  GYT_BAD_MAGIC = 1,
  GYT_BAD_TOTAL = 2,
  GYT_CAP_EXCEEDED = 3,
  GYT_NEV_OVERFLOW = 4,
  GYT_OUT_FULL = 5,
};

}  // namespace

extern "C" {

// Scan [buf, buf+len): validate frames; copy records of `subtype` into
// out (capacity out_cap bytes). A trailing partial frame is left for
// resume. Returns GYT_OK or first error; *consumed = bytes fully parsed,
// *out_nrec = records written, *total_nrec = records of this subtype seen
// (== written unless GYT_OUT_FULL).
int32_t gyt_extract(const uint8_t* buf, int64_t len, uint32_t subtype,
                    uint8_t* out, int64_t out_cap, int64_t* consumed,
                    int64_t* out_nrec, int64_t* total_nrec) {
  const SubtypeInfo* want = info_of(subtype);
  int64_t off = 0, written = 0, seen = 0;
  *consumed = 0;
  *out_nrec = 0;
  *total_nrec = 0;
  if (want == nullptr) return GYT_BAD_TOTAL;

  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;  // partial frame: resume later

    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const SubtypeInfo* si = info_of(ev.subtype);
      if (si != nullptr) {
        if (ev.nevents > si->cap) return GYT_CAP_EXCEEDED;
        const int64_t need =
            HDR_SZ + EV_SZ + static_cast<int64_t>(ev.nevents) * si->itemsize;
        if (need > total) return GYT_NEV_OVERFLOW;
        if (ev.subtype == subtype && ev.nevents > 0) {
          const int64_t nbytes =
              static_cast<int64_t>(ev.nevents) * si->itemsize;
          seen += ev.nevents;
          if (written + nbytes <= out_cap) {
            std::memcpy(out + written, buf + off + HDR_SZ + EV_SZ,
                        static_cast<size_t>(nbytes));
            written += nbytes;
          } else {
            *consumed = off;
            *out_nrec = written / want->itemsize;
            *total_nrec = seen;
            return GYT_OUT_FULL;
          }
        }
      }
      // unknown subtypes skipped (forward compat)
    }
    off += total;
  }
  *consumed = off;
  *out_nrec = written / want->itemsize;
  *total_nrec = seen;
  return GYT_OK;
}

// Count frames + records per subtype without copying (sizing pass).
// counts: array of 4 int64 (order of kSubtypes). Returns error code.
int32_t gyt_scan(const uint8_t* buf, int64_t len, int64_t* counts,
                 int64_t* consumed) {
  int64_t off = 0;
  for (int i = 0; i < 4; i++) counts[i] = 0;
  *consumed = 0;
  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;
    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      for (int i = 0; i < 4; i++) {
        if (kSubtypes[i].subtype == ev.subtype) {
          if (ev.nevents > kSubtypes[i].cap) return GYT_CAP_EXCEEDED;
          const int64_t need = HDR_SZ + EV_SZ +
              static_cast<int64_t>(ev.nevents) * kSubtypes[i].itemsize;
          if (need > total) return GYT_NEV_OVERFLOW;
          counts[i] += ev.nevents;
        }
      }
    }
    off += total;
  }
  *consumed = off;
  return GYT_OK;
}

// Layout handshake: fill (subtype, itemsize, cap) triples so the Python
// loader can verify the compiled table matches wire.py before first use.
int32_t gyt_layout(int64_t* out, int64_t max_triples) {
  int32_t n = 0;
  for (const auto& s : kSubtypes) {
    if (n >= max_triples) break;
    out[n * 3 + 0] = s.subtype;
    out[n * 3 + 1] = s.itemsize;
    out[n * 3 + 2] = s.cap;
    n++;
  }
  return n;
}

}  // extern "C"
