// Native wire deframer: the hot L1 byte path in C++.
//
// The reference's L1 epoll threads validate COMM_HEADER framing and batch
// payload records into DB_WRITE_ARR before handing to workers
// (server/gy_mconnhdlr.cc:2430-2520). This is that stage for the TPU
// ingest tier: scan a byte stream, validate every frame, and compact all
// records of one subtype into a single contiguous output buffer — so
// Python does exactly one np.frombuffer per subtype per drain, no
// per-frame interpreter work.
//
// The subtype table (subtype, itemsize, cap) is NOT compiled in: the
// Python loader pushes it via gyt_set_table() from wire.DTYPE_OF_SUBTYPE
// at load time, so the native path can never drift from wire.py — the
// single-source-of-truth discipline the reference gets from sharing one
// gy_comm_proto.h between all components.
//
// Validation rules are identical to wire.decode_frames: magic check,
// total_sz bounds, per-subtype batch caps, nevents-fits-frame.
//
// Build: ingest/native/build.py (g++ -O3 -shared). Loaded via ctypes
// (ingest/native/__init__.py) with transparent fallback to the Python
// decoder when the shared object is absent.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t MAGIC_PM = 0x47590001u;
constexpr uint32_t MAGIC_MS = 0x47590002u;
constexpr uint32_t MAGIC_NQ = 0x47590003u;
constexpr uint32_t MAX_COMM_DATA_SZ = 16u * 1024u * 1024u;
constexpr uint32_t COMM_EVENT_NOTIFY = 1u;

constexpr int64_t HDR_SZ = 16;   // HEADER_DT
constexpr int64_t EV_SZ = 8;     // EVENT_NOTIFY_DT
constexpr int32_t MAX_TYPES = 64;

struct Header {
  uint32_t magic;
  uint32_t total_sz;
  uint32_t data_type;
  uint32_t padding_sz;
};

struct EventNotify {
  uint32_t subtype;
  uint32_t nevents;
};

struct SubtypeInfo {
  uint32_t subtype;
  int64_t itemsize;
  uint32_t cap;
};

SubtypeInfo g_table[MAX_TYPES];
int32_t g_ntypes = 0;

int32_t index_of(uint32_t subtype) {
  for (int32_t i = 0; i < g_ntypes; i++)
    if (g_table[i].subtype == subtype) return i;
  return -1;
}

const SubtypeInfo* info_of(uint32_t subtype) {
  const int32_t i = index_of(subtype);
  return i >= 0 ? &g_table[i] : nullptr;
}

enum GytErr : int32_t {
  GYT_OK = 0,
  GYT_BAD_MAGIC = 1,
  GYT_BAD_TOTAL = 2,
  GYT_CAP_EXCEEDED = 3,
  GYT_NEV_OVERFLOW = 4,
  GYT_OUT_FULL = 5,
  GYT_BAD_TABLE = 6,
};

}  // namespace

extern "C" {

// Install the subtype table: n triples of (subtype, itemsize, cap).
// Called once by the Python loader before any scan/extract; itemsizes
// must be 8-aligned (wire.py asserts the same on its side).
int32_t gyt_set_table(const int64_t* triples, int32_t n) {
  if (n < 1 || n > MAX_TYPES) return GYT_BAD_TABLE;
  for (int32_t i = 0; i < n; i++) {
    const int64_t itemsize = triples[i * 3 + 1];
    if (itemsize <= 0 || itemsize % 8 != 0) return GYT_BAD_TABLE;
    g_table[i].subtype = static_cast<uint32_t>(triples[i * 3 + 0]);
    g_table[i].itemsize = itemsize;
    g_table[i].cap = static_cast<uint32_t>(triples[i * 3 + 2]);
  }
  g_ntypes = n;
  return GYT_OK;
}

// Echo the installed table back (layout handshake round-trip).
int32_t gyt_layout(int64_t* out, int64_t max_triples) {
  int32_t n = 0;
  for (int32_t i = 0; i < g_ntypes; i++) {
    if (n >= max_triples) break;
    out[n * 3 + 0] = g_table[i].subtype;
    out[n * 3 + 1] = g_table[i].itemsize;
    out[n * 3 + 2] = g_table[i].cap;
    n++;
  }
  return n;
}

// Scan [buf, buf+len): validate frames; copy records of `subtype` into
// out (capacity out_cap bytes). A trailing partial frame is left for
// resume. Returns GYT_OK or first error; *consumed = bytes fully parsed,
// *out_nrec = records written, *total_nrec = records of this subtype seen
// (== written unless GYT_OUT_FULL).
int32_t gyt_extract(const uint8_t* buf, int64_t len, uint32_t subtype,
                    uint8_t* out, int64_t out_cap, int64_t* consumed,
                    int64_t* out_nrec, int64_t* total_nrec) {
  const SubtypeInfo* want = info_of(subtype);
  int64_t off = 0, written = 0, seen = 0;
  *consumed = 0;
  *out_nrec = 0;
  *total_nrec = 0;
  if (want == nullptr) return GYT_BAD_TABLE;

  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;  // partial frame: resume later

    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const SubtypeInfo* si = info_of(ev.subtype);
      if (si != nullptr) {
        if (ev.nevents > si->cap) return GYT_CAP_EXCEEDED;
        const int64_t need =
            HDR_SZ + EV_SZ + static_cast<int64_t>(ev.nevents) * si->itemsize;
        if (need > total) return GYT_NEV_OVERFLOW;
        if (ev.subtype == subtype && ev.nevents > 0) {
          const int64_t nbytes =
              static_cast<int64_t>(ev.nevents) * si->itemsize;
          seen += ev.nevents;
          if (written + nbytes <= out_cap) {
            std::memcpy(out + written, buf + off + HDR_SZ + EV_SZ,
                        static_cast<size_t>(nbytes));
            written += nbytes;
          } else {
            *consumed = off;
            *out_nrec = written / want->itemsize;
            *total_nrec = seen;
            return GYT_OUT_FULL;
          }
        }
      }
      // unknown subtypes skipped (forward compat)
    }
    off += total;
  }
  *consumed = off;
  *out_nrec = written / want->itemsize;
  *total_nrec = seen;
  return GYT_OK;
}

// Count frames + records per subtype without copying (sizing pass).
// counts: array of g_ntypes int64, in gyt_set_table order.
int32_t gyt_scan(const uint8_t* buf, int64_t len, int64_t* counts,
                 int64_t* consumed) {
  int64_t off = 0;
  for (int32_t i = 0; i < g_ntypes; i++) counts[i] = 0;
  *consumed = 0;
  while (off + HDR_SZ <= len) {
    Header h;
    std::memcpy(&h, buf + off, sizeof(h));
    if (h.magic != MAGIC_PM && h.magic != MAGIC_MS && h.magic != MAGIC_NQ)
      return GYT_BAD_MAGIC;
    const int64_t total = static_cast<int64_t>(h.total_sz);
    if (total < HDR_SZ + EV_SZ || total >= MAX_COMM_DATA_SZ)
      return GYT_BAD_TOTAL;
    if (off + total > len) break;
    if (h.data_type == COMM_EVENT_NOTIFY) {
      EventNotify ev;
      std::memcpy(&ev, buf + off + HDR_SZ, sizeof(ev));
      const int32_t idx = index_of(ev.subtype);
      if (idx >= 0) {
        if (ev.nevents > g_table[idx].cap) return GYT_CAP_EXCEEDED;
        const int64_t need = HDR_SZ + EV_SZ +
            static_cast<int64_t>(ev.nevents) * g_table[idx].itemsize;
        if (need > total) return GYT_NEV_OVERFLOW;
        counts[idx] += ev.nevents;
      }
    }
    off += total;
  }
  *consumed = off;
  return GYT_OK;
}

}  // extern "C"
