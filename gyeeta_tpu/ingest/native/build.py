"""Build the native deframer: ``python -m gyeeta_tpu.ingest.native.build``.

One g++ invocation, no external deps (the reference's ingest fast path is
plain C++ over epoll; ours is plain C++ over byte buffers). The shared
object lands next to this file; ``ingest.native`` auto-loads it and falls
back to the pure-Python decoder when absent.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "deframe.cpp"
OUT = HERE / "libgytdeframe.so"


def build(verbose: bool = True) -> pathlib.Path:
    # compile to a unique temp path + atomic rename: concurrent first-use
    # builds (multiple processes) must never load a half-written .so
    tmp = OUT.with_suffix(f".so.tmp{os.getpid()}")
    cxx = os.environ.get("GYT_NATIVE_CXX", "g++")
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC",
           "-Wall", "-Werror", str(SRC), "-o", str(tmp)]
    if verbose:
        print(" ".join(cmd))
    try:
        subprocess.run(cmd, check=True)
        os.replace(tmp, OUT)
    except subprocess.CalledProcessError as e:
        print(f"native build FAILED (rc={e.returncode}): {' '.join(cmd)}",
              file=sys.stderr)
        raise
    finally:
        tmp.unlink(missing_ok=True)
    return OUT


if __name__ == "__main__":
    build()
    print(f"built {OUT}")
    sys.exit(0)
