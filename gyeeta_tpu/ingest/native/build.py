"""Build the native deframer: ``python -m gyeeta_tpu.ingest.native.build``.

One g++ invocation, no external deps (the reference's ingest fast path is
plain C++ over epoll; ours is plain C++ over byte buffers). The shared
object lands next to this file; ``ingest.native`` auto-loads it and falls
back to the pure-Python decoder when absent.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "deframe.cpp"
OUT = HERE / "libgytdeframe.so"


def build(verbose: bool = True) -> pathlib.Path:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-Wall", "-Werror", str(SRC), "-o", str(OUT)]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    build()
    print(f"built {OUT}")
    sys.exit(0)
