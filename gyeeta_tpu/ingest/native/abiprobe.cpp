// ABI compile probe: the minimal extracted header subset of every
// stock gy_comm_proto struct this repo adapts (ingest/refproto.py
// transcribes the ingest half, ingest/refquery.py the query half).
//
// This TU is the C++-compiler side of the proof: abiprobe.py appends a
// generated main() that prints offsetof/sizeof for every field of every
// numpy transcription, compiles the pair with the host toolchain, and
// tests/test_refproto.py asserts the emitted layout equals the numpy
// layout field-for-field. A transcription whose explicit padding
// disagrees with what a real C++ compiler lays out fails loudly; a
// numpy field missing here fails the generated main's compile.
//
// Conventions mirrored from the reference headers (gy_comm_proto.h,
// gy_common_inc.h): little-endian POD structs, natural member
// alignment with EXPLICIT padding members on the wire structs, and
// GY_IP_ADDR carrying the reference's packed+aligned(8) attribute.
// Field names match the numpy transcription 1:1 (the reference's
// trailing-underscore style dropped so the generated emission lines
// need no mapping).

#include <cstdint>
#include <cstddef>

namespace gyt_abi {

// ------------------------------------------------------------ framing
struct COMM_HEADER {            // gy_comm_proto.h:336
  uint32_t magic;
  uint32_t total_sz;
  uint32_t data_type;
  uint32_t padding_sz;
};

struct EVENT_NOTIFY {           // gy_comm_proto.h:486
  uint32_t subtype;
  uint32_t nevents;
};

// ------------------------------------------------------------- address
struct alignas(8) GY_IP_ADDR {  // gy_common_inc.h:10492 (packed,
  uint8_t ip128[16];            // aligned(8): members are naturally
  uint32_t ip32_be;             // packed already)
  int16_t aftype;
  uint16_t ipflags;
};

struct IP_PORT {                // gy_common_inc.h:11162
  uint8_t ip128[16];            // embedded GY_IP_ADDR content
  uint32_t ip32_be;
  int16_t aftype;
  uint16_t ipflags;
  uint16_t port;
  uint8_t pad[6];
};

// ------------------------------------------------------ event notifies
struct TCP_CONN_NOTIFY {        // gy_comm_proto.h:1665
  IP_PORT cli;
  IP_PORT ser;
  IP_PORT nat_cli;
  IP_PORT nat_ser;
  uint64_t tusec_start;
  uint64_t tusec_close;
  uint64_t cli_task_aggr_id;
  uint64_t cli_related_listen_id;
  uint64_t cli_madhava_id;
  uint64_t machid_hi;
  uint64_t machid_lo;
  uint64_t ser_related_listen_id;
  uint64_t ser_glob_id;
  uint64_t ser_madhava_id;
  uint64_t bytes_sent;
  uint64_t bytes_rcvd;
  int32_t cli_pid;
  int32_t ser_pid;
  uint32_t ser_conn_hash;
  uint32_t ser_sock_inode;
  char cli_comm[16];
  char ser_comm[16];
  uint16_t cli_cmdline_len;
  uint8_t is_connect;
  uint8_t is_accept;
  uint8_t is_loopback;
  uint8_t is_pre_existing;
  uint8_t notified_before;
  uint8_t padding_len;
};

struct LISTENER_STATE_NOTIFY {  // gy_comm_proto.h:2183
  uint64_t glob_id;
  uint32_t nqrys_5s;
  uint32_t total_resp_5sec;
  uint32_t nconns;
  uint32_t nconns_active;
  uint32_t ntasks;
  uint32_t p95_5s_resp_ms;
  uint32_t p95_5min_resp_ms;
  uint32_t curr_kbytes_inbound;
  uint32_t curr_kbytes_outbound;
  uint32_t ser_errors;
  uint32_t cli_errors;
  uint32_t tasks_delay_usec;
  uint32_t tasks_cpudelay_usec;
  uint32_t tasks_blkiodelay_usec;
  uint32_t tasks_user_cpu;
  uint32_t tasks_sys_cpu;
  uint32_t tasks_rss_mb;
  uint16_t ntasks_issue;
  uint8_t is_http_svc;
  uint8_t curr_state;
  uint8_t curr_issue;
  uint8_t issue_bit_hist;
  uint8_t high_resp_bit_hist;
  uint8_t last_issue_subsrc;
  uint8_t query_flags;
  uint8_t issue_string_len;
  uint8_t padding_len;
  uint8_t tailpad[1];
};

struct AGGR_TASK_STATE_NOTIFY { // gy_comm_proto.h:2114
  uint64_t aggr_task_id;
  char onecomm[16];
  int32_t pid_arr[2];
  uint32_t tcp_kbytes;
  uint32_t tcp_conns;
  float total_cpu_pct;
  uint32_t rss_mb;
  uint32_t cpu_delay_msec;
  uint32_t vm_delay_msec;
  uint32_t blkio_delay_msec;
  uint16_t ntasks_total;
  uint16_t ntasks_issue;
  uint8_t curr_state;
  uint8_t curr_issue;
  uint8_t issue_bit_hist;
  uint8_t severe_issue_bit_hist;
  uint8_t issue_string_len;
  uint8_t padding_len;
  uint8_t tailpad[2];
};

struct NEW_LISTENER {           // gy_comm_proto.h:1531
  IP_PORT ns_ip_port;           // NS_IP_PORT head (gy_inet_inc.h:105)
  uint64_t inode;               // ... its netns inode tail
  uint64_t glob_id;
  uint64_t aggr_glob_id;
  uint64_t related_listen_id;
  uint64_t tstart_usec;
  uint64_t ser_aggr_task_id;
  uint8_t is_any_ip;
  uint8_t is_pre_existing;
  uint8_t no_aggr_stats;
  uint8_t no_resp_stats;
  char comm[16];
  int32_t start_pid;
  uint16_t cmdline_len;
  uint8_t padding_len;
  uint8_t tailpad[5];
};

struct ACTIVE_CONN_STATS {      // gy_comm_proto.h:2766
  uint64_t listener_glob_id;
  uint64_t cli_aggr_task_id;
  char ser_comm[16];
  char cli_comm[16];
  uint64_t machid_hi;
  uint64_t machid_lo;
  uint64_t remote_madhava_id;
  uint64_t bytes_sent;
  uint64_t bytes_received;
  uint32_t cli_delay_msec;
  uint32_t ser_delay_msec;
  float max_rtt_msec;
  uint16_t active_conns;
  uint8_t connflags;
  uint8_t tailpad[1];
};

struct TASK_TOP_HDR {           // gy_comm_proto.h:1415
  uint16_t nprocs;
  uint16_t npg_procs;
  uint16_t nrss_procs;
  uint16_t nfork_procs;
  uint16_t ext_data_len;
  uint8_t tailpad[6];
};

struct TASK_TOP_PROC {
  uint64_t aggr_task_id;
  int32_t pid;
  int32_t ppid;
  uint32_t rss_mb;
  float cpupct;
  char comm[16];
};

struct TASK_TOP_PG {
  uint64_t aggr_task_id;
  int32_t pg_pid;
  int32_t cpid;
  int32_t ntasks;
  uint32_t tot_rss_mb;
  float tot_cpupct;
  char pg_comm[16];
  char child_comm[16];
  uint8_t tailpad[4];
};

struct TASK_TOP_FORK {
  uint64_t aggr_task_id;
  int32_t pid;
  int32_t ppid;
  int32_t nfork_per_sec;
  char comm[16];
  uint8_t tailpad[4];
};

struct TASK_AGGR_NOTIFY {       // gy_comm_proto.h:1290
  uint64_t aggr_task_id;
  uint64_t related_listen_id;
  char comm[16];
  uint32_t uid;
  uint32_t gid;
  uint16_t cmdline_len;
  uint8_t tag_len;
  uint8_t procflags;
  uint8_t padding_len;
  uint8_t tailpad[3];
};

struct PING_TASK_AGGR {         // gy_comm_proto.h:1384
  uint64_t aggr_task_id;
};

struct PARTHA_STATUS {          // gy_comm_proto.h:1399
  uint8_t is_ok;
  uint8_t pad0[7];
  int64_t curr_sec;
  int64_t clock_sec;
};

struct CPU_MEM_STATE_NOTIFY {   // gy_comm_proto.h:2024
  float cpu_pct;
  float usercpu_pct;
  float syscpu_pct;
  float iowait_pct;
  float cumul_core_cpu_pct;
  uint32_t forks_sec;
  uint32_t procs_running;
  uint32_t cs_sec;
  uint32_t cs_p95_sec;
  uint32_t cs_5min_p95_sec;
  uint32_t cpu_p95;
  uint32_t cpu_5min_p95;
  uint32_t fork_p95_sec;
  uint32_t fork_5min_p95_sec;
  uint32_t procs_p95;
  uint32_t procs_5min_p95;
  uint8_t cpu_state;
  uint8_t cpu_issue;
  uint8_t cpu_issue_bit_hist;
  uint8_t cpu_severe_issue_hist;
  uint8_t cpu_state_string_len;
  uint8_t pad0[3];
  float rss_pct;
  uint8_t pad1[4];
  uint64_t rss_memory_mb;
  uint64_t total_memory_mb;
  uint64_t cached_memory_mb;
  uint64_t locked_memory_mb;
  uint64_t committed_memory_mb;
  float committed_pct;
  uint8_t pad2[4];
  uint64_t swap_free_mb;
  uint64_t swap_total_mb;
  uint32_t pg_inout_sec;
  uint32_t swap_inout_sec;
  uint32_t reclaim_stalls;
  uint32_t pgmajfault;
  uint32_t oom_kill;
  uint32_t rss_pct_p95;
  uint64_t pginout_p95;
  uint64_t swpinout_p95;
  uint64_t allocstall_p95;
  uint8_t mem_state;
  uint8_t mem_issue;
  uint8_t mem_issue_bit_hist;
  uint8_t mem_severe_issue_hist;
  uint8_t mem_state_string_len;
  uint8_t padding_len;
  uint8_t tailpad[2];
};

struct HOST_STATE_NOTIFY {      // gy_comm_proto.h:2289
  uint64_t curr_time_usec;
  uint32_t ntasks_issue;
  uint32_t ntasks_severe;
  uint32_t ntasks;
  uint32_t nlisten_issue;
  uint32_t nlisten_severe;
  uint32_t nlisten;
  uint8_t curr_state;
  uint8_t issue_bit_hist;
  uint8_t cpu_issue;
  uint8_t mem_issue;
  uint8_t severe_cpu_issue;
  uint8_t severe_mem_issue;
  uint8_t pad0[2];
  uint32_t total_cpu_delayms;
  uint32_t total_vm_delayms;
  uint32_t total_io_delayms;
  uint8_t tailpad[4];
};

struct HOST_INFO_NOTIFY {       // gy_comm_proto.h:2844
  char distribution_name[128];
  char kern_version_string[64];
  uint32_t kern_version_num;
  char instance_id[128];
  char cloud_type[64];
  char processor_model[128];
  char cpu_vendor[64];
  uint16_t cores_online;
  uint16_t cores_offline;
  uint16_t max_cores;
  uint16_t isolated_cores;
  uint32_t ram_mb;
  uint32_t corrupted_ram_mb;
  uint16_t num_numa_nodes;
  uint16_t max_cores_per_socket;
  uint16_t threads_per_core;
  uint8_t pad0[6];
  int64_t boot_time_sec;
  uint32_t l1_dcache_kb;
  uint32_t l2_cache_kb;
  uint32_t l3_cache_kb;
  uint32_t l4_cache_kb;
  uint8_t is_virtual_cpu;
  char virtualization_type[64];
  uint8_t tailpad[7];
};

struct NAT_TCP_NOTIFY {         // gy_comm_proto.h:1744
  IP_PORT orig_cli;
  IP_PORT orig_ser;
  IP_PORT nat_cli;
  IP_PORT nat_ser;
  uint8_t is_snat;
  uint8_t is_dnat;
  uint8_t is_ipvs;
  uint8_t tailpad[5];
};

struct API_TRAN {               // gy_proto_common.h:140
  uint64_t treq_usec;
  uint64_t tres_usec;
  uint64_t tupd_usec;
  uint64_t reqlen;
  uint64_t reslen;
  uint64_t reqnum;
  uint64_t response_usec;
  uint64_t reaction_usec;
  uint64_t tconnect_usec;
  GY_IP_ADDR cliip;
  GY_IP_ADDR serip;
  uint64_t glob_id;
  uint64_t conn_id;
  char comm[16];
  int32_t errorcode;
  uint32_t app_sleep_ms;
  uint32_t tran_type;
  uint16_t proto;
  uint16_t cliport;
  uint16_t serport;
  uint16_t request_len;
  uint16_t lenext;
  uint8_t padlen;
  uint8_t tailpad[1];
};

struct HOST_CPU_MEM_CHANGE {    // gy_comm_proto.h:2886
  uint8_t cpu_changed;
  uint8_t pad0;
  uint16_t new_cores_online;
  uint16_t new_cores_offline;
  uint16_t old_cores_online;
  uint16_t old_cores_offline;
  uint8_t mem_changed;
  uint8_t pad1;
  uint32_t new_ram_mb;
  uint32_t old_ram_mb;
  uint8_t mem_corrupt_changed;
  uint8_t pad2[3];
  uint32_t new_corrupted_ram_mb;
  uint32_t old_corrupted_ram_mb;
};

struct NOTIFICATION_MSG {       // gy_comm_proto.h:2913
  uint8_t type;
  uint8_t pad0;
  uint16_t msglen;
  uint8_t padding_len;
  uint8_t tailpad[3];
};

struct LISTENER_DOMAIN_NOTIFY { // gy_comm_proto.h:2724
  uint64_t glob_id;
  uint8_t domain_string_len;
  uint8_t tag_len;
  uint8_t padding_len;
  uint8_t tailpad[5];
};

struct LISTEN_TASKMAP_NOTIFY {  // gy_comm_proto.h:2813
  uint64_t related_listen_id;
  char ser_comm[16];
  uint16_t nlisten;
  uint16_t naggr_taskid;
  uint8_t tailpad[4];
};

// --------------------------------------------------------- handshakes
struct PS_REGISTER_REQ_S {      // gy_comm_proto.h:584
  uint32_t comm_version;
  uint32_t partha_version;
  uint32_t min_shyama_version;
  uint8_t pad0[4];
  uint64_t machine_id_hi;
  uint64_t machine_id_lo;
  char hostname[256];
  char write_access_key[64];
  char cluster_name[64];
  char region_name[64];
  char zone_name[64];
  uint32_t kern_version_num;
  uint8_t pad1[4];
  int64_t curr_sec;
  int64_t last_mdisconn_sec;
  uint64_t last_madhava_id;
  uint64_t flags;
  uint8_t extra_bytes[512];
};

struct PS_REGISTER_RESP_S {     // gy_comm_proto.h:616
  int32_t error_code;
  char error_string[256];
  uint32_t comm_version;
  uint32_t shyama_version;
  uint8_t pad0[4];
  uint64_t shyama_id;
  uint64_t flags;
  uint64_t partha_ident_key;
  int64_t madhava_expiry_sec;
  uint64_t madhava_id;
  uint16_t madhava_port;
  char madhava_hostname[256];
  char madhava_name[64];
  uint8_t extra_bytes[800];
  uint8_t tailpad[6];
};

struct PM_CONNECT_CMD_S {       // gy_comm_proto.h:648
  uint32_t comm_version;
  uint32_t partha_version;
  uint32_t min_madhava_version;
  uint8_t pad0[4];
  uint64_t machine_id_hi;
  uint64_t machine_id_lo;
  uint64_t partha_ident_key;
  char hostname[256];
  char write_access_key[64];
  char cluster_name[64];
  char region_name[64];
  char zone_name[64];
  uint64_t madhava_id;
  uint32_t cli_type;
  uint32_t kern_version_num;
  int64_t curr_sec;
  int64_t clock_sec;
  int64_t process_uptime_sec;
  int64_t last_connect_sec;
  uint64_t flags;
  uint8_t extra_bytes[512];
};

struct PM_CONNECT_RESP_S {      // gy_comm_proto.h:691
  int32_t error_code;
  char error_string[256];
  uint8_t pad0[4];
  uint64_t madhava_id;
  uint32_t comm_version;
  uint32_t madhava_version;
  char region_name[64];
  char zone_name[64];
  char madhava_name[64];
  int64_t curr_sec;
  uint64_t clock_sec;
  uint64_t flags;
  uint8_t extra_bytes[512];
};

// ------------------------------------------------- node (NM) query edge
struct NM_CONNECT_CMD_S {       // gy_comm_proto.h:887
  uint32_t comm_version;
  uint32_t node_version;
  uint32_t min_madhava_version;
  uint8_t pad0[4];
  char node_hostname[256];
  uint32_t node_port;
  uint32_t cli_type;
  int64_t curr_sec;
  int64_t clock_sec;
  uint64_t flags;
  uint8_t extra_bytes[512];
};

struct NM_CONNECT_RESP_S {      // gy_comm_proto.h:923
  int32_t error_code;
  char error_string[256];
  uint8_t pad0[4];
  uint64_t madhava_id;
  uint32_t comm_version;
  uint32_t madhava_version;
  char madhava_name[64];
  int64_t curr_sec;
  uint64_t clock_sec;
  uint64_t flags;
  uint8_t extra_bytes[512];
};

struct QUERY_CMD_S {            // gy_comm_proto.h:502
  uint64_t seqid;
  uint64_t timeoutusec;
  uint32_t subtype;
  uint32_t respformat;
};

struct QUERY_RESPONSE_S {       // gy_comm_proto.h:536
  uint64_t seqid;
  uint32_t resptype;
  uint32_t respformat;
  uint32_t resp_len;
  uint32_t is_completed;
};

}  // namespace gyt_abi
