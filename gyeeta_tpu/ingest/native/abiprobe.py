"""ABI compile probe: prove the numpy struct transcriptions against a
real C++ compiler's layout of the extracted header subset.

``refproto.py``/``refquery.py`` transcribe the stock gy_comm_proto
structs as explicit numpy dtypes with hand-placed padding. This module
turns that transcription into proof: ``abiprobe.cpp`` carries the same
structs as plain C++ (natural member alignment, the reference's
explicit-padding/alignas conventions); a GENERATED main() — one
``offsetof``/``sizeof`` emission line per numpy field, derived from the
dtypes themselves — is appended, compiled with the host toolchain (the
same one that builds ``libgytdeframe.so``) and run. The emitted layout
must equal the numpy layout field-for-field:

- a numpy field missing from the C++ struct fails the compile;
- wrong explicit padding / misordered fields fail the offset compare;
- a size drift fails the sizeof compare.

``tests/test_refproto.py`` asserts the full comparison; hosts without a
C++ toolchain skip WITH A LOGGED REASON (never silently).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import tempfile

import numpy as np

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "abiprobe.cpp"


def probed_structs() -> dict:
    """C++ struct name → numpy dtype, for EVERY adapted stock struct
    (ingest half from refproto, query half from refquery). A dtype
    added to either module must be registered here — the coverage test
    walks this table."""
    from gyeeta_tpu.ingest import refproto as RP
    from gyeeta_tpu.ingest import refquery as RQ

    return {
        "COMM_HEADER": RP.REF_HEADER_DT,
        "EVENT_NOTIFY": RP.REF_EVENT_NOTIFY_DT,
        "GY_IP_ADDR": RP.REF_GY_IP_ADDR_DT,
        "IP_PORT": RP.REF_IP_PORT_DT,
        "TCP_CONN_NOTIFY": RP.REF_TCP_CONN_DT,
        "LISTENER_STATE_NOTIFY": RP.REF_LISTENER_STATE_DT,
        "AGGR_TASK_STATE_NOTIFY": RP.REF_AGGR_TASK_DT,
        "NEW_LISTENER": RP.REF_NEW_LISTENER_DT,
        "ACTIVE_CONN_STATS": RP.REF_ACTIVE_CONN_DT,
        "TASK_TOP_HDR": RP.REF_TOP_HDR_DT,
        "TASK_TOP_PROC": RP.REF_TOP_TASK_DT,
        "TASK_TOP_PG": RP.REF_TOP_PG_DT,
        "TASK_TOP_FORK": RP.REF_TOP_FORK_DT,
        "TASK_AGGR_NOTIFY": RP.REF_TASK_AGGR_DT,
        "PING_TASK_AGGR": RP.REF_PING_TASK_AGGR_DT,
        "PARTHA_STATUS": RP.REF_PARTHA_STATUS_DT,
        "CPU_MEM_STATE_NOTIFY": RP.REF_CPU_MEM_DT,
        "HOST_STATE_NOTIFY": RP.REF_HOST_STATE_DT,
        "HOST_INFO_NOTIFY": RP.REF_HOST_INFO_DT,
        "NAT_TCP_NOTIFY": RP.REF_NAT_TCP_DT,
        "API_TRAN": RP.REF_API_TRAN_DT,
        "HOST_CPU_MEM_CHANGE": RP.REF_CPU_MEM_CHANGE_DT,
        "NOTIFICATION_MSG": RP.REF_NOTIFICATION_MSG_DT,
        "LISTENER_DOMAIN_NOTIFY": RP.REF_LISTENER_DOMAIN_DT,
        "LISTEN_TASKMAP_NOTIFY": RP.REF_LISTEN_TASKMAP_DT,
        "PS_REGISTER_REQ_S": RP.REF_PS_REGISTER_REQ_DT,
        "PS_REGISTER_RESP_S": RP.REF_PS_REGISTER_RESP_DT,
        "PM_CONNECT_CMD_S": RP.REF_PM_CONNECT_CMD_DT,
        "PM_CONNECT_RESP_S": RP.REF_PM_CONNECT_RESP_DT,
        "NM_CONNECT_CMD_S": RQ.REF_NM_CONNECT_CMD_DT,
        "NM_CONNECT_RESP_S": RQ.REF_NM_CONNECT_RESP_DT,
        "QUERY_CMD_S": RQ.REF_QUERY_CMD_DT,
        "QUERY_RESPONSE_S": RQ.REF_QUERY_RESPONSE_DT,
    }


def numpy_layout(dt: np.dtype) -> dict:
    """dtype → {"__sizeof__": itemsize, field: (offset, size)}."""
    out = {"__sizeof__": dt.itemsize}
    for name in dt.names:
        sub, off = dt.fields[name][:2]
        out[name] = (off, sub.itemsize)
    return out


def _gen_main(structs: dict) -> str:
    """The generated TU: include the header subset + emit one line per
    numpy field. ``sizeof`` of a member via the null-deref idiom so
    array members report their full extent."""
    lines = [
        '#include <cstdio>',
        f'#include "{SRC}"',
        'using namespace gyt_abi;',
        '#define P(S, f) std::printf("%s %s %zu %zu\\n", #S, #f, '
        'offsetof(S, f), sizeof ((S*)0)->f)',
        '#define SZ(S) std::printf("%s __sizeof__ %zu %zu\\n", #S, '
        'sizeof(S), alignof(S))',
        'int main() {',
    ]
    for sname, dt in structs.items():
        lines.append(f'  SZ({sname});')
        for field in dt.names:
            lines.append(f'  P({sname}, {field});')
    lines += ['  return 0;', '}', '']
    return "\n".join(lines)


def toolchain() -> str | None:
    import shutil
    cxx = os.environ.get("GYT_NATIVE_CXX", "g++")
    return cxx if shutil.which(cxx) else None


def run_probe(structs: dict | None = None) -> dict | None:
    """Compile + run the probe → {struct: {"__sizeof__": n, field:
    (offset, size)}} as the C++ COMPILER lays it out, or None when the
    host has no toolchain (callers log the skip reason)."""
    if structs is None:
        structs = probed_structs()
    cxx = toolchain()
    if cxx is None:
        return None
    with tempfile.TemporaryDirectory(prefix="gyt_abiprobe") as td:
        main_cpp = pathlib.Path(td) / "abiprobe_main.cpp"
        exe = pathlib.Path(td) / "abiprobe"
        main_cpp.write_text(_gen_main(structs))
        subprocess.run(
            [cxx, "-O0", "-std=c++17", "-Wall", "-Werror",
             str(main_cpp), "-o", str(exe)],
            check=True, capture_output=True, text=True)
        txt = subprocess.run([str(exe)], check=True,
                             capture_output=True, text=True).stdout
    out: dict = {}
    for ln in txt.splitlines():
        sname, field, a, b = ln.split()
        if field == "__sizeof__":
            out.setdefault(sname, {})["__sizeof__"] = int(a)
        else:
            out.setdefault(sname, {})[field] = (int(a), int(b))
    return out


def compare(cxx_layout: dict, structs: dict | None = None) -> list:
    """C++ layout vs numpy layout → list of mismatch strings (empty =
    every adapted struct is byte-compatible with the compiler)."""
    if structs is None:
        structs = probed_structs()
    bad = []
    for sname, dt in structs.items():
        got = cxx_layout.get(sname)
        if got is None:
            bad.append(f"{sname}: missing from probe output")
            continue
        want = numpy_layout(dt)
        if got["__sizeof__"] != want["__sizeof__"]:
            bad.append(f"{sname}: sizeof {got['__sizeof__']} != "
                       f"numpy itemsize {want['__sizeof__']}")
        for field in dt.names:
            g = got.get(field)
            if g is None:
                bad.append(f"{sname}.{field}: not emitted")
            elif g != want[field]:
                bad.append(
                    f"{sname}.{field}: C++ (off={g[0]}, sz={g[1]}) != "
                    f"numpy (off={want[field][0]}, sz={want[field][1]})")
    return bad


def main() -> int:
    import sys
    layout = run_probe()
    if layout is None:
        print("abiprobe: SKIP — no C++ toolchain on this host",
              file=sys.stderr)
        return 0
    bad = compare(layout)
    ns = len(probed_structs())
    if bad:
        print(f"abiprobe: {len(bad)} mismatch(es) across {ns} structs:",
              file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return 1
    nf = sum(len(dt.names) for dt in probed_structs().values())
    print(f"abiprobe: OK — {ns} structs / {nf} fields byte-compatible",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
