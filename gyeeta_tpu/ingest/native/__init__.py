"""ctypes loader for the native deframer, with pure-Python fallback.

``drain(buf)`` is the L1 ingest entry point: one pass over a byte stream →
{subtype: contiguous record array} + bytes consumed. Uses the C++ fast
path (built lazily on first use when g++ is available), else
``wire.decode_frames``.

The subtype table is pushed INTO the library from ``wire.DTYPE_OF_SUBTYPE``
at load time (``gyt_set_table``) and echoed back (``gyt_layout``) — the
native path structurally cannot drift from wire.py the way a compiled-in
table could.

Beyond deframing, this module is the host half of the **wire→columnar
compiler**: ``decode_conn_into``/``decode_resp_into`` and the generic
``split_u64_into``/``pack_f32_into``/``pack_i32_into`` kernels decode raw
record arrays straight into caller-provided preallocated NumPy column
buffers at a lane offset (zero-copy, GIL released for the whole pass).
Column plans (field offset + scalar kind) are compiled HERE from the
wire.py dtypes and executed in C++ — ``ingest/decode.py`` keeps the
bit-identical NumPy reference implementations as the fallback.

Setting ``GYT_PY_INGEST=1`` forces the pure-Python path everywhere (a
``GYT_BENCH_ABLATE``-style debug knob; see OPERATIONS.md) — checked on
every load so tests can toggle it per-process.
"""

from __future__ import annotations

import ctypes
import os
import pathlib

import numpy as np

from gyeeta_tpu.ingest import wire

_SO = pathlib.Path(__file__).resolve().parent / "libgytdeframe.so"
_SRC = pathlib.Path(__file__).resolve().parent / "deframe.cpp"
_lib = None
_load_failed = False

_ERRNAMES = {1: "bad magic", 2: "bad total_sz", 3: "batch cap exceeded",
             4: "nevents does not fill frame", 5: "output buffer full",
             6: "bad subtype table",
             7: "unexpected data_type on event stream",
             8: "payload checksum mismatch"}
# rc → FrameError.reason (the frames_rejected|reason=... label values;
# identical to the labels the pure-Python decoder raises with)
_ERRREASON = {1: "bad_magic", 2: "bad_size", 3: "bad_size",
              4: "bad_size", 6: "bad_frame", 7: "bad_dtype",
              8: "checksum"}

# drain() output ordering; derived from wire.py, never hand-maintained
_SCAN_ORDER = tuple(sorted(wire.DTYPE_OF_SUBTYPE))

# scalar kind codes of the C++ pack kernels (deframe.cpp PackKind)
_KIND = {("u", 1): 1, ("u", 2): 2, ("u", 4): 3, ("u", 8): 4,
         ("i", 4): 5, ("f", 4): 6}


def _forced_python() -> bool:
    return os.environ.get("GYT_PY_INGEST", "") not in ("", "0")


def _ensure_built() -> bool:
    """Build (or rebuild, if deframe.cpp is newer) the shared object."""
    try:
        if _SO.exists() and (not _SRC.exists()
                             or _SO.stat().st_mtime >= _SRC.stat().st_mtime):
            return True
        from gyeeta_tpu.ingest.native import build
        build.build(verbose=False)
        return True
    except Exception:
        return _SO.exists()


def _load():
    global _lib, _load_failed
    if _forced_python():
        return None
    if _lib is not None or _load_failed:
        return _lib
    if not _ensure_built():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
        return _bind_and_handshake(lib)
    except Exception:
        # unloadable or stale .so (e.g. missing gyt_set_table symbol):
        # fall back to the pure-Python decoder permanently
        _load_failed = True
        return None


def _bind_and_handshake(lib):
    global _lib
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.gyt_set_table.restype = ctypes.c_int32
    lib.gyt_set_table.argtypes = [i64p, ctypes.c_int32]
    lib.gyt_extract.restype = ctypes.c_int32
    lib.gyt_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_int64, i64p, i64p, i64p]
    lib.gyt_extract_multi.restype = ctypes.c_int32
    lib.gyt_extract_multi.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p), i64p, i64p, i64p]
    lib.gyt_scan.restype = ctypes.c_int32
    lib.gyt_scan.argtypes = [ctypes.c_char_p, ctypes.c_int64, i64p, i64p]
    # sizing scan that also counts records in skipped unknown-subtype
    # frames (chaos-tier loss accounting); a .so predating the symbol
    # fails the bind here and the loader falls back to pure Python
    lib.gyt_scan2.restype = ctypes.c_int32
    lib.gyt_scan2.argtypes = [ctypes.c_char_p, ctypes.c_int64, i64p,
                              i64p, i64p]
    lib.gyt_layout.restype = ctypes.c_int32
    lib.gyt_layout.argtypes = [i64p, ctypes.c_int64]
    # push the subtype table from wire.py (single source of truth) ...
    n = len(_SCAN_ORDER)
    tri = (ctypes.c_int64 * (3 * n))()
    for i, st in enumerate(_SCAN_ORDER):
        tri[i * 3 + 0] = st
        tri[i * 3 + 1] = wire.DTYPE_OF_SUBTYPE[st].itemsize
        tri[i * 3 + 2] = wire.MAX_OF_SUBTYPE[st]
    rc = lib.gyt_set_table(tri, n)
    if rc != 0:
        raise RuntimeError(f"gyt_set_table: {_ERRNAMES.get(rc, rc)}")
    # ... and verify the round-trip covers every subtype
    back = (ctypes.c_int64 * (3 * n))()
    got = lib.gyt_layout(back, n)
    native = {int(back[i * 3]): (int(back[i * 3 + 1]), int(back[i * 3 + 2]))
              for i in range(got)}
    expect = {st: (wire.DTYPE_OF_SUBTYPE[st].itemsize,
                   wire.MAX_OF_SUBTYPE[st]) for st in _SCAN_ORDER}
    if native != expect:
        raise RuntimeError(
            f"native deframer layout mismatch: {native} != {expect}")
    # columnar conn-decode layout push (same single-source discipline)
    lib.gyt_set_conn_layout.restype = ctypes.c_int32
    lib.gyt_set_conn_layout.argtypes = [i64p, ctypes.c_int32]
    lib.gyt_decode_conn.restype = ctypes.c_int32
    lib.gyt_decode_conn.argtypes = [ctypes.c_void_p, ctypes.c_int64] + \
        [ctypes.c_void_p] * 16
    dt = wire.TCP_CONN_DT
    off = {name: dt.fields[name][1] for name in dt.names}
    fields = [dt.itemsize,
              off["cli"], off["ser"], off["nat_cli"], off["nat_ser"],
              off["tusec_start"], off["tusec_close"],
              off["cli_task_aggr_id"], off["cli_related_listen_id"],
              off["ser_glob_id"], off["bytes_sent"], off["bytes_rcvd"],
              off["host_id"], off["flags"],
              wire.IP_PORT_DT.fields["port"][1]]
    arr = (ctypes.c_int64 * len(fields))(*fields)
    rc = lib.gyt_set_conn_layout(arr, len(fields))
    if rc != 0:
        raise RuntimeError(f"gyt_set_conn_layout: "
                           f"{_ERRNAMES.get(rc, rc)}")
    # resp-decode layout push (wire.RESP_SAMPLE_DT)
    lib.gyt_set_resp_layout.restype = ctypes.c_int32
    lib.gyt_set_resp_layout.argtypes = [i64p, ctypes.c_int32]
    lib.gyt_decode_resp.restype = ctypes.c_int32
    lib.gyt_decode_resp.argtypes = [ctypes.c_void_p, ctypes.c_int64] + \
        [ctypes.c_void_p] * 4
    rdt = wire.RESP_SAMPLE_DT
    rfields = [rdt.itemsize, rdt.fields["glob_id"][1],
               rdt.fields["resp_usec"][1], rdt.fields["host_id"][1]]
    rarr = (ctypes.c_int64 * len(rfields))(*rfields)
    rc = lib.gyt_set_resp_layout(rarr, len(rfields))
    if rc != 0:
        raise RuntimeError(f"gyt_set_resp_layout: "
                           f"{_ERRNAMES.get(rc, rc)}")
    # generic pack kernels (column plans ride along each call)
    lib.gyt_pack_f32.restype = ctypes.c_int32
    lib.gyt_pack_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, i64p,
        ctypes.c_int32, ctypes.c_void_p]
    lib.gyt_split_u64.restype = ctypes.c_int32
    lib.gyt_split_u64.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.gyt_pack_i32.restype = ctypes.c_int32
    lib.gyt_pack_i32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p]
    _lib = lib
    return _lib


def _check(rc: int, what: str) -> None:
    if rc != 0:
        raise RuntimeError(f"{what}: {_ERRNAMES.get(rc, rc)}")


def _ptr(a, off: int = 0):
    """ctypes pointer to lane ``off`` of a contiguous 1-D/2-D array."""
    v = a[off:] if off else a
    return v.ctypes.data_as(ctypes.c_void_p)


def _recs_ptr(recs: np.ndarray):
    recs = np.ascontiguousarray(recs)
    # keep a reference alive for the duration of the call site
    return recs, recs.ctypes.data_as(ctypes.c_void_p)


# column plans: (dtype, fields) → compiled (src_off, kind) int64 array.
# Compiled once per subtype from the wire.py dtype — the "compiler" half
# of the wire→columnar path; deframe.cpp's kernels are the executor.
_PLANS: dict = {}


def _plan(dt: np.dtype, fields: tuple):
    key = (dt, fields)
    ops = _PLANS.get(key)
    if ops is None:
        vals = []
        for f in fields:
            fdt, foff = dt.fields[f][0], dt.fields[f][1]
            vals += [foff, _KIND[(fdt.kind, fdt.itemsize)]]
        ops = (ctypes.c_int64 * len(vals))(*vals)
        _PLANS[key] = ops
    return ops


def available() -> bool:
    return _load() is not None


# ------------------------------------------------------ columnar kernels
def decode_conn_into(recs: np.ndarray, cols: dict, off: int = 0) -> bool:
    """Decode TCP_CONN records into flat column arrays at lane ``off``
    (cols: the 16 non-valid ConnBatch columns, each contiguous and of
    length >= off+len(recs)). Returns False when the native library is
    unavailable — callers fall back to decode.conn_batch."""
    lib = _load()
    if lib is None:
        return False
    if recs.dtype != wire.TCP_CONN_DT:
        raise TypeError(f"decode_conn_into needs TCP_CONN_DT records, "
                        f"got {recs.dtype}")  # C++ walks layout offsets
    recs, rp = _recs_ptr(recs)
    _check(lib.gyt_decode_conn(
        rp, len(recs),
        _ptr(cols["svc_hi"], off), _ptr(cols["svc_lo"], off),
        _ptr(cols["flow_hi"], off), _ptr(cols["flow_lo"], off),
        _ptr(cols["cli_hi"], off), _ptr(cols["cli_lo"], off),
        _ptr(cols["cli_task_hi"], off), _ptr(cols["cli_task_lo"], off),
        _ptr(cols["cli_rel_hi"], off), _ptr(cols["cli_rel_lo"], off),
        _ptr(cols["bytes_sent"], off), _ptr(cols["bytes_rcvd"], off),
        _ptr(cols["duration_us"], off), _ptr(cols["host_id"], off),
        _ptr(cols["is_close"], off), _ptr(cols["is_accept"], off)),
        "gyt_decode_conn")
    return True


def decode_resp_into(recs: np.ndarray, svc_hi, svc_lo, resp_us, host_id,
                     off: int = 0) -> bool:
    """Decode RESP_SAMPLE records into flat columns at lane ``off``
    (bit-identical to decode.resp_batch's numpy math)."""
    lib = _load()
    if lib is None:
        return False
    if recs.dtype != wire.RESP_SAMPLE_DT:
        raise TypeError(f"decode_resp_into needs RESP_SAMPLE_DT records, "
                        f"got {recs.dtype}")
    recs, rp = _recs_ptr(recs)
    _check(lib.gyt_decode_resp(
        rp, len(recs), _ptr(svc_hi, off), _ptr(svc_lo, off),
        _ptr(resp_us, off), _ptr(host_id, off)), "gyt_decode_resp")
    return True


def split_u64_into(recs: np.ndarray, field: str, hi, lo,
                   off: int = 0) -> bool:
    """One u64 record field → (hi, lo) uint32 columns at lane ``off``."""
    lib = _load()
    if lib is None:
        return False
    recs, rp = _recs_ptr(recs)
    _check(lib.gyt_split_u64(
        rp, len(recs), recs.dtype.itemsize, recs.dtype.fields[field][1],
        _ptr(hi, off), _ptr(lo, off)), "gyt_split_u64")
    return True


def pack_f32_into(recs: np.ndarray, fields: tuple, out: np.ndarray,
                  off: int = 0) -> bool:
    """Record fields → float32 matrix rows [off:off+n) of ``out``
    (shape (size, len(fields)), C-contiguous)."""
    lib = _load()
    if lib is None:
        return False
    if not out.flags.c_contiguous or out.dtype != np.float32 \
            or out.shape[1] != len(fields):
        raise ValueError(f"pack_f32_into needs a C-contiguous float32 "
                         f"(size, {len(fields)}) output, got "
                         f"{out.dtype}{out.shape}")
    recs, rp = _recs_ptr(recs)
    _check(lib.gyt_pack_f32(
        rp, len(recs), recs.dtype.itemsize, _plan(recs.dtype, fields),
        len(fields), _ptr(out, off)), "gyt_pack_f32")
    return True


def pack_i32_into(recs: np.ndarray, field: str, out, off: int = 0) -> bool:
    """One scalar record field → int32 column at lane ``off``."""
    lib = _load()
    if lib is None:
        return False
    fdt = recs.dtype.fields[field][0]
    recs, rp = _recs_ptr(recs)
    _check(lib.gyt_pack_i32(
        rp, len(recs), recs.dtype.itemsize, recs.dtype.fields[field][1],
        _KIND[(fdt.kind, fdt.itemsize)], _ptr(out, off)), "gyt_pack_i32")
    return True


def decode_conn(recs, size: int):
    """Native columnar TCP_CONN decode → ConnBatch (or None when the
    native library is unavailable — callers fall back to
    decode.conn_batch). Semantics bit-identical to the Python decoder;
    tests/test_native_ingest.py diffs them on random records."""
    if _load() is None:
        return None
    from gyeeta_tpu.ingest import decode as D

    if len(recs) > size:
        raise ValueError(f"{len(recs)} records exceed batch size {size};"
                         f" split upstream")
    cols = D.alloc_conn_cols(size)
    decode_conn_into(recs, cols, 0)
    valid = np.zeros(size, bool)
    valid[:len(recs)] = True
    return D.ConnBatch(valid=valid, **cols)


def drain(buf: bytes) -> tuple[dict, int]:
    """byte stream → ({subtype: structured record array}, consumed).
    Thin wrapper over :func:`drain2` for callers that don't need the
    unknown-subtype record count."""
    out, consumed, _unknown = drain2(buf)
    return out, consumed


def drain2(buf: bytes) -> tuple[dict, int, int]:
    """byte stream → ({subtype: record array}, consumed, unknown_recs).

    Native path when built; identical semantics to the Python decoder
    (validation errors raise wire.FrameError either way). Two passes
    total: one sizing scan, then ONE frame walk that appends every
    subtype's records into its preallocated array (gyt_extract_multi).
    ``unknown_recs`` counts records claimed by skipped unknown-subtype
    frames — the feed path attributes them to a counter so a corrupted
    subtype byte is accounted loss, never silent loss.
    """
    lib = _load()
    if lib is None:
        return _drain_py2(buf)
    n = len(_SCAN_ORDER)
    counts = (ctypes.c_int64 * n)()
    consumed = ctypes.c_int64()
    unknown = ctypes.c_int64()
    rc = lib.gyt_scan2(buf, len(buf), counts, ctypes.byref(consumed),
                       ctypes.byref(unknown))
    if rc != 0:
        raise wire.FrameError(f"native scan: {_ERRNAMES.get(rc, rc)}",
                              reason=_ERRREASON.get(rc, "bad_frame"))
    out: dict = {}
    outs = (ctypes.c_void_p * n)()
    caps = (ctypes.c_int64 * n)()
    nrec = (ctypes.c_int64 * n)()
    nonempty = False
    for i, subtype in enumerate(_SCAN_ORDER):
        if counts[i] == 0:
            continue
        rec = np.empty(counts[i], wire.DTYPE_OF_SUBTYPE[subtype])
        out[subtype] = rec
        outs[i] = rec.ctypes.data
        caps[i] = rec.nbytes
        nonempty = True
    if not nonempty:
        return out, int(consumed.value), int(unknown.value)
    c2 = ctypes.c_int64()
    rc = lib.gyt_extract_multi(buf, len(buf), outs, caps, nrec,
                               ctypes.byref(c2))
    if rc != 0:
        raise wire.FrameError(f"native extract: {_ERRNAMES.get(rc, rc)}",
                              reason=_ERRREASON.get(rc, "bad_frame"))
    for i, subtype in enumerate(_SCAN_ORDER):
        if counts[i]:
            assert nrec[i] == counts[i], (subtype, nrec[i], counts[i])
    return out, int(consumed.value), int(unknown.value)


def _drain_py(buf: bytes) -> tuple[dict, int]:
    out, consumed, _unknown = _drain_py2(buf)
    return out, consumed


def _drain_py2(buf: bytes) -> tuple[dict, int, int]:
    cnt: dict = {}
    frames, consumed = wire.decode_frames(buf, counts=cnt,
                                          event_only=True)
    out: dict = {}
    for subtype, recs in frames:
        if not len(recs):
            continue     # drain contract: no empty entries (native parity)
        if subtype in out:
            out[subtype] = np.concatenate([out[subtype], recs])
        else:
            out[subtype] = recs.copy()
    return out, consumed, cnt.get("unknown_records", 0)
