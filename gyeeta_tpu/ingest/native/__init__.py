"""ctypes loader for the native deframer, with pure-Python fallback.

``drain(buf)`` is the L1 ingest entry point: one pass over a byte stream →
{subtype: contiguous record array} + bytes consumed. Uses the C++ fast
path when ``libgytdeframe.so`` is built (``python -m
gyeeta_tpu.ingest.native.build``), else ``wire.decode_frames``.
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

from gyeeta_tpu.ingest import wire

_SO = pathlib.Path(__file__).resolve().parent / "libgytdeframe.so"
_lib = None

_ERRNAMES = {1: "bad magic", 2: "bad total_sz", 3: "batch cap exceeded",
             4: "nevents overflows frame", 5: "output buffer full"}

# order must match kSubtypes in deframe.cpp
_SCAN_ORDER = (wire.NOTIFY_TCP_CONN, wire.NOTIFY_LISTENER_STATE,
               wire.NOTIFY_HOST_STATE, wire.NOTIFY_RESP_SAMPLE)


def _load():
    global _lib
    if _lib is not None or not _SO.exists():
        return _lib
    lib = ctypes.CDLL(str(_SO))
    lib.gyt_extract.restype = ctypes.c_int32
    lib.gyt_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_scan.restype = ctypes.c_int32
    lib.gyt_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_layout.restype = ctypes.c_int32
    lib.gyt_layout.argtypes = [ctypes.POINTER(ctypes.c_int64),
                               ctypes.c_int64]
    # layout handshake: a stale .so must never silently mis-slice records
    tri = (ctypes.c_int64 * 12)()
    n = lib.gyt_layout(tri, 4)
    native = {int(tri[i * 3]): (int(tri[i * 3 + 1]), int(tri[i * 3 + 2]))
              for i in range(n)}
    expect = {st: (wire.DTYPE_OF_SUBTYPE[st].itemsize,
                   wire.MAX_OF_SUBTYPE[st]) for st in _SCAN_ORDER}
    if native != expect:
        raise RuntimeError(
            f"native deframer layout mismatch: {native} != {expect}; "
            f"rebuild with python -m gyeeta_tpu.ingest.native.build")
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def drain(buf: bytes) -> tuple[dict, int]:
    """byte stream → ({subtype: structured record array}, consumed).

    Native path when built; identical semantics to the Python decoder
    (validation errors raise wire.FrameError either way).
    """
    lib = _load()
    if lib is None:
        return _drain_py(buf)
    counts = (ctypes.c_int64 * 4)()
    consumed = ctypes.c_int64()
    rc = lib.gyt_scan(buf, len(buf), counts, ctypes.byref(consumed))
    if rc != 0:
        raise wire.FrameError(f"native scan: {_ERRNAMES.get(rc, rc)}")
    out = {}
    for i, subtype in enumerate(_SCAN_ORDER):
        n = counts[i]
        if n == 0:
            continue
        dt = wire.DTYPE_OF_SUBTYPE[subtype]
        rec = np.empty(n, dt)
        c2 = ctypes.c_int64()
        nrec = ctypes.c_int64()
        tot = ctypes.c_int64()
        rc = lib.gyt_extract(
            buf, len(buf), subtype,
            rec.ctypes.data_as(ctypes.c_void_p), rec.nbytes,
            ctypes.byref(c2), ctypes.byref(nrec), ctypes.byref(tot))
        if rc != 0:
            raise wire.FrameError(f"native extract: {_ERRNAMES.get(rc, rc)}")
        assert nrec.value == n, (nrec.value, n)
        out[subtype] = rec
    return out, int(consumed.value)


def _drain_py(buf: bytes) -> tuple[dict, int]:
    frames, consumed = wire.decode_frames(buf)
    out: dict = {}
    for subtype, recs in frames:
        if subtype in out:
            out[subtype] = np.concatenate([out[subtype], recs])
        else:
            out[subtype] = recs.copy()
    return out, consumed
