"""ctypes loader for the native deframer, with pure-Python fallback.

``drain(buf)`` is the L1 ingest entry point: one pass over a byte stream →
{subtype: contiguous record array} + bytes consumed. Uses the C++ fast
path (built lazily on first use when g++ is available), else
``wire.decode_frames``.

The subtype table is pushed INTO the library from ``wire.DTYPE_OF_SUBTYPE``
at load time (``gyt_set_table``) and echoed back (``gyt_layout``) — the
native path structurally cannot drift from wire.py the way a compiled-in
table could.
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

from gyeeta_tpu.ingest import wire

_SO = pathlib.Path(__file__).resolve().parent / "libgytdeframe.so"
_SRC = pathlib.Path(__file__).resolve().parent / "deframe.cpp"
_lib = None
_load_failed = False

_ERRNAMES = {1: "bad magic", 2: "bad total_sz", 3: "batch cap exceeded",
             4: "nevents overflows frame", 5: "output buffer full",
             6: "bad subtype table"}

# drain() output ordering; derived from wire.py, never hand-maintained
_SCAN_ORDER = tuple(sorted(wire.DTYPE_OF_SUBTYPE))


def _ensure_built() -> bool:
    """Build (or rebuild, if deframe.cpp is newer) the shared object."""
    try:
        if _SO.exists() and (not _SRC.exists()
                             or _SO.stat().st_mtime >= _SRC.stat().st_mtime):
            return True
        from gyeeta_tpu.ingest.native import build
        build.build(verbose=False)
        return True
    except Exception:
        return _SO.exists()


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not _ensure_built():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
        return _bind_and_handshake(lib)
    except Exception:
        # unloadable or stale .so (e.g. missing gyt_set_table symbol):
        # fall back to the pure-Python decoder permanently
        _load_failed = True
        return None


def _bind_and_handshake(lib):
    global _lib
    lib.gyt_set_table.restype = ctypes.c_int32
    lib.gyt_set_table.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int32]
    lib.gyt_extract.restype = ctypes.c_int32
    lib.gyt_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_scan.restype = ctypes.c_int32
    lib.gyt_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_layout.restype = ctypes.c_int32
    lib.gyt_layout.argtypes = [ctypes.POINTER(ctypes.c_int64),
                               ctypes.c_int64]
    # push the subtype table from wire.py (single source of truth) ...
    n = len(_SCAN_ORDER)
    tri = (ctypes.c_int64 * (3 * n))()
    for i, st in enumerate(_SCAN_ORDER):
        tri[i * 3 + 0] = st
        tri[i * 3 + 1] = wire.DTYPE_OF_SUBTYPE[st].itemsize
        tri[i * 3 + 2] = wire.MAX_OF_SUBTYPE[st]
    rc = lib.gyt_set_table(tri, n)
    if rc != 0:
        raise RuntimeError(f"gyt_set_table: {_ERRNAMES.get(rc, rc)}")
    # ... and verify the round-trip covers every subtype
    back = (ctypes.c_int64 * (3 * n))()
    got = lib.gyt_layout(back, n)
    native = {int(back[i * 3]): (int(back[i * 3 + 1]), int(back[i * 3 + 2]))
              for i in range(got)}
    expect = {st: (wire.DTYPE_OF_SUBTYPE[st].itemsize,
                   wire.MAX_OF_SUBTYPE[st]) for st in _SCAN_ORDER}
    if native != expect:
        raise RuntimeError(
            f"native deframer layout mismatch: {native} != {expect}")
    # columnar conn-decode layout push (same single-source discipline)
    lib.gyt_set_conn_layout.restype = ctypes.c_int32
    lib.gyt_set_conn_layout.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_int32]
    lib.gyt_decode_conn.restype = ctypes.c_int32
    lib.gyt_decode_conn.argtypes = [ctypes.c_void_p, ctypes.c_int64] + \
        [ctypes.c_void_p] * 16
    dt = wire.TCP_CONN_DT
    off = {name: dt.fields[name][1] for name in dt.names}
    fields = [dt.itemsize,
              off["cli"], off["ser"], off["nat_cli"], off["nat_ser"],
              off["tusec_start"], off["tusec_close"],
              off["cli_task_aggr_id"], off["cli_related_listen_id"],
              off["ser_glob_id"], off["bytes_sent"], off["bytes_rcvd"],
              off["host_id"], off["flags"],
              wire.IP_PORT_DT.fields["port"][1]]
    arr = (ctypes.c_int64 * len(fields))(*fields)
    rc = lib.gyt_set_conn_layout(arr, len(fields))
    if rc != 0:
        raise RuntimeError(f"gyt_set_conn_layout: "
                           f"{_ERRNAMES.get(rc, rc)}")
    _lib = lib
    return _lib


def decode_conn(recs, size: int):
    """Native columnar TCP_CONN decode → ConnBatch (or None when the
    native library is unavailable — callers fall back to
    decode.conn_batch). Semantics bit-identical to the Python decoder;
    tests/test_native_ingest.py diffs them on random records."""
    lib = _load()
    if lib is None:
        return None
    from gyeeta_tpu.ingest import decode as D

    if recs.dtype != wire.TCP_CONN_DT:
        raise TypeError(f"decode_conn needs TCP_CONN_DT records, got "
                        f"{recs.dtype}")   # C++ walks layout offsets
    if len(recs) > size:
        raise ValueError(f"{len(recs)} records exceed batch size {size};"
                         f" split upstream")
    n = len(recs)
    recs = np.ascontiguousarray(recs)
    u32 = lambda: np.zeros(size, np.uint32)     # noqa: E731
    f32 = lambda: np.zeros(size, np.float32)    # noqa: E731
    cols = dict(
        svc_hi=u32(), svc_lo=u32(), flow_hi=u32(), flow_lo=u32(),
        cli_hi=u32(), cli_lo=u32(), cli_task_hi=u32(),
        cli_task_lo=u32(), cli_rel_hi=u32(), cli_rel_lo=u32(),
        bytes_sent=f32(), bytes_rcvd=f32(), duration_us=f32(),
        host_id=np.zeros(size, np.int32),
        is_close=np.zeros(size, np.uint8),
        is_accept=np.zeros(size, np.uint8))
    ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    rc = lib.gyt_decode_conn(
        recs.ctypes.data_as(ctypes.c_void_p), n,
        ptr(cols["svc_hi"]), ptr(cols["svc_lo"]),
        ptr(cols["flow_hi"]), ptr(cols["flow_lo"]),
        ptr(cols["cli_hi"]), ptr(cols["cli_lo"]),
        ptr(cols["cli_task_hi"]), ptr(cols["cli_task_lo"]),
        ptr(cols["cli_rel_hi"]), ptr(cols["cli_rel_lo"]),
        ptr(cols["bytes_sent"]), ptr(cols["bytes_rcvd"]),
        ptr(cols["duration_us"]), ptr(cols["host_id"]),
        ptr(cols["is_close"]), ptr(cols["is_accept"]))
    if rc != 0:
        raise RuntimeError(f"gyt_decode_conn: {_ERRNAMES.get(rc, rc)}")
    valid = np.zeros(size, bool)
    valid[:n] = True
    return D.ConnBatch(
        valid=valid,
        is_close=cols.pop("is_close").astype(bool),
        is_accept=cols.pop("is_accept").astype(bool),
        **cols)


def available() -> bool:
    return _load() is not None


def drain(buf: bytes) -> tuple[dict, int]:
    """byte stream → ({subtype: structured record array}, consumed).

    Native path when built; identical semantics to the Python decoder
    (validation errors raise wire.FrameError either way).
    """
    lib = _load()
    if lib is None:
        return _drain_py(buf)
    n = len(_SCAN_ORDER)
    counts = (ctypes.c_int64 * n)()
    consumed = ctypes.c_int64()
    rc = lib.gyt_scan(buf, len(buf), counts, ctypes.byref(consumed))
    if rc != 0:
        raise wire.FrameError(f"native scan: {_ERRNAMES.get(rc, rc)}")
    out = {}
    for i, subtype in enumerate(_SCAN_ORDER):
        nrecs = counts[i]
        if nrecs == 0:
            continue
        dt = wire.DTYPE_OF_SUBTYPE[subtype]
        rec = np.empty(nrecs, dt)
        c2 = ctypes.c_int64()
        nrec = ctypes.c_int64()
        tot = ctypes.c_int64()
        rc = lib.gyt_extract(
            buf, len(buf), subtype,
            rec.ctypes.data_as(ctypes.c_void_p), rec.nbytes,
            ctypes.byref(c2), ctypes.byref(nrec), ctypes.byref(tot))
        if rc != 0:
            raise wire.FrameError(f"native extract: {_ERRNAMES.get(rc, rc)}")
        assert nrec.value == nrecs, (nrec.value, nrecs)
        out[subtype] = rec
    return out, int(consumed.value)


def _drain_py(buf: bytes) -> tuple[dict, int]:
    frames, consumed = wire.decode_frames(buf)
    out: dict = {}
    for subtype, recs in frames:
        if subtype in out:
            out[subtype] = np.concatenate([out[subtype], recs])
        else:
            out[subtype] = recs.copy()
    return out, consumed
