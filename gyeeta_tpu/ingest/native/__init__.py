"""ctypes loader for the native deframer, with pure-Python fallback.

``drain(buf)`` is the L1 ingest entry point: one pass over a byte stream →
{subtype: contiguous record array} + bytes consumed. Uses the C++ fast
path (built lazily on first use when g++ is available), else
``wire.decode_frames``.

The subtype table is pushed INTO the library from ``wire.DTYPE_OF_SUBTYPE``
at load time (``gyt_set_table``) and echoed back (``gyt_layout``) — the
native path structurally cannot drift from wire.py the way a compiled-in
table could.
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

from gyeeta_tpu.ingest import wire

_SO = pathlib.Path(__file__).resolve().parent / "libgytdeframe.so"
_SRC = pathlib.Path(__file__).resolve().parent / "deframe.cpp"
_lib = None
_load_failed = False

_ERRNAMES = {1: "bad magic", 2: "bad total_sz", 3: "batch cap exceeded",
             4: "nevents overflows frame", 5: "output buffer full",
             6: "bad subtype table"}

# drain() output ordering; derived from wire.py, never hand-maintained
_SCAN_ORDER = tuple(sorted(wire.DTYPE_OF_SUBTYPE))


def _ensure_built() -> bool:
    """Build (or rebuild, if deframe.cpp is newer) the shared object."""
    try:
        if _SO.exists() and (not _SRC.exists()
                             or _SO.stat().st_mtime >= _SRC.stat().st_mtime):
            return True
        from gyeeta_tpu.ingest.native import build
        build.build(verbose=False)
        return True
    except Exception:
        return _SO.exists()


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not _ensure_built():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
        return _bind_and_handshake(lib)
    except Exception:
        # unloadable or stale .so (e.g. missing gyt_set_table symbol):
        # fall back to the pure-Python decoder permanently
        _load_failed = True
        return None


def _bind_and_handshake(lib):
    global _lib
    lib.gyt_set_table.restype = ctypes.c_int32
    lib.gyt_set_table.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int32]
    lib.gyt_extract.restype = ctypes.c_int32
    lib.gyt_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_scan.restype = ctypes.c_int32
    lib.gyt_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.gyt_layout.restype = ctypes.c_int32
    lib.gyt_layout.argtypes = [ctypes.POINTER(ctypes.c_int64),
                               ctypes.c_int64]
    # push the subtype table from wire.py (single source of truth) ...
    n = len(_SCAN_ORDER)
    tri = (ctypes.c_int64 * (3 * n))()
    for i, st in enumerate(_SCAN_ORDER):
        tri[i * 3 + 0] = st
        tri[i * 3 + 1] = wire.DTYPE_OF_SUBTYPE[st].itemsize
        tri[i * 3 + 2] = wire.MAX_OF_SUBTYPE[st]
    rc = lib.gyt_set_table(tri, n)
    if rc != 0:
        raise RuntimeError(f"gyt_set_table: {_ERRNAMES.get(rc, rc)}")
    # ... and verify the round-trip covers every subtype
    back = (ctypes.c_int64 * (3 * n))()
    got = lib.gyt_layout(back, n)
    native = {int(back[i * 3]): (int(back[i * 3 + 1]), int(back[i * 3 + 2]))
              for i in range(got)}
    expect = {st: (wire.DTYPE_OF_SUBTYPE[st].itemsize,
                   wire.MAX_OF_SUBTYPE[st]) for st in _SCAN_ORDER}
    if native != expect:
        raise RuntimeError(
            f"native deframer layout mismatch: {native} != {expect}")
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def drain(buf: bytes) -> tuple[dict, int]:
    """byte stream → ({subtype: structured record array}, consumed).

    Native path when built; identical semantics to the Python decoder
    (validation errors raise wire.FrameError either way).
    """
    lib = _load()
    if lib is None:
        return _drain_py(buf)
    n = len(_SCAN_ORDER)
    counts = (ctypes.c_int64 * n)()
    consumed = ctypes.c_int64()
    rc = lib.gyt_scan(buf, len(buf), counts, ctypes.byref(consumed))
    if rc != 0:
        raise wire.FrameError(f"native scan: {_ERRNAMES.get(rc, rc)}")
    out = {}
    for i, subtype in enumerate(_SCAN_ORDER):
        nrecs = counts[i]
        if nrecs == 0:
            continue
        dt = wire.DTYPE_OF_SUBTYPE[subtype]
        rec = np.empty(nrecs, dt)
        c2 = ctypes.c_int64()
        nrec = ctypes.c_int64()
        tot = ctypes.c_int64()
        rc = lib.gyt_extract(
            buf, len(buf), subtype,
            rec.ctypes.data_as(ctypes.c_void_p), rec.nbytes,
            ctypes.byref(c2), ctypes.byref(nrec), ctypes.byref(tot))
        if rc != 0:
            raise wire.FrameError(f"native extract: {_ERRNAMES.get(rc, rc)}")
        assert nrec.value == nrecs, (nrec.value, nrecs)
        out[subtype] = rec
    return out, int(consumed.value)


def _drain_py(buf: bytes) -> tuple[dict, int]:
    frames, consumed = wire.decode_frames(buf)
    out: dict = {}
    for subtype, recs in frames:
        if subtype in out:
            out[subtype] = np.concatenate([out[subtype], recs])
        else:
            out[subtype] = recs.copy()
    return out, consumed
