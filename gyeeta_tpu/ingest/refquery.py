"""gy_comm_proto query-edge ABI: the node-webserver (NM) conn contract.

``refproto.py`` closed the INGEST half of the serialization boundary
(stock partha handshake + NOTIFY subtypes). This module transcribes the
QUERY half — what a stock Gyeeta NodeJS webserver speaks at a madhava
(routing ``server/gy_mnodehandle.cc:203``):

- ``NM_CONNECT_CMD_S`` / ``NM_CONNECT_RESP_S`` — the node→madhava
  registration handshake (``gy_comm_proto.h:887-952``), version-gated
  like the partha handshakes;
- ``QUERY_CMD_S`` (``gy_comm_proto.h:502``) — seqid/timeout/qtype
  envelope followed by a JSON body; qtypes transcribed from
  ``QUERY_TYPE_E`` (``gy_comm_proto.h:246-258``): ``QUERY_WEB_JSON``,
  ``CRUD_GENERIC_JSON``, ``CRUD_ALERT_JSON``;
- ``QUERY_RESPONSE_S`` (``gy_comm_proto.h:536``) — seqid/resptype/
  format/len envelope; large results stream as is_completed=0 chunks
  closed by a final is_completed=1 frame (the reference's ≤16MB
  SOCK_JSON_WRITER chunk discipline).

Layout conventions follow refproto.py: explicit little-endian numpy
dtypes with the reference's alignas(8) + explicit padding discipline;
``ingest/native/abiprobe.py`` proves each transcription against a C++
compiler's layout of the extracted header subset.

Both sides are implemented (server: ``net/nmhandle.py``; client:
``sim/nodeweb.py`` / ``cli nm``), so the edge is byte-level testable
without a stock webserver in the loop.
"""

from __future__ import annotations

import json

import numpy as np

from gyeeta_tpu.ingest import refproto as RP
from gyeeta_tpu.ingest import wire

# ----------------------------------------------------------- conn magics
# gy_comm_proto.h:39-57 per-edge COMM_HEADER magics: PS/PM are owned by
# refproto; the node edges use the remaining two of the documented set
REF_MAGIC_NM = 0x05777705        # node webserver → madhava
REF_MAGIC_NS = 0x05888805        # node webserver → shyama

# COMM_TYPE_E continuation (gy_comm_proto.h:124; refproto transcribes
# PS_REGISTER_REQ=2, PM_CONNECT_CMD=3, PS_REGISTER_RESP=8,
# PM_CONNECT_RESP=9, EVENT_NOTIFY=14 — the six REQ slots 2..7 mirror
# into RESP slots 8..13, then NOTIFY/QUERY)
REF_COMM_NS_REGISTER_REQ = 6
REF_COMM_NM_CONNECT_CMD = 7
REF_COMM_NS_REGISTER_RESP = 12
REF_COMM_NM_CONNECT_RESP = 13
REF_COMM_QUERY_CMD = 15
REF_COMM_QUERY_RESP = 16

# QUERY_TYPE_E (gy_comm_proto.h:246-258)
REF_QUERY_IGNORE = 0
REF_QUERY_PARTHA_MADHAVA = 1
REF_QUERY_WEB_JSON = 2
REF_QUERY_NODE_MADHAVA = 3
REF_CRUD_GENERIC_JSON = 4
REF_CRUD_ALERT_JSON = 5

# RESP_TYPE_E / RESP_FORMAT_E (gy_comm_proto.h:262-276)
REF_RESP_NULL = 0
REF_RESP_ERROR = 1
REF_RESP_WEB_JSON = 2
REF_RESP_FMT_JSON = 0
REF_RESP_FMT_BINARY = 1

# node version floors (sversion.cc analogues; the node tier versions in
# lockstep with the servers)
REF_MIN_NODE_VERSION = 0x000400       # "0.4.0"

# NM_CONNECT_CMD_S (gy_comm_proto.h:887) — the node's opener to madhava
REF_NM_CONNECT_CMD_DT = np.dtype([
    ("comm_version", "<u4"), ("node_version", "<u4"),
    ("min_madhava_version", "<u4"), ("pad0", "u1", (4,)),
    ("node_hostname", "S256"),
    ("node_port", "<u4"), ("cli_type", "<u4"),
    ("curr_sec", "<i8"), ("clock_sec", "<i8"),
    ("flags", "<u8"),
    ("extra_bytes", "u1", (512,)),
])
assert REF_NM_CONNECT_CMD_DT.itemsize == 816

# NM_CONNECT_RESP_S (gy_comm_proto.h:923)
REF_NM_CONNECT_RESP_DT = np.dtype([
    ("error_code", "<i4"), ("error_string", "S256"),
    ("pad0", "u1", (4,)),
    ("madhava_id", "<u8"), ("comm_version", "<u4"),
    ("madhava_version", "<u4"),
    ("madhava_name", "S64"),
    ("curr_sec", "<i8"), ("clock_sec", "<u8"), ("flags", "<u8"),
    ("extra_bytes", "u1", (512,)),
])
assert REF_NM_CONNECT_RESP_DT.itemsize == 880

# QUERY_CMD_S (gy_comm_proto.h:502): fixed envelope, JSON body follows
REF_QUERY_CMD_DT = np.dtype([
    ("seqid", "<u8"), ("timeoutusec", "<u8"),
    ("subtype", "<u4"),          # QUERY_TYPE_E
    ("respformat", "<u4"),       # RESP_FORMAT_E
])
assert REF_QUERY_CMD_DT.itemsize == 24

# QUERY_RESPONSE_S (gy_comm_proto.h:536): fixed envelope, body follows
REF_QUERY_RESPONSE_DT = np.dtype([
    ("seqid", "<u8"),
    ("resptype", "<u4"),         # RESP_TYPE_E
    ("respformat", "<u4"),
    ("resp_len", "<u4"),         # THIS chunk's body bytes (before pad)
    ("is_completed", "<u4"),     # 0 = more chunks follow (QS_PARTIAL)
])
assert REF_QUERY_RESPONSE_DT.itemsize == 24

_HSZ = RP.REF_HEADER_DT.itemsize
_QSZ = REF_QUERY_CMD_DT.itemsize
_RSZ = REF_QUERY_RESPONSE_DT.itemsize

# streamed-response chunk size: same discipline as the GYT query conn
# (wire.QUERY_CHUNK_BYTES) — well under the 16MB frame cap
NM_CHUNK_BYTES = wire.QUERY_CHUNK_BYTES

# the web qtype table the Node tier sends inside QUERY_WEB_JSON bodies
# ({"qtype": N, "options": {...}} — NODE_QUERY_TYPE_E of the reference
# webserver's gy_nodequery routing, gy_mnodehandle.cc:203): transcribed
# code → GYT query subsystem. String subsys names are also accepted
# (forward compatibility: the reference envelope grows qtypes faster
# than this table; names always work).
SUBSYS_OF_QTYPE = {
    1: "hoststate", 2: "cpumem", 3: "svcstate", 4: "svcinfo",
    5: "svcsumm", 6: "activeconn", 7: "clientconn", 8: "taskstate",
    9: "topcpu", 10: "toprss", 11: "topfork", 12: "tcpconn",
    13: "hostinfo", 14: "notifymsg", 15: "alerts", 16: "alertdef",
    17: "silences", 18: "inhibits", 19: "tracereq", 20: "tracedef",
    21: "clusterstate", 22: "svcmesh", 23: "svcipclust",
    24: "tracestatus", 25: "hostlist", 26: "svcprocmap",
    27: "traceuniq", 28: "cgroupstate",
    # GYT extension beyond the stock table: the heavy-hitter union view
    # (string subsys names always work; the code is for symmetry)
    29: "topk",
}
QTYPE_OF_SUBSYS = {v: k for k, v in SUBSYS_OF_QTYPE.items()}

# "tcpconn" is the node name for the flow view
_SUBSYS_ALIASES = {"tcpconn": "flowstate", "task": "taskstate",
                   "host": "hoststate", "service": "svcstate"}

# CRUD objtype families per verb (gy_comm_proto.h:246-258 routing:
# CRUD_ALERT_JSON → ALERTMGR, CRUD_GENERIC_JSON → generic def CRUD)
ALERT_CRUD_OBJS = ("alertdef", "silence", "inhibit", "action")
GENERIC_CRUD_OBJS = ("tracedef", "tag")


class NMFrameError(wire.FrameError):
    pass


# -------------------------------------------------------------- framing
def _ref_frame(data_type: int, payload: bytes,
               magic: int = REF_MAGIC_NM) -> bytes:
    pad = (-len(payload)) % 8
    total = _HSZ + len(payload) + pad
    if total >= wire.MAX_COMM_DATA_SZ:
        raise NMFrameError(f"NM frame {total}B exceeds 16MB cap")
    hdr = np.zeros((), RP.REF_HEADER_DT)
    hdr["magic"] = magic
    hdr["total_sz"] = total
    hdr["data_type"] = data_type
    hdr["padding_sz"] = pad
    return hdr.tobytes() + payload + b"\x00" * pad


# ------------------------------------------------------------ handshake
def encode_nm_connect_cmd(hostname: str = "nodeweb",
                          node_port: int = 10039,
                          node_version: int = 0x000501,
                          comm_version: int = RP.REF_COMM_VERSION,
                          min_madhava_version: int = 0x000500,
                          cli_type: int = RP.REF_CLI_TYPE_REQ_RESP,
                          curr_sec: int = 0) -> bytes:
    """Synthesized stock-node NM_CONNECT_CMD_S frame (what the Node
    webserver's madhava handler sends on connect)."""
    r = np.zeros((), REF_NM_CONNECT_CMD_DT)
    r["comm_version"] = comm_version
    r["node_version"] = node_version
    r["min_madhava_version"] = min_madhava_version
    r["node_hostname"] = hostname.encode()[:255]
    r["node_port"] = node_port
    r["cli_type"] = cli_type
    r["curr_sec"] = curr_sec
    r["clock_sec"] = curr_sec
    return _ref_frame(REF_COMM_NM_CONNECT_CMD, r.tobytes())


def parse_nm_connect_cmd(body: bytes) -> dict:
    """NM_CONNECT_CMD_S payload → field dict (raises on short body)."""
    if len(body) < REF_NM_CONNECT_CMD_DT.itemsize:
        raise NMFrameError("short NM_CONNECT_CMD_S")
    r = np.frombuffer(body, REF_NM_CONNECT_CMD_DT, count=1)[0]
    return {
        "comm_version": int(r["comm_version"]),
        "node_version": int(r["node_version"]),
        "min_madhava_version": int(r["min_madhava_version"]),
        "node_hostname": RP._cstr(r["node_hostname"]),
        "node_port": int(r["node_port"]),
        "cli_type": int(r["cli_type"]),
        "curr_sec": int(r["curr_sec"]),
    }


def encode_nm_connect_resp(error_code: int, error_string: str,
                           madhava_id: int, curr_sec: int) -> bytes:
    """Byte-exact NM_CONNECT_RESP_S frame."""
    r = np.zeros((), REF_NM_CONNECT_RESP_DT)
    v = r
    v["error_code"] = error_code
    v["error_string"] = error_string.encode()[:255]
    v["madhava_id"] = madhava_id
    v["comm_version"] = RP.REF_COMM_VERSION
    v["madhava_version"] = RP.REF_MADHAVA_VERSION
    v["madhava_name"] = b"gyt-tpu"
    v["curr_sec"] = curr_sec
    v["clock_sec"] = curr_sec
    return _ref_frame(REF_COMM_NM_CONNECT_RESP, r.tobytes())


def parse_nm_connect_resp(buf: bytes) -> dict:
    """Client-side decode of a whole NM_CONNECT_RESP_S frame."""
    hdr = np.frombuffer(buf, RP.REF_HEADER_DT, count=1)[0]
    r = np.frombuffer(buf, REF_NM_CONNECT_RESP_DT, count=1,
                      offset=_HSZ)[0]
    return {"data_type": int(hdr["data_type"]),
            "error_code": int(r["error_code"]),
            "error_string": RP._cstr(r["error_string"]),
            "madhava_id": int(r["madhava_id"]),
            "madhava_version": int(r["madhava_version"]),
            "madhava_name": RP._cstr(r["madhava_name"])}


# --------------------------------------------------------------- queries
def encode_query_cmd(seqid: int, qtype: int, body_obj,
                     timeout_sec: float = 100.0) -> bytes:
    """One QUERY_CMD_S frame: envelope + JSON body."""
    h = np.zeros((), REF_QUERY_CMD_DT)
    h["seqid"] = np.uint64(seqid)
    h["timeoutusec"] = np.uint64(int(timeout_sec * 1e6))
    h["subtype"] = qtype
    h["respformat"] = REF_RESP_FMT_JSON
    body = json.dumps(body_obj).encode()
    return _ref_frame(REF_COMM_QUERY_CMD, h.tobytes() + body)


def parse_query_cmd(body: bytes) -> tuple[int, int, dict]:
    """QUERY_CMD frame payload → (seqid, qtype, json_obj)."""
    if len(body) < _QSZ:
        raise NMFrameError("short QUERY_CMD_S")
    h = np.frombuffer(body, REF_QUERY_CMD_DT, count=1)[0]
    raw = body[_QSZ:]
    try:
        obj = json.loads(raw) if raw.strip(b"\x00") else {}
    except json.JSONDecodeError as e:
        raise NMFrameError(f"bad QUERY_CMD JSON body: {e}") from None
    if not isinstance(obj, dict):
        raise NMFrameError("QUERY_CMD body must be a JSON object")
    return int(h["seqid"]), int(h["subtype"]), obj


def iter_response_frames(seqid: int, obj,
                         resptype: int = REF_RESP_WEB_JSON,
                         chunk_bytes: int = NM_CHUNK_BYTES):
    """Yield the streamed QUERY_RESPONSE_S frame sequence for a JSON
    result: N-1 is_completed=0 chunks + one final is_completed=1 frame
    (the reference's ≤16MB SOCK_JSON_WRITER chunk discipline; mirrors
    ``wire.iter_query_frames``). JSON renders with the same plain
    ``json.dumps`` as the GYT/REST surfaces — byte parity by
    construction."""
    payload = json.dumps(obj).encode()
    for off in range(0, max(len(payload), 1), chunk_bytes):
        body = payload[off: off + chunk_bytes]
        h = np.zeros((), REF_QUERY_RESPONSE_DT)
        h["seqid"] = np.uint64(seqid)
        h["resptype"] = resptype
        h["respformat"] = REF_RESP_FMT_JSON
        h["resp_len"] = len(body)
        h["is_completed"] = 1 if off + chunk_bytes >= len(payload) else 0
        yield _ref_frame(REF_COMM_QUERY_RESP, h.tobytes() + body)


def encode_response_frames(seqid: int, obj,
                           resptype: int = REF_RESP_WEB_JSON) -> bytes:
    """Joined form of :func:`iter_response_frames` (tests)."""
    return b"".join(iter_response_frames(seqid, obj, resptype))


def parse_response_chunk(body: bytes) -> tuple[int, int, int, bytes]:
    """QUERY_RESPONSE frame payload → (seqid, resptype, is_completed,
    body_bytes). Callers accumulate until is_completed."""
    if len(body) < _RSZ:
        raise NMFrameError("short QUERY_RESPONSE_S")
    h = np.frombuffer(body, REF_QUERY_RESPONSE_DT, count=1)[0]
    n = int(h["resp_len"])
    return (int(h["seqid"]), int(h["resptype"]),
            int(h["is_completed"]), body[_RSZ: _RSZ + n])


# ------------------------------------------------- envelope translation
def web_json_to_query(obj: dict) -> dict:
    """A QUERY_WEB_JSON body ({"qtype": N|name, "options": {...}} per
    the reference envelope, or a native {"subsys": ...} request) → the
    GYT query dict ``Runtime.query`` takes. Raises ValueError on
    unknown qtypes (surfaced to the client as an error response)."""
    if "subsys" in obj and "qtype" not in obj:
        return obj                       # native shape passes through
    qtype = obj.get("qtype")
    if isinstance(qtype, str):
        subsys = _SUBSYS_ALIASES.get(qtype, qtype)
    else:
        subsys = SUBSYS_OF_QTYPE.get(qtype)
        if subsys is None:
            raise ValueError(f"unknown web qtype {qtype!r}")
    req = {"subsys": subsys}
    options = obj.get("options") or {}
    if not isinstance(options, dict):
        raise ValueError("options must be a JSON object")
    for k, v in options.items():
        if k == "sortdir":               # reference asc/desc form
            req["sortdesc"] = str(v).lower() != "asc"
        else:
            req[k] = v
    # "multiquery" rides inside options untouched (the engine's crud
    # module validates it)
    return req


def crud_to_request(obj: dict, alert: bool) -> dict:
    """A CRUD_*_JSON body → the GYT crud dict, with the objtype family
    enforced per verb (the reference routes CRUD_ALERT_JSON to ALERTMGR
    only — a tracedef smuggled over the alert verb must not work)."""
    req = dict(obj)
    if "optype" in req and "op" not in req:     # reference field name
        req["op"] = req.pop("optype")
    allowed = ALERT_CRUD_OBJS if alert else GENERIC_CRUD_OBJS
    objtype = req.get("objtype")
    if objtype is None and alert:
        req["objtype"] = objtype = "alertdef"   # the verb's default
    if objtype not in allowed:
        verb = "CRUD_ALERT_JSON" if alert else "CRUD_GENERIC_JSON"
        raise ValueError(f"{verb} objtype must be one of {allowed}")
    return req
