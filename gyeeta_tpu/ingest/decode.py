"""Structured wire records → fixed-shape columnar microbatches.

The device engine consumes only fixed-width numeric columns with a static
batch size (XLA: one traced shape). This module converts decoded record
arrays (``wire.decode_frames``) into padded column dicts:

- 64-bit ids are split into ``(hi, lo)`` uint32 pairs (TPU int path),
- IPs are folded to two uint32 words (xor-fold of the 16 bytes — enough for
  hashing/HLL identity, the only device use of addresses),
- flow 5-tuple → 64-bit flow key via ``hashing.flow_key`` (host-side numpy,
  bit-identical to the device version),
- a ``valid`` lane mask marks padding.

This mirrors what the reference's L1 threads do (validate + batch into
DB_WRITE_ARR, ``server/gy_mconnhdlr.cc:2430-2520``) — but produces tensors,
not pointer arrays. The C++ fast path (ingest/native) emits the identical
layout.
"""

from __future__ import annotations

import logging
from typing import NamedTuple

import numpy as np

from gyeeta_tpu.ingest import native, wire
from gyeeta_tpu.utils import hashing as H

_log = logging.getLogger("gyeeta_tpu.ingest")
_warned_fallback = False


def _count_path(stats, used_native: bool, n: int) -> None:
    """Per-session native-vs-fallback decode counters (selfstats:
    ``ref_native_decoded`` / ``ref_fallback_decoded``) — a silently
    missing .so is visible in the counters, plus a one-time warning."""
    global _warned_fallback
    if stats is not None and n:
        stats.bump("ref_native_decoded" if used_native
                   else "ref_fallback_decoded", n)
    if not used_native and not _warned_fallback:
        _warned_fallback = True
        import os
        if os.environ.get("GYT_PY_INGEST", "") in ("", "0"):
            _log.warning(
                "native ingest decoder unavailable (libgytdeframe.so) — "
                "pure-Python decode fallback in use; build it with "
                "`python -m gyeeta_tpu.ingest.native.build` (selfstats "
                "counter: ref_fallback_decoded)")


def split_u64(a) -> tuple[np.ndarray, np.ndarray]:
    a = np.asarray(a, np.uint64)
    return ((a >> np.uint64(32)).astype(np.uint32),
            (a & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def fold_ip(ip_bytes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N,16) uint8 → two uint32 words (xor-fold halves)."""
    w = ip_bytes.reshape(-1, 4, 4).copy().view("<u4").reshape(-1, 4)
    return (w[:, 0] ^ w[:, 2]).astype(np.uint32), \
        (w[:, 1] ^ w[:, 3]).astype(np.uint32)


class ConnBatch(NamedTuple):
    """Columnar TCP_CONN microbatch (all shape (B,))."""
    svc_hi: np.ndarray        # ser_glob_id split — per-service routing key
    svc_lo: np.ndarray
    flow_hi: np.ndarray       # 5-tuple flow key
    flow_lo: np.ndarray
    cli_hi: np.ndarray        # client endpoint identity (HLL distinct-cli)
    cli_lo: np.ndarray
    cli_task_hi: np.ndarray   # client process-group id
    cli_task_lo: np.ndarray
    cli_rel_hi: np.ndarray    # client related-listener id (0 = client is
    cli_rel_lo: np.ndarray    #   not itself a service) — dep-graph identity
    bytes_sent: np.ndarray    # float32
    bytes_rcvd: np.ndarray    # float32
    duration_us: np.ndarray   # float32 (0 if still open)
    host_id: np.ndarray       # int32 source agent
    is_close: np.ndarray      # bool — close-notification record
    is_accept: np.ndarray     # bool — server-side (accept-observed):
    #                           only these lanes update the svc slab; a
    #                           client-observed record references a
    #                           REMOTE service it must not materialize
    valid: np.ndarray         # bool lane mask


class RespBatch(NamedTuple):
    svc_hi: np.ndarray
    svc_lo: np.ndarray
    resp_us: np.ndarray       # float32 response/service time in usec
    host_id: np.ndarray
    valid: np.ndarray


class ListenerBatch(NamedTuple):
    """Columnar LISTENER_STATE microbatch: key + packed stat columns."""
    svc_hi: np.ndarray
    svc_lo: np.ndarray
    stats: np.ndarray         # (B, NSTAT) float32, see STAT_* indices
    host_id: np.ndarray
    valid: np.ndarray


class HostBatch(NamedTuple):
    """Columnar HOST_STATE microbatch (dense panel write by host_id)."""
    host_id: np.ndarray       # int32
    panel: np.ndarray         # (B, NHOSTCOL) float32, aggstate.HOST_* order
    valid: np.ndarray


class CpuMemBatch(NamedTuple):
    """Columnar CPU_MEM_STATE (2s) microbatch: raw gauges by host."""
    host_id: np.ndarray       # int32
    vals: np.ndarray          # (B, NCM) float32, CM_* indices
    valid: np.ndarray


class TraceBatch(NamedTuple):
    """Columnar REQ_TRACE microbatch: one parsed transaction per lane."""
    key_hi: np.ndarray        # mix(svc, api) — per-API routing key
    key_lo: np.ndarray
    svc_hi: np.ndarray        # service glob id halves (readback)
    svc_lo: np.ndarray
    api_hi: np.ndarray        # interned api signature halves
    api_lo: np.ndarray
    resp_us: np.ndarray       # float32
    byin: np.ndarray          # float32
    byout: np.ndarray         # float32
    proto: np.ndarray         # int32
    is_err: np.ndarray        # bool (status stays in the raw record;
    #                           the engine aggregates only the error bit)
    host_id: np.ndarray       # int32
    valid: np.ndarray


class DeltaBatch(NamedTuple):
    """Columnar SKETCH_DELTA microbatch: the wire's typed-envelope
    records (``wire.DELTA_DT``) expanded into per-family fixed lanes
    the fused fold scatters directly (``engine/step.py:ingest_delta``).
    Expansion happens host-side (pure numpy): sparse payload items
    flatten into (entity, index, weight) lanes; the unique svc-key
    section drives ONE table upsert per dispatch."""
    # unique svc keys across every svc-referencing family (one upsert)
    svc_hi: np.ndarray        # (Lk,) uint32
    svc_lo: np.ndarray
    svc_host: np.ndarray      # (Lk,) int32 — owning agent
    svc_valid: np.ndarray
    # per-svc exact counter rows (ctr_win order + n_conn/n_resp)
    ctr_hi: np.ndarray        # (Lc,)
    ctr_lo: np.ndarray
    ctr_vals: np.ndarray      # (Lc, 6) float32
    ctr_valid: np.ndarray
    # per-svc resp loghist bucket counts
    hist_hi: np.ndarray       # (Lh,)
    hist_lo: np.ndarray
    hist_bucket: np.ndarray   # (Lh,) int32
    hist_w: np.ndarray        # (Lh,) float32
    hist_valid: np.ndarray
    # per-svc distinct-client HLL register maxes
    shll_hi: np.ndarray       # (Ls,)
    shll_lo: np.ndarray
    shll_reg: np.ndarray      # (Ls,) int32
    shll_rank: np.ndarray     # (Ls,) int32
    shll_valid: np.ndarray
    # global flow-HLL register maxes
    ghll_reg: np.ndarray      # (Lg,) int32
    ghll_rank: np.ndarray     # (Lg,) int32
    ghll_valid: np.ndarray
    # per-svc t-digest stage samples (pre-strided at the agent)
    td_hi: np.ndarray         # (Lt,)
    td_lo: np.ndarray
    td_val: np.ndarray        # (Lt,) float32
    td_valid: np.ndarray
    # flow aggregates (CMS / top-K / invertible inputs)
    flow_hi: np.ndarray       # (Lf,)
    flow_lo: np.ndarray
    flow_val: np.ndarray      # (Lf,) float32
    flow_valid: np.ndarray
    # dependency edges (direct-edge fold)
    dep_cli_hi: np.ndarray    # (Ld,)
    dep_cli_lo: np.ndarray
    dep_cli_svc: np.ndarray   # (Ld,) bool
    dep_ser_hi: np.ndarray
    dep_ser_lo: np.ndarray
    dep_nconn: np.ndarray     # (Ld,) float32
    dep_bytes: np.ndarray     # (Ld,) float32
    dep_valid: np.ndarray
    # sweep residuals: agent-truncated flow mass → top-K evicted bound
    evicted_add: np.ndarray   # (1,) float32


# default per-dispatch SKETCH_DELTA record lanes (drain_chunks chunk
# size; GYT_SLAB_DELTA_LANES must stay >= this)
DELTA_LANES_DEFAULT = 256


def _delta_pad(a, lanes, dtype):
    a = np.asarray(a)
    out = np.zeros((lanes,) + a.shape[1:], dtype)
    out[: len(a)] = a[:lanes]
    return out


def _delta_mask(n, lanes):
    v = np.zeros(lanes, bool)
    v[:n] = True
    return v


def delta_batch(recs: np.ndarray, size: int = DELTA_LANES_DEFAULT,
                stats=None, resp_nbuckets: int = 0,
                hll_m_svc: int = 0, hll_m_glob: int = 0) -> DeltaBatch:
    """SKETCH_DELTA records → expanded per-family columnar lanes.

    ``resp_nbuckets`` / ``hll_m_svc`` / ``hll_m_glob``: the consuming
    engine's geometry — payload items whose index falls outside it are
    DROPPED AND COUNTED (``preagg_oob_items``), never scattered out of
    range (a corrupt or mis-negotiated index must not fold garbage).
    Family lane budgets derive from ``size`` at the per-record payload
    maxima, so a ≤size record batch can never overflow a family."""
    n = _check_fit(recs, size)
    r = recs[:n]
    kinds = r["kind"]
    nitem = r["nitem"].astype(np.int64)
    oob = 0

    def pairs_of(mask, cap_items):
        """(svc64, idx, wt, src_row) lanes for one pair-payload kind."""
        rows = np.nonzero(mask)[0]
        if not len(rows):
            z = np.empty(0, np.int64)
            return (np.empty(0, np.uint32), np.empty(0, np.uint32),
                    z, np.empty(0, np.float32), 0)
        P = wire.DELTA_PAIRS
        pv = r["payload"][rows].reshape(len(rows), -1)[
            :, : P * 6].copy().reshape(-1).view(wire.DELTA_PAIR_DT)
        ni = np.minimum(nitem[rows], P)
        lane = np.arange(P)[None, :]
        keep = (lane < ni[:, None]).reshape(-1)
        idx = pv["idx"].astype(np.int64)[keep]
        wt = pv["wt"].astype(np.float32)[keep]
        src = np.repeat(rows, P)[keep]
        no = 0
        if cap_items:
            ok = idx < cap_items
            no = int((~ok).sum())
            idx, wt, src = idx[ok], wt[ok], src[ok]
        return (r["key_hi"][src], r["key_lo"][src], idx, wt, no)

    # ---- ctr rows
    cm = kinds == wire.DK_SVC_CTR
    crows = np.nonzero(cm)[0]
    if len(crows):
        ctr_vals = r["payload"][crows].reshape(len(crows), -1)[
            :, :24].copy().view("<f4")[:, :6]
    else:
        ctr_vals = np.zeros((0, 6), np.float32)
    Lc = size
    ctr = (_delta_pad(r["key_hi"][crows], Lc, np.uint32),
           _delta_pad(r["key_lo"][crows], Lc, np.uint32),
           _delta_pad(ctr_vals, Lc, np.float32),
           _delta_mask(len(crows), Lc))

    # ---- sparse-pair families
    hh, hl, hb, hw, no = pairs_of(kinds == wire.DK_SVC_HIST,
                                  resp_nbuckets)
    oob += no
    Lh = size * wire.DELTA_PAIRS
    sh, sl, sr, srk, no = pairs_of(kinds == wire.DK_SVC_HLL, hll_m_svc)
    oob += no
    gh_, gl_, gr, grk, no = pairs_of(kinds == wire.DK_GLOB_HLL,
                                     hll_m_glob)
    oob += no

    # ---- td sample rows
    tm = np.nonzero(kinds == wire.DK_SVC_TD)[0]
    S = wire.DELTA_SAMPLES
    if len(tm):
        pv = r["payload"][tm].reshape(len(tm), -1).copy().view("<f4")
        ni = np.minimum(nitem[tm], S)
        keep = (np.arange(S)[None, :] < ni[:, None]).reshape(-1)
        td_v = pv.reshape(-1)[keep]
        src = np.repeat(tm, S)[keep]
        td_hi, td_lo = r["key_hi"][src], r["key_lo"][src]
    else:
        td_v = np.empty(0, np.float32)
        td_hi = td_lo = np.empty(0, np.uint32)
    Lt = size * S

    # ---- flow rows
    fm = np.nonzero(kinds == wire.DK_FLOW)[0]
    F = wire.DELTA_FLOWS
    if len(fm):
        pv = r["payload"][fm].reshape(len(fm), -1)[
            :, : F * 12].copy().reshape(-1).view(wire.DELTA_FLOW_DT)
        ni = np.minimum(nitem[fm], F)
        keep = (np.arange(F)[None, :] < ni[:, None]).reshape(-1)
        fl_hi = pv["hi"][keep]
        fl_lo = pv["lo"][keep]
        fl_v = pv["val"].astype(np.float32)[keep]
    else:
        fl_hi = fl_lo = np.empty(0, np.uint32)
        fl_v = np.empty(0, np.float32)
    Lf = size * F

    # ---- dep rows
    dm = np.nonzero(kinds == wire.DK_DEP)[0]
    if len(dm):
        pv = r["payload"][dm].reshape(len(dm), -1)[
            :, :8].copy().view("<f4")
        dep_nconn, dep_bytes = pv[:, 0].copy(), pv[:, 1].copy()
    else:
        dep_nconn = dep_bytes = np.empty(0, np.float32)
    Ld = size

    # ---- residuals + unknown kinds (forward compat inside the subtype)
    resid = float(r["errb"][kinds == wire.DK_RESID].astype(
        np.float64).sum())
    known = np.isin(kinds, (wire.DK_SVC_CTR, wire.DK_SVC_HIST,
                            wire.DK_SVC_HLL, wire.DK_GLOB_HLL,
                            wire.DK_SVC_TD, wire.DK_FLOW, wire.DK_DEP,
                            wire.DK_RESID))
    n_unknown = int((~known).sum())

    # ---- unique svc keys across the svc-referencing families (the
    # one-upsert section; host attribution from the first mention)
    svcm = np.isin(kinds, (wire.DK_SVC_CTR, wire.DK_SVC_HIST,
                           wire.DK_SVC_HLL, wire.DK_SVC_TD))
    k64 = ((r["key_hi"][svcm].astype(np.uint64) << np.uint64(32))
           | r["key_lo"][svcm].astype(np.uint64))
    uk, first = np.unique(k64, return_index=True)
    uhost = r["host_id"][svcm][first].astype(np.int32)
    Lk = size

    if stats is not None:
        fills = (len(crows) + len(hb) + len(sr) + len(gr) + len(td_v)
                 + len(fl_v) + len(dm))
        stats.bump("preagg_lanes", fills)
        if len(crows):
            stats.bump("preagg_source_conn",
                       int(ctr_vals[:, 4].astype(np.float64).sum()))
            stats.bump("preagg_source_resp",
                       int(ctr_vals[:, 5].astype(np.float64).sum()))
        if oob:
            stats.bump("preagg_oob_items", oob)
        if n_unknown:
            stats.bump("preagg_unknown_kinds", n_unknown)

    u32 = np.uint32
    return DeltaBatch(
        svc_hi=_delta_pad((uk >> np.uint64(32)).astype(u32), Lk, u32),
        svc_lo=_delta_pad(uk.astype(u32), Lk, u32),
        svc_host=_delta_pad(uhost, Lk, np.int32),
        svc_valid=_delta_mask(len(uk), Lk),
        ctr_hi=ctr[0], ctr_lo=ctr[1], ctr_vals=ctr[2], ctr_valid=ctr[3],
        hist_hi=_delta_pad(hh, Lh, u32),
        hist_lo=_delta_pad(hl, Lh, u32),
        hist_bucket=_delta_pad(hb.astype(np.int32), Lh, np.int32),
        hist_w=_delta_pad(hw, Lh, np.float32),
        hist_valid=_delta_mask(len(hb), Lh),
        shll_hi=_delta_pad(sh, Lh, u32),
        shll_lo=_delta_pad(sl, Lh, u32),
        shll_reg=_delta_pad(sr.astype(np.int32), Lh, np.int32),
        shll_rank=_delta_pad(srk.astype(np.int32), Lh, np.int32),
        shll_valid=_delta_mask(len(sr), Lh),
        ghll_reg=_delta_pad(gr.astype(np.int32), Lh, np.int32),
        ghll_rank=_delta_pad(grk.astype(np.int32), Lh, np.int32),
        ghll_valid=_delta_mask(len(gr), Lh),
        td_hi=_delta_pad(td_hi, Lt, u32),
        td_lo=_delta_pad(td_lo, Lt, u32),
        td_val=_delta_pad(td_v.astype(np.float32), Lt, np.float32),
        td_valid=_delta_mask(len(td_v), Lt),
        flow_hi=_delta_pad(fl_hi, Lf, u32),
        flow_lo=_delta_pad(fl_lo, Lf, u32),
        flow_val=_delta_pad(fl_v, Lf, np.float32),
        flow_valid=_delta_mask(len(fl_v), Lf),
        dep_cli_hi=_delta_pad(r["aux_hi"][dm], Ld, u32),
        dep_cli_lo=_delta_pad(r["aux_lo"][dm], Ld, u32),
        dep_cli_svc=_delta_pad((r["flags"][dm] & 1).astype(bool), Ld,
                               bool),
        dep_ser_hi=_delta_pad(r["key_hi"][dm], Ld, u32),
        dep_ser_lo=_delta_pad(r["key_lo"][dm], Ld, u32),
        dep_nconn=_delta_pad(dep_nconn, Ld, np.float32),
        dep_bytes=_delta_pad(dep_bytes, Ld, np.float32),
        dep_valid=_delta_mask(len(dm), Ld),
        evicted_add=np.array([resid], np.float32),
    )


class PingBatch(NamedTuple):
    """Columnar TASK_PING microbatch (process-group keepalives): keys
    only — the fold refreshes ``task_last_tick`` for EXISTING rows and
    never inserts (the ref PING_TASK_AGGR ageing refresh)."""
    key_hi: np.ndarray        # aggr_task_id split
    key_lo: np.ndarray
    host_id: np.ndarray       # int32 (shard routing key)
    valid: np.ndarray


class TaskBatch(NamedTuple):
    """Columnar AGGR_TASK_STATE microbatch (process-group 5s sweep)."""
    key_hi: np.ndarray        # aggr_task_id split — process-group key
    key_lo: np.ndarray
    comm_hi: np.ndarray       # interned comm id (name resolution)
    comm_lo: np.ndarray
    rel_hi: np.ndarray        # related_listen_id (task→svc join)
    rel_lo: np.ndarray
    stats: np.ndarray         # (B, NTASKSTAT) float32, TASK_* indices
    state: np.ndarray         # int32 agent-classified state
    issue: np.ndarray         # int32 agent-classified issue source
    host_id: np.ndarray       # int32
    valid: np.ndarray


# stat column indices of ListenerBatch.stats
STAT_NQRYS = 0
STAT_TOTAL_RESP_MS = 1
STAT_NCONNS = 2
STAT_NCONNS_ACTIVE = 3
STAT_NTASKS = 4
STAT_KB_IN = 5
STAT_KB_OUT = 6
STAT_SER_ERRORS = 7
STAT_CLI_ERRORS = 8
STAT_TASKS_DELAY_US = 9
STAT_TASKS_CPUDELAY_US = 10
STAT_TASKS_BLKIODELAY_US = 11
STAT_USER_CPU = 12
STAT_SYS_CPU = 13
STAT_RSS_MB = 14
STAT_NTASKS_ISSUE = 15
NSTAT = 16

# task stat column indices of TaskBatch.stats (and AggState.task_stats)
TASK_TCP_KB = 0
TASK_TCP_CONNS = 1
TASK_CPU_PCT = 2
TASK_RSS_MB = 3
TASK_CPU_DELAY_MS = 4
TASK_VM_DELAY_MS = 5
TASK_BLKIO_DELAY_MS = 6
TASK_NTASKS = 7
TASK_NTASKS_ISSUE = 8
TASK_FORKS_SEC = 9
NTASKSTAT = 10

_TASK_STAT_FIELDS = (
    "tcp_kbytes", "tcp_conns", "total_cpu_pct", "rss_mb", "cpu_delay_msec",
    "vm_delay_msec", "blkio_delay_msec", "ntasks_total", "ntasks_issue",
    "forks_sec",
)

# host panel column indices of HostBatch.panel (and AggState.host_panel)
HOST_NTASKS = 0
HOST_NTASKS_ISSUE = 1
HOST_NTASKS_SEVERE = 2
HOST_NLISTEN = 3
HOST_NLISTEN_ISSUE = 4
HOST_NLISTEN_SEVERE = 5
HOST_CPU_ISSUE = 6
HOST_MEM_ISSUE = 7
HOST_SEVERE_CPU = 8
HOST_SEVERE_MEM = 9
HOST_STATE = 10
NHOSTCOL = 11

_HOST_PANEL_FIELDS = (
    "ntasks", "ntasks_issue", "ntasks_severe", "nlisten", "nlisten_issue",
    "nlisten_severe", "cpu_issue", "mem_issue", "severe_cpu_issue",
    "severe_mem_issue", "curr_state",
)

# cpu/mem column indices of CpuMemBatch.vals (and AggState.host_cm)
CM_CPU_PCT = 0
CM_USERCPU_PCT = 1
CM_SYSCPU_PCT = 2
CM_IOWAIT_PCT = 3
CM_MAX_CORE_CPU_PCT = 4
CM_CS_SEC = 5
CM_FORKS_SEC = 6
CM_PROCS_RUNNING = 7
CM_RSS_PCT = 8
CM_COMMIT_PCT = 9
CM_SWAP_FREE_PCT = 10
CM_PG_INOUT_SEC = 11
CM_SWAP_INOUT_SEC = 12
CM_ALLOCSTALL_SEC = 13
CM_OOM_KILLS = 14
CM_NCPUS = 15
NCM = 16

_CM_FIELDS = (
    "cpu_pct", "usercpu_pct", "syscpu_pct", "iowait_pct",
    "max_core_cpu_pct", "cs_sec", "forks_sec", "procs_running",
    "rss_pct", "commit_pct", "swap_free_pct", "pg_inout_sec",
    "swap_inout_sec", "allocstall_sec", "oom_kills", "ncpus",
)

_LISTENER_STAT_FIELDS = (
    "nqrys_5s", "total_resp_5sec", "nconns", "nconns_active", "ntasks",
    "curr_kbytes_inbound", "curr_kbytes_outbound", "ser_errors",
    "cli_errors", "tasks_delay_usec", "tasks_cpudelay_usec",
    "tasks_blkiodelay_usec", "tasks_user_cpu", "tasks_sys_cpu",
    "tasks_rss_mb", "ntasks_issue",
)


def take_raw_chunks(lst: list, want: int) -> tuple[list, int]:
    """Pop up to ``want`` records off a raw-record-array backlog as a
    LIST of array views — zero copies, no concatenation (the slab
    staging discipline shared by both runtimes). The columnar *_parts
    builders decode each chunk into the output slab at its lane offset,
    so a contiguous record array is never materialized."""
    out, got = [], 0
    while lst and got < want:
        a = lst[0]
        take = min(len(a), want - got)
        if take == len(a):
            lst.pop(0)
        else:
            lst[0] = a[take:]
            a = a[:take]
        out.append(a)
        got += take
    return out, got


def take_raw(lst: list, want: int, dtype) -> np.ndarray:
    """Contiguous-array form of :func:`take_raw_chunks` (the sharded
    runtime's host_id routing needs one array). Copy-free when the
    drain is served by a single staged array — the common small-drain
    path; only a multi-chunk take concatenates."""
    out, _ = take_raw_chunks(lst, want)
    if not out:
        return np.empty(0, dtype)
    return out[0] if len(out) == 1 else np.concatenate(out)


def _pad(a: np.ndarray, size: int, fill=0):
    out = np.full((size,) + a.shape[1:], fill, a.dtype)
    out[: len(a)] = a[:size]
    return out


def _check_fit(recs, size):
    """Batch builders never truncate silently: oversize input is a caller
    bug (wire.decode_frames already enforces per-type caps on the wire)."""
    if len(recs) > size:
        raise ValueError(
            f"{len(recs)} records exceed batch size {size}; split upstream")
    return len(recs)


def conn_batch(recs: np.ndarray, size: int = wire.MAX_CONNS_PER_BATCH
               ) -> ConnBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    svc_hi, svc_lo = split_u64(r["ser_glob_id"])
    # NAT-aware flow identity: when conntrack resolved a translated
    # tuple (nat_cli/nat_ser nonzero), both halves key on the POST-NAT
    # 5-tuple — the only view the two sides share (the reference pairs
    # via conntrack-translated tuples, common/gy_socket_stat.h NAT notes)
    nat_c = r["nat_cli"]["ip"].any(axis=1)
    nat_s = r["nat_ser"]["ip"].any(axis=1)
    eff_cli = np.where(nat_c[:, None], r["nat_cli"]["ip"], r["cli"]["ip"])
    eff_ser = np.where(nat_s[:, None], r["nat_ser"]["ip"], r["ser"]["ip"])
    eff_cport = np.where(nat_c, r["nat_cli"]["port"], r["cli"]["port"])
    eff_sport = np.where(nat_s, r["nat_ser"]["port"], r["ser"]["port"])
    cip_hi, cip_lo = fold_ip(np.ascontiguousarray(eff_cli))
    sip_hi, sip_lo = fold_ip(np.ascontiguousarray(eff_ser))
    proto = np.full(n, 6, np.uint32)  # TCP
    f_hi, f_lo = H.flow_key(cip_hi, cip_lo, sip_hi, sip_lo,
                            eff_cport.astype(np.uint32),
                            eff_sport.astype(np.uint32), proto)
    # client endpoint identity = address hash only (distinct clients)
    c_hi = H.fmix32(cip_hi ^ np.uint32(0xC11E57))
    c_lo = H.fmix32(cip_lo ^ c_hi)
    t_hi, t_lo = split_u64(r["cli_task_aggr_id"])
    rel_hi, rel_lo = split_u64(r["cli_related_listen_id"])
    closed = r["tusec_close"] > 0
    dur = np.where(closed, r["tusec_close"] - r["tusec_start"],
                   0).astype(np.float32)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return ConnBatch(
        svc_hi=_pad(svc_hi, size), svc_lo=_pad(svc_lo, size),
        flow_hi=_pad(f_hi, size), flow_lo=_pad(f_lo, size),
        cli_hi=_pad(c_hi, size), cli_lo=_pad(c_lo, size),
        cli_task_hi=_pad(t_hi, size), cli_task_lo=_pad(t_lo, size),
        cli_rel_hi=_pad(rel_hi, size), cli_rel_lo=_pad(rel_lo, size),
        bytes_sent=_pad(r["bytes_sent"].astype(np.float32), size),
        bytes_rcvd=_pad(r["bytes_rcvd"].astype(np.float32), size),
        duration_us=_pad(dur, size),
        host_id=_pad(r["host_id"].astype(np.int32), size),
        is_close=_pad(closed, size),
        is_accept=_pad((r["flags"] & 2) != 0, size),
        valid=valid,
    )


def alloc_conn_cols(size: int) -> dict:
    """Zeroed flat ConnBatch columns (everything but ``valid``) in the
    exact dtypes the device fold consumes — the preallocated buffers
    the native wire→columnar decoders write into."""
    u32 = lambda: np.zeros(size, np.uint32)     # noqa: E731
    f32 = lambda: np.zeros(size, np.float32)    # noqa: E731
    return dict(
        svc_hi=u32(), svc_lo=u32(), flow_hi=u32(), flow_lo=u32(),
        cli_hi=u32(), cli_lo=u32(), cli_task_hi=u32(),
        cli_task_lo=u32(), cli_rel_hi=u32(), cli_rel_lo=u32(),
        bytes_sent=f32(), bytes_rcvd=f32(), duration_us=f32(),
        host_id=np.zeros(size, np.int32),
        is_close=np.zeros(size, bool),
        is_accept=np.zeros(size, bool))


def _concat_chunks(chunks: list, dtype) -> np.ndarray:
    if not chunks:
        return np.empty(0, dtype)
    return chunks[0] if len(chunks) == 1 else np.concatenate(chunks)


def alloc_resp_cols(size: int) -> dict:
    """Zeroed flat RespBatch columns (everything but ``valid``) — the
    resp half of the preallocated staging-slab buffers."""
    return dict(svc_hi=np.zeros(size, np.uint32),
                svc_lo=np.zeros(size, np.uint32),
                resp_us=np.zeros(size, np.float32),
                host_id=np.zeros(size, np.int32))


def _reuse_cols(cols: dict, n: int, clear_to: int) -> None:
    """Reset a REUSED staging buffer to the all-zero-padding state the
    fresh allocators produce: lanes [n, clear_to) may hold stale values
    from a previous (larger) fill — zero them so a recycled slab is
    bit-identical to a freshly allocated one (every fold op masks by
    ``valid``, but determinism of the device INPUT is part of the
    replay/parity contract)."""
    if clear_to > n:
        for a in cols.values():
            a[n:clear_to] = 0


def conn_batch_parts(chunks: list, size: int, stats=None, out=None,
                     clear_to: int = 0) -> ConnBatch:
    """A LIST of raw TCP_CONN chunks (total ≤ size) → one flat padded
    ConnBatch: each chunk decodes straight into the preallocated column
    buffers at its lane offset (native path; no staging concatenate, no
    per-chunk pad+stack). ``out``: caller-owned column dict from
    :func:`alloc_conn_cols` (the double-buffered staging slabs) —
    reused across dispatches, with lanes [n, clear_to) re-zeroed.
    Fallback: the NumPy reference decoder over the concatenated chunks
    — bit-identical output either way."""
    n = sum(len(c) for c in chunks)
    if n > size:
        raise ValueError(
            f"{n} records exceed batch size {size}; split upstream")
    if native.available():
        cols = out if out is not None else alloc_conn_cols(size)
        if out is not None:
            _reuse_cols(cols, n, clear_to)
        off = 0
        ok = True
        for c in chunks:
            if len(c):
                if not native.decode_conn_into(c, cols, off):
                    ok = False       # library vanished mid-batch
                    break
                off += len(c)
        if ok:
            valid = np.zeros(size, bool)
            valid[:n] = True
            _count_path(stats, True, n)
            return ConnBatch(valid=valid, **cols)
    _count_path(stats, False, n)
    return conn_batch(_concat_chunks(chunks, wire.TCP_CONN_DT), size)


def resp_batch_parts(chunks: list, size: int, stats=None, out=None,
                     clear_to: int = 0) -> RespBatch:
    """A LIST of raw RESP_SAMPLE chunks (total ≤ size) → one flat
    padded RespBatch (see :func:`conn_batch_parts`; ``out`` is an
    :func:`alloc_resp_cols` dict)."""
    n = sum(len(c) for c in chunks)
    if n > size:
        raise ValueError(
            f"{n} records exceed batch size {size}; split upstream")
    if native.available():
        cols = out if out is not None else alloc_resp_cols(size)
        if out is not None:
            _reuse_cols(cols, n, clear_to)
        svc_hi, svc_lo = cols["svc_hi"], cols["svc_lo"]
        resp_us, host_id = cols["resp_us"], cols["host_id"]
        off = 0
        ok = True
        for c in chunks:
            if len(c):
                if not native.decode_resp_into(c, svc_hi, svc_lo,
                                               resp_us, host_id, off):
                    ok = False       # library vanished mid-batch
                    break
                off += len(c)
        if ok:
            valid = np.zeros(size, bool)
            valid[:n] = True
            _count_path(stats, True, n)
            return RespBatch(svc_hi=svc_hi, svc_lo=svc_lo,
                             resp_us=resp_us, host_id=host_id,
                             valid=valid)
    _count_path(stats, False, n)
    return resp_batch(_concat_chunks(chunks, wire.RESP_SAMPLE_DT), size)


def conn_batch_fast(recs: np.ndarray,
                    size: int = wire.MAX_CONNS_PER_BATCH,
                    stats=None) -> ConnBatch:
    """Columnar conn decode via the native C++ path when built
    (bit-identical; ~4x faster), else :func:`conn_batch`."""
    return conn_batch_parts([recs], size, stats=stats)


def resp_batch_fast(recs: np.ndarray,
                    size: int = wire.MAX_RESP_PER_BATCH,
                    stats=None) -> RespBatch:
    """Columnar resp decode via the native C++ path when built
    (bit-identical), else :func:`resp_batch`."""
    return resp_batch_parts([recs], size, stats=stats)


def conn_slab(recs, k: int, b: int, stats=None, out=None,
              clear_to: int = 0) -> ConnBatch:
    """TCP_CONN records (n ≤ k·b; an array or a list of chunk arrays)
    → ConnBatch with (k, b) stacked columns: ONE flat columnar decode
    + a free reshape, replacing k per-chunk decodes plus a tree-wide
    ``np.stack`` (the r3 feed-path hot spot). Record i lands in
    flattened lane i; padding collects at the slab tail — lane
    placement is only ever consumed through the ``valid`` mask, so
    tail-padding and per-chunk padding are equivalent to the fold.
    ``out``/``clear_to``: reuse a preallocated staging buffer (see
    :func:`conn_batch_parts`)."""
    chunks = recs if isinstance(recs, list) else [recs]
    cb = conn_batch_parts(chunks, k * b, stats=stats, out=out,
                          clear_to=clear_to)
    return ConnBatch(*(x.reshape(k, b) for x in cb))


def resp_slab(recs, k: int, b: int, stats=None, out=None,
              clear_to: int = 0) -> RespBatch:
    """RESP_SAMPLE records (n ≤ k·b; array or chunk list) → RespBatch
    with (k, b) stacked columns (see :func:`conn_slab`)."""
    chunks = recs if isinstance(recs, list) else [recs]
    rb = resp_batch_parts(chunks, k * b, stats=stats, out=out,
                          clear_to=clear_to)
    return RespBatch(*(x.reshape(k, b) for x in rb))


def resp_batch(recs: np.ndarray, size: int = wire.MAX_RESP_PER_BATCH
               ) -> RespBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    svc_hi, svc_lo = split_u64(r["glob_id"])
    valid = np.zeros(size, bool)
    valid[:n] = True
    return RespBatch(
        svc_hi=_pad(svc_hi, size), svc_lo=_pad(svc_lo, size),
        resp_us=_pad(r["resp_usec"].astype(np.float32), size),
        host_id=_pad(r["host_id"].astype(np.int32), size),
        valid=valid,
    )


def listener_batch(recs: np.ndarray,
                   size: int = wire.MAX_LISTENERS_PER_BATCH
                   ) -> ListenerBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    svc_hi, svc_lo = split_u64(r["glob_id"])
    stats = np.zeros((n, NSTAT), np.float32)
    for i, f in enumerate(_LISTENER_STAT_FIELDS):
        stats[:, i] = r[f].astype(np.float32)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return ListenerBatch(
        svc_hi=_pad(svc_hi, size), svc_lo=_pad(svc_lo, size),
        stats=_pad(stats, size),
        host_id=_pad(r["host_id"].astype(np.int32), size),
        valid=valid,
    )


def listener_batch_fast(recs: np.ndarray,
                        size: int = wire.MAX_LISTENERS_PER_BATCH,
                        stats=None) -> ListenerBatch:
    """Native columnar LISTENER_STATE decode (id split + one-pass stat
    matrix pack), else :func:`listener_batch` — bit-identical."""
    n = _check_fit(recs, size)
    if not native.available():
        _count_path(stats, False, n)
        return listener_batch(recs, size)
    r = recs[:n]
    svc_hi = np.zeros(size, np.uint32)
    svc_lo = np.zeros(size, np.uint32)
    stat_m = np.zeros((size, NSTAT), np.float32)
    host_id = np.zeros(size, np.int32)
    if not (native.split_u64_into(r, "glob_id", svc_hi, svc_lo)
            and native.pack_f32_into(r, _LISTENER_STAT_FIELDS, stat_m)
            and native.pack_i32_into(r, "host_id", host_id)):
        _count_path(stats, False, n)     # library vanished mid-batch
        return listener_batch(recs, size)
    valid = np.zeros(size, bool)
    valid[:n] = True
    _count_path(stats, True, n)
    return ListenerBatch(svc_hi=svc_hi, svc_lo=svc_lo, stats=stat_m,
                         host_id=host_id, valid=valid)


def task_batch(recs: np.ndarray, size: int = wire.MAX_TASKS_PER_BATCH
               ) -> TaskBatch:
    """AGGR_TASK_STATE records → columnar microbatch (ref
    AGGR_TASK_STATE_NOTIFY, gy_comm_proto.h:2114)."""
    n = _check_fit(recs, size)
    r = recs[:n]
    k_hi, k_lo = split_u64(r["aggr_task_id"])
    c_hi, c_lo = split_u64(r["comm_id"])
    rl_hi, rl_lo = split_u64(r["related_listen_id"])
    stats = np.zeros((n, NTASKSTAT), np.float32)
    for i, f in enumerate(_TASK_STAT_FIELDS):
        stats[:, i] = r[f].astype(np.float32)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return TaskBatch(
        key_hi=_pad(k_hi, size), key_lo=_pad(k_lo, size),
        comm_hi=_pad(c_hi, size), comm_lo=_pad(c_lo, size),
        rel_hi=_pad(rl_hi, size), rel_lo=_pad(rl_lo, size),
        stats=_pad(stats, size),
        state=_pad(r["curr_state"].astype(np.int32), size),
        issue=_pad(r["curr_issue"].astype(np.int32), size),
        host_id=_pad(r["host_id"].astype(np.int32), size),
        valid=valid,
    )


def task_batch_fast(recs: np.ndarray,
                    size: int = wire.MAX_TASKS_PER_BATCH,
                    stats=None) -> TaskBatch:
    """Native columnar AGGR_TASK_STATE decode, else :func:`task_batch`
    — bit-identical."""
    n = _check_fit(recs, size)
    if not native.available():
        _count_path(stats, False, n)
        return task_batch(recs, size)
    r = recs[:n]
    u32 = lambda: np.zeros(size, np.uint32)     # noqa: E731
    i32 = lambda: np.zeros(size, np.int32)      # noqa: E731
    cols = dict(key_hi=u32(), key_lo=u32(), comm_hi=u32(),
                comm_lo=u32(), rel_hi=u32(), rel_lo=u32())
    stat_m = np.zeros((size, NTASKSTAT), np.float32)
    state, issue, host_id = i32(), i32(), i32()
    if not (native.split_u64_into(r, "aggr_task_id", cols["key_hi"],
                                  cols["key_lo"])
            and native.split_u64_into(r, "comm_id", cols["comm_hi"],
                                      cols["comm_lo"])
            and native.split_u64_into(r, "related_listen_id",
                                      cols["rel_hi"], cols["rel_lo"])
            and native.pack_f32_into(r, _TASK_STAT_FIELDS, stat_m)
            and native.pack_i32_into(r, "curr_state", state)
            and native.pack_i32_into(r, "curr_issue", issue)
            and native.pack_i32_into(r, "host_id", host_id)):
        _count_path(stats, False, n)     # library vanished mid-batch
        return task_batch(recs, size)
    valid = np.zeros(size, bool)
    valid[:n] = True
    _count_path(stats, True, n)
    return TaskBatch(stats=stat_m, state=state, issue=issue,
                     host_id=host_id, valid=valid, **cols)


def ping_batch(recs: np.ndarray, size: int = wire.MAX_PINGS_PER_BATCH,
               stats=None) -> PingBatch:
    """TASK_PING records → columnar keepalive microbatch (ref
    PING_TASK_AGGR, gy_comm_proto.h:1384). Key split rides the native
    helper when available — same numpy fallback discipline as the
    other builders."""
    n = _check_fit(recs, size)
    r = recs[:n]
    k_hi = np.zeros(size, np.uint32)
    k_lo = np.zeros(size, np.uint32)
    host_id = np.zeros(size, np.int32)
    used_native = (native.available()
                   and native.split_u64_into(r, "aggr_task_id",
                                             k_hi, k_lo)
                   and native.pack_i32_into(r, "host_id", host_id))
    if not used_native:
        hi, lo = split_u64(r["aggr_task_id"])
        k_hi[:n], k_lo[:n] = hi, lo
        host_id[:n] = r["host_id"].astype(np.int32)
    _count_path(stats, used_native, n)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return PingBatch(key_hi=k_hi, key_lo=k_lo, host_id=host_id,
                     valid=valid)


def drain_chunks(recs: dict, conn_batch: int, resp_batch: int,
                 listener_batch: int):
    """Drained records-by-subtype → a fold plan of lane-sized chunks.

    Shared by the single-node and sharded runtimes so the per-type
    chunking discipline (conn/resp paired into aligned microbatches;
    every stream split at its lane size) lives in exactly one place.
    Yields ``(kind, *chunks)`` with kind in ``connresp | listener |
    host | task | names``.
    """
    conn = recs.get(wire.NOTIFY_TCP_CONN)
    resp = recs.get(wire.NOTIFY_RESP_SAMPLE)
    nc = 0 if conn is None else len(conn)
    nr = 0 if resp is None else len(resp)
    npair = max(-(-nc // conn_batch), -(-nr // resp_batch)) \
        if (nc or nr) else 0
    for i in range(npair):
        cchunk = conn[i * conn_batch:(i + 1) * conn_batch] if nc \
            else np.empty(0, wire.TCP_CONN_DT)
        rchunk = resp[i * resp_batch:(i + 1) * resp_batch] if nr \
            else np.empty(0, wire.RESP_SAMPLE_DT)
        yield ("connresp", cchunk, rchunk)
    lst = recs.get(wire.NOTIFY_LISTENER_STATE)
    if lst is not None:
        for i in range(0, len(lst), listener_batch):
            yield ("listener", lst[i:i + listener_batch])
    hst = recs.get(wire.NOTIFY_HOST_STATE)
    if hst is not None:
        for i in range(0, len(hst), wire.MAX_HOSTS_PER_BATCH):
            yield ("host", hst[i:i + wire.MAX_HOSTS_PER_BATCH])
    tsk = recs.get(wire.NOTIFY_AGGR_TASK_STATE)
    if tsk is not None:
        for i in range(0, len(tsk), wire.MAX_TASKS_PER_BATCH):
            yield ("task", tsk[i:i + wire.MAX_TASKS_PER_BATCH])
    cm = recs.get(wire.NOTIFY_CPU_MEM_STATE)
    if cm is not None:
        for i in range(0, len(cm), wire.MAX_CPUMEM_PER_BATCH):
            yield ("cpumem", cm[i:i + wire.MAX_CPUMEM_PER_BATCH])
    tr = recs.get(wire.NOTIFY_REQ_TRACE)
    if tr is not None:
        for i in range(0, len(tr), wire.MAX_TRACE_PER_BATCH):
            yield ("trace", tr[i:i + wire.MAX_TRACE_PER_BATCH])
    li = recs.get(wire.NOTIFY_LISTENER_INFO)
    if li is not None:
        yield ("listener_info", li)
    hi = recs.get(wire.NOTIFY_HOST_INFO)
    if hi is not None:
        yield ("host_info", hi)
    cg = recs.get(wire.NOTIFY_CGROUP_STATE)
    if cg is not None:
        yield ("cgroup", cg)
    mnt = recs.get(wire.NOTIFY_MOUNT_STATE)
    if mnt is not None:
        yield ("mount", mnt)
    nif = recs.get(wire.NOTIFY_NETIF_STATE)
    if nif is not None:
        yield ("netif", nif)
    png = recs.get(wire.NOTIFY_TASK_PING)
    if png is not None:
        for i in range(0, len(png), wire.MAX_PINGS_PER_BATCH):
            yield ("ping", png[i:i + wire.MAX_PINGS_PER_BATCH])
    dl = recs.get(wire.NOTIFY_SKETCH_DELTA)
    if dl is not None:
        for i in range(0, len(dl), DELTA_LANES_DEFAULT):
            yield ("delta", dl[i:i + DELTA_LANES_DEFAULT])
    ast = recs.get(wire.NOTIFY_AGENT_STATS)
    if ast is not None:
        yield ("agent_stats", ast)
    nm = recs.get(wire.NOTIFY_NAME_INTERN)
    if nm is not None:
        yield ("names", nm)


def resp_from_trace(recs: np.ndarray) -> np.ndarray:
    """REQ_TRACE records → RESP_SAMPLE records (the trace→resp bridge).

    Every parsed transaction carries a measured request→response
    latency; replaying it into the per-service response stream makes
    the svcstate loghist/t-digest percentiles measure REAL latencies
    wherever traces exist (pcap files, traced conns, stock-partha
    streams) — the role of the reference's eBPF response probes
    (``partha/gy_ebpf_kernel.bpf.c:836-931`` feeding
    ``common/gy_socket_stat.cc:1554``), with the protocol parser as
    the observation point instead of a kprobe."""
    out = np.zeros(len(recs), wire.RESP_SAMPLE_DT)
    out["glob_id"] = recs["svc_glob_id"]
    out["resp_usec"] = recs["resp_usec"]
    out["host_id"] = recs["host_id"]
    return out


def trace_batch(recs: np.ndarray, size: int = wire.MAX_TRACE_PER_BATCH
                ) -> TraceBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    svc_hi, svc_lo = split_u64(r["svc_glob_id"])
    api_hi, api_lo = split_u64(r["api_id"])
    # per-API slab key: one mixed 64-bit id over (svc, api)
    k_hi = H.mix64(svc_hi ^ api_hi, svc_lo, 0xA91D)
    k_lo = H.mix64(api_lo, svc_lo ^ api_lo, 0x77E1)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return TraceBatch(
        key_hi=_pad(k_hi, size), key_lo=_pad(k_lo, size),
        svc_hi=_pad(svc_hi, size), svc_lo=_pad(svc_lo, size),
        api_hi=_pad(api_hi, size), api_lo=_pad(api_lo, size),
        resp_us=_pad(r["resp_usec"].astype(np.float32), size),
        byin=_pad(r["bytes_in"].astype(np.float32), size),
        byout=_pad(r["bytes_out"].astype(np.float32), size),
        proto=_pad(r["proto"].astype(np.int32), size),
        is_err=_pad(r["is_error"].astype(bool), size),
        host_id=_pad(r["host_id"].astype(np.int32), size),
        valid=valid,
    )


def cpumem_batch(recs: np.ndarray, size: int = wire.MAX_CPUMEM_PER_BATCH
                 ) -> CpuMemBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    vals = np.zeros((n, NCM), np.float32)
    for i, f in enumerate(_CM_FIELDS):
        vals[:, i] = r[f].astype(np.float32)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return CpuMemBatch(
        host_id=_pad(r["host_id"].astype(np.int32), size),
        vals=_pad(vals, size),
        valid=valid,
    )


def cpumem_batch_fast(recs: np.ndarray,
                      size: int = wire.MAX_CPUMEM_PER_BATCH,
                      stats=None) -> CpuMemBatch:
    """Native columnar CPU_MEM_STATE decode, else :func:`cpumem_batch`
    — bit-identical."""
    n = _check_fit(recs, size)
    if not native.available():
        _count_path(stats, False, n)
        return cpumem_batch(recs, size)
    r = recs[:n]
    vals = np.zeros((size, NCM), np.float32)
    host_id = np.zeros(size, np.int32)
    if not (native.pack_f32_into(r, _CM_FIELDS, vals)
            and native.pack_i32_into(r, "host_id", host_id)):
        _count_path(stats, False, n)     # library vanished mid-batch
        return cpumem_batch(recs, size)
    valid = np.zeros(size, bool)
    valid[:n] = True
    _count_path(stats, True, n)
    return CpuMemBatch(host_id=host_id, vals=vals, valid=valid)


def host_batch(recs: np.ndarray, size: int = wire.MAX_HOSTS_PER_BATCH
               ) -> HostBatch:
    n = _check_fit(recs, size)
    r = recs[:n]
    panel = np.zeros((n, NHOSTCOL), np.float32)
    for i, f in enumerate(_HOST_PANEL_FIELDS):
        panel[:, i] = r[f].astype(np.float32)
    valid = np.zeros(size, bool)
    valid[:n] = True
    return HostBatch(
        host_id=_pad(r["host_id"].astype(np.int32), size),
        panel=_pad(panel, size),
        valid=valid,
    )


def host_batch_fast(recs: np.ndarray,
                    size: int = wire.MAX_HOSTS_PER_BATCH,
                    stats=None) -> HostBatch:
    """Native columnar HOST_STATE decode, else :func:`host_batch` —
    bit-identical."""
    n = _check_fit(recs, size)
    if not native.available():
        _count_path(stats, False, n)
        return host_batch(recs, size)
    r = recs[:n]
    panel = np.zeros((size, NHOSTCOL), np.float32)
    host_id = np.zeros(size, np.int32)
    if not (native.pack_f32_into(r, _HOST_PANEL_FIELDS, panel)
            and native.pack_i32_into(r, "host_id", host_id)):
        _count_path(stats, False, n)     # library vanished mid-batch
        return host_batch(recs, size)
    valid = np.zeros(size, bool)
    valid[:n] = True
    _count_path(stats, True, n)
    return HostBatch(host_id=host_id, panel=panel, valid=valid)
