"""Ingest tier: wire framing, record decode, columnar microbatch packing.

The serialization boundary of the framework (SURVEY §2.4): agents stream
length-prefixed little-endian binary event batches; the ingest tier deframes,
decodes to structured arrays, and packs fixed-shape columnar microbatches for
the jitted device engine. A C++ fast path lives in ``ingest/native``; the
numpy path here is the reference implementation and test oracle.
"""

from gyeeta_tpu.ingest import wire  # noqa: F401
from gyeeta_tpu.ingest import decode  # noqa: F401
