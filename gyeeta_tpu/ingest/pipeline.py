"""Feed pipeline: deframe/decode on a worker thread (L1/L2 split).

The reference splits ingest across L1 threads (recv + validate) and
L2 handlers (process) connected by MPMC queues
(``server/gy_mconnhdlr.h:53-75``, the L1→DB_WRITE_ARR→L2 pipeline).
The single-thread runtime already overlaps HOST decode with DEVICE
folds via async dispatch; this optional pipeline adds the L1/L2
thread split for MULTI-CORE hosts: the native deframer and columnar
decoders release the GIL, so a dedicated worker deframes buffer N+1
while the serving thread dispatches buffer N's folds.

Ordering and framing semantics match direct ``feed`` — ONE worker
owns the partial-frame resume buffer, the bounded queue preserves
byte-stream order, and the serving thread folds results in submission
order. ``flush()`` barriers the pipeline then the runtime, so
cadence/query boundaries see every submitted byte.

Divergences from the direct path, by design:
- **Poison frames do not close connections.** Decode completes after
  ``feed`` returns, and the pipeline is shared across conns, so a
  deep-validation failure cannot be attributed back to its sender.
  The worker resyncs its framing and the failure is COUNTED
  (``frames_bad`` + ``pipeline_frame_errors``) instead of raised.
- **Capture recording moves into the pipeline** (pass ``recorder``):
  only buffers that DECODED cleanly are recorded, preserving the
  "recorded bytes are replayable" invariant that a caller-side write
  could not (it would record bytes whose validation hadn't happened
  yet).
- Deframe latency is observed on the worker and recorded into the
  stats histogram from the serving thread (selfstats stays accurate
  in pipeline mode).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from gyeeta_tpu.ingest import native, wire


class FeedPipeline:
    """Bounded 2-stage pipeline in front of a Runtime.

    ``feed(buf)`` submits bytes to the decode worker and folds any
    COMPLETED deframe results; at most ``depth`` buffers ride the
    queue before submission blocks on the oldest result (natural
    backpressure toward the socket, like the reference's bounded
    pools). Returns records folded BY THIS CALL (drained results),
    not necessarily from ``buf`` itself.
    """

    def __init__(self, rt, depth: int = 4, recorder=None):
        self._rt = rt
        self._ex = ThreadPoolExecutor(1, "gyt-decode")
        self._fifo: deque = deque()
        self.depth = depth
        self._recorder = recorder
        self._pending = b""              # worker-owned framing state
        self.n_frame_errors = 0

    def _deframe(self, buf: bytes):
        """Runs ON THE WORKER: native deframe with resume framing."""
        t0 = time.perf_counter()
        data = (self._pending + buf) if self._pending else buf
        try:
            recs, consumed, unknown = native.drain2(data)
        except wire.FrameError:
            self._pending = b""          # poison frame: resync
            raise
        self._pending = data[consumed:]
        if unknown:
            self._rt.stats.bump("records_unknown_subtype", unknown)
        return buf, recs, (time.perf_counter() - t0) * 1e3

    def _fold_one(self) -> int:
        fut, hid, conn_id = self._fifo.popleft()
        try:
            buf, recs, dt_ms = fut.result()
        except wire.FrameError:
            # see module docstring: counted, not raised — the sender
            # cannot be identified once decode is asynchronous
            self.n_frame_errors += 1
            self._rt.stats.bump("frames_bad")
            self._rt.stats.bump("pipeline_frame_errors")
            return 0
        self._rt.stats.observe_ms("deframe", dt_ms)
        if self._recorder is not None:
            self._recorder.write(buf)    # validated ⇒ replayable
        # WAL append mirrors the recorder's invariant (validated ⇒
        # replayable); the direct path appends inside Runtime.feed,
        # this path feeds records, so the journal hook lives here
        j = getattr(self._rt, "journal", None)
        if j is not None and not getattr(self._rt, "_journal_replaying",
                                         False):
            j.append(buf, hid=hid, conn_id=conn_id,
                     tick=getattr(self._rt, "_tick_no", 0))
        # fold-side visibility (the deframe span above only covers the
        # worker): the serving thread's decode+dispatch wall per buffer
        # rides its own span + timing hist, so the decode/fold overlap
        # win is observable in `obs top` and /metrics (stage
        # `pipeline_fold_dispatch`; the runtime's own `fold_dispatch`
        # hist times just the device dispatch inside this window)
        nrec = sum(len(a) for a in recs.values())
        t1 = time.perf_counter()
        spans = getattr(self._rt, "spans", None)
        if spans is not None:
            with spans.span("fold_dispatch", nrec=nrec,
                            path="native" if native.available()
                            else "python"):
                n = self._rt.ingest_records(recs)
        else:
            n = self._rt.ingest_records(recs)
        self._rt.stats.observe_ms("pipeline_fold_dispatch",
                                  (time.perf_counter() - t1) * 1e3)
        return n

    def feed(self, buf: bytes, hid: int = 0, conn_id: int = 0) -> int:
        self._fifo.append((self._ex.submit(self._deframe, buf),
                           hid, conn_id))
        n = 0
        # fold everything already decoded; block only at depth
        while self._fifo and (self._fifo[0][0].done()
                              or len(self._fifo) > self.depth):
            n += self._fold_one()
        return n

    def flush(self) -> int:
        """Barrier: fold every submitted buffer, then runtime flush."""
        n = 0
        while self._fifo:
            n += self._fold_one()
        self._rt.flush()
        return n

    def close(self) -> None:
        self.flush()
        self._ex.shutdown(wait=True)
