"""gy_comm_proto ingest adapter: stock-partha frames → GYT records.

SURVEY M1's loose end (VERDICT r3 #5): the reference wire format is the
serialization boundary — a stock partha agent should be able to connect
later unmodified. GYT's own frames are fixed-width and interned
(``wire.py``); the reference's are C++ structs with TRAILING
VARIABLE-LENGTH STRINGS (cmdline / issue strings) and per-record
padding. This module decodes the reference layouts into GYT record
arrays (+ NAME_INTERN announcements for every string), so reference
traffic folds through the exact same ``Runtime.feed`` path.

Layouts transcribed as numpy dtypes from the reference ABI (protocol
contract, little-endian throughout ``gy_comm_proto.h:43``):

- ``COMM_HEADER``           — gy_comm_proto.h:336 (magic/total/type/pad)
- ``EVENT_NOTIFY``          — gy_comm_proto.h:486 (subtype/nevents)
- ``TCP_CONN_NOTIFY``       — gy_comm_proto.h:1665 (+ trailing cmdline)
- ``LISTENER_STATE_NOTIFY`` — gy_comm_proto.h:2183 (+ issue string)
- ``AGGR_TASK_STATE_NOTIFY``— gy_comm_proto.h:2114 (+ issue string)
- ``IP_PORT``/``GY_IP_ADDR``— gy_common_inc.h:11162 / :10492 (packed
  u128 address + u32 v4 + af/flags, 8-aligned, port + tail pad)

Only the partha→madhava event subtypes the engine folds are adapted;
unknown subtypes are skipped frame-whole (forward compatibility — the
reference's recv loop does the same for unhandled events).
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils.intern import InternTable

# ----------------------------------------------------- reference constants
REF_MAGIC_PM = 0x05666605        # PM_HDR_MAGIC (partha → madhava)
REF_MAGICS = {0x05555505, 0x05666605, 0x05777705, 0x05888805}

REF_COMM_EVENT_NOTIFY = 14       # COMM_TYPE_E

REF_NOTIFY_LISTENER_STATE = 0x309
REF_NOTIFY_TCP_CONN = 0x30C
REF_NOTIFY_AGGR_TASK_STATE = 0x310

AF_INET, AF_INET6 = 2, 10

REF_HEADER_DT = np.dtype([       # COMM_HEADER, gy_comm_proto.h:336
    ("magic", "<u4"), ("total_sz", "<u4"),
    ("data_type", "<u4"), ("padding_sz", "<u4"),
])

REF_EVENT_NOTIFY_DT = np.dtype([  # EVENT_NOTIFY, gy_comm_proto.h:486
    ("subtype", "<u4"), ("nevents", "<u4"),
])

# GY_IP_ADDR (gy_common_inc.h:10492, packed+aligned(8)) inside IP_PORT
# (gy_common_inc.h:11162): 16B raw v6 address + embedded v4 + af/flags,
# then the port and 8-align tail padding
REF_IP_PORT_DT = np.dtype([
    ("ip128", "u1", (16,)),      # in6_addr raw bytes (network order)
    ("ip32_be", "<u4"),          # v4 address, network byte order
    ("aftype", "<i2"), ("ipflags", "<u2"),
    ("port", "<u2"), ("pad", "u1", (6,)),
])
assert REF_IP_PORT_DT.itemsize == 32

# TCP_CONN_NOTIFY fixed part (gy_comm_proto.h:1665); cli_cmdline_len_
# bytes of cmdline + padding_len_ bytes follow each record
REF_TCP_CONN_DT = np.dtype([
    ("cli", REF_IP_PORT_DT), ("ser", REF_IP_PORT_DT),
    ("nat_cli", REF_IP_PORT_DT), ("nat_ser", REF_IP_PORT_DT),
    ("tusec_start", "<u8"), ("tusec_close", "<u8"),
    ("cli_task_aggr_id", "<u8"), ("cli_related_listen_id", "<u8"),
    ("cli_madhava_id", "<u8"),
    ("machid_hi", "<u8"), ("machid_lo", "<u8"),   # GY_MACHINE_ID pair
    ("ser_related_listen_id", "<u8"), ("ser_glob_id", "<u8"),
    ("ser_madhava_id", "<u8"),
    ("bytes_sent", "<u8"), ("bytes_rcvd", "<u8"),
    ("cli_pid", "<i4"), ("ser_pid", "<i4"),
    ("ser_conn_hash", "<u4"), ("ser_sock_inode", "<u4"),
    ("cli_comm", "S16"), ("ser_comm", "S16"),
    ("cli_cmdline_len", "<u2"),
    ("is_connect", "u1"), ("is_accept", "u1"), ("is_loopback", "u1"),
    ("is_pre_existing", "u1"), ("notified_before", "u1"),
    ("padding_len", "u1"),
])
assert REF_TCP_CONN_DT.itemsize == 280

# LISTENER_STATE_NOTIFY fixed part (gy_comm_proto.h:2183)
REF_LISTENER_STATE_DT = np.dtype([
    ("glob_id", "<u8"),
    ("nqrys_5s", "<u4"), ("total_resp_5sec", "<u4"), ("nconns", "<u4"),
    ("nconns_active", "<u4"), ("ntasks", "<u4"),
    ("p95_5s_resp_ms", "<u4"), ("p95_5min_resp_ms", "<u4"),
    ("curr_kbytes_inbound", "<u4"), ("curr_kbytes_outbound", "<u4"),
    ("ser_errors", "<u4"), ("cli_errors", "<u4"),
    ("tasks_delay_usec", "<u4"), ("tasks_cpudelay_usec", "<u4"),
    ("tasks_blkiodelay_usec", "<u4"), ("tasks_user_cpu", "<u4"),
    ("tasks_sys_cpu", "<u4"), ("tasks_rss_mb", "<u4"),
    ("ntasks_issue", "<u2"),
    ("is_http_svc", "u1"), ("curr_state", "u1"), ("curr_issue", "u1"),
    ("issue_bit_hist", "u1"), ("high_resp_bit_hist", "u1"),
    ("last_issue_subsrc", "u1"), ("query_flags", "u1"),
    ("issue_string_len", "u1"), ("padding_len", "u1"),
    ("tailpad", "u1", (1,)),
])
assert REF_LISTENER_STATE_DT.itemsize == 88

# AGGR_TASK_STATE_NOTIFY fixed part (gy_comm_proto.h:2114)
REF_AGGR_TASK_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("onecomm", "S16"),
    ("pid_arr", "<i4", (2,)),
    ("tcp_kbytes", "<u4"), ("tcp_conns", "<u4"),
    ("total_cpu_pct", "<f4"), ("rss_mb", "<u4"),
    ("cpu_delay_msec", "<u4"), ("vm_delay_msec", "<u4"),
    ("blkio_delay_msec", "<u4"),
    ("ntasks_total", "<u2"), ("ntasks_issue", "<u2"),
    ("curr_state", "u1"), ("curr_issue", "u1"),
    ("issue_bit_hist", "u1"), ("severe_issue_bit_hist", "u1"),
    ("issue_string_len", "u1"), ("padding_len", "u1"),
    ("tailpad", "u1", (2,)),
])
assert REF_AGGR_TASK_DT.itemsize == 72

_HSZ = REF_HEADER_DT.itemsize
_ESZ = REF_EVENT_NOTIFY_DT.itemsize


class RefFrameError(wire.FrameError):
    pass


def _check_nevents(nevents: int, payload: bytes, fsz: int, cap: int,
                   what: str) -> None:
    """The wire's u4 nevents is attacker-controlled: bound it by the
    reference batch cap AND by what the payload could possibly hold
    (each record is ≥ fsz bytes) BEFORE allocating output — the GYT
    decoder enforces the same caps in ``wire.decode_frames``."""
    if nevents > cap or nevents * fsz > len(payload):
        raise RefFrameError(
            f"{what}: nevents {nevents} exceeds cap {cap} or "
            f"payload {len(payload)}B")


def _ip16(rec) -> bytes:
    """One REF_IP_PORT → the wire's 16-byte (v4-mapped) address."""
    if int(rec["aftype"]) == AF_INET:
        return (b"\x00" * 10 + b"\xff\xff"
                + int(rec["ip32_be"]).to_bytes(4, "little"))
        # ip32_be_ holds network-order bytes; little-endian re-pack of
        # the u32 value restores the original byte sequence
    return rec["ip128"].tobytes()


def _copy_ip_port(dst, src) -> None:
    dst["ip"] = np.frombuffer(_ip16(src), np.uint8)
    dst["port"] = src["port"]


def decode_tcp_conn(payload: bytes, nevents: int, host_id: int
                    ) -> tuple[np.ndarray, list]:
    """Variable-length TCP_CONN_NOTIFY walk → GYT TCP_CONN records +
    intern entries for comm/cmdline strings."""
    fsz = REF_TCP_CONN_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_CONNS_PER_BATCH,
                   "tcp_conn")
    out = np.zeros(nevents, wire.TCP_CONN_DT)
    names: list = []
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"tcp_conn record {i} truncated")
        rec = np.frombuffer(payload, REF_TCP_CONN_DT, count=1,
                            offset=off)[0]
        cmdlen = int(rec["cli_cmdline_len"])
        end = off + fsz + cmdlen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"tcp_conn record {i} overflows frame")
        r = out[i]
        for f in ("cli", "ser", "nat_cli", "nat_ser"):
            _copy_ip_port(r[f], rec[f])
        for f in ("tusec_start", "tusec_close", "cli_task_aggr_id",
                  "cli_related_listen_id", "cli_madhava_id",
                  "ser_related_listen_id", "ser_glob_id",
                  "ser_madhava_id", "bytes_sent", "bytes_rcvd",
                  "cli_pid", "ser_pid", "ser_conn_hash",
                  "ser_sock_inode"):
            r[f] = rec[f]
        r["peer_machine_id_hi"] = rec["machid_hi"]
        r["peer_machine_id_lo"] = rec["machid_lo"]
        for src_f, dst_f in (("cli_comm", "cli_comm_id"),
                             ("ser_comm", "ser_comm_id")):
            s = rec[src_f].tobytes().split(b"\x00", 1)[0].decode(
                "utf-8", "replace")
            if s:
                nid = InternTable.intern(s, wire.NAME_KIND_COMM)
                r[dst_f] = nid
                names.append((wire.NAME_KIND_COMM, nid, s))
        if cmdlen:
            cmdline = payload[off + fsz: off + fsz + cmdlen].split(
                b"\x00", 1)[0].decode("utf-8", "replace")
            nid = InternTable.intern(cmdline, wire.NAME_KIND_MISC)
            r["cli_cmdline_id"] = nid
            names.append((wire.NAME_KIND_MISC, nid, cmdline))
        r["host_id"] = host_id
        r["flags"] = (int(rec["is_connect"]) * 1
                      | int(rec["is_accept"]) * 2
                      | int(rec["is_loopback"]) * 4
                      | int(rec["is_pre_existing"]) * 8
                      | int(rec["notified_before"]) * 16)
        off = end
    return out, names


def decode_listener_state(payload: bytes, nevents: int, host_id: int
                          ) -> tuple[np.ndarray, list]:
    fsz = REF_LISTENER_STATE_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_LISTENERS_PER_BATCH,
                   "listener_state")
    out = np.zeros(nevents, wire.LISTENER_STATE_DT)
    names: list = []
    off = 0
    shared = set(wire.LISTENER_STATE_DT.names) \
        & set(REF_LISTENER_STATE_DT.names)
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"listener_state record {i} truncated")
        rec = np.frombuffer(payload, REF_LISTENER_STATE_DT, count=1,
                            offset=off)[0]
        ilen = int(rec["issue_string_len"])
        end = off + fsz + ilen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(
                f"listener_state record {i} overflows frame")
        r = out[i]
        for f in shared:
            if f != "pad":
                r[f] = rec[f]
        if ilen:
            s = payload[off + fsz: off + fsz + ilen].split(
                b"\x00", 1)[0].decode("utf-8", "replace")
            nid = InternTable.intern(s, wire.NAME_KIND_MISC)
            r["issue_string_id"] = nid
            names.append((wire.NAME_KIND_MISC, nid, s))
        r["host_id"] = host_id
        off = end
    return out, names


def decode_aggr_task(payload: bytes, nevents: int, host_id: int
                     ) -> tuple[np.ndarray, list]:
    fsz = REF_AGGR_TASK_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_TASKS_PER_BATCH,
                   "aggr_task")
    out = np.zeros(nevents, wire.AGGR_TASK_DT)
    names: list = []
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"aggr_task record {i} truncated")
        rec = np.frombuffer(payload, REF_AGGR_TASK_DT, count=1,
                            offset=off)[0]
        ilen = int(rec["issue_string_len"])
        end = off + fsz + ilen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"aggr_task record {i} overflows frame")
        r = out[i]
        for f in ("aggr_task_id", "tcp_kbytes", "tcp_conns",
                  "total_cpu_pct", "rss_mb", "cpu_delay_msec",
                  "vm_delay_msec", "blkio_delay_msec", "ntasks_total",
                  "ntasks_issue", "curr_state", "curr_issue"):
            r[f] = rec[f]
        comm = rec["onecomm"].tobytes().split(b"\x00", 1)[0].decode(
            "utf-8", "replace")
        if comm:
            nid = InternTable.intern(comm, wire.NAME_KIND_COMM)
            r["comm_id"] = nid
            names.append((wire.NAME_KIND_COMM, nid, comm))
        # the reference resolves task→listener linkage server-side via
        # its listen-taskmap events; absent here → 0 (unlinked)
        r["host_id"] = host_id
        off = end
    return out, names


_DECODER_OF = {
    REF_NOTIFY_TCP_CONN: (decode_tcp_conn, wire.NOTIFY_TCP_CONN),
    REF_NOTIFY_LISTENER_STATE: (decode_listener_state,
                                wire.NOTIFY_LISTENER_STATE),
    REF_NOTIFY_AGGR_TASK_STATE: (decode_aggr_task,
                                 wire.NOTIFY_AGGR_TASK_STATE),
}


def adapt(buf: bytes, host_id: int) -> tuple[bytes, int]:
    """Reference byte stream → GYT wire frames, ready for
    ``Runtime.feed``.

    Walks COMM_HEADER frames (trailing partial frame left for the
    caller, epoll-resume semantics like ``wire.decode_frames``);
    adapts known partha→madhava event subtypes, emits NAME_INTERN
    frames for every trailing string, and skips unknown subtypes
    frame-whole. Returns ``(gyt_bytes, consumed)``.
    """
    out: list[bytes] = []
    off = 0
    n = len(buf)
    while off + _HSZ <= n:
        hdr = np.frombuffer(buf, REF_HEADER_DT, count=1, offset=off)[0]
        if int(hdr["magic"]) not in REF_MAGICS:
            raise RefFrameError(f"bad reference magic "
                                f"0x{int(hdr['magic']):08x}")
        total = int(hdr["total_sz"])
        if total < _HSZ or total >= wire.MAX_COMM_DATA_SZ:
            raise RefFrameError(f"bad total_sz {total}")
        if off + total > n:
            break                         # partial frame: resume later
        pad = int(hdr["padding_sz"])
        if pad > total - _HSZ:            # unvalidated pad would slice
            raise RefFrameError(          # outside the declared frame
                f"bad padding_sz {pad} for total_sz {total}")
        if int(hdr["data_type"]) == REF_COMM_EVENT_NOTIFY \
                and total - pad >= _HSZ + _ESZ:
            ev = np.frombuffer(buf, REF_EVENT_NOTIFY_DT, count=1,
                               offset=off + _HSZ)[0]
            dec = _DECODER_OF.get(int(ev["subtype"]))
            if dec is not None:
                fn, gyt_subtype = dec
                payload = buf[off + _HSZ + _ESZ: off + total - pad]
                recs, names = fn(payload, int(ev["nevents"]), host_id)
                if names:
                    out.append(wire.encode_frames_chunked(
                        wire.NOTIFY_NAME_INTERN,
                        InternTable.records(names)))
                out.append(wire.encode_frames_chunked(gyt_subtype,
                                                      recs))
        off += total
    return b"".join(out), off
