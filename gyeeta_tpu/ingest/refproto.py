"""gy_comm_proto ingest adapter: stock-partha frames → GYT records.

SURVEY M1's loose end (VERDICT r3 #5): the reference wire format is the
serialization boundary — a stock partha agent should be able to connect
later unmodified. GYT's own frames are fixed-width and interned
(``wire.py``); the reference's are C++ structs with TRAILING
VARIABLE-LENGTH STRINGS (cmdline / issue strings) and per-record
padding. This module decodes the reference layouts into GYT record
arrays (+ NAME_INTERN announcements for every string), so reference
traffic folds through the exact same ``Runtime.feed`` path.

Layouts transcribed as numpy dtypes from the reference ABI (protocol
contract, little-endian throughout ``gy_comm_proto.h:43``):

- ``COMM_HEADER``           — gy_comm_proto.h:336 (magic/total/type/pad)
- ``EVENT_NOTIFY``          — gy_comm_proto.h:486 (subtype/nevents)
- ``TCP_CONN_NOTIFY``       — gy_comm_proto.h:1665 (+ trailing cmdline)
- ``LISTENER_STATE_NOTIFY`` — gy_comm_proto.h:2183 (+ issue string)
- ``AGGR_TASK_STATE_NOTIFY``— gy_comm_proto.h:2114 (+ issue string)
- ``IP_PORT``/``GY_IP_ADDR``— gy_common_inc.h:11162 / :10492 (packed
  u128 address + u32 v4 + af/flags, 8-aligned, port + tail pad)

Only the partha→madhava event subtypes the engine folds are adapted;
unknown subtypes are skipped frame-whole (forward compatibility — the
reference's recv loop does the same for unhandled events).
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils.intern import InternTable

# ----------------------------------------------------- reference constants
REF_MAGIC_PS = 0x05555505        # PS_ADHOC_MAGIC (partha → shyama)
REF_MAGIC_PM = 0x05666605        # PM_HDR_MAGIC (partha → madhava)
REF_MAGICS = {0x05555505, 0x05666605, 0x05777705, 0x05888805}

# COMM_TYPE_E (gy_comm_proto.h:124)
REF_COMM_PS_REGISTER_REQ = 2
REF_COMM_PM_CONNECT_CMD = 3
REF_COMM_PS_REGISTER_RESP = 8
REF_COMM_PM_CONNECT_RESP = 9
REF_COMM_EVENT_NOTIFY = 14

REF_NOTIFY_TASK_TOP_PROCS = 0x303
REF_NOTIFY_TASK_AGGR = 0x305
REF_NOTIFY_PING_TASK_AGGR = 0x306
REF_NOTIFY_NEW_LISTENER = 0x307
REF_NOTIFY_LISTENER_STATE = 0x309
REF_NOTIFY_TCP_CONN = 0x30C
REF_NOTIFY_NAT_TCP = 0x30D
REF_NOTIFY_CPU_MEM_STATE = 0x30F
REF_NOTIFY_AGGR_TASK_STATE = 0x310
REF_NOTIFY_PARTHA_STATUS = 0x311
REF_NOTIFY_ACTIVE_CONN_STATS = 0x312
REF_NOTIFY_LISTENER_DOMAIN = 0x313
REF_NOTIFY_LISTEN_TASKMAP = 0x314
REF_NOTIFY_HOST_INFO = 0x317
REF_NOTIFY_HOST_CPU_MEM_CHANGE = 0x318
REF_NOTIFY_NOTIFICATION_MSG = 0x319
REF_NOTIFY_REQ_TRACE_TRAN = 0x31D
REF_NOTIFY_HOST_STATE = 0x31C        # current version (NOTIFY_PM_EVT
#                                      enum order: 0x301 TASK_MINI_ADD
#                                      … 0x31B LISTEN_CLUSTER_INFO,
#                                      0x31C HOST_STATE)

# version encoding: get_version_from_string("a.b.c", 3) = a<<16|b<<8|c
REF_COMM_VERSION = 1             # COMM_VERSION_NUM (gy_comm_proto.h:16)
REF_MIN_PARTHA_VERSION = 0x000400   # "0.4.0" (server/sversion.cc:15)
REF_MADHAVA_VERSION = 0x000502      # presented version (≥ partha's
#                                     gmin_madhava_version "0.5.0")

AF_INET, AF_INET6 = 2, 10

REF_HEADER_DT = np.dtype([       # COMM_HEADER, gy_comm_proto.h:336
    ("magic", "<u4"), ("total_sz", "<u4"),
    ("data_type", "<u4"), ("padding_sz", "<u4"),
])

REF_EVENT_NOTIFY_DT = np.dtype([  # EVENT_NOTIFY, gy_comm_proto.h:486
    ("subtype", "<u4"), ("nevents", "<u4"),
])

# GY_IP_ADDR (gy_common_inc.h:10492, packed+aligned(8)) inside IP_PORT
# (gy_common_inc.h:11162): 16B raw v6 address + embedded v4 + af/flags,
# then the port and 8-align tail padding
REF_IP_PORT_DT = np.dtype([
    ("ip128", "u1", (16,)),      # in6_addr raw bytes (network order)
    ("ip32_be", "<u4"),          # v4 address, network byte order
    ("aftype", "<i2"), ("ipflags", "<u2"),
    ("port", "<u2"), ("pad", "u1", (6,)),
])
assert REF_IP_PORT_DT.itemsize == 32

# TCP_CONN_NOTIFY fixed part (gy_comm_proto.h:1665); cli_cmdline_len_
# bytes of cmdline + padding_len_ bytes follow each record
REF_TCP_CONN_DT = np.dtype([
    ("cli", REF_IP_PORT_DT), ("ser", REF_IP_PORT_DT),
    ("nat_cli", REF_IP_PORT_DT), ("nat_ser", REF_IP_PORT_DT),
    ("tusec_start", "<u8"), ("tusec_close", "<u8"),
    ("cli_task_aggr_id", "<u8"), ("cli_related_listen_id", "<u8"),
    ("cli_madhava_id", "<u8"),
    ("machid_hi", "<u8"), ("machid_lo", "<u8"),   # GY_MACHINE_ID pair
    ("ser_related_listen_id", "<u8"), ("ser_glob_id", "<u8"),
    ("ser_madhava_id", "<u8"),
    ("bytes_sent", "<u8"), ("bytes_rcvd", "<u8"),
    ("cli_pid", "<i4"), ("ser_pid", "<i4"),
    ("ser_conn_hash", "<u4"), ("ser_sock_inode", "<u4"),
    ("cli_comm", "S16"), ("ser_comm", "S16"),
    ("cli_cmdline_len", "<u2"),
    ("is_connect", "u1"), ("is_accept", "u1"), ("is_loopback", "u1"),
    ("is_pre_existing", "u1"), ("notified_before", "u1"),
    ("padding_len", "u1"),
])
assert REF_TCP_CONN_DT.itemsize == 280

# LISTENER_STATE_NOTIFY fixed part (gy_comm_proto.h:2183)
REF_LISTENER_STATE_DT = np.dtype([
    ("glob_id", "<u8"),
    ("nqrys_5s", "<u4"), ("total_resp_5sec", "<u4"), ("nconns", "<u4"),
    ("nconns_active", "<u4"), ("ntasks", "<u4"),
    ("p95_5s_resp_ms", "<u4"), ("p95_5min_resp_ms", "<u4"),
    ("curr_kbytes_inbound", "<u4"), ("curr_kbytes_outbound", "<u4"),
    ("ser_errors", "<u4"), ("cli_errors", "<u4"),
    ("tasks_delay_usec", "<u4"), ("tasks_cpudelay_usec", "<u4"),
    ("tasks_blkiodelay_usec", "<u4"), ("tasks_user_cpu", "<u4"),
    ("tasks_sys_cpu", "<u4"), ("tasks_rss_mb", "<u4"),
    ("ntasks_issue", "<u2"),
    ("is_http_svc", "u1"), ("curr_state", "u1"), ("curr_issue", "u1"),
    ("issue_bit_hist", "u1"), ("high_resp_bit_hist", "u1"),
    ("last_issue_subsrc", "u1"), ("query_flags", "u1"),
    ("issue_string_len", "u1"), ("padding_len", "u1"),
    ("tailpad", "u1", (1,)),
])
assert REF_LISTENER_STATE_DT.itemsize == 88

# AGGR_TASK_STATE_NOTIFY fixed part (gy_comm_proto.h:2114)
REF_AGGR_TASK_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("onecomm", "S16"),
    ("pid_arr", "<i4", (2,)),
    ("tcp_kbytes", "<u4"), ("tcp_conns", "<u4"),
    ("total_cpu_pct", "<f4"), ("rss_mb", "<u4"),
    ("cpu_delay_msec", "<u4"), ("vm_delay_msec", "<u4"),
    ("blkio_delay_msec", "<u4"),
    ("ntasks_total", "<u2"), ("ntasks_issue", "<u2"),
    ("curr_state", "u1"), ("curr_issue", "u1"),
    ("issue_bit_hist", "u1"), ("severe_issue_bit_hist", "u1"),
    ("issue_string_len", "u1"), ("padding_len", "u1"),
    ("tailpad", "u1", (2,)),
])
assert REF_AGGR_TASK_DT.itemsize == 72

# NEW_LISTENER fixed part (gy_comm_proto.h:1531); cmdline_len_ bytes of
# cmdline + padding_len_ bytes follow each record. ns_ip_port_ is
# NS_IP_PORT (gy_inet_inc.h:105): IP_PORT + the listener netns inode.
REF_NEW_LISTENER_DT = np.dtype([
    ("ns_ip_port", REF_IP_PORT_DT), ("inode", "<u8"),
    ("glob_id", "<u8"), ("aggr_glob_id", "<u8"),
    ("related_listen_id", "<u8"), ("tstart_usec", "<u8"),
    ("ser_aggr_task_id", "<u8"),
    ("is_any_ip", "u1"), ("is_pre_existing", "u1"),
    ("no_aggr_stats", "u1"), ("no_resp_stats", "u1"),
    ("comm", "S16"), ("start_pid", "<i4"),
    ("cmdline_len", "<u2"), ("padding_len", "u1"),
    ("tailpad", "u1", (5,)),
])
assert REF_NEW_LISTENER_DT.itemsize == 112

# ACTIVE_CONN_STATS (gy_comm_proto.h:2766) — fixed-size aggregate of one
# (listener, client process-group) pair's active traffic
REF_ACTIVE_CONN_DT = np.dtype([
    ("listener_glob_id", "<u8"), ("cli_aggr_task_id", "<u8"),
    ("ser_comm", "S16"), ("cli_comm", "S16"),
    ("machid_hi", "<u8"), ("machid_lo", "<u8"),
    ("remote_madhava_id", "<u8"),
    ("bytes_sent", "<u8"), ("bytes_received", "<u8"),
    ("cli_delay_msec", "<u4"), ("ser_delay_msec", "<u4"),
    ("max_rtt_msec", "<f4"),
    ("active_conns", "<u2"),
    ("connflags", "u1"),          # bit0 cli_listener_proc, bit1
    #                               is_remote_listen, bit2 is_remote_cli
    ("tailpad", "u1", (1,)),
])
assert REF_ACTIVE_CONN_DT.itemsize == 104

# TASK_TOP_PROCS (gy_comm_proto.h:1415): one 16B header then four
# variable-count arrays of fixed-size entries
REF_TOP_HDR_DT = np.dtype([
    ("nprocs", "<u2"), ("npg_procs", "<u2"), ("nrss_procs", "<u2"),
    ("nfork_procs", "<u2"), ("ext_data_len", "<u2"),
    ("tailpad", "u1", (6,)),
])
REF_TOP_TASK_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("pid", "<i4"), ("ppid", "<i4"),
    ("rss_mb", "<u4"), ("cpupct", "<f4"), ("comm", "S16"),
])
REF_TOP_PG_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("pg_pid", "<i4"), ("cpid", "<i4"),
    ("ntasks", "<i4"), ("tot_rss_mb", "<u4"), ("tot_cpupct", "<f4"),
    ("pg_comm", "S16"), ("child_comm", "S16"), ("tailpad", "u1", (4,)),
])
REF_TOP_FORK_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("pid", "<i4"), ("ppid", "<i4"),
    ("nfork_per_sec", "<i4"), ("comm", "S16"), ("tailpad", "u1", (4,)),
])
assert REF_TOP_HDR_DT.itemsize == 16
assert REF_TOP_TASK_DT.itemsize == 40
assert REF_TOP_PG_DT.itemsize == 64
assert REF_TOP_FORK_DT.itemsize == 40

# ------------------------------------------------ registration handshake
# PS_REGISTER_REQ_S (gy_comm_proto.h:584) — partha's opener to shyama
REF_PS_REGISTER_REQ_DT = np.dtype([
    ("comm_version", "<u4"), ("partha_version", "<u4"),
    ("min_shyama_version", "<u4"), ("pad0", "u1", (4,)),
    ("machine_id_hi", "<u8"), ("machine_id_lo", "<u8"),
    ("hostname", "S256"), ("write_access_key", "S64"),
    ("cluster_name", "S64"), ("region_name", "S64"),
    ("zone_name", "S64"),
    ("kern_version_num", "<u4"), ("pad1", "u1", (4,)),
    ("curr_sec", "<i8"), ("last_mdisconn_sec", "<i8"),
    ("last_madhava_id", "<u8"), ("flags", "<u8"),
    ("extra_bytes", "u1", (512,)),
])
assert REF_PS_REGISTER_REQ_DT.itemsize == 1096

# PS_REGISTER_RESP_S (gy_comm_proto.h:616) — points partha at a madhava
REF_PS_REGISTER_RESP_DT = np.dtype([
    ("error_code", "<i4"), ("error_string", "S256"),
    ("comm_version", "<u4"), ("shyama_version", "<u4"),
    ("pad0", "u1", (4,)),
    ("shyama_id", "<u8"), ("flags", "<u8"),
    ("partha_ident_key", "<u8"), ("madhava_expiry_sec", "<i8"),
    ("madhava_id", "<u8"), ("madhava_port", "<u2"),
    ("madhava_hostname", "S256"), ("madhava_name", "S64"),
    ("extra_bytes", "u1", (800,)), ("tailpad", "u1", (6,)),
])
assert REF_PS_REGISTER_RESP_DT.itemsize == 1440

# PM_CONNECT_CMD_S (gy_comm_proto.h:648) — partha's opener to madhava
REF_PM_CONNECT_CMD_DT = np.dtype([
    ("comm_version", "<u4"), ("partha_version", "<u4"),
    ("min_madhava_version", "<u4"), ("pad0", "u1", (4,)),
    ("machine_id_hi", "<u8"), ("machine_id_lo", "<u8"),
    ("partha_ident_key", "<u8"),
    ("hostname", "S256"), ("write_access_key", "S64"),
    ("cluster_name", "S64"), ("region_name", "S64"),
    ("zone_name", "S64"),
    ("madhava_id", "<u8"), ("cli_type", "<u4"),
    ("kern_version_num", "<u4"),
    ("curr_sec", "<i8"), ("clock_sec", "<i8"),
    ("process_uptime_sec", "<i8"), ("last_connect_sec", "<i8"),
    ("flags", "<u8"), ("extra_bytes", "u1", (512,)),
])
assert REF_PM_CONNECT_CMD_DT.itemsize == 1120

# PM_CONNECT_RESP_S (gy_comm_proto.h:691)
REF_PM_CONNECT_RESP_DT = np.dtype([
    ("error_code", "<i4"), ("error_string", "S256"),
    ("pad0", "u1", (4,)),
    ("madhava_id", "<u8"), ("comm_version", "<u4"),
    ("madhava_version", "<u4"),
    ("region_name", "S64"), ("zone_name", "S64"),
    ("madhava_name", "S64"),
    ("curr_sec", "<i8"), ("clock_sec", "<u8"), ("flags", "<u8"),
    ("extra_bytes", "u1", (512,)),
])
assert REF_PM_CONNECT_RESP_DT.itemsize == 1008

# CLI_TYPE_E (gy_comm_proto.h:91)
REF_CLI_TYPE_REQ_RESP = 0
REF_CLI_TYPE_REQ_ONLY = 1

_HSZ = REF_HEADER_DT.itemsize
_ESZ = REF_EVENT_NOTIFY_DT.itemsize


# CPU_MEM_STATE_NOTIFY fixed part (gy_comm_proto.h:2024, 200 bytes);
# cpu_state_string_len_ + mem_state_string_len_ bytes of state strings
# + padding_len_ follow each record
REF_CPU_MEM_DT = np.dtype([
    ("cpu_pct", "<f4"), ("usercpu_pct", "<f4"), ("syscpu_pct", "<f4"),
    ("iowait_pct", "<f4"), ("cumul_core_cpu_pct", "<f4"),
    ("forks_sec", "<u4"), ("procs_running", "<u4"), ("cs_sec", "<u4"),
    ("cs_p95_sec", "<u4"), ("cs_5min_p95_sec", "<u4"),
    ("cpu_p95", "<u4"), ("cpu_5min_p95", "<u4"),
    ("fork_p95_sec", "<u4"), ("fork_5min_p95_sec", "<u4"),
    ("procs_p95", "<u4"), ("procs_5min_p95", "<u4"),
    ("cpu_state", "u1"), ("cpu_issue", "u1"),
    ("cpu_issue_bit_hist", "u1"), ("cpu_severe_issue_hist", "u1"),
    ("cpu_state_string_len", "u1"), ("pad0", "u1", (3,)),
    ("rss_pct", "<f4"), ("pad1", "u1", (4,)),
    ("rss_memory_mb", "<u8"), ("total_memory_mb", "<u8"),
    ("cached_memory_mb", "<u8"), ("locked_memory_mb", "<u8"),
    ("committed_memory_mb", "<u8"),
    ("committed_pct", "<f4"), ("pad2", "u1", (4,)),
    ("swap_free_mb", "<u8"), ("swap_total_mb", "<u8"),
    ("pg_inout_sec", "<u4"), ("swap_inout_sec", "<u4"),
    ("reclaim_stalls", "<u4"), ("pgmajfault", "<u4"),
    ("oom_kill", "<u4"), ("rss_pct_p95", "<u4"),
    ("pginout_p95", "<u8"), ("swpinout_p95", "<u8"),
    ("allocstall_p95", "<u8"),
    ("mem_state", "u1"), ("mem_issue", "u1"),
    ("mem_issue_bit_hist", "u1"), ("mem_severe_issue_hist", "u1"),
    ("mem_state_string_len", "u1"), ("padding_len", "u1"),
    ("tailpad", "u1", (2,)),
])
assert REF_CPU_MEM_DT.itemsize == 200

# HOST_STATE_NOTIFY (gy_comm_proto.h:2289, 56 bytes, nevents == 1)
REF_HOST_STATE_DT = np.dtype([
    ("curr_time_usec", "<u8"),
    ("ntasks_issue", "<u4"), ("ntasks_severe", "<u4"),
    ("ntasks", "<u4"),
    ("nlisten_issue", "<u4"), ("nlisten_severe", "<u4"),
    ("nlisten", "<u4"),
    ("curr_state", "u1"), ("issue_bit_hist", "u1"),
    ("cpu_issue", "u1"), ("mem_issue", "u1"),
    ("severe_cpu_issue", "u1"), ("severe_mem_issue", "u1"),
    ("pad0", "u1", (2,)),
    ("total_cpu_delayms", "<u4"), ("total_vm_delayms", "<u4"),
    ("total_io_delayms", "<u4"), ("tailpad", "u1", (4,)),
])
assert REF_HOST_STATE_DT.itemsize == 56

# HOST_INFO_NOTIFY (gy_comm_proto.h:2844, 704 bytes, nevents == 1)
REF_HOST_INFO_DT = np.dtype([
    ("distribution_name", "S128"), ("kern_version_string", "S64"),
    ("kern_version_num", "<u4"), ("instance_id", "S128"),
    ("cloud_type", "S64"), ("processor_model", "S128"),
    ("cpu_vendor", "S64"),
    ("cores_online", "<u2"), ("cores_offline", "<u2"),
    ("max_cores", "<u2"), ("isolated_cores", "<u2"),
    ("ram_mb", "<u4"), ("corrupted_ram_mb", "<u4"),
    ("num_numa_nodes", "<u2"), ("max_cores_per_socket", "<u2"),
    ("threads_per_core", "<u2"), ("pad0", "u1", (6,)),
    ("boot_time_sec", "<i8"),
    ("l1_dcache_kb", "<u4"), ("l2_cache_kb", "<u4"),
    ("l3_cache_kb", "<u4"), ("l4_cache_kb", "<u4"),
    ("is_virtual_cpu", "u1"), ("virtualization_type", "S64"),
    ("tailpad", "u1", (7,)),
])
assert REF_HOST_INFO_DT.itemsize == 704

# NAT_TCP_NOTIFY (gy_comm_proto.h:1744, 136 bytes): conntrack
# orig↔nat tuple pairs resolved AFTER the conn notify
REF_NAT_TCP_DT = np.dtype([
    ("orig_cli", REF_IP_PORT_DT), ("orig_ser", REF_IP_PORT_DT),
    ("nat_cli", REF_IP_PORT_DT), ("nat_ser", REF_IP_PORT_DT),
    ("is_snat", "u1"), ("is_dnat", "u1"), ("is_ipvs", "u1"),
    ("tailpad", "u1", (5,)),
])
assert REF_NAT_TCP_DT.itemsize == 136

# REQ_TRACE_TRAN / API_TRAN (gy_proto_common.h:140, 176 bytes fixed;
# request_len_ bytes of request text + lenext_ ext fields + padlen_
# follow each record) — the reference's request-trace stream
REF_GY_IP_ADDR_DT = np.dtype([
    ("ip128", "u1", (16,)), ("ip32_be", "<u4"),
    ("aftype", "<i2"), ("ipflags", "<u2"),
])
assert REF_GY_IP_ADDR_DT.itemsize == 24
REF_API_TRAN_DT = np.dtype([
    ("treq_usec", "<u8"), ("tres_usec", "<u8"), ("tupd_usec", "<u8"),
    ("reqlen", "<u8"), ("reslen", "<u8"), ("reqnum", "<u8"),
    ("response_usec", "<u8"), ("reaction_usec", "<u8"),
    ("tconnect_usec", "<u8"),
    ("cliip", REF_GY_IP_ADDR_DT), ("serip", REF_GY_IP_ADDR_DT),
    ("glob_id", "<u8"), ("conn_id", "<u8"),
    ("comm", "S16"),
    ("errorcode", "<i4"), ("app_sleep_ms", "<u4"),
    ("tran_type", "<u4"),
    ("proto", "<u2"), ("cliport", "<u2"), ("serport", "<u2"),
    ("request_len", "<u2"), ("lenext", "<u2"),
    ("padlen", "u1"), ("tailpad", "u1", (1,)),
])
assert REF_API_TRAN_DT.itemsize == 176

# reference PROTO_TYPES (gy_proto_common.h:14) → GYT trace protos
_REF_PROTO_MAP = {1: 1, 2: 4, 3: 2, 5: 3, 7: 6}   # HTTP1, HTTP2,
#                 Postgres, Mongo, Sybase; others → 0 (unknown)

# PING_TASK_AGGR (gy_comm_proto.h:1384, 8 bytes): process-group
# keepalive — long-lived quiet groups refresh their ageing clock
# without a stats sweep (the madhava refreshes last_tick and never
# inserts; MAX_NUM_PINGS = 2048)
REF_PING_TASK_AGGR_DT = np.dtype([
    ("aggr_task_id", "<u8"),
])
assert REF_PING_TASK_AGGR_DT.itemsize == 8

# PARTHA_STATUS (gy_comm_proto.h:1399, 24 bytes, nevents == 1): the
# partha's liveness ping (is_ok + clock skew sample) — session-level,
# never engine-fed
REF_PARTHA_STATUS_DT = np.dtype([
    ("is_ok", "u1"), ("pad0", "u1", (7,)),
    ("curr_sec", "<i8"), ("clock_sec", "<i8"),
])
assert REF_PARTHA_STATUS_DT.itemsize == 24

# TASK_AGGR_NOTIFY (gy_comm_proto.h:1290, 48 bytes + cmdline/tag):
# process-group announcements carrying the task→listener linkage
REF_TASK_AGGR_DT = np.dtype([
    ("aggr_task_id", "<u8"), ("related_listen_id", "<u8"),
    ("comm", "S16"), ("uid", "<u4"), ("gid", "<u4"),
    ("cmdline_len", "<u2"), ("tag_len", "u1"), ("procflags", "u1"),
    ("padding_len", "u1"), ("tailpad", "u1", (3,)),
])
assert REF_TASK_AGGR_DT.itemsize == 48

# HOST_CPU_MEM_CHANGE (gy_comm_proto.h:2886, 32 bytes, nevents == 1)
REF_CPU_MEM_CHANGE_DT = np.dtype([
    ("cpu_changed", "u1"), ("pad0", "u1"),
    ("new_cores_online", "<u2"), ("new_cores_offline", "<u2"),
    ("old_cores_online", "<u2"), ("old_cores_offline", "<u2"),
    ("mem_changed", "u1"), ("pad1", "u1"),
    ("new_ram_mb", "<u4"), ("old_ram_mb", "<u4"),
    ("mem_corrupt_changed", "u1"), ("pad2", "u1", (3,)),
    ("new_corrupted_ram_mb", "<u4"), ("old_corrupted_ram_mb", "<u4"),
])
assert REF_CPU_MEM_CHANGE_DT.itemsize == 32

# NOTIFICATION_MSG (gy_comm_proto.h:2913, 8 bytes + msglen_ text)
REF_NOTIFICATION_MSG_DT = np.dtype([
    ("type", "u1"), ("pad0", "u1"), ("msglen", "<u2"),
    ("padding_len", "u1"), ("tailpad", "u1", (3,)),
])
assert REF_NOTIFICATION_MSG_DT.itemsize == 8
_REF_MSGTYPES = {0: "info", 1: "warn", 2: "error", 3: "error"}

# LISTENER_DOMAIN_NOTIFY (gy_comm_proto.h:2724, 16 bytes + domain/tag)
REF_LISTENER_DOMAIN_DT = np.dtype([
    ("glob_id", "<u8"),
    ("domain_string_len", "u1"), ("tag_len", "u1"),
    ("padding_len", "u1"), ("tailpad", "u1", (5,)),
])
assert REF_LISTENER_DOMAIN_DT.itemsize == 16


# LISTEN_TASKMAP_NOTIFY fixed part (gy_comm_proto.h:2813); nlisten_
# u64 listener glob ids then naggr u64 task ids follow each record
REF_LISTEN_TASKMAP_DT = np.dtype([
    ("related_listen_id", "<u8"), ("ser_comm", "S16"),
    ("nlisten", "<u2"), ("naggr_taskid", "<u2"),
    ("tailpad", "u1", (4,)),
])
assert REF_LISTEN_TASKMAP_DT.itemsize == 32


class RefFrameError(wire.FrameError):
    pass


class RefSession:
    """Per-connection adapter state for a stock-partha stream.

    The reference resolves task↔listener linkage server-side from
    LISTEN_TASKMAP events (``gy_comm_proto.h:2813``); this holds that
    map so subsequent AGGR_TASK_STATE records carry their
    ``related_listen_id`` (without it, stock task rows never link to
    their services — taskstate.relsvcid / svcprocmap would stay
    empty for stock fleets). Bounded: newest mappings win."""

    MAX_TASKS = 1 << 20

    def __init__(self, region: str = "", zone: str = ""):
        self.rel_of_task: dict = {}
        self.ncpus = 0               # estimated core count (cpu_mem)
        # cluster placement from the PM_CONNECT handshake (HOST_INFO
        # itself does not carry region/zone — the wire does)
        self.region = region
        self.zone = zone
        # frameless notify payloads collected for the serving edge
        # (bounded; the edge drains them after every adapt run)
        self.notifications: list = []    # (ntype_str, message)
        self.domains: list = []          # (glob_id, domain, tag)
        # adaptation observability: events per reference subtype
        # (drained into selfstats as ref_evt_0x<subtype> counters) +
        # frames skipped whole (unknown subtype, non-NOTIFY data
        # types, truncated NOTIFY bodies)
        import collections
        self.n_events = collections.Counter()   # subtype -> count
        self.n_skipped = 0
        self.nat_conns: list = []        # TCP_CONN record arrays (NAT
        #                                  annotations for the VIP
        #                                  registry; never engine-fed)
        # PARTHA_STATUS liveness: (is_ok, curr_sec) of the newest ping
        # (the serving edge surfaces not-ok transitions)
        self.last_status_ok = True
        self.last_status_sec = 0

    # drained by the serving edge after each adapt() run
    MAX_PENDING = 1024

    def _push(self, lst: list, item) -> None:
        if len(lst) < self.MAX_PENDING:
            lst.append(item)

    def learn_taskmap(self, rel_id: int, task_ids) -> None:
        for t in task_ids:
            if len(self.rel_of_task) >= self.MAX_TASKS \
                    and int(t) not in self.rel_of_task:
                self.rel_of_task.clear()     # epoch reset, re-learns
            self.rel_of_task[int(t)] = rel_id


def _check_nevents(nevents: int, payload: bytes, fsz: int, cap: int,
                   what: str) -> None:
    """The wire's u4 nevents is attacker-controlled: bound it by the
    reference batch cap AND by what the payload could possibly hold
    (each record is ≥ fsz bytes) BEFORE allocating output — the GYT
    decoder enforces the same caps in ``wire.decode_frames``."""
    if nevents > cap or nevents * fsz > len(payload):
        raise RefFrameError(
            f"{what}: nevents {nevents} exceeds cap {cap} or "
            f"payload {len(payload)}B")


def _ip16(rec) -> bytes:
    """One REF_IP_PORT → the wire's 16-byte (v4-mapped) address."""
    if int(rec["aftype"]) == AF_INET:
        return (b"\x00" * 10 + b"\xff\xff"
                + int(rec["ip32_be"]).to_bytes(4, "little"))
        # ip32_be_ holds network-order bytes; little-endian re-pack of
        # the u32 value restores the original byte sequence
    return rec["ip128"].tobytes()


def _copy_ip_port(dst, src) -> None:
    dst["ip"] = np.frombuffer(_ip16(src), np.uint8)
    dst["port"] = src["port"]


def decode_tcp_conn(payload: bytes, nevents: int, host_id: int
                    ) -> tuple[np.ndarray, list]:
    """Variable-length TCP_CONN_NOTIFY walk → GYT TCP_CONN records +
    intern entries for comm/cmdline strings."""
    fsz = REF_TCP_CONN_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_CONNS_PER_BATCH,
                   "tcp_conn")
    out = np.zeros(nevents, wire.TCP_CONN_DT)
    names: list = []
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"tcp_conn record {i} truncated")
        rec = np.frombuffer(payload, REF_TCP_CONN_DT, count=1,
                            offset=off)[0]
        cmdlen = int(rec["cli_cmdline_len"])
        end = off + fsz + cmdlen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"tcp_conn record {i} overflows frame")
        r = out[i]
        for f in ("cli", "ser", "nat_cli", "nat_ser"):
            _copy_ip_port(r[f], rec[f])
        for f in ("tusec_start", "tusec_close", "cli_task_aggr_id",
                  "cli_related_listen_id", "cli_madhava_id",
                  "ser_related_listen_id", "ser_glob_id",
                  "ser_madhava_id", "bytes_sent", "bytes_rcvd",
                  "cli_pid", "ser_pid", "ser_conn_hash",
                  "ser_sock_inode"):
            r[f] = rec[f]
        r["peer_machine_id_hi"] = rec["machid_hi"]
        r["peer_machine_id_lo"] = rec["machid_lo"]
        for src_f, dst_f in (("cli_comm", "cli_comm_id"),
                             ("ser_comm", "ser_comm_id")):
            s = rec[src_f].tobytes().split(b"\x00", 1)[0].decode(
                "utf-8", "replace")
            if s:
                nid = InternTable.intern(s, wire.NAME_KIND_COMM)
                r[dst_f] = nid
                names.append((wire.NAME_KIND_COMM, nid, s))
        if cmdlen:
            cmdline = payload[off + fsz: off + fsz + cmdlen].split(
                b"\x00", 1)[0].decode("utf-8", "replace")
            nid = InternTable.intern(cmdline, wire.NAME_KIND_MISC)
            r["cli_cmdline_id"] = nid
            names.append((wire.NAME_KIND_MISC, nid, cmdline))
        r["host_id"] = host_id
        r["flags"] = (int(rec["is_connect"]) * 1
                      | int(rec["is_accept"]) * 2
                      | int(rec["is_loopback"]) * 4
                      | int(rec["is_pre_existing"]) * 8
                      | int(rec["notified_before"]) * 16)
        off = end
    return out, names


def decode_listener_state(payload: bytes, nevents: int, host_id: int
                          ) -> tuple[np.ndarray, list]:
    fsz = REF_LISTENER_STATE_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_LISTENERS_PER_BATCH,
                   "listener_state")
    out = np.zeros(nevents, wire.LISTENER_STATE_DT)
    names: list = []
    off = 0
    shared = set(wire.LISTENER_STATE_DT.names) \
        & set(REF_LISTENER_STATE_DT.names)
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"listener_state record {i} truncated")
        rec = np.frombuffer(payload, REF_LISTENER_STATE_DT, count=1,
                            offset=off)[0]
        ilen = int(rec["issue_string_len"])
        end = off + fsz + ilen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(
                f"listener_state record {i} overflows frame")
        r = out[i]
        for f in shared:
            if f != "pad":
                r[f] = rec[f]
        if ilen:
            s = payload[off + fsz: off + fsz + ilen].split(
                b"\x00", 1)[0].decode("utf-8", "replace")
            nid = InternTable.intern(s, wire.NAME_KIND_MISC)
            r["issue_string_id"] = nid
            names.append((wire.NAME_KIND_MISC, nid, s))
        r["host_id"] = host_id
        off = end
    return out, names


def decode_cpu_mem(payload: bytes, nevents: int, host_id: int,
                   session: "RefSession | None" = None
                   ) -> tuple[np.ndarray, list]:
    """CPU_MEM_STATE_NOTIFY walk → GYT CPU_MEM records (2s host
    gauges; state strings skipped — the engine classifies itself).

    Semantic mapping caveats (the struct carries neither per-core
    maxima nor a core count):
    - ``cumul_core_cpu_pct_`` is the SUM across cores (can exceed
      100); GYT's ``max_core_cpu_pct`` (hottest core) falls back to
      the host average ``cpu_pct`` — conservative: a saturated single
      core is under-reported, a healthy multi-core host is never
      false-flagged.
    - ``ncpus`` (classifier thresholds scale with it) is ESTIMATED as
      round(sum/average) when the host is busy enough for the ratio
      to be stable (≥5% cpu), cached on the session."""
    fsz = REF_CPU_MEM_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_CPUMEM_PER_BATCH,
                   "cpu_mem")
    out = np.zeros(nevents, wire.CPU_MEM_DT)
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"cpu_mem record {i} truncated")
        rec = np.frombuffer(payload, REF_CPU_MEM_DT, count=1,
                            offset=off)[0]
        end = (off + fsz + int(rec["cpu_state_string_len"])
               + int(rec["mem_state_string_len"])
               + int(rec["padding_len"]))
        if end > len(payload):
            raise RefFrameError(f"cpu_mem record {i} overflows")
        r = out[i]
        for f in ("cpu_pct", "usercpu_pct", "syscpu_pct",
                  "iowait_pct", "cs_sec", "forks_sec",
                  "procs_running", "rss_pct", "pg_inout_sec",
                  "swap_inout_sec"):
            r[f] = rec[f]
        cpu = float(rec["cpu_pct"])
        if session is not None and cpu >= 5.0:
            session.ncpus = max(1, round(
                float(rec["cumul_core_cpu_pct"]) / cpu))
        r["ncpus"] = session.ncpus if session is not None else 0
        r["max_core_cpu_pct"] = cpu          # see docstring caveat
        r["commit_pct"] = rec["committed_pct"]
        tot_swap = float(rec["swap_total_mb"])
        r["swap_free_pct"] = (100.0 * float(rec["swap_free_mb"])
                              / tot_swap) if tot_swap else 100.0
        r["allocstall_sec"] = rec["reclaim_stalls"]
        r["oom_kills"] = rec["oom_kill"]
        r["host_id"] = host_id
        off = end
    return out, []


def decode_host_state(payload: bytes, nevents: int, host_id: int
                      ) -> tuple[np.ndarray, list]:
    """HOST_STATE_NOTIFY → GYT HOST_STATE records (fixed size)."""
    fsz = REF_HOST_STATE_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_HOSTS_PER_BATCH,
                   "host_state")
    recs = np.frombuffer(payload, REF_HOST_STATE_DT, count=nevents)
    out = np.zeros(nevents, wire.HOST_STATE_DT)
    for f in ("curr_time_usec", "ntasks_issue", "ntasks_severe",
              "ntasks", "nlisten_issue", "nlisten_severe", "nlisten",
              "curr_state", "issue_bit_hist", "cpu_issue", "mem_issue",
              "severe_cpu_issue", "severe_mem_issue"):
        out[f] = recs[f]
    out["host_id"] = host_id
    return out, []


def decode_host_info(payload: bytes, nevents: int, host_id: int,
                     session: "RefSession | None" = None
                     ) -> tuple[np.ndarray, list]:
    """HOST_INFO_NOTIFY → GYT HOST_INFO records + interned strings
    (the hostinfo inventory view for stock fleets). Region/zone come
    from the session (the PM_CONNECT handshake carries them; this
    struct does not)."""
    fsz = REF_HOST_INFO_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_HOST_INFO_PER_BATCH,
                   "host_info")
    recs = np.frombuffer(payload, REF_HOST_INFO_DT, count=nevents)
    out = np.zeros(nevents, wire.HOST_INFO_DT)
    names: list = []
    for i in range(nevents):
        rec = recs[i]
        r = out[i]
        r["ncpus"] = rec["cores_online"]
        r["nnuma"] = max(int(rec["num_numa_nodes"]), 1)
        r["ram_mb"] = rec["ram_mb"]
        # wire value is attacker-controlled: clamp into the unsigned
        # usec field instead of letting numpy raise OverflowError
        boot = int(rec["boot_time_sec"])
        r["boot_tusec"] = min(max(boot, 0), (1 << 63) // 10**6) \
            * 1_000_000
        region = session.region if session is not None else ""
        zone = session.zone if session is not None else ""
        for val, dst in ((_cstr(rec["kern_version_string"]),
                          "kern_ver_id"),
                         (_cstr(rec["distribution_name"]), "distro_id"),
                         (_cstr(rec["processor_model"]), "cputype_id"),
                         (_cstr(rec["instance_id"]), "instance_id"),
                         (region, "region_id"), (zone, "zone_id")):
            nid = InternTable.intern(val, wire.NAME_KIND_MISC)
            r[dst] = nid
            names.append((wire.NAME_KIND_MISC, nid, val))
        cloud = _cstr(rec["cloud_type"]).lower()
        r["cloud_type"] = (1 if "aws" in cloud else
                           2 if "gcp" in cloud or "google" in cloud
                           else 3 if "azure" in cloud else 0)
        virt = _cstr(rec["virtualization_type"]).lower()
        r["virt_type"] = (2 if any(m in virt for m in
                                   ("docker", "lxc", "container",
                                    "podman")) else
                          1 if rec["is_virtual_cpu"] else 0)
        r["host_id"] = host_id
    return out, names


def decode_listen_taskmap(payload: bytes, nevents: int,
                          session: "RefSession") -> None:
    """LISTEN_TASKMAP walk → session task→listener map (no GYT frames;
    linkage applies to later AGGR_TASK_STATE records)."""
    fsz = REF_LISTEN_TASKMAP_DT.itemsize
    _check_nevents(nevents, payload, fsz, 2048, "listen_taskmap")
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"listen_taskmap record {i} truncated")
        rec = np.frombuffer(payload, REF_LISTEN_TASKMAP_DT, count=1,
                            offset=off)[0]
        nl, na = int(rec["nlisten"]), int(rec["naggr_taskid"])
        if nl > 2048 or na > 128:        # the reference's own caps
            raise RefFrameError(f"listen_taskmap record {i} overflows")
        end = off + fsz + (nl + na) * 8
        if end > len(payload):
            raise RefFrameError(f"listen_taskmap record {i} overflows")
        tasks = np.frombuffer(payload, "<u8", count=na,
                              offset=off + fsz + nl * 8)
        session.learn_taskmap(int(rec["related_listen_id"]), tasks)
        off = end


def decode_notification_msg(payload: bytes, nevents: int,
                            session: "RefSession") -> None:
    """NOTIFICATION_MSG walk → session notifications (the agent's
    operator messages land in the notifymsg ring)."""
    fsz = REF_NOTIFICATION_MSG_DT.itemsize
    _check_nevents(nevents, payload, fsz, 128, "notification_msg")
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"notification_msg {i} truncated")
        rec = np.frombuffer(payload, REF_NOTIFICATION_MSG_DT, count=1,
                            offset=off)[0]
        ln = int(rec["msglen"])
        end = off + fsz + ln + int(rec["padding_len"])
        if ln > 512 or end > len(payload):
            raise RefFrameError(f"notification_msg {i} overflows")
        msg = payload[off + fsz: off + fsz + ln].split(
            b"\x00", 1)[0].decode("utf-8", "replace")
        if msg:
            session._push(session.notifications,
                          (_REF_MSGTYPES.get(int(rec["type"]), "info"),
                           msg))
        off = end


def decode_listener_domain(payload: bytes, nevents: int,
                           session: "RefSession") -> None:
    """LISTENER_DOMAIN walk → session (glob_id, domain, tag) — the
    serving edge resolves the listener's bind address and primes the
    DNS cache (resolved-AS names for svcipclust annotations)."""
    fsz = REF_LISTENER_DOMAIN_DT.itemsize
    _check_nevents(nevents, payload, fsz, 512, "listener_domain")
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"listener_domain {i} truncated")
        rec = np.frombuffer(payload, REF_LISTENER_DOMAIN_DT, count=1,
                            offset=off)[0]
        dlen, tlen = int(rec["domain_string_len"]), int(rec["tag_len"])
        end = off + fsz + dlen + tlen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"listener_domain {i} overflows")
        dom = payload[off + fsz: off + fsz + dlen].split(
            b"\x00", 1)[0].decode("utf-8", "replace")
        tag = payload[off + fsz + dlen: off + fsz + dlen + tlen].split(
            b"\x00", 1)[0].decode("utf-8", "replace")
        if dom or tag:
            session._push(session.domains,
                          (int(rec["glob_id"]), dom, tag))
        off = end


# NAT_TCP batch cap — the reference's NAT_TCP_NOTIFY::MAX_NUM_CONNS
REF_MAX_NAT_PER_BATCH = 2048


def _ip16_col(tup) -> np.ndarray:
    """(N,) REF_IP_PORT records → (N, 16) wire addresses (v4-mapped
    where aftype is AF_INET) — the vectorized :func:`_ip16`."""
    raw = np.ascontiguousarray(tup["ip128"])
    v4 = np.zeros_like(raw)
    v4[:, 10:12] = 0xFF
    v4[:, 12:16] = np.ascontiguousarray(
        tup["ip32_be"]).view(np.uint8).reshape(-1, 4)
    is4 = (np.ascontiguousarray(tup["aftype"]) == AF_INET)[:, None]
    return np.where(is4, v4, raw)


def decode_req_trace_tran(payload: bytes, nevents: int, host_id: int
                          ) -> tuple[np.ndarray, list]:
    """REQ_TRACE_TRAN walk → GYT REQ_TRACE records + interned API
    signatures.

    The reference streams RAW request text per transaction and
    normalizes server-side; here the request normalizes through the
    SAME :func:`~gyeeta_tpu.trace.proto.normalize_sql`-style signature
    path the local parsers use, so stock-partha traces and
    locally-captured traces aggregate under identical API ids. The
    trace→resp bridge then feeds svcstate latencies for free, and
    error transactions accumulate into ser_errors (the trace fold)."""
    from gyeeta_tpu.trace.proto import normalize_http, normalize_sql
    from gyeeta_tpu.utils import hashing as H

    fsz = REF_API_TRAN_DT.itemsize
    # tolerant cap: the reference producer batches ≤256 (API_TRAN::
    # MAX_NUM_REQS) but our pipeline accepts its own trace batch size
    _check_nevents(nevents, payload, fsz, wire.MAX_TRACE_PER_BATCH,
                   "req_trace_tran")
    out = np.zeros(nevents, wire.REQ_TRACE_DT)
    names: list = []
    seen: dict = {}
    seen_comm: dict = {}
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"req_trace_tran {i} truncated")
        rec = np.frombuffer(payload, REF_API_TRAN_DT, count=1,
                            offset=off)[0]
        rlen = int(rec["request_len"])
        end = (off + fsz + rlen + int(rec["lenext"])
               + int(rec["padlen"]))
        if rlen > 16384 or end > len(payload):
            raise RefFrameError(f"req_trace_tran {i} overflows")
        req_text = payload[off + fsz: off + fsz + rlen].split(
            b"\x00", 1)[0]
        proto = int(rec["proto"])
        if not req_text:
            api = "(empty)"
        elif proto in (1, 2) and b" " in req_text:   # HTTP1/HTTP2:
            meth, _, path = req_text.partition(b" ")  # method + path
            api = normalize_http(meth, path.split(b" ", 1)[0])
        else:
            api = normalize_sql(req_text)
        api_id = seen.get(api)
        if api_id is None:
            # unsalted content hash — the id convention of
            # transactions_to_records (trace/proto.py:292), so stock
            # and locally-parsed traces share API identities
            api_id = int(H.hash_bytes_np(api.encode()))
            seen[api] = api_id
            names.append((wire.NAME_KIND_API, api_id, api))
        r = out[i]
        r["svc_glob_id"] = rec["glob_id"]
        r["api_id"] = api_id
        r["conn_id"] = rec["conn_id"]
        r["tusec"] = rec["treq_usec"]
        r["resp_usec"] = min(int(rec["response_usec"]), 0xFFFFFFFF)
        r["bytes_in"] = min(int(rec["reqlen"]), 0xFFFFFFFF)
        r["bytes_out"] = min(int(rec["reslen"]), 0xFFFFFFFF)
        err = int(rec["errorcode"])
        r["status"] = min(abs(err), 0xFFFF)
        r["is_error"] = err != 0
        r["proto"] = _REF_PROTO_MAP.get(proto, 0)
        r["host_id"] = host_id
        comm = rec["comm"].tobytes().split(b"\x00", 1)[0].decode(
            "utf-8", "replace")
        if comm:
            cid = seen_comm.get(comm)
            if cid is None:            # trace batches repeat one comm:
                cid = InternTable.intern(comm, wire.NAME_KIND_COMM)
                seen_comm[comm] = cid  # dedup the announcements
                names.append((wire.NAME_KIND_COMM, cid, comm))
            r["cli_comm_id"] = cid
        off = end
    return out, names


def decode_ping_task_aggr(payload: bytes, nevents: int, host_id: int
                          ) -> tuple[np.ndarray, list]:
    """PING_TASK_AGGR walk → GYT TASK_PING records (fixed size): the
    keepalive refreshes the group's device-table ageing clock, so
    long-lived quiet stock task rows stop ageing out between 5s
    sweeps (``engine/step.ping_tasks``)."""
    fsz = REF_PING_TASK_AGGR_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_PINGS_PER_BATCH,
                   "ping_task_aggr")
    recs = np.frombuffer(payload, REF_PING_TASK_AGGR_DT, count=nevents)
    out = np.zeros(nevents, wire.TASK_PING_DT)
    out["aggr_task_id"] = recs["aggr_task_id"]
    out["host_id"] = host_id
    return out, []


def decode_partha_status(payload: bytes, nevents: int,
                         session: "RefSession") -> None:
    """PARTHA_STATUS walk → session liveness (frameless): the newest
    ping's (is_ok, curr_sec) lands on the session; ok→not-ok
    transitions raise an operator notification (the reference treats
    these as host liveness for its parthalist views)."""
    fsz = REF_PARTHA_STATUS_DT.itemsize
    _check_nevents(nevents, payload, fsz, 16, "partha_status")
    recs = np.frombuffer(payload, REF_PARTHA_STATUS_DT, count=nevents)
    for rec in recs:
        ok = bool(rec["is_ok"])
        if session.last_status_ok and not ok:
            session._push(session.notifications,
                          ("warn", "partha reports degraded status"))
        session.last_status_ok = ok
        session.last_status_sec = int(rec["curr_sec"])


def decode_task_aggr(payload: bytes, nevents: int,
                     session: "RefSession") -> None:
    """TASK_AGGR walk → session task→listener linkage (a second
    source besides LISTEN_TASKMAP: group announcements carry their
    related_listen_id directly)."""
    fsz = REF_TASK_AGGR_DT.itemsize
    _check_nevents(nevents, payload, fsz, 1200, "task_aggr")
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"task_aggr {i} truncated")
        rec = np.frombuffer(payload, REF_TASK_AGGR_DT, count=1,
                            offset=off)[0]
        end = (off + fsz + int(rec["cmdline_len"])
               + int(rec["tag_len"]) + int(rec["padding_len"]))
        if end > len(payload):
            raise RefFrameError(f"task_aggr {i} overflows")
        rel = int(rec["related_listen_id"])
        if rel:
            session.learn_taskmap(rel, [int(rec["aggr_task_id"])])
        off = end


def decode_cpu_mem_change(payload: bytes, nevents: int,
                          session: "RefSession") -> None:
    """HOST_CPU_MEM_CHANGE → operator notifications (cores on/offline,
    RAM resize, memory corruption — the reference raises the same as
    host notifications)."""
    fsz = REF_CPU_MEM_CHANGE_DT.itemsize
    _check_nevents(nevents, payload, fsz, 16, "cpu_mem_change")
    recs = np.frombuffer(payload, REF_CPU_MEM_CHANGE_DT, count=nevents)
    for rec in recs:
        if rec["cpu_changed"]:
            session._push(session.notifications, (
                "warn", f"host cores changed: "
                f"{int(rec['old_cores_online'])} → "
                f"{int(rec['new_cores_online'])} online"))
        if rec["mem_changed"]:
            session._push(session.notifications, (
                "warn", f"host RAM changed: {int(rec['old_ram_mb'])}"
                f" → {int(rec['new_ram_mb'])} MB"))
        if rec["mem_corrupt_changed"]:
            session._push(session.notifications, (
                "error", f"corrupted RAM changed: "
                f"{int(rec['old_corrupted_ram_mb'])} → "
                f"{int(rec['new_corrupted_ram_mb'])} MB"))


def decode_nat_tcp(payload: bytes, nevents: int,
                   session: "RefSession") -> None:
    """NAT_TCP walk → session NAT annotations.

    Conntrack resolves some translations AFTER the conn notify; the
    reference fixes the conn up server-side. Here the DNAT/IPVS pairs
    become synthetic TCP_CONN records carrying ONLY tuple fields
    (ser = the dialed VIP, nat_* = the translated tuple,
    ser_glob_id = 0) for the VIP/NAT cluster registry — never
    engine-fed, so no phantom connections are counted. Pure-SNAT
    records (server tuple unchanged) are dropped: registering a
    service's own address as its "VIP" would fabricate self-clusters
    and eat the bounded registry."""
    fsz = REF_NAT_TCP_DT.itemsize
    _check_nevents(nevents, payload, fsz, REF_MAX_NAT_PER_BATCH,
                   "nat_tcp")
    recs = np.frombuffer(payload, REF_NAT_TCP_DT, count=nevents)
    ser_ip = _ip16_col(recs["orig_ser"])
    nat_ser_ip = _ip16_col(recs["nat_ser"])
    translated = ((recs["is_dnat"] | recs["is_ipvs"]) != 0) & (
        (ser_ip != nat_ser_ip).any(axis=1)
        | (recs["orig_ser"]["port"] != recs["nat_ser"]["port"]))
    recs = recs[translated]
    if not len(recs):
        return
    out = np.zeros(len(recs), wire.TCP_CONN_DT)
    for src, dst in (("orig_cli", "cli"), ("orig_ser", "ser"),
                     ("nat_cli", "nat_cli"), ("nat_ser", "nat_ser")):
        out[dst]["ip"] = _ip16_col(recs[src])
        out[dst]["port"] = recs[src]["port"]
    session._push(session.nat_conns, out)


# frameless stateful subtypes: consume into the session, emit nothing
_SESSION_DECODERS = {
    REF_NOTIFY_LISTEN_TASKMAP: decode_listen_taskmap,
    REF_NOTIFY_NOTIFICATION_MSG: decode_notification_msg,
    REF_NOTIFY_LISTENER_DOMAIN: decode_listener_domain,
    REF_NOTIFY_NAT_TCP: decode_nat_tcp,
    REF_NOTIFY_HOST_CPU_MEM_CHANGE: decode_cpu_mem_change,
    REF_NOTIFY_TASK_AGGR: decode_task_aggr,
    REF_NOTIFY_PARTHA_STATUS: decode_partha_status,
}


def decode_aggr_task(payload: bytes, nevents: int, host_id: int,
                     session: "RefSession | None" = None
                     ) -> tuple[np.ndarray, list]:
    fsz = REF_AGGR_TASK_DT.itemsize
    _check_nevents(nevents, payload, fsz, wire.MAX_TASKS_PER_BATCH,
                   "aggr_task")
    out = np.zeros(nevents, wire.AGGR_TASK_DT)
    names: list = []
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"aggr_task record {i} truncated")
        rec = np.frombuffer(payload, REF_AGGR_TASK_DT, count=1,
                            offset=off)[0]
        ilen = int(rec["issue_string_len"])
        end = off + fsz + ilen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"aggr_task record {i} overflows frame")
        r = out[i]
        for f in ("aggr_task_id", "tcp_kbytes", "tcp_conns",
                  "total_cpu_pct", "rss_mb", "cpu_delay_msec",
                  "vm_delay_msec", "blkio_delay_msec", "ntasks_total",
                  "ntasks_issue", "curr_state", "curr_issue"):
            r[f] = rec[f]
        comm = rec["onecomm"].tobytes().split(b"\x00", 1)[0].decode(
            "utf-8", "replace")
        if comm:
            nid = InternTable.intern(comm, wire.NAME_KIND_COMM)
            r["comm_id"] = nid
            names.append((wire.NAME_KIND_COMM, nid, comm))
        # task→listener linkage from the session's LISTEN_TASKMAP map
        # (sessionless callers: 0 = unlinked)
        if session is not None:
            r["related_listen_id"] = session.rel_of_task.get(
                int(rec["aggr_task_id"]), 0)
        r["host_id"] = host_id
        off = end
    return out, names


def decode_new_listener(payload: bytes, nevents: int, host_id: int
                        ) -> tuple[np.ndarray, list]:
    """NEW_LISTENER walk → GYT LISTENER_INFO records (the svcinfo
    registry feed) + intern entries for comm/cmdline strings."""
    fsz = REF_NEW_LISTENER_DT.itemsize
    _check_nevents(nevents, payload, fsz, 2048, "new_listener")
    out = np.zeros(nevents, wire.LISTENER_INFO_DT)
    names: list = []
    off = 0
    for i in range(nevents):
        if off + fsz > len(payload):
            raise RefFrameError(f"new_listener record {i} truncated")
        rec = np.frombuffer(payload, REF_NEW_LISTENER_DT, count=1,
                            offset=off)[0]
        cmdlen = int(rec["cmdline_len"])
        end = off + fsz + cmdlen + int(rec["padding_len"])
        if end > len(payload):
            raise RefFrameError(f"new_listener record {i} overflows")
        r = out[i]
        _copy_ip_port(r["addr"], rec["ns_ip_port"])
        for f in ("glob_id", "related_listen_id", "is_any_ip"):
            r[f] = rec[f]
        r["tusec_start"] = rec["tstart_usec"]
        r["pid"] = rec["start_pid"]
        comm = rec["comm"].tobytes().split(b"\x00", 1)[0].decode(
            "utf-8", "replace")
        if comm:
            nid = InternTable.intern(comm, wire.NAME_KIND_COMM)
            r["comm_id"] = nid
            names.append((wire.NAME_KIND_COMM, nid, comm))
        if cmdlen:
            # NAME_KIND_COMM: the kind svcinfo resolves cmdline_id
            # through (utils/svcreg.py:93), same as the GYT agent
            cmdline = payload[off + fsz: off + fsz + cmdlen].split(
                b"\x00", 1)[0].decode("utf-8", "replace")
            nid = InternTable.intern(cmdline, wire.NAME_KIND_COMM)
            r["cmdline_id"] = nid
            names.append((wire.NAME_KIND_COMM, nid, cmdline))
        r["host_id"] = host_id
        off = end
    return out, names


def decode_active_conn(payload: bytes, nevents: int, host_id: int
                       ) -> tuple[np.ndarray, list]:
    """ACTIVE_CONN_STATS → synthetic GYT TCP_CONN records.

    Each reference record aggregates one (listener, client
    process-group) pair's live traffic; the engine's conn fold keys
    flows by 5-tuple, so the synthetic record carries a flow identity
    derived from (listener_glob_id, cli_aggr_task_id, remote machine)
    — unique and STABLE per pair, so repeated stats for the same pair
    hit the same flow slot (bytes accumulate; the distinct-client HLL
    counts each pair once, matching the reference's per-pair
    aggregation in its activeconn tables)."""
    fsz = REF_ACTIVE_CONN_DT.itemsize
    _check_nevents(nevents, payload, fsz, 2048, "active_conn_stats")
    recs = np.frombuffer(payload, REF_ACTIVE_CONN_DT, count=nevents)
    out = np.zeros(nevents, wire.TCP_CONN_DT)
    names: list = []
    out["ser_glob_id"] = recs["listener_glob_id"]
    out["cli_task_aggr_id"] = recs["cli_aggr_task_id"]
    out["bytes_sent"] = recs["bytes_sent"]
    out["bytes_rcvd"] = recs["bytes_received"]
    out["peer_machine_id_hi"] = recs["machid_hi"]
    out["peer_machine_id_lo"] = recs["machid_lo"]
    out["ser_madhava_id"] = recs["remote_madhava_id"]
    # synthetic flow identity: mix the pair ids into the client
    # address bytes + port so decode.conn_batch's flow key is unique
    # per (svc, cli-group, remote machine) and repeatable
    cli_aggr = np.ascontiguousarray(recs["cli_aggr_task_id"])
    mix = np.ascontiguousarray(
        recs["listener_glob_id"]
        ^ np.uint64(0x9E3779B97F4A7C15) * cli_aggr
        ^ recs["machid_lo"])
    ip = out["cli"]["ip"]
    ip[:, 0:8] = mix.view(np.uint8).reshape(-1, 8)
    ip[:, 8:16] = cli_aggr.view(np.uint8).reshape(-1, 8)
    out["cli"]["port"] = (mix & np.uint64(0xFFFF)).astype(np.uint16)
    out["ser"]["port"] = 1
    # server-side observation unless the listener itself is remote
    is_remote_listen = (recs["connflags"] & 2) != 0
    out["flags"] = np.where(is_remote_listen, 0, 2)   # is_accept bit
    out["host_id"] = host_id
    for i in range(nevents):
        for f in ("ser_comm", "cli_comm"):
            s = recs[i][f].tobytes().split(b"\x00", 1)[0].decode(
                "utf-8", "replace")
            if s:
                nid = InternTable.intern(s, wire.NAME_KIND_COMM)
                out[i]["ser_comm_id" if f == "ser_comm"
                       else "cli_comm_id"] = nid
                names.append((wire.NAME_KIND_COMM, nid, s))
    return out, names


def decode_task_top_procs(payload: bytes, nevents: int, host_id: int
                          ) -> tuple[np.ndarray, list]:
    """TASK_TOP_PROCS → GYT AGGR_TASK_STATE records.

    The reference sends top-N CPU / process-group / RSS / fork-rate
    slices per host; GYT's topcpu/toppgcpu/toprss/topfork subsystems
    are sort presets over the task slab, so the slices fold as task
    records (cpu%/rss from the top lists, fork rate from the fork
    list) and the views come out the same way the host-collector path
    produces them (``net/taskproc.py``)."""
    hsz = REF_TOP_HDR_DT.itemsize
    rows: list = []
    names: list = []
    off = 0
    for i in range(nevents):
        if off + hsz > len(payload):
            raise RefFrameError(f"task_top_procs {i} truncated")
        hdr = np.frombuffer(payload, REF_TOP_HDR_DT, count=1,
                            offset=off)[0]
        np_, npg, nrss, nfork = (int(hdr["nprocs"]),
                                 int(hdr["npg_procs"]),
                                 int(hdr["nrss_procs"]),
                                 int(hdr["nfork_procs"]))
        need = (hsz + (np_ + nrss) * REF_TOP_TASK_DT.itemsize
                + npg * REF_TOP_PG_DT.itemsize
                + nfork * REF_TOP_FORK_DT.itemsize)
        # caps are the reference's TASK_MAX_*_N (gy_comm_proto.h:1418);
        # ext_data_len_ is defined as exactly the four arrays' bytes
        # (TASK_TOP_PROCS::validate, gy_comm_proto.cc:677) — a nonzero
        # mismatch means a layout drift we must not guess through
        ext = int(hdr["ext_data_len"])
        if np_ > 15 or npg > 10 or nrss > 8 or nfork > 5 \
                or off + need > len(payload) \
                or (ext and ext != need - hsz):
            raise RefFrameError(f"task_top_procs {i} overflows")
        o = off + hsz
        top = np.frombuffer(payload, REF_TOP_TASK_DT, count=np_,
                            offset=o)
        o += np_ * REF_TOP_TASK_DT.itemsize
        pg = np.frombuffer(payload, REF_TOP_PG_DT, count=npg, offset=o)
        o += npg * REF_TOP_PG_DT.itemsize
        rss = np.frombuffer(payload, REF_TOP_TASK_DT, count=nrss,
                            offset=o)
        o += nrss * REF_TOP_TASK_DT.itemsize
        fork = np.frombuffer(payload, REF_TOP_FORK_DT, count=nfork,
                             offset=o)
        off = off + need
        # group-id keyed merge: one task record per distinct aggr id
        acc: dict = {}

        def _merge(aid, comm, cpupct=0.0, rss_mb=0, ntasks=1,
                   forks=0.0):
            a = acc.setdefault(int(aid), dict(
                comm=comm, cpupct=0.0, rss_mb=0, ntasks=0, forks=0.0))
            a["cpupct"] = max(a["cpupct"], float(cpupct))
            a["rss_mb"] = max(a["rss_mb"], int(rss_mb))
            a["ntasks"] = max(a["ntasks"], int(ntasks))
            a["forks"] = max(a["forks"], float(forks))
        for t in top:
            _merge(t["aggr_task_id"], t["comm"], t["cpupct"],
                   t["rss_mb"])
        for t in pg:
            _merge(t["aggr_task_id"], t["pg_comm"], t["tot_cpupct"],
                   t["tot_rss_mb"], t["ntasks"])
        for t in rss:
            _merge(t["aggr_task_id"], t["comm"], t["cpupct"],
                   t["rss_mb"])
        for t in fork:
            _merge(t["aggr_task_id"], t["comm"],
                   forks=t["nfork_per_sec"])
        for aid, a in acc.items():
            r = np.zeros(1, wire.AGGR_TASK_DT)[0]
            r["aggr_task_id"] = aid
            comm = a["comm"].tobytes().split(b"\x00", 1)[0].decode(
                "utf-8", "replace") if a["comm"] is not None else ""
            if comm:
                nid = InternTable.intern(comm, wire.NAME_KIND_COMM)
                r["comm_id"] = nid
                names.append((wire.NAME_KIND_COMM, nid, comm))
            r["total_cpu_pct"] = a["cpupct"]
            r["rss_mb"] = a["rss_mb"]
            r["ntasks_total"] = max(a["ntasks"], 1)
            r["forks_sec"] = a["forks"]
            r["host_id"] = host_id
            rows.append(r)
    out = np.array(rows, wire.AGGR_TASK_DT) if rows \
        else np.empty(0, wire.AGGR_TASK_DT)
    return out, names


# subtype → (decoder, gyt_subtype, wants_session): session-aware
# decoders take the per-conn RefSession as a keyword (table-encoded so
# the dispatch loop stays generic as stateful subtypes accumulate)
_DECODER_OF = {
    REF_NOTIFY_TCP_CONN: (decode_tcp_conn, wire.NOTIFY_TCP_CONN,
                          False),
    REF_NOTIFY_LISTENER_STATE: (decode_listener_state,
                                wire.NOTIFY_LISTENER_STATE, False),
    REF_NOTIFY_AGGR_TASK_STATE: (decode_aggr_task,
                                 wire.NOTIFY_AGGR_TASK_STATE, True),
    REF_NOTIFY_NEW_LISTENER: (decode_new_listener,
                              wire.NOTIFY_LISTENER_INFO, False),
    REF_NOTIFY_ACTIVE_CONN_STATS: (decode_active_conn,
                                   wire.NOTIFY_TCP_CONN, False),
    REF_NOTIFY_TASK_TOP_PROCS: (decode_task_top_procs,
                                wire.NOTIFY_AGGR_TASK_STATE, False),
    REF_NOTIFY_CPU_MEM_STATE: (decode_cpu_mem,
                               wire.NOTIFY_CPU_MEM_STATE, True),
    REF_NOTIFY_HOST_STATE: (decode_host_state,
                            wire.NOTIFY_HOST_STATE, False),
    REF_NOTIFY_HOST_INFO: (decode_host_info,
                           wire.NOTIFY_HOST_INFO, True),
    REF_NOTIFY_REQ_TRACE_TRAN: (decode_req_trace_tran,
                                wire.NOTIFY_REQ_TRACE, False),
    REF_NOTIFY_PING_TASK_AGGR: (decode_ping_task_aggr,
                                wire.NOTIFY_TASK_PING, False),
}


# ------------------------------------------------ registration handshake
def _cstr(rec_field) -> str:
    return rec_field.tobytes().split(b"\x00", 1)[0].decode(
        "utf-8", "replace")


def parse_ps_register_req(body: bytes) -> dict:
    """PS_REGISTER_REQ_S payload → field dict (raises on short body)."""
    if len(body) < REF_PS_REGISTER_REQ_DT.itemsize:
        raise RefFrameError("short PS_REGISTER_REQ_S")
    r = np.frombuffer(body, REF_PS_REGISTER_REQ_DT, count=1)[0]
    return {
        "comm_version": int(r["comm_version"]),
        "partha_version": int(r["partha_version"]),
        "min_shyama_version": int(r["min_shyama_version"]),
        "machine_id_hi": int(r["machine_id_hi"]),
        "machine_id_lo": int(r["machine_id_lo"]),
        "hostname": _cstr(r["hostname"]),
        "cluster_name": _cstr(r["cluster_name"]),
        "region_name": _cstr(r["region_name"]),
        "zone_name": _cstr(r["zone_name"]),
        "kern_version_num": int(r["kern_version_num"]),
        "last_madhava_id": int(r["last_madhava_id"]),
    }


def parse_pm_connect_cmd(body: bytes) -> dict:
    """PM_CONNECT_CMD_S payload → field dict."""
    if len(body) < REF_PM_CONNECT_CMD_DT.itemsize:
        raise RefFrameError("short PM_CONNECT_CMD_S")
    r = np.frombuffer(body, REF_PM_CONNECT_CMD_DT, count=1)[0]
    return {
        "comm_version": int(r["comm_version"]),
        "partha_version": int(r["partha_version"]),
        "min_madhava_version": int(r["min_madhava_version"]),
        "machine_id_hi": int(r["machine_id_hi"]),
        "machine_id_lo": int(r["machine_id_lo"]),
        "partha_ident_key": int(r["partha_ident_key"]),
        "hostname": _cstr(r["hostname"]),
        "cluster_name": _cstr(r["cluster_name"]),
        "region_name": _cstr(r["region_name"]),
        "zone_name": _cstr(r["zone_name"]),
        "madhava_id": int(r["madhava_id"]),
        "cli_type": int(r["cli_type"]),
    }


def _ref_frame(data_type: int, payload: np.ndarray, magic: int) -> bytes:
    hdr = np.zeros(1, REF_HEADER_DT)
    hdr[0]["magic"] = magic
    hdr[0]["total_sz"] = _HSZ + payload.nbytes
    hdr[0]["data_type"] = data_type
    return hdr.tobytes() + payload.tobytes()


def encode_ps_register_resp(error_code: int, error_string: str,
                            madhava_hostname: str, madhava_port: int,
                            partha_ident_key: int, madhava_id: int,
                            curr_sec: int) -> bytes:
    """Byte-exact PS_REGISTER_RESP_S frame (the shyama reply that
    points the partha at its madhava — here: ourselves)."""
    r = np.zeros(1, REF_PS_REGISTER_RESP_DT)
    v = r[0]
    v["error_code"] = error_code
    v["error_string"] = error_string.encode()[:255]
    v["comm_version"] = REF_COMM_VERSION
    v["shyama_version"] = REF_MADHAVA_VERSION
    v["shyama_id"] = madhava_id ^ 0x5359414D41       # distinct role id
    v["partha_ident_key"] = partha_ident_key
    v["madhava_expiry_sec"] = curr_sec + 900
    v["madhava_id"] = madhava_id
    v["madhava_port"] = madhava_port
    v["madhava_hostname"] = madhava_hostname.encode()[:255]
    v["madhava_name"] = b"gyt-tpu"
    return _ref_frame(REF_COMM_PS_REGISTER_RESP, r, REF_MAGIC_PS)


def encode_pm_connect_resp(error_code: int, error_string: str,
                           madhava_id: int, curr_sec: int) -> bytes:
    """Byte-exact PM_CONNECT_RESP_S frame."""
    r = np.zeros(1, REF_PM_CONNECT_RESP_DT)
    v = r[0]
    v["error_code"] = error_code
    v["error_string"] = error_string.encode()[:255]
    v["madhava_id"] = madhava_id
    v["comm_version"] = REF_COMM_VERSION
    v["madhava_version"] = REF_MADHAVA_VERSION
    v["madhava_name"] = b"gyt-tpu"
    v["curr_sec"] = curr_sec
    v["clock_sec"] = curr_sec
    return _ref_frame(REF_COMM_PM_CONNECT_RESP, r, REF_MAGIC_PM)


def encode_ps_register_req(machine_id_hi: int, machine_id_lo: int,
                           hostname: str = "parthahost",
                           partha_version: int = 0x000501,
                           comm_version: int = REF_COMM_VERSION,
                           curr_sec: int = 0) -> bytes:
    """Synthesized stock-partha PS_REGISTER_REQ_S (fixture source —
    what partha/gy_paconnhdlr.cc:1730 sends)."""
    r = np.zeros(1, REF_PS_REGISTER_REQ_DT)
    v = r[0]
    v["comm_version"] = comm_version
    v["partha_version"] = partha_version
    v["min_shyama_version"] = 0x000500
    v["machine_id_hi"] = machine_id_hi
    v["machine_id_lo"] = machine_id_lo
    v["hostname"] = hostname.encode()[:255]
    v["cluster_name"] = b"cluster0"
    v["curr_sec"] = curr_sec
    return _ref_frame(REF_COMM_PS_REGISTER_REQ, r, REF_MAGIC_PS)


def encode_pm_connect_cmd(machine_id_hi: int, machine_id_lo: int,
                          partha_ident_key: int, madhava_id: int,
                          hostname: str = "parthahost",
                          partha_version: int = 0x000501,
                          comm_version: int = REF_COMM_VERSION,
                          min_madhava_version: int = 0x000500,
                          cli_type: int = REF_CLI_TYPE_REQ_ONLY,
                          curr_sec: int = 0, region: str = "",
                          zone: str = "") -> bytes:
    """Synthesized stock-partha PM_CONNECT_CMD_S."""
    r = np.zeros(1, REF_PM_CONNECT_CMD_DT)
    v = r[0]
    v["comm_version"] = comm_version
    v["partha_version"] = partha_version
    v["min_madhava_version"] = min_madhava_version
    v["machine_id_hi"] = machine_id_hi
    v["machine_id_lo"] = machine_id_lo
    v["partha_ident_key"] = partha_ident_key
    v["hostname"] = hostname.encode()[:255]
    v["cluster_name"] = b"cluster0"
    v["region_name"] = region.encode()[:63]
    v["zone_name"] = zone.encode()[:63]
    v["madhava_id"] = madhava_id
    v["cli_type"] = cli_type
    v["curr_sec"] = curr_sec
    return _ref_frame(REF_COMM_PM_CONNECT_CMD, r, REF_MAGIC_PM)


def parse_ps_register_resp(buf: bytes) -> dict:
    """Client-side decode of PS_REGISTER_RESP_S (fixture assertions)."""
    hdr = np.frombuffer(buf, REF_HEADER_DT, count=1)[0]
    r = np.frombuffer(buf, REF_PS_REGISTER_RESP_DT, count=1,
                      offset=_HSZ)[0]
    return {"data_type": int(hdr["data_type"]),
            "error_code": int(r["error_code"]),
            "error_string": _cstr(r["error_string"]),
            "partha_ident_key": int(r["partha_ident_key"]),
            "madhava_id": int(r["madhava_id"]),
            "madhava_port": int(r["madhava_port"]),
            "madhava_hostname": _cstr(r["madhava_hostname"])}


def parse_pm_connect_resp(buf: bytes) -> dict:
    hdr = np.frombuffer(buf, REF_HEADER_DT, count=1)[0]
    r = np.frombuffer(buf, REF_PM_CONNECT_RESP_DT, count=1,
                      offset=_HSZ)[0]
    return {"data_type": int(hdr["data_type"]),
            "error_code": int(r["error_code"]),
            "error_string": _cstr(r["error_string"]),
            "madhava_id": int(r["madhava_id"]),
            "madhava_version": int(r["madhava_version"])}


def adapt(buf: bytes, host_id: int,
          session: "RefSession | None" = None) -> tuple[bytes, int]:
    """Reference byte stream → GYT wire frames, ready for
    ``Runtime.feed``.

    Walks COMM_HEADER frames (trailing partial frame left for the
    caller, epoll-resume semantics like ``wire.decode_frames``);
    adapts known partha→madhava event subtypes, emits NAME_INTERN
    frames for every trailing string, and skips unknown subtypes
    frame-whole. ``session`` carries per-connection adapter state
    (the LISTEN_TASKMAP task→listener linkage). Returns
    ``(gyt_bytes, consumed)``.
    """
    out: list[bytes] = []
    off = 0
    n = len(buf)
    while off + _HSZ <= n:
        hdr = np.frombuffer(buf, REF_HEADER_DT, count=1, offset=off)[0]
        if int(hdr["magic"]) not in REF_MAGICS:
            raise RefFrameError(f"bad reference magic "
                                f"0x{int(hdr['magic']):08x}")
        total = int(hdr["total_sz"])
        if total < _HSZ or total >= wire.MAX_COMM_DATA_SZ:
            raise RefFrameError(f"bad total_sz {total}")
        if off + total > n:
            break                         # partial frame: resume later
        pad = int(hdr["padding_sz"])
        if pad > total - _HSZ:            # unvalidated pad would slice
            raise RefFrameError(          # outside the declared frame
                f"bad padding_sz {pad} for total_sz {total}")
        if int(hdr["data_type"]) == REF_COMM_EVENT_NOTIFY \
                and total - pad >= _HSZ + _ESZ:
            ev = np.frombuffer(buf, REF_EVENT_NOTIFY_DT, count=1,
                               offset=off + _HSZ)[0]
            subtype = int(ev["subtype"])
            # payload slices LAZILY: unknown subtypes skip frame-whole
            # without paying a bytes copy on the ingest hot path
            sdec = _SESSION_DECODERS.get(subtype)
            if sdec is not None:
                # stateful, frameless: consumed into the session
                if session is not None:
                    sdec(buf[off + _HSZ + _ESZ: off + total - pad],
                         int(ev["nevents"]), session)
                    session.n_events[subtype] += int(ev["nevents"])
                off += total
                continue
            dec = _DECODER_OF.get(subtype)
            if dec is not None:
                fn, gyt_subtype, wants_session = dec
                payload = buf[off + _HSZ + _ESZ: off + total - pad]
                if wants_session:
                    recs, names = fn(payload, int(ev["nevents"]),
                                     host_id, session=session)
                else:
                    recs, names = fn(payload, int(ev["nevents"]),
                                     host_id)
                if names:
                    out.append(wire.encode_frames_chunked(
                        wire.NOTIFY_NAME_INTERN,
                        InternTable.records(names)))
                out.append(wire.encode_frames_chunked(gyt_subtype,
                                                      recs))
                if session is not None:
                    session.n_events[subtype] += len(recs)
            elif session is not None:
                session.n_skipped += 1
        elif session is not None:
            # non-NOTIFY data types and truncated NOTIFY bodies skip
            # frame-whole too — count them so data loss is visible
            session.n_skipped += 1
        off += total
    return b"".join(out), off
