"""Real process collection: /proc walk → AGGR_TASK_STATE records.

The userspace analogue of the reference's task handler: it watches
fork/exec/exit via the proc connector and aggregates tasks into
process groups keyed by a comm+cgroup hash
(``common/gy_task_handler.cc:2568``, aggr id construction
``gy_task_handler.h:180``). Without netlink-connector privileges the
same information is recovered by sweeping ``/proc/[pid]`` on the 5s
cadence:

- **grouping**: pids aggregate by ``comm`` into the same stable
  ``aggr_task_id`` the TCP collector stamps on outbound conns
  (:func:`gyeeta_tpu.net.tcpconn.aggr_task_id_of`), so conn→task joins
  line up without coordination.
- **cpu%**: delta of utime+stime across sweeps over wall time.
- **delays**: ``/proc/[pid]/schedstat`` field 2 is time spent waiting
  on the runqueue — the userspace stand-in for taskstats
  ``cpu_delay_total``; ``delayacct_blkio_ticks`` (stat field 42) gives
  block-IO delay when delayacct is on.
- **forks**: pids whose ``starttime`` postdates the previous sweep
  count as forks in their group (plus exits inferred by
  disappearance) — the TOPFORK signal.

Everything is delta-based and privilege-graceful: unreadable pids
(other users' /proc under hidepid, racing exits) are skipped, never
raised.
"""

from __future__ import annotations

import os
import time

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.net.tcpconn import aggr_task_id_of
from gyeeta_tpu.utils.intern import InternTable

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE_MB = (os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf")
            else 4096) / (1 << 20)


def _read_pid(pid: str):
    """One process sample: (comm, cpu_ticks, rss_mb, starttime_ticks,
    blkio_ticks, runq_wait_ns) or None on any error (racing exit)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # comm may contain spaces/parens: split around the LAST ')'
        lp = data.rindex(b")")
        comm = data[data.index(b"(") + 1: lp].decode(
            "utf-8", "replace")[:16]
        rest = data[lp + 2:].split()
        utime, stime = int(rest[11]), int(rest[12])
        starttime = int(rest[19])
        rss_pages = int(rest[21])
        blkio = int(rest[39]) if len(rest) > 39 else 0
        runq = 0
        try:
            with open(f"/proc/{pid}/schedstat", "rb") as f:
                parts = f.read().split()
            if len(parts) >= 2:
                runq = int(parts[1])
        except (OSError, ValueError):
            pass
        return (comm, utime + stime, rss_pages * _PAGE_MB, starttime,
                blkio, runq)
    except (OSError, ValueError, IndexError):
        return None


class ProcTaskCollector:
    """5s-cadence /proc sweep → per-process-group wire records.

    ``sweep(task_net=None, listener_of_comm=None)`` →
    (AGGR_TASK_DT records, NAME_INTERN records). ``task_net`` is the
    TCP collector's per-group {aggr_id: [kbytes, nconns]} traffic map;
    ``listener_of_comm`` maps a comm to its listener glob_id so
    serving groups carry ``related_listen_id`` (the task↔svc join the
    reference maintains via its listener↔task tables).
    """

    def __init__(self, host_id: int = 0, machine_id: int = 1,
                 max_groups: int = wire.MAX_TASKS_PER_BATCH,
                 netlink_delays: bool = True):
        self.host_id = host_id
        self.machine_id = machine_id
        self.max_groups = max_groups
        self._prev_pids: dict = {}     # pid -> starttime (fork detect)
        self._prev_group: dict = {}    # comm -> [cpu_ticks, blkio, runq]
        self._prev_vm: dict = {}       # comm -> vm_delay_ns total
        self._prev_t = 0.0
        self._announced: set = set()   # comm ids already name-announced
        # netlink TASKSTATS: swap-in + reclaim + thrashing delays, the
        # classes schedstat cannot see (ref gy_acct_taskstat.h:209).
        # Privilege-gated: None when CAP_NET_ADMIN/kernel support is
        # absent — vm_delay_msec then stays 0 (documented degradation)
        self._td = None
        if netlink_delays:
            from gyeeta_tpu.net import taskdelays
            if taskdelays.available():
                self._td = taskdelays.TaskDelayReader()
        # cn_proc connector: EVENT-accurate fork counting (ref consumes
        # the proc-connector stream, gy_misc.h:1181) — replaces the
        # starttime/appearance inference when the multicast join is
        # permitted; same degradation discipline as the delays
        self._pc = None
        if netlink_delays:
            from gyeeta_tpu.net import procconn
            if procconn.available():
                try:
                    self._pc = procconn.ProcConnector()
                except OSError:
                    self._pc = None

    def sweep(self, task_net=None, listener_of_comm=None
              ) -> tuple[np.ndarray, np.ndarray]:
        now = time.monotonic()
        dt = max(now - self._prev_t, 1e-3) if self._prev_t else 0.0
        first = self._prev_t == 0.0
        self._prev_t = now
        task_net = task_net or {}
        listener_of_comm = listener_of_comm or {}

        try:
            pids = [d for d in os.listdir("/proc") if d.isdigit()]
        except OSError:
            return (np.empty(0, wire.AGGR_TASK_DT),
                    np.empty(0, wire.NAME_INTERN_DT))

        groups: dict = {}   # comm -> [cpu, rss, n, forks, blkio, runq]
        vm_now: dict = {}   # comm -> swap+reclaim+thrash delay ns total
        cur_pids: dict = {}
        comm_of_pid: dict = {}
        for pid in pids:
            s = _read_pid(pid)
            if s is None:
                continue
            comm, cpu, rss, starttime, blkio, runq = s
            cur_pids[pid] = starttime
            comm_of_pid[int(pid)] = comm
            g = groups.setdefault(comm, [0, 0.0, 0, 0, 0, 0])
            g[0] += cpu
            g[1] += rss
            g[2] += 1
            prev_start = self._prev_pids.get(pid)
            if not first and (prev_start is None
                              or prev_start != starttime):
                g[3] += 1              # new pid (or pid reuse) = a fork
            g[4] += blkio
            g[5] += runq
            if self._td is not None:
                d = self._td.get(int(pid))
                if d is not None:
                    vm_now[comm] = (vm_now.get(comm, 0)
                                    + d["swapin_delay_ns"]
                                    + d["freepages_delay_ns"]
                                    + d["thrashing_delay_ns"])
        self._prev_pids = cur_pids

        if self._pc is not None:
            # event-accurate forks override the starttime inference:
            # count FORK events by the parent's comm group (parent
            # resolved from this sweep's /proc read; a parent that
            # already exited falls through silently)
            from gyeeta_tpu.net.procconn import PROC_EVENT_FORK
            ev_forks: dict = {}
            for e in self._pc.poll():
                # new PROCESSES only: a thread clone also emits FORK
                # but with child_pid != child_tgid — counting those
                # would inflate thread-pool-heavy comms
                if e.what == PROC_EVENT_FORK \
                        and e.child_pid == e.child_tgid:
                    comm = comm_of_pid.get(e.tgid)
                    if comm is not None:
                        ev_forks[comm] = ev_forks.get(comm, 0) + 1
            for comm, nf in ev_forks.items():
                if comm in groups:
                    groups[comm][3] = nf

        # truncation: primary order is group size (the taskstate /
        # topcpu signal), with a BOUNDED reserve of slots for the top
        # fork-churners a by-size sort would drop (single-pid
        # respawners, the TOPFORK signal) — neither signal can evict
        # the other wholesale
        comms = sorted(groups, key=lambda c: -groups[c][2])
        if len(comms) > self.max_groups:
            nres = max(self.max_groups // 8, 1)
            base = comms[: self.max_groups - nres]
            kept = set(base)
            forkers = [c for c in sorted(
                groups, key=lambda c: -groups[c][3])
                if groups[c][3] > 0 and c not in kept][:nres]
            # unused reserve slots go back to the by-size order
            fill = [c for c in comms[len(base):]
                    if c not in forkers][: nres - len(forkers)]
            comms = base + forkers + fill
        # baselines advance for EVERY group each sweep — a group capped
        # out of the report must not accumulate multi-sweep deltas that
        # later get divided by a single dt
        prev_of = {c: self._prev_group.get(
            c, [groups[c][0], groups[c][4], groups[c][5]])
            for c in comms}
        self._prev_group = {c: [g[0], g[4], g[5]]
                            for c, g in groups.items()}
        prev_vm_of, self._prev_vm = self._prev_vm, dict(vm_now)
        out = np.zeros(len(comms), wire.AGGR_TASK_DT)
        names = []
        from gyeeta_tpu.semantic import states as S
        for i, comm in enumerate(comms):
            cpu, rss, n, forks, blkio, runq = groups[comm]
            pg = prev_of[comm]
            aggr_id = aggr_task_id_of(self.machine_id, comm)
            comm_id = InternTable.intern(comm, wire.NAME_KIND_COMM)
            if comm_id not in self._announced:
                self._announced.add(comm_id)
                names.append((wire.NAME_KIND_COMM, comm_id, comm))
            r = out[i]
            r["aggr_task_id"] = aggr_id
            r["comm_id"] = comm_id
            r["related_listen_id"] = listener_of_comm.get(comm, 0)
            net = task_net.get(aggr_id)
            if net:
                r["tcp_kbytes"] = min(int(net[0]), 2**32 - 1)
                r["tcp_conns"] = min(int(net[1]), 2**32 - 1)
            if dt:
                r["total_cpu_pct"] = 100.0 * max(cpu - pg[0], 0) \
                    / _CLK_TCK / dt
                # delays accumulated THIS sweep (ns / ticks → msec)
                r["cpu_delay_msec"] = min(
                    max(runq - pg[2], 0) / 1e6, 2**31)
                r["blkio_delay_msec"] = min(
                    max(blkio - pg[1], 0) * 1000.0 / _CLK_TCK, 2**31)
                if comm in vm_now:
                    pv = prev_vm_of.get(comm, vm_now[comm])
                    r["vm_delay_msec"] = min(
                        max(vm_now[comm] - pv, 0) / 1e6, 2**31)
                r["forks_sec"] = forks / dt
            r["rss_mb"] = min(int(rss), 2**32 - 1)
            r["ntasks_total"] = min(n, 2**16 - 1)
            cpu_d = float(r["cpu_delay_msec"])
            io_d = float(r["blkio_delay_msec"])
            issue = cpu_d > 500 or io_d > 300
            r["ntasks_issue"] = min(n, 2**16 - 1) if issue else 0
            r["curr_state"] = (
                S.STATE_SEVERE if cpu_d > 1200 else
                S.STATE_BAD if issue else
                S.STATE_OK if float(r["total_cpu_pct"]) > 1.0
                else S.STATE_IDLE)
            r["curr_issue"] = (
                S.TISSUE_CPU_DELAY if cpu_d > 500 else
                S.TISSUE_BLKIO_DELAY if io_d > 300 else S.TISSUE_NONE)
            r["host_id"] = self.host_id
        return out, (InternTable.records(names) if names
                     else np.empty(0, wire.NAME_INTERN_DT))

    def close(self) -> None:
        if self._td is not None:
            self._td.close()
            self._td = None
        if self._pc is not None:
            self._pc.close()
            self._pc = None
