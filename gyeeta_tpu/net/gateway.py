"""Query-fabric gateway: fan-in proxy + distributed edge cache + push.

The serving edge after PR 9/10 is snapshot-isolated and cached PER
PROCESS — but dashboards still poll ONE replica, and every replica
renders the same snapshot independently. This tier is the missing
multiplier (ROADMAP open item 3): a thin asyncio proxy that speaks the
EXISTING query edges on the front and fans out to N serve replicas on
the back, with the render shared fleet-wide:

- **One port, three dialects** (magic-peeked like ``GytServer``):
  HTTP/REST (``POST /query``, ``GET /v1/<subsys>``, SSE
  ``GET /v1/subscribe``, ``/metrics``, ``/healthz``), the GYT binary
  query protocol (``COMM_QUERY_CMD`` + the ``COMM_SUBSCRIBE_CMD``
  stream), and the stock NM node-webserver dialect
  (``net/nmhandle.py`` — a stock Node tier can point at a gateway
  unchanged).

- **(snaptick, request-hash) edge cache**: every snapshot-tier
  response already carries ``snaptick`` — the designed distributed
  cache key. Requests key through the SAME normalizer as the
  replica-side result cache (``query/normalize.py``), entries live in
  an in-gateway LRU, and invalidation is BY TICK ADVANCE (a new tick
  is a new key; old entries age out of the LRU) — no invalidation
  protocol at all. SINGLE-FLIGHT collapse at the (tick, key) level
  means a dashboard stampede onto a fresh tick renders each distinct
  query exactly once per gateway; the peer exchange (below) makes
  that once per FLEET. Upstream error envelopes negative-cache for
  ``GYT_GW_NEG_TTL_S`` so a bad query in a dashboard loop cannot
  hammer the replicas.

- **Peer exchange**: gateways gossip results, not liveness — on a
  local miss the gateway asks its peers for (tick, key) over a tiny
  HTTP POST (``/gw/peer``) before rendering upstream; the peer answers
  from its cache, WAITING on its own in-flight single-flight render if
  one is running. A result rendered once serves the whole tier.

- **Push subscriptions** (``net/subs.py``): the gateway polls each
  upstream's ``serverstatus`` once per tick (ONE cheap cached query
  per upstream per tick — not per client), and when ``snaptick``
  advances it re-renders each subscribed query once THROUGH the edge
  cache, diffs against the last delivered version
  (``query/delta.py``), and pushes the delta to every subscriber —
  REST SSE and GYT binary both.

- **Fault domains** (ISSUE 15): every upstream carries a circuit
  breaker — EWMA latency + a consecutive-failure count with a
  K-failure threshold (``--gw-down-after``; ONE bad poll never marks
  a replica down), half-open probing on a jittered exponential
  backoff, and per-upstream state on the labeled
  ``gyt_gw_upstream_state{upstream,state}`` gauge family (flaps
  counted in ``gyt_gw_upstream_flaps_total{upstream}``). Renders
  fail over health-ordered — live replicas first, marked-down ones
  tried LAST rather than never, so a fabric with >=1 live replica
  never surfaces an upstream error — and a render that exceeds the
  hedge latency budget (``GYT_GW_HEDGE_MS``) fires the same request
  at the next-healthiest replica, first response wins (the wedged-
  not-dead replica case: the breaker only opens on failures, the
  hedge bounds the latency meanwhile). Subscription state survives
  gateway restarts via the hub's persisted version ring
  (``--sub-persist``, ``net/subs.py``).

The gateway is deliberately **jax-free** (it imports the thin-client
half of the tree only): it can run on any box between the dashboards
and the replicas, and N gateways scale the query edge without touching
the fold tier. Metrics are first-class: its own ``Stats`` registry
renders at ``GET /metrics`` as the ``gyt_gw_*`` families
(OPERATIONS.md "Query fabric").
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import time
import urllib.parse
from collections import OrderedDict
from typing import Optional

from gyeeta_tpu.net.agent import QueryClient
from gyeeta_tpu.query.normalize import request_key
from gyeeta_tpu.utils.selfstats import Stats

log = logging.getLogger("gyeeta_tpu.net.gateway")

_MAX_BODY = 8 << 20
_MAX_HDR = 64 << 10

# the tick-watch poll request: answered from the replica's snapshot
# result cache after the first ask per tick (~a dict lookup upstream)
_POLL_REQ = {"subsys": "serverstatus", "maxrecs": 1}


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _envi(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class _Upstream:
    """One serve replica: a small checkout pool of query conns, the
    watcher's last-seen snaptick, and the circuit-breaker health
    state — EWMA latency, a consecutive-failure count (K failures
    before mark-down, never one bad poll), and half-open probing on
    a jittered exponential backoff."""

    def __init__(self, host: str, port: int, nconns: int,
                 stats: Optional[Stats] = None, down_after: int = 3,
                 probe_base_s: float = 1.0, probe_max_s: float = 15.0):
        self.host, self.port = host, int(port)
        self.label = f"{host}:{int(port)}"
        self.tick = -1
        self.tick_at = 0.0
        self.stats = stats
        self.state = "up"           # up | down | half_open
        self.fails = 0              # CONSECUTIVE failures
        self.ewma_ms: Optional[float] = None
        self.down_after = max(1, int(down_after))
        self.probe_base_s = float(probe_base_s)
        self.probe_max_s = float(probe_max_s)
        self.backoff_s = self.probe_base_s
        self.probe_at = 0.0
        self._pool: asyncio.Queue = asyncio.Queue()
        for _ in range(max(1, nconns)):
            self._pool.put_nowait(None)
        self._gauge_state()

    # ------------------------------------------------------- circuit
    @property
    def up(self) -> bool:
        return self.state == "up"

    def _gauge_state(self) -> None:
        if self.stats is None:
            return
        for st in ("up", "down", "half_open"):
            self.stats.gauge(
                f"gw_upstream_state|upstream={self.label},state={st}",
                1.0 if st == self.state else 0.0)
        if self.ewma_ms is not None:
            self.stats.gauge(
                f"gw_upstream_ewma_ms|upstream={self.label}",
                round(self.ewma_ms, 3))

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self._gauge_state()

    def record_ok(self, lat_ms: float) -> None:
        self.ewma_ms = lat_ms if self.ewma_ms is None \
            else 0.7 * self.ewma_ms + 0.3 * lat_ms
        self.fails = 0
        self.backoff_s = self.probe_base_s
        if self.state != "up":
            if self.stats is not None:
                self.stats.bump("gw_upstream_recoveries"
                                f"|upstream={self.label}")
            self._set_state("up")
        else:
            self._gauge_state()         # refresh the EWMA gauge

    def record_fail(self) -> None:
        self.fails += 1
        if self.state == "up":
            if self.fails < self.down_after:
                return                  # the one-bad-poll fix: wait K
            if self.stats is not None:
                self.stats.bump("gw_upstream_flaps"
                                f"|upstream={self.label}")
            self._set_state("down")
            self._arm_probe()
            return
        # a failed half-open probe (or a failed last-resort attempt):
        # stay down, back off further
        self._set_state("down")
        self.backoff_s = min(self.backoff_s * 2.0, self.probe_max_s)
        self._arm_probe()

    def _arm_probe(self) -> None:
        import random as _r
        self.probe_at = time.monotonic() \
            + self.backoff_s * (0.5 + _r.random())

    def probe_due(self) -> bool:
        return self.state != "down" or time.monotonic() >= self.probe_at

    # ---------------------------------------------------------- pool
    async def checkout(self, timeout: float) -> QueryClient:
        qc = await self._pool.get()
        if qc is None:
            qc = QueryClient(request_timeout=timeout)
            try:
                await qc.connect(self.host, self.port)
            except BaseException:
                self._pool.put_nowait(None)
                raise
        return qc

    def checkin(self, qc: Optional[QueryClient]) -> None:
        self._pool.put_nowait(qc)

    async def discard(self, qc: QueryClient) -> None:
        self._pool.put_nowait(None)
        try:
            await qc.close()
        except Exception:       # noqa: BLE001
            pass


#: SubscribeStream counter -> gateway stat, folded as deltas per relay
_HUB_FOLD = (("events", "gw_region_events"),
             ("event_bytes", "gw_region_event_bytes"),
             ("resyncs", "gw_region_resyncs"),
             ("forced_resyncs", "gw_region_forced_resyncs"),
             ("reconnects", "gw_region_reconnects"),
             ("stalls", "gw_region_stalls"),
             ("conn_errors", "gw_region_conn_errors"),
             ("conn_lost", "gw_region_conn_lost"))


class _HubRelay:
    """One inter-region subscription: a supervised
    :class:`~gyeeta_tpu.net.subs.SubscribeStream` to the peer region's
    gateway front, holding the latest FULL response for its key. The
    local ``SubscriptionHub`` fetches from the held version, so every
    local dashboard subscriber and CQ group on this key rides ONE WAN
    delta stream; a WAN gap surfaces as the stream's counted, in-band
    ``resync`` full (``gyt_gw_region_resyncs_total``), never as silent
    divergence, and inter-region bytes follow delta churn
    (``gyt_gw_region_event_bytes_total``), not panel size."""

    __slots__ = ("gw", "key", "req", "held", "tick", "last_used",
                 "stream", "task", "_folded", "_advanced")

    def __init__(self, gw: "FabricGateway", req: dict, key: str):
        from gyeeta_tpu.net.subs import SubscribeStream
        self.gw, self.key = gw, key
        self.req = {k: v for k, v in req.items()
                    if k not in ("last_snaptick", "subscribe")}
        self.held: Optional[dict] = None
        self.tick = -1
        self.last_used = time.monotonic()
        self._folded: collections.Counter = collections.Counter()
        self._advanced = asyncio.Event()
        self.stream = SubscribeStream(
            [(u.host, u.port) for u in gw.upstreams], self.req,
            stall_timeout=gw.hub_stall_s)
        self.task = asyncio.create_task(self._run())

    def done(self) -> bool:
        return self.task.done()

    def stop(self) -> None:
        self.stream.stop()
        self.task.cancel()

    def fold(self) -> None:
        """Publish the stream's counter DELTAS since the last fold
        onto the gateway's gyt_gw_region_* families."""
        c = self.stream.counters
        for src, dst in _HUB_FOLD:
            d = c[src] - self._folded[src]
            if d:
                self.gw.stats.bump(dst, d)
                self._folded[src] = c[src]

    async def _run(self) -> None:
        try:
            async for resp in self.stream.responses():
                self.held = resp
                st = resp.get("snaptick")
                if st is not None and int(st) > self.tick:
                    self.tick = int(st)
                ev, self._advanced = self._advanced, asyncio.Event()
                ev.set()
                self.fold()
                self.gw._hub_advance(self.tick)     # noqa: SLF001
        except asyncio.CancelledError:
            raise
        except Exception:       # noqa: BLE001 — relay dies visibly
            self.gw.stats.bump("gw_region_relay_errors")
            log.exception("hub relay %s failed", self.key)

    async def current(self, target: int, settle_s: float,
                      first_s: float) -> Optional[dict]:
        """The latest held full, waiting (bounded) for the relay to
        reach ``target``: ``first_s`` budget before the FIRST full
        (a fresh WAN subscribe), ``settle_s`` for a tick to land.
        Returns whatever is held when the budget runs out — a lagging
        view, or None when the WAN is down before the first full."""
        t0 = time.monotonic()
        while self.held is None or self.tick < target:
            budget = (first_s if self.held is None else settle_s) \
                - (time.monotonic() - t0)
            if budget <= 0 or self.done():
                break
            ev = self._advanced
            try:
                await asyncio.wait_for(ev.wait(), budget)
            except (asyncio.TimeoutError, TimeoutError):
                break
        return self.held


class FabricGateway:
    def __init__(self, upstreams, host: str = "127.0.0.1",
                 port: int = 0, peers=(), stats: Optional[Stats] = None,
                 poll_s: Optional[float] = None,
                 cache_max: Optional[int] = None,
                 neg_ttl_s: Optional[float] = None,
                 peer_timeout_s: Optional[float] = None,
                 upstream_conns: Optional[int] = None,
                 upstream_timeout_s: float = 30.0,
                 write_timeout: float = 10.0,
                 down_after: Optional[int] = None,
                 hedge_ms: Optional[float] = None,
                 sub_persist: Optional[str] = None,
                 advertise: Optional[str] = None,
                 hub: bool = False):
        self.host, self.port = host, int(port)
        self.stats = stats if stats is not None else Stats()
        # hub mode (ISSUE 19): ``upstreams`` are a PEER REGION's
        # gateways and this gateway FETCHES from their subscription
        # stream instead of polling per tick — every local panel and
        # CQ group rides ONE inter-region delta stream per key
        # (gyt_gw_region_* families). One-shot / historical queries
        # still pass through the same pooled query conns.
        self.hub = bool(hub)
        self.hub_stall_s = _envf("GYT_GW_HUB_STALL_S", 10.0)
        self.hub_settle_s = _envf("GYT_GW_HUB_SETTLE_S", 0.5)
        self.hub_first_s = _envf("GYT_GW_HUB_FIRST_S", 15.0)
        self.hub_idle_s = _envf("GYT_GW_HUB_IDLE_S", 60.0)
        self._hub_relays: dict = {}             # key -> _HubRelay
        self._hub_tick = -1
        self._hub_kick = asyncio.Event()
        self._hub_hb_key = request_key(dict(_POLL_REQ))
        # peer-exchange tick floor (owner-tick poll-skew fix): when a
        # peer asks us — the rendezvous owner — for a tick our own
        # poller has not seen yet, ADOPT it. The fabric already
        # reached that tick (the asker saw it on its replica), so
        # rendering under our stale tick would alias the result where
        # the asker never looks (peer_hits=0 flake, CHANGES PR 16).
        self._tick_floor = -1
        # circuit-breaker + hedge knobs (OPERATIONS.md "Failure
        # domains & degradation"): K consecutive failures before an
        # upstream is marked down; latency budget past which a render
        # hedges to the next-healthiest replica (0 disables hedging)
        self.down_after = _envi("GYT_GW_DOWN_AFTER", 3) \
            if down_after is None else int(down_after)
        self.hedge_ms = _envf("GYT_GW_HEDGE_MS", 75.0) \
            if hedge_ms is None else float(hedge_ms)
        self.probe_base_s = _envf("GYT_GW_PROBE_BASE_S", 1.0)
        self.probe_max_s = _envf("GYT_GW_PROBE_MAX_S", 15.0)
        # the identity PEERS route to this gateway under (rendezvous
        # owner hashing needs every fleet member to rank the same
        # ident for this process its peers dial)
        self.advertise = advertise or os.environ.get("GYT_GW_ADVERTISE")
        self.poll_s = _envf("GYT_GW_POLL_S", 0.5) \
            if poll_s is None else float(poll_s)
        self.cache_max = _envi("GYT_GW_CACHE_MAX", 4096) \
            if cache_max is None else int(cache_max)
        self.neg_ttl_s = _envf("GYT_GW_NEG_TTL_S", 2.0) \
            if neg_ttl_s is None else float(neg_ttl_s)
        self.peer_timeout_s = _envf("GYT_GW_PEER_TIMEOUT_S", 0.5) \
            if peer_timeout_s is None else float(peer_timeout_s)
        nconns = _envi("GYT_GW_UPSTREAM_CONNS", 2) \
            if upstream_conns is None else int(upstream_conns)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.write_timeout = float(write_timeout)
        self.upstreams = [
            _Upstream(h, p, nconns, stats=self.stats,
                      down_after=self.down_after,
                      probe_base_s=self.probe_base_s,
                      probe_max_s=self.probe_max_s)
            for h, p in upstreams]
        if not self.upstreams:
            raise ValueError("gateway needs at least one upstream")
        self.peers = [(h, int(p)) for h, p in peers]
        self._peer_conns: dict = {}       # (h,p) -> [reader,writer,lock]
        self._rr = 0
        self._server = None
        self._tasks: list = []
        # (tick, key) -> ["ok", resp, body|None] | ["neg", msg, expiry]
        self._cache: OrderedDict = OrderedDict()
        self._flight: dict = {}           # (tick, key) -> Future
        # historical edge cache: at=/window= responses whose anchor
        # lies INSIDE compaction coverage are immutable by
        # construction — no TTL, invalidation never (LRU bound only);
        # keyed by normalized request + aliased under the RESOLVED
        # tick (gyt_gw_hist_cache_* family)
        self._hist_cache: OrderedDict = OrderedDict()
        self.hist_cache_max = _envi("GYT_GW_HIST_CACHE_MAX", 4096)
        self._pushed_tick = -1
        self._pushing = False
        import secrets as _sec
        self._madhava_id = _sec.randbits(63) | 1   # NM-front identity
        from gyeeta_tpu.net.qexec import JsonRenderPool
        self._render = JsonRenderPool(stats=self.stats)
        from gyeeta_tpu.net.subs import SubscriptionHub
        self.subs = SubscriptionHub(
            self._hub_fetch if self.hub else self.query, self.stats,
            persist_path=sub_persist
            or os.environ.get("GYT_GW_SUB_PERSIST") or None)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        if self.hub:
            # no per-tick WAN polls: the remote tick arrives on the
            # heartbeat relay inside _hub_drive
            self._tasks = [asyncio.create_task(self._hub_drive())]
        else:
            self._tasks = [asyncio.create_task(self._watch_upstream(u))
                           for u in self.upstreams]
        log.info("fabric gateway on %s:%d -> %d upstream(s), "
                 "%d peer(s)%s", self.host, self.port,
                 len(self.upstreams), len(self.peers),
                 " [hub]" if self.hub else "")
        return self.host, self.port

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        for rel in self._hub_relays.values():
            rel.stop()
        self._hub_relays.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for u in self.upstreams:
            while not u._pool.empty():        # noqa: SLF001
                qc = u._pool.get_nowait()     # noqa: SLF001
                if qc is not None:
                    await qc.close()
        for ent in self._peer_conns.values():
            if ent[1] is not None:
                ent[1].close()
        self._peer_conns.clear()
        self.subs.close()
        self._render.close()

    # ------------------------------------------------------------- upstream
    @property
    def fabric_tick(self) -> int:
        t = max((u.tick for u in self.upstreams), default=-1)
        if self._hub_tick > t:          # hub mode: the relay's view
            t = self._hub_tick
        if self._tick_floor > t:        # peer-adopted (poll skew)
            t = self._tick_floor
        return t

    # ------------------------------------------------------------- topology
    def topology(self) -> dict:
        """The PR-15 health model as a queryable panel
        (``/v1/topology`` on every front): per-upstream circuit state
        (the breakers' live view — up / half_open / down, consecutive
        fails, latency EWMA, probe deadline), the peer fleet, and the
        rendezvous OWNER of every live subscription / continuous-query
        key — so SubscribeStream supervisors and agents route off the
        SAME view the breakers maintain instead of probing blind."""
        now = time.monotonic()
        ups = []
        for u in self.upstreams:
            ups.append({
                "upstream": u.label, "host": u.host, "port": u.port,
                "state": u.state, "tick": u.tick, "fails": u.fails,
                "ewma_ms": round(u.ewma_ms, 3)
                if u.ewma_ms is not None else None,
                "probe_in_s": round(max(0.0, u.probe_at - now), 3)
                if u.state == "down" else None,
            })
        me = self._ident()
        owners = {}
        sub_keys = list(self.subs._by_key) \
            + list(self.subs._cq_groups)            # noqa: SLF001
        for key in sub_keys[:256]:
            own = self._owner_peer(key)
            owners[key] = me if own is None else f"{own[0]}:{own[1]}"
        return {
            "t": "topology",
            "fabric_tick": self.fabric_tick,
            "self": me,
            "peers": [f"{h}:{p}" for h, p in self.peers],
            "upstreams": ups,
            "owners": owners,
            "subscribers": self.subs.nsubs,
            "sub_keys": len(self.subs._by_key),     # noqa: SLF001
            "cq_groups": len(self.subs._cq_groups),  # noqa: SLF001
            "cq_subscribers": sum(
                len(g.subs)
                for g in self.subs._cq_groups.values()),  # noqa: SLF001
        }

    async def _query_one(self, u: _Upstream, req: dict,
                         timeout: Optional[float] = None) -> dict:
        from gyeeta_tpu.ingest import wire
        if u.state == "down" and time.monotonic() >= u.probe_at:
            # this attempt IS the half-open probe: one request tests
            # the circuit, success closes it, failure re-arms backoff
            u._set_state("half_open")       # noqa: SLF001
        try:
            qc = await u.checkout(self.upstream_timeout_s)
        except (ConnectionError, OSError, TimeoutError,
                asyncio.IncompleteReadError, wire.FrameError):
            # connect/handshake failure — the COMMON way a replica is
            # down; it must feed the breaker like a request failure
            u.record_fail()
            raise
        t0 = time.perf_counter()
        try:
            out = await qc.query(req, timeout=timeout)
        except RuntimeError:
            # server error ENVELOPE: the conn (and replica) is healthy
            # — reuse it, and the circuit records a SUCCESS
            u.checkin(qc)
            u.record_ok((time.perf_counter() - t0) * 1e3)
            raise
        except (ConnectionError, OSError, TimeoutError,
                asyncio.IncompleteReadError, wire.FrameError):
            await u.discard(qc)
            u.record_fail()
            raise
        except BaseException:
            # cancellation (a hedge loser) or unexpected: the conn is
            # mid-request and can never be reused; NOT a health
            # signal — a cancelled request says nothing about the
            # replica
            await u.discard(qc)
            raise
        u.checkin(qc)
        u.record_ok((time.perf_counter() - t0) * 1e3)
        return out

    def _ranked(self) -> list:
        """Failover order: live replicas first (rotated so load
        spreads; the rotation's successor is the hedge target),
        half-open probes next, and marked-DOWN replicas LAST rather
        than never — a fabric with >=1 live replica never surfaces an
        upstream error, and a fully-down fabric still tries everyone
        instead of failing by label alone."""
        ups = sorted((u for u in self.upstreams if u.state == "up"),
                     key=lambda u: u.ewma_ms or 0.0)
        half = [u for u in self.upstreams if u.state == "half_open"]
        down = sorted((u for u in self.upstreams
                       if u.state == "down"),
                      key=lambda u: u.probe_at)
        if len(ups) > 1:
            self._rr = (self._rr + 1) % len(ups)
            ups = ups[self._rr:] + ups[:self._rr]
        return ups + half + down

    def _hedge_budget_s(self, u: _Upstream) -> float:
        """Latency budget before the hedge fires: the knob floor, or
        4x the primary's EWMA when traffic has taught us its normal —
        a loaded-but-healthy replica must not double every render."""
        return max(self.hedge_ms, 4.0 * (u.ewma_ms or 0.0)) / 1e3

    async def _query_hedged(self, u1: _Upstream, u2: _Upstream,
                            req: dict) -> dict:
        """First-response-wins over (primary, next-healthiest): the
        hedge fires when the primary exceeds the latency budget
        (counted — the wedged-not-dead replica case, where the
        breaker sees no failure to open on), or immediately on a fast
        primary conn failure (plain failover). RuntimeError envelopes
        win outright — every replica answers them identically."""
        t1 = asyncio.ensure_future(self._query_one(u1, dict(req)))
        done, _ = await asyncio.wait({t1},
                                     timeout=self._hedge_budget_s(u1))
        if done:
            exc = t1.exception()
            if exc is None:
                return t1.result()
            if isinstance(exc, RuntimeError):
                raise exc
            # primary died fast: just fail over, no hedge needed
            return await self._query_one(u2, dict(req))
        self.stats.bump("gw_hedged_requests")
        t2 = asyncio.ensure_future(self._query_one(u2, dict(req)))
        pending: set = {t1, t2}
        winner = None
        err: Optional[BaseException] = None
        try:
            while pending and winner is None:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    exc = t.exception()
                    if exc is None:
                        winner = t
                        break
                    if isinstance(exc, RuntimeError):
                        raise exc
                    err = exc
            if winner is None:
                raise err if err is not None else \
                    ConnectionError("hedged render failed")
            if winner is t2:
                self.stats.bump("gw_hedged_wins")
            return winner.result()
        finally:
            for t in (t1, t2):
                if not t.done():
                    t.cancel()
                elif not t.cancelled():
                    t.exception()       # mark retrieved

    async def _upstream_query(self, req: dict) -> dict:
        """One render upstream: health-ordered failover with hedged
        reads. RuntimeError (the server's own error envelope)
        propagates without failover — it is the QUERY's error and
        every replica would answer it identically."""
        order = self._ranked()
        last: Optional[BaseException] = None
        idx, n = 0, len(order)
        while idx < n:
            u = order[idx]
            hedge = (self.hedge_ms > 0 and u.state == "up"
                     and idx + 1 < n and order[idx + 1].state == "up")
            try:
                if hedge:
                    out = await self._query_hedged(u, order[idx + 1],
                                                   req)
                else:
                    out = await self._query_one(u, req)
                self.stats.bump("gw_renders_upstream")
                return out
            except RuntimeError:
                raise
            except Exception as e:      # noqa: BLE001 — conn trouble
                self.stats.bump("gw_upstream_errors")
                last = e
            # a hedged attempt that raised already consumed BOTH
            idx += 2 if hedge else 1
        raise ConnectionError(f"no upstream reachable: {last}")

    async def _watch_upstream(self, u: _Upstream) -> None:
        """One cheap poll per tick per upstream: watch ``snaptick``
        advance and trigger the subscription push when the FABRIC tick
        (max across upstreams) moves. Health transitions live in the
        circuit breaker (``record_ok``/``record_fail`` inside
        ``_query_one``): a single failed poll only increments the
        consecutive-failure count — mark-down takes ``down_after`` of
        them — and a down upstream is polled on its jittered probe
        backoff instead of every tick."""
        while True:
            if not u.probe_due():
                await asyncio.sleep(
                    min(self.poll_s,
                        max(0.05, u.probe_at - time.monotonic())))
                continue
            try:
                out = await self._query_one(u, dict(_POLL_REQ),
                                            timeout=10.0)
                tick = int(out.get("snaptick", -1))
                if tick > u.tick:
                    u.tick = tick
                u.tick_at = time.monotonic()
                self.stats.gauge("gw_fabric_tick",
                                 float(self.fabric_tick))
                self.stats.gauge(
                    "gw_upstreams_up",
                    float(sum(1 for x in self.upstreams if x.up)))
                new = self.fabric_tick
                if new > self._pushed_tick and not self._pushing:
                    self._pushing = True
                    try:
                        await self.subs.push_tick()
                        # only a COMPLETED push advances the mark: a
                        # failed push retries on the next poll instead
                        # of silently waiting out the tick, and the
                        # error must not flag the polled upstream down
                        self._pushed_tick = new
                    except asyncio.CancelledError:
                        raise
                    except Exception:   # noqa: BLE001 — counted
                        self.stats.bump("gw_push_errors")
                        log.exception("subscription push failed at "
                                      "tick %d", new)
                    finally:
                        self._pushing = False
            except asyncio.CancelledError:
                raise
            except Exception:       # noqa: BLE001 — counted; the
                # circuit breaker (not this handler) decides when the
                # upstream is DOWN: K consecutive failures, not one
                self.stats.bump("gw_poll_errors")
                self.stats.gauge(
                    "gw_upstreams_up",
                    float(sum(1 for x in self.upstreams if x.up)))
            await asyncio.sleep(self.poll_s)

    # ------------------------------------------------------ hub mode
    def _hub_advance(self, tick: int) -> None:
        """A relay saw a newer remote tick: adopt it as the hub's
        fabric tick and kick the push driver."""
        if tick > self._hub_tick:
            self._hub_tick = tick
            self.stats.gauge("gw_region_tick", float(tick))
            self._hub_kick.set()

    def _hub_relay_for(self, req: dict) -> _HubRelay:
        key = request_key(req)
        rel = self._hub_relays.get(key)
        if rel is None or rel.done():
            if rel is not None:
                rel.stop()
            rel = self._hub_relays[key] = _HubRelay(self, req, key)
            self.stats.bump("gw_region_relays_opened")
            self.stats.gauge("gw_region_keys",
                             float(len(self._hub_relays)))
        rel.last_used = time.monotonic()
        return rel

    async def _hub_fetch(self, req: dict) -> dict:
        """The SubscriptionHub's fetch in hub mode: serve the key's
        relay-held full instead of rendering upstream — N local
        subscribers on one key cost ONE inter-region stream. Falls
        back to a one-shot passthrough (counted) only before the
        first full lands, so the first subscriber still gets a base
        while the WAN subscribe is in flight."""
        rel = self._hub_relay_for(req)
        resp = await rel.current(self._hub_tick, self.hub_settle_s,
                                 self.hub_first_s)
        if resp is None:
            self.stats.bump("gw_region_fetch_fallbacks")
            return await self.query(dict(req))
        return resp

    async def _hub_drive(self) -> None:
        """Hub-mode push driver: the remote region's tick arrives on
        the heartbeat relay (the same ``serverstatus`` request poll
        mode uses — but ONE standing subscription instead of a poll
        per upstream per tick). When it advances, give the active
        relays a short settle window to land the same tick, then run
        the local subscription push once — the exact analogue of
        ``_watch_upstream``'s guarded push, driven by events instead
        of polls."""
        self._hub_relay_for(dict(_POLL_REQ))
        while True:
            try:
                await asyncio.wait_for(self._hub_kick.wait(), 1.0)
            except (asyncio.TimeoutError, TimeoutError):
                pass
            self._hub_kick.clear()
            now = time.monotonic()
            for key, rel in list(self._hub_relays.items()):
                rel.fold()
                if key == self._hub_hb_key:
                    rel.last_used = now     # the heartbeat never idles
                elif now - rel.last_used > self.hub_idle_s:
                    # no local fetch touched this key for a while: the
                    # last subscriber left — stop paying WAN for it
                    rel.stop()
                    del self._hub_relays[key]
                    self.stats.bump("gw_region_relays_closed")
            self.stats.gauge("gw_region_keys",
                             float(len(self._hub_relays)))
            new = self.fabric_tick
            if new > self._pushed_tick and not self._pushing:
                deadline = time.monotonic() + self.hub_settle_s
                while time.monotonic() < deadline and any(
                        r.held is not None and r.tick < new
                        for r in self._hub_relays.values()):
                    await asyncio.sleep(0.02)
                self._pushing = True
                try:
                    await self.subs.push_tick()
                    self._pushed_tick = new
                except asyncio.CancelledError:
                    raise
                except Exception:   # noqa: BLE001 — counted, retried
                    self.stats.bump("gw_push_errors")
                    log.exception("hub push failed at tick %d", new)
                finally:
                    self._pushing = False

    # ------------------------------------------------------ cache + query
    @staticmethod
    def _cacheable(req: dict) -> bool:
        if any(k in req for k in ("op", "multiquery", "at", "window",
                                  "tstart", "tend")):
            return False
        return req.get("consistency") != "strong"

    def _cache_put(self, ck, entry) -> None:
        self._cache[ck] = entry
        self._cache.move_to_end(ck)
        while len(self._cache) > self.cache_max:
            self._cache.popitem(last=False)

    def _cache_body(self, ck) -> Optional[bytes]:
        ent = self._cache.get(ck)
        if ent is None or ent[0] != "ok":
            return None
        if ent[2] is None:
            ent[2] = json.dumps(ent[1]).encode()
        return ent[2]

    # --------------------------------------------------- historical cache
    @staticmethod
    def _hist_anchor(req: dict) -> Optional[str]:
        """Classify a historical request's anchor: ``"abs"`` — the
        instant/range is spelled absolutely, so the answer can be
        immutable; ``"rel"`` — anchored to "now"/the newest shard
        (``at=-15m``, ``window=`` without ``tend``), re-resolving
        every pass; None — not a historical request."""
        if any(k in req for k in ("op", "multiquery")):
            return None
        if "at" in req:
            v = req["at"]
            if isinstance(v, str) and v.strip().startswith("-"):
                return "rel"
            return "abs"
        if "tstart" in req:
            return "abs" if "tend" in req else "rel"
        if "window" in req:
            return "abs" if "tend" in req else "rel"
        if "tend" in req:
            return "rel"
        return None

    @staticmethod
    def _hist_immutable(req: dict, resp: dict) -> bool:
        """An absolute historical answer is immutable ONLY when its
        anchor resolved INSIDE compaction coverage at render time: a
        request past the frontier (or before the earliest shard)
        would re-resolve once compaction appends/retires windows.
        Coverage rides the response (``timeview._cover``)."""
        cover_t = resp.get("hist_cover_t")
        cover_tick = resp.get("hist_cover_tick")
        if cover_t is None:
            return False
        if "at" in req:
            v = req["at"]
            if isinstance(v, str) and v.strip().startswith("tick:"):
                try:
                    return int(v.strip()[5:]) <= int(cover_tick)
                except (TypeError, ValueError):
                    return False
            try:
                ts = float(v)
            except (TypeError, ValueError):
                return False
            # resolved-behind (resp.at <= ts): genuine "state at ts";
            # resolved-AHEAD means the before-everything fallback fired
            return resp.get("at", ts + 1) <= ts <= float(cover_t)
        end = req.get("tend")
        try:
            return end is not None and float(end) <= float(cover_t)
        except (TypeError, ValueError):
            return False

    def _hist_put(self, key: str, resp: dict) -> None:
        self._hist_cache[key] = resp
        self._hist_cache.move_to_end(key)
        while len(self._hist_cache) > self.hist_cache_max:
            self._hist_cache.popitem(last=False)

    async def _hist_query(self, req: dict, anchor: str) -> dict:
        key = request_key(req)
        if anchor == "abs":
            ent = self._hist_cache.get(key)
            if ent is not None:
                self.stats.bump("gw_hist_cache_hits")
                self._hist_cache.move_to_end(key)
                return ent
            self.stats.bump("gw_hist_cache_misses")
        else:
            self.stats.bump("gw_hist_cache_uncacheable")
        resp = await self._upstream_query(dict(req))
        cacheable = self._hist_immutable(req, resp)
        if anchor == "abs" and cacheable:
            self._hist_put(key, resp)
        # alias every interior at= answer under its RESOLVED tick so
        # any spelling of the same instant (epoch seconds, a relative
        # -15m that landed here, tick:N) shares one entry forever
        tick = resp.get("tick")
        if tick is not None and "at" in req and cacheable:
            alias = request_key({**{k: v for k, v in req.items()
                                    if k != "at"},
                                 "at": f"tick:{int(tick)}"})
            if alias != key:
                self._hist_put(alias, resp)
        return resp

    async def query(self, req: dict, _from_peer: bool = False) -> dict:
        """THE query entry every front shares. Cache-eligible requests
        collapse onto the (fabric-tick, normalized-key) edge cache with
        single-flight + owner-routed peer exchange; everything else
        passes through to a replica. ``_from_peer`` marks a render
        forwarded BY a peer (``_serve_peer``): it must not hop again —
        rendezvous ownership is consistent fleet-wide, but an
        asymmetric peer config would otherwise ping-pong forever.
        Raises RuntimeError with the server's error envelope,
        ConnectionError when no upstream answers."""
        if req.get("subsys") == "topology":
            # breaker-aware topology hints (/v1/topology on every
            # front): rendered from the gateway's OWN health model —
            # never forwarded upstream, never cached
            self.stats.bump("gw_queries|edge=topology")
            return self.topology()
        if not self._cacheable(req):
            anchor = self._hist_anchor(req)
            if anchor is not None \
                    and req.get("consistency") != "strong":
                return await self._hist_query(req, anchor)
            self.stats.bump("gw_queries_uncached")
            return await self._upstream_query(req)
        key = request_key(req)
        tick = self.fabric_tick
        ck = (tick, key)
        ent = self._cache.get(ck)
        if ent is not None:
            if ent[0] == "ok":
                self.stats.bump("gw_cache_hits|tier=local")
                self._cache.move_to_end(ck)
                return ent[1]
            if ent[2] > time.monotonic():       # negative entry alive
                self.stats.bump("gw_cache_hits|tier=neg")
                raise RuntimeError(ent[1])
            self._cache.pop(ck, None)
        fut = self._flight.get(ck)
        if fut is not None:
            self.stats.bump("gw_singleflight_waits")
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._flight[ck] = fut
        try:
            self.stats.bump("gw_cache_misses")
            resp = None
            if self.peers and not _from_peer:
                got = await self._peer_get(tick, key, req)
                if got is not None and got[0] == "neg":
                    # the owner's render errored: share the negative
                    # verdict so the fleet, not just the owner,
                    # collapses the broken-panel stampede
                    self._cache_put(
                        ck, ["neg", got[1],
                             time.monotonic() + self.neg_ttl_s])
                    raise RuntimeError(got[1])
                if got is not None:
                    resp = got[1]
            if resp is not None:
                self.stats.bump("gw_cache_hits|tier=peer")
            if resp is None and self.hub:
                # hub mode: an active inter-region relay already holds
                # this key's current full — a one-shot dashboard query
                # must not cost a WAN render
                rel = self._hub_relays.get(key)
                if rel is not None and rel.held is not None:
                    resp = rel.held
                    self.stats.bump("gw_cache_hits|tier=region")
            if resp is None:
                try:
                    resp = await self._upstream_query(dict(req))
                except RuntimeError as e:
                    # negative cache: the error is the result of THIS
                    # query at THIS tick — a stampede of a broken
                    # dashboard panel must not hammer the replicas
                    self._cache_put(
                        ck, ["neg", str(e),
                             time.monotonic() + self.neg_ttl_s])
                    raise
            ent = ["ok", resp, None]
            self._cache_put(ck, ent)
            st = resp.get("snaptick")
            if st is not None and (st, key) != ck:
                # the replica rendered a fresher (or lagging) tick:
                # alias under ITS tick too, so the next lookup at that
                # tick hits
                self._cache_put((st, key), ent)
                if st < tick:
                    # lagging replica: keep ONLY the (st, key) alias —
                    # parking the stale render under the current tick
                    # would serve last tick's data for the whole tick
                    # and single-flight would never re-render it from
                    # a caught-up replica
                    self._cache.pop(ck, None)
            elif st is None:
                # uncacheable response shape (no snaptick: local
                # subsystems, strong reads) — do not serve it across
                # ticks
                self._cache.pop(ck, None)
            fut.set_result(resp)
            return resp
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._flight.pop(ck, None)
            if not fut.done():          # pragma: no cover — safety
                fut.cancel()
            elif not fut.cancelled():
                fut.exception()     # mark retrieved (no loop warning)

    # ------------------------------------------------------ peer exchange
    async def _peer_post_one(self, peer, body: bytes):
        ent = self._peer_conns.get(peer)
        if ent is None:
            ent = self._peer_conns[peer] = [None, None,
                                            asyncio.Lock()]
        # one request in flight per peer conn: responses arrive in
        # write order, so an unserialized second reader would consume
        # the FIRST request's response (cross-query poisoning)
        async with ent[2]:
            try:
                if ent[1] is None or ent[1].is_closing():
                    ent[0], ent[1] = await asyncio.open_connection(
                        *peer)
                reader, writer = ent[0], ent[1]
                writer.write(
                    f"POST /gw/peer HTTP/1.1\r\nHost: gw\r\n"
                    f"Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body)
                await writer.drain()
                head = await reader.readuntil(b"\r\n\r\n")
                status = int(head.split()[1])
                clen = 0
                for ln in head.decode("latin1").split("\r\n"):
                    if ln.lower().startswith("content-length:"):
                        clen = int(ln.split(":", 1)[1])
                payload = await reader.readexactly(clen) if clen \
                    else b""
                return status, payload
            except BaseException:
                # request may be half-done (cancel on timeout, IO
                # error): the stream position is unknown, so the conn
                # cannot be reused
                if ent[1] is not None:
                    ent[1].close()
                    ent[0] = ent[1] = None
                raise

    def _ident(self) -> str:
        return self.advertise or f"{self.host}:{self.port}"

    @staticmethod
    def _rdv_score(ident: str, key: str) -> int:
        import hashlib
        return int.from_bytes(
            hashlib.blake2b(f"{ident}\x00{key}".encode(),
                            digest_size=8).digest(), "big")

    def _owner_peer(self, key: str) -> Optional[tuple]:
        """Rendezvous-hash owner of ``key`` across the fleet (self +
        peers): every gateway ranks the same idents, so the whole
        fleet agrees on ONE owner per key with no coordination —
        N-gateway fleets do one peer hop instead of an in-order scan,
        and membership changes only reshuffle 1/N of the keys.
        Returns None when THIS gateway owns the key."""
        best_peer = None
        best = self._rdv_score(self._ident(), key)
        for h, p in self.peers:
            s = self._rdv_score(f"{h}:{p}", key)
            if s > best:
                best, best_peer = s, (h, p)
        return best_peer

    async def _peer_get(self, tick: int, key: str,
                        req: dict) -> Optional[tuple]:
        """On a local miss route to the rendezvous OWNER of the key
        (ROADMAP query-fabric item c): the owner answers from its
        cache, waits on its own in-flight render, or renders upstream
        itself — one peer hop, one render per fleet. A clean miss is
        impossible from the owner (it renders), so the in-order scan
        of the remaining peers runs only when the owner is DOWN.
        Returns ("hit", resp) | ("neg", errmsg) | None (render
        locally). Bounded by ``peer_timeout_s`` per peer — a slow
        peer must cost less than the render it saves."""
        owner = self._owner_peer(key)
        if owner is None:
            # this gateway owns the key: peers route here; render
            self.stats.bump("gw_peer_owner_self")
            return None
        body = json.dumps({"tick": tick, "key": key,
                           "req": req}).encode()
        probe = json.dumps({"tick": tick, "key": key}).encode()
        peers = [owner] + [p for p in self.peers if p != owner]
        for i, peer in enumerate(peers):
            self.stats.bump("gw_peer_requests")
            try:
                status, payload = await asyncio.wait_for(
                    self._peer_post_one(peer,
                                        body if i == 0 else probe),
                    self.peer_timeout_s)
                if status == 200:
                    obj = json.loads(payload)
                    if obj.get("neg") is not None:
                        return ("neg", obj["neg"])
                    self.stats.bump("gw_peer_hits")
                    return ("hit", obj["resp"])
                if i == 0:
                    # the owner answered but could not render (its
                    # upstreams unreachable): render locally — our
                    # replica view may differ from the owner's
                    return None
            except asyncio.CancelledError:
                raise
            except Exception:       # noqa: BLE001 — peer down/slow
                # conn teardown happens inside _peer_post_one under
                # the per-peer lock; closing here could kill a fresh
                # conn another coroutine just opened
                self.stats.bump("gw_peer_errors")
                if i == 0:
                    # owner down: degrade to the PR-13 in-order scan
                    # of the remaining peers' caches
                    self.stats.bump("gw_peer_owner_down")
        return None

    async def _serve_peer(self, obj: dict):
        """The answering half: local cache lookup, waiting on an
        in-flight render for the SAME (tick, key), and — when the
        caller forwarded the full request because WE own the key —
        rendering upstream ourselves. Ownership is what makes a
        fresh-tick stampede render once per FLEET, not once per
        gateway. A render error ships as ``neg`` so the whole fleet
        shares the negative verdict."""
        self.stats.bump("gw_peer_served_requests")
        ck = (int(obj.get("tick", -1)), str(obj.get("key", "")))
        if ck[0] > self.fabric_tick:
            # owner-tick poll skew (CHANGES PR 16 flake): the asker's
            # replica already published this tick, our poller just
            # has not seen it yet. Adopt it as a floor so the render
            # below caches under the tick the asker (and everyone
            # else at that tick) will look up — NOT under our stale
            # one, which made owner-routed renders invisible
            # (peer_hits=0) until the next poll.
            self._tick_floor = ck[0]
            self.stats.bump("gw_peer_tick_adopted")
        ent = self._cache.get(ck)
        if ent is not None and ent[0] == "ok":
            self.stats.bump("gw_peer_served_hits")
            return {"resp": ent[1]}
        fut = self._flight.get(ck)
        if fut is not None:
            try:
                resp = await asyncio.wait_for(asyncio.shield(fut), 2.0)
                self.stats.bump("gw_peer_served_hits")
                return {"resp": resp}
            except Exception:       # noqa: BLE001
                pass
        req = obj.get("req")
        if isinstance(req, dict) and req:
            # owner-routed render: _from_peer pins the hop count at 1
            try:
                resp = await self.query(dict(req), _from_peer=True)
                self.stats.bump("gw_peer_served_renders")
                return {"resp": resp}
            except RuntimeError as e:
                return {"neg": str(e)}
            except asyncio.CancelledError:
                raise
            except Exception:       # noqa: BLE001 — upstreams down
                self.stats.bump("gw_peer_served_errors")
        return None

    # ---------------------------------------------------------- the fronts
    async def _handle(self, reader, writer) -> None:
        try:
            try:
                first = await asyncio.wait_for(reader.readexactly(4),
                                               10.0)
            except (asyncio.IncompleteReadError, ConnectionError,
                    asyncio.TimeoutError, TimeoutError):
                return
            from gyeeta_tpu.ingest import refproto, wire
            magic = int.from_bytes(first, "little")
            if magic in (wire.MAGIC_PM, wire.MAGIC_MS, wire.MAGIC_NQ):
                await self._gyt_front(reader, writer, first)
            elif magic in refproto.REF_MAGICS:
                await self._nm_front(reader, writer, first)
            else:
                await self._http_front(reader, writer, first)
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        except Exception:           # pragma: no cover — keep serving
            log.exception("gateway conn failed")
        finally:
            self.subs.unsubscribe_conn(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass

    # ---- GYT binary front
    async def _gyt_front(self, reader, writer, first: bytes) -> None:
        from gyeeta_tpu import version
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.net.subs import SubscribeError
        import numpy as np
        dtype, payload = await wire.read_frame(reader, first)
        if dtype != wire.COMM_REGISTER_REQ:
            return
        req = np.frombuffer(payload, wire.REGISTER_REQ_DT, count=1)[0]
        if int(req["conn_type"]) != wire.CONN_QUERY:
            # the gateway serves QUERIES; event conns belong on the
            # serve tier
            writer.write(wire.encode_register_resp(
                wire.REG_ERR_VERSION, 0, version.CURR_WIRE_VERSION, 0))
            await writer.drain()
            return
        writer.write(wire.encode_register_resp(
            wire.REG_OK, 0xFFFFFFFF, version.CURR_WIRE_VERSION, 0))
        await writer.drain()
        while True:
            try:
                dtype, payload = await wire.read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            if dtype == wire.COMM_SUBSCRIBE_CMD:
                try:
                    seqid, _, req = wire.decode_query_payload(payload)
                except Exception:       # noqa: BLE001
                    continue

                async def send(ev, _seqid=seqid, _w=writer):
                    _w.write(wire.encode_query(_seqid, ev,
                                               wire.QS_PARTIAL,
                                               resp=True))
                    await asyncio.wait_for(_w.drain(),
                                           self.write_timeout)

                try:
                    await self.subs.subscribe(
                        req or {}, send,
                        last_snaptick=(req or {}).get("last_snaptick"),
                        conn_tag=writer)
                    self.stats.bump("gw_queries|edge=gyt_sub")
                except (SubscribeError, ValueError, RuntimeError,
                        ConnectionError) as e:
                    writer.write(wire.encode_query(
                        seqid, {"error": str(e)}, wire.QS_ERROR,
                        resp=True))
                    await writer.drain()
                continue
            if dtype != wire.COMM_QUERY_CMD:
                continue
            try:
                seqid, _, req = wire.decode_query_payload(payload)
            except Exception:           # noqa: BLE001
                continue
            self.stats.bump("gw_queries|edge=gyt")
            try:
                with self.stats.timeit("gw_query"):
                    out = await self.query(req or {})
            except Exception as e:      # noqa: BLE001
                status = wire.QS_ERROR
                writer.write(wire.encode_query(
                    seqid, {"error": str(e)}, status, resp=True))
                await writer.drain()
                continue
            for frame in wire.iter_query_frames(seqid, out, wire.QS_OK):
                writer.write(frame)
                await writer.drain()

    # ---- stock NM front
    async def _nm_front(self, reader, writer, first: bytes) -> None:
        from gyeeta_tpu.ingest import refproto as RP
        from gyeeta_tpu.ingest import refquery as RQ
        from gyeeta_tpu.ingest import wire
        import numpy as np
        hdr_b = first + await reader.readexactly(
            RP.REF_HEADER_DT.itemsize - len(first))
        hdr = np.frombuffer(hdr_b, RP.REF_HEADER_DT, count=1)[0]
        total = int(hdr["total_sz"])
        if total < len(hdr_b) or total >= wire.MAX_COMM_DATA_SZ:
            return
        body = await reader.readexactly(total - len(hdr_b))
        if int(hdr["data_type"]) != RQ.REF_COMM_NM_CONNECT_CMD:
            # only the node-webserver dialect fronts here; partha
            # event conns belong on the serve tier
            self.stats.bump("gw_nm_rejected")
            return
        from gyeeta_tpu.net import nmhandle
        await nmhandle.serve_nm_gateway(self, reader, writer, body)

    # ---- HTTP front
    async def _http_front(self, reader, writer, first: bytes) -> None:
        pending = first
        while True:
            try:
                head = pending + await reader.readuntil(b"\r\n\r\n")
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            except asyncio.LimitOverrunError:
                await self._respond(writer, 431,
                                    {"error": "headers too large"})
                return
            pending = b""
            if len(head) > _MAX_HDR:
                await self._respond(writer, 431,
                                    {"error": "headers too large"})
                return
            lines = head.decode("latin1").split("\r\n")
            parts = lines[0].split()
            if len(parts) != 3:
                await self._respond(writer, 400,
                                    {"error": "bad request line"})
                return
            method, target, _ = parts
            headers = {}
            for ln in lines[1:]:
                if ":" in ln:
                    k, v = ln.split(":", 1)
                    headers[k.strip().lower()] = v.strip()
            try:
                clen = int(headers.get("content-length", 0) or 0)
            except ValueError:
                clen = -1
            if clen < 0 or clen > _MAX_BODY:
                await self._respond(writer, 400,
                                    {"error": "bad content-length"})
                return
            body = await reader.readexactly(clen) if clen else b""
            keep = headers.get("connection",
                               "keep-alive").lower() != "close"
            streamed = await self._http_route(writer, method, target,
                                              body)
            if streamed or not keep:
                return

    async def _http_route(self, writer, method: str, target: str,
                          body: bytes) -> bool:
        """→ True when the response is a stream that owns the conn
        (SSE); the caller stops the keep-alive loop."""
        path, _, qs = target.partition("?")
        try:
            if method == "GET" and path == "/metrics":
                from gyeeta_tpu.obs import prom
                await self._respond_text(writer, 200,
                                         prom.render(self.stats),
                                         prom.CONTENT_TYPE)
                return False
            if method == "GET" and path == "/healthz":
                fresh = [u for u in self.upstreams if u.up]
                ok = bool(fresh)
                await self._respond(writer, 200 if ok else 503, {
                    "ok": ok, "fabric_tick": self.fabric_tick,
                    "upstreams_up": len(fresh),
                    "upstreams": len(self.upstreams),
                    "subscribers": self.subs.nsubs})
                return False
            if method == "POST" and path == "/gw/peer":
                out = await self._serve_peer(json.loads(body or b"{}"))
                if out is None:
                    await self._respond(writer, 404, {"miss": True})
                else:
                    await self._respond(writer, 200, out)
                return False
            if method == "GET" and path == "/v1/subscribe":
                await self._sse_subscribe(writer, qs)
                return True
            if method == "POST" and path == "/query":
                req = json.loads(body or b"{}")
                self.stats.bump("gw_queries|edge=http")
                with self.stats.timeit("gw_query"):
                    await self._respond(writer, 200,
                                        await self.query(req))
                return False
            if method == "GET" and path.startswith("/v1/"):
                req = self._req_of_qs(path[4:].strip("/"), qs)
                self.stats.bump("gw_queries|edge=http")
                with self.stats.timeit("gw_query"):
                    await self._respond(writer, 200,
                                        await self.query(req))
                return False
            await self._respond(writer, 404, {"error": "not found"})
        except (ValueError, KeyError, RuntimeError) as e:
            await self._respond(writer, 400, {"error": str(e)})
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            await self._respond(writer, 502,
                                {"error": "upstream unreachable"})
        return False

    @staticmethod
    def _req_of_qs(subsys: str, qs: str) -> dict:
        req = {"subsys": subsys}
        q = urllib.parse.parse_qs(qs)
        for k in ("filter", "sortcol", "consistency"):
            if k in q:
                req[k] = q[k][0]
        for k in ("maxrecs",):
            if k in q:
                req[k] = int(q[k][0])
        for k in ("tstart", "tend"):
            if k in q:
                req[k] = float(q[k][0])
        for k in ("at", "window"):
            if k in q:
                req[k] = q[k][0]
        if "sortdesc" in q:
            req["sortdesc"] = q["sortdesc"][0].lower() in ("1", "true")
        if "cq" in q:
            # continuous query: the subscription is a STANDING FILTER
            # (enter/leave/change membership events), not a panel view
            req["cq"] = q["cq"][0].lower() in ("1", "true")
        return req

    # ---- SSE subscription edge
    async def _sse_subscribe(self, writer, qs: str) -> None:
        q = urllib.parse.parse_qs(qs)
        if "subsys" not in q:
            await self._respond(writer, 400,
                                {"error": "subscribe needs subsys"})
            return
        req = self._req_of_qs(q["subsys"][0], qs)
        last = None
        if "last_snaptick" in q:
            try:
                last = int(q["last_snaptick"][0])
            except ValueError:
                pass
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()

        async def send(ev, _w=writer):
            data = json.dumps(ev)
            _w.write(f"event: {ev.get('t', 'message')}\n"
                     f"data: {data}\n\n".encode())
            await asyncio.wait_for(_w.drain(), self.write_timeout)

        from gyeeta_tpu.net.subs import SubscribeError
        try:
            await self.subs.subscribe(req, send, last_snaptick=last,
                                      conn_tag=writer)
            self.stats.bump("gw_queries|edge=sse")
        except (SubscribeError, ValueError, RuntimeError,
                ConnectionError) as e:
            writer.write(f"event: error\ndata: "
                         f"{json.dumps({'error': str(e)})}\n\n"
                         .encode())
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            return
        # park until the CLIENT hangs up; pushes arrive from the hub
        # (unsubscribe happens in _handle's finally)
        transport = writer.transport
        while not transport.is_closing():
            await asyncio.sleep(0.5)

    # ------------------------------------------------------- http encode
    _REASON = {200: "OK", 400: "Bad Request", 404: "Not Found",
               413: "Payload Too Large", 431: "Headers Too Large",
               502: "Bad Gateway", 503: "Service Unavailable"}

    async def _respond(self, writer, status: int, obj) -> None:
        await self._respond_bytes(writer, status,
                                  await self._render.encode(obj),
                                  "application/json")

    @classmethod
    async def _respond_text(cls, writer, status: int, text: str,
                            ctype: str) -> None:
        await cls._respond_bytes(writer, status, text.encode(), ctype)

    @classmethod
    async def _respond_bytes(cls, writer, status: int, body: bytes,
                             ctype: str) -> None:
        reason = cls._REASON.get(status, "Error")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        await writer.drain()
