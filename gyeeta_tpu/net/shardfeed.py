"""Per-shard ingest loops for the ``--shards`` serving mode.

The flat serving edge feeds every conn's bytes straight into
``Runtime.feed`` from the conn handler. At fleet scale on a mesh that
couples two rates that should be independent: how fast agent sockets
drain, and how fast the mesh program folds. :class:`ShardFeeder`
decouples them with the reference's L1→L2 handoff shape
(``server/gy_mconnhdlr.h`` MPMC queues), sharded the same way the fold
is: every mesh shard gets a BOUNDED byte queue keyed by the conn's
sticky ``hid`` (the layout's hid→shard hash — the same rule that places
the records on devices and the chunks in ``shard_NN/`` WAL subdirs),
and one drain task per shard feeds the runtime in arrival order.

Why it helps even on one controller loop: conn reads stop paying fold
latency (they enqueue in microseconds and yield), drains batch
everything queued per shard into back-to-back ``feed`` calls (fuller
staging slabs per dispatch), and overload becomes a COUNTED per-shard
drop (``gyt_shard_ingest_dropped_*{shard=...}``) under the admission
controller's throttle instead of an invisible socket-buffer stall.
Queue depth and byte occupancy ride per-shard gauges.
"""

from __future__ import annotations

import asyncio
import collections
import logging
from typing import Optional

log = logging.getLogger("gyeeta_tpu.net.shardfeed")


class ShardFeeder:
    def __init__(self, rt, queue_max_mb: float = 8.0):
        self.rt = rt
        self.n = int(getattr(rt, "n", 1))
        self.max_bytes = int(queue_max_mb * (1 << 20))
        self._q: list = [collections.deque() for _ in range(self.n)]
        self._q_bytes = [0] * self.n
        self._wake: list = [asyncio.Event() for _ in range(self.n)]
        self._tasks: list = []
        self._started = False

    def shard_of(self, hid: int) -> int:
        lay = getattr(self.rt, "layout", None)
        if lay is not None:
            return int(lay.shard_of_host(int(hid)))
        return int(hid) % self.n

    # ------------------------------------------------------------ submit
    def submit(self, buf: bytes, hid: int = 0, conn_id: int = 0) -> int:
        """Enqueue one complete-frame run onto its shard's ingest
        queue. Past the byte bound the OLDEST queued run drops,
        counted per shard — the wire outran the fold and the throttle;
        never a silent stall. Returns len(buf) (the feed-path
        convention of returning 'accepted')."""
        s = self.shard_of(hid)
        q = self._q[s]
        q.append((buf, hid, conn_id))
        self._q_bytes[s] += len(buf)
        stats = self.rt.stats
        while self._q_bytes[s] > self.max_bytes and len(q) > 1:
            old = q.popleft()
            self._q_bytes[s] -= len(old[0])
            stats.bump(f"shard_ingest_dropped|shard={s}")
            stats.bump(f"shard_ingest_dropped_bytes|shard={s}",
                       len(old[0]))
        stats.gauge(f"shard_ingest_queue_bytes|shard={s}",
                    float(self._q_bytes[s]))
        self._wake[s].set()
        return len(buf)

    # ------------------------------------------------------------- drain
    def _drain_shard_now(self, s: int) -> int:
        """Feed everything queued for shard ``s`` right now (one
        back-to-back run — fuller staging slabs per dispatch)."""
        fed = 0
        q = self._q[s]
        while q:
            buf, hid, conn_id = q.popleft()
            self._q_bytes[s] -= len(buf)
            self.rt.feed(buf, hid=hid, conn_id=conn_id)
            fed += 1
        self.rt.stats.gauge(f"shard_ingest_queue_bytes|shard={s}",
                            float(self._q_bytes[s]))
        return fed

    async def _drain_loop(self, s: int) -> None:
        while True:
            await self._wake[s].wait()
            self._wake[s].clear()
            try:
                self._drain_shard_now(s)
            except Exception:              # pragma: no cover
                log.exception("shard %d ingest drain failed", s)
            # yield so conn readers and other shards interleave
            await asyncio.sleep(0)

    def flush_pending(self) -> int:
        """Synchronous barrier: every submitted run is fed before a
        tick or a strong-consistency query reads state (the
        ``_feed_barrier`` contract of the serving edge)."""
        fed = 0
        for s in range(self.n):
            fed += self._drain_shard_now(s)
        return fed

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            return
        self._tasks = [asyncio.create_task(self._drain_loop(s))
                       for s in range(self.n)]
        self._started = True

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        self._started = False
        self.flush_pending()      # nothing submitted stays unfolded

    def queue_depth(self, s: Optional[int] = None) -> int:
        if s is not None:
            return len(self._q[s])
        return sum(len(q) for q in self._q)
