"""Event-driven process lifecycle via the netlink proc connector.

The reference subscribes to cn_proc for fork/exec/exit events
(``common/gy_misc.h:1181`` carries the proc_event layout; the task
handler consumes the stream) instead of polling /proc. This is the
userspace-possible half of that design: a NETLINK_CONNECTOR socket in
PROC_CN_MCAST_LISTEN mode delivering per-event records the 5s /proc
sweep can fold in — fork counts become event-accurate instead of
inferred from starttime deltas, and exits are seen the moment they
happen rather than at the next sweep.

Privilege-gated (CAP_NET_ADMIN to subscribe); :func:`available`
probes once and everything degrades to the sweep-only inference path.

ABI: cn_msg (20 bytes: cb_id idx/val, seq, ack, len, flags) wraps
proc_event (40 bytes: what, cpu, timestamp_ns, event_data) — offsets
verified against <linux/cn_proc.h> with a compile probe.
"""

from __future__ import annotations

import os
import socket
import struct
from typing import Optional

NETLINK_CONNECTOR = 11
CN_IDX_PROC = 1
CN_VAL_PROC = 1
PROC_CN_MCAST_LISTEN = 1
PROC_CN_MCAST_IGNORE = 2

PROC_EVENT_NONE = 0
PROC_EVENT_FORK = 0x1
PROC_EVENT_EXEC = 0x2
PROC_EVENT_COMM = 0x200
PROC_EVENT_EXIT = 0x80000000

_NLHDR = 16
_CNHDR = 20


class ProcEvent:
    __slots__ = ("what", "pid", "tgid", "child_pid", "child_tgid",
                 "exit_code")

    def __init__(self, what, pid, tgid, child_pid=0, child_tgid=0,
                 exit_code=0):
        self.what = what
        self.pid = pid
        self.tgid = tgid
        self.child_pid = child_pid
        self.child_tgid = child_tgid
        self.exit_code = exit_code


class ProcConnector:
    """cn_proc multicast listener → drained :class:`ProcEvent` lists."""

    def __init__(self, rcvbuf: int = 4 << 20):
        self._sock = socket.socket(socket.AF_NETLINK, socket.SOCK_DGRAM,
                                   NETLINK_CONNECTOR)
        self._sock.bind((0, CN_IDX_PROC))
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                  rcvbuf)
        except OSError:
            pass
        self._sock.setblocking(False)
        self._send_op(PROC_CN_MCAST_LISTEN)
        self.n_events = 0

    def _send_op(self, op: int) -> None:
        cn = struct.pack("<IIIIHH", CN_IDX_PROC, CN_VAL_PROC, 0, 0, 4,
                         0) + struct.pack("<I", op)
        nl = struct.pack("<IHHII", _NLHDR + len(cn), 3,  # NLMSG_DONE
                         0, 0, os.getpid()) + cn
        self._sock.send(nl)

    def poll(self, max_msgs: int = 4096) -> list:
        """Drain pending events (non-blocking)."""
        out: list[ProcEvent] = []
        for _ in range(max_msgs):
            try:
                msg = self._sock.recv(8192)
            except (BlockingIOError, OSError):
                break
            off = 0
            while off + _NLHDR <= len(msg):
                ln = struct.unpack_from("<I", msg, off)[0]
                if ln < _NLHDR or off + ln > len(msg):
                    break
                body = msg[off + _NLHDR: off + ln]
                off += (ln + 3) & ~3
                if len(body) < _CNHDR + 16:
                    continue
                # proc_event: what u32, cpu u32, timestamp u64, data
                what = struct.unpack_from("<I", body, _CNHDR)[0]
                data = body[_CNHDR + 16:]
                ev = self._decode(what, data)
                if ev is not None:
                    out.append(ev)
        self.n_events += len(out)
        return out

    @staticmethod
    def _decode(what: int, data: bytes) -> Optional[ProcEvent]:
        if what == PROC_EVENT_FORK and len(data) >= 16:
            ppid, ptgid, cpid, ctgid = struct.unpack_from("<iiii", data)
            return ProcEvent(PROC_EVENT_FORK, ppid, ptgid, cpid, ctgid)
        if what == PROC_EVENT_EXEC and len(data) >= 8:
            pid, tgid = struct.unpack_from("<ii", data)
            return ProcEvent(PROC_EVENT_EXEC, pid, tgid)
        if what == PROC_EVENT_EXIT and len(data) >= 12:
            pid, tgid, code = struct.unpack_from("<iiI", data)
            return ProcEvent(PROC_EVENT_EXIT, pid, tgid, exit_code=code)
        return None                    # COMM/UID/… not consumed

    def close(self) -> None:
        try:
            self._send_op(PROC_CN_MCAST_IGNORE)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


_probe_result: Optional[bool] = None


def available() -> bool:
    """True when cn_proc multicast can be joined (cached)."""
    global _probe_result
    if _probe_result is None:
        try:
            c = ProcConnector()
            c.close()
            _probe_result = True
        except OSError:
            _probe_result = False
    return _probe_result
