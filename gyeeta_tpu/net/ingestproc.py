"""Multi-process ingest workers: the million-agent control plane edge.

One Python process used to own every shard's socket edge — deframe,
decode, WAL append and fold all shared one GIL, which ROADMAP names
"the actual ceiling for 'millions of users', independent of device
speed". This module splits the ingest edge out of the fold process
(the sPIN near-wire-processing shape, PAPERS.md 1709.05483; the
per-process device-mesh decomposition of SNIPPETS.md [2] —
``make_array_from_process_local_data``: every process builds its own
shard-local data, the runtime assembles the global view):

- ``serve --ingest-procs N`` runs N **ingest worker processes**, each
  owning a sticky SHARD GROUP (shard ``s`` → worker ``s % N`` — the
  same ``ShardLayout`` hid-hash that places folds and ``shard_NN/``
  WAL subdirs partitions the socket edge). The supervisor (the fold
  process) keeps the ONE listening socket and the registration
  handshake (hostmap allocation is shared state); the instant an
  event conn registers, its socket fd is handed to the owning worker
  over a ``SCM_RIGHTS`` control channel. Workers then own the bulk
  read loop: wire validation, native deframe/decode, and the WAL
  append for their shards — near the wire, off the fold GIL.
- Workers publish **decoded columnar record batches** — never raw
  bytes — into per-shard shared-memory rings (``utils/shmring.py``).
  The fold process drains rings straight into its per-shard staging
  slabs (``ShardedRuntime.ingest_records(recs, shard=s)`` →
  ``sharded.stack_prerouted``), so the fused fold dispatch path is
  unchanged.
- Crash containment: a SIGKILL'd worker loses only its open conns.
  The supervisor detects death (process exit or a stale heartbeat
  word in the ring header), respawns the worker onto the SAME shard
  group, rings and WAL subdirs (sticky assignment), and the agents
  reconnect through the supervisor's still-open listener — no port
  churn. The accounting ledger extends across the process boundary:
  worker-side accepted counters live in the ring header, ring
  overwrites are counted in records by the consumer, and
  ``accepted + dropped + spooled == records_built`` stays exact
  through a crash/respawn window (tests/test_ingestproc.py).

``--ingest-procs 1`` (the default) spawns nothing: the in-process
path is byte-for-byte today's behavior.

Control protocol (AF_UNIX SOCK_SEQPACKET, one JSON header + optional
binary tail per packet, fds via SCM_RIGHTS):

    supervisor → worker:  conn (fd + initial bytes), wal (journal a
                          chunk for a supervisor-handled ref conn),
                          tick, quiesce, seal, stop
    worker → supervisor:  conn_closed, quiesced, sealed, stopped

``quiesce`` is the checkpoint barrier: workers fsync their journals
and reply (positions, ring heads); the supervisor drains every ring
to the replied head before recording positions — everything at or
below a checkpointed WAL position is provably folded.
"""

from __future__ import annotations

import json
import logging
import os
import selectors
import socket
import struct
import subprocess
import sys
import threading
import time
import uuid
from typing import Optional

log = logging.getLogger("gyeeta_tpu.net.ingestproc")

_MSG_HDR = struct.Struct("<I")          # json length; binary tail follows
_CTRL_BUF = 4 << 20
_READ_SZ = 1 << 20


def _pack_msg(obj: dict, blob: bytes = b"") -> bytes:
    j = json.dumps(obj).encode()
    return _MSG_HDR.pack(len(j)) + j + blob


def _unpack_msg(data: bytes) -> tuple[dict, bytes]:
    (jlen,) = _MSG_HDR.unpack_from(data, 0)
    obj = json.loads(data[_MSG_HDR.size:_MSG_HDR.size + jlen])
    return obj, data[_MSG_HDR.size + jlen:]


def drain_interval_s(env=None) -> float:
    env = os.environ if env is None else env
    return max(0.001,
               float(env.get("GYT_INGEST_DRAIN_MS", "15")) / 1e3)


def hb_stale_s(env=None) -> float:
    """Heartbeat age past which a live-pid worker counts as wedged."""
    env = os.environ if env is None else env
    return max(0.5, float(env.get("GYT_INGEST_HB_STALE_S", "5.0")))


# ======================================================================
# Worker process
# ======================================================================

class _Conn:
    __slots__ = ("sock", "fd", "hid", "conn_id", "pending", "last_rx",
                 "shard")

    def __init__(self, sock, hid, conn_id, shard):
        self.sock = sock
        self.fd = sock.fileno()
        self.hid = hid
        self.conn_id = conn_id
        self.shard = shard
        self.pending = b""
        self.last_rx = time.time()


class IngestWorker:
    """One shard group's wire edge: accept-handoff conns, validate,
    deframe/decode, WAL-append, publish decoded slabs. Runs a
    selector loop on the main thread; the only other threads are the
    WAL writer threads inside each :class:`~..utils.journal.Journal`."""

    def __init__(self, cfg: dict, ctrl_fd: int):
        from gyeeta_tpu.utils import shmring
        self.cfg = cfg
        self.w = int(cfg["worker"])
        self.nshards = int(cfg["nshards"])
        self.shards = [int(s) for s in cfg["shards"]]
        self.idle_timeout = float(cfg.get("idle_timeout") or 0)
        self.shm = shmring.WorkerShm(cfg["shm"])
        # per-shard publish staging (the edge's analogue of the fold's
        # staging slabs): decoded records accumulate until a slot's
        # worth is ready or the stage ages out — per-slot fixed costs
        # then amortize over hundreds of records even when the wire
        # delivers dribbles (small recvs used to cost 3-4x per record)
        self._stage: dict = {}             # shard → {subtype: [arrays]}
        self._stage_bytes = {}             # shard → staged payload bytes
        self._stage_t0 = {}                # shard → first-stage time
        self._stage_max_age = float(
            os.environ.get("GYT_INGEST_STAGE_MS", "15")) / 1e3
        self.shm.bump_epoch()
        self.shm.set_counter("done", 0)
        self.ctrl = socket.socket(fileno=ctrl_fd)
        self.ctrl.setblocking(False)
        self.sel = selectors.DefaultSelector()
        self.sel.register(self.ctrl, selectors.EVENT_READ, None)
        self.conns: dict[int, _Conn] = {}
        self.tick = 0
        self.running = True
        self._stop_reason: Optional[str] = None
        # per-owned-shard WAL (same shard_NN/ layout the in-process
        # ShardedJournal writes; a 1-shard flat runtime keeps the flat
        # dir so Runtime replay reads it unchanged)
        self.journals: dict = {}
        jdir = cfg.get("journal_dir")
        if jdir:
            from gyeeta_tpu.utils.journal import Journal
            jkw = cfg.get("journal_kw") or {}
            fmt = cfg.get("wal_subdir_fmt", "shard_{:02d}")
            for s in self.shards:
                sub = jdir if self.nshards == 1 \
                    else os.path.join(jdir, fmt.format(s))
                self.journals[s] = Journal(sub, stats=_ShmStats(self.shm),
                                           **jkw)

    # ------------------------------------------------------------ ctrl
    def _ctrl_recv(self) -> bool:
        try:
            data, fds, _flags, _addr = socket.recv_fds(
                self.ctrl, _CTRL_BUF, 4)
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            data, fds = b"", []
        if not data:
            # supervisor gone: a dying fold process takes the edge
            # down with it (agents reconnect to the respawned stack)
            self.running = False
            self._stop_reason = "ctrl_eof"
            return False
        msg, blob = _unpack_msg(data)
        cmd = msg.get("cmd")
        if cmd == "conn" and fds:
            from gyeeta_tpu.ingest import wire
            sock = socket.socket(fileno=fds[0])
            sock.setblocking(False)
            hid = int(msg["hid"])
            shard = hid % self.nshards
            c = _Conn(sock, hid, int(msg["conn_id"]), shard)
            self.conns[c.fd] = c
            self.sel.register(sock, selectors.EVENT_READ, c)
            self.shm.add_counter("conns_open")
            if blob:
                try:
                    self._on_bytes(c, blob)
                except wire.FrameError:
                    # poison bytes buffered before the handoff: same
                    # containment as _on_readable — only this conn dies,
                    # never the whole shard group's worker
                    self.shm.add_counter("frames_bad")
                    self._close_conn(c, "frame_error")
        elif cmd == "wal":
            # a supervisor-handled conn's validated chunk (stock-partha
            # adapter path): journal it here — this worker owns the
            # shard's WAL files
            j = self.journals.get(int(msg["hid"]) % self.nshards)
            if j is not None:
                j.append(blob, hid=int(msg["hid"]),
                         conn_id=int(msg.get("conn_id", 0)),
                         tick=self.tick)
                self.shm.add_counter("wal_appended_chunks")
        elif cmd == "tick":
            self.tick = int(msg["tick"])
        elif cmd == "quiesce":
            # staged records MUST publish before the position ships:
            # the checkpoint contract is "everything at/below the
            # position is in a ring the supervisor will drain" — a
            # record parked in worker staging would otherwise fold
            # after the checkpoint yet sit below its WAL position
            self._flush_stage()
            for j in self.journals.values():
                j.fsync()
            self._reply(msg, "quiesced",
                        wal={str(s): list(j.position())
                             for s, j in self.journals.items()},
                        heads=self.shm.heads())
        elif cmd == "seal":
            self._reply(msg, "sealed",
                        bounds={str(s): j.seal_active()
                                for s, j in self.journals.items()})
        elif cmd == "stop":
            self.running = False
            self._stop_reason = "stop"
            self._stop_req = msg
        return True

    def _ctrl_send(self, data: bytes, timeout: float = 5.0) -> bool:
        """Send one ctrl packet, waiting (bounded) for the SEQPACKET
        buffer to drain on EAGAIN. SEQPACKET sends are atomic, so a
        BlockingIOError means NOTHING was sent and a straight retry is
        safe. Dropping instead would be far worse than a short stall:
        a lost conn_closed parks the supervisor's handoff task on its
        death event forever, and a lost quiesced/stopped reply stalls
        the checkpoint barrier for its full timeout."""
        import select
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.ctrl.sendall(data)
                return True
            except BlockingIOError:
                remain = deadline - time.monotonic()
                if remain <= 0:             # pragma: no cover
                    return False
                select.select([], [self.ctrl], [], min(remain, 0.1))
            except OSError:                 # pragma: no cover
                return False

    def _reply(self, req: dict, ev: str, **kw) -> None:
        self._ctrl_send(_pack_msg({"ev": ev, "req": req.get("req"),
                                   **kw}))

    def _notify(self, ev: str, **kw) -> None:
        self._ctrl_send(_pack_msg({"ev": ev, **kw}))

    # ------------------------------------------------------------ conns
    def _close_conn(self, c: _Conn, reason: str) -> None:
        try:
            self.sel.unregister(c.sock)
        except (KeyError, ValueError):      # pragma: no cover
            pass
        try:
            c.sock.close()
        except OSError:                     # pragma: no cover
            pass
        self.conns.pop(c.fd, None)
        self.shm.add_counter("conns_closed")
        self._notify("conn_closed", hid=c.hid, conn_id=c.conn_id,
                     reason=reason)

    def _on_readable(self, c: _Conn) -> None:
        from gyeeta_tpu.ingest import wire
        # drain-to-EAGAIN with a byte budget: coalesce whatever the
        # wire already delivered into ONE deframe pass (per-chunk
        # costs amortize; the budget keeps one hot conn from starving
        # the others in the selector round)
        parts = []
        got = 0
        eof = False
        while got < 4 * _READ_SZ:
            try:
                data = c.sock.recv(_READ_SZ)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(c, "error")
                return
            if not data:
                eof = True
                break
            parts.append(data)
            got += len(data)
        if got:
            c.last_rx = time.time()
            try:
                self._on_bytes(c, b"".join(parts))
            except wire.FrameError:
                # poison header/frame: counted, conn closed — the agent
                # reconnects and resyncs (the in-process edge does the
                # same)
                self.shm.add_counter("frames_bad")
                self._close_conn(c, "frame_error")
                return
        if eof:
            if c.pending:
                self.shm.add_counter("frames_bad")
            self._close_conn(c, "eof")

    def _on_bytes(self, c: _Conn, data: bytes) -> None:
        from gyeeta_tpu.ingest import wire
        data = (c.pending + data) if c.pending else data
        k = wire.complete_prefix(data)      # may raise FrameError
        c.pending = data[k:]
        if k:
            self._ingest_chunk(c, data[:k])

    # ----------------------------------------------------------- ingest
    def _ingest_chunk(self, c: _Conn, chunk: bytes) -> None:
        """One validated complete-frame run: WAL append (post-
        validation, pre-publish — the same ordering the in-process
        feed path uses), native deframe to record arrays, shard split,
        ring publish."""
        from gyeeta_tpu.ingest import native, wire
        from gyeeta_tpu.utils import shmring
        j = self.journals.get(c.shard)
        if j is not None:
            j.append(chunk, hid=c.hid, conn_id=c.conn_id,
                     tick=self.tick)
            self.shm.add_counter("wal_appended_chunks")
        recs, _consumed, unknown = native.drain2(chunk)
        if unknown:
            self.shm.add_counter("unknown_records", unknown)
        nrec = sum(len(a) for a in recs.values())
        self.shm.add_counter("accepted_chunks")
        self.shm.add_counter("accepted_bytes", len(chunk))
        if not nrec:
            return
        self.shm.add_counter("accepted_records", nrec)
        now = time.time()
        for shard, srecs in self._split_shards(recs, c.shard).items():
            st = self._stage.setdefault(shard, {})
            for subtype, arr in srecs.items():
                st.setdefault(subtype, []).append(arr)
                self._stage_bytes[shard] = \
                    self._stage_bytes.get(shard, 0) + arr.nbytes
            self._stage_t0.setdefault(shard, now)
            if self._stage_bytes[shard] >= self.shm.slot_payload:
                self._flush_shard(shard)

    def _flush_shard(self, shard: int) -> None:
        """Publish one shard's staged records (merged per subtype) —
        a slot's worth amortizes the per-slot fixed cost ~100x vs
        publishing every dribble chunk on its own."""
        import numpy as np

        from gyeeta_tpu.utils import shmring
        st = self._stage.pop(shard, None)
        self._stage_bytes.pop(shard, None)
        self._stage_t0.pop(shard, None)
        if not st:
            return
        merged = {sub: (arrs[0] if len(arrs) == 1
                        else np.concatenate(arrs))
                  for sub, arrs in st.items()}
        for payload, n in shmring.split_records(
                merged, self.shm.slot_payload):
            self.shm.publish(shard, payload, n)

    def _flush_stage(self, only_aged: bool = False) -> None:
        now = time.time()
        for shard in list(self._stage):
            if not only_aged or now - self._stage_t0.get(shard, now) \
                    >= self._stage_max_age:
                self._flush_shard(shard)

    def _split_shards(self, recs: dict, home: int) -> dict:
        """Route each record array per shard by its host hash (the
        layout rule, ``mesh.shard_of_host`` = hid % nshards); records
        without a host column ride the conn's home shard. Relay conns
        carry many hosts per chunk, so this is per-RECORD routing —
        the same split the fold's ``_stage_raw`` used to do."""
        import numpy as np
        if self.nshards == 1:
            return {0: recs}
        out: dict = {}
        for subtype, arr in recs.items():
            names = arr.dtype.names or ()
            if "host_id" not in names:
                out.setdefault(home, {})[subtype] = arr
                continue
            dest = arr["host_id"].astype(np.int64) % self.nshards
            order = np.argsort(dest, kind="stable")
            arr = arr[order]
            bounds = np.searchsorted(dest[order],
                                     np.arange(self.nshards + 1))
            for s in range(self.nshards):
                a, b = int(bounds[s]), int(bounds[s + 1])
                if b > a:
                    out.setdefault(s, {})[subtype] = arr[a:b]
        return out

    # ------------------------------------------------------------- loop
    def run(self) -> None:
        import signal
        signal.signal(signal.SIGTERM, self._on_sigterm)
        last_hb = 0.0
        last_reap = time.time()
        while self.running:
            events = self.sel.select(timeout=0.2 if not self._stage
                                     else self._stage_max_age)
            for key, _ev in events:
                if key.data is None:
                    if not self._ctrl_recv():
                        break
                else:
                    self._on_readable(key.data)
            # age-based flush only: an idle SELECT round is not a
            # quiet wire — a worker that outruns its producers sees
            # empty rounds constantly, and flushing dribbles there
            # would undo the whole point of staging (the select
            # timeout above shrinks to the staging budget while
            # records are parked, so age is honored promptly)
            self._flush_stage(only_aged=True)
            now = time.time()
            if now - last_hb >= 0.2:
                self.shm.heartbeat()
                last_hb = now
            if self.idle_timeout and now - last_reap >= 1.0:
                last_reap = now
                for c in list(self.conns.values()):
                    if now - c.last_rx > self.idle_timeout:
                        self._close_conn(c, "idle")
        self._finish()

    def _on_sigterm(self, _sig, _frm) -> None:
        self.running = False
        self._stop_reason = self._stop_reason or "sigterm"

    def _finish(self) -> None:
        """Graceful exit: close conns, drain + fsync the WAL, publish
        final positions, mark done in the ring header. Everything
        already published stays in the rings for the supervisor's
        final drain — a clean SIGTERM leaves an EMPTY replay window."""
        for c in list(self.conns.values()):
            self._close_conn(c, "worker_stop")
        self._flush_stage()
        positions = {}
        for s, j in self.journals.items():
            j.close()                      # drain + fsync + close
            positions[str(s)] = list(j.position())
        self.shm.heartbeat()
        self.shm.set_counter("done", 1)
        req = getattr(self, "_stop_req", None)
        if req is not None:
            self._reply(req, "stopped", wal=positions,
                        heads=self.shm.heads())
        self.shm.close()


class _ShmStats:
    """Stats shim mapping the worker Journal's counters onto ring-
    header words (the supervisor renders them as gyt_ingest_proc_*)."""

    _MAP = {"wal_backlog_dropped": "wal_backlog_dropped"}

    def __init__(self, shm):
        self.shm = shm

    def bump(self, name, n=1):
        tgt = self._MAP.get(name)
        if tgt:
            self.shm.add_counter(tgt, n)

    def gauge(self, name, v):
        pass

    def timeit(self, name):
        import contextlib
        return contextlib.nullcontext()


def worker_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="gyeeta_tpu.net.ingestproc")
    ap.add_argument("--ctrl-fd", type=int, required=True)
    ap.add_argument("--cfg", required=True)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING,
        format="%(asctime)s %(levelname)s ingestproc %(message)s")
    cfg = json.loads(args.cfg)
    IngestWorker(cfg, args.ctrl_fd).run()
    return 0


# ======================================================================
# Supervisor (fold-process side)
# ======================================================================

class _WorkerHandle:
    """Supervisor-side state for one worker slot: subprocess, ctrl
    socket + reader thread, shm segment, pending sync requests, and
    the conns currently assigned to it."""

    def __init__(self, w: int, shards: list):
        self.w = w
        self.shards = shards
        self.proc: Optional[subprocess.Popen] = None
        self.ctrl: Optional[socket.socket] = None
        self.shm = None
        self.reader: Optional[threading.Thread] = None
        self.up = False
        self.pending: dict = {}            # req id → [Event, reply]
        self.conns: dict = {}              # conn_id → death Event
        self.last_counters: dict = {}
        self.spawned = 0


class IngestSupervisor:
    """Spawn/respawn ingest workers, hand off registered event conns,
    drain the shared-memory rings into the runtime, and carry the
    WAL/checkpoint barrier across the process boundary."""

    def __init__(self, rt, nprocs: int, journal_dir: Optional[str],
                 idle_timeout: Optional[float] = None):
        from gyeeta_tpu.utils import shmring
        self.rt = rt
        self.stats = rt.stats
        self.n = int(getattr(rt, "n", 1))
        self.nprocs = int(nprocs)
        if self.nprocs > max(1, self.n):
            raise ValueError(
                f"--ingest-procs {self.nprocs} > shards {self.n}: one "
                "worker owns at least one whole shard group")
        self.journal_dir = journal_dir
        self.idle_timeout = idle_timeout
        self._layout = getattr(rt, "layout", None)
        self._sharded = self.n > 1
        self._lock = threading.Lock()       # ctrl sends + spawn state
        self._req_seq = 0
        self._stopping = False
        self._loop = None                   # asyncio loop (set at start)
        self._final_wal: Optional[dict] = None
        self._run_id = uuid.uuid4().hex[:8]
        groups = [[s for s in range(max(1, self.n))
                   if s % self.nprocs == w]
                  for w in range(self.nprocs)]
        self.workers = [_WorkerHandle(w, groups[w])
                        for w in range(self.nprocs)]
        slots, slot_kb = shmring.ring_slots(), shmring.ring_slot_bytes()
        for h in self.workers:
            h.shm = shmring.WorkerShm(
                f"gyt_ing_{os.getpid()}_{self._run_id}_{h.w}",
                nshards=max(1, self.n), slots=slots,
                slot_bytes=slot_kb, create=True)

    # ---------------------------------------------------------- workers
    def worker_of_shard(self, shard: int) -> int:
        return int(shard) % self.nprocs

    def worker_of_hid(self, hid: int) -> int:
        s = (int(self._layout.shard_of_host(int(hid)))
             if self._layout is not None else int(hid) % max(1, self.n))
        return self.worker_of_shard(s)

    def start(self, loop=None) -> None:
        self._loop = loop
        for h in self.workers:
            self._spawn(h)

    def _spawn(self, h: _WorkerHandle) -> None:
        # zero the heartbeat words BEFORE the child exists: they
        # persist in the shared segment across respawns, and poll()'s
        # wedged check must not judge the new worker by the dead
        # epoch's last stamp (slow interpreter/numpy startup past
        # GYT_INGEST_HB_STALE_S would otherwise respawn-loop forever).
        # hb_seq == 0 disarms the check until the new loop's first beat.
        h.shm.set_counter("hb_seq", 0)
        h.shm.set_counter("hb_time_us", 0)
        sup_sock, child_sock = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_SEQPACKET)
        for s in (sup_sock, child_sock):
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                             _CTRL_BUF)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                             _CTRL_BUF)
            except OSError:                 # pragma: no cover
                pass
        jkw = None
        if self.journal_dir:
            o = self.rt.opts
            jkw = dict(segment_max_bytes=o.journal_segment_mb << 20,
                       fsync_bytes=o.journal_fsync_kb << 10,
                       fsync_ms=o.journal_fsync_ms,
                       backlog_max_bytes=o.journal_backlog_mb << 20)
        cfg = {"worker": h.w, "nshards": max(1, self.n),
               "shards": h.shards, "shm": h.shm.name,
               "journal_dir": self.journal_dir, "journal_kw": jkw,
               "idle_timeout": self.idle_timeout,
               "wal_subdir_fmt": getattr(self._layout,
                                         "WAL_SUBDIR_FMT",
                                         "shard_{:02d}")}
        child_fd = child_sock.fileno()
        env = dict(os.environ, GYT_SHMRING_NOTRACK="1")
        # the worker must import gyeeta_tpu regardless of the
        # supervisor's cwd (serve may run from anywhere)
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_parent + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        # the worker never touches jax — make sure a TPU-pinning env
        # can't make N workers grab the accelerator runtime
        env.setdefault("JAX_PLATFORMS", "cpu")
        h.proc = subprocess.Popen(
            [sys.executable, "-m", "gyeeta_tpu.net.ingestproc",
             "--ctrl-fd", str(child_fd), "--cfg", json.dumps(cfg)],
            pass_fds=[child_fd], env=env, close_fds=True)
        child_sock.close()
        h.ctrl = sup_sock
        h.up = True
        h.spawned += 1
        h.reader = threading.Thread(
            target=self._reader_loop, args=(h,),
            name=f"gyt-ingest-ctrl-{h.w}", daemon=True)
        h.reader.start()
        log.info("ingest worker %d: pid %d, shards %s", h.w,
                 h.proc.pid, h.shards)

    # ----------------------------------------------------- ctrl plumbing
    def _reader_loop(self, h: _WorkerHandle) -> None:
        ctrl = h.ctrl
        while True:
            try:
                data = ctrl.recv(_CTRL_BUF)
            except OSError:
                data = b""
            if not data:
                break
            try:
                msg, _blob = _unpack_msg(data)
            except Exception:               # pragma: no cover
                continue
            ev = msg.get("ev")
            rid = msg.get("req")
            if rid is not None and rid in h.pending:
                slot = h.pending.pop(rid)
                slot[1] = msg
                slot[0].set()
            elif ev == "conn_closed":
                self._on_conn_closed(h, msg)
        # EOF: the worker died (or closed on graceful stop) — release
        # its conns so the serving edge closes them and agents reconnect
        self._release_conns(h)

    def _on_conn_closed(self, h: _WorkerHandle, msg: dict) -> None:
        ev = h.conns.pop(int(msg.get("conn_id", 0)), None)
        reason = msg.get("reason", "")
        if reason == "idle":
            self.stats.bump("conn_timeouts|kind=idle")
        if ev is not None:
            self._set_event(ev)

    def _release_conns(self, h: _WorkerHandle) -> None:
        conns, h.conns = h.conns, {}
        for ev in conns.values():
            self._set_event(ev)

    def _set_event(self, ev) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(ev.set)
        else:                               # pragma: no cover
            ev.set()

    def _send(self, h: _WorkerHandle, msg: dict, blob: bytes = b"",
              fds: tuple = ()) -> bool:
        if not h.up or h.ctrl is None:
            return False
        data = _pack_msg(msg, blob)
        try:
            with self._lock:
                if fds:
                    socket.send_fds(h.ctrl, [data], list(fds))
                else:
                    h.ctrl.sendall(data)
            return True
        except OSError:
            return False

    def _request(self, h: _WorkerHandle, msg: dict,
                 timeout: float = 30.0) -> Optional[dict]:
        """Synchronous ctrl round trip (safe from any thread: the
        reply is fulfilled by the reader thread)."""
        with self._lock:
            self._req_seq += 1
            rid = self._req_seq
        ev = threading.Event()
        slot = [ev, None]
        h.pending[rid] = slot
        if not self._send(h, {**msg, "req": rid}):
            h.pending.pop(rid, None)
            return None
        if not ev.wait(timeout):
            h.pending.pop(rid, None)
            return None
        return slot[1]

    # ------------------------------------------------------------ handoff
    def handoff(self, hid: int, conn_id: int, sock_fd: int,
                initial: bytes, death_event) -> bool:
        """Hand one registered event conn to its shard group's worker.
        Returns False when the worker is down (the caller closes the
        conn; the agent reconnects after the respawn)."""
        h = self.workers[self.worker_of_hid(hid)]
        if not h.up:
            return False
        h.conns[int(conn_id)] = death_event
        ok = self._send(h, {"cmd": "conn", "hid": int(hid),
                            "conn_id": int(conn_id)},
                        blob=initial, fds=(sock_fd,))
        if not ok:
            h.conns.pop(int(conn_id), None)
        return ok

    def forward_wal(self, hid: int, conn_id: int, chunk: bytes) -> bool:
        """Journal a supervisor-handled conn's validated chunk in the
        owning worker (stock-partha adapter streams keep durability
        in mproc mode; the records themselves fold in-process)."""
        h = self.workers[self.worker_of_hid(hid)]
        return self._send(h, {"cmd": "wal", "hid": int(hid),
                              "conn_id": int(conn_id)}, blob=chunk)

    def broadcast_tick(self, tick: int) -> None:
        for h in self.workers:
            self._send(h, {"cmd": "tick", "tick": int(tick)})

    def ring_backlog_frac(self) -> float:
        """Worst committed-but-unconsumed occupancy across every
        (worker, shard) ring, as a fraction of ring capacity — the
        admission controller's overload signal (``net/server.py``
        throttles agents BEFORE the drop-oldest rings shed). Reads two
        shared-memory words per ring; 0.0 when nothing is spawned."""
        worst = 0.0
        for h in self.workers:
            if h.shm is None or not h.shm.slots:
                continue
            for s in range(max(1, self.n)):
                # fraction per ring, against ITS OWN capacity — mixing
                # a global worst count with one worker's slot count
                # skews the signal under per-worker sizing
                f = h.shm.backlog(s) / h.shm.slots
                if f > worst:
                    worst = f
        return worst

    # -------------------------------------------------------------- drain
    def drain(self, max_slots_per_ring: int = 0) -> int:
        """Drain every ring into the runtime's staging slabs. Called
        from the serving loop (drain task + feed barrier). Returns
        records ingested; ring overwrites land on counted per-shard
        drop counters — the no-silent-loss ledger."""
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.utils import shmring
        total = 0
        for h in self.workers:
            for s in range(max(1, self.n)):
                bufs, nrec, ds, dr = h.shm.drain(s, max_slots_per_ring)
                if ds:
                    self.stats.bump(
                        f"ingest_ring_dropped_slots|shard={s}", ds)
                    self.stats.bump(
                        f"ingest_ring_dropped_records|shard={s}", dr)
                if not bufs:
                    continue
                consumed = 0
                for payload in bufs:
                    recs, nr = shmring.unpack_sections(
                        payload, wire.DTYPE_OF_SUBTYPE)
                    consumed += nr
                    if not recs:
                        continue
                    if self._sharded:
                        total += self.rt.ingest_records(recs, shard=s)
                    else:
                        total += self.rt.ingest_records(recs)
                # the fold-side half of the cross-process ledger:
                # published == consumed + dropped, exactly
                self.stats.bump("ingest_ring_consumed_records",
                                consumed)
                self.stats.gauge(
                    f"ingest_ring_backlog_slots|proc={h.w}",
                    float(h.shm.backlog()))
        return total

    def _drain_to_heads(self, heads_by_worker: dict,
                        timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.drain()
            lag = 0
            for h in self.workers:
                heads = heads_by_worker.get(h.w)
                if heads is None:
                    continue
                tails = h.shm.tails()
                lag += sum(max(0, int(hd) - int(t))
                           for hd, t in zip(heads, tails))
            if lag == 0:
                return
            time.sleep(0.002)

    # -------------------------------------------------- checkpoint barrier
    def quiesce(self, timeout: float = 30.0) -> dict:
        """The cross-process checkpoint barrier: every worker fsyncs
        its journals and replies (positions, ring heads); the rings
        are drained to those heads before returning. The returned
        per-shard positions are safe to record in a checkpoint —
        every chunk at/below them has been folded (or counted as a
        ring drop)."""
        if self._stopping and self._final_wal is not None:
            return dict(self._final_wal)
        positions: dict = {}
        heads: dict = {}
        for h in self.workers:
            rep = self._request(h, {"cmd": "quiesce"}, timeout)
            if rep is None:
                continue                    # dead worker: files are as
            #                                 durable as its last fsync
            heads[h.w] = rep.get("heads") or []
            for s, pos in (rep.get("wal") or {}).items():
                positions[int(s)] = [int(pos[0]), int(pos[1])]
        self._drain_to_heads(heads)
        return positions

    def seal(self, timeout: float = 30.0) -> dict:
        """Proxy ``Journal.seal_active`` into the workers (the history
        compactor's handoff). Returns {shard: first-sealed bound}."""
        bounds: dict = {}
        for h in self.workers:
            rep = self._request(h, {"cmd": "seal"}, timeout)
            for s, b in ((rep or {}).get("bounds") or {}).items():
                bounds[int(s)] = int(b)
        return bounds

    # ----------------------------------------------------------- monitor
    def poll(self) -> int:
        """Liveness + metrics pass (call at ~1s cadence from the
        serving loop): respawn dead/wedged workers onto their sticky
        shard groups, fold worker-header counter deltas into the
        fold-process Stats registry (→ gyt_ingest_proc_* rows).
        Returns workers respawned."""
        from gyeeta_tpu.utils.shmring import COUNTER_NAMES
        respawned = 0
        stale = hb_stale_s()
        for h in self.workers:
            ctrs = h.shm.counters()
            # counter deltas → labeled counters (monotone totals render
            # in /metrics; deltas keep respawn resets correct)
            last = h.last_counters
            for name in ("accepted_records", "accepted_chunks",
                         "accepted_bytes", "published_records",
                         "frames_bad", "unknown_records",
                         "wal_appended_chunks", "wal_backlog_dropped"):
                d = ctrs[name] - last.get(name, 0)
                if d > 0:
                    self.stats.bump(
                        f"ingest_proc_{name}|proc={h.w}", d)
            h.last_counters = {k: ctrs[k] for k in COUNTER_NAMES}
            age = h.shm.hb_age_s()
            self.stats.gauge(
                f"ingest_proc_heartbeat_age_seconds|proc={h.w}",
                round(min(age, 1e9), 3))
            self.stats.gauge(f"ingest_proc_up|proc={h.w}",
                             1.0 if h.up else 0.0)
            self.stats.gauge(f"ingest_proc_epoch|proc={h.w}",
                             float(h.shm.epoch()))
            # the worker's pid as a gauge: lets an operator (or the
            # fault-injection harness) target one worker from OUTSIDE
            # the serve process — kill a wedged one, strace a slow one
            if h.proc is not None:
                self.stats.gauge(f"ingest_proc_pid|proc={h.w}",
                                 float(h.proc.pid))
            self.stats.gauge(f"ingest_proc_conns|proc={h.w}",
                             float(max(0, ctrs["conns_open"]
                                       - ctrs["conns_closed"])))
            if self._stopping:
                continue
            dead = h.proc is not None and h.proc.poll() is not None
            wedged = (h.up and not dead and ctrs["hb_seq"] > 0
                      and age > stale)
            if dead or wedged:
                if wedged:                  # pragma: no cover — chaos
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
                self._teardown(h)
                self.stats.bump(f"ingest_proc_respawns|proc={h.w}")
                self.rt.notifylog.add(
                    f"ingest worker {h.w} "
                    f"{'wedged' if wedged else 'died'} — respawning "
                    f"onto shards {h.shards}", ntype="warn",
                    source="selfmon")
                self._spawn(h)
                respawned += 1
        return respawned

    def _teardown(self, h: _WorkerHandle) -> None:
        h.up = False
        self._release_conns(h)
        if h.ctrl is not None:
            try:
                h.ctrl.close()
            except OSError:                 # pragma: no cover
                pass
            h.ctrl = None
        if h.proc is not None:
            try:
                h.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:   # pragma: no cover
                h.proc.kill()
                h.proc.wait(timeout=5.0)

    # --------------------------------------------------------- lifecycle
    def stop(self, timeout: float = 30.0) -> dict:
        """Graceful stop: workers close conns, drain + fsync their
        WALs and report final positions; the rings are drained to
        their final heads BEFORE this returns — the final checkpoint
        therefore supersedes the whole WAL window (respawn replays
        ZERO chunks). Returns the final per-shard WAL positions."""
        self._stopping = True
        positions: dict = {}
        heads: dict = {}
        for h in self.workers:
            rep = self._request(h, {"cmd": "stop"}, timeout)
            if rep is not None:
                heads[h.w] = rep.get("heads") or []
                for s, pos in (rep.get("wal") or {}).items():
                    positions[int(s)] = [int(pos[0]), int(pos[1])]
        self._drain_to_heads(heads)
        for h in self.workers:
            self._teardown(h)
        self._final_wal = dict(positions)
        return positions

    def close(self) -> None:
        for h in self.workers:
            if h.shm is not None:
                h.shm.close()
                h.shm.unlink()

    def wal_positions(self) -> Optional[dict]:
        return self._final_wal


# ======================================================================
# Fold-process WAL view
# ======================================================================

class ProcWalView:
    """Duck-types :class:`~gyeeta_tpu.utils.journal.Journal` for the
    fold process while ingest WORKERS own the segment writers: the
    checkpoint path (``fsync``/``position``/``truncate_upto``), replay
    (``read_from`` — only used before workers spawn), the compactor
    handoff (``seal_active``/``sealed_upto``/``set_truncate_floor``)
    and the health gauges all keep working; ``append`` forwards the
    chunk to the owning worker's journal over the control channel."""

    def __init__(self, sup: IngestSupervisor, path, n_shards: int,
                 stats=None, subdir_fmt: str = "shard_{:02d}"):
        import pathlib
        from gyeeta_tpu.utils.journal import _NullStats
        self.sup = sup
        self.dir = pathlib.Path(path)
        self.n = int(n_shards)
        self.subdir_fmt = subdir_fmt
        self.stats = stats if stats is not None else _NullStats()
        self._pos: dict = {}               # shard → [seg, off]
        self._floors: dict = {}
        self._sealed: dict = {}

    def _subdir(self, s: int):
        return self.dir if self.n == 1 \
            else self.dir / self.subdir_fmt.format(s)

    # ------------------------------------------------------------ append
    def append(self, buf: bytes, hid: int = 0, conn_id: int = 0,
               tick: int = 0) -> None:
        if not self.sup.forward_wal(hid, conn_id, buf):
            self.stats.bump("wal_forward_failed")

    def poll(self) -> None:
        pass

    # ----------------------------------------------------------- barrier
    def fsync(self) -> None:
        self._pos.update(self.sup.quiesce())

    def position(self) -> list:
        """Per-shard [seg, off] (the ShardedJournal shape) from the
        last quiesce; shards with no traffic yet report [0, MAGIC]."""
        from gyeeta_tpu.utils.journal import MAGIC
        out = []
        for s in range(self.n):
            out.append(list(self._pos.get(s, [0, len(MAGIC)])))
        return out if self.n > 1 else tuple(out[0])

    def seal_active(self):
        b = self.sup.seal()
        self._sealed.update(b)
        if self.n == 1:
            return b.get(0, 0)
        return [b.get(s, 0) for s in range(self.n)]

    def sealed_upto(self):
        if self.n == 1:
            return self._sealed.get(0, 0)
        return [self._sealed.get(s, 0) for s in range(self.n)]

    def set_truncate_floor(self, seq, name: str = "compact") -> None:
        fl = self._floors.setdefault(name, {})
        if isinstance(seq, (list, tuple)):
            for s, v in enumerate(seq):
                fl[s] = max(fl.get(s, 0), int(v))
        else:
            for s in range(self.n):
                fl[s] = max(fl.get(s, 0), int(seq))

    # ---------------------------------------------------------- truncate
    def truncate_upto(self, bounds) -> int:
        """File-level truncation (safe cross-process: workers hold
        only their ACTIVE segment open, and the bound never reaches
        it — the bound IS a quiesced position's segment)."""
        from gyeeta_tpu.utils.journal import _SEG_FMT, dir_segments
        n = 0
        per = {}
        if isinstance(bounds, (list, tuple)) \
                and bounds and isinstance(bounds[0], (list, tuple)):
            per = {s: int(b[0]) for s, b in enumerate(bounds)}
        else:
            b = int(bounds[0]) if isinstance(bounds, (list, tuple)) \
                else int(bounds)
            per = {s: b for s in range(self.n)}
        for s in range(self.n):
            bound = per.get(s, 0)
            floors = [fl[s] for fl in self._floors.values() if s in fl]
            if floors:
                bound = min(bound, min(floors))
            d = self._subdir(s)
            if not d.is_dir():
                continue
            segs = dir_segments(d)
            for seg in segs:
                if seg >= bound or seg == (segs[-1] if segs else 0):
                    continue
                try:
                    (d / _SEG_FMT.format(seg)).unlink()
                    n += 1
                except OSError:             # pragma: no cover
                    pass
        if n:
            self.stats.bump("wal_segments_deleted", n)
        return n

    # -------------------------------------------------------------- read
    def read_from(self, pos=None):
        """K-way tick-merged read over the shard subdirs (only used
        at restore time, before the workers spawn — the files are
        quiet then)."""
        import heapq
        from gyeeta_tpu.utils.journal import read_sealed

        if self.n == 1:
            p = tuple(pos) if pos else None
            for _seg, _nxt, _t, hid, tick, cid, chunk in read_sealed(
                    self.dir, p, None, stats=self.stats):
                yield hid, tick, cid, chunk
            return
        pos_list = None
        if pos is not None:
            pos = list(pos)
            if pos and isinstance(pos[0], (list, tuple)):
                pos_list = pos
            else:
                self.stats.bump("wal_position_gap")

        def stream(s):
            p = tuple(pos_list[s]) if pos_list is not None \
                and s < len(pos_list) else None
            d = self._subdir(s)
            if not d.is_dir():
                return
            for _seg, _nxt, _t, hid, tick, cid, chunk in read_sealed(
                    d, p, None, stats=self.stats):
                yield (tick, s, hid, cid, chunk)

        for tick, _s, hid, cid, chunk in heapq.merge(
                *(stream(s) for s in range(self.n)),
                key=lambda e: e[0]):
            yield hid, tick, cid, chunk

    # ------------------------------------------------------------ gauges
    def gauges(self) -> dict:
        total = 0
        nseg = 0
        for s in range(self.n):
            d = self._subdir(s)
            if not d.is_dir():
                continue
            for p in d.glob("gyt_wal_*.gytwal"):
                try:
                    total += p.stat().st_size
                    nseg += 1
                except OSError:             # pragma: no cover
                    pass
        try:
            backlog = sum(h.shm.backlog() for h in self.sup.workers)
        except (ValueError, OSError):       # rings already unlinked
            backlog = 0
        return {"journal_segments": float(nseg),
                "journal_bytes": float(total),
                "journal_backlog_bytes": 0.0,
                "journal_pending_bytes": 0.0,
                "ingest_ring_backlog_slots": float(backlog)}

    # --------------------------------------------------------- lifecycle
    def close(self) -> None:
        pass                                # workers own the writers

    def abort(self) -> None:
        pass


if __name__ == "__main__":
    sys.exit(worker_main())
