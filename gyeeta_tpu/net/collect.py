"""Real host collectors: /proc, /sys/fs/cgroup, os-release → wire records.

The agent-side measurement half of the reference's L2/L3 collectors,
re-scoped to what a userspace-only agent can read:

- :class:`CpuMemCollector` — /proc/stat + /proc/meminfo + /proc/vmstat
  deltas → one ``CPU_MEM_DT`` record per 2s sweep (the reference's
  ``SYS_CPU_STATS``/``SYS_MEM_STATS`` sampling,
  ``common/gy_sys_stat.cc:1144``; classification stays server-side);
- :func:`collect_host_info` — os-release / cpuinfo / topology →
  ``HOST_INFO_DT`` + its NAME_INTERN announcements (the
  ``SYS_HARDWARE`` inventory, ``common/gy_sys_hardware.h``; cloud IMDS
  is left "none" — no egress assumption, unlike
  ``common/gy_cloud_metadata.cc``);
- :class:`CgroupCollector` — cgroup v2 unified (or v1 cpuacct/memory)
  walk with usage/throttle deltas → ``CGROUP_DT`` records (the
  ``CGROUP_HANDLE`` stats tier, ``common/gy_cgroup_stat.h``).

Everything degrades to empty records when a surface is missing
(containers often mask /proc pieces); collectors never raise on I/O.

eBPF flow/response capture has no userspace equivalent — conn/resp
streams still come from instrumented workloads or the simulator; these
collectors make the host/cgroup/inventory subsystems REAL on any Linux
box the agent runs on.
"""

from __future__ import annotations

import os
import pathlib
import time
from typing import Optional

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils.intern import InternTable


def _read(path: str) -> str:
    try:
        return pathlib.Path(path).read_text()
    except OSError:
        return ""


def _fields(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) >= 2:
            out[parts[0].rstrip(":")] = parts[1]
    return out


# ------------------------------------------------------------------ cpumem
class _CpuSample:
    def __init__(self):
        stat = _read("/proc/stat")
        self.t = time.monotonic()
        self.cores = {}
        self.total = None
        self.ctxt = 0
        self.processes = 0
        self.procs_running = 0
        self.btime = 0
        for line in stat.splitlines():
            p = line.split()
            if not p:
                continue
            if p[0] == "cpu":
                self.total = np.array(p[1:11], np.float64)
            elif p[0].startswith("cpu"):
                self.cores[p[0]] = np.array(p[1:11], np.float64)
            elif p[0] == "ctxt":
                self.ctxt = int(p[1])
            elif p[0] == "processes":
                self.processes = int(p[1])
            elif p[0] == "procs_running":
                self.procs_running = int(p[1])
            elif p[0] == "btime":
                self.btime = int(p[1])
        vm = _fields(_read("/proc/vmstat"))
        self.pgin = int(vm.get("pgpgin", 0)) + int(vm.get("pgpgout", 0))
        self.swap = int(vm.get("pswpin", 0)) + int(vm.get("pswpout", 0))
        self.oom = int(vm.get("oom_kill", 0))
        self.allocstall = sum(int(v) for k, v in vm.items()
                              if k.startswith("allocstall"))


def _cpu_pcts(prev: np.ndarray, cur: np.ndarray):
    """(total%, user%, sys%, iowait%) from two /proc/stat count rows."""
    d = cur - prev
    tot = max(float(d.sum()), 1e-9)
    idle = float(d[3] + d[4])                  # idle + iowait
    return (100.0 * (tot - idle) / tot,
            100.0 * float(d[0] + d[1]) / tot,  # user + nice
            100.0 * float(d[2]) / tot,
            100.0 * float(d[4]) / tot)


class CpuMemCollector:
    """Delta-based host CPU/mem sampler; call :meth:`sample` per sweep."""

    def __init__(self, host_id: int = 0):
        self.host_id = host_id
        self._prev = _CpuSample()

    def sample(self) -> np.ndarray:
        cur = _CpuSample()
        prev, self._prev = self._prev, cur
        dt = max(cur.t - prev.t, 1e-3)
        out = np.zeros(1, wire.CPU_MEM_DT)
        r = out[0]
        if cur.total is not None and prev.total is not None:
            cpu, usr, sys_, iow = _cpu_pcts(prev.total, cur.total)
            r["cpu_pct"], r["usercpu_pct"] = cpu, usr
            r["syscpu_pct"], r["iowait_pct"] = sys_, iow
            core_pcts = [
                _cpu_pcts(prev.cores[c], cur.cores[c])[0]
                for c in cur.cores if c in prev.cores]
            r["max_core_cpu_pct"] = max(core_pcts, default=cpu)
        r["cs_sec"] = (cur.ctxt - prev.ctxt) / dt
        r["forks_sec"] = (cur.processes - prev.processes) / dt
        r["procs_running"] = cur.procs_running
        mem = _fields(_read("/proc/meminfo"))

        def kb(key):
            return float(mem.get(key, 0))

        total = max(kb("MemTotal"), 1.0)
        avail = kb("MemAvailable")
        r["rss_pct"] = 100.0 * (total - avail) / total
        climit = kb("CommitLimit")
        r["commit_pct"] = (100.0 * kb("Committed_AS") / climit
                           if climit > 0 else 0.0)
        stot = kb("SwapTotal")
        r["swap_free_pct"] = (100.0 * kb("SwapFree") / stot
                              if stot > 0 else 100.0)
        r["pg_inout_sec"] = (cur.pgin - prev.pgin) / dt
        r["swap_inout_sec"] = (cur.swap - prev.swap) / dt
        r["allocstall_sec"] = (cur.allocstall - prev.allocstall) / dt
        r["oom_kills"] = cur.oom - prev.oom
        r["ncpus"] = len(cur.cores) or (os.cpu_count() or 1)
        r["host_id"] = self.host_id
        return out


# ---------------------------------------------------------------- hostinfo
def collect_host_info(host_id: int = 0):
    """→ (HOST_INFO_DT record array, NAME_INTERN record array)."""

    def osrel(key):
        for line in _read("/etc/os-release").splitlines():
            if line.startswith(key + "="):
                return line.split("=", 1)[1].strip().strip('"')
        return ""

    distro = osrel("PRETTY_NAME") or osrel("NAME") or "linux"
    kern = os.uname().release
    cputype = ""
    for line in _read("/proc/cpuinfo").splitlines():
        if line.startswith("model name"):
            cputype = line.split(":", 1)[1].strip()
            break
    if not cputype:
        cputype = os.uname().machine
    mem = _fields(_read("/proc/meminfo"))
    nnuma = len([d for d in pathlib.Path(
        "/sys/devices/system/node").glob("node[0-9]*")]) \
        if pathlib.Path("/sys/devices/system/node").exists() else 1
    btime = 0
    for line in _read("/proc/stat").splitlines():
        if line.startswith("btime"):
            btime = int(line.split()[1])
    hyper = "hypervisor" in _read("/proc/cpuinfo")
    in_container = pathlib.Path("/.dockerenv").exists() or \
        "container" in os.environ
    is_k8s = pathlib.Path(
        "/var/run/secrets/kubernetes.io").exists()

    def mid(s):
        return InternTable.intern(s, wire.NAME_KIND_MISC)

    out = np.zeros(1, wire.HOST_INFO_DT)
    r = out[0]
    r["host_id"] = host_id
    r["ncpus"] = os.cpu_count() or 1
    r["nnuma"] = max(nnuma, 1)
    r["ram_mb"] = float(mem.get("MemTotal", 0)) / 1024
    r["swap_mb"] = float(mem.get("SwapTotal", 0)) / 1024
    r["boot_tusec"] = btime * 1_000_000
    r["kern_ver_id"] = mid(kern)
    r["distro_id"] = mid(distro)
    r["cputype_id"] = mid(cputype)
    # cloud IMDS is config-gated (GYT_CLOUD_META=1) — the no-egress
    # default stays, but the descope is a flag, not an absence
    # (utils/cloudmeta.py; ref gy_cloud_metadata.cc:27-67)
    from gyeeta_tpu.utils import cloudmeta
    cm = cloudmeta.detect()
    iid = cm["instance_id"] if cm else ""
    region = cm["region"] if cm else ""
    zone = cm["zone"] if cm else ""
    r["instance_id"] = mid(iid)
    r["region_id"] = mid(region)
    r["zone_id"] = mid(zone)
    r["virt_type"] = 2 if in_container else (1 if hyper else 0)
    r["cloud_type"] = cm["cloud_type"] if cm else 0
    r["is_k8s"] = is_k8s
    names = InternTable.records(
        [(wire.NAME_KIND_MISC, mid(s), s)
         for s in (kern, distro, cputype, "", iid, region, zone)])
    return out, names


# ----------------------------------------------------------------- cgroups
_CG_ROOT = "/sys/fs/cgroup"


def _cg_is_v2(root: str = _CG_ROOT) -> bool:
    return pathlib.Path(root, "cgroup.controllers").exists()


class _CgSample:
    def __init__(self, path: pathlib.Path, v2: bool,
                 root: str = _CG_ROOT):
        self.t = time.monotonic()
        if v2:
            st = _fields(_read(str(path / "cpu.stat")))
            self.cpu_usec = int(st.get("usage_usec", 0))
            self.nr_periods = int(st.get("nr_periods", 0))
            self.nr_throttled = int(st.get("nr_throttled", 0))
            self.rss = int(_read(str(path / "memory.current")) or 0)
            lim = _read(str(path / "memory.max")).strip()
            self.mem_limit = -1 if lim in ("", "max") else int(lim)
            cpu_max = _read(str(path / "cpu.max")).split()
            self.cpu_limit_pct = -1.0
            if len(cpu_max) == 2 and cpu_max[0] != "max":
                self.cpu_limit_pct = 100.0 * int(cpu_max[0]) / int(
                    cpu_max[1])
            mst = _fields(_read(str(path / "memory.stat")))
            self.pgmaj = int(mst.get("pgmajfault", 0))
            pids = _read(str(path / "pids.current")).strip()
            self.nprocs = int(pids) if pids.isdigit() else 0
        else:
            sub = _sub(path, root)
            self.cpu_usec = int(
                _read(f"{root}/cpuacct{sub}/cpuacct.usage")
                or 0) // 1000
            st = _fields(_read(f"{root}/cpu{sub}/cpu.stat"))
            self.nr_periods = int(st.get("nr_periods", 0))
            self.nr_throttled = int(st.get("nr_throttled", 0))
            self.rss = int(_read(
                f"{root}/memory{sub}/memory.usage_in_bytes") or 0)
            lim = _read(
                f"{root}/memory{sub}/memory.limit_in_bytes").strip()
            self.mem_limit = int(lim) if lim.isdigit() else -1
            if self.mem_limit > 1 << 60:        # v1 "unlimited"
                self.mem_limit = -1
            self.cpu_limit_pct = -1.0
            mst = _fields(_read(f"{root}/memory{sub}/memory.stat"))
            self.pgmaj = int(mst.get("pgmajfault", 0))
            procs = _read(f"{root}/cpu{sub}/cgroup.procs")
            self.nprocs = len(procs.splitlines())


def _sub(path: pathlib.Path, root: str = _CG_ROOT) -> str:
    """v1 helper: the subpath below the controller root ('' for root)."""
    s = str(path)
    for ctrl in ("/cpuacct", "/cpu", "/memory"):
        pre = root + ctrl
        if s.startswith(pre):
            return s[len(pre):]
    return ""


class CgroupCollector:
    """Tracks up to ``max_groups`` cgroup dirs (top 2 levels) with
    delta-based cpu%/throttle rates. v2 unified or v1 controllers."""

    def __init__(self, host_id: int = 0, root: str = _CG_ROOT,
                 max_groups: int = 64):
        self.host_id = host_id
        self.root = pathlib.Path(root)
        self.v2 = _cg_is_v2(root)
        self.max_groups = max_groups
        self._base = self.root if self.v2 else self.root / "cpu"
        self._prev: dict[str, _CgSample] = {}

    def _dirs(self):
        base = self._base
        if not base.exists():
            return
        yield base
        n = 1

        def children(d):
            # per-directory guard: one unreadable slice must not end
            # the walk for every group sorting after it
            try:
                return sorted(p for p in d.iterdir() if p.is_dir())
            except OSError:
                return []

        for d1 in children(base):
            yield d1
            n += 1
            if n >= self.max_groups:
                return
            for d2 in children(d1):
                yield d2
                n += 1
                if n >= self.max_groups:
                    return

    def sample(self) -> tuple[np.ndarray, np.ndarray]:
        """→ (CGROUP_DT records, NAME_INTERN records for the paths)."""
        recs = []
        names = []
        ncpu = os.cpu_count() or 1
        seen = set()
        for d in self._dirs():
            key = str(d)
            seen.add(key)
            try:
                cur = _CgSample(d, self.v2, str(self.root))
            except (OSError, ValueError):
                continue
            prev = self._prev.get(key)
            self._prev[key] = cur
            if prev is None:
                continue                  # need a delta
            dt = max(cur.t - prev.t, 1e-3)
            r = np.zeros((), wire.CGROUP_DT)
            disp = "/" + str(d.relative_to(self._base)) \
                if d != self._base else "/"
            dir_id = InternTable.intern(disp, wire.NAME_KIND_MISC)
            r["cg_id"] = np.uint64(dir_id) ^ np.uint64(self.host_id)
            r["dir_id"] = dir_id
            # cpu% normalized to one core (matches sim semantics)
            r["cpu_pct"] = min(
                (cur.cpu_usec - prev.cpu_usec) / (dt * 1e4), 1e4)
            r["cpu_limit_pct"] = cur.cpu_limit_pct
            dper = cur.nr_periods - prev.nr_periods
            dthr = cur.nr_throttled - prev.nr_throttled
            r["cpu_throttled_pct"] = 100.0 * dthr / dper if dper else 0.0
            r["rss_mb"] = cur.rss / (1 << 20)
            r["memory_limit_mb"] = (cur.mem_limit / (1 << 20)
                                    if cur.mem_limit > 0 else -1.0)
            r["pgmajfault_sec"] = (cur.pgmaj - prev.pgmaj) / dt
            r["nprocs"] = cur.nprocs
            r["is_v2"] = self.v2
            thr = float(r["cpu_throttled_pct"])
            busy = float(r["cpu_pct"]) > 90.0 * ncpu
            r["state"] = 3 if (thr > 25.0 or busy) else 1
            r["host_id"] = self.host_id
            recs.append(r)
            names.append((wire.NAME_KIND_MISC, dir_id, disp))
        # evict samples for cgroups that vanished (pod churn would grow
        # the baseline dict without bound otherwise)
        for key in [k for k in self._prev if k not in seen]:
            del self._prev[key]
        rec_arr = (np.array(recs, dtype=wire.CGROUP_DT)
                   if recs else np.empty(0, wire.CGROUP_DT))
        return rec_arr, InternTable.records(names)


# ----------------------------------------------------------------- mounts
_NETWORK_FS = {"nfs", "nfs4", "cifs", "smbfs", "glusterfs", "cephfs",
               "ocfs2", "afs", "9p", "fuse.sshfs"}
_SKIP_FS = {"proc", "sysfs", "devtmpfs", "devpts", "tmpfs", "cgroup",
            "cgroup2", "securityfs", "debugfs", "tracefs", "configfs",
            "pstore", "bpf", "mqueue", "hugetlbfs", "autofs", "ramfs",
            "binfmt_misc", "fusectl", "rpc_pipefs",
            "squashfs", "nsfs", "efivarfs"}
# NOTE: overlay is NOT skipped — a containerized agent's rootfs is
# overlayfs and filling its writable layer is exactly the disk-full
# signal mount monitoring exists for


class MountCollector:
    """Mount/filesystem inventory with freespace (the MOUNT_HDLR
    capability, ``common/gy_mount_disk.h:233``): /proc/self/mounts +
    statvfs per real filesystem; pseudo-filesystems are skipped the
    way the reference's fscategory filter does."""

    def __init__(self, host_id: int = 0, max_mounts: int = 256):
        self.host_id = host_id
        self.max_mounts = max_mounts

    def sample(self) -> tuple[np.ndarray, np.ndarray]:
        from gyeeta_tpu.utils import hashing as H

        rows, names = [], []
        seen = set()
        for line in _read("/proc/self/mounts").splitlines():
            p = line.split()
            if len(p) < 3:
                continue
            dev, mnt, fstype = p[0], p[1], p[2]
            base_fs = fstype.split(".", 1)[0]
            if fstype in _SKIP_FS or base_fs in _SKIP_FS:
                continue
            mnt = mnt.replace("\\040", " ")
            if mnt in seen or len(rows) >= self.max_mounts:
                continue
            seen.add(mnt)
            is_netfs = (base_fs in _NETWORK_FS or fstype in _NETWORK_FS)
            if is_netfs and not os.environ.get("GYT_STAT_NETFS"):
                # a hung NFS/CIFS server turns statvfs into an
                # UNINTERRUPTIBLE sleep that would freeze the agent's
                # whole event loop — inventory network mounts without
                # touching them (GYT_STAT_NETFS=1 opts in)
                size_mb = free_mb = 0.0
                st = None
            else:
                try:
                    st = os.statvfs(mnt)
                except OSError:
                    continue
                size_mb = st.f_blocks * st.f_frsize / (1 << 20)
                if size_mb <= 0:
                    continue
                free_mb = st.f_bavail * st.f_frsize / (1 << 20)
            r = np.zeros((), wire.MOUNT_DT)
            dir_id = InternTable.intern(mnt, wire.NAME_KIND_MISC)
            fs_id = InternTable.intern(fstype, wire.NAME_KIND_MISC)
            r["mnt_id"] = H.hash_bytes_np(
                f"{dev}:{mnt}".encode()) or 1
            r["dir_id"], r["fstype_id"] = dir_id, fs_id
            r["size_mb"] = size_mb
            r["free_mb"] = free_mb
            r["used_pct"] = (100.0 * (1.0 - free_mb / size_mb)
                             if size_mb else 0.0)
            tot_i = st.f_files if st is not None else 0
            r["inodes_used_pct"] = (
                100.0 * (tot_i - st.f_favail) / tot_i if tot_i else 0.0)
            r["is_network_fs"] = is_netfs
            r["host_id"] = self.host_id
            rows.append(r)
            names += [(wire.NAME_KIND_MISC, dir_id, mnt),
                      (wire.NAME_KIND_MISC, fs_id, fstype)]
        recs = (np.stack(rows) if rows
                else np.empty(0, wire.MOUNT_DT))
        return recs, InternTable.records(names) if names \
            else np.empty(0, wire.NAME_INTERN_DT)


# ------------------------------------------------------------ interfaces
class NetIfCollector:
    """Interface inventory + rate deltas (the NET_IF_HDLR capability,
    ``common/gy_netif.h:708``): /sys/class/net statistics swept on the
    agent cadence; loopback included (it carries real traffic in
    single-box deployments)."""

    def __init__(self, host_id: int = 0, max_ifs: int = 64):
        self.host_id = host_id
        self.max_ifs = max_ifs
        self._prev: dict[str, tuple] = {}
        self._t_prev = 0.0

    @staticmethod
    def _stat(ifname: str, stat: str) -> int:
        try:
            return int(_read(
                f"/sys/class/net/{ifname}/statistics/{stat}") or 0)
        except ValueError:
            return 0

    def sample(self) -> tuple[np.ndarray, np.ndarray]:
        now = time.monotonic()
        dt = max(now - self._t_prev, 1e-3) if self._t_prev else 0.0
        self._t_prev = now
        try:
            allifs = sorted(os.listdir("/sys/class/net"))
        except OSError:
            allifs = []
        # physical interfaces FIRST under the cap: a k8s node's 100+
        # veth/cali* names must never crowd out the real uplink
        phys = [i for i in allifs
                if os.path.exists(f"/sys/class/net/{i}/device")]
        rest = [i for i in allifs if i not in set(phys)]
        ifs = (phys + rest)[: self.max_ifs]
        rows, names = [], []
        from gyeeta_tpu.utils import hashing as H
        for ifname in ifs:
            cur = (self._stat(ifname, "rx_bytes"),
                   self._stat(ifname, "tx_bytes"),
                   self._stat(ifname, "rx_errors"),
                   self._stat(ifname, "tx_errors"))
            prev = self._prev.get(ifname)
            self._prev[ifname] = cur
            if prev is None or not dt:
                continue                  # need a delta baseline
            r = np.zeros((), wire.NETIF_DT)
            name_id = InternTable.intern(ifname, wire.NAME_KIND_MISC)
            r["if_id"] = H.hash_bytes_np(b"IF" + ifname.encode()) or 1
            r["name_id"] = name_id
            try:
                r["speed_mbps"] = float(
                    _read(f"/sys/class/net/{ifname}/speed") or -1)
            except ValueError:
                r["speed_mbps"] = -1.0
            r["rx_mb_sec"] = max(cur[0] - prev[0], 0) / dt / (1 << 20)
            r["tx_mb_sec"] = max(cur[1] - prev[1], 0) / dt / (1 << 20)
            r["rx_errs_sec"] = max(cur[2] - prev[2], 0) / dt
            r["tx_errs_sec"] = max(cur[3] - prev[3], 0) / dt
            oper = _read(f"/sys/class/net/{ifname}/operstate").strip()
            r["is_up"] = oper in ("up", "unknown")   # lo says unknown
            r["host_id"] = self.host_id
            rows.append(r)
            names.append((wire.NAME_KIND_MISC, name_id, ifname))
        # forget vanished interfaces
        for k in [k for k in self._prev if k not in set(ifs)]:
            del self._prev[k]
        recs = (np.stack(rows) if rows
                else np.empty(0, wire.NETIF_DT))
        return recs, InternTable.records(names) if names \
            else np.empty(0, wire.NAME_INTERN_DT)
