"""Network serving edge: the TCP boundary of the framework.

``server.GytServer`` — accepts agent event streams + query clients over
COMM_HEADER framing (the madhava L1 accept/recv role,
``server/gy_mconnhdlr.cc:2430``). ``agent.NetAgent`` — a partha-equivalent
client: registers, then streams simulator telemetry. ``agent.QueryClient``
— the Node-webserver-equivalent query peer.
"""

from gyeeta_tpu.net.agent import NetAgent, QueryClient  # noqa: F401


def __getattr__(name):
    # GytServer pulls in the Runtime (and with it jax); thin clients
    # importing this package must stay jax-free, so load it lazily.
    # The fabric gateway (jax-free by design) loads lazily too — most
    # importers of this package never run one.
    if name == "GytServer":
        from gyeeta_tpu.net.server import GytServer
        return GytServer
    if name == "FabricGateway":
        from gyeeta_tpu.net.gateway import FabricGateway
        return FabricGateway
    raise AttributeError(name)
