"""Streaming query subscriptions: register once, receive per-tick deltas.

The reference's NodeJS webserver LONG-POLLS madhava: every dashboard
re-asks its query every few seconds and the server re-renders it even
when nothing changed. This module inverts the edge on the same wire
format: a client registers a query ONCE and the serving tier watches
``snaptick`` advance — when a new engine view publishes, each DISTINCT
subscribed query is rendered once, diffed once against the previously
delivered version (``query/delta.py``), and the delta is pushed to
every subscriber of that query. Thousands of dashboards cost one
render + one diff per tick, not thousands of polls.

:class:`SubscriptionHub` is the shared server half — the SAME hub runs
inside :class:`~gyeeta_tpu.net.server.GytServer` (fetching from the
local snapshot tier) and inside the fan-in gateway
(``net/gateway.py``, fetching through the distributed edge cache), so
both edges push identical event streams. Subscriptions group by the
NORMALIZED request key (``query/normalize.py`` — the cache-key
function), which is what makes "thousands of dashboards, one render"
literal: every subscriber of a semantically-equal query lands in one
group.

Reconnect: a subscriber that held version T re-subscribes with
``last_snaptick=T``; if the hub still holds T in its short version
history it answers with a delta (or an ``ack`` when T is current),
otherwise a full resync — COUNTED (``gw_sub_resyncs``) and marked
in-band (``resync: true`` on the full event), never silent — and the
client never has to special-case it
(``query/delta.py:apply_event`` handles all three).

Fault-domain continuation (ISSUE 15): version rings are RETAINED
(bounded) when the last subscriber of a key disconnects, so a brief
client outage resumes with a delta instead of a resync; and with
``persist_path`` set the hub appends every pushed version to a small
append-only file and restores the rings on construction — a
RESTARTED gateway replays the missing deltas to clients that
reconnect with ``last_snaptick``, when its ring still covers them.

Client halves: :class:`SubscribeClient` speaks the GYT binary
``COMM_SUBSCRIBE_CMD`` stream (``events(stall_timeout=...)`` raises a
typed :class:`SubscriptionStalled` instead of hanging forever on a
frozen hub); :func:`read_sse_events` parses the REST
``/v1/subscribe`` SSE stream. Both yield the same event dicts.
:class:`SubscribeStream` supervises a subscription across gateway
failures: reconnect with ``last_snaptick`` on conn loss or stall,
rotating endpoints — at-least-once, no-gap: its reassembled
responses are byte-identical to an uninterrupted subscription's at
every common snaptick (property-tested).

Continuous queries (ISSUE 18): a subscription carrying ``cq: true``
plus a ``filter`` is a STANDING PREDICATE, not a panel view. The hub
canonicalizes the filter (``query/cq.py``), groups subscribers by
``(subsys, canonical-criteria)``, and per tick runs ONE predicate pass
per group over only the panel rows that CHANGED — computed from the
same row-keyed diff the panel subscriptions already pay for, never
from a second render. Subscribers receive first-class ``enter`` /
``change`` / ``leave`` membership events (``query/delta.py`` applies
them) and heartbeat acks on quiet ticks. Group membership is carried
incrementally across ticks, versioned through the SAME retained /
persisted rings as panel subscriptions — a reconnect (or a restarted
gateway) resumes with enter/leave deltas when the ring covers the
client's version, else a counted, ``resync``-marked full. N standing
filters over F distinct criteria cost F predicate passes and ≤1 panel
render per tick, shared with any plain full-panel subscriber.

Metrics (all through the hub's ``Stats`` registry — rendered as
``gyt_gw_*`` / ``gyt_cq_*`` by ``obs/prom.py``): ``gw_subscribers`` /
``gw_sub_keys`` gauges, ``gw_deltas_pushed`` / ``gw_resyncs`` /
``gw_sub_events`` / ``gw_sub_dropped`` counters, ``gw_delta_bytes`` /
``gw_full_bytes`` (the delta-vs-full wire ratio, QUERYLAT_r08), the
``gw_push`` stage hist (render+diff+deliver lag per key per tick);
and for the continuous-query tier ``cq_groups`` / ``cq_subscribers``
gauges plus ``cq_group_evals`` (THE amortization proof: one bump per
live group per tick, compare against ``cq_subscribers``),
``cq_panel_renders`` / ``cq_panel_render_shared``, ``cq_events|kind=``,
``cq_resyncs``, ``cq_fetch_errors`` / ``cq_eval_errors`` counters.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
from typing import Optional

from gyeeta_tpu.query import cq as CQ, delta as D
from gyeeta_tpu.query.normalize import normalize_request, request_key

log = logging.getLogger("gyeeta_tpu.net.subs")

# subscription-channel control fields stripped from the query envelope
# before normalization (they select HOW to deliver, not WHAT to render)
_SUB_FIELDS = ("last_snaptick", "subscribe")


def sub_history(env=None) -> int:
    env = os.environ if env is None else env
    try:
        return max(2, int(env.get("GYT_GW_SUB_HISTORY", "4")))
    except ValueError:
        return 4


def delta_max_ratio(env=None) -> float:
    """Delta-vs-full tradeoff knob: a delta that serializes to ≥ this
    fraction of the full body is replaced by a full resync (1.0 = only
    beat the full body; lower = prefer fulls sooner)."""
    env = os.environ if env is None else env
    try:
        return float(env.get("GYT_GW_DELTA_MAX_RATIO", "1.0"))
    except ValueError:
        return 1.0


def retain_keys(env=None) -> int:
    """Version rings RETAINED after the last subscriber of a key
    disconnects (the reconnect-continuation window), bounding hub
    memory when many distinct queries come and go."""
    env = os.environ if env is None else env
    try:
        return max(0, int(env.get("GYT_GW_SUB_RETAIN_KEYS", "1024")))
    except ValueError:
        return 1024


def persist_max_bytes(env=None) -> int:
    env = os.environ if env is None else env
    try:
        mb = float(env.get("GYT_GW_SUB_PERSIST_MAX_MB", "16"))
    except ValueError:
        mb = 16.0
    return max(1 << 20, int(mb * (1 << 20)))


class SubscribeError(ValueError):
    """Subscription rejected at registration (bad envelope / at
    capacity) — the edge answers its error frame and keeps the conn."""


class SubscriptionStalled(RuntimeError):
    """No event or keepalive arrived within the stall deadline — the
    hub (or the path to it) is wedged, not merely quiet: every tick
    delivers at least one event per subscription, so silence past
    ~3x the tick interval means the stream is dead even though the
    TCP conn looks alive. Typed so supervisors reconnect instead of
    treating it as a server rejection."""


class _Sub:
    __slots__ = ("sid", "key", "send", "last_tick", "conn_tag")

    def __init__(self, sid, key, send, last_tick, conn_tag):
        self.sid = sid
        self.key = key
        self.send = send
        self.last_tick = last_tick
        self.conn_tag = conn_tag


class _CQGroup:
    """One normalized standing filter + every subscriber asking it.
    Lives only while it has subscribers — its membership version ring
    outlives it (retained/persisted), so a re-subscribe rebuilds the
    group from the ring and resumes with deltas."""

    __slots__ = ("key", "m", "subs")

    def __init__(self, key, m):
        self.key = key
        self.m = m                      # CQ.Membership
        self.subs: dict = {}


class _CQPanel:
    """Per-subsystem panel state shared by every criteria group
    standing on it: the previous tick's row map, diffed ONCE per tick
    (the changed-rows set every group's predicate pass runs over)."""

    __slots__ = ("prev", "tick")

    def __init__(self, prev=None, tick=None):
        self.prev = prev
        self.tick = tick


class SubscriptionHub:
    """One per serving process. ``fetch`` is the tier's full-render
    function ``async (req) -> resp`` — the snapshot query path on a
    serve replica, the edge-cached query path on a gateway."""

    def __init__(self, fetch, stats, history: Optional[int] = None,
                 max_ratio: Optional[float] = None,
                 max_subs: int = 4096,
                 persist_path: Optional[str] = None,
                 retain: Optional[int] = None):
        self._fetch = fetch
        self.stats = stats
        self.history = sub_history() if history is None else int(history)
        self.max_ratio = delta_max_ratio() if max_ratio is None \
            else float(max_ratio)
        self.max_subs = int(max_subs)
        self.retain = retain_keys() if retain is None else int(retain)
        self._seq = 0
        self._subs: dict[int, _Sub] = {}
        self._by_key: dict[str, dict] = {}
        self._req_of_key: dict[str, dict] = {}
        # key -> deque[(snaptick, resp)] newest-last: the reconnect
        # window (how far back a delta can base) AND the diff source.
        # Rings OUTLIVE their subscribers (bounded by ``retain``) so a
        # reconnect resumes with a delta, and optionally persist to an
        # append-only file so a RESTART does too.
        self._versions: dict[str, collections.deque] = {}
        # continuous-query tier: criteria group per normalized
        # standing filter, panel diff state per subsystem
        self._cq_groups: dict[str, _CQGroup] = {}
        self._cq_panels: dict[str, _CQPanel] = {}
        self._persist_path = persist_path
        self._persist_f = None
        self._persist_max = persist_max_bytes()
        if persist_path:
            self._load_persist()

    # ------------------------------------------------- persistence
    def _load_persist(self) -> None:
        """Restore the version rings from the append-only file. A
        torn tail (SIGKILL mid-append) is truncated and counted —
        every complete line before it is usable."""
        import os as _os
        path = self._persist_path
        if _os.path.exists(path):
            with open(path, "rb") as f:
                for raw in f:
                    if not raw.endswith(b"\n"):
                        self.stats.bump("gw_sub_persist_torn")
                        break
                    try:
                        obj = json.loads(raw)
                        key = obj["k"]
                        st = obj["st"]
                        resp = obj["resp"]
                    except (ValueError, KeyError):
                        self.stats.bump("gw_sub_persist_torn")
                        continue
                    dq = self._versions.setdefault(
                        key, collections.deque(maxlen=self.history))
                    dq.append((st, resp))
                    if isinstance(obj.get("req"), dict):
                        self._req_of_key[key] = obj["req"]
        self._persist_f = open(path, "ab")
        keys = len(self._versions)
        if keys:
            self.stats.gauge("gw_sub_persist_restored_keys",
                             float(keys))
            log.info("subscription continuation: restored %d key "
                     "ring(s) from %s", keys, path)

    def _persist_append(self, key: str, resp: dict) -> None:
        f = self._persist_f
        if f is None:
            return
        line = json.dumps(
            {"k": key, "req": self._req_of_key.get(key),
             "st": resp.get("snaptick"), "resp": resp},
            default=str).encode() + b"\n"
        try:
            f.write(line)
            f.flush()
            self.stats.bump("gw_sub_persist_appends")
            if f.tell() > self._persist_max:
                self._persist_compact()
        except OSError:
            self.stats.bump("gw_sub_persist_errors")

    def _persist_compact(self) -> None:
        """Rewrite the file with only the LIVE rings (tmp + rename:
        a crash mid-compaction leaves the old complete file)."""
        import os as _os
        path = self._persist_path
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for key, dq in self._versions.items():
                req = self._req_of_key.get(key)
                for st, resp in dq:
                    f.write(json.dumps(
                        {"k": key, "req": req, "st": st,
                         "resp": resp}, default=str).encode() + b"\n")
            f.flush()
            _os.fsync(f.fileno())
        _os.replace(tmp, path)
        self._persist_f.close()
        self._persist_f = open(path, "ab")
        self.stats.bump("gw_sub_persist_compactions")

    def close(self) -> None:
        if self._persist_f is not None:
            try:
                self._persist_f.close()
            except OSError:         # pragma: no cover
                pass
            self._persist_f = None

    # ------------------------------------------------------------ gauges
    def _gauge(self) -> None:
        self.stats.gauge("gw_subscribers", float(len(self._subs)))
        self.stats.gauge("gw_sub_keys", float(len(self._by_key)))
        self.stats.gauge("cq_groups", float(len(self._cq_groups)))
        self.stats.gauge("cq_subscribers", float(
            sum(len(g.subs) for g in self._cq_groups.values())))

    @property
    def nsubs(self) -> int:
        return len(self._subs)

    # --------------------------------------------------------- lifecycle
    async def subscribe(self, req: dict, send, last_snaptick=None,
                        conn_tag=None) -> int:
        """Register one subscription; ``send`` is ``async (event) ->
        None``. The initial event (full / delta-from-last-seen / ack)
        is delivered before this returns. Raises
        :class:`SubscribeError` on a bad envelope or at capacity."""
        if len(self._subs) >= self.max_subs:
            self.stats.bump("gw_subs_rejected|reason=capacity")
            raise SubscribeError(
                f"subscription capacity {self.max_subs} reached")
        req = {k: v for k, v in req.items() if k not in _SUB_FIELDS}
        if any(k in req for k in ("op", "multiquery", "at", "window",
                                  "tstart", "tend")):
            self.stats.bump("gw_subs_rejected|reason=envelope")
            raise SubscribeError(
                "subscriptions carry live point-in-time queries only")
        if req.get("consistency") == "strong":
            self.stats.bump("gw_subs_rejected|reason=envelope")
            raise SubscribeError(
                "subscriptions serve the snapshot tier "
                "(consistency=strong cannot stream)")
        if req.get("cq"):
            return await self._subscribe_cq(req, send, last_snaptick,
                                            conn_tag)
        norm = normalize_request(req)
        key = request_key(norm)
        self._seq += 1
        sid = self._seq
        self._req_of_key[key] = dict(norm)
        # a ring with no LIVE subscribers (retained across
        # disconnects, or restored from the persist file after a
        # restart) is a delta BASE, not a current view: fetch fresh,
        # append it to the ring, and the reconnect below replays the
        # missing delta from the client's last-seen version
        cur = self._latest(key) if self._by_key.get(key) else None
        if cur is None:
            resp = await self._fetch(dict(norm))
            # another subscriber may have raced the fetch; keep the
            # newest version only once
            latest = self._latest(key)
            if latest is None or latest[0] != resp.get("snaptick"):
                self._push_version(key, resp)
            cur = self._latest(key)
        tick, resp = cur
        ev = None
        resync = False
        if last_snaptick is not None and last_snaptick == tick:
            ev = D.ack_event(tick)
        elif last_snaptick is not None:
            held = self._version_at(key, last_snaptick)
            if held is not None:
                ev, db, fb = D.compute_event(held, resp,
                                             self.max_ratio)
                self.stats.bump("gw_delta_bytes", db)
                self.stats.bump("gw_full_bytes", fb)
                self.stats.bump("gw_sub_resumes")
            else:
                # continuation gap: the ring no longer covers the
                # client's version — a full resync, COUNTED and
                # marked in-band, never silent
                self.stats.bump("gw_resyncs")
                self.stats.bump("gw_sub_resyncs")
                resync = True
        if ev is None and not (last_snaptick is not None
                               and last_snaptick == tick):
            ev = D.full_event(resp)
            if resync:
                ev = dict(ev)
                ev["resync"] = True
        sub = _Sub(sid, key, send, tick, conn_tag)
        self._subs[sid] = sub
        self._by_key.setdefault(key, {})[sid] = sub
        self._gauge()
        self.stats.bump("gw_subs_registered")
        try:
            await send(ev)
            self.stats.bump("gw_sub_events")
        except Exception:
            self.unsubscribe(sid)
            raise
        return sid

    # ------------------------------------------------ continuous queries
    async def _subscribe_cq(self, req: dict, send, last_snaptick,
                            conn_tag) -> int:
        """Register one STANDING FILTER (``cq: true``): validate +
        canonicalize the criteria, land the subscriber in its
        ``(subsys, canonical-filter)`` group, prime membership from
        the shared panel state (one render at most — none when the
        panel is already live), and deliver the initial event chain:
        full / enter+change+leave-from-last-seen / ack."""
        extra = set(req) - {"subsys", "filter", "cq"}
        if extra:
            self.stats.bump("gw_subs_rejected|reason=envelope")
            raise SubscribeError(
                f"a continuous query is subsys+filter only "
                f"(membership is a set): unexpected {sorted(extra)}")
        subsys = req.get("subsys")
        filt = req.get("filter")
        if not subsys or not filt:
            self.stats.bump("gw_subs_rejected|reason=envelope")
            raise SubscribeError(
                "a continuous query needs subsys and filter")
        try:
            canon, tree = CQ.parse_standing(subsys, filt)
        except ValueError as e:
            self.stats.bump("gw_subs_rejected|reason=filter")
            raise SubscribeError(str(e)) from e
        key = CQ.group_key(subsys, canon)
        self._req_of_key[key] = CQ.normalize_cq(subsys, canon)
        group = self._cq_groups.get(key)
        if group is None:
            m = CQ.Membership(subsys, canon, tree)
            # a retained / persist-restored membership version is the
            # resume BASE: restore it, then prime against the current
            # panel below so held clients get enter/leave deltas
            latest = self._latest(key)
            if latest is not None:
                m.members = CQ.members_of_response(latest[1])
                m.snaptick = latest[0]
            group = _CQGroup(key, m)
            await self._prime_cq(group)
            self._cq_groups[key] = group
        tick = group.m.snaptick
        evs = None
        if last_snaptick is not None and last_snaptick == tick:
            evs = [D.ack_event(tick)]
        elif last_snaptick is not None:
            held = self._version_at(key, last_snaptick)
            if held is not None:
                tmp = CQ.Membership(
                    subsys, canon, None, kf=group.m.kf,
                    members=CQ.members_of_response(held),
                    snaptick=last_snaptick)
                e, c, lv = CQ.rebuild(tmp, group.m.members, tick)
                evs = CQ.events_of(last_snaptick, tick, group.m.kf,
                                   e, c, lv)
                if not evs:
                    # same membership at a newer tick (changed, then
                    # changed back): an empty change advances the
                    # client's version without a resync
                    evs = [{"t": "change", "snaptick": tick,
                            "base": last_snaptick, "kf": group.m.kf,
                            "rows": {}}]
                self.stats.bump("gw_sub_resumes")
            else:
                self.stats.bump("gw_resyncs")
                self.stats.bump("gw_sub_resyncs")
                self.stats.bump("cq_resyncs")
                ev = dict(D.full_event(CQ.response_of(group.m)))
                ev["resync"] = True
                evs = [ev]
        if evs is None:
            evs = [D.full_event(CQ.response_of(group.m))]
        self._seq += 1
        sid = self._seq
        sub = _Sub(sid, key, send, tick, conn_tag)
        self._subs[sid] = sub
        group.subs[sid] = sub
        self._gauge()
        self.stats.bump("gw_subs_registered")
        try:
            for ev in evs:
                await send(ev)
                self.stats.bump("gw_sub_events")
        except Exception:
            self.unsubscribe(sid)
            raise
        return sid

    async def _prime_cq(self, group: _CQGroup) -> None:
        """Bring a new/retained/restored group's membership to the
        CURRENT panel tick. Reuses the live shared panel state when
        another group already keeps it hot (no render); otherwise one
        render, which then seeds the panel state every later group on
        this subsystem shares."""
        m = group.m
        panel = self._cq_panels.get(m.subsys)
        if panel is None or panel.prev is None:
            resp = await self._fetch(CQ.panel_request(m.subsys))
            rows = resp.get("recs") or []
            prev = {}
            for r in rows:
                prev[CQ.row_key(r, m.kf)] = r
            panel = _CQPanel(prev, resp.get("snaptick"))
            self._cq_panels[m.subsys] = panel
            self.stats.bump("cq_panel_renders")
        rows = list(panel.prev.values())
        mask = CQ.match_mask(m.tree, m.subsys, rows)
        new_members = {k: r for (k, r), hit
                       in zip(panel.prev.items(), mask) if hit}
        changed = CQ.rebuild(m, new_members, panel.tick)
        if m.snaptick is None:
            m.snaptick = panel.tick
        latest = self._latest(group.key)
        if latest is None or latest[0] != m.snaptick \
                or any(changed):
            self._push_version(group.key, CQ.response_of(m))

    def unsubscribe(self, sid: int) -> None:
        sub = self._subs.pop(sid, None)
        if sub is None:
            return
        grp = self._by_key.get(sub.key)
        if grp is not None:
            grp.pop(sid, None)
            if not grp:
                # last subscriber gone: the key stops costing a render
                # per tick, but its version ring is RETAINED (bounded
                # by ``retain``) so a reconnect with last_snaptick
                # resumes with a delta instead of a resync
                self._by_key.pop(sub.key, None)
                self._evict_retained()
        cg = self._cq_groups.get(sub.key)
        if cg is not None:
            cg.subs.pop(sid, None)
            if not cg.subs:
                # last standing subscriber gone: the group stops
                # costing a predicate pass; its membership version
                # ring is RETAINED like any subscription key, so a
                # re-subscribe rebuilds the group and resumes with
                # enter/leave deltas
                self._cq_groups.pop(sub.key, None)
                if not any(g.m.subsys == cg.m.subsys
                           for g in self._cq_groups.values()):
                    self._cq_panels.pop(cg.m.subsys, None)
                self._evict_retained()
        self._gauge()

    def _evict_retained(self) -> None:
        live = len(self._by_key) + len(self._cq_groups)
        over = len(self._versions) - live - self.retain
        if over <= 0:
            return
        for key in list(self._versions):
            if over <= 0:
                break
            if key in self._by_key or key in self._cq_groups:
                continue
            self._versions.pop(key, None)
            self._req_of_key.pop(key, None)
            self.stats.bump("gw_sub_retained_evicted")
            over -= 1

    def conn_subscribed(self, conn_tag) -> bool:
        return any(s.conn_tag == conn_tag
                   for s in self._subs.values())

    def unsubscribe_conn(self, conn_tag) -> int:
        sids = [s.sid for s in self._subs.values()
                if s.conn_tag == conn_tag]
        for sid in sids:
            self.unsubscribe(sid)
        return len(sids)

    # ---------------------------------------------------------- versions
    def _latest(self, key):
        dq = self._versions.get(key)
        return dq[-1] if dq else None

    def _version_at(self, key, tick):
        dq = self._versions.get(key) or ()
        for t, resp in dq:
            if t == tick:
                return resp
        return None

    def _push_version(self, key, resp) -> None:
        dq = self._versions.setdefault(
            key, collections.deque(maxlen=self.history))
        dq.append((resp.get("snaptick"), resp))
        self._persist_append(key, resp)

    # -------------------------------------------------------------- push
    async def push_tick(self) -> int:
        """``snaptick`` advanced: render each subscribed query once,
        diff once, deliver to every subscriber. Returns events sent.
        A failing subscriber (dead conn, send deadline) is dropped and
        counted — one wedged dashboard cannot stall the tier."""
        sent = 0
        fetched: dict = {}
        for key in list(self._by_key):
            grp = self._by_key.get(key)
            req = self._req_of_key.get(key)
            if not grp or req is None:
                continue
            with self.stats.timeit("gw_push"):
                try:
                    resp = await self._fetch(dict(req))
                except Exception as e:      # noqa: BLE001 — counted
                    # upstream shed/error: subscribers keep their last
                    # version; next tick retries
                    self.stats.bump("gw_sub_fetch_errors")
                    log.debug("subscription fetch failed for %s: %s",
                              req.get("subsys"), e)
                    continue
                fetched[key] = resp
                try:
                    sent += await self._push_key(key, grp, resp)
                except Exception as e:      # noqa: BLE001 — counted
                    # malformed response / diff failure: contain it to
                    # THIS key — the remaining subscriptions still get
                    # their tick, and the watcher must not mark the
                    # upstream down for it
                    self.stats.bump("gw_sub_push_errors")
                    log.debug("subscription push failed for %s: %s",
                              req.get("subsys"), e)
        if self._cq_groups:
            sent += await self._push_cq(fetched)
        return sent

    async def _push_cq(self, fetched: dict) -> int:
        """Advance every live criteria group: per subsystem, ONE panel
        render (reused from this tick's regular pushes when a plain
        subscriber already paid for it), ONE row-keyed diff, and per
        group ONE predicate pass over only the CHANGED rows — then
        enter/change/leave events (or heartbeat acks) to every
        subscriber."""
        sent = 0
        by_subsys: dict[str, list] = {}
        for g in self._cq_groups.values():
            if g.subs:
                by_subsys.setdefault(g.m.subsys, []).append(g)
        for subsys, groups in by_subsys.items():
            with self.stats.timeit("cq_push"):
                preq = CQ.panel_request(subsys)
                pkey = request_key(normalize_request(preq))
                resp = fetched.get(pkey)
                if resp is not None:
                    self.stats.bump("cq_panel_render_shared")
                else:
                    try:
                        resp = await self._fetch(preq)
                    except Exception as e:  # noqa: BLE001 — counted
                        # upstream shed/error: membership holds, next
                        # tick retries (subscribers see a quiet tick)
                        self.stats.bump("cq_fetch_errors")
                        log.debug("cq panel fetch failed for %s: %s",
                                  subsys, e)
                        continue
                    self.stats.bump("cq_panel_renders")
                try:
                    sent += await self._push_cq_panel(
                        subsys, groups, resp)
                except Exception as e:      # noqa: BLE001 — counted
                    self.stats.bump("gw_sub_push_errors")
                    log.debug("cq push failed for %s: %s", subsys, e)
        return sent

    async def _push_cq_panel(self, subsys, groups, resp) -> int:
        sent = 0
        tick = resp.get("snaptick")
        panel = self._cq_panels.get(subsys)
        if panel is not None and panel.tick == tick:
            return 0                    # no advance for this panel
        kf = groups[0].m.kf
        curr = {}
        for r in resp.get("recs") or []:
            curr[CQ.row_key(r, kf)] = r
        if panel is not None and panel.prev is not None:
            changed_keys, changed_rows, removed = \
                CQ.panel_diff(panel.prev, curr)
            full_pass = False
        else:                           # pragma: no cover — defensive
            changed_keys = list(curr.keys())
            changed_rows = list(curr.values())
            removed = []
            full_pass = True
        cols = CQ.columns_of_rows(subsys, changed_rows) \
            if changed_rows else {}
        for g in groups:
            # THE amortization contract: one bump per live group per
            # tick — gyt_cq_group_evals_total / ticks == n_groups, no
            # matter how many subscribers stand behind each group
            self.stats.bump("cq_group_evals")
            base = g.m.snaptick
            try:
                if changed_rows:
                    match = CQ.match_mask(g.m.tree, subsys,
                                          changed_rows, cols)
                else:
                    match = ()
                if full_pass:
                    new_members = {
                        k: r for k, r, hit
                        in zip(changed_keys, changed_rows, match)
                        if hit}
                    e, c, lv = CQ.rebuild(g.m, new_members, tick)
                else:
                    e, c, lv = CQ.advance(g.m, changed_keys,
                                          changed_rows, match,
                                          removed, tick)
            except Exception as ex:     # noqa: BLE001 — counted
                # a row the predicate cannot evaluate (projected
                # response, bad field): contain to THIS group
                self.stats.bump("cq_eval_errors")
                log.debug("cq eval failed for %s: %s", g.m.filt, ex)
                continue
            evs = CQ.events_of(base, g.m.snaptick, g.m.kf, e, c, lv)
            for ev in evs:
                self.stats.bump(f"cq_events|kind={ev['t']}")
            if evs:
                self._push_version(g.key, CQ.response_of(g.m))
            full_ev = None
            for sub in list(g.subs.values()):
                if evs and sub.last_tick == base:
                    out = evs
                elif not evs and sub.last_tick == g.m.snaptick:
                    # quiet tick: heartbeat so stall detection holds
                    # (every tick delivers ≥1 event per subscription)
                    out = [D.ack_event(g.m.snaptick)]
                else:
                    # late joiner / missed a tick: full resync
                    if full_ev is None:
                        full_ev = D.full_event(CQ.response_of(g.m))
                        self.stats.bump("gw_resyncs")
                        self.stats.bump("cq_resyncs")
                    out = [full_ev]
                try:
                    for ev in out:
                        await sub.send(ev)
                        self.stats.bump("gw_sub_events")
                    sub.last_tick = g.m.snaptick
                    sent += 1
                except Exception:       # noqa: BLE001 — dead conn
                    self.stats.bump("gw_sub_dropped")
                    self.unsubscribe(sub.sid)
        self._cq_panels[subsys] = _CQPanel(curr, tick)
        return sent

    async def _push_key(self, key, grp, resp) -> int:
        """Diff + deliver one subscribed query's new version. Raises
        on malformed responses — push_tick contains that per key."""
        sent = 0
        prev = self._latest(key)
        tick = resp.get("snaptick")
        if prev is not None and prev[0] == tick:
            return 0                     # no advance for this key
        ev = None
        if prev is not None:
            ev, db, fb = D.compute_event(prev[1], resp,
                                         self.max_ratio)
            self.stats.bump("gw_delta_bytes", db)
            self.stats.bump("gw_full_bytes", fb)
            if ev["t"] == "delta":
                self.stats.bump("gw_deltas_pushed")
            else:
                self.stats.bump("gw_resyncs")
        full_ev = None
        for sub in list(grp.values()):
            if prev is not None and sub.last_tick == prev[0] \
                    and ev is not None:
                out = ev
            elif sub.last_tick == tick:
                continue
            else:
                # late joiner / missed a tick: full resync
                if full_ev is None:
                    full_ev = D.full_event(resp)
                    self.stats.bump("gw_resyncs")
                out = full_ev
            try:
                await sub.send(out)
                sub.last_tick = tick
                sent += 1
                self.stats.bump("gw_sub_events")
            except Exception:           # noqa: BLE001 — dead conn
                self.stats.bump("gw_sub_dropped")
                self.unsubscribe(sub.sid)
        self._push_version(key, resp)
        return sent


# ===================================================================
# client halves
# ===================================================================

class SubscribeClient:
    """GYT binary subscription conn: registers as a query client, sends
    ONE ``COMM_SUBSCRIBE_CMD`` and then iterates the pushed event
    stream. One subscription per conn (the stream owns the read side;
    multiplexing poll queries over it would race the pushes)."""

    def __init__(self, machine_id: Optional[int] = None):
        from gyeeta_tpu.utils import hashing as H
        self.machine_id = machine_id if machine_id is not None \
            else H.hash_bytes_np(b"subscribe-client")
        self._reader = None
        self._writer = None
        self._seq = 0

    async def connect(self, host: str, port: int,
                      timeout: float = 10.0) -> None:
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.net.agent import register
        reader, writer, status, _ = await asyncio.wait_for(
            register(host, port, self.machine_id, wire.CONN_QUERY),
            timeout)
        if status != wire.REG_OK:
            writer.close()
            raise ConnectionRefusedError(f"registration status {status}")
        self._reader, self._writer = reader, writer

    async def subscribe(self, req: dict,
                        last_snaptick=None) -> None:
        from gyeeta_tpu.ingest import wire
        self._seq += 1
        body = dict(req)
        if last_snaptick is not None:
            body["last_snaptick"] = last_snaptick
        payload = json.dumps(body).encode()
        import numpy as np
        h = np.zeros((), wire.QUERY_HDR_DT)
        h["seqid"] = np.uint64(self._seq)
        h["nbytes"] = len(payload)
        self._writer.write(wire._frame(          # noqa: SLF001
            wire.COMM_SUBSCRIBE_CMD, h.tobytes() + payload,
            wire.MAGIC_NQ))
        await self._writer.drain()

    async def events(self, stall_timeout: Optional[float] = None):
        """Async-iterate pushed event dicts until the conn closes.
        A QS_ERROR frame raises RuntimeError with the server's error.
        ``stall_timeout`` (seconds; callers use ~3x the tick
        interval) raises a typed :class:`SubscriptionStalled` when no
        event arrives in time — every tick delivers at least one
        event per subscription, so prolonged silence means the hub or
        the path to it is WEDGED even though the conn looks alive;
        without it a frozen hub would park the consumer forever."""
        from gyeeta_tpu.ingest import wire
        while True:
            try:
                if stall_timeout is not None and stall_timeout > 0:
                    dtype, payload = await asyncio.wait_for(
                        wire.read_frame(self._reader), stall_timeout)
                else:
                    dtype, payload = await wire.read_frame(
                        self._reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            except (asyncio.TimeoutError, TimeoutError):
                raise SubscriptionStalled(
                    f"no subscription event within "
                    f"{stall_timeout:.1f}s (hub frozen or path "
                    f"wedged)") from None
            if dtype != wire.COMM_QUERY_RESP:
                raise wire.FrameError(
                    f"expected QUERY_RESP on subscription, got {dtype}")
            _seqid, status, body = wire.decode_query_chunk(payload)
            obj = json.loads(body or b"null")
            if status == wire.QS_ERROR:
                raise RuntimeError(
                    (obj or {}).get("error", "subscription error"))
            yield obj

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None


class SubscribeStream:
    """Supervised subscription with the agent spool's at-least-once /
    no-gap contract across GATEWAY failures: connect to the first
    reachable endpoint, subscribe, apply events; on conn loss, stall
    (:class:`SubscriptionStalled`) or rejection, reconnect with
    ``last_snaptick`` — rotating endpoints with jittered backoff, so
    a killed gateway hands its subscribers to a peer (or its own
    restart). A continuation gap arrives as a counted full resync
    (``counters["resyncs"]``), never silently: the yielded responses
    are byte-identical to an uninterrupted subscription's at every
    common snaptick (property-tested in ``tests/test_failover.py``).
    """

    def __init__(self, endpoints, req: dict,
                 stall_timeout: Optional[float] = None,
                 backoff_base: float = 0.25, backoff_cap: float = 5.0,
                 machine_id: Optional[int] = None):
        if not endpoints:
            raise ValueError("SubscribeStream needs >=1 endpoint")
        self.endpoints = [tuple(e) for e in endpoints]
        self.req = dict(req)
        self.stall_timeout = stall_timeout
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.machine_id = machine_id
        self.held: Optional[dict] = None
        self.last_snaptick = None
        self.counters: collections.Counter = collections.Counter()
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    async def responses(self):
        """Async-iterate reassembled FULL responses, one per applied
        event, forever (until :meth:`stop`). Reconnects internally;
        consumers only ever see complete views."""
        import random as _r
        ep = 0
        backoff = self.backoff_base
        while not self._stop:
            sc = SubscribeClient(machine_id=self.machine_id)
            host, port = self.endpoints[ep % len(self.endpoints)]
            try:
                await sc.connect(host, port)
                await sc.subscribe(self.req,
                                   last_snaptick=self.last_snaptick)
                backoff = self.backoff_base
                async for ev in sc.events(
                        stall_timeout=self.stall_timeout):
                    self.counters["events"] += 1
                    # what actually crossed the wire: the EVENT (delta
                    # or full), not the reassembled view — consumers
                    # (the hub-mode gateway's inter-region relay) use
                    # this to prove bytes ∝ delta churn, not panel size
                    self.counters["event_bytes"] += len(
                        json.dumps(ev, separators=(",", ":")))
                    if ev.get("t") == "full" and ev.get("resync"):
                        self.counters["resyncs"] += 1
                    try:
                        held = D.apply_event(self.held, ev)
                    except D.ResyncRequired:
                        # base gap mid-stream (hub ring aged out):
                        # drop the held version and force a full on
                        # the re-subscribe — counted, never silent
                        self.counters["forced_resyncs"] += 1
                        self.held = None
                        self.last_snaptick = None
                        break
                    if ev.get("t") == "ack":
                        continue            # nothing new to yield
                    self.held = held
                    self.last_snaptick = held.get("snaptick")
                    yield held
                self.counters["conn_lost"] += 1
            except SubscriptionStalled:
                self.counters["stalls"] += 1
            except (ConnectionError, OSError, RuntimeError,
                    asyncio.IncompleteReadError):
                self.counters["conn_errors"] += 1
            finally:
                await sc.close()
            if self._stop:
                return
            ep += 1                         # rotate endpoints
            self.counters["reconnects"] += 1
            await asyncio.sleep(backoff * (0.5 + _r.random()))
            backoff = min(backoff * 2.0, self.backoff_cap)


async def read_sse_events(reader):
    """Parse an SSE byte stream → async iterator of event dicts (the
    ``data:`` JSON payloads; comments and event/id lines skipped —
    the event type rides inside the JSON as ``t``)."""
    buf = b""
    while True:
        chunk = await reader.read(65536)
        if not chunk:
            return
        buf += chunk
        while b"\n\n" in buf:
            block, buf = buf.split(b"\n\n", 1)
            data_lines = [ln[5:].strip() for ln in block.split(b"\n")
                          if ln.startswith(b"data:")]
            if data_lines:
                yield json.loads(b"\n".join(data_lines))
