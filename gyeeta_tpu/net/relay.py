"""Remote ingest relay: the shm-ring contract promoted to TCP.

``utils/shmring.py`` carries an exact cross-PROCESS ledger — every
record a worker publishes is either consumed by the fold or counted as
a drop, anchored by the per-shard cumulative record chain in each slot
header. This module carries the SAME contract across MACHINES (the
madhava→shyama hop of the source paper's two-level topology; the sPIN
near-wire shape of PAPERS.md): a :class:`RelayWorker` runs the full
ingest edge — accept, registration (forwarded to the supervisor so
hostmap allocation stays global), wire validation, native
deframe/decode, WAL append, shard split — on a REMOTE host and ships
decoded columnar batches to the supervisor as commit-then-head framed
messages over one TCP uplink:

- Every ``T_BATCH`` frame carries ``(shard, nrec, seq, cum)`` where
  ``cum`` is the relay's cumulative published-record count for that
  shard — the TCP analogue of the slot header's ``cum_records``
  anchor. The consumer's gap math is byte-for-byte the ring drain's:
  ``gap = (cum - nrec) - accounted`` counts EXACTLY the records lost
  to relay spool overflow, a connection death mid-frame, or a relay
  process restart. ``published == consumed + counted drops`` holds
  across the wire, across reconnects, and across relay respawns.
- The relay's bounded send spool is drop-OLDEST (the ring's overwrite
  policy): a WAN stall sheds the oldest batches counted, never blocks
  the socket edge, and never grows without bound. ``cum`` advances at
  publish time — before the spool — so shed batches surface as counted
  gaps at the consumer, not silence.
- Epochs mirror the worker monitor: each relay process run carries a
  fresh instance token in its HELLO. A new token finalizes the
  previous epoch — any records published-but-never-consumed are
  counted dropped right then (``hw - accounted`` per shard), exactly
  like the supervisor draining a dead worker's rings. A reconnect
  with the SAME token is a continuation: the retained spool resumes
  and nothing is double-counted (frames leave the spool only once
  fully written, so at-most-once delivery + exact counted loss).
- Heartbeats (0.2s) carry the relay's counter block and per-shard
  ``cum`` high-water marks, so the supervisor's ledger includes
  records that died in a lost spool and its monitor rows
  (``gyt_relay_up``, ``gyt_relay_heartbeat_age_seconds``,
  ``gyt_relay_epoch``, ``gyt_relay_pid``) mirror the local
  ``gyt_ingest_proc_*`` supervision surface.
- WAL ownership moves WITH the edge: ``--journal-dir`` makes the
  relay journal validated chunks on ITS host (the remote worker owns
  its shard WALs, same as the local mproc split). The supervisor
  never journals relay-fed records — re-journaling a decoded batch
  would double-count on replay.

The supervisor side (:class:`RelayHub`) is ~200 lines riding the
existing machinery: batches unpack through ``shmring.unpack_sections``
into ``Runtime.ingest_records`` (the staging path the local ring drain
uses), registration RPCs land on the server's sticky
machine-id→host_id allocator, and all accounting renders as the
``gyt_relay_*`` metric families.
"""

from __future__ import annotations

import json
import logging
import os
import selectors
import socket
import struct
import time
from collections import deque
from typing import Optional

from gyeeta_tpu.net.ingestproc import IngestWorker, _Conn, _ShmStats

log = logging.getLogger("gyeeta_tpu.net.relay")

# ---------------------------------------------------------------- frames
# [magic u32 | type u16 | flags u16 | body_len u32] + body
RELAY_MAGIC = 0x47595452                  # "RTYG" on the wire
_FH = struct.Struct("<IHHI")
# batch body prefix: shard, nrec, seq, cum (then packed record sections)
_BH = struct.Struct("<IIQQ")
MAX_BODY = 16 * 1024 * 1024               # same cap as the wire tier

T_HELLO = 1        # relay → hub   JSON {relay_id, token, pid, nshards?}
T_HELLO_OK = 2     # hub → relay   JSON {ok, nshards, tick} | {error}
T_HB = 3           # relay → hub   JSON {hb, counters, cum}
T_BATCH = 4        # relay → hub   _BH + pack_sections payload
T_RPC = 5          # relay → hub   JSON {rid, op, ...}
T_RPC_RESP = 6     # hub → relay   JSON {rid, ...}
T_TICK = 7         # hub → relay   JSON {tick}

# relay-side counters beyond the shmring set, reported via heartbeat
# and folded into gyt_relay_proc_* rows by the hub (delta-folded, so
# respawn resets stay correct)
_EXTRA_COUNTERS = ("spool_dropped_batches", "spool_dropped_records",
                   "reg_refused", "uplink_reconnects")
_FOLD_COUNTERS = ("accepted_records", "accepted_chunks",
                  "accepted_bytes", "published_records", "frames_bad",
                  "unknown_records", "wal_appended_chunks",
                  "wal_backlog_dropped", "spool_dropped_batches",
                  "spool_dropped_records", "reg_refused",
                  "uplink_reconnects")


def frame(ftype: int, body: bytes) -> bytes:
    if len(body) >= MAX_BODY:
        raise ValueError(f"relay frame body {len(body)}B over cap")
    return _FH.pack(RELAY_MAGIC, ftype, 0, len(body)) + body


def jframe(ftype: int, obj: dict) -> bytes:
    return frame(ftype, json.dumps(obj).encode())


def batch_spool_max(env=None) -> int:
    env = os.environ if env is None else env
    return max(1 << 20, int(env.get("GYT_RELAY_SPOOL_MB", "8")) << 20)


def batch_payload_bytes(env=None) -> int:
    env = os.environ if env is None else env
    return max(4096, int(env.get("GYT_RELAY_BATCH_KB", "128")) * 1024)


def hb_interval_s(env=None) -> float:
    env = os.environ if env is None else env
    return max(0.05, float(env.get("GYT_RELAY_HB_S", "0.2")))


def hb_stale_s(env=None) -> float:
    env = os.environ if env is None else env
    return max(0.5, float(env.get("GYT_RELAY_HB_STALE_S", "5.0")))


# ======================================================================
# Relay-side publisher: the WorkerShm duck type
# ======================================================================

class RelayPublisher:
    """Duck-types the ``WorkerShm`` producer surface the IngestWorker
    machinery publishes through, backed by a bounded drop-oldest frame
    spool instead of shared-memory rings. ``cum`` advances at publish
    time — BEFORE spool admission — so a shed batch is a counted gap
    at the consumer, exactly like a ring overwrite."""

    def __init__(self, slot_payload: int, spool_max: int):
        from gyeeta_tpu.utils import shmring
        self.slot_payload = int(slot_payload)
        self.spool_max = int(spool_max)
        self.spool: deque = deque()        # whole T_BATCH frames
        self.spool_bytes = 0
        self._cum: dict[int, int] = {}
        self._seq: dict[int, int] = {}
        self._counters = {n: 0 for n in shmring.COUNTER_NAMES}
        for n in _EXTRA_COUNTERS:
            self._counters[n] = 0
        self._counters["pid"] = os.getpid()

    # --- counter surface (same names/semantics as the ring header) ---
    def counter(self, name: str) -> int:
        return self._counters.get(name, 0)

    def set_counter(self, name: str, value: int) -> None:
        self._counters[name] = int(value)

    def add_counter(self, name: str, n: int = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + int(n)

    def counters(self) -> dict:
        d = dict(self._counters)
        d["spool_bytes"] = self.spool_bytes
        return d

    def heartbeat(self) -> None:
        self.add_counter("hb_seq")
        self.set_counter("hb_time_us", int(time.time() * 1e6))

    def bump_epoch(self) -> int:
        return 0                           # epochs ride the HELLO token

    def heads(self) -> list:
        n = (max(self._cum) + 1) if self._cum else 0
        return [self._cum.get(s, 0) for s in range(n)]

    def cum(self) -> dict:
        return dict(self._cum)

    def close(self) -> None:
        pass

    # ----------------------------------------------------------- publish
    def publish(self, shard: int, payload: bytes, nrec: int) -> None:
        if len(payload) > self.slot_payload:
            raise ValueError(
                f"payload {len(payload)}B > batch {self.slot_payload}B")
        shard = int(shard)
        seq = self._seq.get(shard, 0) + 1
        cum = self._cum.get(shard, 0) + int(nrec)
        self._seq[shard] = seq
        self._cum[shard] = cum
        self.add_counter("published_records", nrec)
        self.add_counter("published_slots")
        f = frame(T_BATCH, _BH.pack(shard, int(nrec), seq, cum)
                  + payload)
        self.spool.append(f)
        self.spool_bytes += len(f)
        while self.spool_bytes > self.spool_max and len(self.spool) > 1:
            old = self.spool.popleft()
            self.spool_bytes -= len(old)
            _s, onrec, _q, _c = _BH.unpack_from(old, _FH.size)
            self.add_counter("spool_dropped_batches")
            self.add_counter("spool_dropped_records", onrec)


# ======================================================================
# Relay worker process (remote host)
# ======================================================================

class _PendingConn:
    """An accepted agent conn before its registration round trip."""

    __slots__ = ("sock", "fd", "buf", "leftover", "t0", "rid")

    def __init__(self, sock):
        self.sock = sock
        self.fd = sock.fileno()
        self.buf = b""
        self.leftover = b""
        self.t0 = time.time()
        self.rid = 0


_L_LISTEN = "listen"
_L_UPLINK = "uplink"


class RelayWorker(IngestWorker):
    """The ingest edge on a remote host. Reuses the IngestWorker's
    validated byte path (``_on_bytes`` → ``_ingest_chunk`` → staged
    ``_flush_shard``) verbatim, with :class:`RelayPublisher` standing
    in for the shared-memory rings and a supervised TCP uplink in
    place of the ctrl socket. Single-threaded selector loop; the only
    other threads are WAL writer threads (``--journal-dir``)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.relay_id = str(cfg.get("relay_id") or
                            f"relay-{socket.gethostname()}")
        # fresh instance token per process run = the epoch boundary
        import uuid
        self.token = uuid.uuid4().hex[:16]
        self.w = 0
        self.nshards = int(cfg.get("nshards") or 1)
        self._nshards_known = bool(cfg.get("nshards"))
        self.shards = list(range(self.nshards))
        self.idle_timeout = float(cfg.get("idle_timeout") or 0)
        self.shm = RelayPublisher(batch_payload_bytes(),
                                  batch_spool_max())
        self._stage = {}
        self._stage_bytes = {}
        self._stage_t0 = {}
        self._stage_max_age = float(
            os.environ.get("GYT_INGEST_STAGE_MS", "15")) / 1e3
        self.sel = selectors.DefaultSelector()
        self.conns: dict[int, _Conn] = {}
        self.tick = 0
        self.running = True
        self._stop_reason: Optional[str] = None
        self.journals: dict = {}
        self._jdir = cfg.get("journal_dir")
        self._jkw = cfg.get("journal_kw") or {}
        self._wal_fmt = cfg.get("wal_subdir_fmt", "shard_{:02d}")
        # agent listener
        self._listener = socket.create_server(
            (cfg.get("listen_host", "127.0.0.1"),
             int(cfg.get("listen_port", 0))), backlog=128)
        self._listener.setblocking(False)
        self.listen_addr = self._listener.getsockname()[:2]
        self.sel.register(self._listener, selectors.EVENT_READ,
                          _L_LISTEN)
        # supervisor uplink
        self.sup_host, self.sup_port = cfg["supervisor"]
        self._up_sock: Optional[socket.socket] = None
        self._up_state = "down"            # down | connecting | up
        self._up_ready = False             # HELLO_OK received
        self._up_rx = b""
        self._up_partial: Optional[bytes] = None
        self._up_off = 0
        self._up_events = 0
        self._up_next_t = 0.0
        self._up_backoff = 0.0
        self._ctrlq: deque = deque()       # HELLO/RPC/HB — never shed
        self._pending_regs: dict[int, _PendingConn] = {}
        self._pending_by_fd: dict[int, _PendingConn] = {}
        self._reg_rid = 0
        self._conn_seq = 0
        self._hb_s = hb_interval_s()
        self._reg_timeout = float(
            os.environ.get("GYT_RELAY_REG_TIMEOUT_S", "10"))

    # -------------------------------------------------- supervisor-free
    def _notify(self, ev: str, **kw) -> None:
        # conn lifecycle events stay local: the hub supervises via
        # heartbeats, not per-conn ctrl messages
        pass

    def _make_journals(self) -> None:
        if not self._jdir or self.journals:
            return
        from gyeeta_tpu.utils.journal import Journal
        for s in range(self.nshards):
            sub = self._jdir if self.nshards == 1 \
                else os.path.join(self._jdir, self._wal_fmt.format(s))
            self.journals[s] = Journal(sub, stats=_ShmStats(self.shm),
                                       **self._jkw)

    # ------------------------------------------------------------ uplink
    def _up_drop(self, why: str) -> None:
        if self._up_sock is not None:
            try:
                self.sel.unregister(self._up_sock)
            except (KeyError, ValueError):
                pass
            try:
                self._up_sock.close()
            except OSError:
                pass
        if self._up_state != "down":
            self.shm.add_counter("uplink_reconnects")
        self._up_sock = None
        self._up_state = "down"
        self._up_ready = False
        self._up_rx = b""
        # a half-written frame died with the conn: the consumer counts
        # it as a cum gap — exactly a ring overwrite's fate
        self._up_partial = None
        self._up_off = 0
        self._up_backoff = min(2.0, max(0.2, self._up_backoff * 2))
        self._up_next_t = time.monotonic() + self._up_backoff
        # registrations in flight can never complete: refuse them so
        # the agents retry against the respawned uplink
        for p in list(self._pending_regs.values()):
            self._drop_pending(p, "uplink_down")
        log.info("relay %s: uplink down (%s)", self.relay_id, why)

    def _up_connect(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            s.connect((self.sup_host, self.sup_port))
        except BlockingIOError:
            pass
        except OSError:
            s.close()
            self._up_backoff = min(2.0, max(0.2, self._up_backoff * 2))
            self._up_next_t = time.monotonic() + self._up_backoff
            return
        self._up_sock = s
        self._up_state = "connecting"
        self._up_events = selectors.EVENT_READ | selectors.EVENT_WRITE
        self.sel.register(s, self._up_events, _L_UPLINK)

    def _up_established(self) -> None:
        err = self._up_sock.getsockopt(socket.SOL_SOCKET,
                                       socket.SO_ERROR)
        if err:
            self._up_drop(f"connect_error_{err}")
            return
        self._up_state = "up"
        self._up_backoff = 0.0
        hello = {"relay_id": self.relay_id, "token": self.token,
                 "pid": os.getpid(), "wire": 1}
        if self._nshards_known:
            hello["nshards"] = self.nshards
        self._ctrlq.appendleft(jframe(T_HELLO, hello))

    def _up_want_write(self) -> bool:
        return bool(self._ctrlq or self.shm.spool
                    or self._up_partial is not None)

    def _up_update_events(self) -> None:
        if self._up_sock is None or self._up_state == "connecting":
            return
        ev = selectors.EVENT_READ
        if self._up_want_write():
            ev |= selectors.EVENT_WRITE
        if ev != self._up_events:
            self._up_events = ev
            try:
                self.sel.modify(self._up_sock, ev, _L_UPLINK)
            except (KeyError, ValueError):   # pragma: no cover
                pass

    def _up_flush(self) -> None:
        if self._up_state != "up" or self._up_sock is None:
            return
        while True:
            if self._up_partial is None:
                if self._ctrlq:
                    self._up_partial = self._ctrlq.popleft()
                elif self.shm.spool:
                    f = self.shm.spool.popleft()
                    self.shm.spool_bytes -= len(f)
                    self._up_partial = f
                else:
                    break
                self._up_off = 0
            try:
                n = self._up_sock.send(self._up_partial[self._up_off:])
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._up_drop("send_error")
                return
            if n <= 0:                     # pragma: no cover
                break
            self._up_off += n
            if self._up_off >= len(self._up_partial):
                self._up_partial = None
                self._up_off = 0

    def _up_readable(self) -> None:
        try:
            data = self._up_sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._up_drop("recv_error")
            return
        if not data:
            self._up_drop("eof")
            return
        self._up_rx += data
        while len(self._up_rx) >= _FH.size:
            magic, ftype, _fl, blen = _FH.unpack_from(self._up_rx, 0)
            if magic != RELAY_MAGIC or blen >= MAX_BODY:
                self._up_drop("bad_frame")
                return
            if len(self._up_rx) < _FH.size + blen:
                break
            body = self._up_rx[_FH.size:_FH.size + blen]
            self._up_rx = self._up_rx[_FH.size + blen:]
            try:
                self._up_dispatch(ftype, body)
            except Exception:              # pragma: no cover
                log.exception("relay uplink dispatch failed")

    def _up_dispatch(self, ftype: int, body: bytes) -> None:
        if ftype == T_HELLO_OK:
            msg = json.loads(body)
            if not msg.get("ok"):
                log.error("relay %s rejected by supervisor: %s",
                          self.relay_id, msg.get("error"))
                self.running = False
                self._stop_reason = "hello_rejected"
                return
            n = int(msg.get("nshards", 1))
            if self._nshards_known and n != self.nshards:
                log.error("relay %s: nshards drift %d -> %d; exiting",
                          self.relay_id, self.nshards, n)
                self.running = False
                self._stop_reason = "nshards_drift"
                return
            self.nshards = n
            self._nshards_known = True
            self.shards = list(range(n))
            self.tick = int(msg.get("tick", self.tick))
            self._make_journals()
            self._up_ready = True
        elif ftype == T_RPC_RESP:
            msg = json.loads(body)
            self._on_reg_resp(msg)
        elif ftype == T_TICK:
            self.tick = int(json.loads(body).get("tick", self.tick))

    # ------------------------------------------------------ registration
    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:                # pragma: no cover
                return
            if not self._up_ready:
                # no uplink, no hostmap: refuse now, the agent's
                # supervised reconnect retries after the uplink heals
                self.shm.add_counter("reg_refused")
                sock.close()
                continue
            sock.setblocking(False)
            p = _PendingConn(sock)
            self._pending_by_fd[p.fd] = p
            self.sel.register(sock, selectors.EVENT_READ, p)

    def _drop_pending(self, p: _PendingConn, _why: str) -> None:
        try:
            self.sel.unregister(p.sock)
        except (KeyError, ValueError):
            pass
        try:
            p.sock.close()
        except OSError:
            pass
        self._pending_by_fd.pop(p.fd, None)
        if p.rid:
            self._pending_regs.pop(p.rid, None)
        self.shm.add_counter("reg_refused")

    def _on_reg_readable(self, p: _PendingConn) -> None:
        try:
            data = p.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop_pending(p, "error")
            return
        if not data:
            self._drop_pending(p, "eof")
            return
        p.buf += data
        self._try_register(p)

    def _try_register(self, p: _PendingConn) -> None:
        from gyeeta_tpu.ingest import wire
        import numpy as np
        hsz = wire.HEADER_DT.itemsize
        if len(p.buf) < hsz:
            return
        magic, total = struct.unpack_from("<II", p.buf, 0)
        if magic != wire.MAGIC_PM or total < hsz \
                or total >= wire.MAX_COMM_DATA_SZ:
            self.shm.add_counter("frames_bad")
            self._drop_pending(p, "bad_magic")
            return
        if len(p.buf) < total:
            return
        dtype = int.from_bytes(p.buf[8:12], "little")
        if dtype != wire.COMM_REGISTER_REQ \
                or total < hsz + wire.REGISTER_REQ_DT.itemsize:
            self.shm.add_counter("frames_bad")
            self._drop_pending(p, "no_register")
            return
        if p.rid:                          # already in flight
            return
        req = np.frombuffer(p.buf, wire.REGISTER_REQ_DT, count=1,
                            offset=hsz)[0]
        p.leftover = bytes(p.buf[total:])
        p.buf = b""
        self._reg_rid += 1
        p.rid = self._reg_rid
        self._pending_regs[p.rid] = p
        mid = (int(req["machine_id_hi"]) << 64) \
            | int(req["machine_id_lo"])
        self._ctrlq.append(jframe(T_RPC, {
            "rid": p.rid, "op": "register", "mid": mid,
            "conn_type": int(req["conn_type"]),
            "wire_version": int(req["wire_version"]),
            "hostname_id": int(req["hostname_id"])}))

    def _on_reg_resp(self, msg: dict) -> None:
        from gyeeta_tpu import version
        from gyeeta_tpu.ingest import wire
        p = self._pending_regs.pop(int(msg.get("rid", 0)), None)
        if p is None:
            return
        self._pending_by_fd.pop(p.fd, None)
        status = int(msg.get("status", wire.REG_ERR_CAPACITY))
        hid = int(msg.get("hid", 0))
        resp = wire.encode_register_resp(
            status, hid, version.CURR_WIRE_VERSION,
            int(msg.get("last_seq", 0)))
        try:
            p.sock.sendall(resp)
        except OSError:
            self._drop_pending(p, "resp_error")
            return
        event = (status == wire.REG_OK and hid != 0xFFFFFFFF
                 and int(msg.get("conn_type", wire.CONN_EVENT))
                 == wire.CONN_EVENT)
        if not event:
            # the relay is an EVENT-only edge: query conns belong on
            # the serving tier / gateway, not the ingest relay
            self._drop_pending(p, "refused")
            return
        self._conn_seq += 1
        c = _Conn(p.sock, hid, self._conn_seq, hid % self.nshards)
        self.conns[c.fd] = c
        try:
            self.sel.modify(p.sock, selectors.EVENT_READ, c)
        except (KeyError, ValueError):     # pragma: no cover
            self._drop_pending(p, "sel_error")
            return
        self.shm.add_counter("conns_open")
        if p.leftover:
            try:
                self._on_bytes(c, p.leftover)
            except wire.FrameError:
                self.shm.add_counter("frames_bad")
                self._close_conn(c, "frame_error")

    def _reap_pending(self, now: float) -> None:
        for p in list(self._pending_by_fd.values()):
            if now - p.t0 > self._reg_timeout:
                self._drop_pending(p, "reg_timeout")

    # ------------------------------------------------------------- loop
    def run(self) -> None:
        import signal
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:                 # non-main thread (tests)
            pass
        last_hb = 0.0
        last_reap = time.time()
        while self.running:
            now_m = time.monotonic()
            if self._up_sock is None and now_m >= self._up_next_t:
                self._up_connect()
            timeout = 0.2 if not self._stage else self._stage_max_age
            events = self.sel.select(timeout=timeout)
            for key, ev in events:
                data = key.data
                if data is _L_LISTEN:
                    self._accept()
                elif data is _L_UPLINK:
                    if self._up_state == "connecting" \
                            and ev & selectors.EVENT_WRITE:
                        self._up_established()
                    if self._up_sock is not None \
                            and ev & selectors.EVENT_READ:
                        self._up_readable()
                elif isinstance(data, _PendingConn):
                    self._on_reg_readable(data)
                else:
                    self._on_readable(data)
            self._flush_stage(only_aged=True)
            now = time.time()
            if now - last_hb >= self._hb_s:
                self.shm.heartbeat()
                if self._up_ready:
                    self._ctrlq.append(jframe(T_HB, {
                        "hb": self.shm.counter("hb_seq"),
                        "counters": self.shm.counters(),
                        "cum": {str(s): c
                                for s, c in self.shm.cum().items()}}))
                last_hb = now
            self._up_flush()
            self._up_update_events()
            if now - last_reap >= 1.0:
                last_reap = now
                self._reap_pending(now)
                if self.idle_timeout:
                    for c in list(self.conns.values()):
                        if now - c.last_rx > self.idle_timeout:
                            self._close_conn(c, "idle")
        self._finish()

    def _finish(self) -> None:
        """Graceful exit: close conns, flush the stage, give the spool
        a bounded final flush (records the kernel already holds still
        deliver; anything left is the next epoch's counted drop),
        close WALs."""
        for c in list(self.conns.values()):
            self._close_conn(c, "relay_stop")
        for p in list(self._pending_by_fd.values()):
            self._drop_pending(p, "relay_stop")
        self._flush_stage()
        if self._up_ready:
            self._ctrlq.append(jframe(T_HB, {
                "hb": self.shm.counter("hb_seq") + 1,
                "counters": self.shm.counters(),
                "cum": {str(s): c
                        for s, c in self.shm.cum().items()}}))
        deadline = time.monotonic() + 2.0
        while (self._up_state == "up" and self._up_want_write()
               and time.monotonic() < deadline):
            self._up_flush()
            if self._up_want_write():
                time.sleep(0.005)
        for j in self.journals.values():
            j.close()
        if self._up_sock is not None:
            try:
                self._up_sock.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:                    # pragma: no cover
            pass


# ======================================================================
# Supervisor-side hub (serve process)
# ======================================================================

class _RelayState:
    """Hub-side ledger + liveness state for one relay identity."""

    __slots__ = ("relay_id", "token", "writer", "accounted", "hw",
                 "last_hb", "last_counters", "epochs", "pid",
                 "connects")

    def __init__(self, relay_id: str):
        self.relay_id = relay_id
        self.token: Optional[str] = None
        self.writer = None
        self.accounted: dict[int, int] = {}   # consumed + dropped (cum)
        self.hw: dict[int, int] = {}          # published high water
        self.last_hb = time.monotonic()
        self.last_counters: dict = {}
        self.epochs = 0
        self.pid = 0
        self.connects = 0


class RelayHub:
    """Accept relay uplinks, consume framed batches into the runtime's
    staging slabs with the ring drain's exact gap accounting, answer
    registration RPCs against the server's sticky hostmap, and publish
    the ``gyt_relay_*`` supervision rows."""

    def __init__(self, rt, register_cb, host: str = "0.0.0.0",
                 port: int = 0):
        self.rt = rt
        self.stats = rt.stats
        self.register_cb = register_cb
        self.host, self.port = host, int(port)
        self._sharded = int(getattr(rt, "n", 1)) > 1
        self.nshards = max(1, int(getattr(rt, "n", 1)))
        self._relays: dict[str, _RelayState] = {}
        self._server = None
        self._mon_task = None
        self._tick = 0

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        import asyncio
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.host, self.port = sock[0], sock[1]
        self._mon_task = asyncio.create_task(self._monitor())
        log.info("relay hub on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._mon_task is not None:
            self._mon_task.cancel()
            self._mon_task = None
        if self._server is not None:
            self._server.close()
            for st in self._relays.values():
                if st.writer is not None:
                    try:
                        st.writer.close()
                    except Exception:      # pragma: no cover
                        pass
                    st.writer = None
            await self._server.wait_closed()
            self._server = None

    def broadcast_tick(self, tick: int) -> None:
        self._tick = int(tick)
        f = jframe(T_TICK, {"tick": self._tick})
        for st in self._relays.values():
            if st.writer is not None:
                try:
                    st.writer.write(f)
                except Exception:          # pragma: no cover
                    pass

    def relays_up(self) -> int:
        return sum(1 for st in self._relays.values()
                   if st.writer is not None)

    # ------------------------------------------------------------ ledger
    def _finalize_epoch(self, st: _RelayState) -> None:
        """Close a dead epoch's books: records the relay published
        that never arrived (lost spool, death mid-frame) are counted
        dropped NOW — the TCP analogue of draining a dead worker's
        rings. published == consumed + dropped holds exactly at every
        epoch boundary."""
        for shard, hw in st.hw.items():
            gap = hw - st.accounted.get(shard, 0)
            if gap > 0:
                self.stats.bump(
                    f"relay_dropped_records|relay={st.relay_id},"
                    f"shard={shard}", gap)
                st.accounted[shard] = hw

    # ------------------------------------------------------------- conn
    async def _handle(self, reader, writer) -> None:
        st: Optional[_RelayState] = None
        try:
            st = await self._conn_loop(reader, writer)
        except Exception:                  # pragma: no cover
            log.exception("relay hub conn failed")
        finally:
            if st is not None and st.writer is writer:
                st.writer = None
                self.stats.gauge(
                    f"relay_up|relay={st.relay_id}", 0.0)
            try:
                writer.close()
            except Exception:              # pragma: no cover
                pass

    async def _read_frame(self, reader):
        hdr = await reader.readexactly(_FH.size)
        magic, ftype, _fl, blen = _FH.unpack(hdr)
        if magic != RELAY_MAGIC or blen >= MAX_BODY:
            raise ValueError(f"bad relay frame {magic:#x}/{blen}")
        body = await reader.readexactly(blen) if blen else b""
        return ftype, body

    async def _conn_loop(self, reader, writer):
        import asyncio
        try:
            ftype, body = await asyncio.wait_for(
                self._read_frame(reader), 15.0)
        except (asyncio.IncompleteReadError, ValueError,
                asyncio.TimeoutError, ConnectionError, OSError):
            return None
        if ftype != T_HELLO:
            self.stats.bump("relay_frames_bad")
            return None
        hello = json.loads(body)
        relay_id = str(hello.get("relay_id") or "")
        token = str(hello.get("token") or "")
        if not relay_id or not token:
            writer.write(jframe(T_HELLO_OK,
                                {"ok": False, "error": "bad hello"}))
            await writer.drain()
            return None
        want_n = hello.get("nshards")
        if want_n is not None and int(want_n) != self.nshards:
            writer.write(jframe(T_HELLO_OK, {
                "ok": False,
                "error": f"nshards {want_n} != {self.nshards}"}))
            await writer.drain()
            return None
        st = self._relays.get(relay_id)
        if st is None:
            st = _RelayState(relay_id)
            self._relays[relay_id] = st
            self.rt.notifylog.add(
                f"ingest relay registered: {relay_id}",
                source="selfmon")
        if st.writer is not None:
            try:
                st.writer.close()          # new uplink wins
            except Exception:              # pragma: no cover
                pass
        if st.token is not None and st.token != token:
            # a NEW process instance: the old epoch's in-flight spool
            # is gone — close its books exactly, then start fresh
            self._finalize_epoch(st)
            st.accounted = {}
            st.hw = {}
            st.last_counters = {}
            st.epochs += 1
            self.stats.bump(f"relay_epochs|relay={relay_id}")
            self.rt.notifylog.add(
                f"ingest relay {relay_id} restarted (epoch "
                f"{st.epochs})", ntype="warn", source="selfmon")
        elif st.token == token:
            self.stats.bump(f"relay_reconnects|relay={relay_id}")
        else:
            st.epochs += 1
        st.token = token
        st.writer = writer
        st.pid = int(hello.get("pid", 0))
        st.last_hb = time.monotonic()
        st.connects += 1
        writer.write(jframe(T_HELLO_OK, {"ok": True,
                                         "nshards": self.nshards,
                                         "tick": self._tick}))
        await writer.drain()
        self.stats.gauge(f"relay_up|relay={relay_id}", 1.0)
        while True:
            try:
                ftype, body = await self._read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError,
                    OSError):
                return st
            except ValueError:
                self.stats.bump("relay_frames_bad")
                return st
            if st.writer is not writer:
                return st                  # superseded by a new uplink
            if ftype == T_BATCH:
                self._on_batch(st, body)
            elif ftype == T_HB:
                self._on_hb(st, json.loads(body))
            elif ftype == T_RPC:
                await self._on_rpc(st, writer, json.loads(body))

    # ---------------------------------------------------------- consume
    def _publish_hw(self, st: _RelayState, shard: int,
                    cum: int) -> None:
        hw = st.hw.get(shard, 0)
        if cum > hw:
            self.stats.bump(
                f"relay_published_records|relay={st.relay_id}",
                cum - hw)
            st.hw[shard] = cum

    def _on_batch(self, st: _RelayState, body: bytes) -> None:
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.utils import shmring
        if len(body) < _BH.size:
            self.stats.bump("relay_frames_bad")
            return
        shard, nrec, _seq, cum = _BH.unpack_from(body, 0)
        shard = int(shard) % self.nshards
        rid = st.relay_id
        self._publish_hw(st, shard, int(cum))
        acc = st.accounted.get(shard, 0)
        gap = (int(cum) - int(nrec)) - acc
        if gap > 0:
            # the drain-side half of the cross-machine ledger: records
            # the relay published that never reached us (spool shed /
            # conn death) — counted, attributed, never silent
            self.stats.bump(
                f"relay_dropped_records|relay={rid},shard={shard}",
                gap)
        st.accounted[shard] = max(acc, int(cum))
        recs, nr = shmring.unpack_sections(body[_BH.size:],
                                           wire.DTYPE_OF_SUBTYPE)
        if nr < int(nrec):
            self.stats.bump(f"relay_unknown_records|relay={rid}",
                            int(nrec) - nr)
        if recs:
            if self._sharded:
                self.rt.ingest_records(recs, shard=shard)
            else:
                self.rt.ingest_records(recs)
        self.stats.bump(f"relay_consumed_records|relay={rid}",
                        int(nrec))
        self.stats.bump(f"relay_batches|relay={rid}")
        self.stats.bump(f"relay_bytes|relay={rid}",
                        len(body) + _FH.size)

    def _on_hb(self, st: _RelayState, msg: dict) -> None:
        st.last_hb = time.monotonic()
        for s, c in (msg.get("cum") or {}).items():
            self._publish_hw(st, int(s) % self.nshards, int(c))
        ctrs = msg.get("counters") or {}
        last = st.last_counters
        for name in _FOLD_COUNTERS:
            d = int(ctrs.get(name, 0)) - int(last.get(name, 0))
            if d > 0:
                self.stats.bump(
                    f"relay_proc_{name}|relay={st.relay_id}", d)
        st.last_counters = {k: int(v) for k, v in ctrs.items()
                            if isinstance(v, (int, float))}
        self.stats.gauge(f"relay_spool_bytes|relay={st.relay_id}",
                         float(ctrs.get("spool_bytes", 0)))
        self.stats.gauge(
            f"relay_conns|relay={st.relay_id}",
            float(max(0, int(ctrs.get("conns_open", 0))
                      - int(ctrs.get("conns_closed", 0)))))

    async def _on_rpc(self, st: _RelayState, writer,
                      msg: dict) -> None:
        rid = msg.get("rid")
        if msg.get("op") == "register":
            status, hid, last_seq = self.register_cb(
                int(msg.get("mid", 0)),
                int(msg.get("conn_type", 0)),
                int(msg.get("wire_version", 0)))
            self.stats.bump(
                f"relay_registrations|relay={st.relay_id}")
            writer.write(jframe(T_RPC_RESP, {
                "rid": rid, "status": int(status), "hid": int(hid),
                "last_seq": int(last_seq),
                "conn_type": int(msg.get("conn_type", 0))}))
        else:
            writer.write(jframe(T_RPC_RESP,
                                {"rid": rid, "error": "unknown op"}))
        try:
            await writer.drain()
        except (ConnectionError, OSError):  # pragma: no cover
            pass

    # ----------------------------------------------------------- monitor
    async def _monitor(self) -> None:
        import asyncio
        stale = hb_stale_s()
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for st in self._relays.values():
                up = st.writer is not None
                age = now - st.last_hb
                self.stats.gauge(f"relay_up|relay={st.relay_id}",
                                 1.0 if up and age < stale else 0.0)
                self.stats.gauge(
                    f"relay_heartbeat_age_seconds|relay={st.relay_id}",
                    round(min(age, 1e9), 3))
                self.stats.gauge(f"relay_epoch|relay={st.relay_id}",
                                 float(st.epochs))
                if st.pid:
                    self.stats.gauge(f"relay_pid|relay={st.relay_id}",
                                     float(st.pid))


# ======================================================================
# CLI entry (the remote-host process)
# ======================================================================

def relay_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu.net.relay",
        description="remote ingest relay: agents register and stream "
                    "here; decoded batches ship to the supervisor "
                    "over one exact-ledger TCP uplink")
    ap.add_argument("--supervisor", required=True,
                    help="HOST:PORT of the serve process --relay-port")
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--listen-port", type=int, default=0)
    ap.add_argument("--relay-id", default=None)
    ap.add_argument("--journal-dir", default=None,
                    help="WAL root on THIS host (the relay owns its "
                         "shard WALs, like a local ingest worker)")
    ap.add_argument("--idle-timeout", type=float, default=None)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s relay %(message)s")
    host, _, port = args.supervisor.rpartition(":")
    cfg = {"supervisor": (host or "127.0.0.1", int(port)),
           "listen_host": args.listen_host,
           "listen_port": args.listen_port,
           "relay_id": args.relay_id,
           "journal_dir": args.journal_dir,
           "idle_timeout": args.idle_timeout}
    w = RelayWorker(cfg)
    # machine-parsable bind line: harnesses (and operators scripting
    # ephemeral ports) read the actual listen address from stdout
    print(f"RELAY_LISTEN {w.listen_addr[0]} {w.listen_addr[1]}",
          flush=True)
    w.run()
    return 0


if __name__ == "__main__":                 # pragma: no cover
    raise SystemExit(relay_main())
