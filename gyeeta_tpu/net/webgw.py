"""Web gateway: HTTP/JSON face over the query protocol (L7 tier).

The reference fronts madhava/shyama with a Node.js webserver speaking
its JSON envelope over NM conns (the repo's out-of-tree web tier; the
server side is the NM handshake in ``server/gy_mnodehandle.cc``).
Here the same tier is one asyncio process bridging REST to the GYT
query conn. A STOCK node webserver needs no gateway at all: the server
itself speaks the NM conn contract (``net/nmhandle.py``), and both
surfaces render the same ``Runtime.query`` dict with plain
``json.dumps`` — NM and REST responses are parity-tested byte-equal
for identical queries (``tests/test_nmquery.py``):

- ``POST /query``            — raw JSON query/CRUD/multiquery envelope
- ``GET  /v1/<subsys>``      — convenience: query params ``filter``,
  ``maxrecs``, ``sortcol``, ``sortdesc``, ``tstart``, ``tend``, plus
  the time-travel params ``at`` (pin a snapshot instant) and
  ``window`` (trailing-duration aggregate) served from compaction
  shards (``history/timeview.py``), and ``consistency``
  (``snapshot`` — the server default: read the last published
  per-tick engine view off-loop; ``strong`` — flush-then-read on the
  serving loop, the pre-snapshot semantics)
- ``GET  /healthz``          — gateway + upstream liveness
- ``GET  /metrics``          — Prometheus text-format exposition of the
  upstream server's self-metrics (the ``metrics`` query subsystem,
  rendered by ``obs/prom.py``) — point a standard scraper here

One upstream :class:`~gyeeta_tpu.net.agent.QueryClient` serialized by
a lock (the query conn multiplexes by seqid, but the client helper
reads responses inline); dropped upstream conns reconnect per request.
Stdlib-only HTTP/1.1 (Content-Length framing, keep-alive) — the
gateway carries operator queries, not ingest traffic.
"""

from __future__ import annotations

import asyncio
import json
import urllib.parse
from typing import Optional

from gyeeta_tpu.net.agent import QueryClient

_MAX_BODY = 8 << 20
_MAX_HDR = 64 << 10


class WebGateway:
    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0):
        self.upstream = (upstream_host, upstream_port)
        self.host, self.port = host, port
        self._server = None
        self._qc: Optional[QueryClient] = None
        self._lock = asyncio.Lock()
        # GIL-relief JSON encode tier (GYT_QUERY_PROCS, net/qexec.py):
        # large response bodies encode in a child process so the
        # gateway loop pays a cheap pickle instead of the full dumps
        from gyeeta_tpu.net.qexec import JsonRenderPool
        self._render = JsonRenderPool()

    async def start(self) -> tuple:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._qc is not None:
            await self._qc.close()
            self._qc = None
        self._render.close()

    # -------------------------------------------------------- upstream
    async def _query(self, req: dict) -> dict:
        from gyeeta_tpu.ingest import wire

        async with self._lock:
            for attempt in (0, 1):      # one reconnect on a dead conn
                if self._qc is None:
                    qc = QueryClient()
                    await qc.connect(*self.upstream)
                    self._qc = qc
                try:
                    return await self._qc.query(req)
                except (ConnectionError, OSError,
                        asyncio.IncompleteReadError,
                        wire.FrameError):
                    # FrameError = DESYNCED stream (aborted QS_PARTIAL,
                    # seqid mismatch): the conn must not be reused or
                    # every later request reads the stale tail forever
                    await self._qc.close()
                    self._qc = None
                    if attempt:
                        raise
        raise ConnectionError("upstream unreachable")

    # ------------------------------------------------------------ http
    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except asyncio.LimitOverrunError:
                    await self._respond(writer, 431, {"error":
                                                      "headers too large"})
                    return
                if len(head) > _MAX_HDR:
                    await self._respond(writer, 431, {"error":
                                                      "headers too large"})
                    return
                lines = head.decode("latin1").split("\r\n")
                parts = lines[0].split()
                if len(parts) != 3:
                    await self._respond(writer, 400,
                                        {"error": "bad request line"})
                    return
                method, target, _ = parts
                headers = {}
                for ln in lines[1:]:
                    if ":" in ln:
                        k, v = ln.split(":", 1)
                        headers[k.strip().lower()] = v.strip()
                try:
                    clen = int(headers.get("content-length", 0) or 0)
                except ValueError:
                    clen = -1
                if clen < 0:
                    await self._respond(writer, 400,
                                        {"error": "bad content-length"})
                    return
                if clen > _MAX_BODY:
                    await self._respond(writer, 413,
                                        {"error": "body too large"})
                    return
                body = await reader.readexactly(clen) if clen else b""
                keep = headers.get("connection", "keep-alive") \
                    .lower() != "close"
                streamed = await self._route(writer, method, target,
                                             body)
                if streamed or not keep:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass

    async def _route(self, writer, method: str, target: str,
                     body: bytes):
        path, _, qs = target.partition("?")
        try:
            if method == "GET" and path == "/v1/subscribe":
                await self._sse_subscribe(writer, qs)
                return True          # stream owned the conn: close it
            if method == "GET" and path == "/metrics":
                out = await self._query({"subsys": "metrics"})
                await self._respond_text(
                    writer, 200, out.get("text", ""),
                    out.get("content_type", "text/plain"))
                return
            if method == "GET" and path == "/healthz":
                out = await self._query({"subsys": "serverstatus"})
                up = out.get("nrecs", 0) == 1
                await self._respond(writer, 200 if up else 503,
                                    {"ok": up})
                return
            if method == "POST" and path == "/query":
                req = json.loads(body or b"{}")
                await self._respond(writer, 200, await self._query(req))
                return
            if method == "GET" and path.startswith("/v1/"):
                req = {"subsys": path[4:].strip("/")}
                q = urllib.parse.parse_qs(qs)
                for k in ("filter", "sortcol", "consistency"):
                    if k in q:
                        req[k] = q[k][0]
                for k in ("maxrecs",):
                    if k in q:
                        req[k] = int(q[k][0])
                for k in ("tstart", "tend"):
                    if k in q:
                        req[k] = float(q[k][0])
                # time-travel params (history/timeview.py): at= pins a
                # snapshot instant ("1712000000", "-15m", "tick:24");
                # window= aggregates a trailing duration ("15m", 900)
                for k in ("at", "window"):
                    if k in q:
                        req[k] = q[k][0]
                if "sortdesc" in q:
                    req["sortdesc"] = q["sortdesc"][0].lower() in (
                        "1", "true")
                await self._respond(writer, 200, await self._query(req))
                return
            await self._respond(writer, 404, {"error": "not found"})
        except (ValueError, KeyError, RuntimeError) as e:
            # RuntimeError carries the server's own error envelope
            # (unknown subsystem, bad filter, …) — a CLIENT error here
            await self._respond(writer, 400, {"error": str(e)})
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            await self._respond(writer, 502,
                                {"error": "upstream unreachable"})

    async def _sse_subscribe(self, writer, qs: str) -> None:
        """REST subscription relay: one DEDICATED upstream conn per
        SSE client carrying the server's ``COMM_SUBSCRIBE_CMD`` stream
        (``net/subs.py``) — the upstream hub still renders each
        distinct query once per tick; this edge only re-frames events
        as ``text/event-stream``. ``last_snaptick=`` resumes a
        reconnecting dashboard with a delta when the server still
        holds that version."""
        import json as _json

        from gyeeta_tpu.net.subs import SubscribeClient
        q = urllib.parse.parse_qs(qs)
        if "subsys" not in q:
            await self._respond(writer, 400,
                                {"error": "subscribe needs subsys"})
            return
        req = {"subsys": q["subsys"][0]}
        for k in ("filter", "sortcol"):
            if k in q:
                req[k] = q[k][0]
        if "maxrecs" in q:
            req["maxrecs"] = int(q["maxrecs"][0])
        if "sortdesc" in q:
            req["sortdesc"] = q["sortdesc"][0].lower() in ("1", "true")
        if "cq" in q:
            # continuous query: relay a STANDING FILTER subscription
            # (enter/leave/change membership events) instead of a
            # panel-delta one — the upstream hub does the grouping
            req["cq"] = q["cq"][0].lower() in ("1", "true")
        last = None
        if "last_snaptick" in q:
            try:
                last = int(q["last_snaptick"][0])
            except ValueError:
                pass
        # stall_s= opts the relay into upstream heartbeat-loss
        # detection: a wedged hub surfaces as a typed
        # SubscriptionStalled, relayed below as an `event: error`
        # block instead of an indefinitely-silent stream (clients
        # pick ~3x the server tick interval)
        stall_s = None
        if "stall_s" in q:
            try:
                stall_s = float(q["stall_s"][0])
            except ValueError:
                pass
        sc = SubscribeClient()
        try:
            await sc.connect(*self.upstream)
            await sc.subscribe(req, last_snaptick=last)
        except (ConnectionError, OSError,
                asyncio.IncompleteReadError) as e:
            await self._respond(writer, 502, {"error": str(e)})
            await sc.close()
            return
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        try:
            await writer.drain()
            async for ev in sc.events(stall_timeout=stall_s):
                writer.write(
                    f"event: {ev.get('t', 'message')}\n"
                    f"data: {_json.dumps(ev)}\n\n".encode())
                await writer.drain()
        except RuntimeError as e:
            # upstream rejected the subscription (bad filter,
            # capacity) or the stream STALLED past stall_s
            # (SubscriptionStalled is a RuntimeError): relay it as an
            # SSE error event — mirroring FabricGateway._sse_subscribe
            # — so the client can tell either from an empty stream
            try:
                writer.write(
                    f"event: error\n"
                    f"data: {_json.dumps({'error': str(e)})}\n\n"
                    .encode())
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        except (ConnectionError, OSError):
            pass                       # either side hung up
        finally:
            await sc.close()

    _REASON = {200: "OK", 400: "Bad Request", 404: "Not Found",
               413: "Payload Too Large", 431: "Headers Too Large",
               502: "Bad Gateway", 503: "Service Unavailable"}

    async def _respond(self, writer, status: int, obj) -> None:
        await self._respond_bytes(writer, status,
                                  await self._render.encode(obj),
                                  "application/json")

    @classmethod
    async def _respond_text(cls, writer, status: int, text: str,
                            ctype: str) -> None:
        await cls._respond_bytes(writer, status, text.encode(), ctype)

    @classmethod
    async def _respond_bytes(cls, writer, status: int, body: bytes,
                             ctype: str) -> None:
        reason = cls._REASON.get(status, "Error")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        await writer.drain()
