"""NM conn edge: the node-webserver query channel on the GYT server.

The role of madhava's NM conn handling (``server/gy_mnodehandle.cc``:
handshake at :61, ``web_query_route_qtype`` at :203) on this server:
``GytServer`` routes a stock ``NM_CONNECT_CMD_S`` opener here
(magic-peeked, same as the partha handshakes); the handshake is
version-gated and answers ``NM_CONNECT_RESP_S`` with a sticky conn
identity, then the conn loops on ``QUERY_CMD_S`` frames:

- ``QUERY_WEB_JSON``    → the reference qtype/options envelope,
  translated (``refquery.web_json_to_query``) and answered by the SAME
  ``Runtime.query`` path the GYT protocol and REST gateway share — so
  Runtime and ShardedRuntime both serve NM conns, and NM/REST JSON is
  identical by construction;
- ``CRUD_GENERIC_JSON`` → tracedef/tag CRUD (``query/crud.py`` →
  ``trace/defs.py``);
- ``CRUD_ALERT_JSON``   → alertdef/silence/inhibit/action CRUD
  (``alerts/manager.py``), objtype family enforced per verb.

Responses stream as chunked ``QUERY_RESPONSE_S`` frames (is_completed=0
partials + a final complete frame — the ≤16MB SOCK_JSON_WRITER
discipline) with a drain per chunk: bounded transport memory.

Observability: ``nm_conns`` gauge, per-verb ``nm_queries|verb=...``
labeled counters and ``nm_<verb>`` timing hists land in the Stats
registry and surface through the existing /metrics exporter.
"""

from __future__ import annotations

import asyncio
import logging
import time

import numpy as np

from gyeeta_tpu.ingest import refproto as RP
from gyeeta_tpu.ingest import refquery as RQ
from gyeeta_tpu.ingest import wire

log = logging.getLogger("gyeeta_tpu.net.nm")

# per-verb observability names (the {verb=...} label values)
_VERB_OF_QTYPE = {
    RQ.REF_QUERY_WEB_JSON: "web_json",
    RQ.REF_CRUD_GENERIC_JSON: "crud_generic_json",
    RQ.REF_CRUD_ALERT_JSON: "crud_alert_json",
}


class NMConnState:
    """Sticky per-conn identity issued at the NM handshake (the
    reference pins the node's host/port pair on its conn object and
    reuses it across queries; reconnects present the same identity)."""

    def __init__(self, hostname: str, port: int, conn_id: int):
        self.hostname = hostname
        self.port = port
        self.conn_id = conn_id          # sticky per (hostname, port)
        self.n_queries = 0
        self.t_connect = time.time()


def _gate_nm(req: dict) -> tuple[int, str]:
    """Version gates of the NM handshake (the same validate_fields
    discipline as the partha gates, ``gy_comm_proto.h:55-56``)."""
    if req["comm_version"] != RP.REF_COMM_VERSION:
        return 101, (f"comm version {req['comm_version']} unsupported "
                     f"(need {RP.REF_COMM_VERSION})")
    if req["node_version"] < RQ.REF_MIN_NODE_VERSION:
        return 103, "node version below minimum supported"
    if req["min_madhava_version"] > RP.REF_MADHAVA_VERSION:
        return 102, "server version below node's minimum"
    return 0, ""


async def serve_nm_conn(server, reader, writer, body: bytes) -> None:
    """Handle one NM conn end-to-end: ``body`` is the already-read
    NM_CONNECT_CMD_S payload (the server's pre-registration loop peeled
    the COMM_HEADER). Returns when the conn closes."""
    rt = server.rt
    req = RQ.parse_nm_connect_cmd(body)
    err, es = _gate_nm(req)
    now = int(time.time())
    writer.write(RQ.encode_nm_connect_resp(err, es, server._madhava_id,
                                           now))
    await writer.drain()
    if err:
        rt.stats.bump("nm_conns_rejected")
        return
    st = server._nm_register(req["node_hostname"], req["node_port"])
    rt.stats.bump("nm_conns_accepted")
    server._nm_conns_live += 1
    rt.stats.gauge("nm_conns", server._nm_conns_live)
    log.info("nm conn: node %s:%d (conn id %d)", st.hostname, st.port,
             st.conn_id)
    try:
        await _query_loop(server, reader, writer, st)
    finally:
        server._nm_conns_live -= 1
        rt.stats.gauge("nm_conns", server._nm_conns_live)


async def _read_nm_frame(reader) -> tuple[int, bytes]:
    """One reference COMM_HEADER frame → (data_type, payload). Raises
    IncompleteReadError at EOF, FrameError on poison headers."""
    hsz = RP.REF_HEADER_DT.itemsize
    hdr_b = await reader.readexactly(hsz)
    hdr = np.frombuffer(hdr_b, RP.REF_HEADER_DT, count=1)[0]
    if int(hdr["magic"]) not in RP.REF_MAGICS:
        raise wire.FrameError(
            f"bad NM magic 0x{int(hdr['magic']):08x}")
    total = int(hdr["total_sz"])
    if total < hsz or total >= wire.MAX_COMM_DATA_SZ:
        raise wire.FrameError(f"bad NM total_sz {total}")
    pad = int(hdr["padding_sz"])
    if pad > total - hsz:
        raise wire.FrameError(f"bad NM padding_sz {pad}")
    body = await reader.readexactly(total - hsz)
    return int(hdr["data_type"]), body[: len(body) - pad]


async def _route(server, qtype: int, obj: dict) -> dict:
    """One NM request → the shared engine path. Raises ValueError on
    envelope errors (caught into an error response by the loop).
    QUERY_WEB_JSON rides ``server.run_query`` — the same snapshot +
    off-loop executor routing as the GYT and REST edges, so NM/REST
    parity holds through the snapshot path by construction; CRUD
    mutates live structures and stays inline."""
    rt = server.rt
    if qtype == RQ.REF_QUERY_WEB_JSON:
        return await server.run_query(RQ.web_json_to_query(obj))
    server._feed_barrier()
    if qtype == RQ.REF_CRUD_GENERIC_JSON:
        return rt.crud(RQ.crud_to_request(obj, alert=False))
    if qtype == RQ.REF_CRUD_ALERT_JSON:
        return rt.crud(RQ.crud_to_request(obj, alert=True))
    raise ValueError(f"unsupported NM query type {qtype}")


async def serve_nm_gateway(gw, reader, writer, body: bytes) -> None:
    """The NM dialect on the FABRIC GATEWAY front (``net/gateway.py``):
    same handshake gates, but queries route through the gateway's
    (snaptick, request-hash) edge cache instead of a local runtime —
    a stock node webserver pointed at a gateway shares the fleet's
    renders without knowing the tier exists. Because this rides the
    SAME ``gw.query`` entry as the HTTP/GYT fronts, a stock NM also
    sees the gateway-local panels (``subsys=topology`` — the breaker /
    owner-map health model). CRUD verbs translate and pass through to
    a replica (mutations are never cached)."""
    req = RQ.parse_nm_connect_cmd(body)
    err, es = _gate_nm(req)
    now = int(time.time())
    writer.write(RQ.encode_nm_connect_resp(err, es, gw._madhava_id,
                                           now))
    await writer.drain()
    if err:
        gw.stats.bump("gw_nm_rejected")
        return
    gw.stats.bump("gw_nm_conns_accepted")
    while True:
        try:
            dtype, fbody = await _read_nm_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        if dtype != RQ.REF_COMM_QUERY_CMD:
            gw.stats.bump("gw_nm_frames_unknown_type")
            continue
        seqid, qtype, obj = RQ.parse_query_cmd(fbody)
        verb = _VERB_OF_QTYPE.get(qtype, f"qtype_{qtype}")
        gw.stats.bump(f"gw_queries|edge=nm,verb={verb}")
        try:
            if qtype == RQ.REF_QUERY_WEB_JSON:
                q = RQ.web_json_to_query(obj)
            elif qtype == RQ.REF_CRUD_GENERIC_JSON:
                q = RQ.crud_to_request(obj, alert=False)
            elif qtype == RQ.REF_CRUD_ALERT_JSON:
                q = RQ.crud_to_request(obj, alert=True)
            else:
                raise ValueError(f"unsupported NM query type {qtype}")
            with gw.stats.timeit("gw_query"):
                out = await gw.query(q)
        except Exception as e:          # noqa: BLE001 — envelope error
            writer.write(RQ.encode_response_frames(
                seqid, {"error": str(e), "errcode": 400},
                RQ.REF_RESP_ERROR))
            await writer.drain()
            continue
        for frame in RQ.iter_response_frames(seqid, out):
            writer.write(frame)
            await writer.drain()


async def _query_loop(server, reader, writer, st: NMConnState) -> None:
    rt = server.rt
    outstanding = 0
    bad_frames = 0
    while True:
        try:
            # idle deadline (server.idle_timeout): a silent NM conn is
            # reaped on the same labeled counter as every other conn
            if server.idle_timeout:
                dtype, body = await asyncio.wait_for(
                    _read_nm_frame(reader), server.idle_timeout)
            else:
                dtype, body = await _read_nm_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        except (asyncio.TimeoutError, TimeoutError):
            rt.stats.bump("conn_timeouts|kind=idle")
            log.info("nm conn: node %s:%d idle — reaped", st.hostname,
                     st.port)
            return
        if dtype != RQ.REF_COMM_QUERY_CMD:
            rt.stats.bump("nm_frames_unknown_type")
            bad_frames += 1
            if bad_frames > server.frame_error_budget:
                # per-conn error budget, same discipline as the GYT
                # query loop: junk frames must not spin forever
                rt.stats.bump("frames_rejected|reason=error_budget")
                return
            continue
        seqid, qtype, obj = RQ.parse_query_cmd(body)
        verb = _VERB_OF_QTYPE.get(qtype, f"qtype_{qtype}")
        rt.stats.bump(f"nm_queries|verb={verb}")
        st.n_queries += 1
        if outstanding >= wire.MAX_OUTSTANDING_QUERIES:
            writer.write(RQ.encode_response_frames(
                seqid, {"error": "busy", "errcode": 503},
                RQ.REF_RESP_ERROR))
            await writer.drain()
            continue
        outstanding += 1
        try:
            with rt.stats.timeit(f"nm_{verb}"):
                out = await _route(server, qtype, obj)
        except Exception as e:
            from gyeeta_tpu.net.qexec import Overloaded
            outstanding -= 1
            rt.stats.bump("nm_query_errors")
            # shed → 503 (counted in gyt_queries_shed_total), envelope
            # errors → 400; either way the conn and loop stay live
            code = 503 if isinstance(e, Overloaded) else 400
            writer.write(RQ.encode_response_frames(
                seqid, {"error": str(e), "errcode": code},
                RQ.REF_RESP_ERROR))
            await writer.drain()
            continue
        try:
            # large results stream as is_completed=0 chunks with a
            # drain per chunk (bounded transport memory)
            sent = 0
            try:
                for frame in RQ.iter_response_frames(seqid, out):
                    writer.write(frame)
                    await writer.drain()
                    sent += 1
            except Exception as e:
                if sent == 0 and not isinstance(e, ConnectionError):
                    # e.g. unserializable result: the query still gets
                    # its error response and the conn survives
                    writer.write(RQ.encode_response_frames(
                        seqid, {"error": str(e), "errcode": 500},
                        RQ.REF_RESP_ERROR))
                    await writer.drain()
                else:
                    raise       # mid-stream failure: close (resync)
        finally:
            outstanding -= 1
