"""Sealed-segment shipping: the compaction tier's WAN hop.

PR 17 carried ingest (``net/relay.py``) and query (``gateway
--hub-from``) across regions; compaction workers still had to mount
the source filesystem. This module moves the remaining tier: SEALED
``gyt_wal_*.gytwal`` segments — immutable by construction — ship from
a source region (:class:`~gyeeta_tpu.history.shipper.SegmentShipper`)
to a compaction region's STAGING directory, where the stock
``ParallelCompactor`` / ``Compactor`` replays them into a parted store
exactly as if local (same file names, same ``shard_NN/`` layout, same
seq numbering → bit-identical parts).

The supervision shape is the relay's, adapted to a file-granular unit
of work:

- **Epochs**: each shipper process run carries a fresh instance token
  in its HELLO. A new token is an epoch boundary (counted); a
  reconnect with the SAME token is a continuation — in-flight partial
  transfers resume at the byte offset the receiver already holds.
- **Content hashes**: every segment announces ``blake2b`` over its
  full bytes. The receiver verifies the hash over the COMPLETE landed
  file (including any resumed prefix) before publishing it — a
  mismatch discards the partial, counts ``ship_hash_mismatches``, and
  the shipper re-ships from scratch. No torn or corrupted segment can
  ever become visible to the compactor.
- **Atomic landing**: bytes stream into a hidden ``.ship_*.part``
  file (invisible to ``dir_segments``/the compactor); on verify the
  receiver fsyncs, renames to the final segment name, fsyncs the
  directory, then appends the landing to the content-hash LEDGER
  (``gyt_ship_ledger.jsonl``, fsynced) before acking. Every crash
  interleaving reconciles on the next announce: rename-but-no-ledger
  re-verifies the landed file's hash; ledger-but-no-ack answers the
  re-announce with ``done``. Partials are kept across disconnects and
  shipper SIGKILLs (segments are immutable, the end-to-end hash makes
  offset resume safe) but SWEPT, counted, on receiver restart (a torn
  receiver-side tail is not trustworthy).
- **Ledger**: the append-only JSONL ledger is the authoritative
  dedup + provenance record — one line per terminal key
  ``shard/seq`` with status ``landed``/``shed``/``dropped``, the
  content hash, and the source identity (shipper id, instance token,
  epoch, pid). A landed-then-swept segment (staging reclaim after
  compaction) still answers ``done`` by ledger, so re-announces after
  ANY crash never double-land or double-count. ``gyeeta_tpu compact
  list`` renders it as per-segment provenance.
- **Global ledger invariant**: ``sealed == shipped + counted drops``.
  ``sealed`` (segments the source ever sealed) arrives on shipper
  heartbeats as the monotone per-shard ``sealed_upto`` sum (monotone
  across shipper restarts — seq numbering is persistent); ``shipped``
  and ``dropped`` are distinct ledger keys, re-derived from the
  ledger at receiver restart. Receiver staging-bound sheds and
  shipper-announced permanent drops (``T_SDROP``) are the ONLY drop
  paths, both counted — never silence.
- **Bounded staging**: a META whose size would push staging past
  ``GYT_SHIP_STAGE_MB`` is SHED (terminal, counted,
  ``ship_stage_sheds``); landed segments strictly below the
  compaction floor are swept by :meth:`SegmentReceiver.sweep_below`
  to reclaim staging space (``ship_staged_swept``).

The source journal side of the contract lives in
``utils/journal.py``: the shipper registers a NAMED truncate floor
(``set_truncate_floor(seq, name="ship")``) at the oldest unshipped
segment, and truncation bounds at the MIN over all named floors — a
sealed-but-unshipped segment can never be deleted by checkpoint
truncation, no matter how far ahead checkpoints or local compaction
run.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pathlib
import struct
import time
from typing import Optional

log = logging.getLogger("gyeeta_tpu.net.segship")

# ---------------------------------------------------------------- frames
# [magic u32 | type u16 | flags u16 | body_len u32] + body — the relay
# frame shape with its own magic so a mis-wired port fails loudly.
SHIP_MAGIC = 0x47595453                   # "STYG" on the wire
_FH = struct.Struct("<IHHI")
MAX_BODY = 16 * 1024 * 1024

T_SHELLO = 1      # shipper → recv  JSON {shipper_id, token, pid,
#                                         layout, nshards}
T_SHELLO_OK = 2   # recv → shipper  JSON {ok} | {ok: False, error}
T_SMETA = 3       # shipper → recv  JSON {shard, seq, size, hash,
#                                         nrec, src{...}}
T_SRESP = 4       # recv → shipper  JSON {status: send|done|shed|
#                                         conflict, off?}
T_SDATA = 5       # shipper → recv  raw segment bytes at current offset
T_SEND = 6        # shipper → recv  JSON {} — end of segment stream
T_SACK = 7        # recv → shipper  JSON {ok} | {ok: False, reason}
T_SDROP = 8       # shipper → recv  JSON {shard, seq, size, nrec,
#                                         reason} — permanent drop
T_SHB = 9         # shipper → recv  JSON {counters, sealed_segments}

LEDGER_NAME = "gyt_ship_ledger.jsonl"
_PART_FMT = ".ship_{:08d}.part"
_PART_GLOB = ".ship_*.part"

# shipper-side cumulative counters the receiver delta-folds per epoch
# into ship_src_* rows (same shape as the relay hub's _FOLD_COUNTERS —
# a respawned shipper restarts them at 0, the new-token epoch boundary
# resets the fold baseline)
_FOLD_COUNTERS = ("ship_sealed_records", "ship_sealed_bytes",
                  "ship_reconnects", "ship_hash_retries")


def frame(ftype: int, body: bytes) -> bytes:
    if len(body) >= MAX_BODY:
        raise ValueError(f"ship frame body {len(body)}B over cap")
    return _FH.pack(SHIP_MAGIC, ftype, 0, len(body)) + body


def jframe(ftype: int, obj: dict) -> bytes:
    return frame(ftype, json.dumps(obj).encode())


def hb_interval_s(env=None) -> float:
    env = os.environ if env is None else env
    return max(0.05, float(env.get("GYT_SHIP_HB_S", "0.2")))


def hb_stale_s(env=None) -> float:
    env = os.environ if env is None else env
    return max(0.5, float(env.get("GYT_SHIP_HB_STALE_S", "5.0")))


def chunk_bytes(env=None) -> int:
    env = os.environ if env is None else env
    return max(4096, int(env.get("GYT_SHIP_CHUNK_KB", "256")) * 1024)


def stage_max_bytes(env=None) -> int:
    env = os.environ if env is None else env
    return max(1 << 20, int(env.get("GYT_SHIP_STAGE_MB", "1024")) << 20)


def seg_hash(path) -> str:
    """blake2b content hash of a segment file (the ship identity)."""
    h = hashlib.blake2b(digest_size=32)
    with open(path, "rb") as f:
        while True:
            b = f.read(1 << 20)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def key_of(shard: int, seq: int) -> str:
    return f"{int(shard):02d}/{int(seq):08d}"


# ======================================================================
# Receiver (compaction-region side)
# ======================================================================

class _ShipperState:
    """Receiver-side liveness + epoch state for one shipper identity."""

    __slots__ = ("shipper_id", "token", "writer", "last_hb",
                 "last_counters", "epochs", "pid", "connects")

    def __init__(self, shipper_id: str):
        self.shipper_id = shipper_id
        self.token: Optional[str] = None
        self.writer = None
        self.last_hb = time.monotonic()
        self.last_counters: dict = {}
        self.epochs = 0
        self.pid = 0
        self.connects = 0


class _Recv:
    """One in-flight segment transfer on one connection."""

    __slots__ = ("key", "meta", "path", "part", "f", "hasher", "off")

    def __init__(self, key, meta, path, part, f, hasher, off):
        self.key = key
        self.meta = meta
        self.path = path          # final segment path
        self.part = part          # hidden partial path
        self.f = f
        self.hasher = hasher
        self.off = off


class SegmentReceiver:
    """Accept shipper uplinks and land sealed WAL segments into a
    staging directory, hash-verified and crash-consistent, publishing
    the ``gyt_ship_*`` supervision rows. The staging dir replays
    through the stock compactors exactly as a local WAL root."""

    def __init__(self, staging_dir, stats=None, host: str = "0.0.0.0",
                 port: int = 0, floors_fn=None, notifylog=None,
                 env=None):
        from gyeeta_tpu.utils.journal import _NullStats
        self.dir = pathlib.Path(staging_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.stats = stats if stats is not None else _NullStats()
        self.host, self.port = host, int(port)
        self.env = os.environ if env is None else env
        self.stage_max = stage_max_bytes(self.env)
        # optional compaction-floor source: () -> per-shard floor list
        # (or a flat int); the monitor sweeps landed segments strictly
        # below it so staging disk is bounded by compaction lag
        self.floors_fn = floors_fn
        self.notifylog = notifylog
        self._shippers: dict[str, _ShipperState] = {}
        self._server = None
        self._mon_task = None
        self.owner: Optional[dict] = None   # {shipper, layout, nshards}
        self.ledger: dict[str, dict] = {}
        self._ledger_f = None
        # crash-injection hooks for the chaos smoke: die at the k-th
        # landing, either right after the rename (mode "rename" — the
        # ledger never hears of a durably landed file) or right after
        # the ledger append (mode "ledger" — landed + ledgered, never
        # acked). Both must reconcile on the next announce.
        self._die_after = int(self.env.get("GYT_SHIP_RECV_DIE_AFTER",
                                           "0") or 0)
        self._die_mode = self.env.get("GYT_SHIP_RECV_DIE_MODE",
                                      "ledger")
        self._landings = 0
        self._load_ledger()
        self._sweep_partials()

    # --------------------------------------------------------- durability
    def _load_ledger(self) -> None:
        """Replay the ledger into memory; a torn tail line (crash mid
        append) is dropped, counted — every complete line is a terminal
        fact. Global shipped/dropped counters re-derive here so a
        receiver restart keeps the ledger invariant exact."""
        lp = self.dir / LEDGER_NAME
        shipped = dropped = 0
        if lp.exists():
            with open(lp, "rb") as f:
                for raw in f:
                    if not raw.endswith(b"\n"):
                        self.stats.bump("ship_ledger_torn_tail")
                        break
                    try:
                        e = json.loads(raw)
                    except ValueError:
                        self.stats.bump("ship_ledger_torn_tail")
                        break
                    if e.get("meta") == "owner":
                        self.owner = e
                        continue
                    k = e.get("k")
                    if not k or k in self.ledger:
                        continue
                    self.ledger[k] = e
                    if e.get("status") == "landed":
                        shipped += 1
                        self.stats.bump("ship_shipped_records",
                                        int(e.get("nrec", 0)))
                        self.stats.bump("ship_shipped_bytes",
                                        int(e.get("size", 0)))
                    else:
                        dropped += 1
                        self.stats.bump("ship_dropped_records",
                                        int(e.get("nrec", 0)))
                        self.stats.bump("ship_dropped_bytes",
                                        int(e.get("size", 0)))
        if shipped:
            self.stats.bump("ship_shipped_segments", shipped)
        if dropped:
            self.stats.bump("ship_dropped_segments", dropped)
        self._ledger_f = open(lp, "ab")

    def _ledger_append(self, entry: dict) -> None:
        self._ledger_f.write(json.dumps(entry, sort_keys=True).encode()
                             + b"\n")
        self._ledger_f.flush()
        os.fsync(self._ledger_f.fileno())
        if "k" in entry:
            self.ledger[entry["k"]] = entry

    def _sweep_partials(self) -> None:
        """Receiver restart: a partial's tail may be torn (our own
        unsynced writes died with us) — sweep them all, counted. The
        shipper re-ships from offset 0; the content hash would have
        rejected the torn bytes anyway."""
        n = 0
        for p in list(self.dir.glob(_PART_GLOB)) \
                + list(self.dir.glob("shard_*/" + _PART_GLOB)):
            try:
                p.unlink()
                n += 1
            except OSError:                # pragma: no cover
                pass
        if n:
            self.stats.bump("ship_partials_swept", n)

    def _dir_for(self, shard: int) -> pathlib.Path:
        if self.owner and self.owner.get("layout") == "sharded":
            d = self.dir / f"shard_{int(shard):02d}"
            d.mkdir(parents=True, exist_ok=True)
            return d
        return self.dir

    def staging_bytes(self) -> int:
        total = 0
        for pat in ("*.gytwal", "shard_*/*.gytwal",
                    _PART_GLOB, "shard_*/" + _PART_GLOB):
            for p in self.dir.glob(pat):
                try:
                    total += p.stat().st_size
                except OSError:            # pragma: no cover
                    pass
        return total

    def sweep_below(self, floors) -> int:
        """Reclaim staging: delete LANDED segments strictly below the
        per-shard compaction floor (``journal.floors_of`` of the parted
        store's position). The ledger entries stay — a re-announce of a
        swept segment still answers ``done`` by hash."""
        from gyeeta_tpu.utils.journal import _SEG_FMT, dir_segments
        if floors is None:
            return 0
        if not isinstance(floors, (list, tuple)):
            floors = [int(floors)]
        n = 0
        for s, fl in enumerate(floors):
            d = self._dir_for(s)
            for seq in dir_segments(d):
                if seq >= int(fl):
                    continue
                if self.ledger.get(key_of(s, seq),
                                   {}).get("status") != "landed":
                    continue
                try:
                    (d / _SEG_FMT.format(seq)).unlink()
                    n += 1
                except OSError:            # pragma: no cover
                    pass
        if n:
            self.stats.bump("ship_staged_swept", n)
        return n

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        import asyncio
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.host, self.port = sock[0], sock[1]
        self._mon_task = asyncio.create_task(self._monitor())
        # publish the staging footprint immediately (the monitor's
        # first tick is a second away; scrapes must not miss it)
        self.stats.gauge("ship_staging_bytes",
                         float(self.staging_bytes()))
        log.info("segment receiver on %s:%d staging=%s",
                 self.host, self.port, self.dir)
        return self.host, self.port

    async def stop(self) -> None:
        if self._mon_task is not None:
            self._mon_task.cancel()
            self._mon_task = None
        if self._server is not None:
            self._server.close()
            for st in self._shippers.values():
                if st.writer is not None:
                    try:
                        st.writer.close()
                    except Exception:      # pragma: no cover
                        pass
                    st.writer = None
            await self._server.wait_closed()
            self._server = None
        if self._ledger_f is not None:
            self._ledger_f.close()
            self._ledger_f = None

    async def _monitor(self) -> None:
        import asyncio
        stale = hb_stale_s(self.env)
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for st in self._shippers.values():
                up = st.writer is not None
                age = now - st.last_hb
                self.stats.gauge(
                    f"ship_up|shipper={st.shipper_id}",
                    1.0 if up and age < stale else 0.0)
                self.stats.gauge(
                    f"ship_heartbeat_age_seconds|shipper="
                    f"{st.shipper_id}", round(min(age, 1e9), 3))
                self.stats.gauge(
                    f"ship_epoch|shipper={st.shipper_id}",
                    float(st.epochs))
                if st.pid:
                    self.stats.gauge(
                        f"ship_pid|shipper={st.shipper_id}",
                        float(st.pid))
            self.stats.gauge("ship_staging_bytes",
                             float(self.staging_bytes()))
            if self.floors_fn is not None:
                try:
                    self.sweep_below(self.floors_fn())
                except Exception:          # pragma: no cover
                    log.exception("ship staging sweep failed")

    # -------------------------------------------------------------- conn
    async def _read_frame(self, reader):
        import asyncio  # noqa: F401 — exception types on callers
        hdr = await reader.readexactly(_FH.size)
        magic, ftype, _fl, blen = _FH.unpack(hdr)
        if magic != SHIP_MAGIC or blen >= MAX_BODY:
            raise ValueError(f"bad ship frame {magic:#x}/{blen}")
        body = await reader.readexactly(blen) if blen else b""
        return ftype, body

    async def _handle(self, reader, writer) -> None:
        import asyncio
        st: Optional[_ShipperState] = None
        rx: Optional[_Recv] = None
        try:
            st, rx = await self._conn_loop(reader, writer)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except ValueError:
            self.stats.bump("ship_frames_bad")
        except Exception:                  # pragma: no cover
            log.exception("ship receiver conn failed")
        finally:
            # keep the partial on disk — a reconnect resumes from its
            # offset (segments are immutable; the end hash protects it)
            if rx is not None and rx.f is not None:
                try:
                    rx.f.close()
                except OSError:            # pragma: no cover
                    pass
            if st is not None and st.writer is writer:
                st.writer = None
                self.stats.gauge(
                    f"ship_up|shipper={st.shipper_id}", 0.0)
            try:
                writer.close()
            except Exception:              # pragma: no cover
                pass

    async def _conn_loop(self, reader, writer):
        import asyncio
        try:
            ftype, body = await asyncio.wait_for(
                self._read_frame(reader), 15.0)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ValueError, ConnectionError, OSError):
            return None, None
        if ftype != T_SHELLO:
            self.stats.bump("ship_frames_bad")
            return None, None
        hello = json.loads(body)
        shipper_id = str(hello.get("shipper_id") or "")
        token = str(hello.get("token") or "")
        layout = str(hello.get("layout") or "flat")
        if not shipper_id or not token \
                or layout not in ("flat", "sharded"):
            writer.write(jframe(T_SHELLO_OK,
                                {"ok": False, "error": "bad hello"}))
            await writer.drain()
            return None, None
        if self.owner is None:
            # first shipper binds the staging dir: ONE source region
            # per staging dir (shard/seq must be collision-free for
            # the replay to be bit-identical) — recorded in the ledger
            self.owner = {"meta": "owner", "shipper": shipper_id,
                          "layout": layout,
                          "nshards": int(hello.get("nshards", 1))}
            self._ledger_append(self.owner)
        if self.owner.get("shipper") != shipper_id \
                or self.owner.get("layout") != layout:
            writer.write(jframe(T_SHELLO_OK, {
                "ok": False,
                "error": f"staging dir owned by shipper "
                         f"{self.owner.get('shipper')}"
                         f"/{self.owner.get('layout')}"}))
            await writer.drain()
            self.stats.bump("ship_hello_refused")
            return None, None
        st = self._shippers.get(shipper_id)
        if st is None:
            st = _ShipperState(shipper_id)
            self._shippers[shipper_id] = st
            if self.notifylog is not None:
                self.notifylog.add(
                    f"segment shipper registered: {shipper_id}",
                    source="selfmon")
        if st.writer is not None:
            try:
                st.writer.close()          # new uplink wins
            except Exception:              # pragma: no cover
                pass
        if st.token is not None and st.token != token:
            # a NEW shipper process: epoch boundary — the fold
            # baseline for its cumulative heartbeat counters resets
            st.last_counters = {}
            st.epochs += 1
            self.stats.bump(f"ship_epochs|shipper={shipper_id}")
            if self.notifylog is not None:
                self.notifylog.add(
                    f"segment shipper {shipper_id} restarted "
                    f"(epoch {st.epochs})", ntype="warn",
                    source="selfmon")
        elif st.token == token:
            self.stats.bump(f"ship_reconnects|shipper={shipper_id}")
        st.token = token
        st.writer = writer
        st.pid = int(hello.get("pid", 0))
        st.last_hb = time.monotonic()
        st.connects += 1
        writer.write(jframe(T_SHELLO_OK, {"ok": True}))
        await writer.drain()
        self.stats.gauge(f"ship_up|shipper={shipper_id}", 1.0)
        rx: Optional[_Recv] = None
        while True:
            ftype, body = await self._read_frame(reader)
            if st.writer is not writer:
                return st, rx              # superseded by a new uplink
            if ftype == T_SMETA:
                rx = self._on_meta(st, writer, json.loads(body))
            elif ftype == T_SDATA:
                if rx is None:
                    self.stats.bump("ship_frames_bad")
                else:
                    rx.f.write(body)
                    rx.hasher.update(body)
                    rx.off += len(body)
            elif ftype == T_SEND:
                rx = self._on_end(st, writer, rx)
            elif ftype == T_SDROP:
                self._on_drop(st, writer, json.loads(body))
            elif ftype == T_SHB:
                self._on_hb(st, json.loads(body))
            else:
                self.stats.bump("ship_frames_bad")
            await writer.drain()

    # ------------------------------------------------------------ segment
    def _on_meta(self, st: _ShipperState, writer,
                 meta: dict) -> Optional[_Recv]:
        shard, seq = int(meta.get("shard", 0)), int(meta.get("seq", 0))
        size = int(meta.get("size", 0))
        want = str(meta.get("hash") or "")
        k = key_of(shard, seq)
        ent = self.ledger.get(k)
        if ent is not None:
            if ent.get("status") == "landed" and ent.get("hash") != want:
                # an immutable segment re-announced with a DIFFERENT
                # hash: source-side corruption or seq reuse — refuse,
                # loudly; the landed bytes stay authoritative
                self.stats.bump("ship_hash_conflicts")
                writer.write(jframe(T_SRESP, {"status": "conflict",
                                              "k": k}))
                return None
            writer.write(jframe(T_SRESP, {
                "status": "done" if ent.get("status") == "landed"
                else "shed", "k": k}))
            return None
        d = self._dir_for(shard)
        from gyeeta_tpu.utils.journal import _SEG_FMT
        final = d / _SEG_FMT.format(seq)
        if final.exists():
            # landed but crashed before the ledger append: verify the
            # file's hash NOW — a match completes the landing (ledger +
            # done), a mismatch sweeps the stray and re-receives
            if seg_hash(final) == want:
                self._land_ledger(st, meta, k)
                writer.write(jframe(T_SRESP, {"status": "done",
                                              "k": k}))
                return None
            try:
                final.unlink()
            except OSError:                # pragma: no cover
                pass
            self.stats.bump("ship_hash_mismatches")
        part = d / _PART_FMT.format(seq)
        have = part.stat().st_size if part.exists() else 0
        if have == 0 \
                and self.staging_bytes() + size > self.stage_max:
            # bounded staging: a segment that cannot fit is SHED —
            # terminal, counted, in the ledger (the drop half of
            # sealed == shipped + dropped). The source keeps its copy
            # pinned only until this verdict; never silent.
            self.stats.bump("ship_stage_sheds")
            self._drop_ledger(st, meta, k, "stage_full")
            writer.write(jframe(T_SRESP, {"status": "shed", "k": k}))
            return None
        hasher = hashlib.blake2b(digest_size=32)
        if have > size:                    # stale oversized partial
            try:
                part.unlink()
            except OSError:                # pragma: no cover
                pass
            have = 0
        if have:
            with open(part, "rb") as f:
                while True:
                    b = f.read(1 << 20)
                    if not b:
                        break
                    hasher.update(b)
            self.stats.bump("ship_resumes")
        f = open(part, "ab")
        writer.write(jframe(T_SRESP, {"status": "send", "off": have,
                                      "k": k}))
        return _Recv(k, meta, final, part, f, hasher, have)

    def _on_end(self, st: _ShipperState, writer,
                rx: Optional[_Recv]) -> None:
        if rx is None:
            self.stats.bump("ship_frames_bad")
            return None
        meta = rx.meta
        size = int(meta.get("size", 0))
        ok = (rx.off == size
              and rx.hasher.hexdigest() == str(meta.get("hash")))
        if not ok:
            # transfer corruption: discard the partial entirely — the
            # shipper re-ships the immutable source bytes from scratch
            try:
                rx.f.close()
                rx.part.unlink()
            except OSError:                # pragma: no cover
                pass
            self.stats.bump("ship_hash_mismatches")
            writer.write(jframe(T_SACK, {"ok": False, "k": rx.key,
                                         "reason": "hash"}))
            return None
        # atomic landing: data fsync → rename → dir fsync → ledger
        # (fsynced) → ack. A crash between any two steps reconciles on
        # re-announce (see _on_meta's final-exists branch).
        rx.f.flush()
        os.fsync(rx.f.fileno())
        rx.f.close()
        os.rename(rx.part, rx.path)
        dfd = os.open(rx.path.parent, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._landings += 1
        if self._die_after and self._landings >= self._die_after \
                and self._die_mode == "rename":
            os._exit(9)
        self._land_ledger(st, meta, rx.key)
        if self._die_after and self._landings >= self._die_after \
                and self._die_mode == "ledger":
            os._exit(9)
        writer.write(jframe(T_SACK, {"ok": True, "k": rx.key}))
        return None

    def _land_ledger(self, st: _ShipperState, meta: dict,
                     k: str) -> None:
        self._ledger_append({
            "k": k, "shard": int(meta.get("shard", 0)),
            "seq": int(meta.get("seq", 0)), "status": "landed",
            "hash": str(meta.get("hash")),
            "size": int(meta.get("size", 0)),
            "nrec": int(meta.get("nrec", 0)),
            "src": dict(meta.get("src") or {},
                        shipper=st.shipper_id, token=st.token,
                        epoch=st.epochs, pid=st.pid),
            "t_usec": int(time.time() * 1e6)})
        self.stats.bump("ship_shipped_segments")
        self.stats.bump("ship_shipped_records",
                        int(meta.get("nrec", 0)))
        self.stats.bump("ship_shipped_bytes",
                        int(meta.get("size", 0)))

    def _drop_ledger(self, st: _ShipperState, meta: dict, k: str,
                     reason: str) -> None:
        self._ledger_append({
            "k": k, "shard": int(meta.get("shard", 0)),
            "seq": int(meta.get("seq", 0)), "status": "dropped"
            if reason != "stage_full" else "shed",
            "reason": reason, "hash": str(meta.get("hash") or ""),
            "size": int(meta.get("size", 0)),
            "nrec": int(meta.get("nrec", 0)),
            "src": dict(meta.get("src") or {},
                        shipper=st.shipper_id, token=st.token,
                        epoch=st.epochs, pid=st.pid),
            "t_usec": int(time.time() * 1e6)})
        self.stats.bump("ship_dropped_segments")
        self.stats.bump("ship_dropped_records",
                        int(meta.get("nrec", 0)))
        self.stats.bump("ship_dropped_bytes",
                        int(meta.get("size", 0)))

    def _on_drop(self, st: _ShipperState, writer, msg: dict) -> None:
        """Shipper-announced permanent drop (its pinned backlog hit
        its bound and shed the oldest unshipped segment): enters the
        ledger as a counted drop so the global invariant still
        closes."""
        k = key_of(int(msg.get("shard", 0)), int(msg.get("seq", 0)))
        if k not in self.ledger:
            self._drop_ledger(st, msg, k,
                              str(msg.get("reason") or "source_shed"))
        writer.write(jframe(T_SACK, {"ok": True, "k": k}))

    def _on_hb(self, st: _ShipperState, msg: dict) -> None:
        st.last_hb = time.monotonic()
        sid = st.shipper_id
        sealed = msg.get("sealed_segments")
        if sealed is not None:
            # monotone across shipper restarts (seq numbering is
            # persistent in the source dir) — a plain set, no folding
            self.stats.gauge(f"ship_sealed_segments|shipper={sid}",
                             float(sealed))
        ctrs = msg.get("counters") or {}
        last = st.last_counters
        for name in _FOLD_COUNTERS:
            d = int(ctrs.get(name, 0)) - int(last.get(name, 0))
            if d > 0:
                self.stats.bump(f"ship_src_{name[5:]}|shipper={sid}",
                                d)
        st.last_counters = {key: int(v) for key, v in ctrs.items()
                            if isinstance(v, (int, float))}


# ======================================================================
# CLI entry (the compaction-region staging process)
# ======================================================================

def recv_main(argv=None) -> int:
    import argparse
    import asyncio
    import signal

    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu.net.segship",
        description="segment-ship receiver: sealed WAL segments from "
                    "a source region land here, hash-verified, for "
                    "the compaction tier to replay as if local")
    ap.add_argument("--staging", required=True,
                    help="staging dir (becomes the compactor's "
                         "--journal-dir)")
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--listen-port", type=int, default=0)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s segship %(message)s")
    from gyeeta_tpu.utils.selfstats import Stats

    async def run():
        rcv = SegmentReceiver(args.staging, stats=Stats(),
                              host=args.listen_host,
                              port=args.listen_port)
        host, port = await rcv.start()
        # machine-parsable bind line, like the relay's RELAY_LISTEN
        print(f"SHIP_LISTEN {host} {port}", flush=True)
        stopper = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stopper.set)
            except (NotImplementedError, ValueError):
                pass
        await stopper.wait()
        await rcv.stop()

    asyncio.run(run())
    return 0


if __name__ == "__main__":                 # pragma: no cover
    raise SystemExit(recv_main())
