"""Real TCP connection collector: netlink sock_diag → wire records.

The first REAL traffic source (VERDICT r3 #3): the agent's own host's
TCP connections and listeners, observed from userspace — the analogue
of the reference's inet_diag full-connection sweep
(``common/gy_socket_stat.cc:8598`` inet_diag_thread, 15s cadence,
``gy_socket_stat.h:996``) and its listener inventory, without eBPF.

Three sources, best-effort and privilege-graceful:

- **netlink NETLINK_SOCK_DIAG** (primary): one dump request per family
  enumerates every TCP socket with its tuple, state, queues, uid and
  inode; the ``INET_DIAG_INFO`` attribute carries ``struct tcp_info``
  whose ``tcpi_bytes_acked``/``tcpi_bytes_received`` (kernel ≥4.1) give
  REAL per-connection byte counts — the userspace stand-in for the
  reference's eBPF ``tcp_sendmsg``/``tcp_cleanup_rbuf`` accounting.
- **/proc/net/tcp{,6}** (fallback): same tuples/states/inodes, no byte
  counters.
- **/proc/net/nf_conntrack** (optional): original↔reply tuple pairs
  fill ``nat_cli``/``nat_ser`` the way the reference's netlink
  conntrack listener does (``gy_socket_stat.cc:1292``).

Sweep semantics (delta-based, like every collector here):

- listeners → stable glob_ids hashed from (machine_id, ip, port);
  first sight emits LISTENER_INFO (+ name announcements from the
  owning process's comm via the /proc fd→inode walk), every sweep
  emits LISTENER_STATE with real conn counts + byte rates.
- established conns → TCP_CONN records. A socket whose local port is
  a listening port is accept-observed (``flags`` bit1, the service
  side, ``ser_glob_id`` = listener id); otherwise connect-observed
  (bit0, ``ser_glob_id`` 0 — the remote service is unknown exactly as
  in the reference, resolved server-side by pairing). Byte fields are
  per-sweep DELTAS (the engine folds them additively); close is
  detected by disappearance and emits a final record with
  ``tusec_close`` set.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils import hashing as H
from gyeeta_tpu.utils.intern import InternTable

# ---------------------------------------------------------------- netlink
NETLINK_SOCK_DIAG = 4
SOCK_DIAG_BY_FAMILY = 20
NLM_F_REQUEST = 0x1
NLM_F_DUMP = 0x300            # NLM_F_ROOT | NLM_F_MATCH
NLMSG_ERROR = 2
NLMSG_DONE = 3
INET_DIAG_INFO = 2
TCP_ESTABLISHED = 1
TCP_LISTEN = 10
# struct tcp_info offsets (linux/tcp.h, append-only ABI): 8 lead bytes,
# 24 u32s, 2 u64 pacing rates → bytes_acked @120, bytes_received @128
_TCPI_BYTES_ACKED_OFF = 120
_TCPI_BYTES_RECEIVED_OFF = 128


class SockEntry:
    """One kernel TCP socket (family-normalized to 16-byte addresses)."""

    __slots__ = ("saddr", "sport", "daddr", "dport", "state", "inode",
                 "uid", "rqueue", "wqueue", "bytes_acked",
                 "bytes_received")

    def __init__(self, saddr: bytes, sport: int, daddr: bytes,
                 dport: int, state: int, inode: int, uid: int = 0,
                 rqueue: int = 0, wqueue: int = 0,
                 bytes_acked: int = 0, bytes_received: int = 0):
        self.saddr, self.sport = saddr, sport
        self.daddr, self.dport = daddr, dport
        self.state, self.inode, self.uid = state, inode, uid
        self.rqueue, self.wqueue = rqueue, wqueue
        self.bytes_acked = bytes_acked
        self.bytes_received = bytes_received

    @property
    def key(self):
        return (self.saddr, self.sport, self.daddr, self.dport)


def _map4(addr4: bytes) -> bytes:
    """IPv4 → IPv4-mapped IPv6 (the wire's 16-byte address form)."""
    return b"\x00" * 10 + b"\xff\xff" + addr4


def _diag_request(family: int, states: int) -> bytes:
    # nlmsghdr + inet_diag_req_v2 (+ sockid zeroed)
    req = struct.pack("=BBBBI", family, socket.IPPROTO_TCP,
                      1 << (INET_DIAG_INFO - 1), 0, states) + b"\x00" * 48
    hdr = struct.pack("=IHHII", 16 + len(req), SOCK_DIAG_BY_FAMILY,
                      NLM_F_REQUEST | NLM_F_DUMP, 1, 0)
    return hdr + req


def _parse_diag_msg(payload: bytes, family: int) -> Optional[SockEntry]:
    if len(payload) < 72:
        return None
    fam, state = payload[0], payload[1]
    sport, dport = struct.unpack_from(">HH", payload, 4)
    src = payload[8:24]
    dst = payload[24:40]
    expires, rqueue, wqueue, uid, inode = struct.unpack_from(
        "=IIIII", payload, 52)
    if fam == socket.AF_INET:
        src, dst = _map4(src[:4]), _map4(dst[:4])
    ent = SockEntry(src, sport, dst, dport, state, inode, uid,
                    rqueue, wqueue)
    # walk rtattrs for INET_DIAG_INFO (tcp_info byte counters)
    off = 72
    while off + 4 <= len(payload):
        alen, atype = struct.unpack_from("=HH", payload, off)
        if alen < 4 or off + alen > len(payload):
            break
        if atype == INET_DIAG_INFO:
            info = payload[off + 4: off + alen]
            if len(info) >= _TCPI_BYTES_RECEIVED_OFF + 8:
                (ent.bytes_acked,) = struct.unpack_from(
                    "=Q", info, _TCPI_BYTES_ACKED_OFF)
                (ent.bytes_received,) = struct.unpack_from(
                    "=Q", info, _TCPI_BYTES_RECEIVED_OFF)
        off += (alen + 3) & ~3
    return ent


def list_tcp_netlink(states: int = (1 << TCP_ESTABLISHED)
                     | (1 << TCP_LISTEN)) -> Optional[list]:
    """All TCP sockets via sock_diag, or None when netlink yields
    nothing. A per-family failure (e.g. NLMSG_ERROR on AF_INET6 when
    ipv6 is disabled) skips only that family — the v4 results, with
    their tcp_info byte counters, are still worth more than the /proc
    fallback."""
    out: list[SockEntry] = []
    any_ok = False
    for family in (socket.AF_INET, socket.AF_INET6):
        try:
            s = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW,
                              NETLINK_SOCK_DIAG)
        except (OSError, AttributeError):
            return None
        fam_ok = True
        fam_out: list[SockEntry] = []
        try:
            s.settimeout(2.0)
            s.sendto(_diag_request(family, states), (0, 0))
            done = False
            while not done:
                data = s.recv(1 << 20)
                off = 0
                while off + 16 <= len(data):
                    mlen, mtype = struct.unpack_from("=IH", data, off)
                    if mlen < 16 or off + mlen > len(data):
                        done = True
                        break
                    if mtype == NLMSG_DONE:
                        done = True
                        break
                    if mtype == NLMSG_ERROR:
                        fam_ok = False
                        done = True
                        break
                    if mtype == SOCK_DIAG_BY_FAMILY:
                        ent = _parse_diag_msg(
                            data[off + 16: off + mlen], family)
                        if ent is not None:
                            fam_out.append(ent)
                    off += (mlen + 3) & ~3
        except OSError:
            fam_ok = False
        finally:
            s.close()
        if fam_ok:
            any_ok = True
            out.extend(fam_out)
    return out if any_ok else None


# ------------------------------------------------------- /proc/net fallback
def _parse_proc_net(path: str, v6: bool) -> list:
    out = []
    try:
        with open(path) as f:
            lines = f.readlines()[1:]
    except OSError:
        return out
    for line in lines:
        p = line.split()
        if len(p) < 10:
            continue
        try:
            laddr, lport = p[1].rsplit(":", 1)
            raddr, rport = p[2].rsplit(":", 1)
            state = int(p[3], 16)
            uid = int(p[7])
            inode = int(p[9])
            rxq, txq = p[4].rsplit(":", 1)
            if v6:
                # 4 native-endian 32-bit groups
                src = b"".join(bytes.fromhex(laddr[i:i + 8])[::-1]
                               for i in range(0, 32, 8))
                dst = b"".join(bytes.fromhex(raddr[i:i + 8])[::-1]
                               for i in range(0, 32, 8))
            else:
                src = _map4(bytes.fromhex(laddr)[::-1])
                dst = _map4(bytes.fromhex(raddr)[::-1])
            out.append(SockEntry(src, int(lport, 16), dst,
                                 int(rport, 16), state, inode, uid,
                                 int(rxq, 16), int(txq, 16)))
        except (ValueError, IndexError):
            continue
    return out


def list_tcp_proc() -> list:
    return (_parse_proc_net("/proc/net/tcp", False)
            + _parse_proc_net("/proc/net/tcp6", True))


# ------------------------------------------------------------- /proc pids
def inode_owners(inodes: set) -> dict:
    """{socket inode: (pid, comm)} via one bounded /proc fd walk (the
    reference resolves socket→task the same way outside eBPF,
    ``common/gy_socket_stat.cc`` diag→task matching)."""
    out: dict[int, tuple] = {}
    if not inodes:
        return out
    try:
        pids = [d for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return out
    for pid in pids:
        fd_dir = f"/proc/{pid}/fd"
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            continue
        comm = None
        for fd in fds:
            try:
                tgt = os.readlink(f"{fd_dir}/{fd}")
            except OSError:
                continue
            if not tgt.startswith("socket:["):
                continue
            try:
                ino = int(tgt[8:-1])
            except ValueError:
                continue
            if ino in inodes and ino not in out:
                if comm is None:
                    try:
                        with open(f"/proc/{pid}/comm") as f:
                            comm = f.read().strip()[:16]
                    except OSError:
                        comm = "?"
                out[ino] = (int(pid), comm)
        if len(out) == len(inodes):
            break
    return out


# -------------------------------------------------------------- conntrack
def conntrack_nat_map(path: str = "/proc/net/nf_conntrack",
                      max_lines: int = 65536) -> dict:
    """{(cli_ip, cli_port, ser_ip, ser_port): (nat_cli.., nat_ser..)}
    for entries whose reply tuple shows address translation."""
    out: dict = {}
    try:
        with open(path) as f:
            lines = f.readlines()[:max_lines]
    except OSError:
        return out
    import ipaddress
    for line in lines:
        if " tcp " not in line:
            continue
        kv: dict[str, list] = {}
        for tok in line.split():
            if "=" in tok:
                k, v = tok.split("=", 1)
                kv.setdefault(k, []).append(v)
        try:
            o_src, o_dst = kv["src"][0], kv["dst"][0]
            o_sp, o_dp = int(kv["sport"][0]), int(kv["dport"][0])
            r_src, r_dst = kv["src"][1], kv["dst"][1]
            r_sp, r_dp = int(kv["sport"][1]), int(kv["dport"][1])
        except (KeyError, IndexError, ValueError):
            continue
        if (r_src, r_sp, r_dst, r_dp) == (o_dst, o_dp, o_src, o_sp):
            continue                      # no translation

        def ip16(s):
            return ipaddress.ip_address(s).packed.rjust(16, b"\x00") \
                if ":" in s else _map4(ipaddress.ip_address(s).packed)

        key = (ip16(o_src), o_sp, ip16(o_dst), o_dp)
        # post-NAT server = reply source; post-NAT client = reply dest
        out[key] = (ip16(r_dst), r_dp, ip16(r_src), r_sp)
    return out


# ---------------------------------------------------------------- collector
def listener_glob_id(machine_id: int, addr: bytes, port: int) -> int:
    """Stable nonzero 64-bit listener id (survives agent restarts —
    the role of the reference's listener shm glob ids)."""
    gid = H.hash_bytes_np(
        b"L" + machine_id.to_bytes(8, "little") + addr
        + port.to_bytes(2, "little"))
    return gid or 1


_ANY6 = b"\x00" * 16
_ANY4 = _map4(b"\x00" * 4)
_V4PFX = b"\x00" * 10 + b"\xff\xff"
_LOOP6 = b"\x00" * 15 + b"\x01"


def _is_loopback_pair(cli_addr: bytes, ser_addr: bytes) -> bool:
    """Both ends on this host: same address, 127/8, or ::1."""
    def is_lo(a: bytes) -> bool:
        return (a == _LOOP6
                or (a[:12] == _V4PFX and a[12] == 127))
    return cli_addr == ser_addr or (is_lo(cli_addr) and is_lo(ser_addr))


class TcpConnCollector:
    """15s-cadence sweep of this host's real TCP world → wire records.

    ``sweep()`` → dict with keys ``conns`` (TCP_CONN_DT), ``listeners``
    (LISTENER_STATE_DT), ``listener_info`` (new listeners only),
    ``names`` (NAME_INTERN_DT), each a record array ready for
    ``wire.encode_frame``.
    """

    def __init__(self, host_id: int = 0, machine_id: int = 1,
                 use_netlink: bool = True, conntrack: bool = True):
        self.host_id = host_id
        self.machine_id = machine_id
        self.use_netlink = use_netlink
        self.conntrack = conntrack
        self._known_listeners: dict = {}   # (addr,port) -> (glob_id, comm)
        self._conn_prev: dict = {}         # key -> [acked, recvd, t0us, pre]
        self._first_sweep = True

    # -- live-capture targeting --------------------------------------
    def listener_ports(self, gids) -> set:
        """TCP ports of the given listener glob ids (the live-capture
        port filter; one registry owns the (addr, port) → gid shape)."""
        return {port for (_a, port), (gid, _c)
                in self._known_listeners.items() if gid in gids}

    def resolve_listener(self, addr16: bytes, port: int,
                         gids=None) -> Optional[int]:
        """(captured server addr, port) → listener glob id.

        Exact (addr, port) match wins; otherwise a wildcard-bound
        listener on the port (0.0.0.0/:: — the common case, and the
        reason port-only inversion would misattribute dual-stack
        listeners); otherwise any listener on the port. Restricted to
        ``gids`` when given so an untraced listener sharing the port
        can never claim traced records."""
        if len(addr16) == 4:          # pcap v4 → v4-mapped (registry
            addr16 = b"\x00" * 10 + b"\xff\xff" + addr16   # format)
        best = None
        for (a, p), (gid, _c) in self._known_listeners.items():
            if p != port or (gids is not None and gid not in gids):
                continue
            if a == addr16:
                return gid
            if a in (b"\x00" * 16,
                     b"\x00" * 10 + b"\xff\xff" + b"\x00" * 4):
                best = gid                         # wildcard bind
            elif best is None:
                best = gid
        return best

    # -- one sweep ---------------------------------------------------
    def _snapshot(self) -> tuple:
        """→ (sockets, have_bytes). have_bytes is False on the /proc
        fallback — byte baselines must NOT be clobbered then, or the
        next netlink sweep would bill a conn's whole lifetime as one
        delta."""
        if self.use_netlink:
            socks = list_tcp_netlink()
            if socks is not None:
                return socks, True
        return list_tcp_proc(), False

    def sweep(self) -> dict:
        now_us = int(time.time() * 1e6)
        socks, have_bytes = self._snapshot()
        listeners = [s for s in socks if s.state == TCP_LISTEN]
        estab = [s for s in socks if s.state == TCP_ESTABLISHED]
        nat = conntrack_nat_map() if self.conntrack else {}
        # evict listeners that stopped listening (their LISTENER_STATE
        # rows stop; a reappearance re-announces LISTENER_INFO)
        cur_lkeys = {(s.saddr, s.sport) for s in listeners}
        for k in [k for k in self._known_listeners
                  if k not in cur_lkeys]:
            del self._known_listeners[k]

        # listener identity + (pid, comm) for NEW listeners only (the
        # /proc fd walk is the expensive part; known ones are cached)
        lmap: dict = {}                    # port -> [(addr, glob_id)]
        new_listeners = []
        need_inodes = set()
        for s in listeners:
            k = (s.saddr, s.sport)
            known = self._known_listeners.get(k)
            if known is None:
                gid = listener_glob_id(self.machine_id, s.saddr, s.sport)
                new_listeners.append((s, gid))
                need_inodes.add(s.inode)
            else:
                gid = known[0]
            lmap.setdefault(s.sport, []).append((s.saddr, gid))
        owners = inode_owners(need_inodes) if need_inodes else {}

        names: list = []
        li_recs = np.zeros(len(new_listeners), wire.LISTENER_INFO_DT)
        for i, (s, gid) in enumerate(new_listeners):
            pid, comm = owners.get(s.inode, (0, "?"))
            self._known_listeners[(s.saddr, s.sport)] = (gid, comm)
            comm_id = InternTable.intern(comm, wire.NAME_KIND_COMM)
            # service display name: comm:port — unique per listener and
            # human-readable (the reference uses comm + resolved domain)
            svc_name = f"{comm}:{s.sport}"
            names += [(wire.NAME_KIND_COMM, comm_id, comm),
                      (wire.NAME_KIND_SVC, gid, svc_name)]
            r = li_recs[i]
            r["glob_id"] = gid
            r["addr"]["ip"] = np.frombuffer(s.saddr, np.uint8)
            r["addr"]["port"] = s.sport
            r["tusec_start"] = now_us
            r["comm_id"] = comm_id
            r["cmdline_id"] = comm_id
            r["related_listen_id"] = gid
            r["pid"] = pid
            r["is_any_ip"] = s.saddr in (_ANY6, _ANY4)
            r["host_id"] = self.host_id

        def match_listener(addr: bytes, port: int) -> int:
            for laddr, gid in lmap.get(port, ()):
                if laddr in (_ANY6, _ANY4) or laddr == addr:
                    return gid
            return 0

        # established conns: classify + byte deltas. The /proc fd walk
        # runs only for NEW outbound conns — known ones carry their
        # cached (pid, comm) in the prev entry.
        conn_rows = []
        per_listener: dict = {}      # gid -> [nconn, active, kin, kout]
        task_net: dict = {}          # aggr_task_id -> [kbytes, nconns]
        seen_keys = set()
        new_out_inodes = {
            s.inode for s in estab
            if s.inode and s.key not in self._conn_prev
            and not match_listener(s.saddr, s.sport)}
        out_owners = inode_owners(new_out_inodes) \
            if new_out_inodes else {}

        for s in estab:
            key = s.key
            seen_keys.add(key)
            prev = self._conn_prev.get(key)
            new = prev is None
            gid = match_listener(s.saddr, s.sport)
            if new:
                # [acked, recvd, t0us, pre-existing, pid, comm]
                prev = [0, 0, now_us, self._first_sweep, 0, ""]
                if not gid:
                    prev[4], prev[5] = out_owners.get(s.inode, (0, ""))
                self._conn_prev[key] = prev
            if have_bytes:
                d_acked = max(s.bytes_acked - prev[0], 0)
                d_recvd = max(s.bytes_received - prev[1], 0)
                prev[0], prev[1] = s.bytes_acked, s.bytes_received
            else:
                d_acked = d_recvd = 0
            st = per_listener.setdefault(gid, [0, 0, 0.0, 0.0]) \
                if gid else None
            if st is not None:
                st[0] += 1
                if d_acked or d_recvd or s.rqueue or s.wqueue:
                    st[1] += 1
                st[2] += d_recvd / 1024.0
                st[3] += d_acked / 1024.0
            elif prev[5]:
                # outbound with a known owner: per-process-group traffic
                # (feeds AGGR_TASK tcp_kbytes/tcp_conns via taskproc)
                tn = task_net.setdefault(
                    aggr_task_id_of(self.machine_id, prev[5]), [0.0, 0])
                tn[0] += (d_acked + d_recvd) / 1024.0
                tn[1] += 1
            if not (new or d_acked or d_recvd):
                continue                  # idle known conn: nothing new
            conn_rows.append(self._conn_record(
                s, gid, d_acked, d_recvd, prev, nat, now_us, names,
                close=False))

        # disappeared conns → close records
        gone = [k for k in self._conn_prev if k not in seen_keys]
        for key in gone:
            prev = self._conn_prev.pop(key)
            s = SockEntry(key[0], key[1], key[2], key[3],
                          TCP_ESTABLISHED, 0)
            gid = match_listener(s.saddr, s.sport)
            conn_rows.append(self._conn_record(
                s, gid, 0, 0, prev, nat, now_us, names, close=True))

        conns = (np.stack(conn_rows) if conn_rows
                 else np.empty(0, wire.TCP_CONN_DT))

        # per-listener 5s-equivalent state
        ls = np.zeros(len(self._known_listeners), wire.LISTENER_STATE_DT)
        for i, ((addr, port), (gid, _comm)) in enumerate(
                self._known_listeners.items()):
            r = ls[i]
            st = per_listener.get(gid, [0, 0, 0.0, 0.0])
            r["glob_id"] = gid
            r["nconns"], r["nconns_active"] = st[0], st[1]
            r["curr_kbytes_inbound"] = min(int(st[2]), 2**32 - 1)
            r["curr_kbytes_outbound"] = min(int(st[3]), 2**32 - 1)
            r["ntasks"] = 1
            r["curr_state"] = 2 if st[1] else 1    # OK / IDLE
            r["host_id"] = self.host_id

        self._first_sweep = False
        return {
            "conns": conns,
            "listeners": ls,
            "listener_info": li_recs,
            "names": InternTable.records(names) if names
            else np.empty(0, wire.NAME_INTERN_DT),
            # joins for the /proc task collector (same sweep cadence);
            # a comm owning several listeners joins to the SMALLEST gid
            # — deterministic across sweeps and agent restarts (gids
            # are stable hashes)
            "task_net": task_net,
            "listener_of_comm": self._listener_of_comm(),
        }

    def _listener_of_comm(self) -> dict:
        out: dict = {}
        for gid, comm in self._known_listeners.values():
            if comm and comm != "?":
                cur = out.get(comm)
                if cur is None or gid < cur:
                    out[comm] = gid
        return out

    def _conn_record(self, s: SockEntry, gid: int, d_acked: int,
                     d_recvd: int, prev: list, nat: dict,
                     now_us: int, names: list,
                     close: bool) -> np.ndarray:
        r = np.zeros((), wire.TCP_CONN_DT)
        inbound = gid != 0
        if inbound:
            cli_addr, cli_port = s.daddr, s.dport
            ser_addr, ser_port = s.saddr, s.sport
            # client-perspective bytes: what the client SENT is what we
            # (the server) received
            bsent, brcvd = d_recvd, d_acked
            r["ser_glob_id"] = gid
            r["ser_related_listen_id"] = gid
            r["flags"] = 2
        else:
            cli_addr, cli_port = s.saddr, s.sport
            ser_addr, ser_port = s.daddr, s.dport
            bsent, brcvd = d_acked, d_recvd
            r["flags"] = 1
            pid, comm = prev[4], prev[5]
            if comm:
                r["cli_pid"] = pid
                comm_id = InternTable.intern(comm, wire.NAME_KIND_COMM)
                r["cli_comm_id"] = comm_id
                names.append((wire.NAME_KIND_COMM, comm_id, comm))
                r["cli_task_aggr_id"] = aggr_task_id_of(
                    self.machine_id, comm)
        if _is_loopback_pair(cli_addr, ser_addr):
            r["flags"] |= 4
        r["cli"]["ip"] = np.frombuffer(cli_addr, np.uint8)
        r["cli"]["port"] = cli_port
        r["ser"]["ip"] = np.frombuffer(ser_addr, np.uint8)
        r["ser"]["port"] = ser_port
        natv = nat.get((cli_addr, cli_port, ser_addr, ser_port))
        if natv:
            r["nat_cli"]["ip"] = np.frombuffer(natv[0], np.uint8)
            r["nat_cli"]["port"] = natv[1]
            r["nat_ser"]["ip"] = np.frombuffer(natv[2], np.uint8)
            r["nat_ser"]["port"] = natv[3]
        r["tusec_start"] = prev[2]
        if close:
            r["tusec_close"] = now_us
        if prev[3]:
            r["flags"] |= 8               # pre-existing at first sweep
        r["bytes_sent"] = bsent
        r["bytes_rcvd"] = brcvd
        r["ser_sock_inode"] = s.inode & 0xFFFFFFFF
        r["host_id"] = self.host_id
        return r


def aggr_task_id_of(machine_id: int, comm: str) -> int:
    """Stable process-group id: (machine, comm) → nonzero u64. The
    reference aggregates tasks the same way — a hash over comm +
    cgroup identity (``common/gy_task_handler.h:180``); shared by this
    collector and the /proc task collector so conn→task joins line up."""
    tid = H.hash_bytes_np(
        b"T" + machine_id.to_bytes(8, "little") + comm.encode())
    return tid or 1
