"""GytServer: the TCP serving edge (asyncio, COMM_HEADER framing).

The role of madhava's accept + L1 threads and shyama's registrar in one
single-controller process (ref ``server/gy_mconnhdlr.cc:2430-2520`` recv/
frame loop; ``server/gy_shconnhdlr.cc:7463`` partha registration,
``:5876`` placement): agents connect, register their machine-id (version
gated, ``common/gy_comm_proto.h:55-56``), get a sticky dense ``host_id``,
and stream EVENT_NOTIFY frames that drain straight into ``Runtime.feed``;
query clients multiplex JSON queries over the same framing (``QUERY_CMD``/
``QUERY_RESPONSE``, :502,536).

Connection roles commit at registration (the CLI_TYPE_E discipline,
``gy_comm_proto.h:91-99``): an event conn switches to bulk reads — every
``read()`` hands whatever bytes arrived to ``Runtime.feed``, which owns
framing, partial-frame resume and the staged K-slab fold path, so the
per-frame work stays in the native deframer, not in Python. A query conn
stays frame-at-a-time and answers each ``QUERY_CMD`` with a framed JSON
response (seqid echoed).

Concurrency model: one asyncio loop owns the Runtime — the TPU device
pipeline is the parallelism (no L2 worker pools).
"""

from __future__ import annotations

import asyncio
import json
import logging
import pathlib
from typing import Optional

import numpy as np

from gyeeta_tpu import version
from gyeeta_tpu.ingest import refproto, refquery, wire
from gyeeta_tpu.runtime import Runtime

log = logging.getLogger("gyeeta_tpu.net")

_HSZ = wire.HEADER_DT.itemsize
_READ_SZ = 1 << 20


class _ConnReaped(Exception):
    """A conn deadline fired (handshake / idle / write); the counter
    was already bumped — callers just unwind and close."""

    def __init__(self, kind: str):
        super().__init__(f"conn reaped ({kind} deadline)")
        self.kind = kind


class GytServer:
    def __init__(self, rt: Runtime, host: str = "127.0.0.1",
                 port: int = 0, tick_interval: Optional[float] = 5.0,
                 hostmap_path: Optional[str] = None,
                 record_path: Optional[str] = None,
                 advertise_host: Optional[str] = None,
                 feed_pipeline: bool = False,
                 handshake_timeout: float = 10.0,
                 idle_timeout: Optional[float] = None,
                 write_timeout: float = 10.0,
                 frame_error_budget: int = 8,
                 throttle_hold_ms: int = 1500,
                 throttle_lag_s: float = 0.75,
                 throttle_pending_mb: float = 32.0,
                 throttle_slab_frac: float = 0.85,
                 throttle_ring_frac: float = 0.75,
                 query_workers: Optional[int] = None,
                 query_queue_max: Optional[int] = None,
                 query_snapshot: Optional[bool] = None,
                 shard_ingest: bool = False,
                 shard_queue_mb: float = 8.0,
                 ingest_procs: int = 1,
                 sub_persist: Optional[str] = None,
                 relay_port: Optional[int] = None,
                 relay_host: str = "0.0.0.0"):
        self.rt = rt
        self.host = host
        self.port = port
        # the madhava address handed to stock parthas in
        # PS_REGISTER_RESP_S: a wildcard bind is not dialable, so it
        # falls back to the machine's hostname (configure explicitly
        # when parthas reach the server through NAT/a service VIP)
        import socket as _socket
        self.advertise_host = advertise_host or (
            host if host not in ("", "0.0.0.0", "::") else
            _socket.gethostname())
        self.tick_interval = tick_interval
        # ---- conn deadlines (the slow-loris / half-open hardening):
        # handshake_timeout bounds the registration phase (any role);
        # idle_timeout reaps silent conns — default tied to the
        # expected sweep cadence (agents sweep every ~tick_interval, so
        # 12 missed sweeps = dead); write_timeout bounds control pushes
        # into a non-draining peer; frame_error_budget closes a query
        # conn after N recoverable frame-level errors. Every reap or
        # reject lands on a labeled counter (conn_timeouts|kind=...,
        # frames_rejected|reason=...) rendered in /metrics.
        self.handshake_timeout = handshake_timeout
        # ---- admission control (server→agent backpressure): when the
        # durable-ingest tier falls behind — journal fsync lag past
        # throttle_lag_s, unsynced WAL bytes past throttle_pending_mb,
        # staged-slab occupancy past throttle_slab_frac, or the
        # droppressure vector active — push a COMM_THROTTLE telling
        # agents to hold feeds in their PR-4 spool for throttle_hold_ms.
        # Priority-aware (PSketch, PAPERS.md): trace/pcap first
        # (FEED_TRACE), everything only under engine drop pressure
        # (FEED_ALL) — health classification degrades last.
        # throttle_hold_ms=0 disables the controller.
        self.throttle_hold_ms = int(throttle_hold_ms)
        self.throttle_lag_s = float(throttle_lag_s)
        self.throttle_pending_mb = float(throttle_pending_mb)
        self.throttle_slab_frac = float(throttle_slab_frac)
        # worker-ring backlog (multi-process ingest, ROADMAP
        # control-plane item c): occupancy past throttle_ring_frac
        # trips the trace throttle, ≥0.95 holds EVERYTHING — throttle
        # the agents BEFORE the drop-oldest rings shed records
        self.throttle_ring_frac = float(throttle_ring_frac)
        self._throttle_level = 0          # 0=off, 1=trace, 2=all
        if idle_timeout is None:
            idle_timeout = max(30.0, 12.0 * tick_interval) \
                if tick_interval else 60.0
        self.idle_timeout = idle_timeout if idle_timeout > 0 else None
        self.write_timeout = write_timeout
        self.frame_error_budget = frame_error_budget
        # optional wire capture (utils/replay.py): every complete-frame
        # run fed to the runtime is also appended to the capture file
        self._recorder = None
        if record_path:
            from gyeeta_tpu.utils.replay import StreamRecorder
            self._recorder = StreamRecorder(record_path)
        self._server: Optional[asyncio.AbstractServer] = None
        self._tick_task: Optional[asyncio.Task] = None
        # optional liveness watchdog (utils/crashguard.TickWatchdog):
        # beaten after each successful tick; the daemon arms it
        self.watchdog = None
        # machine-id → host_id stickiness (the pardbmap_ placement map,
        # gy_shconnhdlr.cc:5876); optionally persisted across restarts
        self._hostmap_path = pathlib.Path(hostmap_path) \
            if hostmap_path else None
        self.hostmap: dict[int, int] = self._load_hostmap()
        # host_id → event-conn writer: the reverse-direction channel for
        # server→agent control (trace capture enable/disable — the
        # reference's CLI_TYPE_RESP_REQ conns carry this, gy_comm_proto.h)
        self._event_writers: dict[int, asyncio.StreamWriter] = {}
        self._open_conns: set = set()      # every live conn's writer
        self._conn_seq = 0                 # dense conn ids (WAL
        #                                    attribution: torn tails
        #                                    name their conn)
        # optional L1/L2 decode pipeline (multi-core hosts): deframe
        # runs on a worker thread; tick/query paths barrier through
        # _feed_barrier so no submitted bytes are invisible at a
        # cadence or query boundary
        # stock LISTENER_DOMAIN payloads awaiting svcreg resolution
        self._pending_domains: dict = {}
        self._pipe = None
        if feed_pipeline:
            from gyeeta_tpu.ingest.pipeline import FeedPipeline
            # the recorder moves INTO the pipeline: only buffers that
            # decoded cleanly get recorded (replayability; see the
            # pipeline docstring for the poison-frame divergence)
            self._pipe = FeedPipeline(rt, recorder=self._recorder)
        # --shards mode: per-shard ingest loops between the conn
        # handlers and the mesh runtime (net/shardfeed.py). Mutually
        # exclusive with the decode pipeline — the feeder owns the
        # handoff.
        self._feeder = None
        if shard_ingest and getattr(rt, "n", 1) > 1:
            if self._pipe is not None:
                raise ValueError(
                    "--feed-pipeline and shard ingest are mutually "
                    "exclusive (the shard feeder owns the handoff)")
            from gyeeta_tpu.net.shardfeed import ShardFeeder
            self._feeder = ShardFeeder(rt, queue_max_mb=shard_queue_mb)
        # ---- multi-process ingest edge (net/ingestproc.py): N worker
        # processes own wire validation + deframe/decode + WAL append
        # for their sticky shard groups and publish decoded record
        # batches into shared-memory rings; this process keeps the ONE
        # listener + registration and drains the rings into the fold.
        # ingest_procs <= 1 (the default) spawns nothing — byte-for-
        # byte today's in-process path.
        self._ingest = None
        self._ingest_tasks: list = []
        if ingest_procs and int(ingest_procs) > 1:
            if getattr(rt, "n", 1) < int(ingest_procs):
                raise ValueError(
                    f"--ingest-procs {ingest_procs} needs --shards >= "
                    f"{ingest_procs} (one worker owns at least one "
                    "whole shard group)")
            from gyeeta_tpu.net.ingestproc import IngestSupervisor, \
                ProcWalView
            self._ingest = IngestSupervisor(
                rt, int(ingest_procs),
                journal_dir=rt.opts.journal_dir,
                idle_timeout=self.idle_timeout)
            if rt.journal is not None:
                # the WORKERS own the WAL writers from here: release
                # this process's segment handles (restore/replay used
                # them already — Daemon builds the server after
                # recovery) and swap in the cross-process view so
                # checkpoint/truncate/compactor handoff keep working
                rt.journal.close()
                rt.journal = ProcWalView(
                    self._ingest, rt.opts.journal_dir,
                    getattr(rt, "n", 1), stats=rt.stats,
                    subdir_fmt=getattr(
                        getattr(rt, "layout", None), "WAL_SUBDIR_FMT",
                        "shard_{:02d}"))
        # ---- remote ingest relay hub (net/relay.py): accepts REMOTE
        # relay uplinks carrying the shm-ring contract over TCP —
        # decoded batches with cumulative per-shard record chains, so
        # published == consumed + counted drops holds across machines.
        # Registration RPCs land on the SAME sticky hostmap; the relay
        # owns its WAL on its own host. relay_port=None binds nothing.
        self._relay = None
        if relay_port is not None:
            from gyeeta_tpu.net.relay import RelayHub
            self._relay = RelayHub(rt, self._relay_register,
                                   host=relay_host, port=relay_port)
        # stock-partha registration state: machine-id → the ident key
        # issued at PS_REGISTER (the SM_PARTHA_IDENT_NOTIFY flow,
        # gy_comm_proto.h:946 — shyama hands the key to madhava; the
        # single controller holds both roles so a dict suffices)
        self._ref_idents: dict[int, int] = {}
        # stable madhava id presented to stock parthas (sticky across
        # a process run; parthas compare it on reconnect)
        import secrets as _sec
        self._madhava_id = _sec.randbits(63) | 1
        # NM query edge (node-webserver conns, net/nmhandle.py): sticky
        # conn identity per (hostname, port) + live-conn gauge
        self._nm_idents: dict[tuple, object] = {}
        self._nm_conns_live = 0
        # ---- snapshot-isolated query serving (query/snapshot.py +
        # net/qexec.py): live queries on ANY edge default to reading
        # the last published per-tick snapshot on a bounded worker
        # pool — the fold never waits on a dashboard and a dashboard
        # never waits on the fold. CRUD, multiquery, historical SQL
        # and explicit consistency=strong requests stay inline on the
        # loop (they mutate live structures / need the live handle).
        from gyeeta_tpu.net import qexec as _qexec
        self.query_snapshot = (_qexec.snapshot_serving_enabled()
                               if query_snapshot is None
                               else bool(query_snapshot))
        self.qexec = _qexec.QueryExecutor(rt, workers=query_workers,
                                          queue_max=query_queue_max)
        # ---- streaming subscriptions (net/subs.py): clients register
        # a query ONCE (COMM_SUBSCRIBE_CMD on the GYT edge; the REST
        # gateway relays /v1/subscribe onto it) and the tick loop
        # pushes per-tick row deltas — render once, diff once, push to
        # every subscriber of that normalized query
        from gyeeta_tpu.net.subs import SubscriptionHub
        self.subs = SubscriptionHub(self._sub_fetch, rt.stats,
                                    persist_path=sub_persist)

    async def _sub_fetch(self, req: dict) -> dict:
        """Subscription render: the same admission-controlled off-loop
        snapshot path every poll query rides (``net/qexec.py``)."""
        return await self.qexec.run(req)

    async def push_subscriptions(self) -> int:
        """Push per-tick subscription deltas (called by the tick loop
        after ``run_tick``; tests drive it directly after manual
        ticks). Returns events delivered."""
        if not self.subs.nsubs:
            return 0
        return await self.subs.push_tick()

    def _nm_register(self, hostname: str, port: int):
        """Sticky NM conn identity for a node (hostname, port) pair —
        reconnects get the same conn_id (the reference's per-node conn
        object). Bounded like the partha ident map."""
        from gyeeta_tpu.net import nmhandle
        key = (hostname, port)
        st = self._nm_idents.get(key)
        if st is None:
            if len(self._nm_idents) >= 4 * self.rt.cfg.n_hosts + 64:
                self._nm_idents.clear()      # epoch reset, re-learns
            st = nmhandle.NMConnState(hostname, port,
                                      len(self._nm_idents) + 1)
            self._nm_idents[key] = st
        return st

    # -------------------------------------------------------- registration
    def _load_hostmap(self) -> dict:
        if self._hostmap_path and self._hostmap_path.exists():
            raw = json.loads(self._hostmap_path.read_text())
            return {int(k): int(v) for k, v in raw.items()}
        return {}

    def _save_hostmap(self) -> None:
        if self._hostmap_path:
            tmp = self._hostmap_path.with_suffix(".tmp")
            tmp.write_text(json.dumps(
                {str(k): v for k, v in self.hostmap.items()}))
            tmp.replace(self._hostmap_path)

    def _register(self, req: np.ndarray) -> tuple[int, int]:
        """REGISTER_REQ record → (status, host_id)."""
        ver = int(req["wire_version"])
        if ver < version.MIN_WIRE_VERSION:
            return wire.REG_ERR_VERSION, 0
        if int(req["conn_type"]) != wire.CONN_EVENT:
            return wire.REG_OK, 0xFFFFFFFF    # query conns hold no host slot
        mid = (int(req["machine_id_hi"]) << 64) | int(req["machine_id_lo"])
        return self._host_for_machine(mid)

    def _host_for_machine(self, mid: int) -> tuple[int, int]:
        """Sticky machine-id → dense host_id allocation (shared by the
        GYT and stock-partha registration paths)."""
        hid = self.hostmap.get(mid)
        if hid is not None:
            # a known machine re-registering IS a reconnect — the
            # server-side half of the supervision story (the agent's
            # spool counters arrive separately as NOTIFY_AGENT_STATS)
            self.rt.stats.bump("agent_reconnects")
        if hid is None:
            if len(self.hostmap) >= self.rt.cfg.n_hosts:
                return wire.REG_ERR_CAPACITY, 0
            used = set(self.hostmap.values())
            hid = next(i for i in range(self.rt.cfg.n_hosts)
                       if i not in used)
            self.hostmap[mid] = hid
            self._save_hostmap()
            self.rt.stats.bump("agents_registered")
            self.rt.notifylog.add(
                f"agent registered: machine {mid:032x} -> host {hid}",
                source="agent")
        return wire.REG_OK, hid

    def _relay_register(self, mid: int, conn_type: int,
                        ver: int) -> tuple[int, int, int]:
        """Registration RPC from a remote ingest relay → (status,
        host_id, last_seq). Same gates + sticky hostmap as the local
        handshake, so an agent's identity survives moving between a
        direct conn and any relay."""
        if ver < version.MIN_WIRE_VERSION:
            return wire.REG_ERR_VERSION, 0, 0
        if conn_type != wire.CONN_EVENT:
            return wire.REG_OK, 0xFFFFFFFF, 0
        status, hid = self._host_for_machine(mid)
        last_seq = 0
        if status == wire.REG_OK:
            last_seq = int(getattr(self.rt, "_sweep_last_seq",
                                   {}).get(hid, 0))
        return status, hid, last_seq

    _DOMAIN_MAX_PENDING = 8192
    _DOMAIN_MAX_AGE_TICKS = 12

    def _drain_ref_session(self, sess) -> None:
        """Route frameless stock-partha payloads collected by the
        adapter session: agent NOTIFICATION_MSGs → the notifymsg ring;
        LISTENER_DOMAIN names queue for tick-time resolution (the
        referenced LISTENER_INFO may still ride the decode pipeline —
        resolving inline would force a pipeline barrier per batch)."""
        if sess.notifications:
            msgs, sess.notifications = sess.notifications, []
            for ntype, msg in msgs:
                self.rt.notifylog.add(msg, ntype=ntype, source="agent")
        if sess.domains:
            doms, sess.domains = sess.domains, []
            for gid, dom, _tag in doms:
                if dom and len(self._pending_domains) < \
                        self._DOMAIN_MAX_PENDING:
                    self._pending_domains[gid] = (dom, 0)
        if sess.nat_conns:
            nats, sess.nat_conns = sess.nat_conns, []
            for recs in nats:
                # VIP/NAT registry only — never engine-fed
                self.rt.natclusters.observe_conns(recs)
        if sess.n_events:
            evs = sess.n_events
            sess.n_events = type(evs)()
            for subtype, cnt in evs.items():
                self.rt.stats.bump(f"ref_evt_0x{subtype:x}", cnt)
        if sess.n_skipped:
            # distinct from frames_ref_skipped (pre-registration
            # handshake skips): this counts post-adapt whole-frame
            # skips (unknown subtype / non-NOTIFY / truncated)
            self.rt.stats.bump("ref_unadapted_frames", sess.n_skipped)
            sess.n_skipped = 0

    def _resolve_pending_domains(self) -> None:
        """Tick-cadence domain resolution (after run_tick: the feed
        barrier already ran). Unresolvable entries retry for a few
        ticks — a listener announced slightly later still gets its
        domain — then drop COUNTED, not silently."""
        if not self._pending_domains:
            return
        nxt: dict = {}
        for gid, (dom, age) in self._pending_domains.items():
            info = self.rt.svcreg.get(gid)
            if info is not None:
                self.rt.dns.prime(info["ip"], dom)
            elif age + 1 < self._DOMAIN_MAX_AGE_TICKS:
                nxt[gid] = (dom, age + 1)
            else:
                self.rt.stats.bump("ref_domains_unresolved")
        self._pending_domains = nxt

    # ----------------------------------------------------------- feed path
    def _feed(self, buf: bytes, hid: int = 0, conn_id: int = 0) -> int:
        """Ingest complete-frame bytes: through the decode pipeline
        when enabled, else directly. ``hid``/``conn_id`` attribute the
        bytes in the write-ahead journal."""
        if self._feeder is not None:
            return self._feeder.submit(buf, hid=hid, conn_id=conn_id)
        if self._pipe is not None:
            return self._pipe.feed(buf, hid=hid, conn_id=conn_id)
        return self.rt.feed(buf, hid=hid, conn_id=conn_id)

    def _feed_barrier(self) -> None:
        """Make every submitted byte visible (pipeline / shard-queue /
        ingest-ring barrier) before a tick or query reads state. With
        ingest workers this drains what the rings HOLD — bytes still
        inside a worker's deframe loop surface next barrier (the
        cross-process analogue of a conn's partial frame)."""
        if self._ingest is not None:
            self._ingest.drain()
        if self._feeder is not None:
            self._feeder.flush_pending()
        if self._pipe is not None:
            self._pipe.flush()

    # ---------------------------------------------------- query routing
    def _inline_query(self, req: dict) -> bool:
        """True when the request must run inline on the loop: CRUD and
        multiquery mutate/compose against live structures, relational
        tstart/tend history reads a thread-bound DB handle, shard-tier
        at=/window= requests materialize through the runtime's shared
        TimeView, and an explicit ``consistency=strong`` asked for the
        flush-then-read semantics (tests / ``nm probe``)."""
        if not self.query_snapshot:
            return True
        if req.get("op") or "multiquery" in req:
            return True
        if req.get("consistency") == "strong":
            return True
        return any(k in req for k in ("at", "window", "tstart", "tend"))

    async def run_query(self, req: dict) -> dict:
        """One query request → response dict, shared by the GYT query
        loop and the NM edge (the REST gateway rides the GYT loop).
        Snapshot-eligible queries run OFF-loop on the executor with
        admission control; everything else keeps the original inline
        strong path (feed barrier + live read). Raises
        :class:`~gyeeta_tpu.net.qexec.Overloaded` on shed."""
        if self._inline_query(req):
            self._feed_barrier()
            return self.rt.query(req)
        return await self.qexec.run(req)

    # ------------------------------------------------------------- serving
    async def start(self) -> tuple[str, int]:
        # snapshot serving needs a snapshot BEFORE the first tick: the
        # bootstrap publish happens here on the loop, so query worker
        # threads never publish (they'd race the feed path)
        if self.query_snapshot and getattr(self.rt, "snapshot",
                                           None) is None:
            self.rt.publish_snapshot()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.host, self.port = sock[0], sock[1]
        if self._feeder is not None:
            self._feeder.start()
        if self._ingest is not None:
            self._ingest.start(asyncio.get_running_loop())
            self._ingest_tasks = [
                asyncio.create_task(self._ingest_drain_loop()),
                asyncio.create_task(self._ingest_monitor_loop())]
        if self._relay is not None:
            await self._relay.start()
        if self.tick_interval:
            self._tick_task = asyncio.create_task(self._tick_loop())
        log.info("gyt server on %s:%d", self.host, self.port)
        return self.host, self.port

    async def _ingest_drain_loop(self) -> None:
        """Pull decoded record batches out of the worker rings into
        the staging slabs. Adaptive cadence: drain again immediately
        while records flow, back off to the poll interval when idle
        (an empty drain reads one head word per ring)."""
        from gyeeta_tpu.net import ingestproc
        iv = ingestproc.drain_interval_s()
        while True:
            try:
                n = self._ingest.drain()
            except Exception:                  # pragma: no cover
                log.exception("ingest ring drain failed")
                n = 0
            await asyncio.sleep(0.0 if n else iv)

    async def _ingest_monitor_loop(self) -> None:
        """Worker liveness + metrics cadence: respawn dead/wedged
        workers onto their sticky shard groups, publish the
        gyt_ingest_proc_* counter/gauge rows."""
        while True:
            await asyncio.sleep(1.0)
            try:
                self._ingest.poll()
            except Exception:                  # pragma: no cover
                log.exception("ingest worker monitor failed")

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
            self._tick_task = None
        if self._relay is not None:
            # stop accepting relay batches before the runtime winds
            # down (a batch landing mid-close would stage into a
            # closing runtime); shutdown is not relay loss — no epoch
            # finalize, the relays reconnect to the restarted hub
            await self._relay.stop()
        if self._server:
            self._server.close()
            # force-close live conns BEFORE wait_closed: since 3.12.1
            # Server.wait_closed waits for every active handler, and a
            # stopping server must not wait on agents that never hang
            # up (the crash/restart path drops them; they reconnect)
            for w in list(self._open_conns):
                w.close()
            await self._server.wait_closed()
            self._server = None
        if self._recorder is not None:
            rec, self._recorder = self._recorder, None
            rec.close()      # live conns see None, never a closed file
        self.subs.close()    # flush + close the continuation ring file
        if self._ingest is not None:
            # graceful worker drain BEFORE the runtime closes: workers
            # stop their conns, fsync + close their WALs and report
            # final positions; every ring slot is folded before stop()
            # returns — the final checkpoint supersedes the whole WAL
            # window (the SIGTERM drain contract, tested with
            # --ingest-procs 2 in tests/test_ingestproc.py)
            for t in self._ingest_tasks:
                t.cancel()
            self._ingest_tasks = []
            self._ingest.stop()
            self._ingest.close()     # rings unlinked (positions cached)
        if self._feeder is not None:
            await self._feeder.stop()    # drain queued runs, then fold
        if self._pipe is not None:
            self._pipe.close()           # barrier + worker shutdown
        self.qexec.close()   # query worker pool (no new snapshot reads)
        self.rt.close()      # alert delivery worker + history handle

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick_interval)
            try:
                self._feed_barrier()
                self.rt.run_tick()
                if self._ingest is not None:
                    # workers stamp WAL chunks with the window tick
                    # (replay merge order + compactor window evidence)
                    self._ingest.broadcast_tick(self.rt._tick_no)
                if self._relay is not None:
                    # remote relays stamp THEIR WALs with the same tick
                    self._relay.broadcast_tick(self.rt._tick_no)
                self._resolve_pending_domains()
                await self.push_trace_control()
                await self.push_throttle()
                await self.push_subscriptions()
                if self.watchdog is not None:
                    self.watchdog.beat()      # liveness heartbeat
            except Exception:                     # pragma: no cover
                log.exception("tick failed")

    # ------------------------------------------------- admission control
    def throttle_level(self) -> int:
        """Evaluate the durable-ingest pressure signals → 0 (open),
        1 (hold trace/pcap feeds), 2 (hold every sweep). Reads the
        gauges ``run_tick``'s one-readback health pass just refreshed
        — no extra device transfer."""
        if not self.throttle_hold_ms:
            return 0
        g = self.rt.stats.gauges
        # engine drop pressure: the engine is ALREADY shedding — hold
        # everything (spooled sweeps beat probe-failure garbage)
        if g.get("engine_drop_pressure"):
            return 2
        lvl = 0
        # worker-ring backlog (multi-process ingest): the rings are
        # drop-oldest — occupancy approaching full means the NEXT
        # burst sheds records, so agents must spool first. Head−tail
        # occupancy reads two shared-memory words per shard ring.
        if self._ingest is not None:
            frac = self._ingest.ring_backlog_frac()
            g_ = self.rt.stats.gauge
            g_("ingest_ring_backlog_frac", frac)
            if frac >= 0.95:
                return 2
            if frac > self.throttle_ring_frac:
                lvl = 1
        if g.get("journal_fsync_lag_seconds", 0.0) > self.throttle_lag_s:
            lvl = 1
        if g.get("journal_pending_bytes", 0.0) \
                > self.throttle_pending_mb * (1 << 20):
            lvl = 1
        # staged-slab occupancy: records accepted but not yet folded
        cap = max(1, (self.rt.cfg.conn_batch + self.rt.cfg.resp_batch)
                  * self.rt.cfg.fold_k)
        staged = (getattr(self.rt, "_n_conn_raw", 0)
                  + getattr(self.rt, "_n_resp_raw", 0))
        if staged / cap > self.throttle_slab_frac:
            lvl = 1
        return lvl

    async def push_throttle(self) -> int:
        """Admission-control push: (re)issue COMM_THROTTLE holds while
        pressure persists, release early when it clears. Every
        transition lands on ``throttle|feed=...`` (rendered as
        ``gyt_throttle_total{feed=...}``); the current level rides the
        ``throttle_state`` gauge. Returns frames pushed."""
        lvl = self.throttle_level()
        prev = self._throttle_level
        if lvl != prev:
            if lvl == 2:
                self.rt.stats.bump("throttle|feed=all")
            elif lvl == 1:
                self.rt.stats.bump("throttle|feed=trace")
            else:
                self.rt.stats.bump("throttle_released")
            self.rt.notifylog.add(
                f"admission control: throttle level {prev} -> {lvl} "
                f"(journal lag/pending, slab occupancy, droppressure)",
                ntype="warn" if lvl else "info", source="selfmon")
        self._throttle_level = lvl
        self.rt.stats.gauge("throttle_state", float(lvl))
        if lvl == 0 and prev == 0:
            return 0                      # steady open state: no frame
        # one frame always carries BOTH classes with their hold: a
        # level drop releases the no-longer-held class early (hold 0)
        # instead of waiting out its deadline on the agent
        frame = wire.encode_throttle_multi(
            ((wire.FEED_TRACE, self.throttle_hold_ms if lvl >= 1 else 0),
             (wire.FEED_ALL, self.throttle_hold_ms if lvl == 2 else 0)))
        n = 0
        for hid, w in list(self._event_writers.items()):
            try:
                w.write(frame)
                if self.write_timeout:
                    await asyncio.wait_for(w.drain(), self.write_timeout)
                else:
                    await w.drain()
                n += 1
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    TimeoutError):
                # a dead conn re-learns the hold on reconnect (the
                # controller re-pushes every tick while pressure holds)
                continue
        return n

    async def push_trace_control(self) -> int:
        """Evaluate tracedefs and push enable/disable diffs to the
        owning agents' event conns (the REQ_TRACE_SET distribution,
        ``gy_shconnhdlr.cc:1272`` → partha). Returns records pushed."""
        diffs = self.rt.trace_control_diff(
            hosts=list(self._event_writers))
        n = 0
        for hid, (enable, disable) in diffs.items():
            w = self._event_writers.get(hid)
            if w is None:
                continue
            ids = list(enable) + list(disable)
            flags = [1] * len(enable) + [0] * len(disable)
            try:
                w.write(wire.encode_trace_set(ids, flags))
                # write deadline: a non-draining agent (full socket
                # buffers, wedged peer) must not stall the tick loop —
                # reap the conn and re-emit the diff on reconnect
                if self.write_timeout:
                    await asyncio.wait_for(w.drain(), self.write_timeout)
                else:
                    await w.drain()
                n += len(ids)
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    TimeoutError) as e:
                if isinstance(e, (asyncio.TimeoutError, TimeoutError)) \
                        and not isinstance(e, OSError):
                    self.rt.stats.bump("conn_timeouts|kind=write")
                    w.close()     # half-dead conn: force the reconnect
                # the diff was already committed to the applied state;
                # a failed push that does NOT tear down the reader path
                # would leave the host silently out of sync. Restore the
                # pre-diff state so next tick re-emits the SAME diff
                # (forget_host would lose pending disables forever).
                self.rt.tracedefs.unapply(hid, enable, disable)
        if n:
            self.rt.stats.bump("trace_sets_pushed", n)
        return n

    async def _tread(self, coro, kind: str):
        """Await ``coro`` under the ``kind`` conn deadline. A fired
        deadline bumps ``conn_timeouts|kind=...`` and raises
        :class:`_ConnReaped` so the conn unwinds and closes without
        ever blocking the tick loop."""
        t = self.handshake_timeout if kind == "handshake" \
            else self.idle_timeout
        if not t:
            return await coro
        try:
            return await asyncio.wait_for(coro, t)
        except (asyncio.TimeoutError, TimeoutError):
            self.rt.stats.bump(f"conn_timeouts|kind={kind}")
            raise _ConnReaped(kind) from None

    async def _read_frame(self, reader, first: bytes = b""
                          ) -> tuple[int, bytes]:
        """→ (data_type, payload_bytes). Raises IncompleteReadError at
        EOF, FrameError (with reason) on poison headers — the shared
        validated reader (``ingest/wire.py:read_frame``); ``first``
        carries bytes already peeked off the stream."""
        return await wire.read_frame(reader, first)

    async def _ref_conn(self, reader, writer, first: bytes,
                        conn_id: int = 0) -> None:
        """Stock-partha connection: the gy_comm_proto registration
        handshake, then the reference NOTIFY stream via the adapter.

        The single controller plays BOTH reference roles
        (``gy_comm_proto.h:584-952``): a PS_REGISTER_REQ_S gets a
        PS_REGISTER_RESP_S pointing the partha at ourselves as its
        madhava (ident key issued here, the SM_PARTHA_IDENT_NOTIFY
        flow collapsed); a PM_CONNECT_CMD_S validates versions + the
        ident key, allocates the sticky host_id, replies
        PM_CONNECT_RESP_S, and hands the conn to the event loop —
        where ``refproto.adapt`` folds the notify stream natively.
        """
        import secrets
        import time as _time

        RP = refproto
        hdr_b = first + await self._tread(reader.readexactly(
            RP.REF_HEADER_DT.itemsize - len(first)), "handshake")
        while True:
            hdr = np.frombuffer(hdr_b, RP.REF_HEADER_DT, count=1)[0]
            if int(hdr["magic"]) not in RP.REF_MAGICS:
                raise wire.FrameError(
                    f"bad reference magic 0x{int(hdr['magic']):08x}",
                    reason="bad_magic")
            total = int(hdr["total_sz"])
            if total < len(hdr_b) or total >= wire.MAX_COMM_DATA_SZ:
                raise wire.FrameError(f"bad ref total_sz {total}",
                                      reason="bad_size")
            body = await self._tread(
                reader.readexactly(total - len(hdr_b)), "handshake")
            dtype = int(hdr["data_type"])
            now = int(_time.time())
            if dtype == RP.REF_COMM_PS_REGISTER_REQ:
                req = RP.parse_ps_register_req(body)
                err, es = self._ref_gate(req, "min_shyama_version")
                key = 0
                if not err:
                    mid = ((req["machine_id_hi"] << 64)
                           | req["machine_id_lo"])
                    # bound the unauthenticated-registration state:
                    # slack over n_hosts for churned machine ids, but
                    # no unbounded growth from random-id floods
                    if mid not in self._ref_idents and \
                            len(self._ref_idents) >= \
                            4 * self.rt.cfg.n_hosts:
                        err, es = 116, "max partha registrations"
                    else:
                        key = self._ref_idents.setdefault(
                            mid, secrets.randbits(63) | 1)
                writer.write(RP.encode_ps_register_resp(
                    err, es, self.advertise_host, self.port, key,
                    self._madhava_id, now))
                await writer.drain()
                if err:
                    self.rt.stats.bump("conns_ref_rejected")
                    return
                self.rt.stats.bump("ref_ps_registered")
                # the partha now dials its madhava (us) on new conns;
                # this shyama conn stays up for status traffic
            elif dtype == RP.REF_COMM_PM_CONNECT_CMD:
                req = RP.parse_pm_connect_cmd(body)
                err, es = self._ref_gate(req, "min_madhava_version")
                mid = ((req["machine_id_hi"] << 64)
                       | req["machine_id_lo"])
                host_id = 0
                if not err and self._ref_idents.get(mid) != \
                        req["partha_ident_key"]:
                    err, es = 113, ("unknown partha ident key - "
                                    "register with shyama first")
                if not err:
                    status, host_id = self._host_for_machine(mid)
                    if status != wire.REG_OK:
                        err, es = 116, "max partha hosts exceeded"
                writer.write(RP.encode_pm_connect_resp(
                    err, es, self._madhava_id, now))
                await writer.drain()
                if err:
                    self.rt.stats.bump("conns_ref_rejected")
                    return
                self.rt.stats.bump("ref_pm_connected")
                # conns_ref_adapted is counted by the event loop when
                # it sees the first reference-magic data (one count
                # per adapted conn, same as direct-stream ref conns)
                await self._event_loop(
                    reader, host_id,
                    ref_session=refproto.RefSession(
                        region=req.get("region_name", ""),
                        zone=req.get("zone_name", "")),
                    conn_id=conn_id)
                return
            elif dtype == refquery.REF_COMM_NM_CONNECT_CMD:
                # stock node webserver: the query edge (NM_CONNECT_CMD_S
                # → RESP_S handshake + QUERY_WEB_JSON / CRUD_*_JSON
                # loop, net/nmhandle.py)
                from gyeeta_tpu.net import nmhandle
                await nmhandle.serve_nm_conn(self, reader, writer, body)
                return
            else:
                # pre-registration frame of an unhandled type: skip it
                # whole (the reference's recv loop does the same for
                # unknown events)
                self.rt.stats.bump("frames_ref_skipped")
            hdr_b = await self._tread(
                reader.readexactly(RP.REF_HEADER_DT.itemsize),
                "handshake")

    def _ref_gate(self, req: dict, min_field: str) -> tuple[int, str]:
        """Version gates of the reference's validate_fields
        (``gy_comm_proto.h:55-56``): comm version must match ours;
        partha must be ≥ our floor; our version must satisfy the
        partha's floor. → (err_code, error_string)."""
        RP = refproto
        if req["comm_version"] != RP.REF_COMM_VERSION:
            return 101, (f"comm version {req['comm_version']} "
                         f"unsupported (need {RP.REF_COMM_VERSION})")
        if req["partha_version"] < RP.REF_MIN_PARTHA_VERSION:
            return 103, "partha version below minimum supported"
        if req.get(min_field, 0) > RP.REF_MADHAVA_VERSION:
            return 102, "server version below partha's minimum"
        return 0, ""

    async def _handle_conn(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        self._open_conns.add(writer)
        self._conn_seq += 1
        conn_id = self._conn_seq
        try:
            # peek the first header: a reference COMM_HEADER magic means
            # a STOCK PARTHA — route it through the gy_comm_proto
            # registration handshake instead of GYT registration.
            # The whole pre-registration phase runs under the handshake
            # deadline: a slow-loris peer (valid magic, header never
            # completed) is reaped, counted, and cannot pin a handler.
            try:
                first = await self._tread(reader.readexactly(4),
                                          "handshake")
            except (asyncio.IncompleteReadError, ConnectionError,
                    _ConnReaped):
                return
            if int.from_bytes(first, "little") in refproto.REF_MAGICS:
                try:
                    await self._ref_conn(reader, writer, first, conn_id)
                except (asyncio.IncompleteReadError, ConnectionError,
                        _ConnReaped):
                    pass
                return
            # every conn opens with one REGISTER_REQ declaring its role
            try:
                dtype, payload = await self._tread(
                    self._read_frame(reader, first), "handshake")
            except (asyncio.IncompleteReadError, ConnectionError,
                    _ConnReaped):
                return
            if dtype != wire.COMM_REGISTER_REQ:
                self.rt.stats.bump("conns_unregistered")
                return
            req = np.frombuffer(payload, wire.REGISTER_REQ_DT, count=1)[0]
            status, host_id = self._register(req)
            # v4 tail: the durable sweep-seq high-water mark for this
            # host — a reconnecting agent prunes already-durable sweeps
            # from its resend spool (the WAL dedup contract)
            last_seq = 0
            preagg = None
            if (status == wire.REG_OK
                    and int(req["conn_type"]) == wire.CONN_EVENT
                    and host_id != 0xFFFFFFFF):
                last_seq = int(getattr(self.rt, "_sweep_last_seq",
                                       {}).get(host_id, 0))
                # edge pre-aggregation advert (wire v5): when the
                # serve tier opts in (GYT_PREAGG=1), tell the agent
                # EXACTLY which sketch geometry to fold with — the
                # engine-cfg constants its delta partials must land in
                # (sketch/edgefold.py). Pre-v5 agents ignore the tail.
                from gyeeta_tpu.sketch import edgefold
                if edgefold.preagg_enabled():
                    preagg = edgefold.params_of_cfg(self.rt.cfg)
                    self.rt.stats.bump("preagg_agents_negotiated")
            writer.write(wire.encode_register_resp(
                status, host_id, version.CURR_WIRE_VERSION, last_seq,
                preagg=preagg))
            await writer.drain()
            if status != wire.REG_OK:
                return
            if int(req["conn_type"]) == wire.CONN_EVENT:
                if host_id != 0xFFFFFFFF:
                    self._event_writers[host_id] = writer
                    # reconnect resync: re-push full capture state
                    self.rt.tracedefs.forget_host(host_id)
                try:
                    if self._ingest is not None \
                            and host_id != 0xFFFFFFFF:
                        await self._handoff_event_conn(
                            reader, writer, host_id, conn_id)
                    else:
                        await self._event_loop(reader, host_id,
                                               conn_id=conn_id)
                finally:
                    if self._event_writers.get(host_id) is writer:
                        del self._event_writers[host_id]
                        # applied capture state is unknowable once the
                        # conn drops; rebuild it on reconnect
                        self.rt.tracedefs.forget_host(host_id)
            else:
                await self._query_loop(reader, writer)
        except wire.FrameError as e:
            log.warning("conn %s: %s — closing", peer, e)
            self.rt.stats.bump("conns_framing_errors")
            # attribute the reject (bad_magic / bad_size / truncated /
            # bad_frame) — the no-silent-loss accounting surface
            self.rt.stats.bump(
                "frames_rejected|reason="
                f"{getattr(e, 'reason', 'bad_frame')}")
        except _ConnReaped as e:
            log.info("conn %s: %s", peer, e)
        finally:
            self._open_conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):   # pragma: no cover
                pass

    async def _handoff_event_conn(self, reader, writer, host_id: int,
                                  conn_id: int) -> None:
        """Multi-process ingest: hand this registered event conn's
        socket to its shard group's worker and park until it dies.

        The transport stops reading FIRST; whatever the stream reader
        already buffered ships to the worker as initial bytes (no
        awaits between the pause and the snapshot, so no byte can
        slip past). This process keeps the (paused) transport: the
        reverse direction — trace control, COMM_THROTTLE — still
        writes from the supervisor, while the worker owns every read.
        A worker crash (or its conn_closed notice) sets the death
        event; unwinding closes the socket and the agent reconnects
        through this listener — same port, same sticky hid, same
        shard group after the respawn."""
        transport = writer.transport
        transport.pause_reading()
        initial = bytes(reader._buffer)          # noqa: SLF001
        reader._buffer.clear()                   # noqa: SLF001
        sock = writer.get_extra_info("socket")
        death = asyncio.Event()
        if sock is None or not self._ingest.handoff(
                host_id, conn_id, sock.fileno(), initial, death):
            # owning worker down (respawn window): close — the agent's
            # supervision loop retries and lands on the fresh worker
            self.rt.stats.bump("ingest_handoff_failed")
            return
        self.rt.stats.bump("ingest_conns_handed_off")
        await death.wait()

    async def _event_loop(self, reader, host_id: int = 0,
                          ref_session=None, conn_id: int = 0) -> None:
        """Bulk ingest: socket bytes → Runtime.feed.

        Partial-frame reassembly happens HERE, per connection: the
        runtime decoder is shared by every conn, so each conn's
        trailing partial frame must be held back or another conn's
        bytes would splice into the middle of it (the reference's
        per-conn recv buffers give the same guarantee,
        ``common/gy_epoll_conntrack.h`` partial-read resume).

        A conn whose frames carry the REFERENCE's COMM_HEADER magics
        (a stock partha / gy_comm_proto producer) is detected by its
        first complete header and routed through the ingest adapter
        (``ingest/refproto.py``) — adapted GYT frames feed the same
        runtime path, and the capture recorder sees the ADAPTED bytes
        (recorded bytes are always replayable GYT frames)."""
        pending = b""
        ref_mode = False
        if ref_session is None:               # per-conn adapter state
            ref_session = refproto.RefSession()
        while True:
            # idle deadline: an agent conn that stops sweeping (half-
            # open, wedged peer) is reaped on the sweep-cadence budget
            data = await self._tread(reader.read(_READ_SZ), "idle")
            if not data:
                if pending:
                    # EOF mid-frame: the tail was truncated in flight —
                    # count it, don't just drop it on the floor
                    self.rt.stats.bump(
                        "frames_rejected|reason=truncated")
                return
            data = pending + data
            if not ref_mode and len(data) >= 4 and int.from_bytes(
                    data[:4], "little") in refproto.REF_MAGICS:
                ref_mode = True
                self.rt.stats.bump("conns_ref_adapted")
            if ref_mode:
                try:
                    gyt, k = refproto.adapt(data, host_id,
                                            session=ref_session)
                except wire.FrameError:
                    self.rt.stats.bump("frames_bad")
                    raise
                pending = data[k:]
                if gyt:
                    self._feed(gyt, host_id, conn_id)
                    # pipeline mode records inside the pipeline (only
                    # validated buffers)
                    rec = self._recorder
                    if rec is not None and self._pipe is None:
                        rec.write(gyt)
                # drain AFTER the feed: domain payloads reference
                # listeners whose LISTENER_INFO may ride the same batch
                self._drain_ref_session(ref_session)
                continue
            try:
                k = wire.complete_prefix(data)
            except wire.FrameError:
                # poison header: close the conn — the agent reconnects
                # and resyncs (the reference closes on bad COMM_HEADER)
                self.rt.stats.bump("frames_bad")
                raise
            pending = data[k:]
            if k:
                # feed FIRST: a chunk that fails deep validation
                # (nevents caps) must not poison the capture file —
                # recorded bytes are exactly the ingested bytes
                self._feed(data[:k], host_id, conn_id)
                rec = self._recorder   # no await between check & write
                if rec is not None and self._pipe is None:
                    rec.write(data[:k])

    async def _query_loop(self, reader, writer) -> None:
        try:
            await self._query_loop_inner(reader, writer)
        finally:
            # conn teardown IS unsubscribe: every subscription this
            # conn registered stops costing a render share
            self.subs.unsubscribe_conn(writer)

    async def _subscribe_cmd(self, writer, payload) -> bool:
        """One COMM_SUBSCRIBE_CMD → hub registration whose pushes ride
        this conn as QS_PARTIAL QUERY_RESP frames (seqid echoed).
        Returns False on a recoverable envelope error (the conn and
        its error budget continue)."""
        from gyeeta_tpu.net.subs import SubscribeError
        try:
            seqid, _, req = wire.decode_query_payload(payload)
        except Exception:
            self.rt.stats.bump("frames_rejected|reason=bad_query")
            return False

        async def send(ev, _seqid=seqid, _w=writer):
            _w.write(wire.encode_query(_seqid, ev, wire.QS_PARTIAL,
                                       resp=True))
            if self.write_timeout:
                await asyncio.wait_for(_w.drain(), self.write_timeout)
            else:
                await _w.drain()

        try:
            last = (req or {}).get("last_snaptick")
            await self.subs.subscribe(req or {}, send,
                                      last_snaptick=last,
                                      conn_tag=writer)
            self.rt.stats.bump("net_subscribes")
            return True
        except (SubscribeError, ValueError, RuntimeError) as e:
            writer.write(wire.encode_query(seqid, {"error": str(e)},
                                           wire.QS_ERROR, resp=True))
            await writer.drain()
            return False

    async def _query_loop_inner(self, reader, writer) -> None:
        outstanding = 0
        bad_frames = 0
        while True:
            try:
                # a conn holding subscriptions is PUSH-only from here:
                # it legitimately never sends another frame, so the
                # idle reap does not apply (dead conns surface as
                # failed pushes and unsubscribe there)
                if self.subs.conn_subscribed(writer):
                    dtype, payload = await self._read_frame(reader)
                else:
                    dtype, payload = await self._tread(
                        self._read_frame(reader), "idle")
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            if dtype == wire.COMM_SUBSCRIBE_CMD:
                if not await self._subscribe_cmd(writer, payload):
                    bad_frames += 1
                    if bad_frames > self.frame_error_budget:
                        self.rt.stats.bump(
                            "frames_rejected|reason=error_budget")
                        return
                continue
            if dtype != wire.COMM_QUERY_CMD:
                self.rt.stats.bump("frames_unknown_type")
                bad_frames += 1
                if bad_frames > self.frame_error_budget:
                    # per-conn error budget: N recoverable frame-level
                    # errors → close (a peer spraying junk that parses
                    # as frames must not spin the loop forever)
                    self.rt.stats.bump(
                        "frames_rejected|reason=error_budget")
                    return
                continue
            try:
                seqid, _, req = wire.decode_query_payload(payload)
            except Exception:
                self.rt.stats.bump("frames_rejected|reason=bad_query")
                bad_frames += 1
                if bad_frames > self.frame_error_budget:
                    self.rt.stats.bump(
                        "frames_rejected|reason=error_budget")
                    return
                continue
            if outstanding >= wire.MAX_OUTSTANDING_QUERIES:
                writer.write(wire.encode_query(
                    seqid, {"error": "busy"}, wire.QS_BUSY, resp=True))
                await writer.drain()
                continue
            outstanding += 1
            try:
                self.rt.stats.bump("net_queries")
                out = await self.run_query(req)
            except Exception as e:
                from gyeeta_tpu.net.qexec import Overloaded
                outstanding -= 1
                # admission-control shed answers QS_BUSY (counted in
                # gyt_queries_shed_total) — the client backs off; a
                # plain error keeps the conn and the loop alive
                status = wire.QS_BUSY if isinstance(e, Overloaded) \
                    else wire.QS_ERROR
                writer.write(wire.encode_query(seqid, {"error": str(e)},
                                               status, resp=True))
                await writer.drain()
                continue
            try:
                # large results stream as QS_PARTIAL chunks with a drain
                # per chunk: bounded transport memory (the 16MB-frame /
                # multi-GB discipline of the reference webserver)
                sent = 0
                try:
                    for frame in wire.iter_query_frames(seqid, out,
                                                        wire.QS_OK):
                        writer.write(frame)
                        await writer.drain()
                        sent += 1
                except Exception as e:
                    if sent == 0 and not isinstance(e, ConnectionError):
                        # e.g. unserializable result: the query still
                        # gets its QS_ERROR and the conn survives
                        writer.write(wire.encode_query(
                            seqid, {"error": str(e)}, wire.QS_ERROR,
                            resp=True))
                        await writer.drain()
                    else:
                        raise   # mid-stream failure: close (resync)
            finally:
                outstanding -= 1
