"""Off-loop query executor: bounded worker pool + admission control.

Every query edge (GYT binary, REST gateway, stock NM) used to execute
inline on the asyncio event loop — the same loop that drains agent
sockets into ``Runtime.feed``. A dashboard fleet therefore stalled the
fold and the fold stalled query p99. With snapshot serving
(``query/snapshot.py``) a live query never touches the fold, so it can
leave the loop entirely: :class:`QueryExecutor` runs it on a bounded
``ThreadPoolExecutor`` (snapshot reads are thread-safe — frozen device
buffers + GIL-shared result caches), and sheds with a COUNTED overload
error once the in-flight window fills, instead of wedging the loop
behind an unbounded queue (``gyt_queries_shed_total``; the reference's
L2 pools bound their MPMC queues the same way,
``server/gy_mconnhdlr.h:53-75``).

Shedding is queue-depth-aware and policy-selectable (ROADMAP query
item (d)): under sustained overload the default ``lifo`` policy serves
the NEWEST waiting query first and sheds the OLDEST — a dashboard
refreshing every second wants its latest request answered, not a
30-second-old one it already gave up on; the stale request costs the
same render and produces an ignored response. ``fifo`` keeps classic
arrival order with tail-drop (shed the newest arrival when full) as
the control. Every shed lands on ``gyt_queries_shed_total{policy=…}``.

Knobs (env, read at construction; also settable via ``serve`` flags):

- ``GYT_QUERY_WORKERS``    — pool width (default 4)
- ``GYT_QUERY_QUEUE_MAX``  — max in-flight (queued + running) before
  shedding (default 128)
- ``GYT_QUERY_SHED_POLICY`` — ``lifo`` (default: serve newest, shed
  oldest) or ``fifo`` (serve oldest, shed newest arrival)
- ``GYT_QUERY_SNAPSHOT``   — 0 routes the serving edges back to inline
  strong-consistency execution (the pre-snapshot behavior; the
  escape hatch)

GIL relief (ISSUE-12): the worker threads above still serialize on
the GIL for the pure-Python half of a render, and the REST gateway
additionally pays ``json.dumps`` of every response body ON its
serving loop — at dashboard fan-out sizes that encode is the loop's
single biggest CPU bite. :class:`JsonRenderPool` moves the final
JSON encode of LARGE responses into a ``ProcessPoolExecutor`` behind
``GYT_QUERY_PROCS`` (default 0 = off): the loop thread pays a cheap
C-speed pickle of the row dicts, the child pays the slow encode with
its own GIL, and the bytes come back ready to write. Small responses
(below ``GYT_QUERY_PROCS_MIN_ROWS``, default 64 rows) stay inline —
the pickle round trip would cost more than it frees. The win is
measured, not assumed: ``_querylat.py``'s render-offload phase
records loop-thread CPU per response in both modes
(QUERYLAT_r07.json ``render_offload`` row).
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import os
from typing import Optional


class Overloaded(Exception):
    """Admission control shed: the in-flight query window is full.
    The serving edge answers a counted busy/overload error; the loop
    (and the fold) stay live."""


def snapshot_serving_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    return str(env.get("GYT_QUERY_SNAPSHOT", "1")).strip().lower() \
        not in ("0", "false", "no")


def query_procs(env=None) -> int:
    env = os.environ if env is None else env
    try:
        return max(0, int(env.get("GYT_QUERY_PROCS", "0")))
    except ValueError:
        return 0


def _encode_json(obj) -> bytes:
    """Child-process encode (top-level for pickling)."""
    import json
    return json.dumps(obj).encode()


class JsonRenderPool:
    """Off-GIL JSON encode tier for the REST gateway edge (see the
    module docstring). Safe by construction: a broken pool (killed
    child, fork trouble) falls back to the inline encode and counts
    it — responses never fail because the relief tier did."""

    def __init__(self, procs: Optional[int] = None,
                 min_rows: Optional[int] = None, stats=None):
        env = os.environ
        self.procs = query_procs() if procs is None else int(procs)
        self.min_rows = int(min_rows if min_rows is not None
                            else env.get("GYT_QUERY_PROCS_MIN_ROWS",
                                         "64"))
        self.stats = stats
        self._pool = None
        if self.procs > 0:
            # spawn, not fork: the serving process is multi-threaded
            # (JAX runtime, query workers, WAL writer) and a forked
            # child can deadlock on locks snapshotted mid-hold
            import multiprocessing
            self._pool = concurrent.futures.ProcessPoolExecutor(
                max_workers=self.procs,
                mp_context=multiprocessing.get_context("spawn"))

    @property
    def enabled(self) -> bool:
        return self._pool is not None

    def _offloadable(self, obj) -> bool:
        return (self._pool is not None and isinstance(obj, dict)
                and obj.get("nrecs", 0) >= self.min_rows)

    def _bump(self, name: str) -> None:
        if self.stats is not None:
            self.stats.bump(name)

    async def encode(self, obj) -> bytes:
        import json
        if not self._offloadable(obj):
            return json.dumps(obj).encode()
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(self._pool, _encode_json,
                                             obj)
            self._bump("query_renders_offloaded")
            return out
        except Exception:               # noqa: BLE001 — relief tier
            self._bump("query_render_offload_errors")
            return json.dumps(obj).encode()

    def encode_sync(self, obj) -> bytes:
        """Blocking form (bench harness)."""
        import json
        if not self._offloadable(obj):
            return json.dumps(obj).encode()
        try:
            out = self._pool.submit(_encode_json, obj).result()
            self._bump("query_renders_offloaded")
            return out
        except Exception:               # noqa: BLE001
            self._bump("query_render_offload_errors")
            return json.dumps(obj).encode()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None


class QueryExecutor:
    def __init__(self, rt, workers: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 shed_policy: Optional[str] = None):
        env = os.environ
        self.rt = rt
        self.workers = int(workers if workers is not None
                           else env.get("GYT_QUERY_WORKERS", "4"))
        self.queue_max = int(queue_max if queue_max is not None
                             else env.get("GYT_QUERY_QUEUE_MAX", "128"))
        self.shed_policy = (shed_policy if shed_policy is not None
                            else env.get("GYT_QUERY_SHED_POLICY",
                                         "lifo")).strip().lower()
        if self.shed_policy not in ("lifo", "fifo"):
            raise ValueError(
                f"GYT_QUERY_SHED_POLICY must be lifo|fifo, got "
                f"{self.shed_policy!r}")
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, self.workers),
            thread_name_prefix="gyt-query")
        self._running = 0             # queries holding a worker thread
        # waiting room, newest at the right; (req, future) pairs.
        # All scheduling state is event-loop-confined — no locks.
        self._pending: collections.deque = collections.deque()

    @property
    def _inflight(self) -> int:
        return self._running + len(self._pending)

    # -------------------------------------------------------------- run
    async def run(self, req: dict) -> dict:
        """Admit one query: execute immediately while the pool has
        headroom, else wait in the policy-ordered queue. Raises
        :class:`Overloaded` (counted, policy-labeled) when admission
        sheds it — which under ``lifo`` is the OLDEST waiter, so THIS
        call usually proceeds and a stale one errors out instead."""
        stats = self.rt.stats
        loop = asyncio.get_running_loop()
        if self._running < self.workers and not self._pending:
            return await self._execute(loop, req)
        if self.shed_policy == "fifo" \
                and self._inflight >= self.queue_max:
            # classic bounded-FIFO tail drop: the NEW arrival sheds
            stats.bump("queries_shed|policy=fifo")
            stats.bump("queries_shed")
            raise Overloaded(
                f"query queue full ({self._inflight} in flight, "
                f"max {self.queue_max})")
        fut = loop.create_future()
        self._pending.append((req, fut))
        if self.shed_policy == "lifo":
            # depth-aware freshness shed: drop the OLDEST waiters past
            # the bound — the dashboard that sent them has already
            # refreshed; the newest request is the one still on screen
            while self._inflight > self.queue_max and len(self._pending) > 1:
                old_req, old_fut = self._pending.popleft()
                if not old_fut.done():
                    stats.bump("queries_shed|policy=lifo")
                    stats.bump("queries_shed")
                    old_fut.set_exception(Overloaded(
                        f"query queue full (lifo: oldest shed, "
                        f"{self._inflight} in flight, max "
                        f"{self.queue_max})"))
        self._gauge()
        return await fut

    async def _execute(self, loop, req: dict) -> dict:
        self._running += 1
        self._gauge()
        try:
            return await loop.run_in_executor(self._pool, self._call,
                                              req)
        finally:
            self._running -= 1
            self._dispatch_next(loop)
            self._gauge()

    def _dispatch_next(self, loop) -> None:
        """A worker freed: hand it the policy's next waiter (lifo =
        newest first; fifo = oldest first)."""
        while self._pending and self._running < self.workers:
            req, fut = (self._pending.pop() if self.shed_policy == "lifo"
                        else self._pending.popleft())
            if fut.done():                # already shed
                continue

            async def _chain(req=req, fut=fut):
                try:
                    out = await self._execute(loop, req)
                except BaseException as e:     # noqa: BLE001
                    if not fut.done():
                        fut.set_exception(e)
                else:
                    if not fut.done():
                        fut.set_result(out)

            loop.create_task(_chain())
            return                        # _execute's finally continues

    def _gauge(self) -> None:
        self.rt.stats.gauge("query_queue_depth", float(self._inflight))

    def _call(self, req: dict) -> dict:
        return self.rt.query({**req, "consistency": "snapshot"})

    def close(self) -> None:
        for _req, fut in self._pending:
            if not fut.done():
                fut.cancel()
        self._pending.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)
