"""Off-loop query executor: bounded worker pool + admission control.

Every query edge (GYT binary, REST gateway, stock NM) used to execute
inline on the asyncio event loop — the same loop that drains agent
sockets into ``Runtime.feed``. A dashboard fleet therefore stalled the
fold and the fold stalled query p99. With snapshot serving
(``query/snapshot.py``) a live query never touches the fold, so it can
leave the loop entirely: :class:`QueryExecutor` runs it on a bounded
``ThreadPoolExecutor`` (snapshot reads are thread-safe — frozen device
buffers + GIL-shared result caches), and sheds with a COUNTED overload
error once the in-flight window fills, instead of wedging the loop
behind an unbounded queue (``gyt_queries_shed_total``; the reference's
L2 pools bound their MPMC queues the same way,
``server/gy_mconnhdlr.h:53-75``).

Knobs (env, read at construction; also settable via ``serve`` flags):

- ``GYT_QUERY_WORKERS``    — pool width (default 4)
- ``GYT_QUERY_QUEUE_MAX``  — max in-flight (queued + running) before
  shedding (default 128)
- ``GYT_QUERY_SNAPSHOT``   — 0 routes the serving edges back to inline
  strong-consistency execution (the pre-snapshot behavior; the
  escape hatch)
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
from typing import Optional


class Overloaded(Exception):
    """Admission control shed: the in-flight query window is full.
    The serving edge answers a counted busy/overload error; the loop
    (and the fold) stay live."""


def snapshot_serving_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    return str(env.get("GYT_QUERY_SNAPSHOT", "1")).strip().lower() \
        not in ("0", "false", "no")


class QueryExecutor:
    def __init__(self, rt, workers: Optional[int] = None,
                 queue_max: Optional[int] = None):
        env = os.environ
        self.rt = rt
        self.workers = int(workers if workers is not None
                           else env.get("GYT_QUERY_WORKERS", "4"))
        self.queue_max = int(queue_max if queue_max is not None
                             else env.get("GYT_QUERY_QUEUE_MAX", "128"))
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, self.workers),
            thread_name_prefix="gyt-query")
        self._inflight = 0

    # -------------------------------------------------------------- run
    async def run(self, req: dict) -> dict:
        """Admit one query and execute it on the pool with
        ``consistency=snapshot`` forced — or raise :class:`Overloaded`
        (counted) when the in-flight window is full. The caller holds
        the event loop; the query holds a worker thread."""
        stats = self.rt.stats
        if self._inflight >= self.queue_max:
            stats.bump("queries_shed")
            raise Overloaded(
                f"query queue full ({self._inflight} in flight, "
                f"max {self.queue_max})")
        self._inflight += 1
        stats.gauge("query_queue_depth", float(self._inflight))
        try:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                self._pool, self._call, req)
        finally:
            self._inflight -= 1
            stats.gauge("query_queue_depth", float(self._inflight))

    def _call(self, req: dict) -> dict:
        return self.rt.query({**req, "consistency": "snapshot"})

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
