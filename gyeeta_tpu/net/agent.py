"""Agent-side clients: NetAgent (partha equivalent) + QueryClient.

NetAgent mirrors partha's connection bring-up (ref
``partha/gy_paconnhdlr.cc:1200`` blocking_shyama_register →
``:1665`` connect_madhava): open a TCP conn, send REGISTER_REQ with the
machine-id, learn the assigned ``host_id``, then construct a single-host
``ParthaSim`` at that global host index and stream its telemetry as
EVENT_NOTIFY frames. On reconnect the machine-id maps back to the same
host_id (sticky placement), and the agent resends its name announcements
(the resend-inventory-on-reconnect recovery of the reference,
``gy_socket_stat.h:1235``).

QueryClient is the Node-webserver peer: a query-role conn multiplexing
JSON queries by seqid.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import numpy as np

from gyeeta_tpu import version
from gyeeta_tpu.ingest import wire
from gyeeta_tpu.sim.partha import ParthaSim
from gyeeta_tpu.utils import hashing as H

_HSZ = wire.HEADER_DT.itemsize


async def _read_frame(reader) -> tuple[int, bytes]:
    hdr_b = await reader.readexactly(_HSZ)
    hdr = np.frombuffer(hdr_b, wire.HEADER_DT, count=1)[0]
    total = int(hdr["total_sz"])
    body = await reader.readexactly(total - _HSZ)
    pad = int(hdr["padding_sz"])
    return int(hdr["data_type"]), body[: len(body) - pad]


async def register(host: str, port: int, machine_id: int, conn_type: int,
                   wire_version: int = version.CURR_WIRE_VERSION,
                   hostname_id: int = 0):
    """Open + register one conn → (reader, writer, status, host_id)."""
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(wire.encode_register_req(
        machine_id, conn_type, wire_version, hostname_id))
    await writer.drain()
    dtype, payload = await _read_frame(reader)
    if dtype != wire.COMM_REGISTER_RESP:
        raise wire.FrameError(f"expected REGISTER_RESP, got {dtype}")
    resp = np.frombuffer(payload, wire.REGISTER_RESP_DT, count=1)[0]
    return reader, writer, int(resp["status"]), int(resp["host_id"])


class NetAgent:
    """One host agent over a real socket.

    ``collect=True`` turns on the REAL host collectors
    (``net/collect.py``): host inventory, 2s CPU/mem gauges, and cgroup
    sweeps are then measured from this machine's /proc //sys instead of
    simulated; conn/resp/listener/task streams stay simulated (their
    kernel-side capture has no userspace equivalent — the reference
    needs eBPF for them)."""

    def __init__(self, machine_id: Optional[int] = None, seed: int = 0,
                 n_svcs: int = 4, n_groups: int = 6,
                 wire_version: int = version.CURR_WIRE_VERSION,
                 collect: bool = False, real: bool = False,
                 livecap: bool = False, cap_ifname: str = "lo"):
        self.machine_id = machine_id if machine_id is not None \
            else H.hash_bytes_np(f"sim-agent-{seed}".encode())
        self.seed = seed
        self.n_svcs = n_svcs
        self.n_groups = n_groups
        self.wire_version = wire_version
        self.collect = collect
        # real=True: flows/listeners come from THIS host's kernel via
        # the sock_diag sweep (net/tcpconn.py) instead of the simulator
        # — the inet_diag path of the reference
        # (``common/gy_socket_stat.cc:8598``). resp/trace streams stay
        # absent in real mode (they need eBPF the reference has and
        # userspace does not).
        self.real = real
        # livecap=True (with real=True): REQ_TRACE_SET enables start a
        # privilege-gated AF_PACKET capture of the traced listeners'
        # ports; parsed transactions stream as REQ_TRACE frames — the
        # reference's per-svc capture activation (gy_svc_net_capture.h
        # :153), with the packet socket as the observation point
        self.livecap = livecap
        self.cap_ifname = cap_ifname
        self._cap = None
        self._cap_ports: set = set()
        self._cap_denied = False      # CAP_NET_RAW refused (final)
        self.host_id: Optional[int] = None
        self.sim: Optional[ParthaSim] = None
        self._tcpconn = None
        self._taskproc = None
        self._cpumem = None
        self._cgroups = None
        self._mounts = None
        self._netifs = None
        self._writer = None
        self._ctrl_task = None
        # svc glob ids with capture enabled by the server (REQ_TRACE_SET
        # analogue); empty = no tracing
        self.trace_enabled: set = set()

    async def connect(self, host: str, port: int) -> int:
        """Register the event conn; returns assigned host_id."""
        # the server re-applies capture state from scratch on reconnect
        # (forget_host → full re-push of current targets only); stale
        # local enables from before the drop must not survive it — and
        # neither may a still-draining old control loop, which could
        # decode a buffered TRACE_SET and re-add them after the clear
        if self._ctrl_task:
            self._ctrl_task.cancel()
            self._ctrl_task = None
        self.trace_enabled.clear()
        hostname_id = self.machine_id & 0xFFFFFFFF
        reader, writer, status, hid = await register(
            host, port, self.machine_id, wire.CONN_EVENT,
            self.wire_version, hostname_id)
        if status != wire.REG_OK:
            writer.close()
            raise ConnectionRefusedError(f"registration status {status}")
        self.host_id = hid
        self._writer = writer
        # a fresh 1-host sim rooted at the assigned global host index —
        # glob_ids/task_ids derive from it, so streams are fleet-unique
        self.sim = ParthaSim(
            n_hosts=1, n_svcs=self.n_svcs, n_groups=self.n_groups,
            seed=1000 + hid, host_base=hid)
        if self.collect:
            from gyeeta_tpu.net import collect as C
            self._cpumem = C.CpuMemCollector(host_id=hid)
            self._cgroups = C.CgroupCollector(host_id=hid)
            self._cgroups.sample()        # prime the delta baseline
            self._mounts = C.MountCollector(host_id=hid)
            self._netifs = C.NetIfCollector(host_id=hid)
            self._netifs.sample()         # prime the rate baseline
        if self.real:
            from gyeeta_tpu.net.taskproc import ProcTaskCollector
            from gyeeta_tpu.net.tcpconn import TcpConnCollector
            self._tcpconn = TcpConnCollector(
                host_id=hid, machine_id=self.machine_id)
            if self._taskproc is not None:
                self._taskproc.close()    # reconnect: no netlink leak
            self._taskproc = ProcTaskCollector(
                host_id=hid, machine_id=self.machine_id)
        # server→agent control frames ride the same conn in reverse
        self._ctrl_task = asyncio.create_task(self._control_loop(reader))
        await self.send_names()
        return hid

    async def _control_loop(self, reader) -> None:
        """Apply COMM_TRACE_SET capture control from the server."""
        while True:
            try:
                dtype, payload = await _read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError,
                    wire.FrameError):
                return
            if dtype != wire.COMM_TRACE_SET:
                continue
            for r in wire.decode_trace_set(payload):
                if r["enable"]:
                    self.trace_enabled.add(int(r["svc_glob_id"]))
                else:
                    self.trace_enabled.discard(int(r["svc_glob_id"]))

    async def send_names(self) -> None:
        """Announce inventory: names + listener metadata + host info
        (the reference agent resends its inventory on reconnect)."""
        import os
        hostname = (os.uname().nodename if (self.collect or self.real)
                    else f"agent-{self.host_id}.sim")
        buf = b""
        if not self.real:
            # sim inventory; real listeners announce themselves on the
            # first sweep (the collector emits LISTENER_INFO on sight)
            buf += (self.sim.name_frames()
                    + wire.encode_frame(wire.NOTIFY_LISTENER_INFO,
                                        self.sim.listener_info_records()))
        # hostname AFTER sim names: the sim announces a placeholder
        # host name and the intern table is last-write-wins
        buf += wire.encode_frame(
            wire.NOTIFY_NAME_INTERN,
            wire_name_record(wire.NAME_KIND_HOST, self.host_id,
                             hostname))
        if self.collect:
            from gyeeta_tpu.net import collect as C
            hi, names = C.collect_host_info(host_id=self.host_id)
            buf += (wire.encode_frame(wire.NOTIFY_NAME_INTERN, names)
                    + wire.encode_frame(wire.NOTIFY_HOST_INFO, hi))
        else:
            buf += self.sim.host_info_frames()
        self._writer.write(buf)
        await self._writer.drain()

    async def send_sweep(self, n_conn: int = 256, n_resp: int = 512
                         ) -> None:
        """One 5s-equivalent sweep: flows, resp samples, state records."""
        s = self.sim
        if self.real:
            buf = self._real_sweep_frames()
        else:
            buf = (s.conn_frames(n_conn) + s.resp_frames(n_resp)
                   + s.listener_frames() + s.task_frames()
                   + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                       s.host_state_records()))
            if self.trace_enabled:
                # capture on for some services: emit their transactions
                buf += s.trace_frames(n_resp,
                                      only_svcs=self.trace_enabled)
        if self.collect:
            buf += wire.encode_frame(wire.NOTIFY_CPU_MEM_STATE,
                                     self._cpumem.sample())
            cg, cgnames = self._cgroups.sample()
            if len(cgnames):
                buf += wire.encode_frame(wire.NOTIFY_NAME_INTERN,
                                         cgnames)
            if len(cg):
                buf += wire.encode_frame(wire.NOTIFY_CGROUP_STATE, cg)
            for sub, (recs, names) in (
                    (wire.NOTIFY_MOUNT_STATE, self._mounts.sample()),
                    (wire.NOTIFY_NETIF_STATE, self._netifs.sample())):
                buf += wire.encode_frames_chunked(
                    wire.NOTIFY_NAME_INTERN, names)
                buf += wire.encode_frames_chunked(sub, recs)
        else:
            buf += (s.cgroup_frames()
                    + wire.encode_frame(wire.NOTIFY_CPU_MEM_STATE,
                                        s.cpu_mem_records()))
        self._writer.write(buf)
        await self._writer.drain()

    def _real_sweep_frames(self) -> bytes:
        """One real sock_diag sweep → wire frames (cap-split per type)."""
        import time as _time

        d = self._tcpconn.sweep()
        trecs, tnames = self._taskproc.sweep(
            task_net=d["task_net"],
            listener_of_comm=d["listener_of_comm"])
        buf = (wire.encode_frames_chunked(wire.NOTIFY_NAME_INTERN,
                                          d["names"])
               + wire.encode_frames_chunked(wire.NOTIFY_NAME_INTERN,
                                            tnames)
               + wire.encode_frames_chunked(wire.NOTIFY_LISTENER_INFO,
                                            d["listener_info"])
               + wire.encode_frames_chunked(wire.NOTIFY_TCP_CONN,
                                            d["conns"])
               + wire.encode_frames_chunked(wire.NOTIFY_LISTENER_STATE,
                                            d["listeners"])
               + wire.encode_frames_chunked(
                   wire.NOTIFY_AGGR_TASK_STATE, trecs))
        hs = np.zeros(1, wire.HOST_STATE_DT)
        hs[0]["curr_time_usec"] = int(_time.time() * 1e6)
        hs[0]["nlisten"] = len(d["listeners"])
        hs[0]["ntasks"] = int(trecs["ntasks_total"].sum())
        hs[0]["ntasks_issue"] = int(trecs["ntasks_issue"].sum())
        hs[0]["curr_state"] = 1               # OK; issues come from the
        hs[0]["host_id"] = self.host_id       # server-side classifiers
        buf += wire.encode_frame(wire.NOTIFY_HOST_STATE, hs)
        if self.livecap:
            buf += self._livecap_frames()
        return buf

    def _livecap_frames(self) -> bytes:
        """Drain the live capture → REQ_TRACE frames for traced svcs.

        The capture's port set tracks the TRACED listeners (trace
        control diff → ports via the sock_diag listener registry).
        Retargeting mutates the live socket's port filter in place —
        still-traced services keep their buffered frames and in-flight
        TCP state. Degrades to no-op without CAP_NET_RAW (cached);
        transient open failures retry next sweep."""
        from gyeeta_tpu.trace import livecap as LC
        from gyeeta_tpu.trace.proto import transactions_to_records

        want = self._tcpconn.listener_ports(self.trace_enabled)
        if not want:
            if self._cap is not None:
                self._cap.close()
                self._cap = None
            self._cap_ports = set()
            return b""
        if self._cap is None:
            if self._cap_denied:
                return b""
            try:
                self._cap = LC.LiveCapture(self.cap_ifname, ports=want)
                self._cap_ports = set(want)
            except PermissionError:
                self._cap_denied = True       # no CAP_NET_RAW: final
                return b""
            except OSError:
                return b""                    # transient: retry later
        elif want != self._cap_ports:
            # in-place retarget: keep the socket + buffered frames
            self._cap.ports = set(want)
            self._cap_ports = set(want)
        self._cap.poll()
        buf = b""
        for f in self._cap.drain():
            gid = self._tcpconn.resolve_listener(
                f.ser[0], f.ser[1], gids=self.trace_enabled)
            if gid is None:
                continue
            recs, name_recs = transactions_to_records(
                f.transactions, svc_glob_id=gid, host_id=self.host_id)
            buf += (wire.encode_frames_chunked(
                wire.NOTIFY_NAME_INTERN, name_recs)
                + wire.encode_frames_chunked(wire.NOTIFY_REQ_TRACE,
                                             recs))
        return buf

    async def close(self) -> None:
        if self._ctrl_task:
            self._ctrl_task.cancel()
            self._ctrl_task = None
        if self._taskproc is not None:
            self._taskproc.close()        # netlink TASKSTATS socket
            self._taskproc = None
        if self._cap is not None:
            self._cap.close()             # AF_PACKET socket
            self._cap = None
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None


def wire_name_record(kind: int, name_id: int, name: str) -> np.ndarray:
    from gyeeta_tpu.utils.intern import InternTable
    return InternTable.records([(kind, name_id, name)])


class QueryClient:
    """Query-role conn: JSON queries multiplexed by seqid."""

    def __init__(self, machine_id: Optional[int] = None):
        self.machine_id = machine_id if machine_id is not None \
            else H.hash_bytes_np(b"query-client")
        self._reader = None
        self._writer = None
        self._seq = 0

    async def connect(self, host: str, port: int) -> None:
        reader, writer, status, _ = await register(
            host, port, self.machine_id, wire.CONN_QUERY)
        if status != wire.REG_OK:
            writer.close()
            raise ConnectionRefusedError(f"registration status {status}")
        self._reader, self._writer = reader, writer

    async def query(self, req: dict) -> dict:
        import json

        self._seq += 1
        seq = self._seq
        self._writer.write(wire.encode_query(seq, req))
        await self._writer.drain()
        chunks = []       # joined once at the end: O(N) for GB responses
        while True:       # streamed responses: QS_PARTIAL chunks → final
            dtype, payload = await _read_frame(self._reader)
            if dtype != wire.COMM_QUERY_RESP:
                raise wire.FrameError(f"expected QUERY_RESP, got {dtype}")
            seqid, status, chunk = wire.decode_query_chunk(payload)
            if seqid != seq:
                raise wire.FrameError(f"seqid mismatch {seqid} != {seq}")
            chunks.append(chunk)
            if status != wire.QS_PARTIAL:
                break
        obj = json.loads(b"".join(chunks) or b"null")
        if status != wire.QS_OK:
            raise RuntimeError(obj.get("error", f"query status {status}"))
        return obj

    async def close(self) -> None:
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
