"""Agent-side clients: NetAgent (partha equivalent) + QueryClient.

NetAgent mirrors partha's connection bring-up (ref
``partha/gy_paconnhdlr.cc:1200`` blocking_shyama_register →
``:1665`` connect_madhava): open a TCP conn, send REGISTER_REQ with the
machine-id, learn the assigned ``host_id``, then construct a single-host
``ParthaSim`` at that global host index and stream its telemetry as
EVENT_NOTIFY frames. On reconnect the machine-id maps back to the same
host_id (sticky placement), and the agent resends its name announcements
(the resend-inventory-on-reconnect recovery of the reference,
``gy_socket_stat.h:1235``).

:meth:`NetAgent.run_forever` is the supervision tier (the parmon
respawn loop of the reference, ``gypartha.cc:965``, collapsed into the
agent itself): jittered exponential-backoff reconnects, sweeps KEEP
being produced on cadence during an outage and buffer in a bounded
spool (drop-oldest, every drop counted), and the spool resends on
reconnect — at-least-once delivery of sweeps within the spool bound,
with agent-side counters (``stats``) reported to the server as
NOTIFY_AGENT_STATS deltas so fleet-wide loss renders in /metrics.

QueryClient is the Node-webserver peer: a query-role conn multiplexing
JSON queries by seqid. Both clients dial and read under deadlines — a
wedged server yields a clear timeout error plus a counter, never an
infinite hang.
"""

from __future__ import annotations

import asyncio
import collections
import random
from typing import Optional

import numpy as np

from gyeeta_tpu import version
from gyeeta_tpu.ingest import wire
from gyeeta_tpu.sim.partha import ParthaSim
from gyeeta_tpu.utils import hashing as H
from gyeeta_tpu.utils.selfstats import Stats

_HSZ = wire.HEADER_DT.itemsize

# one validated reader on both ends of the wire (ingest/wire.py): magic
# gate + total_sz/padding bounds before any body read — a corrupt header
# can neither hang readexactly on a multi-MB read nor crash a short one
_read_frame = wire.read_frame

# errors that mean "the conn is gone / unusable" to a supervised client
_CONN_ERRORS = (ConnectionError, OSError, EOFError,
                asyncio.IncompleteReadError, wire.FrameError,
                asyncio.TimeoutError, TimeoutError)


async def register_ex(host: str, port: int, machine_id: int,
                      conn_type: int,
                      wire_version: int = version.CURR_WIRE_VERSION,
                      hostname_id: int = 0):
    """Open + register one conn → (reader, writer, status, host_id,
    last_seq, preagg). ``last_seq`` is the server's durable sweep-seq
    high-water mark for this host (0 from pre-v4 servers) — the WAL
    dedup handshake (see ``wire.NOTIFY_SWEEP_SEQ``); ``preagg`` is the
    server's edge pre-aggregation advert (the v5 tail — the sketch
    geometry delta sweeps must fold with), or None."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(wire.encode_register_req(
            machine_id, conn_type, wire_version, hostname_id))
        await writer.drain()
        dtype, payload = await _read_frame(reader)
    except BaseException:
        writer.close()
        raise
    if dtype != wire.COMM_REGISTER_RESP:
        writer.close()
        raise wire.FrameError(f"expected REGISTER_RESP, got {dtype}")
    status, host_id, _ver, last_seq, preagg = \
        wire.decode_register_resp(payload)
    return reader, writer, status, host_id, last_seq, preagg


async def register(host: str, port: int, machine_id: int, conn_type: int,
                   wire_version: int = version.CURR_WIRE_VERSION,
                   hostname_id: int = 0):
    """Open + register one conn → (reader, writer, status, host_id)."""
    reader, writer, status, host_id, _seq, _pre = await register_ex(
        host, port, machine_id, conn_type, wire_version, hostname_id)
    return reader, writer, status, host_id


class NetAgent:
    """One host agent over a real socket.

    ``collect=True`` turns on the REAL host collectors
    (``net/collect.py``): host inventory, 2s CPU/mem gauges, and cgroup
    sweeps are then measured from this machine's /proc //sys instead of
    simulated; conn/resp/listener/task streams stay simulated (their
    kernel-side capture has no userspace equivalent — the reference
    needs eBPF for them)."""

    def __init__(self, machine_id: Optional[int] = None, seed: int = 0,
                 n_svcs: int = 4, n_groups: int = 6,
                 wire_version: int = version.CURR_WIRE_VERSION,
                 collect: bool = False, real: bool = False,
                 livecap: bool = False, cap_ifname: str = "lo",
                 connect_timeout: float = 15.0,
                 spool_max_bytes: int = 8 << 20,
                 resend_last: int = 2,
                 preagg: Optional[bool] = None):
        self.machine_id = machine_id if machine_id is not None \
            else H.hash_bytes_np(f"sim-agent-{seed}".encode())
        self.seed = seed
        self.n_svcs = n_svcs
        self.n_groups = n_groups
        self.wire_version = wire_version
        self.collect = collect
        # real=True: flows/listeners come from THIS host's kernel via
        # the sock_diag sweep (net/tcpconn.py) instead of the simulator
        # — the inet_diag path of the reference
        # (``common/gy_socket_stat.cc:8598``). resp/trace streams stay
        # absent in real mode (they need eBPF the reference has and
        # userspace does not).
        self.real = real
        # livecap=True (with real=True): REQ_TRACE_SET enables start a
        # privilege-gated AF_PACKET capture of the traced listeners'
        # ports; parsed transactions stream as REQ_TRACE frames — the
        # reference's per-svc capture activation (gy_svc_net_capture.h
        # :153), with the packet socket as the observation point
        self.livecap = livecap
        self.cap_ifname = cap_ifname
        self._cap = None
        self._cap_ports: set = set()
        self._cap_denied = False      # CAP_NET_RAW refused (final)
        self.host_id: Optional[int] = None
        self.sim: Optional[ParthaSim] = None
        self._tcpconn = None
        self._taskproc = None
        self._cpumem = None
        self._cgroups = None
        self._mounts = None
        self._netifs = None
        self._writer = None
        self._ctrl_task = None
        # svc glob ids with capture enabled by the server (REQ_TRACE_SET
        # analogue); empty = no tracing
        self.trace_enabled: set = set()
        # ---- delivery continuity (the supervised-reconnect tier)
        # dial deadline: a wedged server must yield a clear timeout
        # error + counter, never an infinite hang
        self.connect_timeout = connect_timeout
        # agent-side self-metrics: reconnects, spool drops/resends,
        # records built/sent — the loss-accounting surface; deltas are
        # reported to the server as NOTIFY_AGENT_STATS on reconnect
        self.stats = Stats()
        # bounded sweep spool: sweeps produced during an outage buffer
        # here (oldest first) and resend on reconnect; drop-oldest when
        # past spool_max_bytes, every drop counted (sweeps AND records)
        self.spool_max_bytes = spool_max_bytes
        self._spool: collections.deque = collections.deque()
        self._spool_bytes = 0
        # recently-sent sweeps held unconfirmed: a write into a dying
        # socket "succeeds" into the kernel buffer, so the last few
        # sweeps respool on conn loss (at-least-once; duplicates are
        # fold noise, silent loss is not)
        self._unconfirmed: collections.deque = collections.deque(
            maxlen=max(1, resend_last))
        self._stats_reported: dict = {}
        # set by the control-loop reader the moment the conn's read
        # half hits EOF/reset — the supervisor's fast-fail signal
        self._conn_dead = False
        # ---- durable-ingest additions (wire v4)
        # monotone per-process sweep counter: every built sweep opens
        # with a NOTIFY_SWEEP_SEQ mark carrying it. The server journals
        # the high-water mark with its checkpoints and echoes it back
        # in REGISTER_RESP, so a reconnect prunes already-DURABLE
        # sweeps from the spool (checkpoint + WAL replay + resend never
        # double-folds a sweep)
        self._sweep_seq = 0
        # server→agent admission control (COMM_THROTTLE): feed class →
        # monotonic deadline until which that class holds in the spool
        self._hold_until: dict[int, float] = {}
        # ---- edge pre-aggregation (wire v5, sketch/edgefold.py)
        # preagg=None follows the server's REGISTER_RESP advert (the
        # serve-negotiated default: GYT_PREAGG=1 on the server flips
        # the fleet); False opts this agent out; True REQUIRES the
        # advert and falls back raw COUNTED when it is absent (the
        # agent cannot guess the server's sketch geometry). Sim-mode
        # only: real collectors keep the raw contract.
        self.preagg = preagg
        self._preagg_params: Optional[dict] = None
        self._edgefold = None

    async def connect(self, host: str, port: int,
                      timeout: Optional[float] = None) -> int:
        """Register the event conn under a dial deadline; returns the
        assigned host_id. Raises ``ConnectionError`` with a clear
        message (and bumps ``connect_timeouts``) when the deadline
        fires against a wedged server."""
        t = self.connect_timeout if timeout is None else timeout
        try:
            return await asyncio.wait_for(self._connect(host, port), t)
        except (asyncio.TimeoutError, TimeoutError):
            self.stats.bump("connect_timeouts")
            self._drop_conn()     # _connect may have died mid-bring-up
            raise ConnectionError(
                f"agent connect to {host}:{port} timed out "
                f"after {t:.1f}s") from None

    async def _connect(self, host: str, port: int) -> int:
        # the server re-applies capture state from scratch on reconnect
        # (forget_host → full re-push of current targets only); stale
        # local enables from before the drop must not survive it — and
        # neither may a still-draining old control loop, which could
        # decode a buffered TRACE_SET and re-add them after the clear
        if self._ctrl_task:
            self._ctrl_task.cancel()
            self._ctrl_task = None
        self.trace_enabled.clear()
        self._conn_dead = False
        hostname_id = self.machine_id & 0xFFFFFFFF
        reader, writer, status, hid, last_seq, preagg_adv = \
            await register_ex(
                host, port, self.machine_id, wire.CONN_EVENT,
                self.wire_version, hostname_id)
        if status != wire.REG_OK:
            writer.close()
            raise ConnectionRefusedError(f"registration status {status}")
        self.host_id = hid
        self._writer = writer
        # the server's durable high-water mark: sweeps at or below it
        # are already in its checkpoint+WAL — drop them from the resend
        # surfaces instead of double-folding them (counted)
        if last_seq:
            self._prune_acked(last_seq)
        # a 1-host sim rooted at the assigned global host index —
        # glob_ids/task_ids derive from it, so streams are fleet-unique.
        # Sticky reconnects (same hid) KEEP the sim: telemetry produced
        # during the outage stays continuous instead of replaying from
        # the seed (the reference agent keeps collecting while down)
        if self.sim is None or self.sim.host_base != hid:
            self.sim = ParthaSim(
                n_hosts=1, n_svcs=self.n_svcs, n_groups=self.n_groups,
                seed=1000 + hid, host_base=hid)
        # edge pre-aggregation: enable only on a server advert (the
        # advert carries the sketch geometry the partials must land
        # in); the local fold's cumulative HLL state survives sticky
        # reconnects like the sim does
        self._preagg_params = None
        if (preagg_adv is not None and self.preagg is not False
                and not self.real):
            self._preagg_params = preagg_adv
            if self._edgefold is None \
                    or self._edgefold.host_id != hid \
                    or self._edgefold.params != preagg_adv:
                from gyeeta_tpu.sketch.edgefold import EdgeFold
                self._edgefold = EdgeFold(preagg_adv, host_id=hid)
        elif self.preagg:
            # explicit opt-in against a server that never advertised:
            # stay raw, counted (never guess the sketch geometry)
            self.stats.bump("preagg_not_advertised")
        if self.collect:
            from gyeeta_tpu.net import collect as C
            self._cpumem = C.CpuMemCollector(host_id=hid)
            self._cgroups = C.CgroupCollector(host_id=hid)
            self._cgroups.sample()        # prime the delta baseline
            self._mounts = C.MountCollector(host_id=hid)
            self._netifs = C.NetIfCollector(host_id=hid)
            self._netifs.sample()         # prime the rate baseline
        if self.real:
            from gyeeta_tpu.net.taskproc import ProcTaskCollector
            from gyeeta_tpu.net.tcpconn import TcpConnCollector
            self._tcpconn = TcpConnCollector(
                host_id=hid, machine_id=self.machine_id)
            if self._taskproc is not None:
                self._taskproc.close()    # reconnect: no netlink leak
            self._taskproc = ProcTaskCollector(
                host_id=hid, machine_id=self.machine_id)
        # server→agent control frames ride the same conn in reverse
        self._ctrl_task = asyncio.create_task(self._control_loop(reader))
        await self.send_names()
        return hid

    async def _control_loop(self, reader) -> None:
        """Apply COMM_TRACE_SET capture control from the server.

        Doubles as the conn-death watch: the read half sees the
        server's FIN/RST immediately, while the write half can keep
        "succeeding" into kernel buffers for several sweeps — sweeps
        that would slip past the unconfirmed ring. The ``_conn_dead``
        flag makes the supervisor stop sending the instant EOF lands."""
        try:
            while True:
                try:
                    dtype, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError,
                        OSError, wire.FrameError):
                    return
                if dtype == wire.COMM_THROTTLE:
                    # admission control: hold the named feed classes in
                    # the spool for hold_ms (0 releases early). Unknown
                    # feed ids are skipped — forward compatible, the
                    # NOTIFY_AGENT_STATS versioning discipline
                    now = asyncio.get_running_loop().time()
                    for t in wire.decode_throttle(payload):
                        feed = int(t["feed"])
                        if feed not in (wire.FEED_TRACE, wire.FEED_ALL):
                            continue
                        hold = int(t["hold_ms"])
                        if hold:
                            self._hold_until[feed] = now + hold / 1e3
                            self.stats.bump(
                                "throttle_held|feed="
                                + ("all" if feed == wire.FEED_ALL
                                   else "trace"))
                        else:
                            self._hold_until.pop(feed, None)
                    continue
                if dtype != wire.COMM_TRACE_SET:
                    continue
                for r in wire.decode_trace_set(payload):
                    if r["enable"]:
                        self.trace_enabled.add(int(r["svc_glob_id"]))
                    else:
                        self.trace_enabled.discard(int(r["svc_glob_id"]))
        finally:
            # only the CURRENT conn's watcher may flag death — a
            # cancelled predecessor's late finally must not poison a
            # freshly established conn
            if self._ctrl_task is asyncio.current_task():
                self._conn_dead = True

    async def send_names(self) -> None:
        """Announce inventory: names + listener metadata + host info
        (the reference agent resends its inventory on reconnect)."""
        import os
        hostname = (os.uname().nodename if (self.collect or self.real)
                    else f"agent-{self.host_id}.sim")
        buf = b""
        if not self.real:
            # sim inventory; real listeners announce themselves on the
            # first sweep (the collector emits LISTENER_INFO on sight)
            buf += (self.sim.name_frames()
                    + wire.encode_frame(wire.NOTIFY_LISTENER_INFO,
                                        self.sim.listener_info_records()))
        # hostname AFTER sim names: the sim announces a placeholder
        # host name and the intern table is last-write-wins
        buf += wire.encode_frame(
            wire.NOTIFY_NAME_INTERN,
            wire_name_record(wire.NAME_KIND_HOST, self.host_id,
                             hostname))
        if self.collect:
            from gyeeta_tpu.net import collect as C
            hi, names = C.collect_host_info(host_id=self.host_id)
            buf += (wire.encode_frame(wire.NOTIFY_NAME_INTERN, names)
                    + wire.encode_frame(wire.NOTIFY_HOST_INFO, hi))
        else:
            buf += self.sim.host_info_frames()
        self._writer.write(buf)
        await self._writer.drain()

    async def send_sweep(self, n_conn: int = 256, n_resp: int = 512
                         ) -> None:
        """One 5s-equivalent sweep: flows, resp samples, state records."""
        buf = self.build_sweep(n_conn, n_resp)
        self._writer.write(buf)
        await self._writer.drain()

    def _held(self, feed: int) -> bool:
        """True while the server's COMM_THROTTLE hold on ``feed`` is
        active (expired holds are dropped lazily)."""
        until = self._hold_until.get(feed)
        if until is None:
            return False
        try:
            now = asyncio.get_running_loop().time()
        except RuntimeError:              # sync caller (tests)
            import time as _t
            now = _t.monotonic()
        if now >= until:
            del self._hold_until[feed]
            return False
        return True

    def _sweep_mark(self) -> bytes:
        """One NOTIFY_SWEEP_SEQ record opening the sweep (the WAL
        dedup mark — see ``_connect``)."""
        self._sweep_seq += 1
        rec = np.zeros(1, wire.SWEEP_SEQ_DT)
        rec["host_id"] = self.host_id or 0
        rec["seq"] = self._sweep_seq
        return wire.encode_frame(wire.NOTIFY_SWEEP_SEQ, rec)

    def build_sweep(self, n_conn: int = 256, n_resp: int = 512) -> bytes:
        """Build one sweep's frames WITHOUT sending (the supervisor
        keeps producing on cadence during an outage and spools these).
        Opens with a sweep-seq mark (WAL dedup)."""
        s = self.sim
        mark = self._sweep_mark()
        if self.real:
            buf = mark + self._real_sweep_frames()
        else:
            if self._preagg_params is not None:
                # edge pre-aggregation: fold the conn/resp streams
                # locally and ship ONE mergeable-delta stream instead
                # of N raw tuples (sketch/edgefold.py); the 5s state
                # sweeps (listener/task/host) are already one record
                # per entity and stay raw
                conn = s.conn_records(n_conn)
                resp = s.resp_records(n_resp)
                delta = self._edgefold.fold_sweep(conn, resp)
                hot = wire.encode_frames_chunked(
                    wire.NOTIFY_SKETCH_DELTA, delta)
                self.stats.bump("preagg_sweeps")
                self.stats.bump("preagg_source_records",
                                len(conn) + len(resp))
                self.stats.bump("preagg_delta_records", len(delta))
            else:
                hot = s.conn_frames(n_conn) + s.resp_frames(n_resp)
            buf = (mark + hot
                   + s.listener_frames() + s.task_frames()
                   + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                       s.host_state_records()))
            if self.trace_enabled and not self._held(wire.FEED_TRACE):
                # capture on for some services: emit their transactions
                # (priority-aware shedding: a FEED_TRACE hold drops the
                # trace stream from the sweep FIRST, so svc/task state
                # — the health classification inputs — degrade last)
                buf += s.trace_frames(n_resp,
                                      only_svcs=self.trace_enabled)
            elif self.trace_enabled:
                self.stats.bump("trace_frames_throttled")
        if self.collect:
            buf += wire.encode_frame(wire.NOTIFY_CPU_MEM_STATE,
                                     self._cpumem.sample())
            cg, cgnames = self._cgroups.sample()
            if len(cgnames):
                buf += wire.encode_frame(wire.NOTIFY_NAME_INTERN,
                                         cgnames)
            if len(cg):
                buf += wire.encode_frame(wire.NOTIFY_CGROUP_STATE, cg)
            for sub, (recs, names) in (
                    (wire.NOTIFY_MOUNT_STATE, self._mounts.sample()),
                    (wire.NOTIFY_NETIF_STATE, self._netifs.sample())):
                buf += wire.encode_frames_chunked(
                    wire.NOTIFY_NAME_INTERN, names)
                buf += wire.encode_frames_chunked(sub, recs)
        else:
            buf += (s.cgroup_frames()
                    + wire.encode_frame(wire.NOTIFY_CPU_MEM_STATE,
                                        s.cpu_mem_records()))
        return buf

    def _real_sweep_frames(self) -> bytes:
        """One real sock_diag sweep → wire frames (cap-split per type)."""
        import time as _time

        d = self._tcpconn.sweep()
        trecs, tnames = self._taskproc.sweep(
            task_net=d["task_net"],
            listener_of_comm=d["listener_of_comm"])
        buf = (wire.encode_frames_chunked(wire.NOTIFY_NAME_INTERN,
                                          d["names"])
               + wire.encode_frames_chunked(wire.NOTIFY_NAME_INTERN,
                                            tnames)
               + wire.encode_frames_chunked(wire.NOTIFY_LISTENER_INFO,
                                            d["listener_info"])
               + wire.encode_frames_chunked(wire.NOTIFY_TCP_CONN,
                                            d["conns"])
               + wire.encode_frames_chunked(wire.NOTIFY_LISTENER_STATE,
                                            d["listeners"])
               + wire.encode_frames_chunked(
                   wire.NOTIFY_AGGR_TASK_STATE, trecs))
        hs = np.zeros(1, wire.HOST_STATE_DT)
        hs[0]["curr_time_usec"] = int(_time.time() * 1e6)
        hs[0]["nlisten"] = len(d["listeners"])
        hs[0]["ntasks"] = int(trecs["ntasks_total"].sum())
        hs[0]["ntasks_issue"] = int(trecs["ntasks_issue"].sum())
        hs[0]["curr_state"] = 1               # OK; issues come from the
        hs[0]["host_id"] = self.host_id       # server-side classifiers
        buf += wire.encode_frame(wire.NOTIFY_HOST_STATE, hs)
        if self.livecap:
            buf += self._livecap_frames()
        return buf

    def _livecap_frames(self) -> bytes:
        """Drain the live capture → REQ_TRACE frames for traced svcs.

        The capture's port set tracks the TRACED listeners (trace
        control diff → ports via the sock_diag listener registry).
        Retargeting mutates the live socket's port filter in place —
        still-traced services keep their buffered frames and in-flight
        TCP state. Degrades to no-op without CAP_NET_RAW (cached);
        transient open failures retry next sweep."""
        from gyeeta_tpu.trace import livecap as LC
        from gyeeta_tpu.trace.proto import transactions_to_records

        want = self._tcpconn.listener_ports(self.trace_enabled)
        if not want:
            if self._cap is not None:
                self._cap.close()
                self._cap = None
            self._cap_ports = set()
            return b""
        if self._cap is None:
            if self._cap_denied:
                return b""
            try:
                self._cap = LC.LiveCapture(self.cap_ifname, ports=want)
                self._cap_ports = set(want)
            except PermissionError:
                self._cap_denied = True       # no CAP_NET_RAW: final
                return b""
            except OSError:
                return b""                    # transient: retry later
        elif want != self._cap_ports:
            # in-place retarget: keep the socket + buffered frames
            self._cap.ports = set(want)
            self._cap_ports = set(want)
        self._cap.poll()
        buf = b""
        for f in self._cap.drain():
            gid = self._tcpconn.resolve_listener(
                f.ser[0], f.ser[1], gids=self.trace_enabled)
            if gid is None:
                continue
            recs, name_recs = transactions_to_records(
                f.transactions, svc_glob_id=gid, host_id=self.host_id)
            buf += (wire.encode_frames_chunked(
                wire.NOTIFY_NAME_INTERN, name_recs)
                + wire.encode_frames_chunked(wire.NOTIFY_REQ_TRACE,
                                             recs))
        return buf

    # --------------------------------------------------- supervision tier
    def _spool_push(self, buf: bytes, nrec: int, seq: int = 0) -> None:
        """Buffer one undelivered sweep; drop-oldest past the byte
        bound, every drop counted (sweeps and records — the no-silent-
        loss accounting). ``seq`` is the sweep's dedup mark (0 = not a
        marked sweep, never pruned by the server ack)."""
        self._spool.append((buf, nrec, seq))
        self._spool_bytes += len(buf)
        self.stats.bump("sweeps_spooled")
        while self._spool_bytes > self.spool_max_bytes \
                and len(self._spool) > 1:
            old, oldrec, _ = self._spool.popleft()
            self._spool_bytes -= len(old)
            self.stats.bump("spool_dropped")
            self.stats.bump("spool_dropped_records", oldrec)

    def spool_len(self) -> int:
        return len(self._spool)

    def spool_records(self) -> int:
        """Records currently buffered (spool + unconfirmed tail)."""
        return (sum(n for _, n, _ in self._spool)
                + sum(n for _, n, _ in self._unconfirmed))

    def _respool_unconfirmed(self) -> None:
        """Conn lost: the last few written sweeps may have died in the
        kernel buffer — move them to the spool front (oldest first) so
        the reconnect resends them (at-least-once delivery)."""
        for buf, nrec, seq in reversed(self._unconfirmed):
            self._spool.appendleft((buf, nrec, seq))
            self._spool_bytes += len(buf)
        self._unconfirmed.clear()
        # re-apply the bound from the old end
        while self._spool_bytes > self.spool_max_bytes \
                and len(self._spool) > 1:
            old, oldrec, _ = self._spool.popleft()
            self._spool_bytes -= len(old)
            self.stats.bump("spool_dropped")
            self.stats.bump("spool_dropped_records", oldrec)

    def _prune_acked(self, last_seq: int) -> None:
        """Drop sweeps the server proved DURABLE (seq ≤ its
        REGISTER_RESP high-water mark) from the spool and the
        unconfirmed ring: the checkpoint+WAL already hold them, so a
        resend would double-fold (counted, the dedup half of the WAL
        contract)."""
        npruned = nrec_pruned = 0
        for ring in (self._spool, self._unconfirmed):
            keep = [e for e in ring
                    if not (e[2] and e[2] <= last_seq)]
            npruned += len(ring) - len(keep)
            for e in ring:
                if e[2] and e[2] <= last_seq:
                    nrec_pruned += e[1]
                    if ring is self._spool:
                        self._spool_bytes -= len(e[0])
            ring.clear()
            ring.extend(keep)
        if npruned:
            self.stats.bump("spool_pruned_acked", npruned)
            self.stats.bump("spool_pruned_records", nrec_pruned)
            # pruned-from-ring sweeps were delivered AND made durable
            self.stats.bump("records_sent", nrec_pruned)

    async def _send_buf(self, buf: bytes, nrec: int, seq: int = 0) -> None:
        """Write one sweep and account it as (tentatively) delivered."""
        if self._conn_dead or self._writer.is_closing():
            # the read half already saw the server go away: writing
            # would "succeed" into a dead socket and overflow the
            # unconfirmed ring's recovery window
            raise ConnectionResetError("conn read half saw EOF")
        self._writer.write(buf)
        await self._writer.drain()
        evicted = None
        if len(self._unconfirmed) == self._unconfirmed.maxlen:
            evicted = self._unconfirmed[0]
        self._unconfirmed.append((buf, nrec, seq))
        if evicted is not None:
            self.stats.bump("records_sent", evicted[1])

    async def _resend_spool(self) -> None:
        """Drain the spool over the live conn (oldest first) — on
        reconnect, and whenever a throttle hold expires with sweeps
        still buffered."""
        while self._spool and not self._held(wire.FEED_ALL):
            buf, nrec, seq = self._spool[0]
            await self._send_buf(buf, nrec, seq)
            self._spool.popleft()
            self._spool_bytes -= len(buf)
            self.stats.bump("spool_resent")

    def _drop_conn(self) -> None:
        """Tear down a dead conn quietly (the supervisor's half of
        ``close()`` — collectors and the sim survive for the retry)."""
        if self._ctrl_task:
            self._ctrl_task.cancel()
            self._ctrl_task = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:       # pragma: no cover — already dead
                pass
            self._writer = None

    def _stats_report_frame(self) -> bytes:
        """NOTIFY_AGENT_STATS frame carrying counter DELTAS since the
        last report (server folds them into monotone counters), or
        b"" when nothing changed."""
        rec = np.zeros(1, wire.AGENT_STATS_DT)
        rec["host_id"] = self.host_id or 0
        changed = False
        for fld in ("spool_dropped", "spool_dropped_records",
                    "spool_resent", "connect_timeouts"):
            cur = int(self.stats.counters.get(fld, 0))
            delta = cur - self._stats_reported.get(fld, 0)
            if delta:
                rec[fld] = delta
                self._stats_reported[fld] = cur
                changed = True
        return wire.encode_frame(wire.NOTIFY_AGENT_STATS, rec) \
            if changed else b""

    async def run_forever(self, host: str, port: int, *,
                          interval: float = 5.0, n_conn: int = 256,
                          n_resp: int = 512, backoff_base: float = 0.5,
                          backoff_cap: float = 30.0,
                          backoff_jitter: float = 0.25,
                          stop: Optional[asyncio.Event] = None) -> None:
        """Supervised agent loop: NEVER exits on a connection failure
        (the parmon respawn discipline, ref ``gypartha.cc:965``).

        Sweeps are produced on ``interval`` cadence whether or not the
        conn is up — undeliverable ones spool (bounded, drop-oldest
        counted) and resend on reconnect. Reconnects follow jittered
        exponential backoff (``backoff_base·2^k`` capped at
        ``backoff_cap``, +0..``backoff_jitter`` fraction of jitter,
        deterministic per agent seed). Returns only when ``stop`` is
        set or the task is cancelled."""
        rng = random.Random((self.seed << 1) ^ 0x5EED)
        loop = asyncio.get_running_loop()
        backoff = backoff_base
        next_retry = loop.time()          # connect immediately
        next_sweep: Optional[float] = None
        while not (stop is not None and stop.is_set()):
            now = loop.time()
            # ---- (re)connect phase, backoff-gated
            if self._writer is None and now >= next_retry:
                try:
                    await self.connect(host, port)
                    if int(self.stats.counters.get("agent_connects", 0)):
                        self.stats.bump("agent_reconnects")
                    self.stats.bump("agent_connects")
                    backoff = backoff_base
                    await self._resend_spool()
                    # report AFTER the resend so this reconnect's
                    # resent/dropped counts ride this report
                    report = self._stats_report_frame()
                    if report:
                        self._writer.write(report)
                        await self._writer.drain()
                    if next_sweep is None:
                        next_sweep = loop.time()
                except asyncio.CancelledError:
                    raise
                except _CONN_ERRORS:
                    self.stats.bump("connect_failures")
                    self._drop_conn()
                    self._respool_unconfirmed()
                    next_retry = loop.time() + backoff * (
                        1.0 + backoff_jitter * rng.random())
                    backoff = min(backoff * 2.0, backoff_cap)
            # ---- sweep cadence (runs even while disconnected, once
            # the first registration has given the sim its identity)
            now = loop.time()
            if next_sweep is not None and now >= next_sweep:
                buf = self.build_sweep(n_conn, n_resp)
                seq = self._sweep_seq
                nrec = wire.count_events(buf)
                self.stats.bump("sweeps_built")
                self.stats.bump("records_built", nrec)
                if self._writer is not None \
                        and not self._held(wire.FEED_ALL):
                    try:
                        await self._send_buf(buf, nrec, seq)
                    except _CONN_ERRORS:
                        self.stats.bump("agent_disconnects")
                        self._drop_conn()
                        self._respool_unconfirmed()
                        self._spool_push(buf, nrec, seq)
                        next_retry = loop.time() + backoff * (
                            1.0 + backoff_jitter * rng.random())
                        backoff = min(backoff * 2.0, backoff_cap)
                else:
                    # outage OR a server FEED_ALL throttle hold: the
                    # sweep rides the same bounded spool either way
                    # (server pressure becomes agent-side spooling)
                    if self._writer is not None:
                        self.stats.bump("sweeps_throttled")
                    self._spool_push(buf, nrec, seq)
                next_sweep += interval
            # a throttle hold that expired with sweeps still buffered:
            # drain them now (the reconnect path drains its own spool)
            if (self._writer is not None and self._spool
                    and not self._held(wire.FEED_ALL)):
                try:
                    await self._resend_spool()
                except asyncio.CancelledError:
                    raise
                except _CONN_ERRORS:
                    self.stats.bump("agent_disconnects")
                    self._drop_conn()
                    self._respool_unconfirmed()
                    next_retry = loop.time() + backoff * (
                        1.0 + backoff_jitter * rng.random())
                    backoff = min(backoff * 2.0, backoff_cap)
            # ---- sleep until the next deadline (sweep / retry / stop)
            deadlines = []
            if next_sweep is not None:
                deadlines.append(next_sweep)
            if self._writer is None:
                deadlines.append(next_retry)
            delay = max(0.0, (min(deadlines) if deadlines
                              else interval) - loop.time())
            if stop is not None:
                try:
                    await asyncio.wait_for(stop.wait(),
                                           timeout=max(delay, 0.001))
                except (asyncio.TimeoutError, TimeoutError):
                    pass
            else:
                await asyncio.sleep(max(delay, 0.001))

    async def close(self) -> None:
        if self._ctrl_task:
            self._ctrl_task.cancel()
            self._ctrl_task = None
        if self._taskproc is not None:
            self._taskproc.close()        # netlink TASKSTATS socket
            self._taskproc = None
        if self._cap is not None:
            self._cap.close()             # AF_PACKET socket
            self._cap = None
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None


def wire_name_record(kind: int, name_id: int, name: str) -> np.ndarray:
    from gyeeta_tpu.utils.intern import InternTable
    return InternTable.records([(kind, name_id, name)])


class QueryClient:
    """Query-role conn: JSON queries multiplexed by seqid.

    Dial and per-request deadlines (``connect_timeout`` /
    ``request_timeout``) guard against a wedged server: a fired
    deadline raises a clear error, bumps a counter on ``stats``, and
    resets the conn (the response stream is desynced once a request
    is abandoned mid-flight)."""

    def __init__(self, machine_id: Optional[int] = None,
                 connect_timeout: float = 10.0,
                 request_timeout: Optional[float] = 60.0):
        self.machine_id = machine_id if machine_id is not None \
            else H.hash_bytes_np(b"query-client")
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.stats = Stats()
        self._reader = None
        self._writer = None
        self._seq = 0

    async def connect(self, host: str, port: int,
                      timeout: Optional[float] = None) -> None:
        t = self.connect_timeout if timeout is None else timeout
        try:
            reader, writer, status, _ = await asyncio.wait_for(
                register(host, port, self.machine_id, wire.CONN_QUERY),
                t)
        except (asyncio.TimeoutError, TimeoutError):
            self.stats.bump("connect_timeouts")
            raise ConnectionError(
                f"query connect to {host}:{port} timed out "
                f"after {t:.1f}s") from None
        if status != wire.REG_OK:
            writer.close()
            raise ConnectionRefusedError(f"registration status {status}")
        self._reader, self._writer = reader, writer

    async def query(self, req: dict,
                    timeout: Optional[float] = None) -> dict:
        t = self.request_timeout if timeout is None else timeout
        if t is None:
            return await self._query(req)
        try:
            return await asyncio.wait_for(self._query(req), t)
        except (asyncio.TimeoutError, TimeoutError):
            self.stats.bump("query_timeouts")
            # the conn is desynced (the response may still arrive):
            # reset it so a retry cannot read a stale tail
            await self.close()
            raise TimeoutError(
                f"query timed out after {t:.1f}s "
                f"(subsys {req.get('subsys')!r})") from None

    async def _query(self, req: dict) -> dict:
        import json

        self._seq += 1
        seq = self._seq
        self._writer.write(wire.encode_query(seq, req))
        await self._writer.drain()
        chunks = []       # joined once at the end: O(N) for GB responses
        while True:       # streamed responses: QS_PARTIAL chunks → final
            dtype, payload = await _read_frame(self._reader)
            if dtype != wire.COMM_QUERY_RESP:
                raise wire.FrameError(f"expected QUERY_RESP, got {dtype}")
            seqid, status, chunk = wire.decode_query_chunk(payload)
            if seqid != seq:
                raise wire.FrameError(f"seqid mismatch {seqid} != {seq}")
            chunks.append(chunk)
            if status != wire.QS_PARTIAL:
                break
        obj = json.loads(b"".join(chunks) or b"null")
        if status != wire.QS_OK:
            raise RuntimeError(obj.get("error", f"query status {status}"))
        return obj

    async def close(self) -> None:
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
