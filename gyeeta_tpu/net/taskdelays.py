"""Netlink TASKSTATS delay accounting (VERDICT r4 missing #6).

``/proc/[pid]/schedstat`` gives runqueue wait and stat field 42 gives
block-IO delay, but swap-in / memory-reclaim / thrashing delays exist
ONLY in the kernel's taskstats genetlink interface — the reference
reads them over netlink (``common/gy_acct_taskstat.h:209``). This is
a dependency-free generic-netlink client for TASKSTATS_CMD_GET:
resolve the family id once, then query per-pid delay totals.

Privilege-gated (needs CAP_NET_ADMIN for the genl query and the
kernel built with CONFIG_TASKSTATS + delayacct enabled):
:func:`available` probes once; callers degrade to the /proc-only
delays cleanly.

Struct offsets are the kernel UAPI ABI (verified against
<linux/taskstats.h> v13 with a compile probe): the delay fields have
been at fixed offsets since v1 (freepages since v4, thrashing v9);
``version`` is checked before reading version-gated fields.
"""

from __future__ import annotations

import os
import socket
import struct
from typing import Optional

NETLINK_GENERIC = 16
GENL_ID_CTRL = 0x10
CTRL_CMD_GETFAMILY = 3
CTRL_ATTR_FAMILY_ID = 1
CTRL_ATTR_FAMILY_NAME = 2

TASKSTATS_CMD_GET = 1
TASKSTATS_CMD_ATTR_PID = 1
TASKSTATS_TYPE_AGGR_PID = 4
TASKSTATS_TYPE_STATS = 3

NLM_F_REQUEST = 1

# taskstats struct offsets (UAPI, stable; see module docstring)
_OFF_VERSION = 0
_OFF_CPU_COUNT = 16
_OFF_CPU_DELAY = 24
_OFF_BLKIO_DELAY = 40
_OFF_SWAPIN_DELAY = 56
_OFF_FREEPAGES_DELAY = 320
_OFF_THRASHING_DELAY = 336
_MIN_STATS_LEN = 328        # through freepages (v4+)


def _nlattr(atype: int, payload: bytes) -> bytes:
    ln = 4 + len(payload)
    pad = (-(ln)) % 4
    return struct.pack("<HH", ln, atype) + payload + b"\x00" * pad


def _nlmsg(mtype: int, payload: bytes, seq: int) -> bytes:
    ln = 16 + len(payload)
    return struct.pack("<IHHII", ln, mtype, NLM_F_REQUEST, seq,
                       os.getpid()) + payload


def _walk_attrs(buf: bytes):
    off = 0
    while off + 4 <= len(buf):
        ln, atype = struct.unpack_from("<HH", buf, off)
        if ln < 4 or off + ln > len(buf):
            return
        yield atype & 0x3FFF, buf[off + 4: off + ln]
        off += (ln + 3) & ~3


class TaskDelayReader:
    """One genetlink socket; per-pid delay queries.

    ``get(pid)`` → {"cpu_delay_ns", "blkio_delay_ns",
    "swapin_delay_ns", "freepages_delay_ns", "thrashing_delay_ns"}
    or None (racing exit / perm / kernel without taskstats)."""

    def __init__(self):
        self._sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW,
                                   NETLINK_GENERIC)
        self._sock.bind((0, 0))
        self._sock.settimeout(1.0)
        self._seq = 1
        self._family = self._resolve_family()
        if self._family is None:
            self._sock.close()
            raise OSError("TASKSTATS genl family unavailable")

    def _resolve_family(self) -> Optional[int]:
        payload = (struct.pack("<BBH", CTRL_CMD_GETFAMILY, 1, 0)
                   + _nlattr(CTRL_ATTR_FAMILY_NAME, b"TASKSTATS\x00"))
        self._sock.send(_nlmsg(GENL_ID_CTRL, payload, self._seq))
        self._seq += 1
        try:
            resp = self._sock.recv(65536)
        except (TimeoutError, OSError):
            return None
        ln, mtype = struct.unpack_from("<IH", resp, 0)
        if mtype == 2:                      # NLMSG_ERROR
            return None
        for atype, val in _walk_attrs(resp[16 + 4:]):
            if atype == CTRL_ATTR_FAMILY_ID and len(val) >= 2:
                return struct.unpack("<H", val[:2])[0]
        return None

    def get(self, pid: int) -> Optional[dict]:
        payload = (struct.pack("<BBH", TASKSTATS_CMD_GET, 1, 0)
                   + _nlattr(TASKSTATS_CMD_ATTR_PID,
                             struct.pack("<I", pid)))
        seq = self._seq
        self._seq += 1
        try:
            self._sock.send(_nlmsg(self._family, payload, seq))
            # match the reply's seq: a stale buffered reply (earlier
            # timeout) must not be attributed to THIS pid
            for _ in range(8):
                resp = self._sock.recv(65536)
                if len(resp) >= 12 and \
                        struct.unpack_from("<I", resp, 8)[0] == seq:
                    break
            else:
                return None
        except (TimeoutError, OSError):
            return None
        mtype = struct.unpack_from("<H", resp, 4)[0]
        if mtype == 2:                      # NLMSG_ERROR (pid gone…)
            return None
        stats = None
        for atype, val in _walk_attrs(resp[16 + 4:]):
            if atype == TASKSTATS_TYPE_AGGR_PID:
                for t2, v2 in _walk_attrs(val):
                    if t2 == TASKSTATS_TYPE_STATS:
                        stats = v2
        if stats is None or len(stats) < _OFF_SWAPIN_DELAY + 8:
            return None
        u64 = lambda off: struct.unpack_from("<Q", stats, off)[0]
        ver = struct.unpack_from("<H", stats, _OFF_VERSION)[0]
        out = {
            "cpu_delay_ns": u64(_OFF_CPU_DELAY),
            "blkio_delay_ns": u64(_OFF_BLKIO_DELAY),
            "swapin_delay_ns": u64(_OFF_SWAPIN_DELAY),
            "freepages_delay_ns": 0,
            "thrashing_delay_ns": 0,
        }
        if ver >= 4 and len(stats) >= _OFF_FREEPAGES_DELAY + 8:
            out["freepages_delay_ns"] = u64(_OFF_FREEPAGES_DELAY)
        if ver >= 9 and len(stats) >= _OFF_THRASHING_DELAY + 8:
            out["thrashing_delay_ns"] = u64(_OFF_THRASHING_DELAY)
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


_probe_result: Optional[bool] = None


def available() -> bool:
    """True when the kernel answers TASKSTATS queries (cached)."""
    global _probe_result
    if _probe_result is None:
        try:
            r = TaskDelayReader()
            _probe_result = r.get(os.getpid()) is not None
            r.close()
        except OSError:
            _probe_result = False
    return _probe_result
