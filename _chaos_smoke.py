"""CI smoke: the chaos tier against a REAL server process.

Short deterministic fault schedule end-to-end: a `python -m gyeeta_tpu
serve` subprocess (write-ahead journal ON) behind the seeded
ChaosProxy, two supervised sim agents (``run_forever``), corruption +
disconnect faults on the wire, a slow-loris conn straight at the
server, one SIGTERM kill (graceful: final checkpoint, fsync-truncated
journal) and one SIGKILL mid-inter-checkpoint-window (the crash the
WAL exists for), each followed by a ``--restore-latest`` restart whose
recovery replays the journal. Fails loud on: agent task exit,
non-convergence (services/hosts missing or Down after recovery), an
unaccounted record delta (silent loss), a SIGKILL recovery that
replayed nothing, or missing hardening/durability counters in the
exposition. Follows the `_metrics_smoke.py` / `_nm_smoke.py` pattern;
run by ci.sh, standalone: ``JAX_PLATFORMS=cpu python _chaos_smoke.py``.
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_server(port: int, ckdir: str, hostmap: str,
                  journal_dir: str = ""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", GYT_PLATFORM="cpu")
    cmd = [sys.executable, "-m", "gyeeta_tpu", "serve",
           "--host", "127.0.0.1", "--port", str(port),
           "--checkpoint-dir", ckdir, "--hostmap", hostmap,
           "--restore-latest", "--tick-interval", "0.5",
           "--handshake-timeout", "2", "--idle-timeout", "10",
           "--stats-interval", "30", "--log-level", "WARNING"]
    if journal_dir:
        # tight fsync cadence: the SIGKILL below must find every
        # accepted pre-kill chunk durable (deterministic smoke)
        cmd += ["--journal-dir", journal_dir,
                "--journal-fsync-ms", "5", "--journal-fsync-kb", "1"]
    return subprocess.Popen(cmd, cwd=HERE, env=env)


async def _wait_ready(port: int, proc, timeout: float = 180.0) -> None:
    """Poll until the server accepts AND answers a query."""
    from gyeeta_tpu.net.agent import QueryClient
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SystemExit(
                f"server process exited early (rc={proc.returncode})")
        try:
            qc = QueryClient(connect_timeout=2.0, request_timeout=10.0)
            await qc.connect("127.0.0.1", port)
            await qc.query({"subsys": "serverstatus"})
            await qc.close()
            return
        except Exception:
            await asyncio.sleep(0.5)
    raise SystemExit("server never became ready")


async def _query(port: int, req: dict) -> dict:
    from gyeeta_tpu.net.agent import QueryClient
    qc = QueryClient(connect_timeout=5.0, request_timeout=30.0)
    await qc.connect("127.0.0.1", port)
    out = await qc.query(req)
    await qc.close()
    return out


async def scenario() -> None:
    from gyeeta_tpu.net.agent import NetAgent
    from gyeeta_tpu.sim.chaos import ChaosProxy, FaultPlan

    tmp = tempfile.mkdtemp(prefix="gyt_chaos_smoke_")
    ckdir = os.path.join(tmp, "ck")
    waldir = os.path.join(tmp, "wal")
    hostmap = os.path.join(tmp, "hostmap.json")
    port = _free_port()

    proc = _spawn_server(port, ckdir, hostmap, waldir)
    agents: list = []
    tasks: list = []
    proxy = None
    stop = asyncio.Event()
    try:
        await _wait_ready(port, proc)
        plan = FaultPlan(seed=5, fault_kinds=("corrupt", "disconnect"),
                         mean_fault_bytes=64 * 1024, resplit=4096)
        proxy = ChaosProxy("127.0.0.1", port, plan)
        ph, pp = await proxy.start()
        agents = [NetAgent(seed=40 + i, n_svcs=2, n_groups=3,
                           spool_max_bytes=64 * 1024,
                           connect_timeout=3.0, resend_last=4)
                  for i in range(2)]
        tasks = [asyncio.create_task(a.run_forever(
            ph, pp, interval=0.3, n_conn=32, n_resp=32,
            backoff_base=0.2, backoff_cap=1.0, stop=stop))
            for a in agents]

        # phase 1: faulted streaming
        t0 = time.monotonic()
        while time.monotonic() - t0 < 6.0:
            await asyncio.sleep(0.5)
            if any(t.done() for t in tasks):
                raise SystemExit("agent supervisor exited during phase 1")

        # ---- the kill: SIGTERM → graceful final checkpoint
        proxy.refusing = True
        proxy.drop_all()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0, \
            f"server shutdown rc={proc.returncode}"
        # outage: supervisors keep producing into the spool
        await asyncio.sleep(1.5)
        assert not any(t.done() for t in tasks), \
            "agent supervisor exited during the outage"

        # ---- restart on the SAME port with --restore-latest
        proc = _spawn_server(port, ckdir, hostmap, waldir)
        await _wait_ready(port, proc)
        proxy.refusing = False

        # a slow-loris conn straight at the restarted server: valid
        # magic, header never completed — must be reaped on the
        # handshake deadline (generous window: first sweeps trigger
        # jit compiles that block the fresh server's loop for a while)
        lr, lw = await asyncio.open_connection("127.0.0.1", port)
        lw.write((0x47590001).to_bytes(4, "little"))
        await lw.drain()

        # phase 2: reconnect + resend + fresh sweeps
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0:
            await asyncio.sleep(0.5)
            if any(t.done() for t in tasks):
                raise SystemExit("agent supervisor exited during phase 2")
            if all(a.stats.counters.get("agent_reconnects", 0) >= 1
                   and a.spool_len() == 0 for a in agents):
                break
        else:
            raise SystemExit("agents never reconnected/drained the spool")
        await asyncio.sleep(1.5)          # a couple of post-recovery sweeps

        # ---- convergence: both hosts, all services, names, nothing Down
        svc = await _query(port, {"subsys": "svcstate"})
        hosts = await _query(port, {"subsys": "hoststate"})
        assert svc["nrecs"] == 4, f"expected 4 services, got {svc}"
        assert all(r["svcname"].startswith("svc-") for r in svc["recs"])
        assert hosts["nrecs"] == 2, f"expected 2 hosts, got {hosts}"
        assert all(r["state"] != "Down" for r in hosts["recs"])

        # the loris must have been reaped by now (handshake deadline
        # 2s; the conn has been up for the whole recovery phase)
        loris_eof = await asyncio.wait_for(lr.read(16), 120.0)
        assert loris_eof == b"", "slow-loris conn was not reaped"
        lw.close()

        # ---- hardening counters render in the exposition
        met = (await _query(port, {"subsys": "metrics"}))["text"]
        assert "gyt_agent_reconnects_total" in met, met[-2000:]
        assert 'gyt_conn_timeouts_total{kind="handshake"}' in met, \
            met[-2000:]
        # phase-2 epoch must have seen the reconnects
        reconn = [ln for ln in met.splitlines()
                  if ln.startswith("gyt_agent_reconnects_total")]
        assert reconn and float(reconn[0].split()[-1]) >= 2, reconn

        # ---- phase 3: SIGKILL mid-inter-checkpoint window. SIGTERM
        # above proved the graceful path (final checkpoint, truncated
        # journal). SIGKILL writes NOTHING on the way down — the
        # restarted server's state must come from checkpoint + WAL
        # replay, and the fleet view must survive byte-for-byte (no
        # periodic checkpoint ran in this epoch, so every accepted
        # record since the restart lives ONLY in the journal).
        reconn_before = {a.seed: a.stats.counters.get(
            "agent_reconnects", 0) for a in agents}
        proxy.refusing = True
        proxy.drop_all()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
        await asyncio.sleep(1.0)         # outage: spool keeps filling
        assert not any(t.done() for t in tasks), \
            "agent supervisor exited during the SIGKILL outage"
        proc = _spawn_server(port, ckdir, hostmap, waldir)
        await _wait_ready(port, proc)
        proxy.refusing = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0:
            await asyncio.sleep(0.5)
            if any(t.done() for t in tasks):
                raise SystemExit(
                    "agent supervisor exited during phase 3")
            if all(a.stats.counters.get("agent_reconnects", 0)
                   > reconn_before[a.seed]
                   and a.spool_len() == 0 for a in agents):
                break
        else:
            raise SystemExit(
                "agents never recovered from the SIGKILL")
        await asyncio.sleep(1.5)
        stop.set()
        await asyncio.wait_for(asyncio.gather(*tasks), 15.0)

        # the SIGKILL recovery REPLAYED the journal (the PR-4 gap):
        # wal counters render in the fresh epoch's exposition
        met3 = (await _query(port, {"subsys": "metrics"}))["text"]
        replayed = [ln for ln in met3.splitlines()
                    if ln.startswith("gyt_wal_replayed_records_total")]
        assert replayed and float(replayed[0].split()[-1]) > 0, \
            "SIGKILL recovery replayed no WAL records"
        assert "gyt_journal_fsync_lag_seconds" in met3
        svc3 = await _query(port, {"subsys": "svcstate"})
        hosts3 = await _query(port, {"subsys": "hoststate"})
        assert svc3["nrecs"] == 4, f"post-SIGKILL services: {svc3}"
        assert hosts3["nrecs"] == 2, f"post-SIGKILL hosts: {hosts3}"
        assert all(r["state"] != "Down" for r in hosts3["recs"])

        # ---- zero silent loss across all three server epochs:
        # everything built is accepted, still spooled, or counted
        # dropped. The killed epochs' accepted counters died with
        # their processes, so bound with the agents' own ledgers:
        # every record the agents still hold or dropped is accounted,
        # and the final state served the full fleet (above). Sanity:
        # drops (if any) were counted, resends happened.
        resent = sum(a.stats.counters.get("spool_resent", 0)
                     for a in agents)
        assert resent >= 1, "no spooled sweeps were resent"
        for a in agents:
            spooled = a.stats.counters.get("sweeps_spooled", 0)
            dropped = a.stats.counters.get("spool_dropped", 0)
            assert spooled >= 1, dict(a.stats.counters)
            assert dropped <= spooled, dict(a.stats.counters)
        # the proxy really injected the schedule
        assert (proxy.stats["corrupt"] + proxy.stats["disconnect"]) >= 1, \
            dict(proxy.stats)

        print(f"chaos smoke: OK — faults={dict(proxy.stats)}, "
              f"reconnects={int(float(reconn[0].split()[-1]))}, "
              f"resent={resent}, svc={svc3['nrecs']}, "
              f"hosts={hosts3['nrecs']}, "
              f"wal_replayed={float(replayed[0].split()[-1]):.0f}",
              file=sys.stderr)
    finally:
        stop.set()
        for t in tasks:
            t.cancel()
        if proxy is not None:
            await proxy.stop()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()


def main() -> int:
    asyncio.run(scenario())
    return 0


if __name__ == "__main__":
    sys.exit(main())
